"""ICI/DCN collectives microbenchmark: psum / all-gather / ppermute.

The TPU-native equivalent of the reference's NCCL all-reduce test
(reference: examples/nccl_test.yaml — torch.distributed all_reduce_bench
reporting busbw): times XLA collectives over the device mesh and reports
algorithmic + bus bandwidth per collective.

Run on any slice:  python examples/collectives_bench.py [--mb 64]
(on CPU it runs on the virtual device mesh — numbers are meaningless
but the harness is exercised.)
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=float, default=64.0,
                    help="payload megabytes")
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    n = jax.device_count()
    mesh = Mesh(np.asarray(jax.devices()).reshape(n), ("x",))
    elems = int(args.mb * 1e6 / 4)
    elems -= elems % max(n, 1)
    x = jnp.ones((elems,), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("x")))
    bytes_total = elems * 4

    def timed(fn, arg):
        fn = jax.jit(fn)
        out = fn(arg)
        _ = float(jnp.sum(out))            # compile + real sync
        t0 = time.time()
        for _ in range(args.iters):
            out = fn(arg)
        _ = float(jnp.sum(out))            # host fetch = sync
        return (time.time() - t0) / args.iters

    results = {}

    ar = shard_map(lambda v: jax.lax.psum(v, "x"), mesh=mesh,
                   in_specs=P("x"), out_specs=P("x"))
    t = timed(ar, xs)
    # Ring all-reduce moves 2*(n-1)/n of the data per link.
    results["all_reduce"] = {
        "time_ms": round(t * 1e3, 3),
        "algbw_gbps": round(bytes_total / t / 1e9, 2),
        "busbw_gbps": round(bytes_total / t / 1e9 * 2 * (n - 1) / n, 2),
    }

    # all_gather replicates its output; the replication checker can't
    # infer that, so it is disabled (kwarg name varies across jax vers).
    try:
        ag = shard_map(lambda v: jax.lax.all_gather(v, "x", tiled=True),
                       mesh=mesh, in_specs=P("x"), out_specs=P(None),
                       check_vma=False)
    except TypeError:
        ag = shard_map(lambda v: jax.lax.all_gather(v, "x", tiled=True),
                       mesh=mesh, in_specs=P("x"), out_specs=P(None),
                       check_rep=False)
    t = timed(ag, xs)
    results["all_gather"] = {
        "time_ms": round(t * 1e3, 3),
        "algbw_gbps": round(bytes_total / t / 1e9, 2),
        "busbw_gbps": round(bytes_total / t / 1e9 * (n - 1) / n, 2),
    }

    perm = [(i, (i + 1) % n) for i in range(n)]
    pp = shard_map(lambda v: jax.lax.ppermute(v, "x", perm), mesh=mesh,
                   in_specs=P("x"), out_specs=P("x"))
    t = timed(pp, xs)
    results["ppermute"] = {
        "time_ms": round(t * 1e3, 3),
        "algbw_gbps": round(bytes_total / t / 1e9, 2),
    }

    print(json.dumps({
        "devices": n,
        "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
        "payload_mb": args.mb,
        **results,
    }))


if __name__ == "__main__":
    main()
