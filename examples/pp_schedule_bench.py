"""Pipeline-schedule microbenchmark: bubble fraction + activation
memory, GPipe vs the 1F1B-equivalent streaming schedule, at pp=2 and
pp=4.

Run on the virtual CPU mesh (no TPU needed):

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pp_schedule_bench.py

What it shows (the honest 1F1B story for a dense lockstep-SPMD
pipeline):

* Bubble fraction is (S-1)/(M+S-1) for BOTH schedules — synchronous
  1F1B does not beat GPipe on steady-state bubble; measured step times
  confirm they match at equal M.
* What 1F1B changes is MEMORY: GPipe buffers every microbatch's
  output ([M, b, S, D]) on top of the O(B) inputs; the streaming
  schedule drops that buffer, so its footprint grows strictly more
  slowly in M (what remains is the input batch itself — this script
  holds b fixed, so B = M*b still grows). At a fixed memory budget
  the lower slope is exactly what lets M rise — and the bubble
  fraction falls with M.

Prints one JSON line per (pp, schedule, M) plus a summary.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

import jax
import jax.numpy as jnp


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel import pipeline as pl
    from skypilot_tpu.parallel import sharding as sh

    n_dev = jax.device_count()
    base = pl.CONFIGS["pp-tiny"]
    rows = []
    for pp in (2, 4):
        if n_dev % pp:
            log(f"skipping pp={pp}: {n_dev} devices not divisible")
            continue
        for M in (4, 8, 16):
            for schedule in ("gpipe", "1f1b"):
                cfg = dataclasses.replace(base, n_stages=pp,
                                          n_microbatches=M,
                                          schedule=schedule)
                mesh = mesh_lib.make_mesh(
                    mesh_lib.default_shape_for(n_dev, pp=pp))
                params = pl.init_params(jax.random.key(0), cfg)
                p_sh = sh.logical_to_sharding(
                    pl.param_logical_axes(cfg), mesh, sh.DEFAULT_RULES)
                params = jax.device_put(params, p_sh)
                constrain = sh.make_constrain(mesh, sh.ACT_RULES)
                B = M * 2
                batch = {"tokens": jnp.ones((B, 64), jnp.int32),
                         "mask": None, "segment_ids": None}
                fn = jax.jit(lambda p, b: pl.loss_fn(
                    p, b, cfg, constrain)[0])
                lowered = fn.lower(params, batch)
                compiled = lowered.compile()
                temp_mb = (compiled.memory_analysis().temp_size_in_bytes
                           / 1e6)
                loss = float(fn(params, batch))       # warm + check
                t0 = time.time()
                reps = 5
                for _ in range(reps):
                    loss = fn(params, batch)
                float(loss)
                dt = (time.time() - t0) / reps
                bubble = (pp - 1) / (M + pp - 1)
                rows.append({"pp": pp, "schedule": schedule, "M": M,
                             "step_ms": round(dt * 1e3, 1),
                             "temp_mb": round(temp_mb, 2),
                             "bubble_frac": round(bubble, 4)})
                log(f"pp={pp} {schedule:5s} M={M:2d}: "
                    f"step {dt*1e3:7.1f}ms temp {temp_mb:8.2f}MB "
                    f"bubble {bubble:.1%}")

    # Summary: the memory slope is the schedule difference; the bubble
    # column shows why raising M (which 1F1B's flat memory permits)
    # is the real lever.
    print(json.dumps({"metric": "pp_schedule_bench", "rows": rows}))


if __name__ == "__main__":
    main()
