// Sequence packer: greedy first-fit packing of tokenized documents into
// fixed [rows, cols] training batches with segment ids and restart
// positions. C ABI, loaded from Python via ctypes
// (skypilot_tpu/data/input_pipeline.py; pure-numpy fallback exists).
//
// The hot loop is trivial but runs per training batch on the host input
// path; native keeps it off the Python interpreter the way the
// reference leans on native code for its data path (reference:
// third-party FUSE/Ray — SURVEY.md §0 "Performance-critical native
// pieces are third-party").
//
// Build: make -C native   (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Pack documents into rows using greedy first-fit on remaining space.
//
//   tokens:    concatenated document tokens (int32)
//   doc_lens:  per-document lengths (int64), n_docs entries
//   out_tokens / out_segments / out_positions: [rows * cols] int32,
//       pre-filled by caller with pad_id / 0 / 0.
//   returns: number of documents placed (<= n_docs; the rest did not
//       fit and should be carried into the next batch).
int64_t pack_documents(const int32_t* tokens, const int64_t* doc_lens,
                       int64_t n_docs, int32_t* out_tokens,
                       int32_t* out_segments, int32_t* out_positions,
                       int64_t rows, int64_t cols, int32_t pad_id) {
  std::vector<int64_t> used(rows, 0);
  std::vector<int32_t> next_segment(rows, 1);
  int64_t offset = 0;
  int64_t placed = 0;
  for (int64_t d = 0; d < n_docs; ++d) {
    const int64_t len = doc_lens[d];
    if (len > cols) {  // oversized docs must be pre-chunked by caller
      offset += len;
      ++placed;  // counted as consumed: dropping silently would stall
      continue;
    }
    int64_t row = -1;
    for (int64_t r = 0; r < rows; ++r) {
      if (cols - used[r] >= len) {
        row = r;
        break;
      }
    }
    if (row < 0) break;  // batch full: stop, carry the rest
    int32_t* t = out_tokens + row * cols + used[row];
    int32_t* s = out_segments + row * cols + used[row];
    int32_t* p = out_positions + row * cols + used[row];
    std::memcpy(t, tokens + offset, len * sizeof(int32_t));
    const int32_t seg = next_segment[row]++;
    for (int64_t i = 0; i < len; ++i) {
      s[i] = seg;
      p[i] = static_cast<int32_t>(i);
    }
    used[row] += len;
    offset += len;
    ++placed;
  }
  return placed;
}

}  // extern "C"
