"""Inference engine: KV-cache decode parity + continuous batching."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.infer import kvcache, sampling
from skypilot_tpu.models import llama


@pytest.fixture(scope="module")
def cfg():
    return llama.CONFIGS["llama3-tiny"]


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.key(0), cfg)


@pytest.fixture(scope="module")
def moe_setup():
    """moe-tiny with generous capacity (no routing drops) + params —
    shared by every MoE inference test."""
    import dataclasses

    from skypilot_tpu.models import moe
    mcfg = dataclasses.replace(moe.CONFIGS["moe-tiny"],
                               capacity_factor=4.0)
    return moe, mcfg, moe.init_params(jax.random.key(0), mcfg)


def greedy_reference(params, cfg, prompt, n_new):
    """Greedy decode via repeated full forwards (the slow oracle)."""
    toks = list(prompt)
    for _ in range(n_new):
        logits = llama.forward(params, jnp.asarray([toks], jnp.int32), cfg)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def test_incremental_decode_matches_full_forward(cfg, params):
    prompt = [3, 17, 42, 7, 99]
    n_new = 8
    want = greedy_reference(params, cfg, prompt, n_new)

    e = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                            prompt_buckets=(16, 64))
    got = e.generate([prompt], max_new_tokens=n_new)[0]
    assert got == want


def test_continuous_batching_isolation(cfg, params):
    """Staggered concurrent requests decode exactly like solo runs."""
    p1, p2 = [5, 9, 31], [44, 2, 8, 19, 3, 27]
    want1 = greedy_reference(params, cfg, p1, 6)
    want2 = greedy_reference(params, cfg, p2, 6)

    e = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                            prompt_buckets=(16,))
    r1 = e.add_request(p1, max_new_tokens=6)
    e.step()   # r1 decodes alone for two steps
    e.step()
    r2 = e.add_request(p2, max_new_tokens=6)
    e.run_to_completion()
    by_rid = {r.rid: r.tokens for r in e.finished}
    assert by_rid[r1] == want1
    assert by_rid[r2] == want2


def test_slots_recycled(cfg, params):
    e = eng.InferenceEngine(params, cfg, n_slots=1, max_len=32,
                            prompt_buckets=(16,))
    outs = e.generate([[1, 2, 3], [4, 5, 6], [7, 8]], max_new_tokens=3)
    assert len(outs) == 3
    assert all(len(o) == 3 for o in outs)
    assert len(e.free_slots) == 1


def test_ttft_recorded(cfg, params):
    e = eng.InferenceEngine(params, cfg, n_slots=1, max_len=32,
                            prompt_buckets=(16,))
    e.add_request([1, 2, 3, 4], max_new_tokens=2)
    e.run_to_completion()
    req = e.finished[0]
    assert req.first_token_s is not None
    assert req.first_token_s >= req.submit_s


def test_eos_stops_decode(cfg, params):
    # Find the greedy first token, then declare it EOS: request must
    # retire after a single token.
    prompt = [3, 17, 42]
    first = greedy_reference(params, cfg, prompt, 1)[0]
    e = eng.InferenceEngine(params, cfg, n_slots=1, max_len=32,
                            prompt_buckets=(16,), eos_id=first)
    out = e.generate([prompt], max_new_tokens=10)[0]
    assert out == [first]


def test_sampling_temperature_valid(cfg, params):
    sp = sampling.SamplingParams(temperature=0.8, top_k=10)
    e = eng.InferenceEngine(params, cfg, n_slots=1, max_len=32,
                            prompt_buckets=(16,), sampling_params=sp)
    out = e.generate([[1, 2, 3]], max_new_tokens=5)[0]
    assert len(out) == 5
    assert all(0 <= t < cfg.vocab_size for t in out)


def test_oversized_prompt_rejected_at_submit(cfg, params):
    e = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                            prompt_buckets=(16,))
    with pytest.raises(ValueError):
        e.add_request(list(range(17)), max_new_tokens=2)
    # Engine is untouched: a valid request still goes through.
    out = e.generate([[1, 2, 3]], max_new_tokens=2)[0]
    assert len(out) == 2
    assert len(e.free_slots) == 2


def test_mixed_bucket_admission(cfg, params):
    """Prompts from different buckets admit in separate waves but all
    decode correctly together."""
    e = eng.InferenceEngine(params, cfg, n_slots=4, max_len=96,
                            prompt_buckets=(8, 32))
    short1, short2 = [1, 2, 3], [9, 8]
    long1 = list(range(1, 21))
    want_s1 = greedy_reference(params, cfg, short1, 4)
    want_l1 = greedy_reference(params, cfg, long1, 4)
    outs = e.generate([short1, long1, short2], max_new_tokens=4)
    assert outs[0] == want_s1
    assert outs[1] == want_l1
    assert len(outs[2]) == 4


def test_max_wave_splits_admission(cfg, params):
    """max_wave caps admission waves: 5 same-bucket requests admit in
    ceil(5/2)=3 waves (on_wave fires per wave), results identical to
    the unsplit engine."""
    e = eng.InferenceEngine(params, cfg, n_slots=8, max_len=64,
                            prompt_buckets=(8,), max_wave=2)
    prompts = [[i + 1, i + 2] for i in range(5)]
    for p in prompts:
        e.add_request(p, max_new_tokens=3)
    waves = []
    e.step_burst(max_burst=4, on_wave=lambda: waves.append(
        len(e.slot_req) + len(e.finished)))
    assert len(waves) == 3
    assert waves == [2, 4, 5]  # cumulative admissions per wave
    e.run_to_completion()
    got = {r.rid: r.tokens for r in e.finished}

    ref = eng.InferenceEngine(params, cfg, n_slots=8, max_len=64,
                              prompt_buckets=(8,))
    want = ref.generate(prompts, max_new_tokens=3)
    assert [got[i] for i in sorted(got)] == want


def test_engine_with_tp_sharded_params(cfg, params):
    """Engine serves correctly with tensor-parallel sharded weights."""
    from skypilot_tpu.parallel import mesh as mesh_lib, sharding as sh
    from skypilot_tpu.models import llama as llama_mod

    prompt = [3, 17, 42, 7]
    want = greedy_reference(params, cfg, prompt, 4)

    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(fsdp=2, tp=4))
    p_sh = sh.logical_to_sharding(
        llama_mod.param_logical_axes(cfg), mesh, sh.DEFAULT_RULES,
        shapes=params)  # divisibility guard: tiny dims stay replicated
    sharded = jax.device_put(params, p_sh)
    e = eng.InferenceEngine(sharded, cfg, n_slots=2, max_len=64,
                            prompt_buckets=(16,))
    got = e.generate([prompt], max_new_tokens=4)[0]
    assert got == want


def test_moe_engine_serves(moe_setup):
    """The engine serves sparse MoE models: incremental decode logits
    match the full forward (generous capacity so no routing drops)."""
    moe, mcfg, mparams = moe_setup
    prompt = [3, 17, 42, 7]

    # Incremental: prefill then two decode steps.
    cache = kvcache.init_cache(mcfg, 1, 32)
    padded = np.zeros((16,), np.int32)
    padded[:len(prompt)] = prompt
    prefix, logits0 = kvcache.prefill(
        mparams, jnp.asarray(padded), jnp.asarray(4), mcfg)
    tok0 = int(jnp.argmax(logits0))
    cache = kvcache.insert(cache, prefix, jnp.asarray(0),
                           jnp.asarray(4), jnp.asarray(tok0))
    cache, logits1 = kvcache.decode_step(mparams, cache, mcfg)

    # Oracle: full forward over prompt + tok0.
    full, _ = moe.forward(mparams,
                          jnp.asarray([prompt + [tok0]], jnp.int32), mcfg)
    np.testing.assert_allclose(np.asarray(logits1[0]),
                               np.asarray(full[0, -1]),
                               rtol=2e-2, atol=6e-2)
    np.testing.assert_allclose(
        np.asarray(logits0), np.asarray(
            moe.forward(mparams, jnp.asarray([prompt], jnp.int32),
                        mcfg)[0][0, -1]), rtol=2e-2, atol=6e-2)

    # End-to-end through the engine.
    e = eng.InferenceEngine(mparams, mcfg, n_slots=2, max_len=32,
                            prompt_buckets=(16,))
    out = e.generate([prompt], max_new_tokens=4)[0]
    assert len(out) == 4
    assert all(0 <= t < mcfg.vocab_size for t in out)


def test_kv_int8_quantize_roundtrip():
    x = jax.random.normal(jax.random.key(0), (4, 7, 2, 64)) * 3.0
    q, scale = kvcache.quantize_rows(x)
    assert q.dtype == jnp.int8 and scale.shape == (4, 7, 2)
    back = kvcache.dequantize_rows(q, scale)
    err = np.abs(np.asarray(back) - np.asarray(x)).max()
    assert err <= float(np.abs(np.asarray(x)).max()) / 127 + 1e-6


def test_kv_int8_cache_shapes(cfg):
    c = kvcache.init_cache(cfg, 3, 16, kv_int8=True)
    assert c["k"].dtype == jnp.int8
    # Row dim minormost: [..., G] minor would tile-pad 8->128 (16x).
    assert c["k_scale"].shape == (cfg.n_layers, 3, cfg.n_kv_heads, 16)
    axes = kvcache.cache_logical_axes(c)
    assert "k_scale" in axes
    assert "k_scale" not in kvcache.cache_logical_axes()


def test_kv_int8_engine_matches_fp_closely(cfg, params):
    """int8 KV decode tracks the fp cache closely: greedy generations
    agree on a short horizon (per-row absmax error is ~1/127)."""
    prompt = list(range(1, 25))
    sp = sampling.SamplingParams(temperature=0.0)  # greedy
    e_fp = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                               prompt_buckets=(32,), sampling_params=sp)
    e_q = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                              prompt_buckets=(32,), sampling_params=sp,
                              kv_int8=True)
    out_fp = e_fp.generate([prompt], max_new_tokens=8)[0]
    out_q = e_q.generate([prompt], max_new_tokens=8)[0]
    assert len(out_q) == len(out_fp)
    # First token comes from the (unquantized) prefill: must agree.
    assert out_q[0] == out_fp[0]
    # The rest run over the int8 cache; demand strong agreement.
    same = sum(a == b for a, b in zip(out_q, out_fp))
    assert same >= len(out_fp) - 1, (out_fp, out_q)


def test_weights_int8_engine_generates_sensibly(cfg, params):
    """w8a8 decode: greedy output stays close to the fp engine (per-
    channel weight + per-token activation int8; ~1% matmul error)."""
    prompt = list(range(1, 20))
    sp = sampling.SamplingParams(temperature=0.0)
    e_fp = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                               prompt_buckets=(32,), sampling_params=sp)
    e_q = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                              prompt_buckets=(32,), sampling_params=sp,
                              weights_int8=True)
    out_fp = e_fp.generate([prompt], max_new_tokens=6)[0]
    out_q = e_q.generate([prompt], max_new_tokens=6)[0]
    assert len(out_q) == len(out_fp)
    # Prefill AND decode are quantized (that is what frees the fp
    # weights): demand strong but not exact agreement.
    same = sum(a == b for a, b in zip(out_q, out_fp))
    assert same >= len(out_fp) - 2, (out_fp, out_q)


def test_weights_int8_composes_with_kv_int8(cfg, params):
    sp = sampling.SamplingParams(temperature=0.0)
    e = eng.InferenceEngine(params, cfg, n_slots=1, max_len=48,
                            prompt_buckets=(16,), sampling_params=sp,
                            kv_int8=True, weights_int8=True)
    out = e.generate([[5, 9, 31]], max_new_tokens=5)[0]
    assert len(out) == 5
    assert all(0 <= t < cfg.vocab_size for t in out)


@pytest.mark.parametrize("family", ["llama", "moe"])
def test_staged_burst_cache_matches_oracle(family, cfg, params,
                                           moe_setup):
    """The staged burst's ONE-flush cache write must leave the cache
    exactly as the per-step path would: after a burst, a single
    decode_step's logits agree with a full forward over the whole
    generated sequence (wrong flush indices/lengths would corrupt
    attention here, not just shift tokens). Parametrized over the
    dense llama path and the MoE (_ffn experts) branch."""
    if family == "llama":
        mcfg, mparams = cfg, params
        fwd = lambda seq: llama.forward(
            mparams, jnp.asarray([seq], jnp.int32), mcfg)[0, -1]
    else:
        moe, mcfg, mparams = moe_setup
        fwd = lambda seq: moe.forward(
            mparams, jnp.asarray([seq], jnp.int32), mcfg)[0][0, -1]
    e = eng.InferenceEngine(mparams, mcfg, n_slots=2, max_len=64,
                            prompt_buckets=(8,))
    prompt = [3, 17, 42, 7]
    e.add_request(list(prompt), max_new_tokens=16)
    e.admit()
    out = e.decode_burst(max_burst=4)       # staged program, k=4
    (req,) = e.slot_req.values()
    assert len(req.tokens) == 5             # admission token + burst
    assert list(out.values())[0] == req.tokens[1:]

    # Logits for the NEXT position via the burst-flushed cache...
    _, logits = kvcache.decode_step(e.params, e.cache, mcfg,
                                    table=e.table_device())
    got = np.asarray(logits[req.slot])
    # ...vs the from-scratch oracle over prompt + generated tokens.
    want = np.asarray(fwd(prompt + req.tokens))
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=6e-2)
