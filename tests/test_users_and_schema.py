"""Users / cluster ownership + sqlite schema versioning.

Reference parity: sky/global_user_state.py:110 (users table), :175
(owner recorded on the cluster), backends/backend_utils.py:1509
(check_owner_identity refuses cross-user ops), and
tests/backward_compatibility_tests.sh (old on-disk state meeting new
code must migrate or fail loudly — here: PRAGMA user_version +
registered migrations, tested against a hand-built v1 fixture).
"""

import socket
import sqlite3
import threading
import urllib.error
import urllib.request

import pytest

from skypilot_tpu import authentication, exceptions, state
from skypilot_tpu.backend import check_owner_identity
from skypilot_tpu.utils import db as db_lib


@pytest.fixture()
def home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    monkeypatch.setenv("SKYPILOT_TPU_USER", "alice")
    return tmp_path


# -- identity ---------------------------------------------------------------

def test_identity_env_override(monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_USER", "alice")
    a = authentication.get_user_identity()
    monkeypatch.setenv("SKYPILOT_TPU_USER", "bob")
    b = authentication.get_user_identity()
    assert a["name"] == "alice" and b["name"] == "bob"
    assert a["id"] != b["id"]
    # Stable: same input, same id.
    monkeypatch.setenv("SKYPILOT_TPU_USER", "alice")
    assert authentication.get_user_identity() == a


def test_identity_server_injected(monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_USER_ID", "deadbeef")
    monkeypatch.setenv("SKYPILOT_TPU_USER_NAME", "carol")
    me = authentication.get_user_identity()
    assert me == {"id": "deadbeef", "name": "carol"}


# -- ownership --------------------------------------------------------------

def test_owner_recorded_and_preserved(home, monkeypatch):
    me = authentication.get_user_identity()
    state.set_cluster("c1", {"provider": "local"}, state.ClusterStatus.UP,
                      owner=me)
    rec = state.get_cluster("c1")
    assert rec["owner"] == me["id"]
    assert state.get_user(me["id"])["name"] == "alice"
    # A later upsert (status refresh) without owner keeps the original.
    state.set_cluster("c1", {"provider": "local"},
                      state.ClusterStatus.STOPPED)
    assert state.get_cluster("c1")["owner"] == me["id"]
    # ... and an upsert by ANOTHER user does not steal it.
    monkeypatch.setenv("SKYPILOT_TPU_USER", "bob")
    other = authentication.get_user_identity()
    state.set_cluster("c1", {"provider": "local"}, state.ClusterStatus.UP,
                      owner=other)
    assert state.get_cluster("c1")["owner"] == me["id"]


def test_check_owner_identity(home, monkeypatch):
    me = authentication.get_user_identity()
    state.set_cluster("mine", {"provider": "local"},
                      state.ClusterStatus.UP, owner=me)
    check_owner_identity("mine")          # owner: fine
    check_owner_identity("nonexistent")   # unknown cluster: no-op here
    monkeypatch.setenv("SKYPILOT_TPU_USER", "mallory")
    with pytest.raises(exceptions.ClusterOwnerIdentityMismatchError,
                       match="owned by alice"):
        check_owner_identity("mine")


def test_ownerless_v1_record_grandfathered(home):
    # Records from pre-ownership schemas have owner NULL: anyone may
    # operate on them (reference grandfathers old clusters the same way).
    state.set_cluster("old", {"provider": "local"}, state.ClusterStatus.UP)
    check_owner_identity("old")


def test_core_ops_refuse_foreign_cluster(home, monkeypatch):
    from skypilot_tpu import core
    me = authentication.get_user_identity()
    state.set_cluster("guarded", {"provider": "local",
                                  "cluster_name": "guarded"},
                      state.ClusterStatus.UP, owner=me)
    monkeypatch.setenv("SKYPILOT_TPU_USER", "mallory")
    for op in (lambda: core.stop("guarded"),
               lambda: core.down("guarded"),
               lambda: core.start("guarded"),
               lambda: core.autostop("guarded", 5),
               lambda: core.cancel("guarded", 1)):
        with pytest.raises(exceptions.ClusterOwnerIdentityMismatchError):
            op()
    # The record is untouched.
    assert state.get_cluster("guarded")["status"] == state.ClusterStatus.UP


# -- schema versioning ------------------------------------------------------

def _v1_state_db(path):
    """Hand-built v1 fixture: the exact pre-ownership schema."""
    conn = sqlite3.connect(path)
    conn.executescript("""
CREATE TABLE clusters (
    name TEXT PRIMARY KEY,
    launched_at INTEGER,
    handle TEXT,
    status TEXT,
    autostop_minutes INTEGER DEFAULT -1,
    autostop_down INTEGER DEFAULT 0,
    price_per_hour REAL DEFAULT 0
);
CREATE TABLE cluster_history (
    name TEXT, launched_at INTEGER, duration_s REAL,
    price_per_hour REAL, resources TEXT, num_nodes INTEGER
);
CREATE TABLE storage (name TEXT PRIMARY KEY, handle TEXT,
                      created_at INTEGER);
INSERT INTO clusters (name, launched_at, handle, status)
    VALUES ('legacy', 123, '{"provider": "local"}', 'UP');
""")
    conn.commit()
    conn.close()


def test_v1_state_db_migrates_in_place(home):
    from skypilot_tpu.utils import paths
    _v1_state_db(paths.state_db())
    # New code reading an old DB: migration runs, data survives, owner
    # reads as NULL (grandfathered).
    rec = state.get_cluster("legacy")
    assert rec["status"] == state.ClusterStatus.UP
    assert rec["owner"] is None
    conn = sqlite3.connect(paths.state_db())
    assert conn.execute("PRAGMA user_version").fetchone()[0] == \
        state.SCHEMA_VERSION
    cols = [r[1] for r in conn.execute(
        "PRAGMA table_info(clusters)").fetchall()]
    assert "owner" in cols
    conn.close()
    # And new writes work on the migrated DB.
    me = authentication.get_user_identity()
    state.set_cluster("fresh", {"provider": "local"},
                      state.ClusterStatus.UP, owner=me)
    assert state.get_cluster("fresh")["owner"] == me["id"]


def test_newer_schema_refused(home, tmp_path):
    path = str(tmp_path / "future.db")
    conn = db_lib.open_versioned(path, "CREATE TABLE t (x);", 1)
    conn.execute("PRAGMA user_version=99")
    conn.commit()
    conn.close()
    with pytest.raises(db_lib.SchemaVersionError, match="newer"):
        db_lib.open_versioned(path, "CREATE TABLE t (x);", 1)


def test_missing_migration_refused(home, tmp_path):
    path = str(tmp_path / "gap.db")
    db_lib.open_versioned(path, "CREATE TABLE t (x);", 1).close()
    with pytest.raises(db_lib.SchemaVersionError, match="no migration"):
        db_lib.open_versioned(path, "CREATE TABLE t (x);", 3,
                              migrations={2: "CREATE TABLE u (y);"})


def test_migration_chain_runs_in_order(home, tmp_path):
    path = str(tmp_path / "chain.db")
    db_lib.open_versioned(path, "CREATE TABLE t (x);", 1).close()
    conn = db_lib.open_versioned(
        path, "CREATE TABLE t (x); CREATE TABLE u (y); CREATE TABLE w (z);",
        3, migrations={2: "CREATE TABLE u (y);", 3: "CREATE TABLE w (z);"})
    tables = {r[0] for r in conn.execute(
        "SELECT name FROM sqlite_master WHERE type='table'").fetchall()}
    assert {"t", "u", "w"} <= tables
    assert conn.execute("PRAGMA user_version").fetchone()[0] == 3
    conn.close()


def test_requests_db_v1_migrates(home):
    from skypilot_tpu.server import requests_db
    from skypilot_tpu.utils import paths
    conn = sqlite3.connect(paths.requests_db())
    conn.executescript("""
CREATE TABLE requests (
    request_id TEXT PRIMARY KEY, name TEXT, status TEXT, payload TEXT,
    result TEXT, error TEXT, pid INTEGER, created_at REAL,
    finished_at REAL
);
INSERT INTO requests (request_id, name, status, payload, created_at)
    VALUES ('abc', 'status', 'SUCCEEDED', '{}', 1.0);
""")
    conn.commit()
    conn.close()
    rec = requests_db.get("abc")
    assert rec["name"] == "status" and rec["user"] is None
    rid = requests_db.create("status", {}, user={"id": "u1", "name": "n"})
    assert requests_db.get(rid)["user"] == {"id": "u1", "name": "n"}


# -- multi-client ownership through the API server --------------------------

@pytest.fixture()
def api_server(tmp_path, monkeypatch):
    from skypilot_tpu.server import server as server_mod
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    monkeypatch.setenv("SKYTPU_API_SERVER_URL", f"http://127.0.0.1:{port}")
    executor = server_mod.Executor()
    executor.start()
    httpd = server_mod._Server(("127.0.0.1", port),
                               server_mod.make_handler())
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    executor.stop()
    httpd.shutdown()


def test_two_clients_ownership_via_server(api_server, monkeypatch):
    """Alice launches through the API server; Bob's down is refused;
    Alice's own down succeeds. The identity rides the X-SkyTPU-User-*
    headers into the request worker's environment."""
    from skypilot_tpu.client import sdk
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task

    monkeypatch.setenv("SKYPILOT_TPU_USER", "alice")
    task = Task(name="t", run="echo hi")
    task.set_resources(Resources(cloud="local"))
    rid = sdk.launch(task, cluster_name="owned")
    assert sdk.get(rid, timeout=120)["cluster_name"] == "owned"

    monkeypatch.setenv("SKYPILOT_TPU_USER", "bob")
    rid = sdk.down("owned")
    with pytest.raises(exceptions.SkyTpuError,
                       match="owned by alice"):
        sdk.get(rid, timeout=60)

    monkeypatch.setenv("SKYPILOT_TPU_USER", "alice")
    rid = sdk.down("owned")
    sdk.get(rid, timeout=60)
    rid = sdk.status()
    assert not any(r["name"] == "owned" for r in sdk.get(rid, timeout=60))


def test_api_auth_required(tmp_path, monkeypatch):
    """With an auth token configured, unauthenticated calls get 401
    (except /api/health) and the SDK's token pickup makes them pass."""
    from skypilot_tpu.client import sdk
    from skypilot_tpu.server import server as server_mod

    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    url = f"http://127.0.0.1:{port}"
    monkeypatch.setenv("SKYTPU_API_SERVER_URL", url)
    httpd = server_mod._Server(
        ("127.0.0.1", port), server_mod.make_handler(auth_token="sesame"))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        # Health stays open for probes.
        assert sdk.api_info()["status"] == "healthy"
        # No token -> 401 on real endpoints.
        monkeypatch.delenv("SKYPILOT_TPU_API_TOKEN", raising=False)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url + "/api/status", timeout=10)
        assert ei.value.code == 401
        # Wrong token -> 401.
        monkeypatch.setenv("SKYPILOT_TPU_API_TOKEN", "wrong")
        with pytest.raises(urllib.error.HTTPError) as ei2:
            sdk.api_status()
        assert ei2.value.code == 401
        # Right token -> through.
        monkeypatch.setenv("SKYPILOT_TPU_API_TOKEN", "sesame")
        assert sdk.api_status() == []
        # Browser path: ?token= on a GET (the dashboard link).
        with urllib.request.urlopen(url + "/dashboard?token=sesame",
                                    timeout=10) as r:
            assert r.status == 200
        with pytest.raises(urllib.error.HTTPError) as ei3:
            urllib.request.urlopen(url + "/dashboard?token=wrong",
                                   timeout=10)
        assert ei3.value.code == 401
    finally:
        httpd.shutdown()
