"""Checkpoint/resume: async orbax roundtrip of the sharded train state."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.train import checkpoints, trainer


@pytest.fixture()
def tc():
    return trainer.TrainConfig(warmup_steps=1, total_steps=10)


def test_roundtrip_sharded(tmp_path, mesh8, tiny_cfg, tc):
    state = trainer.create_train_state(tiny_cfg, tc, mesh8)
    step_fn = trainer.make_train_step(tiny_cfg, tc, mesh8)
    batch = trainer.synthetic_batch(tiny_cfg, 8, 32)
    state, _ = step_fn(state, batch)

    with checkpoints.CheckpointManager(str(tmp_path / "ckpt")) as mgr:
        assert mgr.save(1, state)
        mgr.wait()
        assert mgr.latest_step() == 1

        target = trainer.create_abstract_state(tiny_cfg, tc, mesh8)
        restored = mgr.restore(target)

    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Restored leaves landed with the requested shardings.
    wq = restored["params"]["blocks"]["wq"]
    assert len(wq.sharding.device_set) == 8


def test_resume_continues_identically(tmp_path, mesh8, tiny_cfg, tc):
    """step -> save -> step == restore -> step (bitwise on CPU)."""
    step_fn = trainer.make_train_step(tiny_cfg, tc, mesh8)
    batch = trainer.synthetic_batch(tiny_cfg, 8, 32)
    state = trainer.create_train_state(tiny_cfg, tc, mesh8)
    state, _ = step_fn(state, batch)

    with checkpoints.CheckpointManager(str(tmp_path / "c")) as mgr:
        mgr.save(1, state, force=True)
        mgr.wait()
        cont, m_direct = step_fn(state, batch)

        target = trainer.create_abstract_state(tiny_cfg, tc, mesh8)
        resumed = mgr.restore(target)
    resumed, m_resumed = step_fn(resumed, batch)
    np.testing.assert_allclose(float(m_direct["loss"]),
                               float(m_resumed["loss"]), rtol=1e-6)
    assert int(cont["step"]) == int(resumed["step"]) == 2


def test_max_to_keep(tmp_path, tiny_cfg, tc):
    state = trainer.create_train_state(tiny_cfg, tc, None)
    with checkpoints.CheckpointManager(str(tmp_path / "k"),
                                       max_to_keep=2) as mgr:
        for s in (1, 2, 3):
            mgr.save(s, state, force=True)
        mgr.wait()
        steps = list(mgr.all_steps())
    assert 3 in steps and len(steps) <= 2


def test_restore_missing_raises(tmp_path):
    with checkpoints.CheckpointManager(str(tmp_path / "none")) as mgr:
        with pytest.raises(FileNotFoundError):
            mgr.restore()
