"""Optimizer correctness fuzzing: DP plan vs brute-force enumeration.

Reference parity: tests/test_optimizer_random_dag.py (random DAGs,
ILP/DP cost compared against brute force). Chains only here — the
executable surface (see optimizer.optimize).
"""

import itertools
import random

import pytest

from skypilot_tpu import dag as dag_lib, optimizer
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task


def _chain(n_tasks, rng):
    d = dag_lib.Dag()
    tasks = []
    accels = ["tpu-v5e-8", "tpu-v5e-16", "tpu-v4-8", "tpu-v5p-8", None]
    prev = None
    for i in range(n_tasks):
        t = Task(name=f"t{i}", run="true")
        cfg = {"accelerators": rng.choice(accels)}
        if rng.random() < 0.3:
            cfg["use_spot"] = True
        t.set_resources(Resources.from_yaml_config(
            {k: v for k, v in cfg.items() if v is not None}))
        if rng.random() < 0.5:
            t.estimated_outputs_gb = rng.choice([1.0, 50.0, 500.0])
        if rng.random() < 0.5:
            t.estimated_runtime_seconds = rng.choice([600.0, 3600.0])
        d.add(t)
        if prev is not None:
            d.add_edge(prev, t)
        prev = t
        tasks.append(t)
    return d, tasks


def _brute_force_cost(tasks, per_task):
    best = None
    for combo in itertools.product(*(per_task[t] for t in tasks)):
        total = sum(c.cost for c in combo)
        for (ta, a), (_, b) in zip(zip(tasks, combo),
                                   list(zip(tasks, combo))[1:]):
            total += optimizer._egress_cost(
                a.resources, b.resources, optimizer._edge_gigabytes(ta))
        if best is None or total < best:
            best = total
    return best


@pytest.mark.parametrize("seed", range(8))
def test_dp_matches_brute_force(seed):
    rng = random.Random(seed)
    d, tasks = _chain(rng.randint(1, 4), rng)
    per_task = {t: optimizer._candidates_for(t, set()) for t in tasks}
    # Keep brute force tractable.
    per_task = {t: cands[:6] for t, cands in per_task.items()}

    want = _brute_force_cost(tasks, per_task)

    import unittest.mock as mock
    with mock.patch.object(optimizer, "_candidates_for",
                           side_effect=lambda t, b, rc=None: per_task[t]):
        plan = optimizer.optimize(d)
    got = sum(
        next(c.cost for c in per_task[t]
             if c.resources is plan[t]) for t in tasks)
    # DP must never be worse than brute force; equality unless egress
    # terms made a non-greedy pick cheaper (DP includes them, the `got`
    # sum here recomputes the same way).
    for a, b in zip(tasks, tasks[1:]):
        got += optimizer._egress_cost(plan[a], plan[b],
                                      optimizer._edge_gigabytes(a))
    assert got == pytest.approx(want, rel=1e-9)


def _random_dag(n_tasks, rng, tree_only):
    d = dag_lib.Dag()
    tasks = []
    accels = ["tpu-v5e-8", "tpu-v4-8", None]
    for i in range(n_tasks):
        t = Task(name=f"g{i}", run="true")
        cfg = {"accelerators": rng.choice(accels)}
        t.set_resources(Resources.from_yaml_config(
            {k: v for k, v in cfg.items() if v is not None}))
        if rng.random() < 0.6:
            t.estimated_outputs_gb = rng.choice([1.0, 50.0, 500.0])
        d.add(t)
        # Forward edges only (acyclic by construction); tree_only caps
        # in-degree at 1.
        n_parents = rng.randint(0, 1 if tree_only else 2)
        for p in rng.sample(tasks, k=min(len(tasks), n_parents)):
            d.add_edge(p, t)
        tasks.append(t)
    return d, tasks


def _dag_objective(d, tasks, per_task, plan):
    total = sum(next(c.cost for c in per_task[t]
                     if c.resources is plan[t]) for t in tasks)
    for u, v in d.graph.edges:
        total += optimizer._egress_cost(plan[u], plan[v],
                                        optimizer._edge_gigabytes(u))
    return total


def _dag_brute_force(d, tasks, per_task):
    best = None
    for combo in itertools.product(*(per_task[t] for t in tasks)):
        plan = {t: c.resources for t, c in zip(tasks, combo)}
        total = _dag_objective(d, tasks, per_task, plan)
        if best is None or total < best:
            best = total
    return best


@pytest.mark.parametrize("seed", range(8))
def test_tree_dag_matches_brute_force(seed):
    """Random forests (in_degree <= 1): the tree DP is exact."""
    rng = random.Random(1000 + seed)
    d, tasks = _random_dag(rng.randint(2, 5), rng, tree_only=True)
    per_task = {t: optimizer._candidates_for(t, set())[:5]
                for t in tasks}
    want = _dag_brute_force(d, tasks, per_task)
    import unittest.mock as mock
    with mock.patch.object(optimizer, "_candidates_for",
                           side_effect=lambda t, b, rc=None: per_task[t]):
        plan = optimizer.optimize(d)
    assert _dag_objective(d, tasks, per_task, plan) == \
        pytest.approx(want, rel=1e-9)


@pytest.mark.parametrize("seed", range(12))
def test_general_dag_exact_under_cap(seed):
    """Random multi-parent DAGs up to 8 tasks: below _EXACT_COMBO_CAP
    the optimizer enumerates exhaustively, so the plan must EQUAL the
    brute-force optimum (VERDICT r3 #7 — the role of the reference's
    PuLP ILP, sky/optimizer.py:469)."""
    rng = random.Random(2000 + seed)
    d, tasks = _random_dag(rng.randint(3, 8), rng, tree_only=False)
    per_task = {t: optimizer._candidates_for(t, set())[:4]
                for t in tasks}
    assert all(len(c) >= 1 for c in per_task.values())
    import unittest.mock as mock
    with mock.patch.object(optimizer, "_candidates_for",
                           side_effect=lambda t, b, rc=None: per_task[t]):
        plan = optimizer.optimize(d)
    got = _dag_objective(d, tasks, per_task, plan)
    want = _dag_brute_force(d, tasks, per_task)
    assert got == pytest.approx(want, rel=1e-9)


@pytest.mark.parametrize("seed", range(6))
def test_general_dag_makespan_exact_under_cap(seed):
    """TIME target on multi-parent DAGs: exhaustive path minimizes the
    true makespan (longest node+edge path)."""
    rng = random.Random(3000 + seed)
    d, tasks = _random_dag(rng.randint(3, 6), rng, tree_only=False)
    for t in tasks:
        t.estimated_runtime_seconds = rng.choice([600.0, 3600.0, 7200.0])
    per_task = {t: optimizer._candidates_for(t, set())[:4]
                for t in tasks}

    def makespan(plan):
        finish = {}
        for t in tasks:   # insertion order is topological
            start = 0.0
            for u in d.graph.predecessors(t):
                start = max(start, finish[u] + optimizer._egress_time(
                    plan[u], plan[t], optimizer._edge_gigabytes(u)))
            finish[t] = start + next(
                c.time_s for c in per_task[t] if c.resources is plan[t])
        return max(finish.values())

    best = min(makespan({t: c.resources for t, c in zip(tasks, combo)})
               for combo in itertools.product(
                   *(per_task[t] for t in tasks)))
    import unittest.mock as mock
    with mock.patch.object(optimizer, "_candidates_for",
                           side_effect=lambda t, b, rc=None: per_task[t]):
        plan = optimizer.optimize(
            d, minimize=optimizer.OptimizeTarget.TIME)
    assert makespan(plan) == pytest.approx(best, rel=1e-9)


def test_above_cap_falls_back_to_heuristic(monkeypatch):
    """Above the cap the coordinate-descent fallback still returns a
    plan no worse than the per-task argmin."""
    rng = random.Random(7)
    d, tasks = _random_dag(6, rng, tree_only=False)
    per_task = {t: optimizer._candidates_for(t, set())[:4]
                for t in tasks}
    monkeypatch.setattr(optimizer, "_EXACT_COMBO_CAP", 1)
    import unittest.mock as mock
    with mock.patch.object(optimizer, "_candidates_for",
                           side_effect=lambda t, b, rc=None: per_task[t]):
        plan = optimizer.optimize(d)
    got = _dag_objective(d, tasks, per_task, plan)
    argmin_plan = {t: min(per_task[t], key=lambda c: c.cost).resources
                   for t in tasks}
    assert got <= _dag_objective(d, tasks, per_task, argmin_plan) + 1e-9
