"""Storage subsystem: stores, mounting commands, ignore lists — offline.

Cloud CLI calls are captured by a fake runner; nothing talks to GCS.
"""

import os

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.data import (cloud_stores, mounting_utils, storage,
                               storage_utils)


class FakeRun:
    """Records commands; scripted return codes."""

    def __init__(self, rc=0, out="", fail_on=None):
        self.cmds = []
        self.rc = rc
        self.out = out
        self.fail_on = fail_on or ()

    def __call__(self, cmd):
        self.cmds.append(cmd)
        if any(s in cmd for s in self.fail_on):
            return 1, "boom"
        return self.rc, self.out


def test_split_bucket_url():
    assert storage.split_bucket_url("gs://b/sub/p") == ("b", "sub/p")
    assert storage.split_bucket_url("gs://b") == ("b", "")
    with pytest.raises(ValueError):
        storage.split_bucket_url("/local/path")


def test_gcs_store_lifecycle_commands():
    run = FakeRun()
    st = storage.GcsStore("mybucket", run=run)
    st.create(region="us-central2")
    st.delete()
    assert any("buckets create gs://mybucket" in c and "us-central2" in c
               for c in run.cmds)
    assert any("rm -r gs://mybucket" in c for c in run.cmds)


def test_storage_sync_up_creates_and_uploads(tmp_path):
    rec = FakeRun()

    def scripted(cmd):
        rec.cmds.append(cmd)
        if "buckets describe" in cmd:
            return 1, ""      # bucket does not exist yet
        return 0, ""

    st = storage.Storage(name="out-bkt", source=str(tmp_path),
                         mode=storage.StorageMode.MOUNT, run=scripted)
    st.sync_up(region="us-central2")
    assert any("buckets create" in c for c in rec.cmds)
    assert any("rsync" in c for c in rec.cmds)


def test_external_bucket_not_created_or_deleted():
    run = FakeRun()
    st = storage.Storage(source="gs://public-data/imagenet",
                         mode=storage.StorageMode.COPY, run=run)
    st.sync_up()
    st.delete()
    assert run.cmds == []  # external: no lifecycle ops
    cmds = st.attach_commands("/data")
    # Subpath is honored: only the imagenet prefix is copied.
    assert "gcloud storage rsync -r gs://public-data/imagenet /data" in cmds[0]


def test_subpath_mount_uses_only_dir():
    st = storage.Storage(source="gs://bkt/checkpoints/run1",
                         mode=storage.StorageMode.MOUNT, run=FakeRun())
    (cmd,) = st.attach_commands("/ckpt")
    assert "--only-dir checkpoints/run1" in cmd
    assert " bkt " in cmd


def test_ephemeral_delete():
    run = FakeRun()
    st = storage.Storage(name="scratch", persistent=False, run=run)
    st.delete()
    assert any("rm -r gs://scratch" in c for c in run.cmds)
    # Persistent and external storages never delete.
    run2 = FakeRun()
    storage.Storage(name="keep", persistent=True, run=run2).delete()
    storage.Storage(source="gs://ext/b", persistent=False,
                    run=run2).delete()
    assert run2.cmds == []


def test_mount_mode_uses_gcsfuse():
    st = storage.Storage(name="ckpts", run=FakeRun())
    (cmd,) = st.attach_commands("/outputs")
    assert "gcsfuse" in cmd
    assert "/outputs" in cmd


def test_storage_yaml_roundtrip():
    cfg = {"name": "bkt", "mode": "COPY", "persistent": False}
    st = storage.Storage.from_yaml_config(cfg, run=FakeRun())
    assert st.mode == storage.StorageMode.COPY
    assert not st.persistent
    out = st.to_yaml_config()
    assert out["mode"] == "COPY" and out["name"] == "bkt"
    with pytest.raises(exceptions.StorageError):
        storage.Storage.from_yaml_config({"name": "x", "bogus": 1})


def test_mount_command_quoting():
    cmd = mounting_utils.get_mount_cmd("gs://bkt/sub", "/mnt/path")
    assert "gcsfuse" in cmd and " bkt " in cmd and "/mnt/path" in cmd
    assert "sub" not in cmd.split("gcsfuse")[1]  # bucket only, no subpath


def test_skyignore_patterns(tmp_path):
    (tmp_path / ".skyignore").write_text(
        "# comment\n\n*.ckpt\n/secrets\n!keep.ckpt\n")
    pats = storage_utils.read_ignore_patterns(str(tmp_path))
    assert pats == ["*.ckpt", "/secrets"]  # comments/blank/negation dropped
    args = storage_utils.rsync_exclude_args(str(tmp_path))
    assert args[:2] == ["--exclude", ".git"]
    assert "*.ckpt" in args


def test_gitignore_fallback(tmp_path):
    (tmp_path / ".gitignore").write_text("node_modules\n")
    assert storage_utils.read_ignore_patterns(str(tmp_path)) == [
        "node_modules"]


def test_cloud_stores_registry():
    gs = cloud_stores.get_storage_from_path("gs://b/x")
    assert "gcloud storage rsync" in gs.make_sync_dir_command("gs://b/x",
                                                              "/d")
    http = cloud_stores.get_storage_from_path("https://host/f.bin")
    assert "curl" in http.make_sync_file_command("https://host/f.bin",
                                                 "/tmp/f.bin")
    with pytest.raises(ValueError):
        cloud_stores.get_storage_from_path("ftp://x/y")


def test_data_transfer_commands():
    from skypilot_tpu.data import data_transfer as dt

    rec = []

    def run(cmd):
        rec.append(cmd)
        return 0, ""

    dt.transfer("s3://src-bkt", "gs://dst-bkt", run=run)
    assert "transfer jobs create" in rec[0] and "s3://src-bkt" in rec[0]
    dt.transfer("gs://a", "gs://b", run=run)
    assert "rsync -r gs://a gs://b" in rec[1]
    dt.transfer("/tmp/x", "gs://b", run=run)
    assert "rsync -r /tmp/x gs://b" in rec[2]
    dt.transfer("gs://b/sub", "/tmp/y", run=run)
    assert "gs://b/sub /tmp/y" in rec[3]
    with pytest.raises(exceptions.StorageError):
        dt.transfer("/tmp/a", "/tmp/b", run=run)

    def fail(cmd):
        return 1, "denied"

    with pytest.raises(exceptions.StorageError):
        dt.transfer("gs://a", "gs://b", run=fail)


def test_data_transfer_rejects_gs_to_s3_and_copies_files(tmp_path):
    from skypilot_tpu.data import data_transfer as dt

    rec = []

    def run(cmd):
        rec.append(cmd)
        return 0, ""

    with pytest.raises(exceptions.StorageError):
        dt.transfer("gs://bkt", "s3://dst", run=run)

    f = tmp_path / "model.bin"
    f.write_text("x")
    dt.transfer(str(f), "gs://bkt/ckpt/model.bin", run=run)
    assert rec and rec[-1].startswith("gcloud storage cp ")


def test_s3_store_lifecycle_commands():
    run = FakeRun()
    st = storage.S3Store("mybkt", run=run)
    st.create(region="us-west-2")
    st.upload("/tmp/data")
    st.delete()
    assert any("create-bucket --bucket mybkt" in c
               and "us-west-2" in c for c in run.cmds)
    assert any("s3 sync" in c and "s3://mybkt" in c for c in run.cmds)
    assert any("s3 rb s3://mybkt --force" in c for c in run.cmds)


def test_s3_external_source_copy_and_mount():
    run = FakeRun()
    st = storage.Storage(source="s3://corp-data/sets/v1",
                        mode=storage.StorageMode.COPY, run=run)
    cmds = st.attach_commands("/data")
    assert any("aws s3 sync s3://corp-data/sets/v1" in c for c in cmds)
    st2 = storage.Storage(source="s3://corp-data/sets/v1",
                          mode=storage.StorageMode.MOUNT, run=run)
    (mount_cmd,) = st2.attach_commands("/data")
    assert "goofys" in mount_cmd and "corp-data:sets/v1" in mount_cmd


def test_s3_store_yaml_roundtrip():
    run = FakeRun()
    st = storage.Storage(name="newbkt", store="s3", run=run,
                         mode=storage.StorageMode.COPY, persistent=False)
    cfg = st.to_yaml_config()
    st2 = storage.Storage.from_yaml_config(cfg, run=run)
    assert st2.mode == storage.StorageMode.COPY
    assert not st2.persistent


def test_s3_cloud_store_file_mount_commands():
    st = cloud_stores.get_storage_from_path("s3://bkt/dir")
    assert "aws s3 sync" in st.make_sync_dir_command("s3://bkt/dir", "/d")
    assert "aws s3 cp" in st.make_sync_file_command("s3://bkt/f.txt", "/d/f")


def test_storage_yaml_preserves_s3_scheme():
    run = FakeRun()
    st = storage.Storage(name="nb", store="s3", run=run)
    cfg = st.to_yaml_config()
    assert cfg["store"] == "s3"
    st2 = storage.Storage.from_yaml_config(cfg, run=run)
    assert st2.store.SCHEME == "s3"


# -- Cloudflare R2 (S3 API + account endpoint) ------------------------------

@pytest.fixture()
def r2_config(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "h"))
    monkeypatch.setenv("R2_ENDPOINT",
                       "https://acct.r2.cloudflarestorage.com")


def test_r2_store_lifecycle_commands(r2_config):
    run = FakeRun()
    st = storage.R2Store("r2bucket", run=run)
    st.exists()
    st.create()
    st.delete()
    for cmd in run.cmds:
        assert "--endpoint-url https://acct.r2.cloudflarestorage.com" \
            in cmd
        assert "--profile r2" in cmd
    # The CLI speaks s3://, never r2://.
    assert any("s3 rb s3://r2bucket" in c for c in run.cmds)


def test_r2_storage_from_url(r2_config, tmp_path):
    run = FakeRun()
    st = storage.Storage(source="r2://r2bucket/data", run=run)
    assert st.store.SCHEME == "r2"
    assert st.store.url == "r2://r2bucket/data"
    cmd = st.store.copy_down_command("/dst")
    assert "s3://r2bucket/data" in cmd and "--endpoint-url" in cmd
    mount = st.store.mount_command("/mnt")
    assert "goofys" in mount
    assert "--endpoint https://acct.r2.cloudflarestorage.com" in mount
    assert "--profile r2" in mount


def test_r2_requires_endpoint(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "h"))
    monkeypatch.delenv("R2_ENDPOINT", raising=False)
    st = storage.R2Store("b", run=FakeRun())
    with pytest.raises(exceptions.StorageError, match="endpoint"):
        st.exists()


def test_r2_cloud_store_commands(r2_config):
    cs = cloud_stores.get_storage_from_path("r2://bkt/sub/f.txt")
    f = cs.make_sync_file_command("r2://bkt/sub/f.txt", "/d/f.txt")
    assert "s3://bkt/sub/f.txt" in f and "--endpoint-url" in f
    auto = cs.make_sync_auto_command("r2://bkt/sub/name", "/d/name")
    assert "head-object --bucket bkt --key sub/name" in auto
    assert "--endpoint-url" in auto


# -- Azure Blob (container-centric az://) -----------------------------------

@pytest.fixture()
def az_config(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "h"))
    monkeypatch.setenv("AZURE_STORAGE_ACCOUNT", "skyacct")


def test_az_store_lifecycle_commands(az_config):
    run = FakeRun(out="true")
    st = storage.AzureBlobStore("cont", run=run)
    assert st.exists()
    st.create()
    st.delete()
    for cmd in run.cmds:
        assert "--account-name skyacct" in cmd
        assert "--auth-mode login" in cmd
    assert any("container create --account-name" in c for c in run.cmds)
    assert any("container delete" in c for c in run.cmds)


def test_az_upload_file_vs_dir(az_config, tmp_path):
    run = FakeRun()
    st = storage.AzureBlobStore("cont", run=run)
    f = tmp_path / "cfg.json"
    f.write_text("{}")
    st.upload(str(f), "run1/mount0")
    assert any("blob upload" in c and "run1/mount0/cfg.json" in c
               for c in run.cmds)
    d = tmp_path / "dir"
    d.mkdir()
    st.upload(str(d), "run1/workdir")
    sync = [c for c in run.cmds if "blob sync" in c]
    # azcopy-backed sync: -d destination flag, and NO --auth-mode
    # (the CLI rejects it there).
    assert sync and "-d run1/workdir" in sync[0]
    assert "--auth-mode" not in sync[0]


def test_az_storage_from_url_and_mount(az_config):
    st = storage.Storage(source="az://cont/sub", run=FakeRun())
    assert st.store.SCHEME == "az"
    down = st.store.copy_down_command("/dst")
    # Subpath COPY goes via a temp dir: download-batch recreates full
    # blob paths, so the prefix contents move to /dst (gs/s3 parity).
    assert "download-batch" in down and "--pattern 'sub/*'" in down
    assert "mktemp -d" in down and "cp -a" in down
    mount = st.store.mount_command("/mnt")
    assert "blobfuse2 mount" in mount
    assert "AZURE_STORAGE_ACCOUNT=skyacct" in mount
    assert "--subdirectory=sub/" in mount


def test_az_requires_account(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "h"))
    monkeypatch.delenv("AZURE_STORAGE_ACCOUNT", raising=False)
    with pytest.raises(exceptions.StorageError, match="storage account"):
        storage.AzureBlobStore("c", run=FakeRun()).exists()


def test_az_cloud_store_commands(az_config):
    cs = cloud_stores.get_storage_from_path("az://cont/sub/f.txt")
    f = cs.make_sync_file_command("az://cont/sub/f.txt", "/d/f.txt")
    assert "blob download" in f and "--name sub/f.txt" in f
    auto = cs.make_sync_auto_command("az://cont/sub/name", "/d/name")
    assert "blob exists" in auto and "--query exists" in auto
    # exit-code-0-with-answer-on-stdout: failure is loud, true -> file.
    assert "exit 1" in auto and "grep -qi true" in auto


# -- IBM COS (region-qualified cos://) --------------------------------------

@pytest.fixture()
def cos_config(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "h"))


def test_cos_storage_from_url(cos_config):
    """cos URLs carry the region first (reference IBMCosStore URL form:
    cos://<region>/<bucket>/path)."""
    run = FakeRun()
    st = storage.Storage(source="cos://us-south/cosbucket/data", run=run)
    assert st.store.SCHEME == "cos"
    assert st.store.name == "cosbucket"
    assert st.store.region == "us-south"
    assert st.store.url == "cos://us-south/cosbucket/data"
    cmd = st.store.copy_down_command("/dst")
    assert "s3://cosbucket/data" in cmd
    assert ("--endpoint-url https://s3.us-south.cloud-object-storage"
            ".appdomain.cloud" in cmd)
    assert "--profile ibm" in cmd
    mount = st.store.mount_command("/mnt")
    assert "goofys" in mount and "us-south" in mount


def test_cos_url_without_bucket_rejected(cos_config):
    with pytest.raises(exceptions.StorageError, match="cos://<region>"):
        storage.Storage(source="cos://us-south", run=FakeRun())


def test_cos_lifecycle_commands(cos_config):
    run = FakeRun()
    st = storage.IbmCosStore("b", run=run, region="eu-de")
    st.exists(); st.create(); st.delete()
    for cmd in run.cmds:
        assert "s3.eu-de.cloud-object-storage.appdomain.cloud" in cmd


def test_cos_cloud_store_commands(cos_config):
    cs = cloud_stores.get_storage_from_path("cos://us-south/bkt/sub/f")
    f = cs.make_sync_file_command("cos://us-south/bkt/sub/f", "/d/f")
    assert "s3://bkt/sub/f" in f and "s3.us-south" in f
    auto = cs.make_sync_auto_command("cos://us-south/bkt/sub/n", "/d/n")
    assert "head-object --bucket bkt --key sub/n" in auto


# -- OCI Object Storage (S3-compat endpoint) --------------------------------

@pytest.fixture()
def oci_config(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "h"))
    monkeypatch.setenv("OCI_NAMESPACE", "mytenancy")
    monkeypatch.setenv("OCI_REGION", "us-ashburn-1")


def test_oci_storage_from_url(oci_config):
    run = FakeRun()
    st = storage.Storage(source="oci://ocibucket/data", run=run)
    assert st.store.SCHEME == "oci"
    assert st.store.url == "oci://ocibucket/data"
    cmd = st.store.copy_down_command("/dst")
    assert "s3://ocibucket/data" in cmd
    assert ("--endpoint-url https://mytenancy.compat.objectstorage"
            ".us-ashburn-1.oraclecloud.com" in cmd)
    assert "--profile oci" in cmd
    mount = st.store.mount_command("/mnt")
    assert "goofys" in mount and "mytenancy.compat" in mount


def test_oci_requires_namespace(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "h"))
    for v in ("OCI_NAMESPACE", "OCI_REGION"):
        monkeypatch.delenv(v, raising=False)
    st = storage.OciStore("b", run=FakeRun())
    with pytest.raises(exceptions.StorageError, match="namespace"):
        st.exists()


def test_oci_cloud_store_commands(oci_config):
    cs = cloud_stores.get_storage_from_path("oci://bkt/sub/f.txt")
    f = cs.make_sync_file_command("oci://bkt/sub/f.txt", "/d/f.txt")
    assert "s3://bkt/sub/f.txt" in f and "compat.objectstorage" in f


def test_cos_bucket_root_syncs_as_directory(cos_config):
    """cos://<region>/<bucket> (no subpath) must take the dir-sync path
    — an auto probe would run head-object with an empty --key."""
    cs = cloud_stores.get_storage_from_path("cos://us-south/bkt")
    cmd = cs.make_sync_auto_command("cos://us-south/bkt", "/d")
    assert "head-object" not in cmd
    assert "s3 sync" in cmd and "s3://bkt" in cmd
    # Same guard on the generic S3 family.
    s3 = cloud_stores.get_storage_from_path("s3://bkt")
    assert "head-object" not in s3.make_sync_auto_command("s3://bkt", "/d")


def test_cos_named_store_create_repins_region(cos_config):
    """sync_up(region=...) on a named cos store must move the ENDPOINT,
    not send a mismatched LocationConstraint to the default region."""
    run = FakeRun()
    st = storage.IbmCosStore("b", run=run)
    st.create(region="eu-de")
    assert st.region == "eu-de"
    assert any("s3.eu-de.cloud-object-storage" in c for c in run.cmds)
    assert not any("LocationConstraint" in c for c in run.cmds)
