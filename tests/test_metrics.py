"""Observability metrics core: registry semantics, label handling,
histogram bucket boundaries, concurrency, and the Prometheus text
exposition format (golden test + parser round-trip)."""

import json
import threading

import pytest

from skypilot_tpu.observability import metrics
from skypilot_tpu.utils import timeline


# -- counters / gauges ------------------------------------------------------

def test_counter_basics():
    reg = metrics.Registry()
    c = reg.counter("c_total", "help")
    c.inc()
    c.inc(2.5)
    assert reg.get("c_total")._require_default().value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_inc_dec():
    reg = metrics.Registry()
    g = reg.gauge("g", "help")
    g.set(10)
    g.dec(3)
    g.inc()
    assert g._require_default().value == 8


def test_labeled_metric_rejects_direct_use():
    reg = metrics.Registry()
    c = reg.counter("c_total", "", labelnames=("route",))
    with pytest.raises(ValueError):
        c.inc()
    c.labels(route="/x").inc()
    assert c.labels("/x").value == 1


def test_label_cardinality_and_identity():
    reg = metrics.Registry()
    c = reg.counter("c_total", "", labelnames=("a", "b"))
    c.labels("1", "x").inc()
    c.labels(a="1", b="x").inc()          # same child, either style
    c.labels("2", "x").inc()
    assert c.labels("1", "x").value == 2
    assert len(c.children()) == 2
    with pytest.raises(ValueError):
        c.labels("1")                     # wrong arity
    with pytest.raises(ValueError):
        c.labels(a="1", wrong="x")        # wrong names
    with pytest.raises(ValueError):
        c.labels("1", b="x")              # mixed styles


def test_registry_redeclare_conflicts():
    reg = metrics.Registry()
    c = reg.counter("m", "")
    assert reg.counter("m", "") is c      # idempotent re-declare
    with pytest.raises(ValueError):
        reg.gauge("m", "")                # same name, new type
    reg.counter("l", "", labelnames=("x",))
    with pytest.raises(ValueError):
        reg.counter("l", "", labelnames=("y",))   # new labels
    with pytest.raises(ValueError):
        reg.register(metrics.Counter("m"))
    h = reg.histogram("hb", "", buckets=(0.1, 1.0))
    assert reg.histogram("hb", "", buckets=(1.0, 0.1)) is h  # order-free
    with pytest.raises(ValueError):
        reg.histogram("hb", "", buckets=(0.5, 5.0))   # new buckets


def test_labeled_counter_children_are_monotone():
    reg = metrics.Registry()
    c = reg.counter("c_total", "", labelnames=("k",))
    child = c.labels(k="a")
    child.inc(2)
    with pytest.raises(ValueError):
        child.inc(-1)                     # would read as a reset
    with pytest.raises(TypeError):
        child.dec()
    with pytest.raises(TypeError):
        child.set(0)
    assert child.value == 2


# -- histograms -------------------------------------------------------------

def test_histogram_bucket_boundaries_le_inclusive():
    reg = metrics.Registry()
    h = reg.histogram("h", "", buckets=(0.1, 1.0, 10.0))
    for v in (0.1, 0.05, 1.0, 5.0, 100.0):
        h.observe(v)
    (_, child), = h.children()
    counts, total = child.hist_state()
    # le=0.1 gets 0.05 AND the exactly-on-boundary 0.1.
    assert counts == [2, 1, 1, 1]
    assert total == pytest.approx(106.15)
    rendered = reg.render()
    assert 'h_bucket{le="0.1"} 2' in rendered      # cumulative
    assert 'h_bucket{le="1"} 3' in rendered
    assert 'h_bucket{le="10"} 4' in rendered
    assert 'h_bucket{le="+Inf"} 5' in rendered
    assert "h_count 5" in rendered


def test_histogram_rejects_bad_buckets():
    reg = metrics.Registry()
    with pytest.raises(ValueError):
        reg.histogram("h1", "", buckets=())
    with pytest.raises(ValueError):
        reg.histogram("h2", "", buckets=(1.0, 1.0))


def test_histogram_timer():
    reg = metrics.Registry()
    h = reg.histogram("h", "", buckets=(10.0,))
    with h.time():
        pass
    (_, child), = h.children()
    counts, total = child.hist_state()
    assert sum(counts) == 1 and 0 <= total < 10


def test_suppress_discards_this_threads_observations():
    reg = metrics.Registry()
    c = reg.counter("c_total", "", labelnames=("k",))
    g = reg.gauge("g", "")
    h = reg.histogram("h_seconds", "", buckets=(1.0,))
    g.set(5)
    with metrics.suppress():
        c.labels(k="a").inc()
        g.set(99)
        g.dec(2)
        h.observe(0.5)
        with metrics.suppress():      # nesting is fine
            h.observe(0.5)
    assert c.labels(k="a").value == 0  # child exists, value untouched
    assert g._require_default().value == 5
    (_, child), = h.children()
    counts, hsum = child.hist_state()
    assert sum(counts) == 0 and hsum == 0
    h.observe(0.25)                    # recording resumes after exit
    counts, _ = child.hist_state()
    assert sum(counts) == 1
    # Suppression is per-thread: a concurrent recorder is unaffected.
    with metrics.suppress():
        t = threading.Thread(target=lambda: c.labels(k="b").inc())
        t.start()
        t.join()
    assert c.labels(k="b").value == 1


# -- concurrency ------------------------------------------------------------

def test_concurrent_increments_are_exact():
    reg = metrics.Registry()
    c = reg.counter("c_total", "", labelnames=("t",))
    h = reg.histogram("h", "", buckets=(0.5, 1.5))
    n_threads, per_thread = 8, 500

    def work(i):
        for _ in range(per_thread):
            c.labels(t=str(i % 2)).inc()
            h.observe(1.0)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(child.value for _, child in c.children())
    assert total == n_threads * per_thread
    (_, child), = h.children()
    counts, hsum = child.hist_state()
    assert sum(counts) == n_threads * per_thread
    assert hsum == pytest.approx(n_threads * per_thread * 1.0)


# -- exposition format ------------------------------------------------------

def test_exposition_golden():
    reg = metrics.Registry()
    c = reg.counter("skytpu_reqs_total", "Requests served",
                    labelnames=("route",))
    c.labels(route="/generate").inc(3)
    g = reg.gauge("skytpu_slots", "Active slots")
    g.set(2)
    h = reg.histogram("skytpu_lat_seconds", "Latency",
                      buckets=(0.5, 2.5))
    h.observe(0.2)
    h.observe(7.0)
    assert reg.render() == (
        "# HELP skytpu_lat_seconds Latency\n"
        "# TYPE skytpu_lat_seconds histogram\n"
        'skytpu_lat_seconds_bucket{le="0.5"} 1\n'
        'skytpu_lat_seconds_bucket{le="2.5"} 1\n'
        'skytpu_lat_seconds_bucket{le="+Inf"} 2\n'
        "skytpu_lat_seconds_sum 7.2\n"
        "skytpu_lat_seconds_count 2\n"
        "# HELP skytpu_reqs_total Requests served\n"
        "# TYPE skytpu_reqs_total counter\n"
        'skytpu_reqs_total{route="/generate"} 3\n'
        "# HELP skytpu_slots Active slots\n"
        "# TYPE skytpu_slots gauge\n"
        "skytpu_slots 2\n")


def test_exposition_escapes_label_values():
    reg = metrics.Registry()
    c = reg.counter("c_total", 'multi\nline "help"', labelnames=("v",))
    c.labels(v='a"b\\c\nd').inc()
    out = reg.render()
    assert '# HELP c_total multi\\nline "help"' in out
    assert 'c_total{v="a\\"b\\\\c\\nd"} 1' in out
    # And the parser round-trips the escaped value.
    fam = metrics.parse_exposition(out)["c_total"]
    (labels, value), = fam["samples"]
    assert labels == {"v": 'a"b\\c\nd'} and value == 1
    # Literal backslash followed by 'n' must NOT decode as a newline
    # (ordered str.replace chains get this wrong).
    c.labels(v="a\\nb").inc()
    fam = metrics.parse_exposition(reg.render())["c_total"]
    values = {labels["v"] for labels, _ in fam["samples"]}
    assert "a\\nb" in values


def test_parse_exposition_roundtrip():
    reg = metrics.Registry()
    reg.counter("a_total", "", labelnames=("x", "y")).labels(
        x="1,2", y="z").inc(4)
    reg.gauge("b", "").set(-1.5)
    h = reg.histogram("c_seconds", "", labelnames=("op",),
                      buckets=(1.0,))
    h.labels(op="p").observe(0.5)
    fams = metrics.parse_exposition(reg.render())
    assert fams["a_total"]["type"] == "counter"
    assert fams["a_total"]["samples"] == [({"x": "1,2", "y": "z"}, 4.0)]
    assert fams["b"]["samples"] == [({}, -1.5)]
    hist = fams["c_seconds"]
    assert hist["type"] == "histogram"
    count = next(v for labels, v in hist["samples"]
                 if labels.get("__name__") == "c_seconds_count")
    assert count == 1.0


def test_snapshot_is_json_able():
    reg = metrics.Registry()
    reg.counter("a_total", "h").inc(2)
    h = reg.histogram("b_seconds", "", buckets=(1.0,))
    h.observe(0.5)
    snap = json.loads(json.dumps(reg.snapshot()))
    assert snap["a_total"]["samples"][0]["value"] == 2
    assert snap["b_seconds"]["samples"][0]["count"] == 1
    assert snap["b_seconds"]["samples"][0]["buckets"]["1"] == 1


def test_global_registry_sugar():
    before = metrics.REGISTRY.get("skytpu_test_sugar_total")
    assert before is None
    c = metrics.counter("skytpu_test_sugar_total", "t")
    assert metrics.counter("skytpu_test_sugar_total", "t") is c
    assert "skytpu_test_sugar_total" in metrics.render()


# -- timeline bridge --------------------------------------------------------

def test_timeline_event_records_histogram_without_tracing(monkeypatch):
    monkeypatch.delenv(timeline.ENV_VAR, raising=False)
    timeline._events.clear()
    reg = metrics.Registry()
    h = reg.histogram("span_seconds", "", buckets=(60.0,))
    with timeline.Event("span_seconds", histogram=h._require_default()):
        pass
    (_, child), = h.children()
    counts, _ = child.hist_state()
    assert sum(counts) == 1
    assert not timeline._events        # tracing stayed off


def test_timeline_decorator_histogram_bridge(monkeypatch, tmp_path):
    reg = metrics.Registry()
    h = reg.histogram("op_seconds", "", buckets=(60.0,))

    @timeline.event(name="op_seconds", histogram=h._require_default())
    def op():
        return 7

    monkeypatch.delenv(timeline.ENV_VAR, raising=False)
    assert op() == 7
    # Now with tracing on: same call double-records trace + histogram.
    out = tmp_path / "t.json"
    monkeypatch.setenv(timeline.ENV_VAR, str(out))
    try:
        assert op() == 7
        timeline.save_now()
        (_, child), = h.children()
        counts, _ = child.hist_state()
        assert sum(counts) == 2
        names = [e["name"] for e in
                 json.loads(out.read_text())["traceEvents"]]
        assert "op_seconds" in names
    finally:
        # The buffer is process-global; don't leak our span into later
        # tests that assert tracing-off leaves it empty.
        timeline._events.clear()
        timeline._named_tids.clear()
