"""Regression tests for review findings: FIFO serialization, autostop
daemon, cost accounting, log tailing of unknown jobs."""

import os
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import exceptions, state
from skypilot_tpu.backend import TpuVmBackend
from skypilot_tpu.resources import Resources
from skypilot_tpu.runtime.job_queue import JobStatus
from skypilot_tpu.task import Task


@pytest.fixture(autouse=True)
def sky_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    monkeypatch.setenv("SKYTPU_SKYLET_POLL", "0.2")


def _local_task(run, name=None):
    t = Task(name=name, run=run)
    t.set_resources(Resources(cloud="local"))
    return t


def test_jobs_run_fifo_one_at_a_time():
    """Two jobs on one cluster must serialize, not run concurrently."""
    marker = "fifo_marker"
    # Job 1 sleeps then writes its end time; job 2 writes its start time.
    j1, handle = sky.launch(
        _local_task(f"sleep 1; date +%s.%N > {marker}.end1"),
        cluster_name="fifo")
    j2, _ = sky.exec(_local_task(f"date +%s.%N > {marker}.start2"),
                     cluster_name="fifo")
    backend = TpuVmBackend()
    assert backend.wait_job(handle, j1, 120) == JobStatus.SUCCEEDED
    assert backend.wait_job(handle, j2, 120) == JobStatus.SUCCEEDED
    from skypilot_tpu.provision import local as lp
    ws = lp.get_cluster_info("fifo", "local").hosts[0].workspace
    end1 = float(open(os.path.join(ws, f"{marker}.end1")).read())
    start2 = float(open(os.path.join(ws, f"{marker}.start2")).read())
    assert start2 >= end1, "job 2 started before job 1 finished"


def test_cancel_pending_job():
    j1, handle = sky.launch(_local_task("sleep 5"), cluster_name="cpend")
    j2, _ = sky.exec(_local_task("echo never"), cluster_name="cpend")
    sky.cancel("cpend", j2)
    sky.cancel("cpend", j1)
    backend = TpuVmBackend()
    deadline = time.time() + 10
    while time.time() < deadline:
        q = {j["job_id"]: j["status"] for j in sky.queue("cpend")}
        if q[j1] == JobStatus.CANCELLED and q[j2] == JobStatus.CANCELLED:
            return
        time.sleep(0.1)
    raise AssertionError(f"jobs not cancelled: {q}")


def test_autostop_daemon_stops_idle_cluster():
    """The skylet stops the cluster CLOUD-side; the client's state DB is
    reconciled on the next `status --refresh` (reference semantics:
    skylet/events.py:102 acts on the VM, clients catch up)."""
    j, handle = sky.launch(_local_task("echo done"), cluster_name="auto1",
                           idle_minutes_to_autostop=0)
    TpuVmBackend().wait_job(handle, j, 120)
    from skypilot_tpu.provision import local as lp
    deadline = time.time() + 10
    while time.time() < deadline:
        if lp.query_instances("auto1", "local") == "STOPPED":
            break
        time.sleep(0.2)
    else:
        raise AssertionError("autostop did not stop cluster cloud-side")
    records = sky.status(["auto1"], refresh=True)
    assert records[0]["status"] == state.ClusterStatus.STOPPED


def test_autodown_daemon_removes_cluster():
    j, handle = sky.launch(_local_task("echo done"), cluster_name="auto2")
    TpuVmBackend().wait_job(handle, j, 120)
    sky.autostop("auto2", 0, down_=True)
    from skypilot_tpu.provision import local as lp
    deadline = time.time() + 10
    while time.time() < deadline:
        if lp.query_instances("auto2", "local") == "NOT_FOUND":
            break
        time.sleep(0.2)
    else:
        raise AssertionError("autodown did not remove cluster cloud-side")
    assert sky.status(["auto2"], refresh=True) == []
    assert state.get_cluster("auto2") is None


def test_cost_report_whole_cluster_price():
    t = Task(name="multi", run="echo x", num_nodes=4)
    t.set_resources(Resources(cloud="local"))
    j, handle = sky.launch(t, cluster_name="cost4")
    TpuVmBackend().wait_job(handle, j, 120)
    # Fake a known price then tear down.
    rec = state.get_cluster("cost4")
    state.set_cluster("cost4", rec["handle"], state.ClusterStatus.UP,
                      price_per_hour=36.0)  # whole-cluster $/hr
    sky.down("cost4")
    report = {r["name"]: r for r in sky.cost_report()}
    r = report["cost4"]
    # cost must be duration * 36/3600, NOT additionally * num_nodes.
    expected = r["duration_s"] / 3600.0 * 36.0
    assert abs(r["cost"] - expected) < 1e-6


def test_tail_logs_unknown_job_raises():
    j, handle = sky.launch(_local_task("echo x"), cluster_name="logx")
    TpuVmBackend().wait_job(handle, j, 120)
    with pytest.raises(exceptions.JobNotFoundError):
        sky.tail_logs("logx", 999, follow=True)
