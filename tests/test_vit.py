"""ViT model family: shapes, sharded training, learnability."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import vit
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import trainer


@pytest.fixture(scope="module")
def cfg():
    return vit.CONFIGS["vit-tiny"]


@pytest.fixture(scope="module")
def params(cfg):
    return vit.init_params(jax.random.key(0), cfg)


def test_forward_shapes(cfg, params):
    batch = vit.synthetic_batch(cfg, 2)
    logits = jax.jit(lambda p, x: vit.forward(p, x, cfg))(
        params, batch["images"])
    assert logits.shape == (2, cfg.num_classes)
    assert np.isfinite(np.asarray(logits)).all()


def test_patchify_roundtrip(cfg):
    imgs = jnp.arange(2 * 32 * 32 * 3, dtype=jnp.float32).reshape(
        2, 32, 32, 3)
    patches = vit.patchify(imgs, cfg)
    assert patches.shape == (2, cfg.n_patches, cfg.patch_size ** 2 * 3)
    # First patch = top-left 8x8 block, row-major.
    np.testing.assert_array_equal(
        np.asarray(patches[0, 0]).reshape(8, 8, 3),
        np.asarray(imgs[0, :8, :8, :]))


def test_param_count_matches(cfg, params):
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert n == cfg.num_params()


def test_sharded_train_step(cfg):
    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(dp=2, fsdp=2, tp=2))
    tc = trainer.TrainConfig(warmup_steps=1, total_steps=4)
    state = trainer.create_train_state(cfg, tc, mesh, model=vit)
    step = trainer.make_train_step(cfg, tc, mesh, model=vit)
    batch = vit.synthetic_batch(cfg, 8)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    wu = state["params"]["blocks"]["w_up"]
    assert len(wu.sharding.device_set) == 8


def test_memorizes_fixed_batch(cfg):
    tc = trainer.TrainConfig(learning_rate=3e-3, warmup_steps=1,
                             total_steps=30)
    state = trainer.create_train_state(cfg, tc, None, model=vit)
    step = trainer.make_train_step(cfg, tc, None, model=vit)
    batch = vit.synthetic_batch(cfg, 4)
    first = None
    for _ in range(12):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first
