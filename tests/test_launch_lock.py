"""Per-cluster launch lock: racing clients produce one cluster.

Reference parity: sky/backends/cloud_vm_ray_backend.py:2846 (every
provision runs under a per-cluster file lock).
"""

import threading
import time

import pytest

from skypilot_tpu.utils import timeline


@pytest.fixture(autouse=True)
def sky_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    monkeypatch.setenv("SKYTPU_LOCAL_CLUSTERS_ROOT", str(tmp_path / "cloud"))


def test_filelock_mutual_exclusion(tmp_path):
    """Two threads (distinct fds, same process) exclude each other —
    the flock is per open-file-description, not per process."""
    lockfile = str(tmp_path / "x.lock")
    active = []
    overlaps = []

    def worker():
        with timeline.FileLockEvent(lockfile):
            active.append(1)
            overlaps.append(len(active))
            time.sleep(0.15)
            active.pop()

    threads = [threading.Thread(target=worker) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert max(overlaps) == 1


def test_filelock_timeout(tmp_path):
    lockfile = str(tmp_path / "y.lock")
    held = timeline.FileLockEvent(lockfile)
    held.acquire()
    try:
        with pytest.raises(TimeoutError):
            timeline.FileLockEvent(lockfile, timeout=0.3).acquire()
    finally:
        held.release()
    # Released: a timed acquire now succeeds.
    with timeline.FileLockEvent(lockfile, timeout=1.0):
        pass


def test_concurrent_launch_one_cluster_one_provision():
    """Two clients racing `launch -c same` -> ONE cluster, ONE
    provision call (the second sees the first's UP record and reuses
    it)."""
    from skypilot_tpu import state
    from skypilot_tpu.backend import TpuVmBackend
    from skypilot_tpu.provision import local as lp
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task

    calls = []
    real_run = lp.run_instances

    def counting_run(config):
        calls.append(config.cluster_name)
        return real_run(config)

    lp.run_instances = counting_run
    try:
        task = Task(run="true")
        task.set_resources(Resources(cloud="local"))
        backend = TpuVmBackend()
        results, errors = [], []

        def one():
            try:
                results.append(backend.provision(task, "race"))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=one) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert len(results) == 2
        assert all(h.cluster_name == "race" for h in results)
        assert calls == ["race"], calls  # exactly one provision
        assert state.get_cluster("race") is not None
        backend.teardown(results[0])
    finally:
        lp.run_instances = real_run
