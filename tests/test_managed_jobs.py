"""Managed jobs: submit/succeed, preemption recovery, cancel, strategies.

The controllers run as processes ON the jobs controller cluster
(controller-as-task, VERDICT r1 #3); the client talks to them only
through the typed RPC, so these tests exercise the full recursion:
client -> controller cluster -> per-job cluster.

Preemption is simulated by terminating the job's cluster out-of-band
(the reference does the same with real instance termination in its smoke
tests, tests/smoke_tests/test_managed_job.py — here against the local
fake cloud)."""

import time

import pytest

from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.provision import local as local_provider
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task


@pytest.fixture(autouse=True)
def sky_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    monkeypatch.setenv("SKYTPU_LOCAL_CLUSTERS_ROOT", str(tmp_path / "cloud"))
    monkeypatch.setenv("SKYTPU_JOBS_POLL", "0.2")


def _task(run, name=None):
    t = Task(name=name, run=run)
    t.set_resources(Resources(cloud="local"))
    return t


def _wait_cluster_gone(cluster_name, timeout=15):
    """Terminal status lands before the controller's finally-cleanup."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if local_provider.query_instances(cluster_name,
                                          "local") == "NOT_FOUND":
            return
        time.sleep(0.2)
    raise AssertionError(f"cluster {cluster_name} not cleaned up")


def test_managed_job_succeeds():
    jid = jobs_core.launch(_task("echo managed-ok"), name="mj1")
    status = jobs_core.wait(jid, timeout=120)
    assert status == ManagedJobStatus.SUCCEEDED
    rec = jobs_core.get(jid)
    assert rec["recovery_count"] == 0
    _wait_cluster_gone(rec["cluster_name"])


def test_managed_job_user_failure_no_recovery():
    """A task that fails on a healthy cluster must NOT be retried."""
    jid = jobs_core.launch(_task("exit 7"), name="mj2")
    status = jobs_core.wait(jid, timeout=120)
    assert status == ManagedJobStatus.FAILED
    assert jobs_core.get(jid)["recovery_count"] == 0


def test_managed_job_recovers_from_preemption():
    jid = jobs_core.launch(_task("sleep 4 && echo survived"), name="mj3")
    # Wait for RUNNING, then preempt: terminate the cluster out-of-band.
    deadline = time.time() + 60
    while time.time() < deadline:
        rec = jobs_core.get(jid)
        if (rec["status"] == ManagedJobStatus.RUNNING
                and rec["cluster_name"]
                and local_provider.query_instances(
                    rec["cluster_name"], "local") == "UP"):
            break
        time.sleep(0.1)
    else:
        raise AssertionError(f"job never reached RUNNING: {rec}")
    time.sleep(0.5)  # let the task actually start
    local_provider.terminate_instances(rec["cluster_name"], "local")

    status = jobs_core.wait(jid, timeout=120)
    rec = jobs_core.get(jid)
    assert status == ManagedJobStatus.SUCCEEDED, rec
    assert rec["recovery_count"] >= 1


def test_managed_job_cancel():
    jid = jobs_core.launch(_task("sleep 60"), name="mj4")
    deadline = time.time() + 60
    while jobs_core.get(jid)["status"] not in (ManagedJobStatus.RUNNING,):
        assert time.time() < deadline
        time.sleep(0.1)
    jobs_core.cancel(jid)
    status = jobs_core.wait(jid, timeout=120)
    assert status == ManagedJobStatus.CANCELLED
    rec = jobs_core.get(jid)
    _wait_cluster_gone(rec["cluster_name"])


def test_unknown_strategy_rejected():
    t = _task("echo x")
    t.set_resources(Resources(cloud="local", job_recovery="NOPE"))
    jid = jobs_core.launch(t)
    status = jobs_core.wait(jid, timeout=60)
    assert status == ManagedJobStatus.FAILED_CONTROLLER


def test_queue_lists_jobs():
    j1 = jobs_core.launch(_task("echo a"), name="qa")
    jobs_core.wait(j1, timeout=120)
    rows = jobs_core.queue()
    assert any(r["job_id"] == j1 and r["name"] == "qa" for r in rows)


def test_controller_log_streams_to_client():
    """VERDICT r1 #10: controller logs surface through the client."""
    import io
    jid = jobs_core.launch(_task("echo logged"), name="mjlog")
    jobs_core.wait(jid, timeout=120)
    buf = io.StringIO()
    jobs_core.tail_controller_log(jid, out=buf)
    assert buf.getvalue()  # controller wrote its lifecycle to the log


def test_launching_parallelism_gate(monkeypatch):
    """VERDICT r1 #10: a burst of managed jobs launches at most k
    clusters at a time (reference: sky/jobs/scheduler.py:72)."""
    monkeypatch.setenv("SKYTPU_JOBS_MAX_LAUNCHES", "1")
    jids = [jobs_core.launch(_task("echo x"), name=f"burst{i}")
            for i in range(3)]
    for j in jids:
        assert jobs_core.wait(j, timeout=180) == ManagedJobStatus.SUCCEEDED
    windows = []
    for j in jids:
        rec = jobs_core.get(j)
        assert rec["launch_started_at"] and rec["launch_ended_at"]
        windows.append((rec["launch_started_at"], rec["launch_ended_at"]))
    windows.sort()
    for (s1, e1), (s2, e2) in zip(windows, windows[1:]):
        assert e1 <= s2, f"launch windows overlap: {windows}"


def test_jobs_survive_client_death(tmp_path, monkeypatch):
    """The controller cluster owns the job: wiping the client's home
    mid-run must not stop monitoring/recovery/cleanup."""
    import shutil
    jid = jobs_core.launch(_task("sleep 2; echo ok"), name="mjdeath")
    # Client dies.
    shutil.rmtree(tmp_path / "skyhome", ignore_errors=True)
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "client2"))
    # A fresh client can only see the job if controller state lives on
    # the controller cluster. It has no cluster-state record, so reach
    # the controller via the provider directly.
    from skypilot_tpu import provision
    from skypilot_tpu.controller_utils import JOBS_CONTROLLER_CLUSTER
    from skypilot_tpu.runtime.rpc_client import ClusterRpc
    info = local_provider.get_cluster_info(JOBS_CONTROLLER_CLUSTER, "local")
    rpc = ClusterRpc(provision.get_command_runners(info)[0],
                     JOBS_CONTROLLER_CLUSTER)
    deadline = time.time() + 120
    while time.time() < deadline:
        rec = rpc.call("jobs_get", job_id=jid)
        if rec and ManagedJobStatus(rec["status"]).is_terminal():
            assert ManagedJobStatus(rec["status"]) == \
                ManagedJobStatus.SUCCEEDED
            return
        time.sleep(0.3)
    raise AssertionError("managed job did not finish after client death")


# -- pipelines (reference: multi-document job YAMLs run sequentially) -------

def test_pipeline_runs_tasks_sequentially():
    """Two tasks under ONE managed job: each gets its own cluster, the
    second starts only after the first succeeds, outputs of both are
    snapshotted, and every cluster is gone at the end."""
    import io

    jid = jobs_core.launch([_task("echo step-one", name="a"),
                            _task("echo step-two", name="b")],
                           name="pipe1")
    status = jobs_core.wait(jid, timeout=240)
    assert status == ManagedJobStatus.SUCCEEDED
    rec = jobs_core.get(jid)
    assert rec["num_tasks"] == 2
    assert rec["current_task"] == 1          # finished on the last task
    out = io.StringIO()
    jobs_core.tail_job_output(jid, out=out)
    text = out.getvalue()
    assert "step-one" in text and "step-two" in text
    assert text.index("step-one") < text.index("step-two")
    _wait_cluster_gone(f"sky-jobs-{jid}-t0")
    _wait_cluster_gone(f"sky-jobs-{jid}-t1")


def test_pipeline_failure_stops_chain():
    """A failing step fails the WHOLE pipeline; later tasks never run."""
    jid = jobs_core.launch([_task("exit 3", name="bad"),
                            _task("echo never", name="after")],
                           name="pipe2")
    status = jobs_core.wait(jid, timeout=240)
    assert status == ManagedJobStatus.FAILED
    rec = jobs_core.get(jid)
    assert rec["current_task"] == 0          # died on the first step
    import io
    out = io.StringIO()
    jobs_core.tail_job_output(jid, out=out)
    assert "never" not in out.getvalue()
    _wait_cluster_gone(f"sky-jobs-{jid}-t0")


def test_pipeline_yaml_multi_document(tmp_path):
    """Task.from_yaml_all parses --- separated docs into a pipeline."""
    p = tmp_path / "pipe.yaml"
    p.write_text(
        "name: prep\nresources: {cloud: local}\nrun: echo prep\n"
        "---\n"
        "name: train\nresources: {cloud: local}\nrun: echo train\n")
    tasks = Task.from_yaml_all(str(p))
    assert [t.name for t in tasks] == ["prep", "train"]
    single = Task.from_yaml_all(__file__.replace(
        "test_managed_jobs.py", "../examples/tpu_train_tiny.yaml"))
    assert len(single) == 1


def test_dead_controller_reaped_on_observation():
    """A controller that dies hard (import crash, OOM-kill) must not
    leave its job non-terminal forever: the jobs_list/jobs_get RPC
    sweep marks it FAILED_CONTROLLER (reference: scheduler sweep)."""
    import subprocess

    from skypilot_tpu.jobs import state as jstate

    jid = jstate.add("dead", {"run": "echo hi"}, "EAGER_NEXT_ZONE")
    # A real, already-exited PID (not a made-up number: PID reuse
    # semantics differ).
    proc = subprocess.Popen(["true"])
    proc.wait()
    jstate.set_controller_pid(jid, proc.pid)
    jstate.set_status(jid, jstate.ManagedJobStatus.STARTING)
    assert jstate.reap_dead_controllers() == 1
    assert jstate.get(jid)["status"] == \
        jstate.ManagedJobStatus.FAILED_CONTROLLER
    # Terminal jobs and NULL-pid rows are untouched on a second sweep.
    assert jstate.reap_dead_controllers() == 0


def test_pipeline_cancel_mid_run_stops_chain():
    """Cancel during a pipeline's first (long) task: the job ends
    CANCELLED (via the monitor's CANCELLING check), the second task
    NEVER launches a cluster, and the first task's cluster is torn
    down."""
    jid = jobs_core.launch([_task("sleep 60", name="long"),
                            _task("echo never", name="after")],
                           name="pipecancel")
    deadline = time.time() + 120
    while jobs_core.get(jid)["status"] != ManagedJobStatus.RUNNING:
        assert time.time() < deadline
        time.sleep(0.1)
    jobs_core.cancel(jid)
    status = jobs_core.wait(jid, timeout=120)
    assert status == ManagedJobStatus.CANCELLED
    rec = jobs_core.get(jid)
    assert rec["current_task"] == 0           # never advanced
    _wait_cluster_gone(f"sky-jobs-{jid}-t0")
    assert local_provider.query_instances(f"sky-jobs-{jid}-t1",
                                          "local") == "NOT_FOUND"


def test_pipeline_inter_step_cancel_guard(tmp_path, monkeypatch):
    """The PRE-LAUNCH guard itself: a cancel landing BETWEEN task 0's
    completion and task 1's launch (inter-step teardown takes minutes
    on real clusters) must stop the chain before a new cluster is
    provisioned — driven directly at the controller, since the window
    is unhittable deterministically from outside."""
    from skypilot_tpu.jobs import controller as ctl
    from skypilot_tpu.jobs import state as jstate

    cfg = {"pipeline": [
        {"name": "a", "resources": {"cloud": "local"}, "run": "true"},
        {"name": "b", "resources": {"cloud": "local"}, "run": "true"}]}
    jid = jstate.add("guard", cfg, "EAGER_NEXT_ZONE")
    jstate.set_status(jid, jstate.ManagedJobStatus.RUNNING)
    c = ctl.JobsController(jid)
    c._bind_task(1)
    launched = []
    monkeypatch.setattr(c.strategy, "launch",
                        lambda *a, **k: launched.append(1))
    # The cancel lands in the inter-step window.
    jstate.set_status(jid, jstate.ManagedJobStatus.CANCELLING)
    assert c._run_one_task(1) is False
    assert not launched, "cancelled pipeline still provisioned a cluster"
    assert jstate.get(jid)["status"] == \
        jstate.ManagedJobStatus.CANCELLED
