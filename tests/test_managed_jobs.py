"""Managed jobs: submit/succeed, preemption recovery, cancel, strategies.

Preemption is simulated by terminating the job's cluster out-of-band
(the reference does the same with real instance termination in its smoke
tests, tests/smoke_tests/test_managed_job.py — here against the local
fake cloud)."""

import os
import time

import pytest

from skypilot_tpu import state as cluster_state
from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task


@pytest.fixture(autouse=True)
def sky_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    monkeypatch.setenv("SKYTPU_JOBS_POLL", "0.2")


def _task(run, name=None):
    t = Task(name=name, run=run)
    t.set_resources(Resources(cloud="local"))
    return t


def test_managed_job_succeeds():
    jid = jobs_core.launch(_task("echo managed-ok"), name="mj1")
    status = jobs_core.wait(jid, timeout=60)
    assert status == ManagedJobStatus.SUCCEEDED
    rec = jobs_state.get(jid)
    assert rec["recovery_count"] == 0
    _wait_cluster_gone(rec["cluster_name"])


def _wait_cluster_gone(cluster_name, timeout=15):
    """Terminal status lands before the controller's finally-cleanup."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cluster_state.get_cluster(cluster_name) is None:
            return
        time.sleep(0.2)
    raise AssertionError(f"cluster {cluster_name} not cleaned up")


def test_managed_job_user_failure_no_recovery():
    """A task that fails on a healthy cluster must NOT be retried."""
    jid = jobs_core.launch(_task("exit 7"), name="mj2")
    status = jobs_core.wait(jid, timeout=60)
    assert status == ManagedJobStatus.FAILED
    assert jobs_state.get(jid)["recovery_count"] == 0


def test_managed_job_recovers_from_preemption():
    jid = jobs_core.launch(_task("sleep 4 && echo survived"), name="mj3")
    # Wait for RUNNING, then preempt: terminate the cluster out-of-band.
    deadline = time.time() + 30
    while time.time() < deadline:
        rec = jobs_state.get(jid)
        if rec["status"] == ManagedJobStatus.RUNNING and rec["cluster_name"]:
            if cluster_state.get_cluster(rec["cluster_name"]):
                break
        time.sleep(0.1)
    else:
        raise AssertionError(f"job never reached RUNNING: {rec}")
    from skypilot_tpu.provision import local as local_provider
    time.sleep(0.5)  # let the task actually start
    local_provider.terminate_instances(rec["cluster_name"], "local")

    status = jobs_core.wait(jid, timeout=90)
    rec = jobs_state.get(jid)
    assert status == ManagedJobStatus.SUCCEEDED, rec
    assert rec["recovery_count"] >= 1


def test_managed_job_cancel():
    jid = jobs_core.launch(_task("sleep 60"), name="mj4")
    deadline = time.time() + 30
    while jobs_state.get(jid)["status"] not in (
            ManagedJobStatus.RUNNING,):
        assert time.time() < deadline
        time.sleep(0.1)
    jobs_core.cancel(jid)
    status = jobs_core.wait(jid, timeout=60)
    assert status == ManagedJobStatus.CANCELLED
    rec = jobs_state.get(jid)
    _wait_cluster_gone(rec["cluster_name"])


def test_unknown_strategy_rejected():
    t = _task("echo x")
    t.set_resources(Resources(cloud="local", job_recovery="NOPE"))
    jid = jobs_core.launch(t)
    status = jobs_core.wait(jid, timeout=30)
    assert status == ManagedJobStatus.FAILED_CONTROLLER


def test_queue_lists_jobs():
    j1 = jobs_core.launch(_task("echo a"), name="qa")
    jobs_core.wait(j1, timeout=60)
    rows = jobs_core.queue()
    assert any(r["job_id"] == j1 and r["name"] == "qa" for r in rows)
