"""Azure ARM provider tests against a stateful fake ARM API.

Reference parity: the surface of sky/provision/azure/instance.py
(run/stop/terminate/query/open_ports), tested the way this repo tests
AWS (tests/test_aws_provision.py): a fake transport that models ARM's
resource-group/PUT-upsert semantics, so create/resume/spot/ports/
failover-mapping all run offline.
"""

import re

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import azure
from skypilot_tpu.provision.common import ProvisionConfig


class FakeArm:
    """Minimal stateful ARM: resources keyed by path, RG-scoped,
    PUT = upsert, DELETE of an RG removes everything under it. VM
    power states transition instantly (start/deallocate POSTs)."""

    def __init__(self):
        self.resources = {}        # canonical path -> body
        self.power = {}            # vm path -> "running"/"deallocated"
        self.fail_vm_create = None  # ARM error code to raise on VM PUT
        self.calls = []

    # -- path helpers -------------------------------------------------------
    @staticmethod
    def _split(path):
        p, _, query = path.partition("?")
        return p, query

    def __call__(self, method, path, body):
        p, _ = self._split(path)
        self.calls.append((method, p))
        if method == "PUT":
            return self._put(p, body)
        if method == "GET":
            return self._get(p)
        if method == "POST":
            return self._post(p)
        if method == "DELETE":
            return self._delete(p)
        raise AssertionError(f"unexpected method {method}")

    def _put(self, p, body):
        if "/virtualMachines/" in p and self.fail_vm_create:
            code = self.fail_vm_create
            return 409, {"error": {"code": code,
                                   "message": f"fake {code}"}}
        if "/securityRules/" in p:
            # Model real ARM: a rule subresource PUT merges into the
            # parent NSG's securityRules (replacing a same-name rule) —
            # so tests DO catch a full-body NSG PUT wiping added rules.
            nsg_path, rule_name = p.split("/securityRules/")
            nsg = self.resources.get(nsg_path)
            if nsg is None:
                return 404, {"error": {"code": "ParentResourceNotFound",
                                       "message": nsg_path}}
            rules = nsg.setdefault("properties", {}).setdefault(
                "securityRules", [])
            rules[:] = [r for r in rules if r.get("name") != rule_name]
            rules.append({"name": rule_name, **body})
            return 200, body
        self.resources[p] = body
        if "/virtualMachines/" in p:
            self.power[p] = "running"
            self.resources[p] = dict(body, name=p.rsplit("/", 1)[1])
        if "/publicIPAddresses/" in p:
            n = sum(1 for k in self.resources
                    if "/publicIPAddresses/" in k)
            self.resources[p] = dict(
                body, properties={**body.get("properties", {}),
                                  "ipAddress": f"20.0.0.{n}"})
        if "/networkInterfaces/" in p:
            n = sum(1 for k in self.resources
                    if "/networkInterfaces/" in k)
            props = dict(body.get("properties", {}))
            for ipc in props.get("ipConfigurations", []):
                ipc.setdefault("properties", {})[
                    "privateIPAddress"] = f"10.0.0.{n}"
            self.resources[p] = dict(body, properties=props)
        return 200, self.resources[p]

    def _get(self, p):
        if p.endswith("/instanceView"):
            vm = p[:-len("/instanceView")]
            state = self.power.get(vm)
            if state is None:
                return 404, {"error": {"code": "ResourceNotFound",
                                       "message": "no vm"}}
            return 200, {"statuses": [
                {"code": "ProvisioningState/succeeded"},
                {"code": f"PowerState/{state}"}]}
        if p.endswith("/virtualMachines"):
            rg = p.split("/resourceGroups/")[1].split("/")[0]
            vms = [v for k, v in sorted(self.resources.items())
                   if f"/resourceGroups/{rg}/" in k
                   and "/virtualMachines/" in k
                   and not k.endswith("/instanceView")]
            return 200, {"value": vms}
        if p in self.resources:
            return 200, self.resources[p]
        return 404, {"error": {"code": "ResourceNotFound",
                               "message": p}}

    def _post(self, p):
        if p.endswith("/start"):
            vm = p[:-len("/start")]
            if vm not in self.power:
                return 404, {"error": {"code": "ResourceNotFound",
                                       "message": vm}}
            self.power[vm] = "running"
            return 202, {}
        if p.endswith("/deallocate"):
            vm = p[:-len("/deallocate")]
            if vm not in self.power:
                return 404, {"error": {"code": "ResourceNotFound",
                                       "message": vm}}
            self.power[vm] = "deallocated"
            return 202, {}
        return 404, {"error": {"code": "NotFound", "message": p}}

    def _delete(self, p):
        # RG delete: everything under the group goes.
        m = re.match(r"^/subscriptions/[^/]+/resourceGroups/([^/?]+)$", p)
        if m:
            rg = m.group(1)
            doomed = [k for k in self.resources
                      if f"/resourceGroups/{rg}/" in k]
            if not doomed and p not in self.resources:
                return 404, {"error": {"code": "ResourceGroupNotFound",
                                       "message": rg}}
            for k in doomed:
                self.resources.pop(k, None)
            self.resources.pop(p, None)
            for k in [k for k in self.power
                      if f"/resourceGroups/{rg}/" in k]:
                self.power.pop(k)
            return 202, {}
        self.resources.pop(p, None)
        return 200, {}


@pytest.fixture()
def fake(monkeypatch, tmp_path):
    # get_or_generate_keys needs a key; point at a throwaway one.
    key = tmp_path / "sky-key"
    pub = tmp_path / "sky-key.pub"
    pub.write_text("ssh-ed25519 AAAATESTKEY test")
    key.write_text("private")
    monkeypatch.setenv("SKYPILOT_TPU_SSH_KEY", str(key))
    arm = FakeArm()
    azure.set_transport(arm)
    yield arm
    azure.set_transport(None)


def _config(name="azc", nodes=1, **kw):
    return ProvisionConfig(
        cluster_name=name, num_nodes=nodes, hosts_per_node=1,
        zone="eastus-1", region="eastus",
        instance_type="Standard_NC24ads_A100_v4", **kw)


def test_create_cluster(fake):
    record = azure.run_instances(_config(nodes=2))
    assert record.created_instance_ids == ["azc-0", "azc-1"]
    assert not record.resumed
    azure.wait_instances("azc", "eastus-1")
    assert azure.query_instances("azc", "eastus-1") == "UP"
    # The network stack exists: RG put, NSG with the SSH rule, VNet.
    nsg = next(v for k, v in fake.resources.items()
               if k.endswith("networkSecurityGroups/skytpu-azc-nsg"))
    rules = nsg["properties"]["securityRules"]
    assert any(r["properties"]["destinationPortRange"] == "22"
               for r in rules)
    # VM carries the cluster tag, ssh key, and the Ubuntu image.
    vm = next(v for k, v in fake.resources.items()
              if k.endswith("virtualMachines/azc-0"))
    assert vm["tags"][azure.CLUSTER_TAG] == "azc"
    assert vm["properties"]["storageProfile"]["imageReference"][
        "offer"].startswith("0001-com-ubuntu")
    assert "AAAATESTKEY" in str(vm["properties"]["osProfile"])
    assert vm["zones"] == ["1"]


def test_run_is_idempotent_and_resumes(fake):
    azure.run_instances(_config())
    # Second run: nothing new created.
    record = azure.run_instances(_config())
    assert record.created_instance_ids == []
    assert not record.resumed
    # Stop, then run again: the VM restarts instead of a new create.
    azure.stop_instances("azc", "eastus-1")
    assert azure.query_instances("azc", "eastus-1") == "STOPPED"
    record = azure.run_instances(_config())
    assert record.resumed and record.created_instance_ids == []
    assert azure.query_instances("azc", "eastus-1") == "UP"


def test_spot_custom_image_and_labels(fake):
    azure.run_instances(_config(use_spot=True,
                                image_id="myPublisher:offer:sku:1.2.3",
                                labels={"team": "ml"}))
    vm = next(v for k, v in fake.resources.items()
              if k.endswith("virtualMachines/azc-0"))
    assert vm["properties"]["priority"] == "Spot"
    assert vm["properties"]["evictionPolicy"] == "Deallocate"
    assert vm["properties"]["storageProfile"]["imageReference"] == {
        "publisher": "myPublisher", "offer": "offer", "sku": "sku",
        "version": "1.2.3"}
    assert vm["tags"]["team"] == "ml"


def test_managed_image_id(fake):
    azure.run_instances(_config(
        image_id="/subscriptions/s/resourceGroups/g/providers/"
                 "Microsoft.Compute/images/custom"))
    vm = next(v for k, v in fake.resources.items()
              if k.endswith("virtualMachines/azc-0"))
    assert vm["properties"]["storageProfile"]["imageReference"][
        "id"].endswith("images/custom")


def test_ports_open_as_nsg_rules(fake):
    azure.run_instances(_config(ports=[8080, 8081]))
    nsg = next(v for k, v in fake.resources.items()
               if k.endswith("networkSecurityGroups/skytpu-azc-nsg"))
    ranges = {r["properties"]["destinationPortRange"]
              for r in nsg["properties"]["securityRules"]}
    assert {"22", "8080", "8081"} <= ranges
    # Post-hoc exposure adds a rule without clobbering existing ones.
    azure.open_ports("azc", [9090])
    nsg = next(v for k, v in fake.resources.items()
               if k.endswith("networkSecurityGroups/skytpu-azc-nsg"))
    by_name = {r["name"]: r for r in nsg["properties"]["securityRules"]}
    assert by_name["skytpu-port-9090"]["properties"][
        "destinationPortRange"] == "9090"
    # Re-opening the same port is a no-op (idempotent).
    azure.open_ports("azc", [8080])


def test_capacity_and_quota_errors_map_to_failover_taxonomy(fake):
    fake.fail_vm_create = "SkuNotAvailable"
    with pytest.raises(exceptions.CapacityError):
        azure.run_instances(_config())
    fake.fail_vm_create = "QuotaExceeded"
    with pytest.raises(exceptions.QuotaExceededError):
        azure.run_instances(_config(name="azq"))
    fake.fail_vm_create = "AuthorizationFailed"
    with pytest.raises(exceptions.NoCloudAccessError):
        azure.run_instances(_config(name="aza"))


def test_cluster_info_and_runners(fake):
    azure.run_instances(_config(nodes=2))
    info = azure.get_cluster_info("azc", "eastus-1")
    assert [h.host_id for h in info.hosts] == [0, 1]
    assert all(h.external_ip and h.external_ip.startswith("20.0.0.")
               for h in info.hosts)
    assert all(h.internal_ip.startswith("10.0.0.") for h in info.hosts)
    assert info.head.ssh_user == "azureuser"
    runners = azure.get_command_runners(info)
    assert len(runners) == 2


def test_terminate_deletes_resource_group(fake):
    azure.run_instances(_config())
    assert any("/skytpu-azc/" in k for k in fake.resources)
    azure.terminate_instances("azc", "eastus-1")
    assert not any("/skytpu-azc/" in k for k in fake.resources)
    assert azure.query_instances("azc", "eastus-1") == "NOT_FOUND"
    # Terminating again is clean (RG already gone).
    azure.terminate_instances("azc", "eastus-1")


def test_provision_dispatcher_routes_azure(fake):
    from skypilot_tpu import provision
    provision.run_instances("azure", _config())
    assert provision.query_instances("azure", "azc", "eastus-1") == "UP"
    provision.open_ports("azure", "azc", [7000], "eastus-1")
    assert provision.supports("azure", provision.Feature.STOP)
    provision.terminate_instances("azure", "azc", "eastus-1")


def test_region_of_zone():
    assert azure._region_of_zone("eastus-1") == ("eastus", "1")
    assert azure._region_of_zone("westeurope-2") == ("westeurope", "2")
    assert azure._region_of_zone("eastus") == ("eastus", None)


def test_bad_image_id_fails_loudly(fake):
    with pytest.raises(exceptions.InvalidTaskError):
        azure.run_instances(_config(image_id="not-a-valid-image"))


def test_relaunch_preserves_posthoc_ports(fake):
    """Rules added by open_ports must survive a stop + relaunch: ARM
    NSG PUTs replace securityRules wholesale, so _ensure_network must
    not re-PUT the full body over an existing NSG."""
    azure.run_instances(_config(ports=[8080]))
    azure.open_ports("azc", [9090])
    azure.stop_instances("azc", "eastus-1")
    azure.run_instances(_config(ports=[8080]))
    nsg = next(v for k, v in fake.resources.items()
               if k.endswith("networkSecurityGroups/skytpu-azc-nsg"))
    ranges = {r["properties"]["destinationPortRange"]
              for r in nsg["properties"]["securityRules"]}
    assert "9090" in ranges, ranges
    assert {"22", "8080"} <= ranges


def test_rg_delete_does_not_cross_prefix_boundary(fake):
    azure.run_instances(_config(name="azc"))
    azure.run_instances(_config(name="azc2"))
    azure.terminate_instances("azc", "eastus-1")
    assert azure.query_instances("azc", "eastus-1") == "NOT_FOUND"
    assert azure.query_instances("azc2", "eastus-1") == "UP"


def test_wait_bounded_with_fake_transport(fake):
    azure.run_instances(_config())
    fake.power = {k: "starting" for k in fake.power}
    import time as _t
    t0 = _t.time()
    with pytest.raises(exceptions.ResourcesUnavailableError):
        azure.wait_instances("azc", "eastus-1", timeout=600)
    assert _t.time() - t0 < 5
