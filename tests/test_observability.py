"""Timeline tracing, usage telemetry, callbacks, benchmark subsystem."""

import json
import os
import time

import pytest
from click.testing import CliRunner

import skypilot_tpu.callbacks as sky_callback
from skypilot_tpu.usage import usage_lib
from skypilot_tpu.utils import timeline


def test_timeline_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv(timeline.ENV_VAR, raising=False)

    @timeline.event
    def f():
        return 42

    assert f() == 42
    assert not timeline._events


def test_timeline_records_and_saves(tmp_path, monkeypatch):
    out = tmp_path / "trace.json"
    monkeypatch.setenv(timeline.ENV_VAR, str(out))
    timeline._events.clear()

    @timeline.event(name="my-op")
    def f():
        time.sleep(0.01)
        return 1

    f()
    with timeline.Event("manual", message="hello"):
        pass
    timeline.save_now()
    data = json.loads(out.read_text())
    names = [e["name"] for e in data["traceEvents"]]
    assert "my-op" in names and "manual" in names
    evt = next(e for e in data["traceEvents"] if e["name"] == "my-op")
    assert evt["ph"] == "X" and evt["dur"] >= 10_000  # >= 10ms in us


def test_filelock_event(tmp_path, monkeypatch):
    monkeypatch.setenv(timeline.ENV_VAR, str(tmp_path / "t.json"))
    with timeline.FileLockEvent(str(tmp_path / "x.lock")):
        pass
    assert any("filelock.acquire" in e["name"] for e in timeline._events)


def test_usage_sink_local(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    monkeypatch.delenv(usage_lib.DISABLE_ENV, raising=False)
    monkeypatch.delenv(usage_lib.ENDPOINT_ENV, raising=False)
    with usage_lib.entrypoint_context("launch", cloud="gcp") as msg:
        msg.set("num_nodes", 4)
    rec = json.loads((tmp_path / "usage" / "usage.jsonl")
                     .read_text().strip().splitlines()[-1])
    assert rec["kind"] == "launch"
    assert rec["num_nodes"] == 4 and rec["cloud"] == "gcp"
    assert rec["exception"] is None and rec["schema_version"] == 1


def test_usage_opt_out(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    monkeypatch.setenv(usage_lib.DISABLE_ENV, "1")
    with usage_lib.entrypoint_context("launch"):
        pass
    assert not (tmp_path / "usage").exists()


def test_usage_records_exception(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    monkeypatch.delenv(usage_lib.DISABLE_ENV, raising=False)
    with pytest.raises(ValueError):
        with usage_lib.entrypoint_context("down"):
            raise ValueError("x")
    rec = json.loads((tmp_path / "usage" / "usage.jsonl")
                     .read_text().strip().splitlines()[-1])
    assert rec["exception"] == "ValueError"


def test_callbacks_summary(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYTPU_CALLBACK_LOG_DIR", str(tmp_path))
    sky_callback.init(total_steps=10, warmup_steps=1)
    for _ in range(3):
        with sky_callback.step():
            time.sleep(0.005)
    s = sky_callback.summary()
    assert s["steps"] == 3
    assert s["avg_step_s"] >= 0.004     # warmup step excluded
    assert s["eta_s"] is not None
    sky_callback.write_summary()
    on_disk = json.loads((tmp_path / sky_callback.SUMMARY_FILE).read_text())
    assert on_disk["steps"] == 3


def test_benchmark_state_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    from skypilot_tpu.benchmark import benchmark_state as bs
    bs.add_benchmark("b1", "{}")
    bs.add_result("b1", "c0", "local:tpu-v5e-8", 1.2)
    bs.finish_result("b1", "c0", 600.0, metrics={"steps": 5})
    bs.set_benchmark_status("b1", "FINISHED")
    assert bs.list_benchmarks()[0]["status"] == "FINISHED"
    (row,) = bs.get_results("b1")
    assert row["duration_s"] == 600.0 and row["metrics"]["steps"] == 5
    bs.delete_benchmark("b1")
    assert bs.get_results("b1") == []


def test_benchmark_launch_local(tmp_path, monkeypatch):
    """End-to-end bench over the local fake cloud, two candidates."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    from skypilot_tpu.benchmark import benchmark_utils
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task
    task = Task(run="echo bench-ok", name="b")
    task.set_resources(Resources.from_yaml_config(
        {"cloud": "local", "accelerators": "tpu-v5e-8"}))
    results = benchmark_utils.launch_benchmark(
        "bench-e2e", task, [{}, {"accelerators": "tpu-v5e-8"}])
    assert all(r["status"] == "FINISHED" for r in results)
    rows = benchmark_utils.summarize("bench-e2e")
    assert len(rows) == 2
    assert all(r["cost"] >= 0 for r in rows)


# -- metrics integration (observability PR) ---------------------------------

def _hist_count(hist):
    return sum(sum(child.hist_state()[0]) for _, child in hist.children())


def _counter_total(counter):
    return sum(child.value for _, child in counter.children())


def test_engine_records_ttft_and_slot_occupancy():
    import jax

    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.models import llama

    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    ttft0 = _hist_count(eng.TTFT_SECONDS)
    prefill0 = _counter_total(eng.PREFILL_REQUESTS)
    decode0 = eng.DECODE_TOKENS._require_default().value
    finished0 = eng.REQUESTS_FINISHED._require_default().value

    e = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                            prompt_buckets=(16,))
    assert eng.SLOTS_TOTAL._require_default().value == 2
    e.add_request([3, 17, 42], max_new_tokens=48)
    e.add_request([5, 9], max_new_tokens=48)
    e.step()                      # prefill both -> slots occupied
    assert eng.SLOTS_ACTIVE._require_default().value == 2
    assert _hist_count(eng.TTFT_SECONDS) == ttft0 + 2
    assert _counter_total(eng.PREFILL_REQUESTS) == prefill0 + 2
    # Per-request TTFT was observed from submit time, so every sample
    # is positive and the histogram sum moved.
    while e.slot_req or e.waiting:
        e.step()
    assert eng.SLOTS_ACTIVE._require_default().value == 0
    assert eng.REQUESTS_FINISHED._require_default().value == finished0 + 2
    assert eng.DECODE_TOKENS._require_default().value > decode0
    assert _hist_count(eng.DECODE_STEP_SECONDS) > 0
    assert _hist_count(eng.TPOT_SECONDS) >= 2


def test_engine_wave_size_and_prefill_bucket_labels():
    import jax

    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.models import llama

    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(1), cfg)
    wave0 = _hist_count(eng.WAVE_SIZE)
    e = eng.InferenceEngine(params, cfg, n_slots=4, max_len=64,
                            prompt_buckets=(8, 16))
    e.generate([[1, 2, 3], [4, 5]], max_new_tokens=2)
    assert _hist_count(eng.WAVE_SIZE) > wave0
    # Prefill latency histograms are labeled by prompt bucket.
    labels = {v for v, _ in eng.PREFILL_SECONDS.children()}
    assert ("8",) in labels


def test_timeline_save_is_atomic_and_repeatable(tmp_path, monkeypatch):
    out = tmp_path / "trace.json"
    monkeypatch.setenv(timeline.ENV_VAR, str(out))
    timeline._events.clear()
    with timeline.Event("one"):
        pass
    timeline.save_now()
    first = json.loads(out.read_text())
    with timeline.Event("two"):
        pass
    timeline.save_now()
    timeline.save_now()           # repeat is safe, full buffer each time
    data = json.loads(out.read_text())
    names = [e["name"] for e in data["traceEvents"]]
    assert "one" in names and "two" in names
    assert len(data["traceEvents"]) >= len(first["traceEvents"])
    # No stranded temp files from the atomic replace.
    leftovers = [p for p in os.listdir(tmp_path)
                 if p != "trace.json" and p.startswith("trace.json")]
    assert leftovers == []


def test_timeline_real_thread_ids_and_names(tmp_path, monkeypatch):
    import threading

    monkeypatch.setenv(timeline.ENV_VAR, str(tmp_path / "t.json"))
    timeline._events.clear()
    timeline._named_tids.clear()

    def record():
        with timeline.Event("in-thread"):
            pass

    t = threading.Thread(target=record, name="worker-thread")
    t.start()
    t.join()
    with timeline.Event("in-main"):
        pass
    spans = {e["name"]: e for e in timeline._events if e["ph"] == "X"}
    # Real (unfolded) idents: the two threads get distinct tids.
    assert spans["in-thread"]["tid"] != spans["in-main"]["tid"]
    meta = [e for e in timeline._events
            if e["ph"] == "M" and e["name"] == "thread_name"]
    by_tid = {e["tid"]: e["args"]["name"] for e in meta}
    assert by_tid[spans["in-thread"]["tid"]] == "worker-thread"
    assert spans["in-main"]["tid"] in by_tid


def test_timeline_thread_name_not_inherited_on_ident_reuse(
        tmp_path, monkeypatch):
    """CPython reuses thread idents; a recycled ident must re-emit name
    metadata instead of inheriting the dead thread's track name."""
    import threading

    monkeypatch.setenv(timeline.ENV_VAR, str(tmp_path / "t.json"))
    timeline._events.clear()
    timeline._named_tids.clear()
    cur = threading.current_thread()
    old = cur.name
    try:
        cur.name = "incarnation-1"   # same ident, two names = reuse
        with timeline.Event("a"):
            pass
        cur.name = "incarnation-2"
        with timeline.Event("b"):
            pass
    finally:
        cur.name = old
    meta = [e for e in timeline._events
            if e["ph"] == "M" and e["name"] == "thread_name"]
    assert [e["args"]["name"] for e in meta] == \
        ["incarnation-1", "incarnation-2"]
    timeline._events.clear()
    timeline._named_tids.clear()


def test_timeline_trim_drops_stale_thread_metadata(tmp_path, monkeypatch):
    """Under thread churn, name metadata of threads whose spans aged out
    of the capped buffer must not accumulate without bound."""
    import threading

    monkeypatch.setenv(timeline.ENV_VAR, str(tmp_path / "t.json"))
    timeline._events.clear()
    timeline._named_tids.clear()
    monkeypatch.setattr(timeline, "_MAX_EVENTS", 40)

    def record():
        with timeline.Event("churn"):
            pass

    for i in range(120):
        t = threading.Thread(target=record, name=f"w{i}")
        t.start()
        t.join()
    assert len(timeline._events) <= 2 * 40
    meta_tids = {e["tid"] for e in timeline._events if e["ph"] == "M"}
    span_tids = {e["tid"] for e in timeline._events if e["ph"] != "M"}
    assert meta_tids <= span_tids     # no orphaned thread names
    timeline._events.clear()
    timeline._named_tids.clear()


def test_timeline_flush_skips_clean_buffer(tmp_path, monkeypatch):
    """A daemon flushing every tick must not re-serialize an unchanged
    buffer: after a flush with no new events, the file is untouched."""
    out = tmp_path / "t.json"
    monkeypatch.setenv(timeline.ENV_VAR, str(out))
    timeline._events.clear()
    timeline._named_tids.clear()
    with timeline.Event("tick-span"):
        pass
    timeline.save_now()
    sentinel = '{"traceEvents": [], "sentinel": true}'
    out.write_text(sentinel)
    timeline.save_now()                    # clean buffer -> no rewrite
    assert out.read_text() == sentinel
    with timeline.Event("tick-span-2"):    # dirty again -> rewrites
        pass
    timeline.save_now()
    names = [e["name"] for e in
             json.loads(out.read_text())["traceEvents"]]
    assert "tick-span-2" in names
    timeline._events.clear()
    timeline._named_tids.clear()


def test_job_queue_state_gauges(tmp_path):
    from skypilot_tpu.runtime import job_queue

    db = str(tmp_path / "jobs.db")
    jid = job_queue.add_job(db, "j", "echo hi")
    t_before = job_queue.JOB_TRANSITIONS.labels(status="RUNNING").value
    job_queue.set_status(db, jid, job_queue.JobStatus.RUNNING)
    counts = job_queue.update_state_gauges(db)
    assert counts["RUNNING"] == 1
    assert job_queue.JOBS_BY_STATE.labels(status="RUNNING").value == 1
    # Every status gets a (possibly zero) sample so scrapes see
    # transitions back to zero.
    assert set(counts) == {s.value for s in job_queue.JobStatus}
    assert counts["PENDING"] == 0
    assert (job_queue.JOB_TRANSITIONS.labels(status="RUNNING").value
            == t_before + 1)
    # An unreadable DB must never take a daemon tick down.
    bad = job_queue.update_state_gauges(str(tmp_path / "no" / "x.db"))
    assert set(bad) == {s.value for s in job_queue.JobStatus}
    # A no-op UPDATE (unknown job) records no transition.
    t_ghost = job_queue.JOB_TRANSITIONS.labels(status="FAILED").value
    job_queue.set_status(db, 999, job_queue.JobStatus.FAILED)
    assert (job_queue.JOB_TRANSITIONS.labels(status="FAILED").value
            == t_ghost)


def test_managed_jobs_terminal_counter(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    from skypilot_tpu.jobs import state as jobs_state

    c = jobs_state.MANAGED_TERMINAL.labels(status="SUCCEEDED")
    before = c.value
    jid = jobs_state.add("m", {"run": "true"}, "FAILOVER")
    jobs_state.set_status(jid, jobs_state.ManagedJobStatus.SUCCEEDED)
    assert c.value == before + 1
    # First-wins: a late terminal write does not apply, so no count.
    cancelled = jobs_state.MANAGED_TERMINAL.labels(status="CANCELLED")
    cancelled_before = cancelled.value
    jobs_state.set_status(jid, jobs_state.ManagedJobStatus.CANCELLED)
    assert cancelled.value == cancelled_before
    assert c.value == before + 1


def test_skylet_tick_heartbeat_and_trace_flush(tmp_path, monkeypatch):
    from skypilot_tpu.runtime import job_queue, skylet

    out = tmp_path / "skylet-trace.json"
    monkeypatch.setenv(timeline.ENV_VAR, str(out))
    timeline._events.clear()
    with timeline.Event("skylet-span"):
        pass
    # Age out the throttle: the tick's flush is periodic, not per-event.
    monkeypatch.setattr(timeline, "_last_flush_s", 0.0)
    db = str(tmp_path / "jobs.db")
    job_queue.add_job(db, "j", "echo hi")
    ticks0 = skylet.SKYLET_TICKS._require_default().value
    t0 = time.time()
    skylet.observe_tick(db)
    assert skylet.SKYLET_TICKS._require_default().value == ticks0 + 1
    hb = skylet.SKYLET_HEARTBEAT._require_default().value
    assert t0 <= hb <= time.time()
    assert job_queue.JOBS_BY_STATE.labels(status="PENDING").value >= 1
    # The tick flushed the trace buffer atomically.
    names = [e["name"] for e in
             json.loads(out.read_text())["traceEvents"]]
    assert "skylet-span" in names
    skylet.observe_tick(db)       # idempotent: daemons tick forever
    # An unwritable trace path must not take the tick down either.
    with timeline.Event("skylet-span-2"):
        pass                      # dirty buffer: the flush is attempted
    monkeypatch.setattr(timeline, "_last_flush_s", 0.0)
    blocked = tmp_path / "blocked"
    blocked.write_text("")        # a FILE where a directory is needed
    monkeypatch.setenv(timeline.ENV_VAR, str(blocked / "nested.json"))
    skylet.observe_tick(db)


def test_save_periodic_throttles_full_buffer_rewrites(tmp_path,
                                                      monkeypatch):
    """Per-tick daemon flushes re-serialize the whole buffer; the
    throttled entry point skips until enough news or enough age."""
    out = tmp_path / "t.json"
    monkeypatch.setenv(timeline.ENV_VAR, str(out))
    timeline._events.clear()
    timeline._named_tids.clear()
    with timeline.Event("first"):
        pass
    timeline.save_now()           # flush: _last_flush_s is now fresh
    with timeline.Event("second"):
        pass
    timeline.save_periodic(min_new_events=100, max_age_s=60.0)
    names = [e["name"] for e in
             json.loads(out.read_text())["traceEvents"]]
    assert "second" not in names  # few events + fresh flush: skipped
    timeline.save_periodic(min_new_events=1, max_age_s=60.0)
    names = [e["name"] for e in
             json.loads(out.read_text())["traceEvents"]]
    assert "second" in names      # enough pending events: flushed
    with timeline.Event("third"):
        pass
    monkeypatch.setattr(timeline, "_last_flush_s", 0.0)
    timeline.save_periodic(min_new_events=100, max_age_s=60.0)
    names = [e["name"] for e in
             json.loads(out.read_text())["traceEvents"]]
    assert "third" in names       # stale last flush: age triggers
    timeline._events.clear()
    timeline._named_tids.clear()
