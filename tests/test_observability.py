"""Timeline tracing, usage telemetry, callbacks, benchmark subsystem."""

import json
import os
import time

import pytest
from click.testing import CliRunner

import skypilot_tpu.callbacks as sky_callback
from skypilot_tpu.usage import usage_lib
from skypilot_tpu.utils import timeline


def test_timeline_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv(timeline.ENV_VAR, raising=False)

    @timeline.event
    def f():
        return 42

    assert f() == 42
    assert not timeline._events


def test_timeline_records_and_saves(tmp_path, monkeypatch):
    out = tmp_path / "trace.json"
    monkeypatch.setenv(timeline.ENV_VAR, str(out))
    timeline._events.clear()

    @timeline.event(name="my-op")
    def f():
        time.sleep(0.01)
        return 1

    f()
    with timeline.Event("manual", message="hello"):
        pass
    timeline.save_now()
    data = json.loads(out.read_text())
    names = [e["name"] for e in data["traceEvents"]]
    assert "my-op" in names and "manual" in names
    evt = next(e for e in data["traceEvents"] if e["name"] == "my-op")
    assert evt["ph"] == "X" and evt["dur"] >= 10_000  # >= 10ms in us


def test_filelock_event(tmp_path, monkeypatch):
    monkeypatch.setenv(timeline.ENV_VAR, str(tmp_path / "t.json"))
    with timeline.FileLockEvent(str(tmp_path / "x.lock")):
        pass
    assert any("filelock.acquire" in e["name"] for e in timeline._events)


def test_usage_sink_local(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    monkeypatch.delenv(usage_lib.DISABLE_ENV, raising=False)
    monkeypatch.delenv(usage_lib.ENDPOINT_ENV, raising=False)
    with usage_lib.entrypoint_context("launch", cloud="gcp") as msg:
        msg.set("num_nodes", 4)
    rec = json.loads((tmp_path / "usage" / "usage.jsonl")
                     .read_text().strip().splitlines()[-1])
    assert rec["kind"] == "launch"
    assert rec["num_nodes"] == 4 and rec["cloud"] == "gcp"
    assert rec["exception"] is None and rec["schema_version"] == 1


def test_usage_opt_out(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    monkeypatch.setenv(usage_lib.DISABLE_ENV, "1")
    with usage_lib.entrypoint_context("launch"):
        pass
    assert not (tmp_path / "usage").exists()


def test_usage_records_exception(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    monkeypatch.delenv(usage_lib.DISABLE_ENV, raising=False)
    with pytest.raises(ValueError):
        with usage_lib.entrypoint_context("down"):
            raise ValueError("x")
    rec = json.loads((tmp_path / "usage" / "usage.jsonl")
                     .read_text().strip().splitlines()[-1])
    assert rec["exception"] == "ValueError"


def test_callbacks_summary(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYTPU_CALLBACK_LOG_DIR", str(tmp_path))
    sky_callback.init(total_steps=10, warmup_steps=1)
    for _ in range(3):
        with sky_callback.step():
            time.sleep(0.005)
    s = sky_callback.summary()
    assert s["steps"] == 3
    assert s["avg_step_s"] >= 0.004     # warmup step excluded
    assert s["eta_s"] is not None
    sky_callback.write_summary()
    on_disk = json.loads((tmp_path / sky_callback.SUMMARY_FILE).read_text())
    assert on_disk["steps"] == 3


def test_benchmark_state_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    from skypilot_tpu.benchmark import benchmark_state as bs
    bs.add_benchmark("b1", "{}")
    bs.add_result("b1", "c0", "local:tpu-v5e-8", 1.2)
    bs.finish_result("b1", "c0", 600.0, metrics={"steps": 5})
    bs.set_benchmark_status("b1", "FINISHED")
    assert bs.list_benchmarks()[0]["status"] == "FINISHED"
    (row,) = bs.get_results("b1")
    assert row["duration_s"] == 600.0 and row["metrics"]["steps"] == 5
    bs.delete_benchmark("b1")
    assert bs.get_results("b1") == []


def test_benchmark_launch_local(tmp_path, monkeypatch):
    """End-to-end bench over the local fake cloud, two candidates."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    from skypilot_tpu.benchmark import benchmark_utils
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task
    task = Task(run="echo bench-ok", name="b")
    task.set_resources(Resources.from_yaml_config(
        {"cloud": "local", "accelerators": "tpu-v5e-8"}))
    results = benchmark_utils.launch_benchmark(
        "bench-e2e", task, [{}, {"accelerators": "tpu-v5e-8"}])
    assert all(r["status"] == "FINISHED" for r in results)
    rows = benchmark_utils.summarize("bench-e2e")
    assert len(rows) == 2
    assert all(r["cost"] >= 0 for r in rows)
