"""CLI tests via click's CliRunner (reference pattern: tests/test_cli.py)."""

import pytest
from click.testing import CliRunner

from skypilot_tpu.client import cli as cli_mod


@pytest.fixture(autouse=True)
def sky_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))


@pytest.fixture()
def runner():
    return CliRunner()


def test_status_empty(runner):
    res = runner.invoke(cli_mod.cli, ["status"])
    assert res.exit_code == 0
    assert "No existing clusters" in res.output


def test_launch_dryrun(runner):
    res = runner.invoke(cli_mod.cli, [
        "launch", "echo hi", "--gpus", "tpu-v5e-8", "--dryrun"])
    assert res.exit_code == 0, res.output
    assert "would launch" in res.output
    assert "tpu-v5e-8" in res.output


def test_launch_local_roundtrip(runner):
    res = runner.invoke(cli_mod.cli, [
        "launch", "echo cli-test", "--cloud", "local", "-c", "clic"])
    assert res.exit_code == 0, res.output
    res = runner.invoke(cli_mod.cli, ["status"])
    assert "clic" in res.output
    res = runner.invoke(cli_mod.cli, ["queue", "clic"])
    assert res.exit_code == 0, res.output
    res = runner.invoke(cli_mod.cli, ["logs", "clic", "1"])
    assert res.exit_code == 0, res.output
    assert "cli-test" in res.output
    res = runner.invoke(cli_mod.cli, ["down", "clic"])
    assert res.exit_code == 0, res.output
    res = runner.invoke(cli_mod.cli, ["status"])
    assert "clic" not in res.output


def test_status_ip(runner):
    res = runner.invoke(cli_mod.cli, [
        "launch", "echo up", "--cloud", "local", "-c", "clip"])
    assert res.exit_code == 0, res.output
    try:
        res = runner.invoke(cli_mod.cli, ["status", "clip", "--ip"])
        assert res.exit_code == 0, res.output
        assert res.output.strip()  # one bare address line
        assert "\n" not in res.output.strip()
        res = runner.invoke(cli_mod.cli, ["status", "--ip"])
        assert res.exit_code != 0  # exactly one cluster required
    finally:
        runner.invoke(cli_mod.cli, ["down", "clip"])


def test_launch_from_yaml(runner, tmp_path):
    yaml_file = tmp_path / "task.yaml"
    yaml_file.write_text(
        "name: yamltask\nresources:\n  cloud: local\nrun: echo from-yaml\n")
    res = runner.invoke(cli_mod.cli, [
        "launch", str(yaml_file), "-c", "cyaml"])
    assert res.exit_code == 0, res.output
    res = runner.invoke(cli_mod.cli, ["logs", "cyaml", "1"])
    assert "from-yaml" in res.output
    runner.invoke(cli_mod.cli, ["down", "cyaml"])


def test_show_gpus(runner):
    res = runner.invoke(cli_mod.cli, ["show-gpus", "v5p"])
    assert res.exit_code == 0, res.output
    assert "tpu-v5p-16" in res.output
    res = runner.invoke(cli_mod.cli, ["show-gpus", "A100"])
    assert "A100" in res.output


def test_check(runner):
    res = runner.invoke(cli_mod.cli, ["check"])
    assert res.exit_code == 0, res.output
    assert "local: enabled" in res.output
    assert "gcp:" in res.output


def test_unknown_cluster_errors(runner):
    res = runner.invoke(cli_mod.cli, ["queue", "nope"])
    assert res.exit_code != 0


def test_storage_ls_and_delete(runner, monkeypatch):
    from skypilot_tpu import state
    from skypilot_tpu.data import storage as storage_lib

    state.add_storage("ckpts", {"name": "ckpts", "mode": "MOUNT",
                                "persistent": True})
    res = runner.invoke(cli_mod.cli, ["storage", "ls"])
    assert res.exit_code == 0 and "ckpts" in res.output

    deleted = []
    monkeypatch.setattr(storage_lib, "_local_run",
                        lambda cmd: (deleted.append(cmd) or (0, "")))
    res = runner.invoke(cli_mod.cli, ["storage", "delete", "ckpts"])
    assert res.exit_code == 0, res.output
    assert any("rm -r gs://ckpts" in c for c in deleted)
    assert state.get_storage("ckpts") is None

    res = runner.invoke(cli_mod.cli, ["storage", "delete", "missing"])
    assert "not found" in res.output


def test_api_lifecycle(runner, tmp_path, monkeypatch):
    """api start -> info -> status -> stop against a real subprocess."""
    import socket
    import time as time_mod

    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    monkeypatch.setenv("SKYTPU_API_SERVER_URL", f"http://127.0.0.1:{port}")

    res = runner.invoke(cli_mod.cli, ["api", "start", "--port", str(port)])
    assert res.exit_code == 0, res.output
    try:
        deadline = time_mod.time() + 30
        while time_mod.time() < deadline:
            res = runner.invoke(cli_mod.cli, ["api", "info"])
            if res.exit_code == 0:
                break
            time_mod.sleep(0.5)
        assert res.exit_code == 0, res.output
        assert "healthy" in res.output

        res = runner.invoke(cli_mod.cli, ["api", "status"])
        assert res.exit_code == 0, res.output
        assert "REQUEST" in res.output
    finally:
        res = runner.invoke(cli_mod.cli, ["api", "stop"])
    assert "Stopped" in res.output


def test_cli_reference_up_to_date():
    """docs/cli.md is generated from the click tree; a CLI change must
    regenerate it (python -m skypilot_tpu.client.cli_docs > docs/cli.md)."""
    import os

    from skypilot_tpu.client import cli_docs
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "cli.md")
    with open(path) as f:
        on_disk = f.read()
    assert on_disk == cli_docs.generate(), (
        "docs/cli.md is stale — regenerate with "
        "`python -m skypilot_tpu.client.cli_docs > docs/cli.md`")


def test_status_metrics_view(runner, monkeypatch):
    """`status --metrics` scrapes the API server's /metrics and renders
    counters/gauges/histograms; --raw prints the exposition verbatim."""
    import json
    import socket
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from skypilot_tpu.observability import metrics as metrics_lib

    reg = metrics_lib.Registry()
    reg.counter("skytpu_api_requests_total", "reqs",
                labelnames=("endpoint",)).labels(endpoint="launch").inc(3)
    reg.gauge("skytpu_api_workers_busy", "busy").set(1)
    h = reg.histogram("skytpu_api_request_seconds", "lat",
                      buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(2.0)
    text = reg.render()

    class FakeApi(BaseHTTPRequestHandler):
        def do_GET(self):
            body = text.encode()
            self.send_response(200 if self.path == "/metrics" else 404)
            self.send_header("Content-Type", metrics_lib.CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = HTTPServer(("127.0.0.1", 0), FakeApi)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        monkeypatch.setenv("SKYTPU_API_SERVER_URL",
                           f"http://127.0.0.1:{httpd.server_port}")
        res = runner.invoke(cli_mod.cli, ["status", "--metrics"])
        assert res.exit_code == 0, res.output
        assert "skytpu_api_requests_total" in res.output
        assert "endpoint=launch" in res.output
        assert "n=2" in res.output            # histogram series summary
        assert "avg=1.25" in res.output
        res = runner.invoke(cli_mod.cli, ["status", "--metrics", "--raw"])
        assert res.exit_code == 0, res.output
        assert res.output.strip() == text.strip()
    finally:
        httpd.shutdown()


def test_status_metrics_unreachable(runner, monkeypatch):
    monkeypatch.setenv("SKYTPU_API_SERVER_URL", "http://127.0.0.1:1")
    res = runner.invoke(cli_mod.cli, ["status", "--metrics"])
    assert res.exit_code != 0
    assert "not reachable" in res.output


def test_status_metrics_rejects_cluster_args(runner):
    # --metrics is a server-registry view; silently ignoring cluster
    # names (or --refresh/--ip) would mislead.
    for extra in (["my-cluster"], ["--refresh"], ["--ip", "c"]):
        res = runner.invoke(cli_mod.cli, ["status", "--metrics"] + extra)
        assert res.exit_code != 0
        assert "cannot be combined" in res.output
    res = runner.invoke(cli_mod.cli, ["status", "--raw"])
    assert res.exit_code != 0
    assert "--raw only applies" in res.output
