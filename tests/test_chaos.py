"""Chaos harness: seeded fault plans driven through end-to-end
recovery scenarios against the local fake cloud.

Each scenario injects faults through the named chaos points and asserts
the system CONVERGES (terminal state reached exactly once, no duplicate
cluster launches) and EXPLAINS itself (typed ``chaos.injected`` /
``slo.breach`` / recovery events in the structured log, recovery
counters matching the injected faults). Determinism: the same plan +
seed reproduces the same injection sequence, so a failing chaos run is
a reproducible artifact, not a flake.
"""

import ast
import json
import os
import threading
import time

import pytest

from skypilot_tpu import chaos, exceptions

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def chaos_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    monkeypatch.setenv("SKYTPU_LOCAL_CLUSTERS_ROOT", str(tmp_path / "cloud"))
    monkeypatch.delenv("SKYTPU_CHAOS_PLAN", raising=False)
    monkeypatch.delenv("SKYTPU_CHAOS_PLAN_JSON", raising=False)
    chaos._reset_for_tests()
    from skypilot_tpu.observability import tracing
    tracing._reset_for_tests()
    yield
    chaos._reset_for_tests()


def _events(name):
    from skypilot_tpu.observability import tracing
    return [r for r in tracing.buffered_records() if r.get("name") == name]


# -- plan schema ------------------------------------------------------------

def test_plan_validation_rejects_malformed():
    with pytest.raises(ValueError, match="seed"):
        chaos.parse_plan({"seed": "nope"})
    with pytest.raises(ValueError, match="faults\\[0\\].*point"):
        chaos.parse_plan({"faults": [{"times": 1}]})
    with pytest.raises(ValueError, match="probability"):
        chaos.parse_plan({"faults": [{"point": "x", "probability": 2}]})
    with pytest.raises(ValueError, match="unknown keys"):
        chaos.parse_plan({"faults": [{"point": "x", "nope": 1}]})
    plan = chaos.parse_plan({"seed": 3, "faults": [
        {"point": "rpc.transport", "times": 1}]})
    assert plan.seed == 3 and plan.rules[0].point == "rpc.transport"


def test_point_catalog_matches_code():
    """Every chaos.point() call site in the tree must be cataloged in
    plan.KNOWN_POINTS (and vice versa) — a fault plan targeting a
    point that silently vanished injects nothing."""
    in_code = set()
    pkg = os.path.join(REPO, "skypilot_tpu")
    for dirpath, _, names in os.walk(pkg):
        if "__pycache__" in dirpath or os.path.join("skypilot_tpu",
                                                    "chaos") in dirpath:
            continue
        for fname in names:
            if not fname.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fname),
                      encoding="utf-8") as f:
                tree = ast.parse(f.read())
            for node in ast.walk(tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "point"
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "chaos"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)):
                    in_code.add(node.args[0].value)
    assert in_code == set(chaos.KNOWN_POINTS), (
        f"catalog drift — in code only: "
        f"{sorted(in_code - set(chaos.KNOWN_POINTS))}; in catalog only: "
        f"{sorted(set(chaos.KNOWN_POINTS) - in_code)}")


# -- injector semantics -----------------------------------------------------

def test_same_seed_reproduces_injection_sequence():
    plan = {"seed": 1234, "faults": [
        {"point": "rpc.transport", "probability": 0.4,
         "error": "ConnectionError"}]}

    def run_sequence():
        inj = chaos.configure(plan)
        seq = []
        for _ in range(50):
            try:
                chaos.point("rpc.transport", method="ping", cluster="c")
                seq.append(".")
            except ConnectionError:
                seq.append("X")
        return seq, [f["seq"] for f in inj.fired]

    seq1, fired1 = run_sequence()
    seq2, fired2 = run_sequence()
    assert seq1 == seq2 and fired1 == fired2
    assert 0 < seq1.count("X") < 50       # probabilistic, but seeded
    # A different seed yields a different sequence.
    plan2 = dict(plan, seed=99)
    inj = chaos.configure(plan2)
    seq3 = []
    for _ in range(50):
        try:
            chaos.point("rpc.transport", method="ping", cluster="c")
            seq3.append(".")
        except ConnectionError:
            seq3.append("X")
    assert seq3 != seq1


def test_reusing_the_same_plan_object_starts_fresh():
    """Injector must copy rule counters: re-running the SAME parsed
    Plan (the reproducibility workflow) starts from zero fires."""
    plan = chaos.parse_plan({"seed": 0, "faults": [
        {"point": "skylet.tick", "times": 1}]})
    for _ in range(2):
        chaos.configure(plan)
        with pytest.raises(chaos.ChaosError):
            chaos.point("skylet.tick", cluster="c")
        chaos.point("skylet.tick", cluster="c")   # exhausted


def test_malformed_env_plan_disables_injection_loudly(monkeypatch):
    """A typo'd plan must NOT leak ValueError into production paths
    (probe loops would misread it as component failure) — injection
    disables with a typed chaos.plan_invalid event instead."""
    monkeypatch.setenv("SKYTPU_CHAOS_PLAN_JSON", "{not json")
    chaos._reset_for_tests()
    chaos.point("serve.probe", service="s", replica="1")   # no raise
    assert not chaos.active()
    assert len(_events("chaos.plan_invalid")) == 1


def test_env_inline_plan_activates_and_emits_typed_event(monkeypatch):
    monkeypatch.setenv("SKYTPU_CHAOS_PLAN_JSON", json.dumps(
        {"seed": 0, "faults": [{"point": "jobs.transition", "times": 1,
                                "match": {"status": "RUNNING"}}]}))
    chaos._reset_for_tests()
    assert chaos.active()
    chaos.point("jobs.transition", status="PENDING", job_id=1)  # no match
    with pytest.raises(chaos.ChaosError):
        chaos.point("jobs.transition", status="RUNNING", job_id=1)
    chaos.point("jobs.transition", status="RUNNING", job_id=1)  # exhausted
    evs = _events("chaos.injected")
    assert len(evs) == 1
    assert evs[0]["attrs"]["point"] == "jobs.transition"
    assert evs[0]["attrs"]["ctx.status"] == "RUNNING"


def test_plan_file_activation_and_latency_only_fault(tmp_path,
                                                     monkeypatch):
    plan_path = tmp_path / "plan.json"
    plan_path.write_text(json.dumps(
        {"seed": 0, "faults": [{"point": "serve.probe",
                                "latency_s": 0.15}]}))
    monkeypatch.setenv("SKYTPU_CHAOS_PLAN", str(plan_path))
    chaos._reset_for_tests()
    t0 = time.monotonic()
    chaos.point("serve.probe", service="s", replica="1")   # sleeps, no raise
    assert time.monotonic() - t0 >= 0.14
    assert _events("chaos.injected")[0]["attrs"]["effect"] == "latency"


def test_after_skips_leading_hits():
    chaos.configure({"seed": 0, "faults": [
        {"point": "train.checkpoint_save", "after": 2, "times": 1}]})
    chaos.point("train.checkpoint_save", step=1)
    chaos.point("train.checkpoint_save", step=2)
    with pytest.raises(chaos.ChaosError):
        chaos.point("train.checkpoint_save", step=3)
    chaos.point("train.checkpoint_save", step=4)


# -- scenario 1: provisioning stockout -> zone failover ---------------------

def _local_task(run="true", name=None):
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task
    t = Task(name=name, run=run)
    t.set_resources(Resources(cloud="local"))
    return t


def test_stockout_zone_failover(monkeypatch):
    """Two zones stock out (seeded CapacityError at the provision
    dispatcher); the failover loop blocklists each and lands the SAME
    cluster in the third zone — one cluster, no duplicate launches."""
    monkeypatch.setenv("SKYTPU_LOCAL_ZONES", "zone-a,zone-b,zone-c")
    from skypilot_tpu import state
    from skypilot_tpu.backend import RetryingProvisioner
    inj = chaos.configure({"seed": 7, "faults": [
        {"point": "provision.run_instances", "times": 2,
         "error": "CapacityError",
         "message": "[chaos] ZONE_RESOURCE_POOL_EXHAUSTED"}]})

    handle = RetryingProvisioner().provision(_local_task(), "chaos-fo")
    assert handle.zone == "zone-c"
    # The injection sequence is the failover path: zone-a then zone-b.
    assert [f["ctx"]["zone"] for f in inj.fired] == ["zone-a", "zone-b"]
    assert inj.observed["provision.run_instances"] == 3
    assert len(_events("chaos.injected")) == 2
    rec = state.get_cluster("chaos-fo")
    assert state.ClusterStatus(rec["status"]) == state.ClusterStatus.UP
    # No duplicate launches: the fake cloud holds exactly ONE cluster.
    clusters_root = os.environ["SKYTPU_LOCAL_CLUSTERS_ROOT"]
    assert os.listdir(clusters_root) == ["chaos-fo"]


def test_stockout_everywhere_is_typed_with_history(monkeypatch):
    monkeypatch.setenv("SKYTPU_LOCAL_ZONES", "zone-a,zone-b")
    from skypilot_tpu.backend import RetryingProvisioner
    chaos.configure({"seed": 7, "faults": [
        {"point": "provision.run_instances", "error": "CapacityError"}]})
    with pytest.raises(exceptions.ResourcesUnavailableError) as ei:
        RetryingProvisioner().provision(_local_task(), "chaos-exhaust")
    assert len(ei.value.failover_history) == 2     # one per zone


# -- scenario 2: preemption mid-job -> EAGER_NEXT_ZONE recovery -------------

def test_preemption_recovery_blocklists_evicted_zone(monkeypatch):
    """Slice preempted mid-job: EAGER_NEXT_ZONE tears down, blocklists
    the evicted zone, and relaunches the job in the next zone. A
    standing chaos stockout on the evicted zone is the tripwire — a
    broken blocklist would trip it; an intact one never re-attempts
    zone-a at all."""
    monkeypatch.setenv("SKYTPU_LOCAL_ZONES", "zone-a,zone-b")
    from skypilot_tpu.backend import TpuVmBackend
    from skypilot_tpu.jobs import recovery_strategy
    from skypilot_tpu.provision import local as local_provider
    from skypilot_tpu.runtime.job_queue import JobStatus

    task = _local_task(run="echo recovered-ok", name="chaos-mj")
    strat = recovery_strategy.EagerNextZoneStrategy(task, "chaos-prempt")
    job1, handle1 = strat.launch()
    assert handle1.zone == "zone-a"

    # Preempt: the fake cloud loses the whole slice out-of-band, then
    # chaos declares zone-a permanently stocked out.
    local_provider.terminate_instances("chaos-prempt", "zone-a")
    inj = chaos.configure({"seed": 11, "faults": [
        {"point": "provision.run_instances", "match": {"zone": "zone-a"},
         "error": "CapacityError"}]})
    launches_before = recovery_strategy.RECOVERY_LAUNCHES.labels(
        strategy="EagerNextZoneStrategy").value

    job2, handle2 = strat.recover()
    assert handle2.zone == "zone-b"
    # The evicted zone was never even attempted (blocklist worked) —
    # every provision attempt the injector observed targeted zone-b.
    zones_tried = [o["ctx"]["zone"] for o in inj.observations
                   if o["point"] == "provision.run_instances"]
    assert zones_tried == ["zone-b"]
    assert inj.fired == []
    assert recovery_strategy.RECOVERY_LAUNCHES.labels(
        strategy="EagerNextZoneStrategy").value == launches_before + 1

    # Convergence: the relaunched job runs to SUCCEEDED on the new
    # cluster, and the sky holds exactly one cluster (no duplicates).
    backend = TpuVmBackend()
    assert backend.wait_job(handle2, job2,
                            timeout=60) == JobStatus.SUCCEEDED
    clusters_root = os.environ["SKYTPU_LOCAL_CLUSTERS_ROOT"]
    assert os.listdir(clusters_root) == ["chaos-prempt"]
    backend.teardown(handle2)


# -- scenario 3: RPC partition -> retries, typed error, deadline ------------

def test_rpc_partition_retries_then_typed_error():
    from skypilot_tpu.runtime.rpc_client import (RPC_FAILURES, ClusterRpc,
                                                 ClusterRpcError)
    from skypilot_tpu.utils.command_runner import LocalRunner
    inj = chaos.configure({"seed": 5, "faults": [
        {"point": "rpc.transport", "error": "ConnectionError",
         "message": "[chaos] partition: head unreachable"}]})
    before = RPC_FAILURES.labels(method="ping", kind="transport").value
    rpc = ClusterRpc(LocalRunner(), "chaos-part")
    # Generous budget: asserts the retry count, not the deadline.
    with pytest.raises(ClusterRpcError, match="partition"):
        rpc.call("ping", timeout=30.0)
    assert inj.observed["rpc.transport"] == 3      # idempotent: 3 tries
    assert RPC_FAILURES.labels(method="ping",
                               kind="transport").value == before + 3
    assert len(_events("chaos.injected")) == 3
    # Non-idempotent methods never retry a partition.
    with pytest.raises(ClusterRpcError):
        rpc.call("submit", timeout=5.0)
    assert inj.observed["rpc.transport"] == 4


def test_rpc_partition_respects_overall_deadline():
    """attempts x timeout must not stretch the caller's budget ~3x:
    with a 1.2s budget the retry loop gives up early — and never
    hangs past the deadline."""
    from skypilot_tpu.runtime.rpc_client import ClusterRpc, ClusterRpcError
    from skypilot_tpu.utils.command_runner import LocalRunner
    inj = chaos.configure({"seed": 5, "faults": [
        {"point": "rpc.transport", "error": "ConnectionError"}]})
    rpc = ClusterRpc(LocalRunner(), "chaos-deadline")
    t0 = time.monotonic()
    with pytest.raises(ClusterRpcError):
        rpc.call("ping", timeout=1.2)
    elapsed = time.monotonic() - t0
    assert elapsed < 2.5, f"hung {elapsed:.1f}s past a 1.2s budget"
    assert inj.observed["rpc.transport"] < 3


# -- scenario 4: replica death -> replacement within one probe cycle --------

def _mk_manager(service):
    from skypilot_tpu.serve.replica_managers import ReplicaManager
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec(initial_delay_seconds=60.0, replica_port=18080)
    task_config = {"run": "true", "resources": {"cloud": "local"}}
    return ReplicaManager(service, spec, task_config)


def test_replica_death_replaced_within_one_probe_cycle():
    from skypilot_tpu.observability import health, slo
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve.serve_state import ReplicaStatus

    svc = "chaos-svc"
    dead_url = "http://127.0.0.1:1"       # nothing listens on port 1
    serve_state.upsert_replica(svc, 1, f"sky-serve-{svc}-1",
                               ReplicaStatus.READY, dead_url)
    mgr = _mk_manager(svc)

    # One probe cycle: the dead replica (its cluster has no state
    # record — the slice is gone) is retired and a replacement launch
    # is already in flight.
    mgr.probe_all()
    rows = {r["replica_id"]: r for r in serve_state.list_replicas(svc)}
    assert rows[2]["status"] in (ReplicaStatus.PROVISIONING,
                                 ReplicaStatus.STARTING)
    assert rows.get(1) is None or rows[1]["status"] in (
        ReplicaStatus.PREEMPTED, ReplicaStatus.SHUTTING_DOWN,
        ReplicaStatus.SHUTDOWN)

    # The SLO watchdog explains the death: a component_dead rule over
    # the (really-probed) dead endpoint fires a typed slo.breach.
    comp = health.probe_http(dead_url, comp="replica", instance=f"{svc}/1")
    assert comp["status"] == health.DEAD
    watchdog = slo.Watchdog(
        rules=[slo.SloRule("component-alive", "component_dead",
                           threshold=0.0)],
        snapshot_fn=lambda: ({}, [comp]))
    transitions = watchdog.tick()
    assert [t["event"] for t in transitions] == ["slo.breach"]
    assert len(_events("slo.breach")) == 1

    # Wait out the replacement launch, then probe again: the fresh
    # replica is within its initial_delay grace — NO second relaunch.
    deadline = time.time() + 60
    while time.time() < deadline:
        rows = {r["replica_id"]: r
                for r in serve_state.list_replicas(svc)}
        if rows.get(2, {}).get("status") == ReplicaStatus.STARTING:
            break
        time.sleep(0.2)
    assert rows[2]["status"] == ReplicaStatus.STARTING, rows
    mgr.probe_all()
    rows = {r["replica_id"]: r for r in serve_state.list_replicas(svc)}
    assert rows[2]["status"] == ReplicaStatus.STARTING
    assert max(rows) == 2                  # exactly one replacement
    mgr.terminate_all()


def test_injected_probe_failures_flip_replica_then_self_heal():
    """Seeded probe faults: exactly 3 injected failures flip a READY
    replica NOT_READY (the controller's failure threshold); when the
    fault schedule exhausts, the next cycle flips it back — recovery
    counters match injected faults 1:1."""
    from skypilot_tpu import state as cluster_state
    from skypilot_tpu.provision import local as local_provider
    from skypilot_tpu.provision.common import ProvisionConfig
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve.replica_managers import (
        PROBE_FAILURES, PROBE_FAILURES_BEFORE_NOT_READY)
    from skypilot_tpu.serve.serve_state import ReplicaStatus
    import http.server
    import socketserver

    svc = "chaos-heal"
    # A real, healthy replica endpoint...
    class Ok(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"ok")

        def log_message(self, *a):
            pass

    httpd = socketserver.TCPServer(("127.0.0.1", 0), Ok)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"

    # ...backed by a live fake-cloud cluster so the prober doesn't take
    # the cluster-gone path.
    cluster = f"sky-serve-{svc}-1"
    local_provider.run_instances(ProvisionConfig(
        cluster_name=cluster, num_nodes=1, hosts_per_node=1,
        zone="local", region="local", accelerator=None,
        accelerator_count=0, instance_type=None, use_spot=False,
        runtime_version=None, disk_size=None, image_id=None))
    cluster_state.set_cluster(cluster, {"provider": "local",
                                        "zone": "local"},
                              cluster_state.ClusterStatus.UP, 0.0)
    serve_state.upsert_replica(svc, 1, cluster, ReplicaStatus.READY, url)
    mgr = _mk_manager(svc)

    inj = chaos.configure({"seed": 2, "faults": [
        {"point": "serve.probe", "match": {"service": svc},
         "times": PROBE_FAILURES_BEFORE_NOT_READY}]})
    before = PROBE_FAILURES.labels(service=svc).value

    for i in range(PROBE_FAILURES_BEFORE_NOT_READY):
        mgr.probe_all()
    assert serve_state.list_replicas(svc)[0]["status"] == \
        ReplicaStatus.NOT_READY
    assert PROBE_FAILURES.labels(service=svc).value - before == \
        PROBE_FAILURES_BEFORE_NOT_READY == len(inj.fired)
    assert len(_events("chaos.injected")) == \
        PROBE_FAILURES_BEFORE_NOT_READY

    # Fault schedule exhausted: one clean probe heals the replica.
    mgr.probe_all()
    assert serve_state.list_replicas(svc)[0]["status"] == \
        ReplicaStatus.READY
    httpd.shutdown()


# -- scenario 4c: LB partition from one replica -> clean failover -----------

def test_lb_fails_over_around_partitioned_replica():
    """A standing fault partitions the LB from replica 1: every request
    fails over to replica 2 before any byte reaches the client, the
    failed attempts land in the retry counter, and the injected count
    matches the retries 1:1."""
    import http.server
    import urllib.request
    from skypilot_tpu.serve import load_balancer, serve_state

    class Ok(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            body = b"from-r2"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    replica2 = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Ok)
    threading.Thread(target=replica2.serve_forever, daemon=True).start()
    url1 = "http://127.0.0.1:1"           # partitioned (and dead anyway)
    url2 = f"http://127.0.0.1:{replica2.server_address[1]}"
    svc = "chaos-lb"
    serve_state.add_service(svc, {}, {}, 0)
    serve_state.upsert_replica(svc, 1, "r1",
                               serve_state.ReplicaStatus.READY, url1)
    serve_state.upsert_replica(svc, 2, "r2",
                               serve_state.ReplicaStatus.READY, url2)
    inj = chaos.configure({"seed": 3, "faults": [
        {"point": "serve.lb.forward", "match": {"backend": url1},
         "error": "ConnectionError",
         "message": "[chaos] partitioned from r1"}]})
    retries_before = load_balancer.LB_RETRIES.labels(backend=url1).value

    lb = load_balancer._ThreadingServer(
        ("127.0.0.1", 0),
        load_balancer.make_handler(svc, load_balancer.RoundRobinPolicy()))
    threading.Thread(target=lb.serve_forever, daemon=True).start()
    try:
        bodies = set()
        for _ in range(4):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{lb.server_address[1]}/x",
                    timeout=10) as r:
                assert r.status == 200
                bodies.add(r.read())
        assert bodies == {b"from-r2"}      # every request converged
        r1_attempts = [f for f in inj.fired
                       if f["ctx"]["backend"] == url1]
        assert len(r1_attempts) >= 1       # round-robin did try r1
        assert load_balancer.LB_RETRIES.labels(backend=url1).value \
            - retries_before == len(r1_attempts)
    finally:
        lb.shutdown()
        replica2.shutdown()


# -- scenario 5: hot-tenant spike -> typed shed, scale-out, recovery --------

from conftest import ttft_fams as _ttft_fams  # noqa: E402


def test_hot_tenant_spike_typed_shed_at_both_tiers():
    """Seeded hot-tenant spike, tier by tier: the ``qos.shed`` chaos
    point forces a typed 429 at the LB AND at the model-server
    admission check (one fault each, matched on ``where``), then the
    REAL token bucket takes over — the hot tenant sheds
    ``rate_limited`` while the background tenant sails through."""
    import http.server
    import urllib.error
    import urllib.request
    from skypilot_tpu.infer import qos as qos_lib
    from skypilot_tpu.serve import load_balancer, serve_state

    inj = chaos.configure({"seed": 0, "faults": [
        {"point": "qos.shed", "match": {"tenant": "hot",
                                        "where": "server"}, "times": 1},
        {"point": "qos.shed", "match": {"tenant": "hot",
                                        "where": "lb"}, "times": 1},
    ]})

    # Server tier (the engine's front door), driven directly.
    cfg = qos_lib.QosConfig(enabled=True, default_rate=0.001,
                            default_burst=2.0)
    ac = qos_lib.AdmissionController(cfg, where="server")
    with pytest.raises(qos_lib.RateLimitedError) as ei:
        ac.admit("hot")                     # chaos-forced shed
    assert ei.value.typed_error["type"] == "rate_limited"
    ac.admit("hot")                         # burst allowance
    ac.admit("hot")
    with pytest.raises(qos_lib.RateLimitedError) as ei:
        ac.admit("hot")                     # the real bucket
    assert ei.value.typed_error["retry_after_ms"] > 0
    ac.admit("background")                  # unaffected neighbor

    # LB tier, over real HTTP: the chaos-forced shed arrives as a
    # typed 429 JSON body + Retry-After; the next request proxies.
    class Ok(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            self.rfile.read(n)
            body = b'{"ok": true}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    replica = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Ok)
    threading.Thread(target=replica.serve_forever, daemon=True).start()
    svc = "chaos-qos"
    serve_state.add_service(svc, {}, {}, 0)
    serve_state.upsert_replica(
        svc, 1, "r1", serve_state.ReplicaStatus.READY,
        f"http://127.0.0.1:{replica.server_address[1]}")
    lb = load_balancer._ThreadingServer(
        ("127.0.0.1", 0),
        load_balancer.make_handler(
            svc, load_balancer.RoundRobinPolicy(),
            qos=qos_lib.AdmissionController(
                qos_lib.QosConfig(enabled=True), where="lb")))
    threading.Thread(target=lb.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{lb.server_address[1]}/generate"
    try:
        req = urllib.request.Request(
            url, data=b"{}",
            headers={"x-skytpu-tenant": "hot",
                     "Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as he:
            urllib.request.urlopen(req, timeout=30)
        assert he.value.code == 429
        body = json.loads(he.value.read())
        assert body["error"]["type"] == "rate_limited"
        assert int(he.value.headers["Retry-After"]) >= 1
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200          # fault exhausted
    finally:
        lb.shutdown()
        replica.shutdown()

    # Every shed is attributed: two injected faults, both observed at
    # their tier, and typed chaos.injected events in the log.
    assert [f["ctx"]["where"] for f in inj.fired] == ["server", "lb"]
    assert len(_events("chaos.injected")) == 2


def test_hot_tenant_spike_fairness_and_no_retrace():
    """The engine half of the ROADMAP item 4 scenario: a hot tenant's
    flood + a background tenant under WFQ, a high-priority arrival
    preempting-by-eviction mid-spike — with the program grid warmed
    and the compile watch armed, so the whole multi-tenant episode
    must introduce ZERO unexpected compiles (tenant count never enters
    program identity). Fairness is asserted from flight-record group
    composition, the scenario's own telemetry."""
    import jax
    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.infer import qos as qos_lib
    from skypilot_tpu.models import llama
    from skypilot_tpu.observability import flight as flight_lib

    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    rec = flight_lib.FlightRecorder()
    # Quantum at one request's token cost (10 prompt + 16 budget): DRR
    # alternates tenants request-by-request, so admission mixes
    # tenants — the group composition the fairness assert reads.
    # Prompts outgrow the prefill chunk (10 > 8) so every request is
    # CHUNK-admitted: preempted victims retire into the prefix cache
    # and resume warm, the path the parity guarantee covers.
    e = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                            prompt_buckets=(16,), prefill_chunk=8,
                            prefix_pool=4, max_wave=2, pad_waves=True,
                            qos=qos_lib.FairScheduler(quantum=26),
                            flight_recorder=rec)
    e.warm_programs(max_burst=4)
    e.declare_warmup_complete()

    hot_ids = [e.add_request([10 + i, 2, 3, 4, 5, 6, 7, 8, 9, 11],
                             max_new_tokens=16, tenant="hot")
               for i in range(4)]
    bg_ids = [e.add_request([40 + i, 2, 3, 4, 5, 6, 7, 8, 9, 11],
                            max_new_tokens=16, tenant="background")
              for i in range(2)]
    e.admit()
    for _ in range(3):
        e.step_burst(max_burst=4)
    vip = e.add_request([3, 1, 4, 1, 5, 9], max_new_tokens=6,
                        tenant="vip", priority=1)
    e.run_to_completion(max_burst=4)

    by_rid = {r.rid: r for r in e.finished}
    assert all(by_rid[i].done for i in hot_ids + bg_ids + [vip])
    # Zero unexpected compiles across the whole multi-tenant episode:
    # the compile-watch gate from PR 10 is the retrace arbiter.
    assert e.compile_watch.unexpected == []
    # The high-priority request evicted a running slot; the victim
    # resumed and still finished (parity matrix: tests/test_qos.py).
    assert sum(by_rid[i].preemptions for i in hot_ids + bg_ids) >= 1
    preempts = [r for r in rec.tail() if r["burst"] == "preempt"]
    assert len(preempts) >= 1
    # Fairness from flight-record group composition: decode bursts
    # carried BOTH tenants side by side (nobody owned the machine),
    # and the background tenant drained before the hot flood did.
    decode_recs = [r for r in rec.tail()
                   if r["burst"] in ("decode", "decode1")]
    assert any(
        {"hot", "background"} <= set(r.get("tenants", {}))
        for r in decode_recs)
    # ...and the background tenant got REAL throughput despite
    # arriving behind the whole flood: its first completion precedes
    # the flood's tail (FIFO would strand every background request
    # after every hot one). Full drain order is DRR-proportional, not
    # background-first — fair share, not priority.
    finish_order = [r.rid for r in e.finished]
    assert min(finish_order.index(i) for i in bg_ids) < \
        max(finish_order.index(i) for i in hot_ids)


def test_spike_burn_rate_scaleout_and_slo_recovery():
    """The control-plane half: the TTFT-p95 burn rate (BOTH windows
    breached) scales the fleet out during the spike, and the SLO
    watchdog's typed breach/recovered transitions bracket the episode
    — recovery within SLO is asserted from the transition log, not
    sleeps."""
    from skypilot_tpu.observability import slo
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve.service_spec import SkyServiceSpec

    spec = SkyServiceSpec(min_replicas=1, max_replicas=4,
                          target_ttft_p95_seconds=1.0,
                          upscale_delay_seconds=0.0,
                          downscale_delay_seconds=0.0)
    asc = autoscalers.Autoscaler.from_spec(spec)
    assert isinstance(asc, autoscalers.BurnRateAutoscaler)
    asc._snapshot_fn = None                 # the test feeds observe()
    rule = slo.SloRule("ttft-p95", "histogram_quantile", threshold=1.0,
                       metric="skytpu_ttft_seconds")
    wd = slo.Watchdog(rules=[rule], snapshot_fn=lambda: ({}, []))

    # Healthy baseline, then the spike: slow samples flood both
    # windows -> scale-out AND a typed slo.breach.
    for ts, fams in ((0.0, _ttft_fams(100, 0)),
                     (301.0, _ttft_fams(120, 200)),
                     (602.0, _ttft_fams(120, 500))):
        asc.observe(fams, ts=ts)
        wd.observe(fams, [], ts=ts)
    assert asc.decide(0.0, 1, 1).target == 2
    assert [a["rule"] for a in wd.active_alerts()] == ["ttft-p95"]
    assert len(_events("slo.breach")) == 1

    # Post-scale-out recovery: new samples are fast again in both
    # windows -> slo.recovered fires and the autoscaler drains back.
    for ts, fams in ((903.0, _ttft_fams(2000, 500)),
                     (1204.0, _ttft_fams(5000, 500)),
                     (1505.0, _ttft_fams(9000, 500))):
        asc.observe(fams, ts=ts)
        wd.observe(fams, [], ts=ts)
    assert wd.active_alerts() == []
    assert len(_events("slo.recovered")) == 1
    assert asc.decide(0.0, 2, 2).target <= 2   # calm: no more growth


# -- recovery-budget exhaustion -> typed give-up ----------------------------

def test_recovery_exhaustion_records_typed_give_up(monkeypatch):
    monkeypatch.setenv("SKYTPU_JOBS_MAX_RECOVERY_ATTEMPTS", "2")
    from skypilot_tpu.jobs import state as jobs_state
    from skypilot_tpu.jobs.controller import JobsController
    from skypilot_tpu.jobs.state import ManagedJobStatus

    jid = jobs_state.add("chaos-exhaust", {"run": "true"},
                         "EAGER_NEXT_ZONE")
    jobs_state.set_status(jid, ManagedJobStatus.RUNNING)
    ctl = object.__new__(JobsController)
    ctl.job_id = jid
    ctl.cluster_name = "sky-jobs-chaos"
    ctl.task_recoveries = 2               # budget already spent
    assert ctl._recover() is None
    rec = jobs_state.get(jid)
    assert rec["status"] == ManagedJobStatus.FAILED_RECOVERY
    assert "recovery budget exhausted" in rec["last_error"]
    evs = _events("jobs.recovery_gave_up")
    assert len(evs) == 1 and evs[0]["attrs"]["max_attempts"] == 2
    # Terminal exactly once: a late SUCCEEDED must not apply.
    assert not jobs_state.set_status(jid, ManagedJobStatus.SUCCEEDED)
    assert jobs_state.get(jid)["status"] == \
        ManagedJobStatus.FAILED_RECOVERY


def test_recovery_budget_configurable_via_config_file(tmp_path,
                                                      monkeypatch):
    from skypilot_tpu import config
    from skypilot_tpu.jobs import recovery_strategy
    monkeypatch.delenv("SKYTPU_JOBS_MAX_RECOVERY_ATTEMPTS", raising=False)
    assert recovery_strategy.max_recovery_attempts() == 10   # default
    cfg = tmp_path / "config.yaml"
    cfg.write_text("jobs:\n  max_recovery_attempts: 4\n"
                   "  recovery_backoff_seconds: 0.25\n")
    monkeypatch.setenv("SKYPILOT_TPU_CONFIG", str(cfg))
    config.reload()
    try:
        assert recovery_strategy.max_recovery_attempts() == 4
        pol = recovery_strategy.recovery_backoff_policy()
        assert pol.backoff_base_s == 0.25 and pol.max_attempts == 4
        # Env beats config.
        monkeypatch.setenv("SKYTPU_JOBS_MAX_RECOVERY_ATTEMPTS", "7")
        assert recovery_strategy.max_recovery_attempts() == 7
        # A typo'd override falls through to the config layer (typed
        # event) instead of turning the next recovery into
        # FAILED_CONTROLLER.
        monkeypatch.setenv("SKYTPU_JOBS_MAX_RECOVERY_ATTEMPTS", "ten")
        assert recovery_strategy.max_recovery_attempts() == 4
        assert len(_events("jobs.config_invalid")) == 1
    finally:
        config.reload()
