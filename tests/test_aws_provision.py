"""AWS EC2 provisioning against a fake Query API (offline).

Same seam as the GCP fake (tests/test_gcp_provision.py): a stateful
fake transport models the instance/SG/keypair state machine and returns
real EC2 XML, so the provider's parsing, idempotency, and error mapping
run exactly as they would against the live API (reference tests the
analogous layer in tests/unit_tests with moto-style stubs)."""

import datetime

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import aws, aws_auth
from skypilot_tpu.provision.common import ProvisionConfig


# -- SigV4 ------------------------------------------------------------------

def test_sigv4_derived_key_matches_documented_vector():
    """The AWS General Reference publishes this exact derivation
    example (secret/date/region/service -> signing key)."""
    key = aws_auth.derive_signing_key(
        "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        "20120215", "us-east-1", "iam")
    assert key.hex() == ("f4780e2d9f65fa895f9c67b32ce1baf0"
                         "b0d8a43505a000a1a9e090d414db404d")


def test_sigv4_request_shape():
    creds = aws_auth.AwsCredentials("AKIDEXAMPLE", "secret",
                                    session_token="tok")
    url, headers, body = aws_auth.sign_request(
        creds, "POST", "ec2.us-east-1.amazonaws.com", "/",
        {"Action": "DescribeInstances", "Version": "2016-11-15"},
        region="us-east-1", service="ec2",
        now=datetime.datetime(2026, 1, 2, 3, 4, 5,
                              tzinfo=datetime.timezone.utc))
    assert url == "https://ec2.us-east-1.amazonaws.com/"
    auth = headers["Authorization"]
    assert auth.startswith("AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/"
                           "20260102/us-east-1/ec2/aws4_request")
    # The session token must be part of the signed header set — STS
    # creds fail with an unsigned token.
    assert "x-amz-security-token" in auth
    assert headers["X-Amz-Date"] == "20260102T030405Z"
    assert b"Action=DescribeInstances" in body


def test_credentials_from_ini(tmp_path, monkeypatch):
    for var in ("AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY",
                "AWS_SESSION_TOKEN", "AWS_PROFILE"):
        monkeypatch.delenv(var, raising=False)
    ini = tmp_path / "credentials"
    ini.write_text("[default]\naws_access_key_id = AK1\n"
                   "aws_secret_access_key = SK1\n"
                   "[other]\naws_access_key_id = AK2\n"
                   "aws_secret_access_key = SK2\n")
    monkeypatch.setenv("AWS_SHARED_CREDENTIALS_FILE", str(ini))
    creds = aws_auth.load_credentials()
    assert (creds.access_key, creds.secret_key) == ("AK1", "SK1")
    assert aws_auth.load_credentials("other").access_key == "AK2"
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "ENVK")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "ENVS")
    assert aws_auth.load_credentials().access_key == "ENVK"


# -- fake EC2 ---------------------------------------------------------------

class FakeEc2:
    """Stateful fake: instances keyed by id, one SG per group name.
    Returns genuine EC2 response XML (namespaced, like the real API)."""

    NS = 'xmlns="http://ec2.amazonaws.com/doc/2016-11-15/"'

    def __init__(self, capacity_errors=0, quota_error=False):
        self.instances = {}           # id -> dict
        self.sgs = {}                 # name -> {id, rules: set}
        self.keypairs = set()
        self.calls = []               # (action, params)
        self._next = 0
        self.capacity_errors = capacity_errors
        self.quota_error = quota_error

    def _error(self, code, msg):
        return (f'<Response {self.NS}><Errors><Error><Code>{code}</Code>'
                f"<Message>{msg}</Message></Error></Errors>"
                "<RequestID>x</RequestID></Response>")

    def __call__(self, action, params, region):
        self.calls.append((action, dict(params)))
        return getattr(self, "_" + action)(params, region)

    # -- instances --
    def _RunInstances(self, params, region):
        if self.quota_error:
            return self._error("VcpuLimitExceeded", "vCPU limit")
        if self.capacity_errors > 0:
            self.capacity_errors -= 1
            return self._error("InsufficientInstanceCapacity",
                               "no capacity in AZ")
        n = int(params["MinCount"])
        tags = {}
        i = 1
        while f"TagSpecification.1.Tag.{i}.Key" in params:
            tags[params[f"TagSpecification.1.Tag.{i}.Key"]] = \
                params[f"TagSpecification.1.Tag.{i}.Value"]
            i += 1
        items = []
        for idx in range(n):
            iid = f"i-{self._next:08x}"
            self._next += 1
            self.instances[iid] = {
                "id": iid, "state": "pending", "tags": tags,
                "launch_index": idx,
                "private_ip": f"10.0.0.{len(self.instances) + 10}",
                "public_ip": f"54.1.2.{len(self.instances) + 10}",
                "spot": "InstanceMarketOptions.MarketType" in params,
                "type": params["InstanceType"],
                "image": params["ImageId"],
                "sg": params.get("SecurityGroupId.1"),
                "key": params.get("KeyName"),
            }
            items.append(f"<item><instanceId>{iid}</instanceId>"
                         f"<amiLaunchIndex>{idx}</amiLaunchIndex>"
                         "<instanceState><code>0</code>"
                         "<name>pending</name></instanceState></item>")
        return (f'<RunInstancesResponse {self.NS}><instancesSet>'
                f"{''.join(items)}</instancesSet></RunInstancesResponse>")

    def _DescribeInstances(self, params, region):
        want_cluster = None
        states = set()
        for k, v in params.items():
            if k.startswith("Filter") and k.endswith("Name"):
                base = k[:-len("Name")]
                vals = [params[p] for p in params
                        if p.startswith(base + "Value")]
                if v == "tag:" + aws.CLUSTER_TAG:
                    want_cluster = vals[0]
                elif v == "instance-state-name":
                    states = set(vals)
        items = []
        for inst in self.instances.values():
            # Instances auto-progress pending->running (and a scripted
            # stopping->stopped after _stopping_gets observations).
            if inst["state"] == "pending":
                inst["state"] = "running"
            elif inst["state"] == "stopping":
                left = inst.get("_stopping_gets", 0)
                if left <= 0:
                    inst["state"] = "stopped"
                else:
                    inst["_stopping_gets"] = left - 1
            if want_cluster is not None and \
                    inst["tags"].get(aws.CLUSTER_TAG) != want_cluster:
                continue
            if states and inst["state"] not in states:
                continue
            pub = (f"<ipAddress>{inst['public_ip']}</ipAddress>"
                   if inst["state"] == "running" else "")
            items.append(
                "<item>"
                f"<instanceId>{inst['id']}</instanceId>"
                f"<amiLaunchIndex>{inst['launch_index']}</amiLaunchIndex>"
                "<instanceState><code>16</code>"
                f"<name>{inst['state']}</name></instanceState>"
                f"<privateIpAddress>{inst['private_ip']}</privateIpAddress>"
                f"{pub}"
                f"<groupSet><item><groupId>{inst['sg']}</groupId>"
                "</item></groupSet>"
                "</item>")
        return (f'<DescribeInstancesResponse {self.NS}><reservationSet>'
                f"<item><instancesSet>{''.join(items)}</instancesSet>"
                "</item></reservationSet></DescribeInstancesResponse>")

    def _set_state(self, params, state):
        ids = [v for k, v in params.items()
               if k.startswith("InstanceId.")]
        for iid in ids:
            self.instances[iid]["state"] = state
        return (f'<Response {self.NS}><return>true</return></Response>'
                .replace("Response", "OkResponse"))

    def _StartInstances(self, params, region):
        return self._set_state(params, "pending")

    def _StopInstances(self, params, region):
        return self._set_state(params, "stopped")

    def _TerminateInstances(self, params, region):
        ids = [v for k, v in params.items()
               if k.startswith("InstanceId.")]
        for iid in ids:
            del self.instances[iid]
        return f'<TerminateInstancesResponse {self.NS}/>'

    # -- security groups --
    def _CreateSecurityGroup(self, params, region):
        name = params["GroupName"]
        if name in self.sgs:
            return self._error("InvalidGroup.Duplicate", "exists")
        sg_id = f"sg-{len(self.sgs):04x}"
        self.sgs[name] = {"id": sg_id, "rules": set()}
        return (f'<CreateSecurityGroupResponse {self.NS}>'
                f"<groupId>{sg_id}</groupId>"
                "</CreateSecurityGroupResponse>")

    def _DescribeSecurityGroups(self, params, region):
        name = params.get("Filter.1.Value.1")
        sg = self.sgs.get(name)
        inner = (f"<item><groupId>{sg['id']}</groupId></item>"
                 if sg else "")
        return (f'<DescribeSecurityGroupsResponse {self.NS}>'
                f"<securityGroupInfo>{inner}</securityGroupInfo>"
                "</DescribeSecurityGroupsResponse>")

    def _AuthorizeSecurityGroupIngress(self, params, region):
        sg = next((s for s in self.sgs.values()
                   if s["id"] == params["GroupId"]), None)
        assert sg is not None, "authorize on unknown SG"
        rule = (params.get("IpPermissions.1.IpProtocol"),
                params.get("IpPermissions.1.FromPort"),
                params.get("IpPermissions.1.ToPort"),
                params.get("IpPermissions.1.IpRanges.1.CidrIp")
                or params.get("IpPermissions.1.UserIdGroupPairs.1.GroupId"))
        if rule in sg["rules"]:
            return self._error("InvalidPermission.Duplicate", "exists")
        sg["rules"].add(rule)
        return f'<AuthorizeSecurityGroupIngressResponse {self.NS}/>'

    def _DeleteSecurityGroup(self, params, region):
        for name, sg in list(self.sgs.items()):
            if sg["id"] == params["GroupId"]:
                del self.sgs[name]
        return f'<DeleteSecurityGroupResponse {self.NS}/>'

    # -- keypair / images --
    def _ImportKeyPair(self, params, region):
        if params["KeyName"] in self.keypairs:
            return self._error("InvalidKeyPair.Duplicate", "exists")
        self.keypairs.add(params["KeyName"])
        return f'<ImportKeyPairResponse {self.NS}/>'

    def _DescribeImages(self, params, region):
        return (f'<DescribeImagesResponse {self.NS}><imagesSet>'
                "<item><imageId>ami-old</imageId>"
                "<creationDate>2024-01-01T00:00:00Z</creationDate></item>"
                "<item><imageId>ami-jammy</imageId>"
                "<creationDate>2025-06-01T00:00:00Z</creationDate></item>"
                "</imagesSet></DescribeImagesResponse>")


@pytest.fixture
def fake(monkeypatch, tmp_path):
    f = FakeEc2()
    aws.set_transport(f)
    # Keypair material comes from a scratch key, not the user's (and no
    # ssh-keygen in this image: write the pair directly).
    priv = tmp_path / "sky-key"
    priv.write_text("fake private key\n")
    (tmp_path / "sky-key.pub").write_text("ssh-ed25519 AAAAfake test\n")
    monkeypatch.setenv("SKYPILOT_TPU_SSH_KEY", str(priv))
    from skypilot_tpu import authentication
    authentication.get_or_generate_keys.cache_clear()
    yield f
    aws.set_transport(None)
    authentication.get_or_generate_keys.cache_clear()


def _config(**kw):
    defaults = dict(cluster_name="c1", num_nodes=2, hosts_per_node=1,
                    zone="us-east-1a", region="us-east-1",
                    instance_type="p4d.24xlarge", accelerator="A100",
                    accelerator_count=8)
    defaults.update(kw)
    return ProvisionConfig(**defaults)


def test_create_cluster(fake):
    record = aws.run_instances(_config())
    assert len(record.created_instance_ids) == 2
    assert not record.resumed
    # Gang semantics: one RunInstances with MinCount == MaxCount == 2.
    run = next(p for a, p in fake.calls if a == "RunInstances")
    assert (run["MinCount"], run["MaxCount"]) == ("2", "2")
    assert run["Placement.AvailabilityZone"] == "us-east-1a"
    assert run["TagSpecification.1.Tag.1.Key"] == aws.CLUSTER_TAG
    assert run["TagSpecification.1.Tag.1.Value"] == "c1"
    assert run["ImageId"] == "ami-jammy"       # latest by creationDate
    # Keypair name embeds the key-material hash: a regenerated local
    # key can never silently collide with a stale imported 'sky-key'.
    assert run["KeyName"].startswith(aws.KEYPAIR_PREFIX + "-")
    assert run["KeyName"] in fake.keypairs
    # The cluster SG exists with ssh + intra-group rules.
    sg = fake.sgs[aws._sg_name("c1")]
    assert ("tcp", "22", "22", "0.0.0.0/0") in sg["rules"]
    assert ("-1", None, None, sg["id"]) in sg["rules"]

    aws.wait_instances("c1", "us-east-1a")
    assert aws.query_instances("c1", "us-east-1a") == "UP"


def test_run_is_idempotent_and_resumes(fake):
    aws.run_instances(_config())
    aws.wait_instances("c1", "us-east-1a")
    n_created = len(fake.instances)
    # Second run: nothing new.
    record = aws.run_instances(_config())
    assert not record.created_instance_ids
    assert len(fake.instances) == n_created
    # Stop, then run again -> StartInstances, resumed=True.
    aws.stop_instances("c1", "us-east-1a")
    assert aws.query_instances("c1", "us-east-1a") == "STOPPED"
    record = aws.run_instances(_config())
    assert record.resumed
    assert any(a == "StartInstances" for a, _ in fake.calls)
    assert aws.query_instances("c1", "us-east-1a") == "UP"


def test_spot_and_custom_image_and_labels(fake):
    aws.run_instances(_config(use_spot=True, image_id="ami-custom",
                              labels={"team": "ml"}))
    run = next(p for a, p in fake.calls if a == "RunInstances")
    assert run["InstanceMarketOptions.MarketType"] == "spot"
    assert run["ImageId"] == "ami-custom"
    assert run["TagSpecification.1.Tag.2.Key"] == "team"


def test_ports_open_as_sg_rules(fake):
    aws.run_instances(_config(ports=[8080, 443]))
    sg = fake.sgs[aws._sg_name("c1")]
    assert ("tcp", "8080", "8080", "0.0.0.0/0") in sg["rules"]
    assert ("tcp", "443", "443", "0.0.0.0/0") in sg["rules"]
    # Idempotent re-open.
    aws.open_ports("c1", [8080], "us-east-1a")


def test_relaunch_waits_out_stopping_state(fake):
    """StartInstances on a 'stopping' instance is IncorrectInstanceState
    — run_instances must wait for 'stopped' first, or the failover loop
    misreads a healthy cluster as a zone failure and splits it."""
    aws.run_instances(_config())
    aws.wait_instances("c1", "us-east-1a")
    # Model the transition: instances are mid-stop, one Describe later
    # they are stopped (the fake's auto-progression hook).
    for inst in fake.instances.values():
        inst["state"] = "stopping"
        inst["_stopping_gets"] = 1
    record = aws.run_instances(_config())
    assert record.resumed
    start = next(p for a, p in fake.calls if a == "StartInstances")
    assert len([k for k in start if k.startswith("InstanceId.")]) == 2


def test_open_ports_requires_zone(fake):
    aws.run_instances(_config())
    with pytest.raises(ValueError):
        aws.open_ports("c1", [8080])


def test_capacity_error_maps_to_failover_taxonomy(fake):
    fake.capacity_errors = 1
    with pytest.raises(exceptions.CapacityError):
        aws.run_instances(_config())
    fake.quota_error = True
    with pytest.raises(exceptions.QuotaExceededError):
        aws.run_instances(_config(cluster_name="c2"))


def test_cluster_info_and_runners(fake):
    aws.run_instances(_config())
    aws.wait_instances("c1", "us-east-1a")
    info = aws.get_cluster_info("c1", "us-east-1a")
    assert len(info.hosts) == 2
    assert [h.host_id for h in info.hosts] == [0, 1]
    # Stable rank order = launch index.
    assert [h.worker_id for h in info.hosts] == [0, 0]
    assert info.hosts[0].ssh_user == "ubuntu"
    assert info.hosts[0].external_ip.startswith("54.")
    runners = aws.get_command_runners(info)
    assert len(runners) == 2


def test_terminate_removes_instances_and_sg(fake):
    aws.run_instances(_config())
    aws.terminate_instances("c1", "us-east-1a")
    assert not fake.instances
    assert aws._sg_name("c1") not in fake.sgs
    assert aws.query_instances("c1", "us-east-1a") == "NOT_FOUND"


def test_provision_dispatcher_routes_aws(fake):
    from skypilot_tpu import provision
    assert provision.supports("aws", provision.Feature.STOP)
    record = provision.run_instances("aws", _config())
    assert record.provider == "aws"
    assert provision.query_instances("aws", "c1", "us-east-1a") == "UP"


def test_region_of_zone():
    assert aws._region_of_zone("us-east-1a") == "us-east-1"
    assert aws._region_of_zone("ap-northeast-1b") == "ap-northeast-1"
    assert aws._region_of_zone("eu-west-1") == "eu-west-1"
    # Local/Wavelength zones carry dashed suffixes beyond the letter.
    assert aws._region_of_zone("us-west-2-lax-1a") == "us-west-2"
    with pytest.raises(ValueError):
        aws._region_of_zone("bogus")


def test_open_ports_without_sg_fails_loudly(fake):
    """A missing SG means wrong zone or dead cluster: creating a fresh
    unattached SG would 'succeed' while the real ports stay closed."""
    from skypilot_tpu import exceptions as exc
    with pytest.raises(exc.ClusterNotUpError):
        aws.open_ports("ghost", [8080], "us-east-1a")
