"""Test harness: force an 8-device virtual CPU mesh.

Mirrors the reference's offline-test strategy (reference:
tests/common_test_fixtures.py — everything cloud is mocked, the logic runs
for real). Here additionally the *device* layer is virtualized: 8 CPU
devices stand in for a TPU slice so sharding/gang logic is exercised
without hardware.

Must run before any JAX backend initialization: the axon TPU plugin
registers itself at interpreter start (sitecustomize), so we re-point the
platform at import time, before any test touches jax.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from skypilot_tpu.parallel import mesh as mesh_lib
    return mesh_lib.make_mesh(mesh_lib.MeshShape(dp=2, fsdp=2, tp=2))


@pytest.fixture()
def tiny_cfg():
    from skypilot_tpu.models import llama
    return llama.CONFIGS["llama3-tiny"]
