"""Test harness: force an 8-device virtual CPU mesh.

Mirrors the reference's offline-test strategy (reference:
tests/common_test_fixtures.py — everything cloud is mocked, the logic runs
for real). Here additionally the *device* layer is virtualized: 8 CPU
devices stand in for a TPU slice so sharding/gang logic is exercised
without hardware.

Must run before any JAX backend initialization: the axon TPU plugin
registers itself at interpreter start (sitecustomize), so we re-point the
platform at import time, before any test touches jax.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

# Persistent XLA compilation cache, shared across test processes, the
# subprocess servers/controllers the e2e tests spawn (they inherit the
# env), and successive runs: the suite's wall time is dominated by
# recompiling identical tiny CPU programs. Env vars, not config calls,
# so children get it too.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "skypilot_tpu_tests"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--run-slow", action="store_true", default=False,
        help="include tests marked slow (the full profile; also enabled "
             "by SKYTPU_TESTS_FULL=1)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: compile-heavy / e2e test, excluded from the default fast "
        "profile (run with --run-slow or SKYTPU_TESTS_FULL=1)")


# The fast-profile contract, maintained centrally from measured
# durations (pytest --durations): every test here took >= ~6.5s on the
# suite box. A stale entry (renamed test) just runs in both profiles.
_SLOW_TESTS = {
    "tests/test_advice_r3.py::test_moe_zigzag_matches_contiguous",
    "tests/test_advice_r3.py::test_moe_zigzag_nondivisible_falls_back",
    "tests/test_api_server.py::test_launch_via_server",
    "tests/test_api_server.py::test_request_log_streaming",
    "tests/test_checkpoints.py::test_resume_continues_identically",
    "tests/test_checkpoints.py::test_roundtrip_sharded",
    "tests/test_e2e_local.py::test_failover_retry_until_up",
    "tests/test_e2e_local.py::test_gang_fail_one_kills_all",
    "tests/test_e2e_local.py::test_stop_start_down",
    "tests/test_flash_attention.py::test_backward_matches_oracle",
    "tests/test_flash_attention.py::test_segment_backward_matches_oracle",
    "tests/test_infer.py::test_continuous_batching_isolation",
    "tests/test_infer.py::test_engine_with_tp_sharded_params",
    "tests/test_infer.py::test_incremental_decode_matches_full_forward",
    "tests/test_infer.py::test_mixed_bucket_admission",
    "tests/test_infer.py::test_max_wave_splits_admission",
    "tests/test_infer.py::test_moe_engine_serves",
    "tests/test_infer.py::test_sampling_temperature_valid",
    "tests/test_infer.py::test_weights_int8_composes_with_kv_int8",
    "tests/test_infer.py::test_weights_int8_engine_generates_sensibly",
    "tests/test_kubernetes_provision.py::test_query_and_wait",
    "tests/test_kubernetes_provision.py::test_run_instances_applies_all_pods",
    "tests/test_llama.py::test_chunked_xent_matches_full",
    "tests/test_llama.py::test_overfit_tiny_batch",
    "tests/test_lora.py::test_adapters_learn_base_frozen",
    "tests/test_lora.py::test_sharded_lora_step",
    "tests/test_managed_jobs.py::test_controller_log_streams_to_client",
    "tests/test_managed_jobs.py::test_jobs_survive_client_death",
    "tests/test_managed_jobs.py::test_launching_parallelism_gate",
    "tests/test_managed_jobs.py::test_managed_job_cancel",
    "tests/test_managed_jobs.py::test_managed_job_recovers_from_preemption",
    "tests/test_managed_jobs.py::test_managed_job_succeeds",
    "tests/test_managed_jobs.py::test_managed_job_user_failure_no_recovery",
    "tests/test_managed_jobs.py::test_queue_lists_jobs",
    "tests/test_managed_jobs.py::test_unknown_strategy_rejected",
    "tests/test_managed_jobs.py::test_pipeline_runs_tasks_sequentially",
    "tests/test_managed_jobs.py::test_pipeline_failure_stops_chain",
    "tests/test_managed_jobs.py::test_pipeline_cancel_mid_run_stops_chain",
    "tests/test_infer_tp.py::test_server_main_tp_end_to_end",
    "tests/test_infer_tp.py::test_tp_engine_matches_single_device",
    "tests/test_infer_tp.py::test_sharded_init_materializes_on_mesh",
    "tests/test_infer_tp.py::test_tp_engine_matches_w8a8_and_kv_int8",
    "tests/test_moe.py::test_loss_decreases",
    "tests/test_moe.py::test_train_step_on_ep_mesh",
    "tests/test_observability.py::test_benchmark_launch_local",
    "tests/test_pipeline.py::test_pipelined_matches_sequential",
    "tests/test_pipeline.py::test_train_step_on_pp_mesh",
    "tests/test_recipes.py::test_evaluate_cli_smoke",
    "tests/test_recipes.py::test_train_run_cli_smoke",
    "tests/test_recipes.py::test_train_run_qlora_cli_smoke",
    "tests/test_ring_attention.py::test_packed_model_with_sp",
    "tests/test_ring_attention.py::test_ring_gqa_gradients",
    "tests/test_ring_attention.py::test_ring_gradients_match",
    "tests/test_ring_attention.py::test_ring_segments_gradients",
    "tests/test_ring_attention.py::test_train_step_with_sp",
    "tests/test_ring_attention.py::test_zigzag_gradients_match",
    "tests/test_runtime_fixes.py::test_cost_report_whole_cluster_price",
    "tests/test_serve.py::test_autoscaler_scales_up_under_load",
    "tests/test_serve.py::test_lb_503_when_no_replicas",
    "tests/test_serve.py::test_replica_failure_recovery",
    "tests/test_serve.py::test_rolling_update_zero_downtime",
    "tests/test_serve.py::test_serve_survives_client_death",
    "tests/test_serve.py::test_serve_up_ready_balance_down",
    "tests/test_serve.py::test_streaming_through_lb",
    "tests/test_serve.py::test_tls_termination",
    "tests/test_spot_mix.py::test_spot_preemption_backfills_ondemand",
    "tests/test_qlora.py::test_zero_adapters_match_fp_model",
    "tests/test_qlora.py::test_qlora_adapters_learn",
    "tests/test_qlora.py::test_qlora_grads_only_adapters",
    "tests/test_qlora.py::test_random_quantized_params_device_side",
    "tests/test_sharding.py::test_multislice_mesh_virtual_slices",
    "tests/test_sharding.py::test_sharded_matches_unsharded",
    "tests/test_sharding.py::test_sharded_train_step_runs",
    "tests/test_vit.py::test_memorizes_fixed_batch",
    "tests/test_vit.py::test_sharded_train_step",
    # Second tier (warm-cache durations >= ~4s on the 1-core suite box).
    "tests/test_checkpoints.py::test_max_to_keep",
    "tests/test_multislice_env.py::test_jax_distributed_initializes_from_injected_env",
    "tests/test_lora.py::test_identity_at_init",
    "tests/test_ring_attention.py::test_model_zigzag_matches_contiguous",
    "tests/test_ring_attention.py::test_model_zigzag_nondivisible_falls_back",
    "tests/test_ring_attention.py::test_ring_matches_xla_forward",
    "tests/test_ring_attention.py::test_ring_sp4",
    "tests/test_ring_attention.py::test_ring_nondivisible_dims_replicate",
    "tests/test_ring_attention.py::test_ring_gqa_tp_divides_q_not_kv",
    "tests/test_ring_attention.py::test_model_forward_with_sp",
    "tests/test_pipeline.py::test_pp_sharded_loss_matches_unsharded",
    "tests/test_pipeline.py::test_param_axes_match_shapes",
    "tests/test_pipeline.py::test_1f1b_grads_match_gpipe",
    "tests/test_pipeline.py::test_1f1b_memory_flat_in_microbatches",
    "tests/test_pipeline.py::test_1f1b_on_pp_mesh",
    "tests/test_vit.py::test_forward_shapes",
    "tests/test_infer.py::test_kv_int8_engine_matches_fp_closely",
    "tests/test_infer.py::test_eos_stops_decode",
    "tests/test_infer.py::test_oversized_prompt_rejected_at_submit",
    "tests/test_e2e_local.py::test_multihost_rank_assignment",
    "tests/test_remote_cluster.py::test_multihost_gang_over_fake_ssh",
    "tests/test_remote_cluster.py::test_gang_fail_one_kills_all_over_fake_ssh",
    "tests/test_remote_cluster.py::test_job_survives_client_death",
    "tests/test_remote_cluster.py::test_remote_hosts_import_rsynced_framework",
    "tests/test_moe.py::test_ep_sharded_matches_unsharded",
    "tests/test_recipes.py::test_collectives_bench_smoke",
    "tests/test_runtime_fixes.py::test_jobs_run_fifo_one_at_a_time",
    "tests/test_llama.py::test_causality",
    # Third tier (>= ~3s): the 2-minute fast profile on a 1-core box
    # leaves ~1 smoke test per subsystem fast; everything compile- or
    # subprocess-heavy runs in the full profile.
    "tests/test_checkpoints.py::test_restore_missing_raises",
    "tests/test_vit.py::test_param_count_matches",
    "tests/test_ring_attention.py::test_ring_gqa_unrepeated_kv",
    "tests/test_ring_attention.py::test_ring_segments_gqa_sp4",
    "tests/test_ring_attention.py::test_model_odd_seq_falls_back_to_local",
    "tests/test_remote_cluster.py::test_fresh_client_sees_queue_and_can_exec",
    "tests/test_remote_cluster.py::test_autodown_fires_from_cluster_side",
    "tests/test_remote_cluster.py::test_autostop_fires_from_cluster_side",
    "tests/test_remote_cluster.py::test_tail_logs_bounded_despite_lingering_child",
    "tests/test_e2e_local.py::test_exec_on_existing_cluster_and_queue",
    "tests/test_e2e_local.py::test_launch_end_to_end",
    "tests/test_e2e_local.py::test_env_contract_injected",
    "tests/test_e2e_local.py::test_refresh_detects_external_teardown",
    "tests/test_e2e_local.py::test_setup_and_envs",
    "tests/test_runtime_fixes.py::test_autodown_daemon_removes_cluster",
    "tests/test_runtime_fixes.py::test_tail_logs_unknown_job_raises",
    "tests/test_runtime_fixes.py::test_autostop_daemon_stops_idle_cluster",
    "tests/test_cli.py::test_launch_local_roundtrip",
    "tests/test_cli.py::test_launch_from_yaml",
    "tests/test_infer.py::test_slots_recycled",
    "tests/test_flight.py::test_flight_smoke_bench_wiring",
    "tests/test_flight.py::test_warm_programs_then_zero_unexpected",
    "tests/test_flight.py::test_chunk_verify_interleave_consistency",
    "tests/test_infer_server.py::test_generate_greedy_matches_engine",
    "tests/test_api_server.py::test_failed_request_propagates_error",
    "tests/test_api_server.py::test_api_status_lists_requests",
    "tests/test_moe.py::test_full_capacity_routes_all_tokens",
    "tests/test_cli.py::test_check",
}


def pytest_collection_modifyitems(config, items):
    run_slow = (config.getoption("--run-slow")
                or bool(os.environ.get("SKYTPU_TESTS_FULL")))
    skip = pytest.mark.skip(
        reason="slow (fast profile); use --run-slow or SKYTPU_TESTS_FULL=1")
    for item in items:
        base = item.nodeid.split("[")[0]
        if base in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
        if not run_slow and "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def mesh8():
    from skypilot_tpu.parallel import mesh as mesh_lib
    return mesh_lib.make_mesh(mesh_lib.MeshShape(dp=2, fsdp=2, tp=2))


@pytest.fixture()
def tiny_cfg():
    from skypilot_tpu.models import llama
    return llama.CONFIGS["llama3-tiny"]


def ttft_fams(fast, slow):
    """Cumulative TTFT histogram family: ``fast`` samples <= 0.1 s,
    ``slow`` in (0.1, 5] — the synthetic feed the burn-rate
    autoscaler/SLO tests observe (shared by test_qos/test_chaos)."""
    cum, samples = 0, []
    for le, n in (("0.1", fast), ("5", slow), ("+Inf", 0)):
        cum += n
        samples.append(({"__name__": "skytpu_ttft_seconds_bucket",
                         "le": le}, float(cum)))
    samples.append(({"__name__": "skytpu_ttft_seconds_count"},
                    float(cum)))
    return {"skytpu_ttft_seconds": {"type": "histogram",
                                    "samples": samples}}
