"""The on-cluster runtime: clusters are autonomous (client-death-safe).

Round-2 headline (VERDICT r1 #1): job queue, gang driver, and skylet run
on the cluster head, reached through the typed RPC. These tests emulate
a remote cluster with FakeSSHRunner (scrubbed env, $HOME-rooted hosts,
framework rsynced — the exact code path a real SSH cluster takes) and
assert the reference's load-bearing property (sky/skylet/): a launched
cluster survives its client, is shared between clients, and autostops
by itself.
"""

import shutil
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu.backend import TpuVmBackend
from skypilot_tpu.provision import local as local_provider
from skypilot_tpu.resources import Resources
from skypilot_tpu.runtime.job_queue import JobStatus
from skypilot_tpu.runtime.rpc_client import ClusterRpc
from skypilot_tpu.task import Task


@pytest.fixture()
def remote_world(tmp_path, monkeypatch):
    # The fake "cloud" lives OUTSIDE any client's home: deleting a
    # client's home must not touch cluster-side state.
    monkeypatch.setenv("SKYTPU_LOCAL_CLUSTERS_ROOT", str(tmp_path / "cloud"))
    monkeypatch.setenv("SKYTPU_LOCAL_FAKE_SSH", "1")
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "client1"))
    monkeypatch.setenv("SKYTPU_SKYLET_POLL", "0.2")
    return tmp_path


def _task(run, name="t", num_nodes=1):
    t = Task(name=name, run=run, num_nodes=num_nodes)
    t.set_resources(Resources(cloud="local"))
    return t


def _kill_client(tmp_path, monkeypatch):
    """Client 1 dies: its entire home (state DB, caches) is erased."""
    shutil.rmtree(tmp_path / "client1", ignore_errors=True)
    monkeypatch.delenv("SKYPILOT_TPU_HOME")


def _fresh_client_rpc(tmp_path, monkeypatch, cluster_name):
    """A brand-new client sharing nothing with client 1 except the
    ability to reach the cluster head."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "client2"))
    from skypilot_tpu import provision
    info = local_provider.get_cluster_info(cluster_name, "local")
    return ClusterRpc(provision.get_command_runners(info)[0], cluster_name)


def test_job_survives_client_death(remote_world, monkeypatch):
    job_id, _ = sky.launch(
        _task("sleep 2; echo finished-$SKYTPU_NODE_RANK"),
        cluster_name="rc1")
    _kill_client(remote_world, monkeypatch)

    rpc = _fresh_client_rpc(remote_world, monkeypatch, "rc1")
    deadline = time.time() + 30
    while True:
        job = rpc.get_job(job_id)
        if job["status"].is_terminal():
            break
        assert time.time() < deadline, f"stuck at {job['status']}"
        time.sleep(0.3)
    assert job["status"] == JobStatus.SUCCEEDED
    _, chunks, _ = rpc.read_logs(job_id, {})
    assert "finished-0" in "".join(chunks.values())


def test_fresh_client_sees_queue_and_can_exec(remote_world, monkeypatch):
    job_id, _ = sky.launch(_task("echo one", name="first"),
                           cluster_name="rc2")
    rpc0 = _fresh_client_rpc(remote_world, monkeypatch, "rc2")
    _wait_rpc(rpc0, job_id)
    _kill_client(remote_world, monkeypatch)

    rpc = _fresh_client_rpc(remote_world, monkeypatch, "rc2")
    jobs = rpc.list_jobs()
    assert [j["name"] for j in jobs] == ["first"]
    # A second client can submit to the shared queue directly.
    job2 = rpc.submit("second", "echo two", num_nodes=1)
    _wait_rpc(rpc, job2)
    assert [j["name"] for j in rpc.list_jobs()] == ["second", "first"]


def test_autostop_fires_from_cluster_side(remote_world, monkeypatch):
    job_id, handle = sky.launch(_task("echo done"), cluster_name="rc3",
                                idle_minutes_to_autostop=0)
    TpuVmBackend().wait_job(handle, job_id, 30)
    _kill_client(remote_world, monkeypatch)

    deadline = time.time() + 30
    while local_provider.query_instances("rc3", "local") != "STOPPED":
        assert time.time() < deadline, "cluster-side autostop never fired"
        time.sleep(0.3)


def test_autodown_fires_from_cluster_side(remote_world, monkeypatch):
    job_id, handle = sky.launch(_task("echo done"), cluster_name="rc4")
    TpuVmBackend().wait_job(handle, job_id, 30)
    sky.autostop("rc4", 0, down_=True)
    _kill_client(remote_world, monkeypatch)

    deadline = time.time() + 30
    while local_provider.query_instances("rc4", "local") != "NOT_FOUND":
        assert time.time() < deadline, "cluster-side autodown never fired"
        time.sleep(0.3)


def test_remote_hosts_import_rsynced_framework(remote_world):
    """The fake hosts scrub the client's PYTHONPATH: this import can only
    resolve through the rsynced package + the driver's PYTHONPATH wiring
    (reference: the wheel shipped by sky/backends/wheel_utils.py:140)."""
    job_id, handle = sky.launch(
        _task("python3 -S -c 'import skypilot_tpu; "
              "print(\"imported-ok\", skypilot_tpu.__version__)'"),
        cluster_name="rc5")
    assert TpuVmBackend().wait_job(handle, job_id, 30) == JobStatus.SUCCEEDED
    logs = TpuVmBackend().job_log_paths(handle, job_id)
    assert "imported-ok" in "".join(open(p).read() for p in logs)


def test_multihost_gang_over_fake_ssh(remote_world):
    """Rank contract + head-side log mirroring across 'remote' hosts."""
    job_id, handle = sky.launch(
        _task('echo "h=$SKYTPU_HOST_ID/$SKYTPU_NUM_HOSTS '
              'coord=$JAX_COORDINATOR_ADDRESS"', num_nodes=2),
        cluster_name="rc6")
    assert TpuVmBackend().wait_job(handle, job_id, 30) == JobStatus.SUCCEEDED
    logs = TpuVmBackend().job_log_paths(handle, job_id)
    assert len(logs) == 2
    combined = "".join(open(p).read() for p in logs)
    assert "h=0/2" in combined and "h=1/2" in combined
    assert "coord=127.0.0.1:8476" in combined


def test_gang_fail_one_kills_all_over_fake_ssh(remote_world):
    t = _task('if [ "$SKYTPU_HOST_ID" = "0" ]; then exit 3; '
              'else sleep 30; fi', num_nodes=2)
    start_t = time.time()
    job_id, handle = sky.launch(t, cluster_name="rc7")
    assert TpuVmBackend().wait_job(handle, job_id, 25) == JobStatus.FAILED
    assert time.time() - start_t < 20


def test_tail_logs_bounded_despite_lingering_child(remote_world):
    """VERDICT r1 weak #6: a background child that keeps appending to the
    rank log must not wedge tail_logs(follow=True) after the job ends."""
    run = ("( for i in $(seq 1 100); do echo spam; sleep 0.1; done ) & "
           "echo main-done")
    job_id, handle = sky.launch(_task(run), cluster_name="rc8")
    backend = TpuVmBackend()
    backend.wait_job(handle, job_id, 30)
    import io
    buf = io.StringIO()
    start_t = time.time()
    backend.tail_logs(handle, job_id, follow=True, out=buf)
    assert time.time() - start_t < 10
    assert "main-done" in buf.getvalue()


def _wait_rpc(rpc, job_id, timeout=30):
    deadline = time.time() + timeout
    while True:
        job = rpc.get_job(job_id)
        if job and job["status"].is_terminal():
            return job["status"]
        assert time.time() < deadline
        time.sleep(0.3)
