"""Distributed tracing: context propagation across the request
lifecycle (CLI/SDK -> API server -> worker -> rpc), the structured
event log, and the `skytpu trace` assembly.

The e2e test runs a real API server (thread) + real worker subprocess
and asserts the assembled tree spans at least two distinct processes —
the acceptance bar for per-request debugging at production scale.
"""

import json
import os
import socket
import sqlite3
import threading
import time

import pytest

from skypilot_tpu.observability import tracing, trace_view


@pytest.fixture(autouse=True)
def _fresh_tracing(tmp_path, monkeypatch):
    """Isolate every test: its own home/events dir and a clean buffer
    (the module-global ring + log-file name would otherwise leak state
    across tests in this process)."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    monkeypatch.delenv("SKYTPU_EVENTS_DIR", raising=False)
    monkeypatch.delenv(tracing.ENV_VAR, raising=False)
    tracing._reset_for_tests()
    yield
    tracing._reset_for_tests()


# -- traceparent wire format -------------------------------------------------

def test_traceparent_round_trip():
    ctx = tracing.SpanContext(tracing.new_trace_id(),
                              tracing.new_span_id())
    assert tracing.parse_traceparent(tracing.format_traceparent(ctx)) \
        == ctx


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-zz-xx-01",
    "00-" + "a" * 31 + "-" + "b" * 16 + "-01",     # short trace id
    "00-" + "a" * 32 + "-" + "b" * 15 + "-01",     # short span id
    "00-" + "0" * 32 + "-" + "b" * 16 + "-01",     # all-zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",     # all-zero span id
    "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",     # unknown version
])
def test_malformed_traceparent_rejected(bad):
    assert tracing.parse_traceparent(bad) is None


def test_malformed_header_falls_back_to_fresh_trace(monkeypatch):
    monkeypatch.setenv(tracing.ENV_VAR, "not-a-traceparent")
    with tracing.start_span("s") as sp:
        pass
    rec = tracing.buffered_records()[-1]
    assert rec["parent"] is None            # fresh root, not a crash
    assert rec["trace"] == sp.ctx.trace_id


# -- context stack + env root ------------------------------------------------

def test_nested_spans_parent_child():
    with tracing.start_span("outer") as outer:
        with tracing.start_span("inner") as inner:
            assert tracing.current() == inner.ctx
        assert tracing.current() == outer.ctx
    assert tracing.current() is None
    by_name = {r["name"]: r for r in tracing.buffered_records()}
    assert by_name["inner"]["parent"] == outer.ctx.span_id
    assert by_name["inner"]["trace"] == outer.ctx.trace_id
    assert by_name["outer"]["parent"] is None


def test_env_root_parents_spans(monkeypatch):
    root = tracing.SpanContext(tracing.new_trace_id(),
                               tracing.new_span_id())
    monkeypatch.setenv(tracing.ENV_VAR, tracing.format_traceparent(root))
    with tracing.start_span("child"):
        pass
    rec = tracing.buffered_records()[-1]
    assert rec["trace"] == root.trace_id
    assert rec["parent"] == root.span_id


def test_span_records_exception_status():
    with pytest.raises(ValueError):
        with tracing.start_span("boom"):
            raise ValueError("nope")
    rec = tracing.buffered_records()[-1]
    assert rec["status"] == "error"
    assert rec["error_type"] == "ValueError"


def test_add_event_detached_never_uses_ambient(monkeypatch):
    """ctx=DETACHED records unattributed even with an env root present
    (pre-upgrade autostop.json path: unattributed beats misattributed)."""
    root = tracing.SpanContext(tracing.new_trace_id(),
                               tracing.new_span_id())
    monkeypatch.setenv(tracing.ENV_VAR, tracing.format_traceparent(root))
    tracing.add_event("skylet.autostop_fired", ctx=tracing.DETACHED)
    rec = tracing.buffered_records()[-1]
    assert "trace" not in rec and "parent" not in rec


def test_ring_buffer_bounded():
    for i in range(tracing._MAX_RECORDS + 100):
        tracing.add_event("e", attrs={"i": i})
    assert len(tracing.buffered_records()) <= tracing._MAX_RECORDS


def test_suppress_discards_spans():
    from skypilot_tpu.observability import metrics
    with metrics.suppress():
        with tracing.start_span("warmup"):
            pass
        tracing.add_event("warmup_event")
    assert tracing.buffered_records() == []


# -- event log flush + assembly ---------------------------------------------

def test_flush_and_load_trace_round_trip():
    with tracing.start_span("root") as root:
        with tracing.start_span("child"):
            tracing.add_event("lifecycle", attrs={"k": "v"})
    tracing.flush()
    files = os.listdir(tracing.events_dir())
    assert len(files) == 1 and files[0].endswith(".jsonl")
    records = trace_view.load_trace(root.ctx.trace_id)
    assert {r["name"] for r in records} == {"root", "child", "lifecycle"}
    out = trace_view.render(records, root.ctx.trace_id)
    assert "root" in out and "child" in out and "lifecycle" in out
    # child indents under root; the event attaches under child
    assert out.index("root") < out.index("child") < out.index("lifecycle")


def test_corrupt_log_lines_skipped():
    with tracing.start_span("ok") as sp:
        pass
    tracing.flush()
    with open(os.path.join(tracing.events_dir(), "junk.jsonl"),
              "w") as f:
        f.write("{not json\n\n")
        f.write(json.dumps({"kind": "span", "name": "other-trace",
                            "trace": "f" * 32, "span": "1" * 16,
                            "parent": None, "start_s": 0, "end_s": 1,
                            "pid": 1, "proc": "x"}) + "\n")
    records = trace_view.load_trace(sp.ctx.trace_id)
    assert [r["name"] for r in records] == ["ok"]


def test_orphan_span_roots_subtree():
    """A span whose parent never flushed must not vanish."""
    ctx = tracing.SpanContext(tracing.new_trace_id(), "a" * 16)
    tracing.record_span("orphan", 1.0, 2.0, ctx=ctx,
                        parent_id="dead0000dead0000")
    roots = trace_view.build_tree(tracing.buffered_records())
    assert [n["rec"]["name"] for n in roots] == ["orphan"]


def test_perfetto_export_loadable():
    with tracing.start_span("s"):
        tracing.add_event("e")
    doc = trace_view.to_perfetto(tracing.buffered_records())
    json.loads(json.dumps(doc))                      # serializable
    phases = [e["ph"] for e in doc["traceEvents"]]
    assert "X" in phases and "i" in phases and "M" in phases


def test_gc_event_logs_deletes_only_old_and_beyond_cap():
    """A file dies only when it is BOTH beyond the newest-N cap AND
    older than the TTL: a request burst must never GC minutes-old logs
    whose requests the requests DB still serves."""
    d = tracing.events_dir()
    os.makedirs(d, exist_ok=True)
    now = time.time()
    ages = {"old-0": 9000, "old-1": 8000, "old-2": 7000,   # stale
            "new-0": 30, "new-1": 20, "new-2": 10}         # fresh
    for name, age in ages.items():
        path = os.path.join(d, f"{name}.jsonl")
        with open(path, "w") as f:
            f.write("{}\n")
        os.utime(path, (now - age, now - age))
    # orphaned mkstemp temp (SIGKILL mid-flush): stale -> pruned too
    stale_tmp = os.path.join(d, "dead-1.jsonl.a1b2c3")
    with open(stale_tmp, "w") as f:
        f.write("{")
    os.utime(stale_tmp, (now - 9999, now - 9999))
    removed = tracing.gc_event_logs(max_files=2, max_age_s=3600)
    # the 3 stale files are beyond the newest-2 cap AND old -> gone;
    # new-2 is beyond the cap but fresh -> kept; stale temp -> gone
    assert removed == 4
    assert sorted(os.listdir(d)) == ["new-0.jsonl", "new-1.jsonl",
                                     "new-2.jsonl"]


# -- requests_db schema v3 ---------------------------------------------------

def test_requests_db_v3_trace_and_index():
    from skypilot_tpu.server import requests_db
    trace = {"tp": "00-" + "a" * 32 + "-" + "b" * 16 + "-01",
             "parent": None}
    rid = requests_db.create("status", {}, trace=trace)
    rec = requests_db.get(rid)
    assert rec["trace"] == trace
    from skypilot_tpu.utils import paths
    conn = sqlite3.connect(paths.requests_db())
    try:
        assert conn.execute("PRAGMA user_version").fetchone()[0] == 3
        idx = [r[1] for r in conn.execute(
            "PRAGMA index_list(requests)").fetchall()]
        assert "idx_requests_status" in idx
    finally:
        conn.close()


def test_requests_db_migrates_v2_to_v3():
    """A v2 DB (pre-trace) opened by this client gains the column and
    the status index without losing rows."""
    from skypilot_tpu.utils import paths
    path = paths.requests_db()
    conn = sqlite3.connect(path)
    conn.execute(
        "CREATE TABLE requests (request_id TEXT PRIMARY KEY, name TEXT,"
        " status TEXT, payload TEXT, result TEXT, error TEXT,"
        " pid INTEGER, created_at REAL, finished_at REAL, user TEXT)")
    conn.execute(
        "INSERT INTO requests (request_id, name, status, payload,"
        " created_at) VALUES ('old1', 'status', 'SUCCEEDED', '{}', 1.0)")
    conn.execute("PRAGMA user_version=2")
    conn.commit()
    conn.close()
    from skypilot_tpu.server import requests_db
    rec = requests_db.get("old1")
    assert rec["name"] == "status" and rec["trace"] is None
    conn = sqlite3.connect(path)
    try:
        assert conn.execute("PRAGMA user_version").fetchone()[0] == 3
        idx = [r[1] for r in conn.execute(
            "PRAGMA index_list(requests)").fetchall()]
        assert "idx_requests_status" in idx
    finally:
        conn.close()


# -- RPC carry + transport knobs --------------------------------------------

class _CaptureRunner:
    """Command-runner double capturing the RPC wire payload."""

    def __init__(self, rc=0, marker_resp=None):
        self.rc = rc
        self.calls = []
        from skypilot_tpu.runtime.rpc import MARKER
        resp = marker_resp or {"ok": True, "result": {"pong": True}}
        self.out = MARKER + json.dumps(resp)

    def framework_invocation(self, module):
        return f"python -m {module}"

    def run(self, cmd, env=None, cwd=None, timeout=None, log_path=None,
            stdin=None):
        self.calls.append({"cmd": cmd, "stdin": stdin,
                           "timeout": timeout})
        return self.rc, self.out, ""


def test_rpc_call_carries_trace_and_timeout():
    from skypilot_tpu.runtime import rpc_client
    runner = _CaptureRunner()
    rpc = rpc_client.ClusterRpc(runner, "c1")
    with tracing.start_span("caller") as caller:
        rpc.call("ping", timeout=7.5)
    sent = json.loads(runner.calls[0]["stdin"])
    assert runner.calls[0]["timeout"] == 7.5
    carried = tracing.parse_traceparent(sent["trace"])
    assert carried.trace_id == caller.ctx.trace_id
    # the carried span is the rpc.ping span, a CHILD of the caller span
    by_name = {r["name"]: r for r in tracing.buffered_records()}
    assert by_name["rpc.ping"]["span"] == carried.span_id
    assert by_name["rpc.ping"]["parent"] == caller.ctx.span_id


def test_rpc_default_timeout_and_metrics():
    from skypilot_tpu.observability import metrics
    from skypilot_tpu.runtime import rpc_client
    runner = _CaptureRunner()
    rpc_client.ClusterRpc(runner, "c1").call("ping")
    assert runner.calls[0]["timeout"] == \
        rpc_client.DEFAULT_TIMEOUT_SECONDS
    fam = metrics.REGISTRY.get("skytpu_rpc_seconds")
    counts = {vals: child.hist_state()[0]
              for vals, child in fam.children()}
    assert sum(counts[("ping",)]) >= 1


def test_rpc_timeout_is_transport_failure():
    """A hung transport must surface as the typed RPC error AND count
    as kind=transport — not escape as a raw TimeoutExpired that skips
    the instrumentation."""
    import subprocess as sp
    from skypilot_tpu.observability import metrics
    from skypilot_tpu.runtime import rpc_client

    class _HungRunner(_CaptureRunner):
        def run(self, cmd, env=None, cwd=None, timeout=None,
                log_path=None, stdin=None):
            raise sp.TimeoutExpired(cmd, timeout)

    with pytest.raises(rpc_client.ClusterRpcError) as ei:
        rpc_client.ClusterRpc(_HungRunner(), "c1").call(
            "set_autostop", timeout=3)
    assert "timed out after 3" in str(ei.value)
    fam = metrics.REGISTRY.get("skytpu_rpc_failures_total")
    vals = {v: c.value for v, c in fam.children()}
    assert vals.get(("set_autostop", "transport"), 0) >= 1


def test_rpc_connection_error_is_transport_failure_and_retries():
    """An agent-down ConnectionRefusedError (OSError, not a timeout)
    must count as kind=transport, retry for idempotent methods, and
    surface as the typed RPC error."""
    from skypilot_tpu.observability import metrics
    from skypilot_tpu.runtime import rpc_client

    class _DownRunner(_CaptureRunner):
        def run(self, cmd, env=None, cwd=None, timeout=None,
                log_path=None, stdin=None):
            self.calls.append({})
            raise ConnectionRefusedError("agent down")

    runner = _DownRunner()
    before = 0
    fam = metrics.REGISTRY.get("skytpu_rpc_failures_total")
    if fam is not None:
        before = {v: c.value for v, c in fam.children()}.get(
            ("ping", "transport"), 0)
    with pytest.raises(rpc_client.ClusterRpcError) as ei:
        rpc_client.ClusterRpc(runner, "c1").call("ping")
    assert "ConnectionRefusedError" in str(ei.value)
    assert len(runner.calls) == rpc_client._TRANSPORT_RETRIES  # retried
    fam = metrics.REGISTRY.get("skytpu_rpc_failures_total")
    vals = {v: c.value for v, c in fam.children()}
    assert vals.get(("ping", "transport"), 0) >= \
        before + rpc_client._TRANSPORT_RETRIES


def test_set_autostop_persists_arming_trace(monkeypatch, tmp_path):
    """The skylet must attribute autostop outcomes to the request that
    ARMED autostop: set_autostop persists the caller's context, and
    add_event(ctx=...) attaches to it."""
    from skypilot_tpu.runtime import rpc as rpc_mod
    cdir = str(tmp_path / "cdir")
    os.makedirs(cdir, exist_ok=True)
    arm = tracing.SpanContext(tracing.new_trace_id(),
                              tracing.new_span_id())
    monkeypatch.setenv(tracing.ENV_VAR, tracing.format_traceparent(arm))
    monkeypatch.setattr(rpc_mod, "_ensure_skylet", lambda *a: None)
    rpc_mod._m_set_autostop("c1", cdir, {"idle_minutes": 5,
                                         "down": False})
    from skypilot_tpu.runtime import topology
    with open(os.path.join(cdir, topology.AUTOSTOP_CONFIG)) as f:
        cfg = json.load(f)
    ctx = tracing.parse_traceparent(cfg["trace"])
    assert ctx.trace_id == arm.trace_id
    monkeypatch.delenv(tracing.ENV_VAR)
    tracing.add_event("skylet.autostop_fired", attrs={"down": False},
                      ctx=ctx)
    rec = tracing.buffered_records()[-1]
    assert rec["trace"] == arm.trace_id and rec["parent"] == ctx.span_id


def test_rpc_failure_counted_by_kind():
    from skypilot_tpu.observability import metrics
    from skypilot_tpu.runtime import rpc_client
    runner = _CaptureRunner(
        marker_resp={"ok": False, "error": "x", "etype": "Nope"})
    with pytest.raises(rpc_client.ClusterRpcError):
        rpc_client.ClusterRpc(runner, "c1").call("ping")
    fam = metrics.REGISTRY.get("skytpu_rpc_failures_total")
    vals = {v: c.value for v, c in fam.children()}
    assert vals.get(("ping", "remote"), 0) >= 1
    # the rpc.ping span carries the error status
    rec = [r for r in tracing.buffered_records()
           if r["name"] == "rpc.ping"][-1]
    assert rec["status"] == "error"


def test_rpc_subprocess_installs_carried_context(tmp_path):
    """The head-side rpc process parents its dispatch span to the
    carried context and flushes it to ITS home's event log."""
    import subprocess
    import sys
    home = tmp_path / "headhome"
    parent = tracing.SpanContext(tracing.new_trace_id(),
                                 tracing.new_span_id())
    req = {"method": "ping", "params": {},
           "trace": tracing.format_traceparent(parent)}
    env = {**os.environ, "SKYPILOT_TPU_HOME": str(home)}
    env.pop(tracing.ENV_VAR, None)
    out = subprocess.run(
        [sys.executable, "-S", "-m", "skypilot_tpu.runtime.rpc",
         "--cluster", "tc"],
        input=json.dumps(req), capture_output=True, text=True, env=env,
        cwd="/root/repo", timeout=60)
    assert out.returncode == 0, out.stderr
    records = trace_view.load_trace(
        parent.trace_id, dirs=[str(home / "events")])
    disp = [r for r in records if r["name"] == "rpc.dispatch:ping"]
    assert disp and disp[0]["parent"] == parent.span_id
    assert disp[0]["proc"] == "rpc"


# -- engine span volume ------------------------------------------------------

def test_engine_records_one_decode_span_per_request():
    """Per-slot-per-burst decode spans would flood the ring at high
    occupancy; the engine records exactly one engine.decode per
    finished multi-token request (plus queue_wait/prefill/request)."""
    import jax
    from skypilot_tpu.infer import engine as eng
    from skypilot_tpu.models import llama
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    e = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                            prompt_buckets=(16, 64))
    caller = tracing.SpanContext(tracing.new_trace_id(),
                                 tracing.new_span_id())
    e.add_request([1, 2, 3], max_new_tokens=12, trace_ctx=caller)
    e.run_to_completion(max_burst=4)          # several bursts
    recs = [r for r in tracing.buffered_records()
            if r.get("trace") == caller.trace_id]
    names = [r["name"] for r in recs]
    assert names.count("engine.decode") == 1
    assert names.count("engine.request") == 1
    assert names.count("engine.prefill") == 1
    assert names.count("engine.queue_wait") == 1
    req = next(r for r in recs if r["name"] == "engine.request")
    assert req["parent"] == caller.span_id


# -- e2e: CLI/SDK -> API server -> worker -----------------------------------

@pytest.fixture()
def api_server(tmp_path, monkeypatch):
    from skypilot_tpu.server import server as server_mod
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    monkeypatch.setenv("SKYTPU_API_SERVER_URL",
                       f"http://127.0.0.1:{port}")
    executor = server_mod.Executor()
    executor.start()
    httpd = server_mod._Server(("127.0.0.1", port),
                               server_mod.make_handler())
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    executor.stop()
    httpd.shutdown()


def _wait_reaped(rid, timeout=60):
    """The request span is recorded (and flushed) when the executor
    reaps the worker — shortly after the DB flips to a terminal
    status."""
    from skypilot_tpu.observability import tracing as tr
    from skypilot_tpu.server import requests_db
    deadline = time.time() + timeout
    rec = requests_db.get(rid)
    trace_id = tr.parse_traceparent(rec["trace"]["tp"]).trace_id
    while time.time() < deadline:
        records = trace_view.load_trace(trace_id)
        if any(r["name"].startswith("api.request:") for r in records):
            return trace_id, records
        time.sleep(0.2)
    raise AssertionError(f"request span for {rid} never flushed")


def test_trace_e2e_spans_two_processes(api_server):
    """Acceptance: a request that traversed SDK -> API server -> worker
    assembles into ONE tree with >= 3 spans from >= 2 distinct
    processes, parent/child edges intact, and --perfetto loads."""
    from click.testing import CliRunner
    from skypilot_tpu.client import cli as cli_mod
    from skypilot_tpu.client import sdk

    rid = sdk.status()               # cheap worker: sky.status, no rpc
    sdk.get(rid, timeout=120)
    tracing.flush()                  # the client-side sdk.request span
    trace_id, records = _wait_reaped(rid)

    spans = [r for r in records if r["kind"] == "span"]
    assert len(spans) >= 3
    assert len({r["pid"] for r in spans}) >= 2
    by_name = {r["name"]: r for r in spans}
    api = by_name["api.request:status"]
    worker = by_name["worker.execute:status"]
    sdk_span = by_name["sdk.request:/status"]
    # one tree: sdk -> api request -> worker execution
    assert api["parent"] == sdk_span["span"]
    assert worker["parent"] == api["span"]
    assert worker["proc"] == "worker"
    assert api["pid"] != worker["pid"]

    runner = CliRunner()
    perfetto = os.path.join(os.path.dirname(tracing.events_dir()),
                            "trace.json")
    res = runner.invoke(cli_mod.cli,
                        ["trace", rid, "--perfetto", perfetto])
    assert res.exit_code == 0, res.output
    assert "api.request:status" in res.output
    assert "worker.execute:status" in res.output
    # the tree indents the worker under the request span
    api_line = next(line for line in res.output.splitlines()
                    if "api.request:status" in line)
    worker_line = next(line for line in res.output.splitlines()
                       if "worker.execute:status" in line)
    assert (len(worker_line) - len(worker_line.lstrip())
            > len(api_line) - len(api_line.lstrip()))
    with open(perfetto) as f:
        doc = json.load(f)
    assert len([e for e in doc["traceEvents"]
                if e["ph"] == "X"]) >= 3


def test_trace_cli_unknown_request(api_server):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli as cli_mod
    res = CliRunner().invoke(cli_mod.cli, ["trace", "nope"])
    assert res.exit_code != 0
    assert "no request" in res.output


def test_failed_request_trace_marks_error(api_server):
    from skypilot_tpu import exceptions
    from skypilot_tpu.client import sdk
    rid = sdk.queue("no-such-cluster")
    with pytest.raises(exceptions.SkyTpuError):
        sdk.get(rid, timeout=60)
    trace_id, records = _wait_reaped(rid)
    api = next(r for r in records
               if r["name"] == "api.request:queue")
    assert api["status"] == "error"
    worker_err = [r for r in records if r["name"] == "worker.error"]
    assert worker_err and worker_err[0]["attrs"]["error_type"]
