"""HTTP model server: health, generate, concurrency, bad input."""

import json
import socket
import threading
import urllib.error
import urllib.request

import jax
import pytest

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.infer import server as srv
from skypilot_tpu.models import llama


@pytest.fixture(scope="module")
def model_server():
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    engine = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                                 prompt_buckets=(16,))
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    model, httpd = srv.serve(engine, host="127.0.0.1", port=port)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    assert model._ready.wait(timeout=300)  # warmup compile done
    yield f"http://127.0.0.1:{port}", params, cfg
    model.shutdown()
    httpd.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_health(model_server):
    url, _, _ = model_server
    with urllib.request.urlopen(f"{url}/health", timeout=30) as r:
        assert json.loads(r.read())["status"] == "ok"


def test_generate_greedy_matches_engine(model_server):
    url, params, cfg = model_server
    prompt = [3, 17, 42]
    solo = eng.InferenceEngine(params, cfg, n_slots=1, max_len=64,
                               prompt_buckets=(16,))
    want = solo.generate([prompt], max_new_tokens=5)[0]
    code, out = _post(f"{url}/generate",
                      {"tokens": prompt, "max_new_tokens": 5})
    assert code == 200
    assert out["tokens"] == want
    assert out["ttft_ms"] is not None and out["total_ms"] > 0


def test_concurrent_generates(model_server):
    url, _, _ = model_server
    results = {}

    def one(i):
        code, out = _post(f"{url}/generate",
                          {"tokens": [i + 1, i + 2], "max_new_tokens": 4})
        results[i] = (code, len(out.get("tokens", [])))

    threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert all(results[i] == (200, 4) for i in range(4))


def test_bad_requests(model_server):
    url, _, _ = model_server
    code, out = _post(f"{url}/generate", {"max_new_tokens": 4})
    assert code == 400
    code, out = _post(f"{url}/generate",
                      {"tokens": list(range(99)), "max_new_tokens": 2})
    assert code == 400  # prompt exceeds the largest bucket


def test_prompt_too_long_typed_400(model_server):
    """A prompt past the largest bucket is a CLIENT error: HTTP 400
    with a typed error body (never a 500), on both the blocking and
    the streaming path."""
    url, _, _ = model_server
    for payload in ({"tokens": list(range(99)), "max_new_tokens": 2},
                    {"tokens": list(range(99)), "max_new_tokens": 2,
                     "stream": True}):
        code, out = _post(f"{url}/generate", payload)
        assert code == 400
        err = out["error"]
        assert err["type"] == "prompt_too_long"
        assert err["prompt_len"] == 99 and err["max_prompt_len"] == 16
        assert "message" in err


def test_response_carries_cache_stats(model_server):
    """The response trailer reports per-request prefix-cache stats
    (this server runs without a pool: miss, zero cached tokens)."""
    url, _, _ = model_server
    code, out = _post(f"{url}/generate",
                      {"tokens": [4, 8, 15], "max_new_tokens": 3})
    assert code == 200
    assert out["cache_hit"] is False
    assert out["cached_tokens"] == 0
    assert out["prefill_chunks"] == 0
    # Spec stats ride the same trailer (this engine runs spec-off:
    # both zero, but the fields are always present).
    assert out["spec_drafted"] == 0
    assert out["spec_accepted"] == 0


def test_spec_trailer_on_blocking_and_stream_paths():
    """A speculative engine's per-request drafted/accepted stats reach
    the response trailer on BOTH the blocking result and the stream
    ``done`` chunk, and the spec'd output matches a spec-off engine
    token-for-token through the serving loop."""
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    prompt = [7, 8, 9] * 4
    plain = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                                prompt_buckets=(16,))
    want = plain.generate([prompt], max_new_tokens=8)[0]

    class AlwaysDraft:
        """One fixed draft token per burst: spec_drafted is provably
        nonzero end to end without depending on the random model's
        n-gram structure (rejected drafts roll back; parity holds)."""

        def __init__(self, req):
            pass

        def catch_up(self, prompt, generated):
            pass

        def draft(self, k):
            return [0][:k]

    engine = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                                 prompt_buckets=(16,), spec_k=3,
                                 spec_drafter=AlwaysDraft)
    engine.spec_min_rate = 0.0
    model = srv.ModelServer(engine, max_burst=4, open_burst=2)
    try:
        assert model._ready.wait(timeout=300)
        out = model.submit(prompt, 8)
        assert "error" not in out
        assert out["tokens"] == want
        assert out["spec_drafted"] > 0
        assert 0 <= out["spec_accepted"] <= out["spec_drafted"]

        chunks = list(model.submit_stream(prompt, 8))
        done = chunks[-1]
        assert "done" in done
        streamed = [t for c in chunks for t in c.get("tokens", [])]
        assert streamed == want
        assert done["spec_drafted"] > 0
        assert 0 <= done["spec_accepted"] <= done["spec_drafted"]
    finally:
        model.shutdown()


def test_server_loop_drives_chunked_prefill():
    """End to end through the serving loop: a prompt longer than the
    chunk admits via the chunk queue (interleaved with decode), the
    trailer reports the hit on a repeat, and tokens are identical
    warm vs cold."""
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    engine = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                                 prompt_buckets=(32,),
                                 prefill_chunk=8, prefix_pool=2)
    model = srv.ModelServer(engine, max_burst=4, open_burst=2)
    try:
        assert model._ready.wait(timeout=300)
        prompt = list(range(1, 13))              # 12 tokens, 2 chunks
        cold = model.submit(prompt, 4)
        assert "error" not in cold
        assert cold["cache_hit"] is False
        assert cold["prefill_chunks"] == 2
        warm = model.submit(prompt, 4)
        assert warm["cache_hit"] is True
        assert warm["cached_tokens"] == 8        # chunk-aligned prefix
        assert warm["prefill_chunks"] == 1       # suffix only
        assert warm["tokens"] == cold["tokens"]
    finally:
        model.shutdown()


def _post_stream(url, payload, timeout=300):
    """POST with stream:true; returns [(arrival_time, chunk_dict)]."""
    import time
    req = urllib.request.Request(
        url, data=json.dumps(dict(payload, stream=True)).encode(),
        headers={"Content-Type": "application/json"})
    chunks = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.status == 200
        assert r.headers.get("Content-Type") == "application/x-ndjson"
        buf = b""
        while True:
            piece = r.read1(65536)
            if not piece:
                break
            buf += piece
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if line.strip():
                    chunks.append((time.time(), json.loads(line)))
    return chunks


def test_streaming_tokens_match_blocking(model_server):
    """Streamed chunks concatenate to exactly the blocking result, and
    the first token chunk lands BEFORE generation finishes (the whole
    point of streaming TTFT)."""
    url, _, _ = model_server
    prompt = [5, 9, 2]
    _, blocking = _post(f"{url}/generate",
                        {"tokens": prompt, "max_new_tokens": 24})
    chunks = _post_stream(f"{url}/generate",
                          {"tokens": prompt, "max_new_tokens": 24})
    assert "done" in chunks[-1][1]
    streamed = [t for _, c in chunks for t in c.get("tokens", [])]
    assert streamed == blocking["tokens"]
    assert chunks[-1][1]["ttft_ms"] is not None
    # Multiple emissions (burst=8 over 24 tokens -> >= 3 token chunks),
    # and the first arrives strictly before the done chunk.
    token_chunks = [c for _, c in chunks if "tokens" in c]
    assert len(token_chunks) >= 3
    first_t = next(t for t, c in chunks if "tokens" in c)
    done_t = chunks[-1][0]
    assert first_t < done_t


def test_streaming_oversized_prompt_clean_400(model_server):
    url, _, _ = model_server
    code, out = _post(f"{url}/generate",
                      {"tokens": list(range(99)), "max_new_tokens": 2,
                       "stream": True})
    assert code == 400 and "error" in out


class _FakeEngine:
    """Minimal engine double recording decode burst sizes."""

    def __init__(self, n_slots=4, fail_steps=0):
        self.n_slots = n_slots
        self.waiting = []
        self.slot_req = {}
        self.finished = []
        self.free_slots = list(range(n_slots))
        self.buckets = (16,)
        self.bursts = []
        self.fail_steps = fail_steps
        self._rid = 0
        self.reset_calls = 0

    def add_request(self, tokens, max_new):
        r = eng.Request(rid=self._rid, prompt=list(tokens),
                        max_new_tokens=max_new)
        self._rid += 1
        self.waiting.append(r)
        return r.rid

    def admit(self, on_wave=None):
        if self.fail_steps > 0:
            self.fail_steps -= 1
            raise RuntimeError("boom")
        while self.waiting and self.free_slots:
            r = self.waiting.pop(0)
            r.slot = self.free_slots.pop(0)
            r.tokens.append(7)
            import time as _t
            r.first_token_s = _t.time()
            self.slot_req[r.slot] = r
            if on_wave:
                on_wave()

    def decode_burst(self, max_burst=8):
        self.bursts.append(max_burst)
        for slot, r in list(self.slot_req.items()):
            r.tokens.append(8)
            if len(r.tokens) >= r.max_new_tokens:
                self.slot_req.pop(slot)
                self.free_slots.append(slot)
                self.finished.append(r)
        return {}

    def generate(self, prompts, max_new_tokens=2):
        return [[1] * max_new_tokens for _ in prompts]

    def reset(self):
        self.reset_calls += 1
        self.waiting.clear()
        self.slot_req.clear()
        self.finished.clear()
        self.free_slots = list(range(self.n_slots))


def test_adaptive_burst_short_while_slots_free():
    """Decode bursts stay short while free slots remain (a late arrival
    must not wait out a full max_burst decode before its prefill) and
    go long only once every slot is busy."""
    fake = _FakeEngine(n_slots=2)
    model = srv.ModelServer(fake, max_burst=16, open_burst=2)
    try:
        p1 = model._add([1, 2], 64)
        p2 = model._add([3], 64)      # fills both slots
        p3 = model._add([4], 4)       # waits -> slots stay full
        import time
        deadline = time.time() + 30
        while len(fake.bursts) < 3 and time.time() < deadline:
            time.sleep(0.01)
        assert fake.bursts, "no decode bursts ran"
        # Slots were full from the first decode on -> full bursts.
        assert fake.bursts[0] == 16
        p3.event.wait(timeout=30)
        del p1, p2
    finally:
        model.shutdown()


def test_adaptive_burst_open_window():
    """With free slots remaining and traffic recent, the server uses
    open_burst. open_window_s pinned huge: a loop-thread stall on a
    loaded CI host must not flip the quiet fallback mid-test."""
    fake = _FakeEngine(n_slots=8)
    model = srv.ModelServer(fake, max_burst=16, open_burst=2,
                            open_window_s=1e9)
    try:
        p = model._add([1, 2], 6)
        assert p.event.wait(timeout=30)
        assert fake.bursts and all(b == 2 for b in fake.bursts)
    finally:
        model.shutdown()


def test_adaptive_burst_long_when_quiet():
    """Free slots alone must not pin bursts short: once no request has
    arrived for open_window_s, bursts go long (a partially loaded
    server would otherwise pay per-burst dispatch forever)."""
    fake = _FakeEngine(n_slots=8)
    model = srv.ModelServer(fake, max_burst=16, open_burst=2,
                            open_window_s=0.0)
    try:
        p = model._add([1, 2], 6)
        assert p.event.wait(timeout=30)
        # Every arrival is instantly "quiet" at window 0 -> full bursts
        # despite 7 free slots.
        assert fake.bursts and all(b == 16 for b in fake.bursts)
    finally:
        model.shutdown()


def test_engine_failure_resets_and_recovers():
    """An engine exception fails in-flight requests AND resets the
    engine's queue/slot state so later requests succeed (advisor r3:
    stale waiting entries re-poisoned every subsequent step)."""
    fake = _FakeEngine(n_slots=2, fail_steps=1)
    model = srv.ModelServer(fake, max_burst=4, open_burst=4)
    try:
        p = model._add([1], 4)
        assert p.event.wait(timeout=30)
        assert "error" in (p.result or {})
        assert fake.reset_calls == 1
        assert model._ready.is_set()      # engine reset ok -> healthy
        p2 = model._add([2], 3)
        assert p2.event.wait(timeout=30)
        assert p2.result and "error" not in p2.result
    finally:
        model.shutdown()


def test_engine_reset_failure_flips_health():
    fake = _FakeEngine(n_slots=2, fail_steps=1)

    def bad_reset():
        raise RuntimeError("device gone")

    fake.reset = bad_reset
    model = srv.ModelServer(fake, max_burst=4)
    try:
        p = model._add([1], 4)
        assert p.event.wait(timeout=30)
        assert not model._ready.is_set()  # /health now 503
    finally:
        model.shutdown()


def test_engine_reset_clears_slots():
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    e = eng.InferenceEngine(params, cfg, n_slots=2, max_len=32,
                            prompt_buckets=(8,))
    e.add_request([1, 2, 3], max_new_tokens=64)   # stays active
    e.add_request([4, 5], max_new_tokens=64)
    e.add_request([6], max_new_tokens=2)          # queued (no slot)
    e.step()
    assert e.slot_req and e.waiting
    e.reset()
    assert not e.slot_req and not e.waiting and not e.finished
    assert sorted(e.free_slots) == [0, 1]
    assert int(e.cache["length"].sum()) == 0
    # The engine still serves fresh requests after a reset.
    out = e.generate([[9, 8]], max_new_tokens=3)
    assert len(out[0]) == 3


def test_pad_waves_single_program_per_bucket():
    """pad_waves pads every admission wave to max_wave rows, so results
    are identical to the unpadded engine and odd wave sizes cannot
    trigger fresh prefill compiles mid-traffic."""
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    plain = eng.InferenceEngine(params, cfg, n_slots=8, max_len=32,
                                prompt_buckets=(8,))
    padded = eng.InferenceEngine(params, cfg, n_slots=8, max_len=32,
                                 prompt_buckets=(8,), max_wave=4,
                                 pad_waves=True)
    prompts = [[3, 1, 4], [1, 5], [9, 2, 6, 5], [3, 5, 8], [9, 7]]
    want = plain.generate(prompts, max_new_tokens=4)
    got = padded.generate(prompts, max_new_tokens=4)
    assert got == want


def test_metrics_endpoint_exposition(model_server):
    """GET /metrics returns valid Prometheus text exposition carrying
    the serving histograms after at least one request (acceptance
    criterion of the observability PR)."""
    from skypilot_tpu.observability import metrics as metrics_lib

    url, _, _ = model_server
    code, _ = _post(f"{url}/generate",
                    {"tokens": [2, 7, 1], "max_new_tokens": 3})
    assert code == 200
    with urllib.request.urlopen(f"{url}/metrics", timeout=30) as r:
        assert r.status == 200
        assert r.headers.get("Content-Type") == metrics_lib.CONTENT_TYPE
        text = r.read().decode()
    fams = metrics_lib.parse_exposition(text)
    for name in ("skytpu_ttft_seconds", "skytpu_decode_step_seconds"):
        assert fams[name]["type"] == "histogram"
        count = sum(v for labels, v in fams[name]["samples"]
                    if labels.get("__name__") == f"{name}_count")
        assert count >= 1, name
    slots = fams["skytpu_slots_active"]
    assert slots["type"] == "gauge" and slots["samples"]
    # The gauge is process-global and other tests in this module build
    # their own engines, so assert a pool exists rather than its size.
    assert fams["skytpu_slots_total"]["samples"][0][1] >= 1
    # The HTTP layer observed itself too, labeled by route.
    http = fams["skytpu_http_requests_total"]
    assert any(labels.get("route") == "/generate" and v >= 1
               for labels, v in http["samples"])
    # Server wave-flush span double-records into its histogram.
    assert "skytpu_server_wave_flush_seconds" in fams
    # Unknown paths collapse into route="other": a scanner must not
    # mint unbounded label series in the process-global registry.
    try:
        urllib.request.urlopen(f"{url}/wp-login.php", timeout=30)
    except urllib.error.HTTPError as e:
        assert e.code == 404
    with urllib.request.urlopen(f"{url}/metrics", timeout=30) as r:
        fams2 = metrics_lib.parse_exposition(r.read().decode())
    routes = {labels.get("route")
              for labels, _ in fams2["skytpu_http_requests_total"]["samples"]}
    assert "other" in routes and "/wp-login.php" not in routes


def test_debug_flight_endpoint(model_server):
    """GET /debug/flight returns the engine's live burst ring + the
    compile-watch program registry (docs/observability.md §Flight
    recorder); ?n= caps the tail."""
    url, _, _ = model_server
    code, _ = _post(f"{url}/generate",
                    {"tokens": [4, 9, 2], "max_new_tokens": 3})
    assert code == 200
    with urllib.request.urlopen(f"{url}/debug/flight?n=5",
                                timeout=30) as r:
        assert r.status == 200
        payload = json.loads(r.read())
    assert payload["enabled"] is True
    assert payload["warm"] is False        # no --warm-grid here
    assert payload["unexpected"] == []
    assert 0 < len(payload["records"]) <= 5
    rec = payload["records"][-1]
    assert rec["kind"] == "flight"
    assert rec["burst"] in ("wave", "chunk", "decode", "verify",
                            "decode1")
    assert "layout" in rec["program"]
    # The program registry saw the engine's jit entry points compile.
    assert payload["programs"]
    assert any(k.startswith(("decode_burst", "admit_wave"))
               for k in payload["programs"])


def test_debug_flight_since_cursor(model_server):
    """?since=<seq> is the incremental tail (`skytpu flight --follow`):
    each response carries the ring's cursor, and re-sending it returns
    only records stamped after it."""
    url, _, _ = model_server
    with urllib.request.urlopen(f"{url}/debug/flight?n=1",
                                timeout=30) as r:
        first = json.loads(r.read())
    seq = first["seq"]
    assert seq > 0
    # Nothing new yet: the delta from the cursor is empty.
    with urllib.request.urlopen(f"{url}/debug/flight?since={seq}",
                                timeout=30) as r:
        delta = json.loads(r.read())
    assert delta["records"] == [] and delta["seq"] == seq
    # New traffic lands past the cursor — and only it.
    code, _ = _post(f"{url}/generate",
                    {"tokens": [7, 1, 5], "max_new_tokens": 2})
    assert code == 200
    with urllib.request.urlopen(f"{url}/debug/flight?since={seq}",
                                timeout=30) as r:
        delta = json.loads(r.read())
    assert delta["records"] and delta["seq"] > seq
    assert all(r["seq"] > seq for r in delta["records"])


def test_debug_forensics_endpoint(model_server):
    """GET /debug/forensics: the tail-detector state + exemplar index;
    ?rid= builds the request's critical-path ledger from the live ring
    (docs/observability.md §Request forensics)."""
    url, _, _ = model_server
    code, out = _post(f"{url}/generate",
                      {"tokens": [6, 2, 8], "max_new_tokens": 3})
    assert code == 200
    with urllib.request.urlopen(f"{url}/debug/forensics",
                                timeout=30) as r:
        payload = json.loads(r.read())
    assert payload["enabled"] is True
    assert set(payload["tail"]["estimates"]) == {"ttft", "tpot"}
    assert payload["tail"]["estimates"]["ttft"]["count"] >= 1
    # Find a retired rid in the ring and ask why it was slow.
    with urllib.request.urlopen(f"{url}/debug/flight?n=8192",
                                timeout=30) as r:
        records = json.loads(r.read())["records"]
    retires = [r for r in records if r["burst"] == "retire"]
    assert retires, "forensics-on server emitted no retire records"
    rid = retires[-1]["rids"][0]
    with urllib.request.urlopen(f"{url}/debug/forensics?rid={rid}",
                                timeout=30) as r:
        ans = json.loads(r.read())
    led = ans["ledger"]
    assert led["rid"] == rid
    total = sum(p["ms"] for p in led["phases"])
    assert total == pytest.approx(led["wall_ms"], abs=0.05)
    assert ans["records"]
    # Unknown rid -> typed 404; bad rid -> 400.
    try:
        urllib.request.urlopen(f"{url}/debug/forensics?rid=999999",
                               timeout=30)
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404 and "999999" in json.loads(e.read())["error"]
    try:
        urllib.request.urlopen(f"{url}/debug/forensics?rid=bogus",
                               timeout=30)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400
