"""HTTP model server: health, generate, concurrency, bad input."""

import json
import socket
import threading
import urllib.request

import jax
import pytest

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.infer import server as srv
from skypilot_tpu.models import llama


@pytest.fixture(scope="module")
def model_server():
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    engine = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                                 prompt_buckets=(16,))
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    model, httpd = srv.serve(engine, host="127.0.0.1", port=port)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    assert model._ready.wait(timeout=300)  # warmup compile done
    yield f"http://127.0.0.1:{port}", params, cfg
    model.shutdown()
    httpd.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_health(model_server):
    url, _, _ = model_server
    with urllib.request.urlopen(f"{url}/health", timeout=30) as r:
        assert json.loads(r.read())["status"] == "ok"


def test_generate_greedy_matches_engine(model_server):
    url, params, cfg = model_server
    prompt = [3, 17, 42]
    solo = eng.InferenceEngine(params, cfg, n_slots=1, max_len=64,
                               prompt_buckets=(16,))
    want = solo.generate([prompt], max_new_tokens=5)[0]
    code, out = _post(f"{url}/generate",
                      {"tokens": prompt, "max_new_tokens": 5})
    assert code == 200
    assert out["tokens"] == want
    assert out["ttft_ms"] is not None and out["total_ms"] > 0


def test_concurrent_generates(model_server):
    url, _, _ = model_server
    results = {}

    def one(i):
        code, out = _post(f"{url}/generate",
                          {"tokens": [i + 1, i + 2], "max_new_tokens": 4})
        results[i] = (code, len(out.get("tokens", [])))

    threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert all(results[i] == (200, 4) for i in range(4))


def test_bad_requests(model_server):
    url, _, _ = model_server
    code, out = _post(f"{url}/generate", {"max_new_tokens": 4})
    assert code == 400
    code, out = _post(f"{url}/generate",
                      {"tokens": list(range(99)), "max_new_tokens": 2})
    assert code == 400  # prompt exceeds the largest bucket


def _post_stream(url, payload, timeout=300):
    """POST with stream:true; returns [(arrival_time, chunk_dict)]."""
    import time
    req = urllib.request.Request(
        url, data=json.dumps(dict(payload, stream=True)).encode(),
        headers={"Content-Type": "application/json"})
    chunks = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.status == 200
        assert r.headers.get("Content-Type") == "application/x-ndjson"
        buf = b""
        while True:
            piece = r.read1(65536)
            if not piece:
                break
            buf += piece
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if line.strip():
                    chunks.append((time.time(), json.loads(line)))
    return chunks


def test_streaming_tokens_match_blocking(model_server):
    """Streamed chunks concatenate to exactly the blocking result, and
    the first token chunk lands BEFORE generation finishes (the whole
    point of streaming TTFT)."""
    url, _, _ = model_server
    prompt = [5, 9, 2]
    _, blocking = _post(f"{url}/generate",
                        {"tokens": prompt, "max_new_tokens": 24})
    chunks = _post_stream(f"{url}/generate",
                          {"tokens": prompt, "max_new_tokens": 24})
    assert "done" in chunks[-1][1]
    streamed = [t for _, c in chunks for t in c.get("tokens", [])]
    assert streamed == blocking["tokens"]
    assert chunks[-1][1]["ttft_ms"] is not None
    # Multiple emissions (burst=8 over 24 tokens -> >= 3 token chunks),
    # and the first arrives strictly before the done chunk.
    token_chunks = [c for _, c in chunks if "tokens" in c]
    assert len(token_chunks) >= 3
    first_t = next(t for t, c in chunks if "tokens" in c)
    done_t = chunks[-1][0]
    assert first_t < done_t


def test_streaming_oversized_prompt_clean_400(model_server):
    url, _, _ = model_server
    code, out = _post(f"{url}/generate",
                      {"tokens": list(range(99)), "max_new_tokens": 2,
                       "stream": True})
    assert code == 400 and "error" in out
