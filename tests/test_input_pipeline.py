"""Input pipeline: native/numpy packing parity, segment isolation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.data import input_pipeline as ip
from skypilot_tpu.models import llama


def test_native_matches_numpy_packer():
    docs = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10], [11, 12, 13, 14, 15]]
    a = ip.pack(docs, rows=2, cols=8, force_numpy=True)
    if ip._load_native() is None:
        pytest.skip("native packer unavailable (no g++)")
    b = ip.pack(docs, rows=2, cols=8, force_numpy=False)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_pack_places_and_carries():
    docs = [[1] * 6, [2] * 6, [3] * 6]
    tokens, segs, pos, placed = ip.pack(docs, rows=2, cols=8,
                                        force_numpy=True)
    assert placed == 2                      # third doc doesn't fit
    assert (tokens[0, :6] == 1).all() and (tokens[1, :6] == 2).all()
    assert segs[0, 5] == 1 and segs[0, 6] == 0   # padding segment 0
    assert pos[0, :6].tolist() == list(range(6))


def test_two_docs_share_a_row():
    docs = [[1, 2, 3], [7, 8]]
    tokens, segs, pos, placed = ip.pack(docs, rows=1, cols=8,
                                        force_numpy=True)
    assert placed == 2
    assert tokens[0, :5].tolist() == [1, 2, 3, 7, 8]
    assert segs[0, :5].tolist() == [1, 1, 1, 2, 2]
    assert pos[0, :5].tolist() == [0, 1, 2, 0, 1]


def test_packed_batches_stream_covers_everything():
    docs = [list(range(1, n + 1)) for n in (3, 30, 5, 9, 2, 14)]
    batches = list(ip.packed_batches(iter(docs), batch=2, seq=16,
                                     force_numpy=True))
    total_in = sum(len(d) for d in docs)
    total_out = sum(int((b["segment_ids"] > 0).sum()) for b in batches)
    assert total_out == total_in  # oversized docs chunked, none lost


def test_prefetch_order():
    batches = [{"i": np.asarray(i)} for i in range(5)]
    out = list(ip.prefetch(iter(batches), size=2))
    assert [int(b["i"]) for b in out] == [0, 1, 2, 3, 4]


def test_packed_forward_segment_isolation():
    """Doc B's logits inside a packed row == doc B alone: no leakage."""
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    doc_a, doc_b = [5, 9, 31, 44], [7, 3, 99]
    tokens, segs, pos, _ = ip.pack([doc_a, doc_b], rows=1, cols=16,
                                   force_numpy=True)

    packed_logits = jax.jit(
        lambda p, t, po, s: llama.forward_hidden(
            p, t, cfg, positions=po, segment_ids=s))(
        params, jnp.asarray(tokens), jnp.asarray(pos),
        jnp.asarray(segs))
    solo_b = jax.jit(
        lambda p, t: llama.forward_hidden(p, t, cfg))(
        params, jnp.asarray([doc_b], jnp.int32))

    got = np.asarray(packed_logits[0, 4:7])   # doc B occupies cols 4..6
    want = np.asarray(solo_b[0])
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=6e-2)


def test_packed_loss_masks_boundaries():
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    tokens, segs, pos, _ = ip.pack([[5, 9, 31], [7, 3]], rows=1, cols=8,
                                   force_numpy=True)
    batch = {"tokens": jnp.asarray(tokens),
             "segment_ids": jnp.asarray(segs),
             "positions": jnp.asarray(pos)}
    loss, metrics = jax.jit(
        lambda p, b: llama.loss_fn(p, b, cfg))(params, batch)
    assert np.isfinite(float(loss))
    # Predictable positions: within-doc transitions only = 2 + 1.
    assert float(metrics["tokens"]) == 3.0
