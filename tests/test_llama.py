"""Model correctness: shapes, causality, trainability, GQA."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.train import trainer


def test_forward_shapes(tiny_cfg):
    params = llama.init_params(jax.random.key(0), tiny_cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = jax.jit(lambda p, t: llama.forward(p, t, tiny_cfg))(params, tokens)
    assert logits.shape == (2, 16, tiny_cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_causality(tiny_cfg):
    """Changing a future token must not change logits at earlier positions."""
    params = llama.init_params(jax.random.key(0), tiny_cfg)
    rng = jax.random.key(1)
    tokens = jax.random.randint(rng, (1, 12), 0, tiny_cfg.vocab_size, dtype=jnp.int32)
    mutated = tokens.at[0, 8].set((tokens[0, 8] + 1) % tiny_cfg.vocab_size)
    a = llama.forward(params, tokens, tiny_cfg)
    b = llama.forward(params, mutated, tiny_cfg)
    np.testing.assert_allclose(np.asarray(a[0, :8]), np.asarray(b[0, :8]),
                               rtol=1e-4, atol=1e-4)
    assert not np.allclose(np.asarray(a[0, 8:]), np.asarray(b[0, 8:]))


def test_overfit_tiny_batch(tiny_cfg):
    """Loss must drop fast when memorizing one small batch."""
    tc = trainer.TrainConfig(learning_rate=3e-3, warmup_steps=2,
                             total_steps=60)
    state = trainer.create_train_state(tiny_cfg, tc, mesh=None, seed=0)
    step = trainer.make_train_step(tiny_cfg, tc, mesh=None)
    batch = trainer.synthetic_batch(tiny_cfg, 2, 32, seed=3)
    first = None
    for _ in range(40):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first * 0.5, (first, last)
    assert np.isfinite(last)


def test_param_count_matches_config():
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert n == cfg.num_params()


def test_logical_axes_cover_params(tiny_cfg):
    params = llama.init_params(jax.random.key(0), tiny_cfg)
    axes = llama.param_logical_axes(tiny_cfg)
    pl = jax.tree.structure(params)
    al = jax.tree.structure(axes, is_leaf=lambda x: isinstance(x, tuple))
    assert pl == al
    for leaf, ax in zip(
            jax.tree.leaves(params),
            jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))):
        assert leaf.ndim == len(ax), (leaf.shape, ax)


def test_chunked_xent_matches_full():
    """cfg.xent_chunk computes the same loss/accuracy as the full pass."""
    import dataclasses

    from skypilot_tpu.train import trainer

    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    batch = trainer.synthetic_batch(cfg, 2, 34)  # S-1=33, chunk 8 -> pad 7
    loss_full, m_full = jax.jit(
        lambda p, b: llama.loss_fn(p, b, cfg))(params, batch)

    ccfg = dataclasses.replace(cfg, xent_chunk=8)
    loss_chunk, m_chunk = jax.jit(
        lambda p, b: llama.loss_fn(p, b, ccfg))(params, batch)
    np.testing.assert_allclose(float(loss_full), float(loss_chunk),
                               rtol=1e-4)
    np.testing.assert_allclose(float(m_full["accuracy"]),
                               float(m_chunk["accuracy"]), rtol=1e-4)
    assert float(m_full["tokens"]) == float(m_chunk["tokens"])

    # Gradients flow through the chunked path too.
    g = jax.grad(lambda p: llama.loss_fn(p, batch, ccfg)[0])(params)
    gn = sum(float(jnp.sum(jnp.abs(x))) for x in jax.tree.leaves(g))
    assert gn > 0
