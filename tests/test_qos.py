"""Multi-tenant QoS: admission control, weighted fair queueing, and
priority preemption-by-eviction.

Tier-1 guards for the production-hardening layer (ROADMAP item 4):

* token buckets + typed load shed (429 ``rate_limited`` / 503
  ``overloaded``) at the model server and the load balancer;
* DRR fairness semantics (weights, priority lanes, per-tenant FIFO);
* the headline parity guarantee — a low-priority request preempted
  mid-decode and resumed produces BIT-IDENTICAL greedy output to an
  unpreempted run, across {fp32, int8 KV} x {spec-on, spec-off} on
  the paged layout, with zero leaked blocks after retirement;
* the burn-rate autoscaler (TTFT-p95 multi-window, not QPS);
* the ``_requeue`` queue-depth-gauge invariant (the PR's small fix).
"""

import json
import socket
import threading
import urllib.error
import urllib.request

import jax
import pytest

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.infer import qos as qos_lib
from skypilot_tpu.models import llama
from skypilot_tpu.observability import flight as flight_lib


@pytest.fixture(scope="module")
def cfg():
    return llama.CONFIGS["llama3-tiny"]


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.key(0), cfg)


def _req(rid, tenant="default", priority=0, prompt_len=4,
         max_new=4):
    return eng.Request(rid=rid, prompt=list(range(1, prompt_len + 1)),
                       max_new_tokens=max_new, tenant=tenant,
                       priority=priority)


# -- token bucket -----------------------------------------------------------

def test_token_bucket_burst_then_refill():
    b = qos_lib.TokenBucket(rate=2.0, burst=2.0, now=0.0)
    assert b.take(now=0.0) == 0.0
    assert b.take(now=0.0) == 0.0
    wait = b.take(now=0.0)                 # burst spent
    assert wait == pytest.approx(0.5)      # 1 token / 2 per s
    assert b.take(now=1.0) == 0.0          # refilled
    # Tokens cap at burst: a long idle spell never banks extra.
    b2 = qos_lib.TokenBucket(rate=1.0, burst=2.0, now=0.0)
    assert [b2.take(now=100.0) for _ in range(3)][-1] > 0


# -- DRR reorder ------------------------------------------------------------

def test_reorder_interleaves_hot_and_background():
    import collections
    sched = qos_lib.FairScheduler(quantum=8)   # = one request's cost
    waiting = collections.deque(
        [_req(i, tenant="hot") for i in range(6)]
        + [_req(10, tenant="bg")])
    sched.reorder(waiting)
    order = [r.tenant for r in waiting]
    # The background tenant rides the first DRR round, not position 6.
    assert "bg" in order[:2], order
    # Per-tenant FIFO preserved.
    hot_rids = [r.rid for r in waiting if r.tenant == "hot"]
    assert hot_rids == sorted(hot_rids)


def test_reorder_weights_are_proportional():
    import collections
    # cost = prompt 4 + budget 4 = 8; quantum 8 -> weight w releases
    # w requests per round.
    sched = qos_lib.FairScheduler(
        qos_lib.QosConfig(enabled=True, tenants={
            "paid": qos_lib.TenantSpec(weight=2),
            "free": qos_lib.TenantSpec(weight=1)}), quantum=8)
    waiting = collections.deque(
        [_req(i, tenant="paid") for i in range(4)]
        + [_req(10 + i, tenant="free") for i in range(4)])
    sched.reorder(waiting)
    first_round = [r.tenant for r in waiting][:3]
    assert sorted(first_round) == ["free", "paid", "paid"]


def test_priority_lanes_sort_strictly_first():
    import collections
    sched = qos_lib.FairScheduler()
    waiting = collections.deque(
        [_req(0, tenant="a"), _req(1, tenant="b"),
         _req(2, tenant="a", priority=1)])
    sched.reorder(waiting)
    assert waiting[0].rid == 2


def test_reorder_single_lane_keeps_fifo():
    import collections
    sched = qos_lib.FairScheduler()
    waiting = collections.deque([_req(i) for i in range(5)])
    sched.reorder(waiting)
    assert [r.rid for r in waiting] == [0, 1, 2, 3, 4]


# -- admission controller ---------------------------------------------------

def test_rate_limit_shed_is_typed_429():
    ac = qos_lib.AdmissionController(
        qos_lib.QosConfig(enabled=True, default_rate=1.0,
                          default_burst=1.0), where="server")
    ac.admit("hot")
    with pytest.raises(qos_lib.RateLimitedError) as ei:
        ac.admit("hot")
    e = ei.value
    assert e.http_status == 429
    assert e.typed_error["type"] == "rate_limited"
    assert e.typed_error["tenant"] == "hot"
    assert e.typed_error["retry_after_ms"] > 0
    # Independent buckets: another tenant is unaffected.
    ac.admit("background")


def test_overload_shed_is_typed_503():
    ac = qos_lib.AdmissionController(
        qos_lib.QosConfig(enabled=True, max_waiting=2), where="server")
    ac.admit("t", depth=1)
    with pytest.raises(qos_lib.OverloadedError) as ei:
        ac.admit("t", depth=2)
    assert ei.value.http_status == 503
    assert ei.value.typed_error["type"] == "overloaded"
    assert ei.value.typed_error["queued"] == 2


def test_tenant_label_cardinality_cap():
    qos_lib._reset_labels_for_tests()
    try:
        labels = {qos_lib.tenant_label(f"t{i}") for i in range(40)}
        assert "other" in labels
        assert len(labels) <= qos_lib._MAX_TENANT_LABELS + 1
        # A capped tenant stays capped; a seen one keeps its name.
        assert qos_lib.tenant_label("t0") == "t0"
        assert qos_lib.tenant_label("t39") == "other"
        # A CONFIGURED tenant first seen past the cap bypasses it —
        # scanner-minted names must not collapse the operator's own
        # tenants into 'other' (the bucket-table cap's rationale,
        # applied to the label set).
        cfgd = qos_lib.QosConfig(enabled=True, tenants={
            "paid": qos_lib.TenantSpec()})
        assert qos_lib.tenant_label("paid", cfgd) == "paid"
        assert qos_lib.tenant_label("paid") == "paid"   # now seen
        assert qos_lib.tenant_label("t39", cfgd) == "other"
    finally:
        qos_lib._reset_labels_for_tests()


def test_request_identity_header_body_and_clamp():
    cfg = qos_lib.QosConfig(enabled=True, tenants={
        "bulk": qos_lib.TenantSpec(priority=-1)})
    t, p = qos_lib.request_identity(
        {"x-skytpu-tenant": "acme", "x-skytpu-priority": "2"}, {})
    assert (t, p) == ("acme", 2)
    t, p = qos_lib.request_identity({}, {"tenant": "sdk",
                                         "priority": 99})
    assert (t, p) == ("sdk", 9)            # clamped
    # Body fallback + the tenant's configured default lane.
    t, p = qos_lib.request_identity({}, {"tenant": "bulk"}, cfg=cfg)
    assert (t, p) == ("bulk", -1)
    t, p = qos_lib.request_identity({}, {})
    assert (t, p) == (qos_lib.DEFAULT_TENANT, 0)
    # A whitespace-only header must not mint a tenant="" identity.
    t, _ = qos_lib.request_identity({"x-skytpu-tenant": "   "}, {})
    assert t == qos_lib.DEFAULT_TENANT
    # A CONFIGURED tenant's lane is a ceiling on the client header:
    # priority gates preemption rights, so the operator's lane wins —
    # self-deprioritizing below it is still allowed.
    t, p = qos_lib.request_identity(
        {"x-skytpu-tenant": "bulk", "x-skytpu-priority": "9"}, {},
        cfg=cfg)
    assert (t, p) == ("bulk", -1)
    t, p = qos_lib.request_identity(
        {"x-skytpu-tenant": "bulk", "x-skytpu-priority": "-5"}, {},
        cfg=cfg)
    assert (t, p) == ("bulk", -5)
    # An UNCONFIGURED tenant under a config is capped at the DEFAULT
    # lane: minting a fresh tenant name + a priority header must not
    # be the escape hatch around the operator's ceiling (priority
    # gates preemption rights).
    t, p = qos_lib.request_identity(
        {"x-skytpu-tenant": "fresh-name-123", "x-skytpu-priority": "9"},
        {}, cfg=cfg)
    assert (t, p) == ("fresh-name-123", 0)
    t, p = qos_lib.request_identity(
        {"x-skytpu-tenant": "fresh-name-123", "x-skytpu-priority": "-3"},
        {}, cfg=cfg)
    assert (t, p) == ("fresh-name-123", -3)   # self-deprioritize ok


# -- engine integration: WFQ + flight attribution ---------------------------

def test_wfq_admits_background_ahead_of_flood(params, cfg):
    """Six hot requests enqueued BEFORE one background request; with
    the fair scheduler the background tenant still rides the first
    admission pass, and the burst flight records carry the tenant
    composition the chaos scenario asserts fairness from."""
    rec = flight_lib.FlightRecorder()
    e = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                            prompt_buckets=(16,),
                            qos=qos_lib.FairScheduler(),
                            flight_recorder=rec)
    for i in range(6):
        e.add_request([1 + i, 2, 3], max_new_tokens=8, tenant="hot")
    e.add_request([9, 9, 9], max_new_tokens=8, tenant="background")
    e.admit()
    tenants = sorted(r.tenant for r in e.slot_req.values())
    assert tenants == ["background", "hot"]
    e.run_to_completion(max_burst=4)
    decode_recs = [r for r in rec.tail() if r["burst"] == "decode"]
    assert any(set(r.get("tenants", {})) == {"background", "hot"}
               for r in decode_recs)


def test_requeue_updates_waiting_gauge(params, cfg):
    """The small fix: every re-queue path routes through _requeue so
    skytpu_engine_waiting tracks the deque exactly."""
    e = eng.InferenceEngine(params, cfg, n_slots=1, max_len=64,
                            prompt_buckets=(16,))
    r = _req(0)
    e._requeue(r)
    assert len(e.waiting) == 1
    assert eng.ENGINE_WAITING._require_default().value == 1
    e.waiting.clear()
    e._update_gauges()


# -- preemption-by-eviction: the parity matrix ------------------------------

def _qos_engine(params, cfg, n_slots=1, kv_int8=False, spec_k=0,
                pool=4, **kw):
    return eng.InferenceEngine(
        params, cfg, n_slots=n_slots, max_len=64, prompt_buckets=(48,),
        prefill_chunk=8, prefix_pool=pool, kv_int8=kv_int8,
        spec_k=spec_k, qos=qos_lib.FairScheduler(), **kw)


@pytest.mark.parametrize("kv_int8", [False, True])
@pytest.mark.parametrize("spec_k", [0, 4])
def test_preempt_resume_bit_identical(params, cfg, kv_int8, spec_k):
    """The acceptance matrix: preempted mid-decode, resumed warm from
    the prefix cache, bit-identical greedy output — {fp32, int8} x
    {spec-on, spec-off}, paged layout, zero block leaks."""
    solo = eng.InferenceEngine(
        params, cfg, n_slots=1, max_len=64, prompt_buckets=(48,),
        prefill_chunk=8, prefix_pool=4, kv_int8=kv_int8, spec_k=spec_k)
    low_prompt = list(range(5, 17))
    want = solo.generate([low_prompt], max_new_tokens=14)[0]

    e = _qos_engine(params, cfg, kv_int8=kv_int8, spec_k=spec_k)
    rid_low = e.add_request(low_prompt, max_new_tokens=14, priority=0)
    while not e.slot_req:
        e.step_burst(max_burst=2)
    for _ in range(2):
        e.decode_burst(max_burst=2)
    e.add_request([3, 1, 4], max_new_tokens=4, priority=1)
    e.run_to_completion(max_burst=2)
    by_rid = {r.rid: r for r in e.finished}
    low = by_rid[rid_low]
    assert low.preemptions == 1
    assert low.resumed_len >= 8            # warm resume, not a recompute
    assert low.tokens == want
    # Allocator audit: no block may outlive the requests + cache.
    e.clear_prefix_cache()
    assert e.allocator.used == 0


def test_preempt_cold_resume_without_prefix_cache(params, cfg):
    """No prefix index (pool=0): eviction stores nothing and the
    resume re-prefills the full context — slower, still exact."""
    solo = eng.InferenceEngine(
        params, cfg, n_slots=1, max_len=64, prompt_buckets=(48,),
        prefill_chunk=8, prefix_pool=0)
    low_prompt = list(range(5, 17))
    want = solo.generate([low_prompt], max_new_tokens=12)[0]
    e = _qos_engine(params, cfg, pool=0)
    rid_low = e.add_request(low_prompt, max_new_tokens=12)
    while not e.slot_req:
        e.step_burst(max_burst=2)
    e.decode_burst(max_burst=2)
    e.add_request([3, 1, 4], max_new_tokens=4, priority=1)
    e.run_to_completion(max_burst=2)
    by_rid = {r.rid: r for r in e.finished}
    assert by_rid[rid_low].preemptions == 1
    assert by_rid[rid_low].resumed_len == 0
    assert by_rid[rid_low].tokens == want
    e.clear_prefix_cache()
    assert e.allocator.used == 0


def test_preempt_wave_admitted_victim_resumes_cold(params, cfg):
    """A wave-admitted victim (prompt <= chunk) becomes preemptible
    only once its context outgrows the chunk (the resume must ride the
    chunk path — the only one the parity matrix covers), and its rows
    never enter the SHARED prefix cache: they came from the wave
    program, and the cache promises chunk-origin bytes to later
    sharers. It resumes cold, still exact."""
    solo = eng.InferenceEngine(
        params, cfg, n_slots=1, max_len=64, prompt_buckets=(48,),
        prefill_chunk=8, prefix_pool=4)
    prompt = [5, 6, 7, 8, 9, 10]                # 6 <= chunk: wave path
    want = solo.generate([prompt], max_new_tokens=12)[0]
    e = _qos_engine(params, cfg)
    rid = e.add_request(prompt, max_new_tokens=12)
    while not e.slot_req:
        e.step_burst(max_burst=2)
    (slot,) = e.slot_req
    while len(e.slot_req[slot].prompt) + len(e.slot_req[slot].tokens) \
            <= e.prefill_chunk:
        assert e.preempt_slot(slot) is False    # still wave-sized
        e.decode_burst(max_burst=2)
    e.add_request([3, 1, 4], max_new_tokens=4, priority=1)
    e.run_to_completion(max_burst=2)
    by_rid = {r.rid: r for r in e.finished}
    assert by_rid[rid].preemptions == 1
    assert by_rid[rid].resumed_len == 0         # cold: nothing stored
    assert by_rid[rid].tokens == want
    e.clear_prefix_cache()
    assert e.allocator.used == 0


def test_no_preemption_within_equal_priority(params, cfg):
    """Same-priority work queues; it never evicts a peer (strict
    outranking only — no preemption cycles)."""
    e = _qos_engine(params, cfg)
    e.add_request(list(range(5, 17)), max_new_tokens=12, priority=0)
    while not e.slot_req:
        e.step_burst(max_burst=2)
    e.add_request([3, 1, 4], max_new_tokens=4, priority=0)
    e.admit()
    (resident,) = e.slot_req.values()
    assert resident.preemptions == 0
    assert len(e.waiting) == 1
    e.run_to_completion(max_burst=2)


def test_preempt_refuses_while_burst_in_flight(params, cfg):
    """An un-fetched async burst would commit tokens into a re-queued
    request; preemption must wait for the completion fetch."""
    e = _qos_engine(params, cfg)
    e.add_request(list(range(5, 17)), max_new_tokens=12)
    while not e.slot_req:
        e.step_burst(max_burst=2)
    (slot,) = e.slot_req
    handle = e.dispatch_decode_burst(max_burst=2)
    assert handle is not None
    assert e.preempt_slot(slot) is False
    e.complete_decode_burst(handle)
    assert e.preempt_slot(slot) is True
    e.run_to_completion(max_burst=2)
    e.clear_prefix_cache()
    assert e.allocator.used == 0


def test_preemption_metric_and_flight_record(params, cfg):
    rec = flight_lib.FlightRecorder()
    before = qos_lib.QOS_PREEMPTIONS.labels(
        tenant=qos_lib.tenant_label("victim")).value
    e = _qos_engine(params, cfg, flight_recorder=rec)
    e.add_request(list(range(5, 17)), max_new_tokens=12,
                  tenant="victim")
    while not e.slot_req:
        e.step_burst(max_burst=2)
    e.add_request([3, 1, 4], max_new_tokens=4, priority=1,
                  tenant="vip")
    e.run_to_completion(max_burst=2)
    assert qos_lib.QOS_PREEMPTIONS.labels(
        tenant=qos_lib.tenant_label("victim")).value == before + 1
    pre = [r for r in rec.tail() if r["burst"] == "preempt"]
    assert len(pre) == 1
    assert pre[0]["tenants"] == {"victim": 1}
    # retired_rows is what the resume will read WARM: the chunk-aligned
    # cached rows covering the victim's context after the store — never
    # the raw context length (a cold-resume eviction must read 0).
    assert pre[0]["retired_rows"] >= 8
    assert pre[0]["retired_rows"] % 8 == 0


def test_server_loop_preempts_on_saturated_replica(params, cfg):
    """Regression: the serving loop must reach the engine's
    priority-preemption pass with ZERO free slots — admission is its
    only entry point, and a saturated replica is exactly when the
    priority lanes matter. (`_step` used to gate `eng.admit()` on
    `eng.free_slots`, so over HTTP a vip arrival waited out the
    resident's whole budget and `preemptions` stayed 0.)"""
    import time
    from skypilot_tpu.infer import server as srv
    solo = eng.InferenceEngine(
        params, cfg, n_slots=1, max_len=64, prompt_buckets=(48,),
        prefill_chunk=8, prefix_pool=4)
    low_prompt = list(range(5, 17))
    want = solo.generate([low_prompt], max_new_tokens=14)[0]

    model = srv.ModelServer(_qos_engine(params, cfg))   # one slot
    try:
        assert model._ready.wait(timeout=120)
        results = {}

        def run(name, tokens, mnt, prio):
            results[name] = model.submit(tokens, mnt, priority=prio)

        t_low = threading.Thread(
            target=run, args=("low", low_prompt, 14, 0))
        t_low.start()
        deadline = time.monotonic() + 60
        while not model.engine.slot_req and time.monotonic() < deadline:
            time.sleep(0.01)
        assert model.engine.slot_req      # low holds the only slot
        t_hi = threading.Thread(target=run, args=("hi", [3, 1, 4], 4, 1))
        t_hi.start()
        t_hi.join(timeout=120)
        t_low.join(timeout=120)
        assert not t_hi.is_alive() and not t_low.is_alive()
        assert results["low"]["preemptions"] == 1
        assert results["low"]["tokens"] == want      # parity preserved
        assert len(results["hi"]["tokens"]) == 4
    finally:
        model.shutdown()


# -- typed shed over HTTP (model server + LB) -------------------------------

class _FakeEngine:
    """Engine double: instant admission, one token per burst."""

    def __init__(self, n_slots=4):
        self.n_slots = n_slots
        self.waiting = []
        self.slot_req = {}
        self.finished = []
        self.free_slots = list(range(n_slots))
        self.buckets = (16,)
        self._rid = 0

    def add_request(self, tokens, max_new, **kw):
        r = eng.Request(rid=self._rid, prompt=list(tokens),
                        max_new_tokens=max_new,
                        tenant=kw.get("tenant", "default"),
                        priority=kw.get("priority", 0))
        self._rid += 1
        self.waiting.append(r)
        return r.rid

    def admit(self, on_wave=None):
        import time as _t
        while self.waiting and self.free_slots:
            r = self.waiting.pop(0)
            r.slot = self.free_slots.pop(0)
            r.tokens.append(7)
            r.first_token_s = _t.time()
            self.slot_req[r.slot] = r

    def decode_burst(self, max_burst=8):
        for slot, r in list(self.slot_req.items()):
            r.tokens.append(8)
            if len(r.tokens) >= r.max_new_tokens:
                self.slot_req.pop(slot)
                self.free_slots.append(slot)
                self.finished.append(r)
        return {}

    def generate(self, prompts, max_new_tokens=2):
        return [[1] * max_new_tokens for _ in prompts]

    def reset(self):
        self.waiting.clear()
        self.slot_req.clear()
        self.finished.clear()
        self.free_slots = list(range(self.n_slots))


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_server_typed_shed_429_and_503():
    from skypilot_tpu.infer import server as srv
    ac = qos_lib.AdmissionController(
        qos_lib.QosConfig(enabled=True, default_rate=0.001,
                          default_burst=1.0, max_waiting=50),
        where="server")
    model = srv.ModelServer(_FakeEngine(), qos=ac)
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    httpd = srv._Threading(("127.0.0.1", port),
                           srv.make_handler(model))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{port}/generate"
    try:
        assert model._ready.wait(timeout=60)
        hdrs = {"x-skytpu-tenant": "hot"}
        code, out, _ = _post(url, {"tokens": [1, 2],
                                   "max_new_tokens": 2}, hdrs)
        assert code == 200
        code, out, rhdrs = _post(url, {"tokens": [1, 2],
                                       "max_new_tokens": 2}, hdrs)
        assert code == 429
        assert out["error"]["type"] == "rate_limited"
        assert out["error"]["tenant"] == "hot"
        assert int(rhdrs["Retry-After"]) >= 1
        # Another tenant's bucket is untouched.
        code, _, _ = _post(url, {"tokens": [1], "max_new_tokens": 2},
                           {"x-skytpu-tenant": "bg"})
        assert code == 200
        # Overload shed: queue depth past max_waiting -> typed 503.
        ac.cfg.max_waiting = 1
        model._pending[10_000] = object()     # simulate backlog
        try:
            code, out, _ = _post(url, {"tokens": [1],
                                       "max_new_tokens": 2},
                                 {"x-skytpu-tenant": "bg2"})
            assert code == 503
            assert out["error"]["type"] == "overloaded"
        finally:
            model._pending.pop(10_000, None)
    finally:
        httpd.shutdown()
        model.shutdown()


def test_lb_typed_shed_and_overload(tmp_path, monkeypatch):
    import http.server
    from skypilot_tpu.serve import load_balancer, serve_state
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))

    class Ok(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            self.rfile.read(n)
            body = b'{"ok": true}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    replica = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Ok)
    threading.Thread(target=replica.serve_forever, daemon=True).start()
    svc = "qos-lb"
    serve_state.add_service(svc, {}, {}, 0)
    serve_state.upsert_replica(
        svc, 1, "r1", serve_state.ReplicaStatus.READY,
        f"http://127.0.0.1:{replica.server_address[1]}")
    ac = qos_lib.AdmissionController(
        qos_lib.QosConfig(enabled=True, default_rate=0.001,
                          default_burst=1.0), where="lb")
    lb = load_balancer._ThreadingServer(
        ("127.0.0.1", 0),
        load_balancer.make_handler(
            svc, load_balancer.RoundRobinPolicy(), qos=ac))
    threading.Thread(target=lb.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{lb.server_address[1]}/generate"
    try:
        hdrs = {"x-skytpu-tenant": "hot"}
        code, out, _ = _post(url, {"tokens": [1]}, hdrs)
        assert code == 200
        code, out, rhdrs = _post(url, {"tokens": [1]}, hdrs)
        assert code == 429
        assert out["error"]["type"] == "rate_limited"
        assert int(rhdrs["Retry-After"]) >= 1
        # The SDK path — tenant in the BODY, no header — must land in
        # the same (drained) bucket, not a shared 'default' one...
        code, out, _ = _post(url, {"tokens": [1], "tenant": "hot"}, {})
        assert code == 429
        assert out["error"]["tenant"] == "hot"
        # ...while a different body tenant rides its own fresh bucket.
        code, _, _ = _post(url, {"tokens": [1], "tenant": "sdk-bg"}, {})
        assert code == 200
        # GET traffic is NOT admission-checked (the server tier only
        # guards POST /generate — a tenant's dashboard polls must not
        # drain the quota its generation requests need): the drained
        # 'hot' tenant's GET proxies through instead of shedding 429.
        get_req = urllib.request.Request(
            url, headers={"x-skytpu-tenant": "hot"})
        try:
            with urllib.request.urlopen(get_req, timeout=60) as r:
                get_code = r.status
        except urllib.error.HTTPError as e:
            get_code = e.code
        assert get_code != 429
        # No ready replicas -> typed 503 overloaded.
        serve_state.upsert_replica(
            svc, 1, "r1", serve_state.ReplicaStatus.SHUTDOWN, "")
        code, out, _ = _post(url, {"tokens": [1]},
                             {"x-skytpu-tenant": "bg"})
        assert code == 503
        assert out["error"]["type"] == "overloaded"
    finally:
        lb.shutdown()
        replica.shutdown()


def test_bucket_cap_configured_tenant_bypasses_overflow():
    cap = qos_lib._MAX_TENANT_LABELS
    ac = qos_lib.AdmissionController(
        qos_lib.QosConfig(enabled=True, default_rate=1.0,
                          default_burst=1.0, tenants={
                              "paid": qos_lib.TenantSpec(
                                  rate=1000.0, burst=1000.0)}),
        where="server")
    # A REAL tenant named "other" admits pre-cap and drains its
    # burst-1 bucket — it must not pool quota with the overflow.
    ac.admit("other")
    for i in range(cap - 1):
        ac.admit(f"scan{i}")
    # A configured tenant first seen PAST the cap keeps its own
    # bucket (config bounds those, not a scanner minting names): its
    # burst of 1000 admits freely where the shared bucket would shed.
    for _ in range(10):
        ac.admit("paid")
    # Unconfigured strangers past the cap share ONE default-spec
    # bucket — a fresh one, not tenant "other"'s drained bucket: the
    # first stranger admits, the second sheds immediately.
    ac.admit("stranger-a")
    with pytest.raises(qos_lib.RateLimitedError):
        ac.admit("stranger-b")
    assert "paid" in ac._buckets
    assert qos_lib._OVERFLOW_BUCKET_KEY in ac._buckets
    assert "stranger-a" not in ac._buckets


def test_qos_requests_metric_carries_tier_label():
    # With QoS at both tiers a proxied request is admitted twice —
    # the `where` label is what lets dashboards read ONE tier.
    t = qos_lib.tenant_label("tierlab")
    before = qos_lib.QOS_REQUESTS.labels(tenant=t, where="lb").value
    qos_lib.AdmissionController(
        qos_lib.QosConfig(enabled=True), where="lb").admit("tierlab")
    assert qos_lib.QOS_REQUESTS.labels(
        tenant=t, where="lb").value == before + 1


def test_top_qos_req_rate_reads_server_tier():
    from skypilot_tpu.client import cli as cli_mod

    def fams(req_lb, req_server, shed_lb):
        return {
            "skytpu_qos_requests_total": {"type": "counter", "samples": [
                ({"tenant": "acme", "where": "lb"}, float(req_lb)),
                ({"tenant": "acme", "where": "server"},
                 float(req_server)),
            ]},
            "skytpu_qos_shed_total": {"type": "counter", "samples": [
                ({"tenant": "acme", "reason": "rate_limited",
                  "where": "lb"}, float(shed_lb)),
            ]},
        }

    payload = {"components": [], "alerts": []}
    now = 1000.0
    frame = cli_mod._render_top_frame(
        fams(0, 0, 0), now - 10.0, fams(10, 10, 5), now, payload)
    qos_line = next(l for l in frame.splitlines()
                    if l.startswith("qos"))
    # 10 server-tier admits over 10 s = 1.00/s — NOT 2.00/s (the sum
    # of both tiers double-counts every proxied request). Sheds sum
    # across tiers (a request sheds at most once, at exactly one).
    assert "acme 1.00/s" in qos_line
    assert "shed 0.50/s" in qos_line


# -- burn-rate autoscaler ---------------------------------------------------

from conftest import ttft_fams as _ttft_fams  # noqa: E402


def test_burn_rate_autoscaler_scales_out_and_back():
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec(min_replicas=1, max_replicas=4,
                          target_ttft_p95_seconds=1.0,
                          upscale_delay_seconds=0.0,
                          downscale_delay_seconds=0.0)
    asc = autoscalers.Autoscaler.from_spec(spec)
    assert isinstance(asc, autoscalers.BurnRateAutoscaler)
    asc._snapshot_fn = None                # tests feed observe()

    # Healthy baseline across both windows: no scaling.
    asc.observe(_ttft_fams(100, 0), ts=0.0)
    asc.observe(_ttft_fams(200, 0), ts=301.0)
    asc.observe(_ttft_fams(300, 0), ts=400.0)
    assert asc.decide(0.0, 1, 1).target == 1

    # Latency regression: p95 > 1 s in BOTH windows -> scale out.
    asc.observe(_ttft_fams(300, 100), ts=500.0)
    asc.observe(_ttft_fams(300, 300), ts=801.0)
    assert asc.decide(0.0, 1, 1).target == 2
    # A single-window blip (short recovered, long still bad) does NOT
    # keep scaling: both windows must agree.
    asc.observe(_ttft_fams(900, 300), ts=870.0)
    assert asc.decide(0.0, 2, 2).target == 2

    # Sustained calm (both windows well inside SLO) -> drain back.
    asc.observe(_ttft_fams(2000, 300), ts=1200.0)
    asc.observe(_ttft_fams(4000, 300), ts=1600.0)
    assert asc.decide(0.0, 2, 2).target == 2   # calm starts counting
    asc.observe(_ttft_fams(6000, 300), ts=1700.0)
    assert asc.decide(0.0, 2, 2).target == 1
    # Never below min_replicas.
    asc.observe(_ttft_fams(8000, 300), ts=2100.0)
    assert asc.decide(0.0, 1, 1).target >= 1


def test_burn_rate_respects_upscale_cooldown():
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec(min_replicas=1, max_replicas=8,
                          target_ttft_p95_seconds=0.5,
                          upscale_delay_seconds=120.0)
    asc = autoscalers.BurnRateAutoscaler(spec)
    asc.observe(_ttft_fams(0, 100), ts=0.0)
    asc.observe(_ttft_fams(0, 300), ts=301.0)
    assert asc.decide(0.0, 1, 1).target == 2      # first breach scales
    asc.observe(_ttft_fams(0, 400), ts=360.0)
    assert asc.decide(0.0, 2, 2).target == 2      # cooling down
    asc.observe(_ttft_fams(0, 600), ts=600.0)
    assert asc.decide(0.0, 2, 2).target == 3      # cooldown elapsed


def test_service_spec_ttft_round_trip():
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec.from_yaml_config({
        "readiness_probe": "/health",
        "replica_policy": {"min_replicas": 1, "max_replicas": 3,
                           "target_ttft_p95_seconds": 2.0}})
    assert spec.target_ttft_p95_seconds == 2.0
    out = spec.to_yaml_config()
    assert out["replica_policy"]["target_ttft_p95_seconds"] == 2.0
    again = SkyServiceSpec.from_yaml_config(out)
    assert again.target_ttft_p95_seconds == 2.0


# -- per-tenant KV-block quotas (max_kv_blocks) -----------------------------

def test_kv_quota_stalls_tenant_not_queue(params, cfg):
    """A tenant at its max_kv_blocks quota stalls TYPED: its request
    steps aside (counter fires once per episode, never a 503) while
    other tenants keep admitting, its own retirement unblocks it, and
    the charge/refund accounting drains to exactly zero."""
    qcfg = qos_lib.QosConfig(enabled=True, tenants={
        "hog": qos_lib.TenantSpec(max_kv_blocks=1)})
    e = eng.InferenceEngine(
        params, cfg, n_slots=3, max_len=64, prompt_buckets=(16,),
        kv_block=16, prefix_pool=0,
        qos=qos_lib.FairScheduler(qcfg))
    stalls = eng.QOS_KV_QUOTA_STALLS.labels(tenant="hog")
    before = stalls.value
    # prompt 3 + budget 4 = 7 rows -> 1 block each: hog's first
    # request fills its quota, its second must wait for the refund.
    e.add_request([1, 2, 3], max_new_tokens=4, tenant="hog")
    e.add_request([4, 5, 6], max_new_tokens=4, tenant="hog")
    e.add_request([7, 8, 9], max_new_tokens=4, tenant="bg")
    e.admit()
    assert sorted(r.tenant for r in e.slot_req.values()) \
        == ["bg", "hog"]                    # hog's 2nd stepped aside
    assert len(e.waiting) == 1 and e.waiting[0].kv_quota_stalled
    assert e._tenant_kv["hog"] == 1
    assert eng.QOS_KV_BLOCKS.labels(tenant="hog").value == 1
    assert stalls.value == before + 1
    e.admit()                               # still at quota: once per
    assert stalls.value == before + 1       # episode, not per pass
    done = e.run_to_completion(max_burst=4)
    assert len(done) == 3                   # the retirement freed the
    assert not e.waiting and not e.slot_req  # quota; the 2nd ran
    assert not e._tenant_kv                 # charges pop at zero
    assert eng.QOS_KV_BLOCKS.labels(tenant="hog").value == 0


def test_kv_quota_unsatisfiable_rejected_at_submit(params, cfg):
    """A request whose own worst-case block need exceeds its tenant's
    quota can NEVER admit (the need formula is total-shaped and never
    shrinks) — it must be rejected typed at submit, not stalled
    forever."""
    qcfg = qos_lib.QosConfig(enabled=True, tenants={
        "hog": qos_lib.TenantSpec(max_kv_blocks=1)})
    e = eng.InferenceEngine(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=(16,),
        kv_block=16, prefix_pool=0,
        qos=qos_lib.FairScheduler(qcfg))
    with pytest.raises(eng.KvQuotaUnsatisfiableError) as ei:
        # prompt 3 + budget 60 -> capped at max_len 64 -> 4 blocks.
        e.add_request([1, 2, 3], max_new_tokens=60, tenant="hog")
    assert ei.value.typed_error["type"] == "kv_quota_unsatisfiable"
    assert not e.waiting                    # nothing half-submitted
    # Other tenants (unlimited) are untouched by the hog's cap.
    e.add_request([1, 2, 3], max_new_tokens=60, tenant="bg")
    e.admit()
    assert len(e.slot_req) == 1


def test_kv_quota_unconfigured_tenant_unlimited(params, cfg):
    """max_kv_blocks=0 (the default spec) never stalls — the quota is
    an explicit operator opt-in per tenant."""
    e = eng.InferenceEngine(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=(16,),
        kv_block=16, prefix_pool=0,
        qos=qos_lib.FairScheduler(qos_lib.QosConfig(enabled=True)))
    for i in range(2):
        e.add_request([1 + i, 2, 3], max_new_tokens=4, tenant="any")
    e.admit()
    assert len(e.slot_req) == 2 and not e.waiting


def test_kv_quota_spec_parses_from_env(monkeypatch):
    monkeypatch.setenv(
        "SKYTPU_QOS_TENANTS",
        '{"free": {"rate": 2, "max_kv_blocks": 64}}')
    qcfg = qos_lib.QosConfig.from_env()
    assert qcfg.tenant("free").max_kv_blocks == 64
    assert qcfg.tenant("other").max_kv_blocks == 0


# -- bench wiring -----------------------------------------------------------

def test_bench_qos_smoke():
    """CI-sized bench pass (the spec/span/flight smoke idiom):
    scheduling + preemption parity and the fairness STRUCTURE are
    asserted; wall-clock ratios are reported, gated only on
    hardware (a compute-bound CPU scales decode cost with occupancy,
    so the 1.3x TPOT gate is a TPU artifact gate in bench.py)."""
    from skypilot_tpu.infer import bench_serve
    r = bench_serve.run_qos_smoke()
    assert r["preempt_parity_ok"] and r["sched_parity_ok"]
    assert r["preemptions"] >= 1
    # FIFO strands the background tenant behind the flood; WFQ must
    # beat it by a wide margin (structure, not wall-clock).
    assert r["bg_ttft_wfq_ratio"] < r["bg_ttft_fifo_ratio"]
