"""Draft-model speculative decoding + async draft/verify pipeline.

Tier-1 guards for PR 14's claims: greedy output with a MODEL drafter
is exactly the spec-off output (pipelined and synchronous, fp32 and
int8 KV); the drafter's paged KV advances/rolls back in lockstep with
the verifier's commits (a rejected rollout leaves committed rows
bit-equal to a never-drafted drafter cache — rollback is a length
non-advance); the acceptance-collapse fallback demotes down the
ladder model -> ngram -> off; the pipeline structurally overlaps (a
draft dispatch lands INSIDE a verify's dispatch->fetch window, proven
from flight records, never wall-clock); and the drafter's program
surface is warm-able (zero unexpected compiles with the drafter
live).
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.infer import draft as draft_lib
from skypilot_tpu.infer import engine as eng
from skypilot_tpu.infer import kvcache
from skypilot_tpu.models import llama
from skypilot_tpu.observability import flight as flight_lib


@pytest.fixture(scope="module")
def cfg():
    # fp32: accumulation differences cannot hide behind bf16 eps (the
    # PR 6 test_infer_tp lesson).
    return dataclasses.replace(llama.CONFIGS["llama3-tiny"],
                               dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.key(0), cfg)


@pytest.fixture(scope="module")
def distilled(params, cfg):
    """(target, draft_params, draft_cfg) at the self-distillation
    endpoint: the truncated-layer draft agrees with the target."""
    return draft_lib.self_distilled_pair(params, cfg, 1)


def _prompts(cfg, n=3, length=12, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, length).tolist()
            for _ in range(n)]


def _engine(params, cfg, slots=4, max_len=128, buckets=(32,), **kw):
    return eng.InferenceEngine(params, cfg, n_slots=slots,
                               max_len=max_len, prompt_buckets=buckets,
                               **kw)


def _draft_engine(dparams, dcfg, slots=4, max_len=128, **kw):
    return draft_lib.DraftEngine(dparams, dcfg, n_slots=slots,
                                 max_len=max_len, **kw)


def _random_draft(cfg, seed=7):
    """A 1-layer random draft model: acceptance ~0 on a full-vocab
    workload — the rollback/demotion exercise."""
    dcfg = dataclasses.replace(cfg, n_layers=1)
    return llama.init_params(jax.random.key(seed), dcfg), dcfg


# -- draft-model construction ------------------------------------------------

def test_truncated_draft_shapes(params, cfg):
    dparams, dcfg = draft_lib.truncated_draft(params, cfg, 1)
    assert dcfg.n_layers == 1
    for name, w in dparams["blocks"].items():
        assert w.shape[0] == 1
        assert w.shape[1:] == params["blocks"][name].shape[1:]
    # Clamped to [1, n_layers].
    assert draft_lib.truncated_draft(params, cfg, 99)[1].n_layers \
        == cfg.n_layers
    assert draft_lib.truncated_draft(params, cfg, 0)[1].n_layers == 1


def test_self_distilled_pair_agrees_exactly(params, cfg):
    """The distillation endpoint: zeroed upper residual blocks pass
    the stream through unchanged, so target and truncated draft
    produce BIT-equal logits (fp32: adding exact zeros is exact)."""
    target, dparams, dcfg = draft_lib.self_distilled_pair(params, cfg,
                                                          1)
    toks = jnp.asarray(np.array([[5, 9, 2, 6, 5, 3, 5, 8]], np.int32))
    lens = jnp.asarray(np.array([8], np.int32))
    _, lt = kvcache.prefill_batch(target, toks, lens, cfg)
    _, ld = kvcache.prefill_batch(dparams, toks, lens, dcfg)
    assert np.array_equal(np.asarray(lt), np.asarray(ld))


def test_draft_engine_from_env(params, cfg, monkeypatch):
    de = draft_lib.draft_engine_from_env(params, cfg, 2, 64,
                                         spec="self:1")
    assert de is not None and de.cfg.n_layers == 1
    assert draft_lib.draft_engine_from_env(params, cfg, 2, 64,
                                           spec="") is None
    monkeypatch.setenv("SKYTPU_DRAFT_MODEL", "self:1")
    assert draft_lib.draft_engine_from_env(params, cfg, 2,
                                           64) is not None
    monkeypatch.delenv("SKYTPU_DRAFT_MODEL")
    with pytest.raises(ValueError):
        draft_lib.draft_engine_from_env(params, cfg, 2, 64,
                                        spec="no-such-model")


# -- DraftEngine unit: lockstep + rollback -----------------------------------

def _slot_rows(de, slot, rows):
    """A draft slot's first ``rows`` K/V rows (+ scales when int8) as
    numpy, gathered through its block table in logical order."""
    out = []
    for name in ("k", "v", "k_scale", "v_scale"):
        if name not in de.cache:
            continue
        arr = np.asarray(de.cache[name])
        bl = arr.shape[2] if name in ("k", "v") else arr.shape[3]
        nb = -(-rows // de.kv_block)
        blocks = de.block_table[slot, :nb]
        if name in ("k", "v"):
            rs = arr[:, blocks].reshape(arr.shape[0], -1,
                                        *arr.shape[3:])[:, :rows]
        else:       # scales: [L, nb, G, bl] -> [L, G, rows]
            rs = arr[:, blocks].transpose(0, 2, 1, 3).reshape(
                arr.shape[0], arr.shape[2], -1)[..., :rows]
        del bl
        out.append(rs)
    return out


@pytest.mark.parametrize("kv_int8", [False, True], ids=["fp32", "int8"])
def test_rejected_rollout_leaves_kv_bit_equal(distilled, kv_int8):
    """The lockstep/rollback invariant at the drafter level: a draft
    round whose tokens the verifier fully REJECTS (the correction
    token differs at position 0) leaves every committed row, plus the
    device length/last_token bookkeeping, bit-equal to a drafter that
    NEVER drafted — rollback is purely the length not advancing; the
    rejected rows sit past it, unreadable."""
    _, dparams, dcfg = distilled
    ctx = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]
    de = _draft_engine(dparams, dcfg, slots=2, max_len=64,
                       kv_int8=kv_int8)
    d = de.draft_batch({0: ctx}, 4)
    assert len(d[0]) == 4
    # The verifier rejected everything: committed context extends by
    # ONE token that provably differs from the draft's first.
    corr = (d[0][0] + 1) % dcfg.vocab_size or 1
    ctx2 = ctx + [corr]
    # Sync WITHOUT a fresh rollout (the draft_batch entry would draft
    # again): exactly what the next round's sync pass does.
    st = de._state[0]
    fix = {}
    assert de._sync_slot(0, st, ctx2, fix) == []
    de._dispatch_sync(fix)
    assert st.toks == ctx2[:-1] and st.last == corr

    # A drafter that never drafted, synced to the same context.
    de2 = _draft_engine(dparams, dcfg, slots=2, max_len=64,
                        kv_int8=kv_int8)
    st2 = de2._acquire(0)
    fix2 = {}
    de2._sync_slot(0, st2, ctx2, fix2)
    de2._dispatch_sync(fix2)

    rows = len(ctx2) - 1
    for a, b in zip(_slot_rows(de, 0, rows), _slot_rows(de2, 0, rows)):
        assert np.array_equal(a, b)
    for name in ("length", "last_token"):
        assert (np.asarray(de.cache[name])[0]
                == np.asarray(de2.cache[name])[0])


def test_predraft_reconcile_and_reuse(distilled):
    """The pipeline's reconcile path: a predraft rollout whose chain
    matches the committed context serves the next round with ZERO new
    device work (reuse_hits); a mispredicted one is discarded
    host-side (rollbacks) and the round redrafts."""
    _, dparams, dcfg = distilled
    ctx = [3, 1, 4, 1, 5, 9, 2, 6]
    de = _draft_engine(dparams, dcfg, slots=2, max_len=64)
    d = de.draft_batch({0: ctx}, 3)[0]
    assert de.rollout([0], 4)                 # predraft: bonus + next 3
    assert de.stats()["pending"] == 1
    # Full accept + the drafter's own bonus prediction: the drafter's
    # chain IS the committed context — next round reuses it.
    st = de._state[0]
    rolls0 = de.rollouts
    bonus_chain = st.toks + [st.last]         # pending roll not applied
    de._apply_pending()
    bonus = (de._state[0].toks + [de._state[0].last])[len(ctx) + 3]
    del bonus_chain
    ctx_full = ctx + d + [bonus]
    d2 = de.draft_batch({0: ctx_full}, 3)[0]
    assert len(d2) == 3
    assert de.rollouts == rolls0              # zero new rollouts
    assert de.reuse_hits >= 1
    # Mispredicted round: correction token diverges -> discard +
    # redraft (a fresh rollout runs).
    corr = (d2[0] + 1) % dcfg.vocab_size or 1
    ctx_miss = ctx_full + [corr]
    rb0 = de.rollbacks
    d3 = de.draft_batch({0: ctx_miss}, 3)[0]
    assert len(d3) == 3
    assert de.rollbacks > rb0
    assert de.rollouts == rolls0 + 1


def test_release_frees_blocks_and_reacquire_reingests(distilled):
    _, dparams, dcfg = distilled
    de = _draft_engine(dparams, dcfg, slots=2, max_len=64)
    de.draft_batch({0: [1, 2, 3, 4, 5]}, 2)
    assert de.blocks_used > 0 and de.claimed(0)
    de.release(0)
    assert de.blocks_used == 0 and not de.claimed(0)
    # Re-acquire with a DIFFERENT context: full re-ingest from zero.
    ic0 = de.ingest_chunks
    d = de.draft_batch({0: [9, 8, 7, 6, 5, 4]}, 2)
    assert len(d[0]) == 2
    assert de.ingest_chunks > ic0


# -- engine-level greedy parity ----------------------------------------------

@pytest.fixture(scope="module")
def off_outputs(distilled, cfg):
    """Spec-off reference outputs per kv_int8 (computed once — every
    parity combo below compares against these)."""
    target, _, _ = distilled
    prompts = _prompts(cfg)
    return {kv8: _engine(target, cfg, spec_k=0, kv_int8=kv8).generate(
                prompts, max_new_tokens=24)
            for kv8 in (False, True)}


@pytest.mark.parametrize("pipeline", [True, False],
                         ids=["pipelined", "sync"])
@pytest.mark.parametrize("kv_int8", [False, True], ids=["fp32", "int8"])
def test_model_draft_parity(distilled, cfg, off_outputs, kv_int8,
                            pipeline):
    """Greedy output with the model drafter — pipelined and
    synchronous, fp32 and int8 KV — is exactly the spec-off output."""
    target, dparams, dcfg = distilled
    de = _draft_engine(dparams, dcfg, kv_int8=kv_int8)
    on = _engine(target, cfg, spec_k=4, draft_engine=de,
                 spec_pipeline=pipeline, kv_int8=kv_int8).generate(
                     _prompts(cfg), max_new_tokens=24)
    assert on == off_outputs[kv_int8]


def test_model_draft_parity_low_acceptance(distilled, cfg,
                                           off_outputs):
    """A random 1-layer draft (acceptance ~0 — every round rolls
    back) still emits exactly the spec-off output: draft quality can
    never touch correctness."""
    target, _, _ = distilled
    rp, rcfg = _random_draft(cfg)
    de = _draft_engine(rp, rcfg)
    on = _engine(target, cfg, spec_k=4, draft_engine=de,
                 spec_pipeline=True).generate(_prompts(cfg),
                                              max_new_tokens=24)
    assert on == off_outputs[False]
    assert de.rollbacks > 0


def test_model_draft_parity_with_adapters(distilled, cfg):
    """The parity matrix's adapters axis: a mixed base/fine-tune batch
    under the model drafter emits exactly the spec-off outputs. The
    drafter drafts from the BASE draft model (adapter deltas only
    shape draft quality, never correctness — verification is
    greedy-exact against the target's adapter-aware programs)."""
    from skypilot_tpu.infer import adapters as ad
    target, dparams, dcfg = distilled
    rng = np.random.default_rng(11)
    rank = 4
    shapes = ad.target_shapes(cfg, rank)
    aw = {}
    for t, (sa, sb) in shapes.items():
        sa = sa[:-1] + (rank,)
        sb = (rank,) + sb[1:]
        aw[t] = {
            "a": rng.normal(size=(cfg.n_layers,) + sa).astype(
                np.float32) * 0.05,
            "b": rng.normal(size=(cfg.n_layers,) + sb).astype(
                np.float32) * 0.05}

    def catalog():
        cat = ad.AdapterCatalog(cfg, n_adapters=4, rank=rank)
        cat.register("ft-0", params=aw)
        return cat

    prompts = _prompts(cfg)

    def run(spec_k, de=None):
        e = _engine(target, cfg, spec_k=spec_k, draft_engine=de,
                    spec_pipeline=de is not None, adapters=catalog())
        ids = [e.add_request(p, max_new_tokens=16,
                             adapter="ft-0" if i == 1 else None)
               for i, p in enumerate(prompts)]
        e.run_to_completion()
        by_rid = {r.rid: r.tokens for r in e.finished}
        return [by_rid[i] for i in ids]

    off = run(0)
    on = run(4, de=_draft_engine(dparams, dcfg))
    assert on == off


def test_pipelined_equals_synchronous(distilled, cfg):
    """The pipeline is a scheduling change only: pipelined and
    synchronous spec modes emit identical tokens."""
    target, dparams, dcfg = distilled
    prompts = _prompts(cfg)
    outs = []
    for pipeline in (True, False):
        de = _draft_engine(dparams, dcfg)
        e = _engine(target, cfg, spec_k=4, draft_engine=de,
                    spec_pipeline=pipeline)
        outs.append(e.generate(prompts, max_new_tokens=16))
    assert outs[0] == outs[1]


def test_distilled_acceptance_and_reuse(distilled, cfg):
    """The self-distilled pair accepts (near-)everything, and the
    pipelined predraft serves rounds without fresh draft work."""
    target, dparams, dcfg = distilled
    de = _draft_engine(dparams, dcfg)
    e = _engine(target, cfg, spec_k=4, draft_engine=de,
                spec_pipeline=True)
    e.generate(_prompts(cfg), max_new_tokens=16)
    drafted = sum(r.spec_drafted for r in e.finished)
    accepted = sum(r.spec_accepted for r in e.finished)
    assert drafted > 0
    assert accepted / drafted > 0.9
    assert de.reuse_hits > 0
    # Every request rode the model rung the whole way.
    assert all(r.spec_mode == "model" for r in e.finished)
    # Drafter slots released with their requests.
    assert de.blocks_used == 0 and not de._state


# -- pipeline overlap (structural, from flight records) ----------------------

def test_pipeline_overlap_structural(distilled, cfg):
    """The async pipeline's proof, timing-free: every 'draft' flight
    record (the predraft dispatch) lands INSIDE a verify record's
    dispatch->fetch window — draft and verify overlap instead of
    chaining serially. The verify records carry drafter= and
    overlap_ms attribution."""
    target, dparams, dcfg = distilled
    fl = flight_lib.FlightRecorder()
    de = _draft_engine(dparams, dcfg)
    e = _engine(target, cfg, spec_k=4, draft_engine=de,
                spec_pipeline=True, flight_recorder=fl)
    e.generate(_prompts(cfg), max_new_tokens=16)
    recs = fl.tail()
    drafts = [r for r in recs if r["burst"] == "draft"]
    verifies = [r for r in recs if r["burst"] == "verify"]
    assert drafts and verifies
    for d in drafts:
        assert d["drafter"] == "model"
        assert any(v["ts_s"] <= d["ts_s"] <= v["ts_s"] + v["dur_s"]
                   for v in verifies), \
            "draft dispatch not inside any verify window"
    assert any(r.get("drafter") == "model" for r in verifies)
    assert any(r.get("overlap_ms", 0) > 0 for r in verifies)
    # Synchronous mode emits no 'draft' records (drafting happens
    # inside draft_batch before the dispatch) — the records are the
    # pipeline's signature.
    fl2 = flight_lib.FlightRecorder()
    de2 = _draft_engine(dparams, dcfg)
    e2 = _engine(target, cfg, spec_k=4, draft_engine=de2,
                 spec_pipeline=False, flight_recorder=fl2)
    e2.generate(_prompts(cfg), max_new_tokens=16)
    assert not [r for r in fl2.tail() if r["burst"] == "draft"]


# -- fallback ladder ---------------------------------------------------------

def test_collapse_demotes_model_to_ngram_to_off(params, cfg):
    """The demotion chain: a random draft model's acceptance collapses
    -> the request falls back to the factory drafter (ngram rung) with
    its draft-engine slot released; when THAT rung collapses too (an
    always-wrong factory drafter), speculation turns off for the
    request — and only that request."""
    rp, rcfg = _random_draft(cfg)
    de = _draft_engine(rp, rcfg)
    prompts = _prompts(cfg, n=1)
    # Known-correct continuation, so the always-wrong factory drafter
    # provably mismatches every position.
    oracle_out = _engine(params, cfg, spec_k=0).generate(
        prompts, max_new_tokens=32)
    wrong = {tuple(p): [(t + 1) % cfg.vocab_size for t in o]
             for p, o in zip(prompts, oracle_out)}

    class Wrong:
        def __init__(self, req):
            self.out = wrong[tuple(req.prompt)]
            self.seen = 0

        def catch_up(self, prompt, generated):
            self.seen = len(generated)

        def draft(self, k):
            return self.out[self.seen:self.seen + k]

    e = _engine(params, cfg, spec_k=4, draft_engine=de,
                spec_pipeline=True, spec_drafter=lambda r: Wrong(r))
    ids = [e.add_request(p, max_new_tokens=32) for p in prompts]
    e.admit()
    modes = set()
    while e.slot_req:
        req = next(iter(e.slot_req.values()))
        modes.add(req.spec_mode)
        e.decode_burst(4)
    del ids
    req = e.finished[0]
    assert modes >= {"model", "ngram"}
    assert req.spec_mode == "off" and req.spec_off
    # Output stayed exactly greedy through every rung.
    assert [r.tokens for r in e.finished] == oracle_out
    # The demotion released the draft slot.
    assert de.blocks_used == 0


def test_no_draft_engine_keeps_ngram_ladder(params, cfg):
    """Without a DraftEngine requests start at the ngram rung (PR 8
    behavior preserved) and collapse straight to off."""
    e = _engine(params, cfg, spec_k=2)
    e.generate(_prompts(cfg, n=1), max_new_tokens=8)
    assert e.finished[0].spec_mode in ("ngram", None)
    assert e.draft_engine is None and not e.spec_pipeline


# -- knobs + compile surface -------------------------------------------------

def test_spec_pipeline_env_knob(params, cfg, distilled, monkeypatch):
    target, dparams, dcfg = distilled
    de = _draft_engine(dparams, dcfg)
    monkeypatch.setenv("SKYTPU_SPEC_PIPELINE", "0")
    assert not _engine(target, cfg, spec_k=4,
                       draft_engine=de).spec_pipeline
    monkeypatch.delenv("SKYTPU_SPEC_PIPELINE")
    assert _engine(target, cfg, spec_k=4,
                   draft_engine=de).spec_pipeline
    # No draft engine -> no pipeline, whatever the knob says.
    assert not _engine(params, cfg, spec_k=4,
                       spec_pipeline=True).spec_pipeline


def test_warm_grid_zero_unexpected_compiles_with_drafter(distilled,
                                                         cfg):
    """The compile-watch contract extends to the drafter: after
    warm_programs + declare_warmup_complete, live spec traffic (with
    rollbacks and predrafts) compiles NOTHING on either engine.
    (span_buckets=0 keeps the warm sweep to one rung — the ladder's
    own coverage is test_span_attn's job.)"""
    target, dparams, dcfg = distilled
    de = _draft_engine(dparams, dcfg, span_buckets=0)
    e = _engine(target, cfg, spec_k=4, draft_engine=de,
                spec_pipeline=True, max_wave=4, pad_waves=True,
                span_buckets=0)
    n = e.warm_programs(max_burst=8)
    assert n > 0
    e.declare_warmup_complete()
    assert de.compile_watch.warm
    e.generate(_prompts(cfg), max_new_tokens=24)
    assert e.compile_watch.unexpected == []
    assert de.compile_watch.unexpected == []


def test_top_serve_line_shows_drafter_and_overlap():
    """`skytpu top`'s serve line surfaces the drafter kind, window
    acceptance and the pipeline overlap ratio from the new metric
    families (the ROADMAP item 2 observability slice)."""
    from skypilot_tpu.client import cli as cli_mod

    def fams(drafted, accepted, model_toks, overlap_s, verify_s):
        return {
            "skytpu_ttft_seconds": {"type": "histogram", "samples": []},
            "skytpu_spec_drafted_total": {
                "type": "counter", "samples": [({}, float(drafted))]},
            "skytpu_spec_accepted_total": {
                "type": "counter", "samples": [({}, float(accepted))]},
            "skytpu_spec_draft_tokens_total": {
                "type": "counter",
                "samples": [({"drafter": "model"}, float(model_toks))]},
            "skytpu_spec_overlap_wall_seconds_total": {
                "type": "counter", "samples": [({}, float(overlap_s))]},
            "skytpu_spec_verify_wall_seconds_total": {
                "type": "counter", "samples": [({}, float(verify_s))]},
        }

    payload = {"components": [], "alerts": []}
    now = 1000.0
    frame = cli_mod._render_top_frame(
        fams(0, 0, 0, 0.0, 0.0), now - 10.0,
        fams(100, 90, 100, 4.0, 5.0), now, payload)
    serve_line = next(l for l in frame.splitlines()
                      if l.startswith("serve"))
    assert "spec model acc  90%" in serve_line
    assert "ovl  80%" in serve_line


def test_engine_reset_resets_drafter(distilled, cfg):
    target, dparams, dcfg = distilled
    de = _draft_engine(dparams, dcfg)
    e = _engine(target, cfg, spec_k=4, draft_engine=de)
    ids = [e.add_request(p, max_new_tokens=32)
           for p in _prompts(cfg, n=2)]
    e.admit()
    e.decode_burst(4)
    del ids
    assert de.blocks_used > 0
    e.reset()
    assert de.blocks_used == 0 and not de._state
    assert de.stats()["pending"] == 0
