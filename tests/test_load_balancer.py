"""Load-balancer raw-splice proxy unit tests (fast profile).

The LB forwards replica bytes VERBATIM (no chunk decode/re-encode),
pools keep-alive upstream sockets, and keeps the old retry semantics:
retries before the first forwarded byte, 4xx passthrough, 5xx/connect
failover. These run against an in-process fake replica, so the fast
profile covers the forward path the slow e2e suite exercises for real.
"""

import http.server
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from skypilot_tpu.serve import load_balancer, serve_state


class _Replica(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    requests_seen = []

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(n)
        type(self).requests_seen.append((self.path, body))
        if self.path == "/chunked":
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            for i in range(3):
                data = json.dumps({"i": i}).encode() + b"\n"
                self.wfile.write(b"%x\r\n%s\r\n" % (len(data), data))
                self.wfile.flush()
                time.sleep(0.05)
            self.wfile.write(b"0\r\n\r\n")
        elif self.path == "/plain":
            out = b"plain:" + body
            self.send_response(200)
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)
        elif self.path == "/bad":
            out = b'{"error": "nope"}'
            self.send_response(400)
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)
        elif self.path == "/boom":
            out = b"exploded"
            self.send_response(500)
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)

    do_GET = do_POST

    def log_message(self, *a):
        pass


def _spawn_replica():
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Replica)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


@pytest.fixture()
def lb(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    _Replica.requests_seen = []
    replica, url = _spawn_replica()
    serve_state.add_service("lbtest", {}, {}, 0)
    serve_state.upsert_replica("lbtest", 1, "r1",
                               serve_state.ReplicaStatus.READY, url)
    httpd = load_balancer._ThreadingServer(
        ("127.0.0.1", 0),
        load_balancer.make_handler("lbtest",
                                   load_balancer.LeastLoadPolicy()))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", url
    httpd.shutdown()
    replica.shutdown()


def test_chunked_splice_streams_and_terminates(lb):
    lb_url, _ = lb
    req = urllib.request.Request(lb_url + "/chunked", data=b"{}",
                                 method="POST")
    t0 = time.time()
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.headers.get("Transfer-Encoding") == "chunked"
        pieces, times = [], []
        while True:
            p = r.read1(65536)
            if not p:
                break
            pieces.append(p)
            times.append(time.time() - t0)
    lines = b"".join(pieces).decode().strip().split("\n")
    assert [json.loads(x)["i"] for x in lines] == [0, 1, 2]
    # Streamed, not buffered: first piece well before the last.
    assert times[-1] - times[0] > 0.05


def test_content_length_body_and_keepalive_pooling(lb):
    lb_url, _ = lb
    for i in range(3):
        req = urllib.request.Request(lb_url + "/plain",
                                     data=f"x{i}".encode(), method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.read() == f"plain:x{i}".encode()
    # All three went over ONE pooled upstream connection after the
    # first (the pool held it between requests). The handler pools the
    # socket just after the last client byte goes out — wait a beat.
    parts = load_balancer.urlsplit(lb[1])
    addr = (parts.hostname, parts.port)
    deadline = time.time() + 5
    while (not load_balancer._POOL._idle.get(addr)
           and time.time() < deadline):
        time.sleep(0.02)
    assert len(load_balancer._POOL._idle.get(addr, [])) >= 1


def test_4xx_passthrough(lb):
    lb_url, _ = lb
    req = urllib.request.Request(lb_url + "/bad", data=b"{}",
                                 method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=30)
    assert ei.value.code == 400
    assert json.loads(ei.value.read())["error"] == "nope"
    # A 4xx is NOT a replica failure: no failover, single upstream hit.
    assert len(_Replica.requests_seen) == 1


def test_5xx_fails_over_to_next_replica(lb):
    lb_url, url1 = lb
    # Second healthy replica; first one will 500.
    replica2, url2 = _spawn_replica()
    try:
        serve_state.upsert_replica("lbtest", 2, "r2",
                                   serve_state.ReplicaStatus.READY, url2)
        for _ in range(4):   # least-load alternates; all must succeed
            req = urllib.request.Request(lb_url + "/boom", data=b"{}",
                                         method="POST")
            # /boom 500s on both replicas -> LB exhausts retries -> 503.
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 503
        # Mixed case: /plain works wherever it lands.
        req = urllib.request.Request(lb_url + "/plain", data=b"ok",
                                     method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.read() == b"plain:ok"
    finally:
        replica2.shutdown()


def test_stale_pooled_socket_retried(lb):
    lb_url, url = lb
    req = urllib.request.Request(lb_url + "/plain", data=b"a",
                                 method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        r.read()
    # Poison the pooled socket: close it server-side by closing ALL
    # pooled sockets locally (simulates replica-side idle timeout).
    parts = load_balancer.urlsplit(url)
    addr = (parts.hostname, parts.port)
    for s in load_balancer._POOL._idle.get(addr, []):
        s.close()
    # Next request must transparently retry on a fresh connect.
    req = urllib.request.Request(lb_url + "/plain", data=b"b",
                                 method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.read() == b"plain:b"
