"""Docker image tasks (``image_id: docker:<img>``) against a fake
docker CLI: container setup at launch, job exec inside the container
with the rank env propagated, logs flowing back. Offline — the fake
`docker` executable records every invocation and emulates `exec` by
running the inner command directly (VERDICT r3 #4).
"""

import os
import stat
import textwrap
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu.backend import TpuVmBackend
from skypilot_tpu.resources import Resources
from skypilot_tpu.runtime.job_queue import JobStatus
from skypilot_tpu.task import Task

FAKE_DOCKER = textwrap.dedent("""\
    #!/usr/bin/env -S python3 -S
    import os, subprocess, sys
    args = sys.argv[1:]
    log = os.environ.get("FAKE_DOCKER_LOG")
    if log and args and args[0] != "info":
        with open(log, "a") as f:
            f.write(" ".join(args) + chr(10))
    if not args:
        sys.exit(2)
    cmd = args[0]
    if cmd == "exec":
        i = 1
        env = {}
        while args[i] == "-e":
            k, _, v = args[i + 1].partition("=")
            env[k] = v
            i += 2
        container, rest = args[i], args[i + 1:]
        os.environ.update(env)
        os.environ["IN_FAKE_CONTAINER"] = container
        sys.exit(subprocess.call(rest))
    sys.exit(0)
""")


@pytest.fixture()
def fake_docker(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    bindir = tmp_path / "bin"
    bindir.mkdir()
    exe = bindir / "docker"
    exe.write_text(FAKE_DOCKER)
    exe.chmod(exe.stat().st_mode | stat.S_IEXEC)
    log = tmp_path / "docker_calls.log"
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_DOCKER_LOG", str(log))
    yield log


def _docker_task(run, image="myorg/task-env:1.2", name="d"):
    t = Task(name=name, run=run)
    t.set_resources(Resources(cloud="local",
                              image_id=f"docker:{image}"))
    return t


def test_docker_image_property():
    r = Resources(cloud="local", image_id="docker:ubuntu:22.04")
    assert r.docker_image == "ubuntu:22.04"
    assert Resources(cloud="local").docker_image is None
    assert Resources(cloud="gcp",
                     image_id="projects/x/global/images/y"
                     ).docker_image is None


def test_docker_setup_exec_logs(fake_docker):
    t = _docker_task('echo "inside=$IN_FAKE_CONTAINER '
                     'rank=$SKYTPU_NODE_RANK"')
    job_id, handle = sky.launch(t, cluster_name="cdock")
    status = TpuVmBackend().wait_job(handle, job_id, timeout=60)
    assert status == JobStatus.SUCCEEDED

    calls = fake_docker.read_text().splitlines()
    # Launch-time container setup: pull then (re)create.
    assert any(c.startswith("pull myorg/task-env:1.2") for c in calls)
    runs = [c for c in calls if c.startswith("run ")]
    assert runs and "--net=host" in runs[0] and \
        "--name skytpu-container" in runs[0] and \
        "myorg/task-env:1.2" in runs[0]
    # The job ran through docker exec with the rank env as -e flags.
    execs = [c for c in calls if c.startswith("exec ")]
    assert execs and "SKYTPU_NODE_RANK=0" in execs[0]
    # ...and the command really ran "inside" the container, seeing the
    # injected env.
    log_path = TpuVmBackend().job_log_paths(handle, job_id)[0]
    content = open(log_path).read()
    assert "inside=skytpu-container rank=0" in content
    sky.down("cdock")


def test_docker_exec_on_existing_cluster(fake_docker):
    t = _docker_task("echo first")
    job1, handle = sky.launch(t, cluster_name="cdock2")
    TpuVmBackend().wait_job(handle, job1, timeout=60)
    t2 = _docker_task('echo "second-in=$IN_FAKE_CONTAINER"',
                      name="second")
    job2, _ = sky.exec(t2, cluster_name="cdock2")
    assert TpuVmBackend().wait_job(handle, job2,
                                   timeout=60) == JobStatus.SUCCEEDED
    content = open(
        TpuVmBackend().job_log_paths(handle, job2)[0]).read()
    assert "second-in=skytpu-container" in content
    sky.down("cdock2")
