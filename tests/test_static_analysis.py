"""Tier-1 gate for the static-analysis suite (`skytpu lint`).

Three layers:

1. The whole tree must run clean against the checked-in baseline
   (``lint_baseline.json``) — no new findings, no rotted (stale)
   entries, every entry justified. This is the standing correctness
   gate the framework exists for.
2. Golden fixtures per checker: a ``*_bad.py`` file with seeded
   violations marked ``# expect: <rule>`` must be reported at exactly
   those lines with exactly those rules (nothing more), and its
   ``*_clean.py`` twin must pass.
3. Framework mechanics: per-file cache hit/invalidation (mtime AND
   content), checker-version invalidation, ``--baseline-update``
   round-trip, stale detection, partial (``--changed``) semantics.
"""

import json
import os
import re
import time

import pytest

from skypilot_tpu import analysis
from skypilot_tpu.analysis import baseline as baseline_lib
from skypilot_tpu.analysis import core as analysis_core
from skypilot_tpu.analysis.core import FileContext

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "analysis_fixtures")

_EXPECT_RE = re.compile(r"#\s*expect:\s*([\w-]+(?:\s*,\s*[\w-]+)*)")


@pytest.fixture(autouse=True)
def _isolated_home(tmp_path, monkeypatch):
    """The cache must never write to the real user home from tests."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))


# ---------------------------------------------------------------------------
# 1. The gate: the tree is clean against the baseline.

def test_tree_clean_against_baseline():
    res = analysis.run(root=REPO, use_cache=False)
    msg = []
    for f in res.new:
        msg.append(f.format())
    for k in res.stale:
        msg.append(f"stale baseline entry (remove it): {k}")
    for k in res.unjustified:
        msg.append(f"baseline entry lacks a justification: {k}")
    assert res.clean, (
        "`skytpu lint` is not clean — fix the finding or (for a "
        "genuinely intentional case) baseline it WITH a one-line "
        "justification:\n  " + "\n  ".join(msg))
    # The suite saw the real tree: a scan refactor that silently
    # found nothing would otherwise pass vacuously.
    assert res.files_scanned > 100
    assert len(res.findings) >= 20, (
        "the checked-in baseline grandfathers ~30 findings; seeing "
        f"only {len(res.findings)} means a checker stopped scanning")


def test_baseline_entries_all_justified():
    base = baseline_lib.load(baseline_lib.default_path(REPO))
    assert base, "checked-in baseline missing"
    bad = [k for k, e in base.items()
           if not e["justification"].strip()
           or e["justification"].startswith("TODO")]
    assert not bad, f"baseline entries without justification: {bad}"


# ---------------------------------------------------------------------------
# 2. Golden fixtures.

def _fixture_ctx(name, rel):
    path = os.path.join(FIXTURES, name)
    return FileContext(path, rel)


def _expected(ctx):
    out = {}
    for i, line in enumerate(ctx.lines, start=1):
        m = _EXPECT_RE.search(line)
        if m:
            out[i] = sorted(r.strip() for r in m.group(1).split(","))
    return out


def _run_fixture(checker_name, name, rel, root=None):
    checker = analysis_core.get_checker(checker_name)
    ctx = _fixture_ctx(name, rel)
    if checker.scope == "file":
        findings = checker.check_file(ctx)
    else:
        findings = checker.check_project([ctx], root or REPO)
    return ctx, [f for f in findings if f.path == ctx.rel]


def _assert_golden(checker_name, name, rel, root=None):
    ctx, findings = _run_fixture(checker_name, name, rel, root)
    expected = _expected(ctx)
    got = {}
    for f in findings:
        got.setdefault(f.line, []).append(f.rule)
    got = {line: sorted(rules) for line, rules in got.items()}
    assert got == expected, (
        f"{name}: findings (line->rules) {got} != expected markers "
        f"{expected}")
    # Sanity: a fixture without seeded violations tests nothing.
    assert expected, f"{name} has no # expect: markers"


# (checker, bad fixture, clean twin, rel path that puts it in scope)
_GOLDEN = [
    ("retrace-safety", "retrace_bad.py", "retrace_clean.py",
     "skypilot_tpu/infer/fixture_retrace.py"),
    # Paged-KV shape: the block-gather attention pattern (PR 7) —
    # proves the checker covers table gathers/scatters, not just the
    # contiguous idiom.
    ("retrace-safety", "retrace_paged_bad.py", "retrace_paged_clean.py",
     "skypilot_tpu/infer/fixture_retrace_paged.py"),
    ("host-sync", "host_sync_bad.py", "host_sync_clean.py",
     "skypilot_tpu/infer/engine.py"),
    ("host-sync", "host_sync_paged_bad.py", "host_sync_paged_clean.py",
     "skypilot_tpu/infer/engine.py"),
    # Speculative-decode shape (PR 8): the K-position verify program
    # and the draft/accept hot path are guarded like the paged gather.
    ("retrace-safety", "retrace_spec_bad.py", "retrace_spec_clean.py",
     "skypilot_tpu/infer/fixture_retrace_spec.py"),
    ("host-sync", "host_sync_spec_bad.py", "host_sync_spec_clean.py",
     "skypilot_tpu/infer/engine.py"),
    # Draft-model speculation + async pipeline (PR 14): the drafter's
    # jitted rollout/lockstep-sync shape and the DraftEngine hot path
    # (infer/draft.py scope) are guarded like the verify shape.
    ("retrace-safety", "retrace_draft_bad.py",
     "retrace_draft_clean.py",
     "skypilot_tpu/infer/fixture_retrace_draft.py"),
    ("host-sync", "host_sync_draft_bad.py",
     "host_sync_draft_clean.py",
     "skypilot_tpu/infer/draft.py"),
    # Span-bucketed attention (PR 9): the static-span gather and the
    # host-side bucket/headroom selection are guarded like the paged
    # and spec shapes before them.
    ("retrace-safety", "retrace_span_bad.py", "retrace_span_clean.py",
     "skypilot_tpu/infer/fixture_retrace_span.py"),
    ("host-sync", "host_sync_span_bad.py", "host_sync_span_clean.py",
     "skypilot_tpu/infer/engine.py"),
    # Flight recorder (PR 10): burst records and the compile-watch
    # wrapper are host-only — a fetch on the record path stalls the
    # pipeline the recorder observes.
    ("host-sync", "host_sync_flight_bad.py",
     "host_sync_flight_clean.py",
     "skypilot_tpu/observability/flight.py"),
    # Multi-tenant QoS (PR 11): the DRR reorder / admission check run
    # per admission pass / per HTTP request — pure host bookkeeping;
    # a device fetch to rank tenants stalls the admission pipeline.
    ("host-sync", "host_sync_qos_bad.py", "host_sync_qos_clean.py",
     "skypilot_tpu/infer/qos.py"),
    # Paged-attention kernel (PR 12): Pallas kernel bodies are
    # reachable through their functools.partial wrappers (the
    # pallas_call idiom; retrace v3) and the per-tenant KV quota /
    # charge bookkeeping joined the host-sync engine scope (v7).
    ("retrace-safety", "retrace_kernel_bad.py",
     "retrace_kernel_clean.py",
     "skypilot_tpu/infer/fixture_retrace_kernel.py"),
    ("host-sync", "host_sync_kernel_bad.py",
     "host_sync_kernel_clean.py",
     "skypilot_tpu/infer/engine.py"),
    # Multi-LoRA adapter catalog (PR 13): the per-slot (A, B) gather
    # is guarded like the paged/span/spec shapes (adapter identity
    # must stay device DATA — concretizing it bakes one fine-tune
    # into the program), and the catalog claim/retire bookkeeping
    # joined the host-sync engine scope (v8).
    ("retrace-safety", "retrace_adapter_bad.py",
     "retrace_adapter_clean.py",
     "skypilot_tpu/infer/fixture_retrace_adapter.py"),
    ("host-sync", "host_sync_adapter_bad.py",
     "host_sync_adapter_clean.py",
     "skypilot_tpu/infer/engine.py"),
    # Device-truth attribution (PR 16): the calibrator tick/estimate
    # path, the HBM ledger and the roofline cost model ride every
    # dispatch / flight record — host-only by design, the sampled
    # calibration bracket being the one baselined sync (v10).
    ("host-sync", "host_sync_attr_bad.py",
     "host_sync_attr_clean.py",
     "skypilot_tpu/observability/attribution.py"),
    # Training goodput (PR 18): step_start/step_end bracket every
    # train step and the anomaly watchdog rides the loop's own loss
    # fetch — wall clocks and host dicts only; a device fetch inside
    # the ledger stalls the step it is measuring (v12).
    ("host-sync", "host_sync_goodput_bad.py",
     "host_sync_goodput_clean.py",
     "skypilot_tpu/observability/goodput.py"),
    ("lock-discipline", "locks_bad.py", "locks_clean.py",
     "skypilot_tpu/utils/fixture_locks.py"),
    ("typed-errors", "typed_errors_bad.py", "typed_errors_clean.py",
     "skypilot_tpu/server/fixture_typed.py"),
    ("bare-print", "bare_print_bad.py", "bare_print_clean.py",
     "skypilot_tpu/runtime/fixture_print.py"),
    ("adhoc-retry", "adhoc_retry_bad.py", "adhoc_retry_clean.py",
     "skypilot_tpu/fixture_retry.py"),
]


@pytest.mark.parametrize("checker,bad,clean,rel", _GOLDEN,
                         ids=[g[0] for g in _GOLDEN])
def test_golden_fixture(checker, bad, clean, rel):
    _assert_golden(checker, bad, rel)
    _, clean_findings = _run_fixture(checker, clean, rel)
    assert not clean_findings, (
        f"{clean}: clean twin produced findings: "
        f"{[f.format() for f in clean_findings]}")


def test_golden_metric_catalog(tmp_path):
    """Project-scope: needs a synthetic docs catalog at the root."""
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "| skytpu_documented_total | ... |\n"
        "| skytpu_documented_seconds | ... |\n"
        "| skytpu_fleet_scrape_up | ... |\n"
        "| skytpu_fleet_merge_errors | ... |\n")
    rel = "skypilot_tpu/observability/fixture_metrics.py"
    _assert_golden("metric-catalog", "metric_catalog_bad.py", rel,
                   root=str(tmp_path))
    _, clean_findings = _run_fixture(
        "metric-catalog", "metric_catalog_clean.py", rel,
        root=str(tmp_path))
    assert not clean_findings, [f.format() for f in clean_findings]


def test_retrace_unreachable_function_not_flagged():
    """`never_jitted` concretizes freely: no root reaches it."""
    ctx, findings = _run_fixture(
        "retrace-safety", "retrace_bad.py",
        "skypilot_tpu/infer/fixture_retrace.py")
    lines_with = [f.line for f in findings]
    src_line = next(i for i, l in enumerate(ctx.lines, 1)
                    if "never_jitted" in l)
    assert all(ln <= src_line for ln in lines_with)


def test_host_sync_out_of_scope_method_not_flagged():
    _, findings = _run_fixture("host-sync", "host_sync_bad.py",
                               "skypilot_tpu/infer/engine.py")
    assert not any("unscoped_helper" in f.ident for f in findings)


def test_bare_print_out_of_scope_dir():
    """The same file outside the daemon dirs produces nothing."""
    checker = analysis_core.get_checker("bare-print")
    ctx = _fixture_ctx("bare_print_bad.py",
                       "skypilot_tpu/client/fixture_print.py")
    assert checker.check_file(ctx) == []


# ---------------------------------------------------------------------------
# 3. Framework mechanics on a synthetic mini-tree.

def _mini_tree(tmp_path):
    root = tmp_path / "repo"
    pkg = root / "skypilot_tpu" / "runtime"
    pkg.mkdir(parents=True)
    mod = pkg / "daemon.py"
    mod.write_text('def tick():\n    print("hi")\n')
    return str(root), str(mod)


def _run_mini(root, **kw):
    return analysis.run(root=root, checkers=["bare-print"], **kw)


# Cache tests run the FULL suite (a checker subset deliberately never
# touches the cache — see test_checker_subset_run_never_touches_cache).

def _prints(res):
    return [f for f in res.findings if f.checker == "bare-print"]


def test_cache_hit_and_content_invalidation(tmp_path):
    root, mod = _mini_tree(tmp_path)
    cpath = str(tmp_path / "cache.json")
    r1 = analysis.run(root=root, cache_path=cpath)
    assert len(_prints(r1)) == 1 and r1.files_from_cache == 0
    r2 = analysis.run(root=root, cache_path=cpath)
    assert r2.files_from_cache == 1
    assert [f.to_dict() for f in _prints(r2)] == \
        [f.to_dict() for f in _prints(r1)]
    # Edit the file (force a different mtime too): cache must miss.
    with open(mod, "w") as f:
        f.write('def tick():\n    print("hi")\n    print("again")\n')
    os.utime(mod, (time.time() + 5, time.time() + 5))
    r3 = analysis.run(root=root, cache_path=cpath)
    assert r3.files_from_cache == 0
    assert len(_prints(r3)) == 2


def test_cache_touch_without_edit_rehashes_not_rescans(tmp_path):
    """mtime changed + content identical => the sha check reuses the
    cached result (a `touch` or fresh checkout must not go cold)."""
    root, mod = _mini_tree(tmp_path)
    cpath = str(tmp_path / "cache.json")
    analysis.run(root=root, cache_path=cpath)
    os.utime(mod, (time.time() + 60, time.time() + 60))
    r = analysis.run(root=root, cache_path=cpath)
    assert r.files_from_cache == 1


def test_cache_invalidated_by_checker_version(tmp_path, monkeypatch):
    root, _ = _mini_tree(tmp_path)
    cpath = str(tmp_path / "cache.json")
    analysis.run(root=root, cache_path=cpath)
    checker = analysis_core.get_checker("bare-print")
    monkeypatch.setattr(type(checker), "version",
                        checker.version + 1)
    r = analysis.run(root=root, cache_path=cpath)
    assert r.files_from_cache == 0          # digest changed: cold
    assert len(_prints(r)) == 1


def test_corrupt_cache_degrades_to_cold_run(tmp_path):
    root, _ = _mini_tree(tmp_path)
    cpath = str(tmp_path / "cache.json")
    with open(cpath, "w") as f:
        f.write("{not json")
    r = analysis.run(root=root, cache_path=cpath)
    assert len(_prints(r)) == 1


def test_baseline_update_round_trip(tmp_path):
    root, mod = _mini_tree(tmp_path)
    bpath = os.path.join(root, "lint_baseline.json")
    r1 = _run_mini(root, use_cache=False)
    assert r1.new and not r1.clean
    entries = baseline_lib.updated(r1.findings, {})
    # The TODO placeholder is rejected by the gate until justified.
    assert all(e["justification"].startswith("TODO")
               for e in entries.values())
    for e in entries.values():
        e["justification"] = "fixture: intentional"
    baseline_lib.save(bpath, entries)
    r2 = _run_mini(root, use_cache=False)
    assert r2.clean and not r2.new
    # Justifications survive a second update.
    entries2 = baseline_lib.updated(r2.findings,
                                    baseline_lib.load(bpath))
    assert all(e["justification"] == "fixture: intentional"
               for e in entries2.values())
    # Fixing the violation makes the entry stale -> gate fails again.
    with open(mod, "w") as f:
        f.write("def tick():\n    return 1\n")
    r3 = _run_mini(root, use_cache=False)
    assert r3.stale and not r3.clean


def test_baseline_count_budget(tmp_path):
    """N grandfathered hits; the N+1th still fails."""
    root, mod = _mini_tree(tmp_path)
    bpath = os.path.join(root, "lint_baseline.json")
    r1 = _run_mini(root, use_cache=False)
    entries = baseline_lib.updated(r1.findings, {})
    for e in entries.values():
        e["justification"] = "fixture: one print allowed"
    baseline_lib.save(bpath, entries)
    with open(mod, "a") as f:
        f.write('\ndef tock():\n    print("extra")\n')
    r2 = _run_mini(root, use_cache=False)
    assert len(r2.new) == 1 and not r2.clean


def test_partial_run_skips_stale_detection(tmp_path):
    root, _ = _mini_tree(tmp_path)
    bpath = os.path.join(root, "lint_baseline.json")
    baseline_lib.save(bpath, {
        "bare-print::skypilot_tpu/runtime/gone.py::print":
            {"count": 1, "justification": "file was deleted"}})
    full = _run_mini(root, use_cache=False)
    assert full.stale
    part = _run_mini(root, use_cache=False,
                     files=["skypilot_tpu/runtime/daemon.py"])
    assert part.partial and not part.stale
    assert len(part.findings) == 1          # still finds the print


def test_unjustified_baseline_fails_gate(tmp_path):
    root, _ = _mini_tree(tmp_path)
    bpath = os.path.join(root, "lint_baseline.json")
    r1 = _run_mini(root, use_cache=False)
    baseline_lib.save(bpath, baseline_lib.updated(r1.findings, {}))
    r2 = _run_mini(root, use_cache=False)
    assert r2.unjustified and not r2.clean
    # Justification checks are subset-independent: a partial
    # (--changed) run must fail on them too, not pass vacuously.
    r3 = _run_mini(root, use_cache=False,
                   files=["skypilot_tpu/runtime/daemon.py"])
    assert r3.partial and r3.unjustified and not r3.clean


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    root, mod = _mini_tree(tmp_path)
    with open(mod, "w") as f:
        f.write("def broken(:\n")
    r = _run_mini(root, use_cache=False)
    assert any(f.checker == "framework" and f.rule == "parse-error"
               for f in r.findings)


def test_finding_keys_are_line_stable(tmp_path):
    """Shifting code down must not change baseline identity."""
    root, mod = _mini_tree(tmp_path)
    k1 = _run_mini(root, use_cache=False).findings[0].key
    src = open(mod).read()
    with open(mod, "w") as f:
        f.write("# a new leading comment\n\n" + src)
    r = _run_mini(root, use_cache=False)
    assert r.findings[0].key == k1
    assert r.findings[0].line > 2


# ---------------------------------------------------------------------------
# CLI.

def test_cli_lint_json_clean():
    from click.testing import CliRunner

    from skypilot_tpu.client import cli as cli_mod
    res = CliRunner().invoke(cli_mod.cli, ["lint", "--json",
                                           "--no-cache"])
    assert res.exit_code == 0, res.output
    payload = json.loads(res.output)
    assert payload["clean"] is True
    assert payload["findings"] == []
    assert payload["baselined"] >= 20


def test_project_results_cached_and_invalidated_by_any_edit(tmp_path):
    """Project-scope findings are cached under a whole-tree content
    digest: a warm unchanged run reuses them; editing ANY file — or a
    checker's extra input like the docs catalog — recomputes."""
    root, mod = _mini_tree(tmp_path)
    docs = os.path.join(root, "docs")
    os.makedirs(docs)
    cat = os.path.join(docs, "observability.md")
    with open(cat, "w") as f:
        f.write("skytpu_fleet_scrape_up skytpu_fleet_merge_errors\n")
    cpath = str(tmp_path / "cache.json")

    def degenerate(res):
        return [f for f in res.findings
                if f.rule == "scan-degenerate"]

    r1 = analysis.run(root=root, cache_path=cpath)
    assert degenerate(r1)                   # mini tree: no metrics
    data1 = json.load(open(cpath))
    assert data1["files"]["//project"]["findings"]
    r2 = analysis.run(root=root, cache_path=cpath)
    assert degenerate(r2)                   # served from the cache
    # Editing any tree file invalidates the project digest.
    with open(mod, "a") as f:
        f.write("X = 1\n")
    r3 = analysis.run(root=root, cache_path=cpath)
    assert degenerate(r3)
    d3 = json.load(open(cpath))["files"]["//project"]["digest"]
    assert d3 != data1["files"]["//project"]["digest"]
    # Editing an extra input (the docs catalog) invalidates too.
    with open(cat, "a") as f:
        f.write("more\n")
    analysis.run(root=root, cache_path=cpath)
    d4 = json.load(open(cpath))["files"]["//project"]["digest"]
    assert d4 != d3


def test_checker_subset_run_never_touches_cache(tmp_path):
    """A --checker run's digest covers only the subset; writing it
    would clobber the full run's warm cache (and vice versa)."""
    root, _ = _mini_tree(tmp_path)
    cpath = str(tmp_path / "cache.json")
    r = _run_mini(root, cache_path=cpath)     # checkers subset
    assert len(r.findings) == 1
    assert not os.path.exists(cpath)
    full = analysis.run(root=root, cache_path=cpath)
    assert os.path.exists(cpath)
    before = open(cpath).read()
    _run_mini(root, cache_path=cpath)
    assert open(cpath).read() == before       # untouched
    again = analysis.run(root=root, cache_path=cpath)
    assert again.files_from_cache == full.files_scanned


def test_cli_baseline_update_refused_on_subset_runs():
    from click.testing import CliRunner

    from skypilot_tpu.client import cli as cli_mod
    for args in (["lint", "--baseline-update", "--changed"],
                 ["lint", "--baseline-update", "--checker",
                  "bare-print"],
                 ["lint", "--baseline-update",
                  "skypilot_tpu/utils/db.py"]):
        res = CliRunner().invoke(cli_mod.cli, args)
        assert res.exit_code != 0, args
        assert "full run" in res.output


def test_cli_lint_nonexistent_path_errors():
    from click.testing import CliRunner

    from skypilot_tpu.client import cli as cli_mod
    res = CliRunner().invoke(
        cli_mod.cli, ["lint", "/tmp/does-not-exist-xyz.py",
                      "--no-cache"])
    assert res.exit_code != 0
    assert "resolve" in res.output


def test_cli_lint_checker_filter_unknown():
    from click.testing import CliRunner

    from skypilot_tpu.client import cli as cli_mod
    res = CliRunner().invoke(
        cli_mod.cli, ["lint", "--checker", "no-such-checker"])
    assert res.exit_code != 0
    assert "no-such-checker" in res.output


def test_all_five_checker_families_registered():
    names = {c.name for c in analysis_core.all_checkers()}
    assert {"retrace-safety", "host-sync", "lock-discipline",
            "typed-errors", "bare-print", "adhoc-retry",
            "metric-catalog"} <= names
