# Golden fixture: seeded host-sync violations on the training-goodput
# step-ledger path (PR 18). step_start/step_end bracket EVERY train
# step and the watchdog's observe rides every logging tick — all pure
# host clock/dict arithmetic over values the loop already fetched;
# consulting the device to attribute time stalls the very step the
# ledger is measuring. Checked as if it were
# skypilot_tpu/observability/goodput.py (the goodput step-ledger
# scope). Never imported.
import numpy as np


class GoodputRecorder:
    def step_start(self, step):
        self._step_t0 = float(self._device_clock)    # expect: host-sync
        self._phases = {}

    def step_end(self, tokens=0, loss=None, grad_norm=None):
        self._last_state.block_until_ready()         # expect: host-sync
        wall = np.asarray(self._wall_dev)            # expect: host-sync
        self._buckets["productive"] += wall[0]
        self.recorder.record("train_step", dur_s=wall[0], toks=tokens)


class AnomalyWatchdog:
    def observe(self, step, loss, grad_norm=None):
        cur = loss.item()                            # expect: host-sync
        if grad_norm is not None:
            cur = max(cur, float(grad_norm))         # expect: host-sync
        self._last = cur
        return None
