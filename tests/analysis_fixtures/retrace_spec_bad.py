# Golden fixture: seeded retrace-safety violations in the K-position
# speculative verify shape. Checked as if it lived at
# skypilot_tpu/infer/ (a jit-root directory). Never imported.
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def verify_accept(cache, draft, n_draft, toks):
    k = draft.shape[1]
    match = (toks[:, :k] == draft) & (
        jnp.arange(k)[None, :] < n_draft[:, None])
    if match.any():                           # expect: traced-branch
        match = match & match
    n_match = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                      axis=1)
    first = int(n_match[0])                   # expect: concretize
    host = np.asarray(n_match)                # expect: host-transfer
    accepted = jnp.zeros(jnp.sum(n_match))    # expect: dynamic-shape
    return n_match, first, host, accepted
