# Golden fixture: seeded retrace-safety violations in the draft
# rollout/lockstep shape. Checked as if it lived at
# skypilot_tpu/infer/ (a jit-root directory). Never imported.
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def draft_rollout_sync(cache, active, lengths, tokens):
    # The lockstep sync is data-only; branching on the mask or
    # concretizing a length would retrace per round.
    if active.any():                          # expect: traced-branch
        lengths = lengths + 0
    new_len = cache["length"] + active.astype(jnp.int32)
    rows = int(new_len[0])                    # expect: concretize
    host = np.asarray(new_len)                # expect: host-transfer
    kept = jnp.zeros(jnp.sum(new_len))        # expect: dynamic-shape
    out = dict(cache)
    out["length"] = jnp.where(active, lengths, cache["length"])
    out["last_token"] = jnp.where(active, tokens,
                                  cache["last_token"])
    return out, rows, host, kept
