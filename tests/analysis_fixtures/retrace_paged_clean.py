# Clean twin: the paged block-gather attention pattern done right —
# static shapes from .shape, gather clamps + mask instead of branches,
# scatter through the table. Never imported.
import jax
import jax.numpy as jnp


@jax.jit
def paged_attend(cache, table, length):
    nb = table.shape[1] - 1
    bl = cache.shape[1]
    rows = nb * bl
    batch = table.shape[0]
    pages = cache[table[:, :nb]].reshape(batch, rows)
    valid = jnp.arange(rows)[None, :] < length[:, None]
    return jnp.where(valid, pages, 0.0)


@jax.jit
def paged_scatter(cache, table, rows_new, pos):
    bl = cache.shape[1]
    blk = table[jnp.arange(table.shape[0]), pos // bl]
    return cache.at[blk, pos % bl].set(rows_new)
