# Clean twin: typed errors with a typed_error body.


class PromptTooLongError(ValueError):
    def __init__(self, n, cap):
        super().__init__(f"{n} > {cap}")
        self.typed_error = {"type": "prompt_too_long",
                            "prompt_len": n, "max_prompt_len": cap}


def handle(req):
    if req is None:
        raise ValueError("no request")
    if len(req) > 128:
        raise PromptTooLongError(len(req), 128)
    return req
