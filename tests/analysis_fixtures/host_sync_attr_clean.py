# Clean twin: device-truth attribution done right — the sampling
# decision is a host counter under a lock, the EWMA consumes a
# monotonic-clock delta the calibration bracket already measured, the
# ledger is recomputed from host bookkeeping (counts x bytes), and the
# roofline prices a dispatch from program-dict scalars. The device is
# consulted only by the bracket itself (the one baselined sync).
# Never imported.
import time


class DeviceTimeCalibrator:
    def tick(self, key):
        if self.every <= 0:
            return False
        with self._lock:
            c = self._counts.get(key, 0) + 1
            self._counts[key] = c
        return c % self.every == 1

    def update(self, key, dev_s):
        with self._lock:
            prev = self._ewma.get(key)
            self._ewma[key] = (dev_s if prev is None
                               else prev + self.alpha * (dev_s - prev))
            self._stamp[key] = time.monotonic()

    def estimate(self, key):
        if key is None:
            return None
        with self._lock:
            return self._ewma.get(key)


class HbmLedger:
    def set_bytes(self, component, n):
        with self._lock:
            self._components[component] = max(n, 0)

    def total(self):
        with self._lock:
            return sum(self._components.values())


class Roofline:
    def record_cost(self, burst, program, n_slots, toks):
        span = program.get("span") or self.max_len
        flops = 2 * self.param_count * toks
        moved = (self.weight_bytes
                 + n_slots * span * self.kv_token_bytes
                 + toks * self.kv_token_bytes)
        return flops, moved
