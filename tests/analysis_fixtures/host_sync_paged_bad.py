# Golden fixture: seeded host-sync violations around the paged block
# table. Checked as if it were skypilot_tpu/infer/engine.py (the
# hot-loop scope). Never imported.
import numpy as np


class InferenceEngine:
    def dispatch_decode_burst(self, max_burst=8):
        table = self.table_device()
        first_block = int(table[0, 0])        # expect: host-sync
        host = np.asarray(table)              # expect: host-sync
        table.block_until_ready()             # expect: host-sync
        used = self.cache["length"].item()    # expect: host-sync
        return first_block, host, used
