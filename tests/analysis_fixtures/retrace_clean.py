# Clean twin of retrace_bad.py: the same shapes of code written
# trace-safely — static branches, static shapes, no host pulls.
import functools
import math

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("k",))
def decode(cache, toks, *, k):
    if k > 4:                         # static argname: trace constant
        toks = toks + 1
    n = int(toks.shape[0])            # .shape is static under trace
    cap = math.ceil(n / 2)
    pad = jnp.zeros((toks.shape[0], int(cap)))
    out = jnp.where(toks > 0, toks, pad[:, 0])
    return _helper(cache, out), n


def _helper(cache, toks):
    if cache is None:                 # is-None: static
        return toks
    return jnp.maximum(toks, 0)
