# Golden fixture: seeded host-sync violations on the draft-model
# pipeline path. Checked as if it were skypilot_tpu/infer/draft.py
# (the DraftEngine hot-loop scope). Never imported.
import numpy as np


class DraftEngine:
    def rollout(self, slots, k):
        # The async predraft must DISPATCH only — fetching here
        # serializes the draft behind the verify instead of
        # overlapping it (the pipeline's whole point).
        toks = self._dispatch_rollout(slots, k)
        toks.block_until_ready()                           # expect: host-sync
        self._pending_roll = (toks, slots, k)

    def _sync_slot(self, slot, st, ctx, fix):
        # Lockstep sync is pure host bookkeeping over the token
        # mirror; peeking at device lengths per slot per round drains
        # the dispatch pipeline once per spec burst.
        rows = int(self.cache["length"][slot])             # expect: host-sync
        pending = self.cache["last_token"].item()          # expect: host-sync
        return [rows, pending]

    def _dispatch_sync(self, fix):
        probe = np.asarray(self.cache["length"])           # expect: host-sync
        return probe
