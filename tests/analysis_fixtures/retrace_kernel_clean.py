# Clean twin: the Pallas paged-attention kernel done right — the span
# sweep is a STATIC argument (one compiled program per ladder rung,
# selected on the host), the block table stays a device operand
# (scalar prefetch routes it; nothing is pulled to the host), and the
# kernel body — reachable through its ``functools.partial`` wrapper —
# is pure array math. Never imported.
import functools

import jax
import jax.numpy as jnp


def _kernel(table_ref, q_ref, k_ref, o_ref, *, span_blocks):
    q = q_ref[...]
    k = k_ref[...]
    s = jnp.einsum("rk,mk->rm", q, k)
    o_ref[...] = jnp.where(s > 0, s, 0.0)


def paged_attn(q, k_pool, table, lengths, *, span_blocks):
    bl = k_pool.shape[2]                          # static: block rows
    kernel = functools.partial(_kernel, span_blocks=span_blocks)
    valid = (jnp.arange(span_blocks * bl)[None, :]
             < lengths[:, None])
    return kernel, valid


@functools.partial(jax.jit, static_argnames=("span_blocks",))
def decode_step(cache, table, lengths, *, span_blocks):
    return paged_attn(cache["q"], cache["k"], table, lengths,
                      span_blocks=span_blocks)
