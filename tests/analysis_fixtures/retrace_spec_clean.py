# Clean twin: the K-position verify shape done right — K is static
# (one compiled program), acceptance is pure array math (masked match
# + cumprod, no python branch on traced values), rollback is a
# where() on the length vector, and the commit count stays on device.
# Never imported.
import jax
import jax.numpy as jnp


@jax.jit
def verify_accept(cache, draft, n_draft, toks, active):
    k = draft.shape[1]                        # static: draft is [B, k]
    match = (toks[:, :k] == draft) & (
        jnp.arange(k)[None, :] < n_draft[:, None])
    n_match = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                      axis=1)
    n_commit = jnp.where(active, n_match + 1, 0).astype(jnp.int32)
    length = cache["length"] + n_commit       # rollback = no advance
    batch = jnp.arange(draft.shape[0])
    last = jnp.where(active, toks[batch, n_match],
                     cache["last_token"])
    return dict(cache, length=length, last_token=last), n_commit
