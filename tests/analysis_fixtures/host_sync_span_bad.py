# Golden fixture: seeded host-sync violations on the span-selection /
# lazy-growth path. Span buckets and block headroom must come from
# HOST bookkeeping (request token lists, the numpy block table) —
# peeking at device lengths to pick a bucket would drain the dispatch
# pipeline once per burst. Checked as if it were
# skypilot_tpu/infer/engine.py (the hot-loop scope). Never imported.
import numpy as np


class InferenceEngine:
    def _span_groups(self, width):
        lengths = np.asarray(self.cache["length"])  # expect: host-sync
        groups = {}
        for slot in self.slot_req:
            rows = int(self.cache["length"][slot])  # expect: host-sync
            groups.setdefault(self._span_for(rows), []).append(slot)
        return sorted(groups.items()), lengths

    def _ensure_headroom(self, slot, req, need_rows):
        used = self.cache["length"].item()          # expect: host-sync
        return used < need_rows
