# Clean twin: block-table bookkeeping without device fetches — the
# authoritative table is host numpy; device programs only get
# dispatched. Never imported.


class InferenceEngine:
    def dispatch_decode_burst(self, max_burst=8):
        # Host-side numpy table ops: slicing, masking, tolist — none
        # of these touch the device.
        row = self.block_table[0]
        shared = row[row < self.n_kv_blocks].tolist()
        need = len(shared)
        self.cache, self.rng, toks = self._decode_burst_fn(
            self.params, self.cache, self.rng, self.table_device(),
            k=max_burst)
        return need, toks
