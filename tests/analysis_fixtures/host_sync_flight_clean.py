# Clean twin: the flight recorder done right — records are built from
# values that already live on the host (ints, floats, lists the engine
# bookkeeping maintains), the compile-watch wrapper only takes wall
# timestamps around the dispatch, and the device is never consulted.
# Never imported.
import time


class FlightRecorder:
    def record(self, burst, **fields):
        rec = {"kind": "flight", "burst": burst,
               "ts_ms": int(time.time() * 1000)}
        rec.update(fields)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._records.append(rec)

    def tail(self, n=None):
        with self._lock:
            recs = list(self._records)
        return recs[-n:] if n else recs


class CompileWatch:
    def wrap(self, name, fn, static_argnames=()):
        def wrapped(*args, **kwargs):
            key = name + str([kwargs.get(a) for a in static_argnames])
            with self._lock:
                hit = key in self._programs
            if hit:
                return fn(*args, **kwargs)
            t0 = time.monotonic()
            out = fn(*args, **kwargs)
            with self._lock:
                self._programs[key] = time.monotonic() - t0
            return out
        return wrapped
