# Golden fixture: daemon prints (checked as if in skypilot_tpu/
# runtime/). Never imported.
import sys


def tick(err):
    print(f"heartbeat failed: {err}")        # expect: bare-print
    print("retrying", file=sys.stderr)       # expect: bare-print
