# Golden fixture: seeded retrace-safety violations. Checked as if it
# lived at skypilot_tpu/infer/ (a jit-root directory). Never imported.
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k",))
def decode(cache, toks, *, k):
    if (toks > 0).any():                  # expect: traced-branch
        toks = toks + 1
    n = int(toks[0])                      # expect: concretize
    host = np.asarray(toks)               # expect: host-transfer
    pad = jnp.zeros(jnp.sum(toks))        # expect: dynamic-shape
    return _helper(cache, toks), n, host, pad


def _helper(cache, toks):
    # Reached from the jitted root through the call graph.
    return toks.item()                    # expect: concretize


def never_jitted(x):
    # Unreachable from any root: host code may concretize freely.
    return int(x[0])
