# Clean twin: retry through the policy module; narrow catches.
from skypilot_tpu.utils import retry


def flaky(op):
    policy = retry.RetryPolicy(max_attempts=3, retry_on=(OSError,))
    try:
        return retry.call(op, policy=policy, name="fixture")
    except OSError as e:
        return {"error": str(e)}


def cleanup(op):
    with_lock = None
    try:
        op.cleanup()
    except OSError:
        pass   # narrow type: allowed
    return with_lock
