# Clean twin: prefixed and documented.
from skypilot_tpu.observability import metrics

OK = metrics.counter("skytpu_documented_total", "in the catalog")
ALSO_OK = metrics.histogram("skytpu_documented_seconds", "also there")
