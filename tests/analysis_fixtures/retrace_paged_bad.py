# Golden fixture: seeded retrace-safety violations in the paged
# block-gather attention shape. Checked as if it lived at
# skypilot_tpu/infer/ (a jit-root directory). Never imported.
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def paged_attend(cache, table, length):
    nb = table.shape[1] - 1
    pages = cache[table[:, :nb]]              # gather: fine
    if (table >= 0).any():                    # expect: traced-branch
        pages = pages * 2
    first = int(table[0, 0])                  # expect: concretize
    host_tbl = np.asarray(table)              # expect: host-transfer
    live = jnp.zeros(jnp.sum(length))         # expect: dynamic-shape
    return pages, first, host_tbl, live
