# Golden fixture: seeded lock-discipline violations. Never imported.
import json
import threading
import time

_lock = threading.Lock()
_ring = []                              # guarded-by: _lock


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []                  # guarded-by: _lock
        self._buf.append("init ok")     # __init__ is construction

    def ok(self, rec):
        with self._lock:
            self._buf.append(rec)

    def bad_append(self, rec):
        self._buf.append(rec)           # expect: guarded-mutation

    def bad_swap(self, rec):
        out, self._buf = self._buf, []  # expect: guarded-mutation
        return out

    def bad_flush(self):
        with self._lock:
            return json.dumps(self._buf)  # expect: blocking-under-lock


def record(rec):
    _ring.append(rec)                   # expect: guarded-mutation


def drain_slowly():
    with _lock:
        time.sleep(0.1)                 # expect: blocking-under-lock
        del _ring[:]
