# Golden fixture: seeded host-sync violations on the speculative
# verify/accept path. Checked as if it were
# skypilot_tpu/infer/engine.py (the hot-loop scope). Never imported.
import numpy as np


class InferenceEngine:
    def _draft_for(self, req):
        # Drafting must be pure host work (the n-gram index); peeking
        # at device state per draft drains the pipeline every burst.
        pending = int(self.cache["last_token"][req.slot])  # expect: host-sync
        last = self.cache["length"].item()                 # expect: host-sync
        return [pending, last]

    def spec_decode_burst(self):
        self.cache, toks, n_commit = self._verify_fn(
            self.params, self.cache, self.draft, self.n_draft,
            self.active, self.table_device(), k=4)
        toks.block_until_ready()                           # expect: host-sync
        probe = np.asarray(self.cache["length"])           # expect: host-sync
        return probe
