# Clean twin: span selection and lazy growth done right — buckets
# come from host-tracked request state (prompt/token list lengths plus
# the in-flight count), headroom from the host numpy block table; the
# device is never consulted. Never imported.


class InferenceEngine:
    def _slot_rows(self, req):
        return (len(req.prompt) + len(req.tokens)
                + self._inflight_tokens)

    def _span_groups(self, width):
        groups = {}
        for slot, req in self.slot_req.items():
            rows = self._slot_rows(req)
            if not self._ensure_headroom(slot, req, rows + width):
                continue
            groups.setdefault(self._span_for(rows), []).append(slot)
        return sorted(groups.items())

    def _ensure_headroom(self, slot, req, need_rows):
        row = self.block_table[slot]
        have = len(row[row < self.n_kv_blocks])
        return have * self.kv_block >= need_rows
