# Clean twin: structured events reach the recorder AND stderr.
from skypilot_tpu.observability import tracing


def tick(err):
    tracing.add_event("skylet.heartbeat_failed",
                      {"error": str(err)}, echo=True)
