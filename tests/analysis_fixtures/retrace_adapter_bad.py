# Golden fixture: seeded retrace-safety violations in the multi-LoRA
# adapter-gather shape (PR 13) — the exact mistakes the adapter path
# invites: concretizing a traced adapter id to pick a pool slice in
# Python (bakes ONE adapter into the compiled program — the mixed
# batch silently serves the wrong fine-tune), branching on the traced
# id to skip the delta, and building the gather from a host-fetched
# aid vector. Checked as if it lived at skypilot_tpu/infer/ (a
# jit-root directory). Never imported.
import jax
import jax.numpy as jnp
import numpy as np


def _lora_delta(h, pool_a, pool_b, aid):
    slot = int(aid[0])                            # expect: concretize
    if (aid > 0).any():                           # expect: traced-branch
        a = pool_a[slot]
        u = jnp.einsum("bsd,dr->bsr", h, a)
        return jnp.einsum("bsr,rhk->bshk", u, pool_b[slot])
    return jnp.zeros_like(h)


def adapter_proj(h, pool, aid):
    host_aid = np.asarray(aid)                    # expect: host-transfer
    delta = _lora_delta(h, pool["a"], pool["b"], aid)
    return delta, host_aid


@jax.jit
def decode_step(cache, pool, aid):
    return adapter_proj(cache["x"], pool, aid)
