# Golden fixture: metric naming/catalog drift. Checked against a
# synthetic docs catalog that documents only skytpu_documented_total.
from skypilot_tpu.observability import metrics

OK = metrics.counter("skytpu_documented_total", "in the catalog")
BAD_PREFIX = metrics.counter(  # expect: bad-prefix, undocumented
    "prefixless_total", "x")
BAD_DOC = metrics.gauge(       # expect: undocumented
    "skytpu_not_in_docs", "x")
