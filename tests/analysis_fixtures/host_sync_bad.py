# Golden fixture: seeded host-sync violations. Checked as if it were
# skypilot_tpu/infer/engine.py (the hot-loop scope). Never imported.
import jax
import numpy as np


class InferenceEngine:
    def step_burst(self, max_burst=8):
        toks = self._decode_fn()
        toks.block_until_ready()          # expect: host-sync
        vals = np.asarray(toks)           # expect: host-sync
        first = int(toks[0])              # expect: host-sync
        loss = toks.item()                # expect: host-sync
        got = jax.device_get(toks)        # expect: host-sync
        return vals, first, loss, got

    def unscoped_helper(self, x):
        # Not a hot-loop method: fetches are allowed here.
        return np.asarray(x)
