# Golden fixture: generic raises on a request path (checked as if in
# skypilot_tpu/server/). Never imported.


def handle(req):
    if req is None:
        raise RuntimeError("no request")     # expect: generic-raise
    if req == "boom":
        raise Exception("opaque")            # expect: generic-raise
    if not isinstance(req, dict):
        raise ValueError("narrow builtins stay allowed")
    return req
