# Clean twin of host_sync_draft_bad.py: the same methods doing the
# same jobs with pure host bookkeeping — the draft path's one
# deliberate completion fetch lives in draft_batch/_apply_pending and
# is baselined with a justification, not seeded here. Never imported.
import numpy as np


class DraftEngine:
    def rollout(self, slots, k):
        # Dispatch only; the tokens land lazily at the next
        # draft_batch (the deferred, baselined fetch).
        live = [s for s in slots if s in self._state]
        if not live:
            return False
        toks = self._dispatch_rollout(live, k)
        self._pending_roll = (toks, live, k)
        return True

    def _sync_slot(self, slot, st, ctx, fix):
        # Host token mirror only — row validity is decided by
        # comparison against the committed context, never by a device
        # peek.
        v = st.confirmed
        limit = min(len(st.toks), len(ctx) - 1)
        while v < limit and st.toks[v] == ctx[v]:
            v += 1
        del st.toks[v:]
        fix[slot] = (len(ctx) - 1, ctx[-1])
        return []

    def _dispatch_sync(self, fix):
        active = np.zeros((self.n_slots + 1,), bool)
        for slot in fix:
            active[slot] = True
        self.cache = self._sync_fn(self.cache, active)
