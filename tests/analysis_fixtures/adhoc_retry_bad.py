# Golden fixture: hand-rolled retry + broad swallow. Never imported.
import time


def flaky(op):
    for _ in range(3):
        try:
            return op()
        except OSError:
            time.sleep(1.0)               # expect: sleep-in-except
    try:
        op.cleanup()
    except Exception:                     # expect: except-pass
        pass
