# Clean twin: the per-tenant KV-block quota / charge bookkeeping done
# right — the charge is counted from the host numpy block table, the
# quota check from host-tracked request state and the tenant counter
# dict; the device is never consulted. Never imported.


class InferenceEngine:
    def _sync_kv_charge(self, slot, tenant=None):
        row = self.block_table[slot]
        have = len(row[row < self.n_kv_blocks])
        if tenant is not None and have:
            self._slot_kv_charge[slot] = (tenant, have)
        else:
            self._slot_kv_charge.pop(slot, None)

    def _kv_quota_blocked(self, req):
        need = self._need_blocks(
            req, len(req.prompt) + len(req.tokens))
        used = self._tenant_kv.get(req.tenant, 0)
        return used + need > self._kv_quota(req.tenant)
