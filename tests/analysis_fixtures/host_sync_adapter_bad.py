# Golden fixture: seeded host-sync violations on the adapter-catalog
# claim/retire path (PR 13). Acquire/release and the per-slot
# adapter-id bookkeeping run at EVERY claim and retirement — they must
# read host state (the registry dict, pin counters, the numpy aid
# array); fetching the device aid vector or pool state to pick a slot
# would stall admission itself. Checked as if it were
# skypilot_tpu/infer/engine.py (the hot-loop scope). Never imported.
import numpy as np


class InferenceEngine:
    def _acquire_adapter(self, req):
        ids = np.asarray(self._aid_dev)              # expect: host-sync
        slot = int(self.adapters.pool["wq"]["a"][0, 0, 0, 0])  # expect: host-sync
        req.adapter_slot = slot
        return ids

    def _set_slot_adapter(self, slot, pool_slot):
        cur = self._aid_dev[slot].item()             # expect: host-sync
        if cur != pool_slot:
            self.adapter_ids[slot] = pool_slot
            self._aid_dirty = True
