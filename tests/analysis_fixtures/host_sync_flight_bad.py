# Golden fixture: seeded host-sync violations on the flight-recorder
# path. A burst record is assembled from HOST bookkeeping (request
# token lists, host timestamps, static program args) — fetching the
# burst's device arrays to "enrich" the record would drain the
# dispatch pipeline once per burst, turning the observer into the
# stall it exists to diagnose. Checked as if it were
# skypilot_tpu/observability/flight.py (the recorder scope). Never
# imported.
import numpy as np


class FlightRecorder:
    def record(self, burst, toks_dev=None, **fields):
        toks = np.asarray(toks_dev)                # expect: host-sync
        fields["toks"] = int(toks_dev.sum())       # expect: host-sync
        with self._lock:
            self._records.append({"burst": burst, **fields,
                                  "n": len(toks)})


class CompileWatch:
    def wrap(self, name, fn, static_argnames=()):
        def wrapped(*args, **kwargs):
            out = fn(*args, **kwargs)
            out[0]["length"].block_until_ready()   # expect: host-sync
            return out
        return wrapped
