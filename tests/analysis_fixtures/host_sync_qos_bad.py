# Golden fixture: seeded host-sync violations on the QoS path. The
# DRR reorder runs on the engine loop before EVERY admission pass and
# preemption-by-eviction is a block-table edit — both must work purely
# from host state (request token lists, refcounts, token buckets).
# Consulting the device to rank tenants or pick a victim would stall
# the very admission pipeline QoS schedules. Checked as if it were
# skypilot_tpu/infer/qos.py (the scheduler scope). Never imported.
import numpy as np


class FairScheduler:
    def reorder(self, waiting):
        # "Smarter" fairness by live device occupancy: a fetch per
        # admission pass.
        rows = np.asarray(self.cache["length"])      # expect: host-sync
        order = sorted(waiting, key=lambda r: rows[r.slot or 0])
        waiting.clear()
        waiting.extend(order)

    def request_cost(self, req):
        # Costing by the slot's DEVICE length instead of the host
        # token lists.
        return int(self.cache["length"][req.slot])   # expect: host-sync


class AdmissionController:
    def admit(self, tenant, depth=None):
        load = self.slots_active_dev.item()          # expect: host-sync
        if load > self.cfg.max_waiting:
            raise OverloadedError(load, self.cfg.max_waiting)


class OverloadedError(Exception):
    def __init__(self, depth, max_waiting):
        super().__init__(f"{depth} > {max_waiting}")
