# Golden fixture: seeded retrace-safety violations in the Pallas
# paged-attention kernel shape — the exact mistakes the kernel path
# invites: deriving the span sweep from TRACED lengths instead of
# taking it as a static argument, pulling the block table to the host
# inside the wrapper, and concretizing/branching INSIDE the kernel
# body (which is only reachable through the ``functools.partial``
# the pallas_call idiom wraps it in — the v3 reachability extension).
# Checked as if it lived at skypilot_tpu/infer/ (a jit-root
# directory). Never imported.
import functools

import jax
import jax.numpy as jnp
import numpy as np


def _kernel(table_ref, q_ref, k_ref, o_ref, *, span_blocks):
    j = int(table_ref[0])                         # expect: concretize
    if (q_ref[...] > 0).any():                    # expect: traced-branch
        o_ref[...] = q_ref[...] + j


def paged_attn(q, k_pool, table, lengths):
    span_blocks = int(jnp.max(lengths))           # expect: concretize
    host_table = np.asarray(table)                # expect: host-transfer
    kernel = functools.partial(_kernel, span_blocks=span_blocks)
    cols = jnp.arange(jnp.max(lengths))           # expect: dynamic-shape
    return kernel, host_table, cols


@jax.jit
def decode_step(cache, table, lengths):
    return paged_attn(cache["q"], cache["k"], table, lengths)
