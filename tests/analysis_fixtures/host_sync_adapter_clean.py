# Clean twin: the adapter-catalog claim/retire bookkeeping done
# right — pins, residency and the per-slot adapter ids are host dicts
# and a host numpy array; the device copy is only WRITTEN (cached,
# dirty-tracked), never read back. Never imported.


class InferenceEngine:
    def _acquire_adapter(self, req):
        if self.adapters is None or req.adapter is None:
            req.adapter_slot = 0
            return "ok"
        slot = self.adapters.acquire(req.adapter)
        if slot is None:
            return "stall"
        req.adapter_slot = slot
        req.adapter_pinned = slot > 0
        return "ok"

    def _set_slot_adapter(self, slot, pool_slot):
        if self.adapter_ids[slot] != pool_slot:
            self.adapter_ids[slot] = pool_slot
            self._aid_dirty = True
