# Golden fixture: seeded host-sync violations on the device-truth
# attribution path. The calibrator/ledger/roofline ride every dispatch
# and every flight record from HOST state (monotonic timestamps,
# program-dict scalars, allocator counts x bytes) — the ONE legal sync
# is the sampled calibration bracket itself. Anything else here
# (deciding WHETHER to sample by fetching a device counter, costing a
# burst by reading its arrays) turns the attribution layer into the
# very stall it exists to measure. Checked as if it were
# skypilot_tpu/observability/attribution.py (the attribution scope).
# Never imported.
import numpy as np


class DeviceTimeCalibrator:
    def tick(self, key, dispatched_dev=None):
        # The sampling decision read off the DEVICE: every tick — i.e.
        # every dispatch of every program — becomes a blocking fetch.
        c = int(dispatched_dev)                    # expect: host-sync
        with self._lock:
            self._counts[key] = c
        return c % self.every == 1

    def update(self, key, out):
        # Syncing on the OUTPUT inside update: the bracket already
        # measured the duration; draining again doubles the stall.
        out.block_until_ready()                    # expect: host-sync
        with self._lock:
            self._ewma[key] = self._host_dur(out)

    def estimate(self, key, ewma_dev=None):
        # Estimates are read once per flight record on the engine
        # loop — a device-resident EWMA makes every record a fetch.
        return float(ewma_dev)                     # expect: host-sync


class HbmLedger:
    def set_bytes(self, component, used_rows_dev=None, row_bytes=0):
        # The ledger mirrors host bookkeeping by design; counting
        # device-side rows re-introduces the drift it exists to avoid
        # AND stalls the refresh that runs inside the serving loop.
        n = np.asarray(used_rows_dev)              # expect: host-sync
        with self._lock:
            self._components[component] = n.sum() * row_bytes


class Roofline:
    def record_cost(self, burst, program, toks_dev=None):
        # Costing the burst from its device arrays instead of the
        # program-dict scalars: one pipeline drain per flight record.
        toks = toks_dev.sum().item()               # expect: host-sync
        return (2 * self.param_count * toks,
                toks * self.kv_token_bytes)
