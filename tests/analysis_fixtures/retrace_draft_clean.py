# Clean twin of retrace_draft_bad.py: the lockstep sync as pure
# masked data flow — no traced branches, no concretization, shapes
# static. Never imported.
import jax
import jax.numpy as jnp


@jax.jit
def draft_rollout_sync(cache, active, lengths, tokens):
    out = dict(cache)
    out["length"] = jnp.where(active, lengths.astype(jnp.int32),
                              cache["length"])
    out["last_token"] = jnp.where(active, tokens.astype(jnp.int32),
                                  cache["last_token"])
    return out
