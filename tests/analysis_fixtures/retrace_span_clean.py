# Clean twin: the span-bucketed gather done right — the span is a
# STATIC argument (one compiled program per ladder rung, selected on
# the host from host-tracked lengths), the block-table prefix is
# sliced by static host math, and the validity mask is pure array
# math against it. Never imported.
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("span",))
def span_attn(cache, table, lengths, *, span):
    bl = cache["k"].shape[2]                      # static: block rows
    nb = span // bl                               # static host math
    tbl = table[:, :nb]                           # block-table prefix
    k = jnp.take(cache["k"], tbl, axis=1)
    valid = jnp.arange(span)[None, :] < lengths[:, None]
    return k, valid
