# Clean twin: the QoS path done right — DRR over host request lists
# (prompt/tokens lengths ARE host state), token buckets fed by wall
# clocks, preemption victims picked from the host slot map. The device
# is never consulted. Never imported.
import time


class FairScheduler:
    def reorder(self, waiting):
        if len(waiting) < 2:
            return
        lanes = {}
        order = []
        for r in waiting:
            key = (r.priority, r.tenant)
            if key not in lanes:
                lanes[key] = []
                order.append(key)
            lanes[key].append(r)
        out = []
        deficit = {key: 0 for key in order}
        remaining = len(waiting)
        while remaining:
            for key in order:
                q = lanes[key]
                if not q:
                    continue
                deficit[key] += self.quantum
                while q and self.request_cost(q[0]) <= deficit[key]:
                    r = q.pop(0)
                    deficit[key] -= self.request_cost(r)
                    out.append(r)
                    remaining -= 1
        waiting.clear()
        waiting.extend(out)

    def request_cost(self, req):
        return max(len(req.prompt) + len(req.tokens)
                   + req.max_new_tokens, 1)


class AdmissionController:
    def admit(self, tenant, depth=None):
        now = time.monotonic()
        with self._lock:
            bucket = self._buckets.get(tenant)
            wait_s = bucket.take(now) if bucket is not None else 0.0
        if wait_s > 0:
            raise RateLimitedError(tenant, wait_s)


class RateLimitedError(Exception):
    def __init__(self, tenant, wait_s):
        super().__init__(f"{tenant}: retry in {wait_s:.2f}s")
