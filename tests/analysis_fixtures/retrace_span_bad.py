# Golden fixture: seeded retrace-safety violations in the
# span-bucketed decode-attention shape — the exact mistakes span
# bucketing invites: deriving the span from TRACED lengths inside the
# program instead of taking it as a static argument (one compiled
# program per ladder rung). Checked as if it lived at
# skypilot_tpu/infer/ (a jit-root directory). Never imported.
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def span_attn(cache, table, lengths):
    span = int(jnp.max(lengths))                  # expect: concretize
    host = np.asarray(lengths)                    # expect: host-transfer
    if (lengths >= span).any():                   # expect: traced-branch
        span = span + 1
    rows = jnp.arange(jnp.max(lengths))           # expect: dynamic-shape
    valid = rows[None, :] < lengths[:, None]
    k = cache["k"][:, :span]
    return k, valid, host
