# Clean twin: the speculative verify/accept path done right — the
# drafter is pure host bookkeeping, the ONE completion fetch happens
# on already-host data after the verify burst's deliberate sync point
# (baselined in the real engine), and nothing else touches the
# device. Never imported.
import numpy as np


class InferenceEngine:
    def _draft_for(self, req):
        # Pure host work: python lists + the n-gram index dict.
        if req.spec_off:
            return []
        req.drafter.catch_up(req.prompt, req.tokens)
        return req.drafter.draft(self.spec_k)

    def spec_decode_burst(self):
        draft = np.zeros((self.n_slots + 1, self.spec_k), np.int32)
        n_draft = np.zeros((self.n_slots + 1,), np.int32)
        for slot, req in self.slot_req.items():
            d = self._draft_for(req)
            n_draft[slot] = len(d)
            draft[slot, :len(d)] = d
        self.cache, toks, n_commit = self._verify_fn(
            self.params, self.cache, draft, n_draft, self.active,
            self.table_device(), k=self.spec_k)
        return toks, n_commit
