# Clean twin: the hot loop only dispatches; casts touch host values.
import time

import numpy as np


class InferenceEngine:
    def step_burst(self, max_burst=8):
        active = np.zeros((9,), bool)     # host alloc, not a fetch
        self.cache, toks = self._decode_fn(active)
        k = int(len(self.slot_req))       # len(): host-side
        t0 = float(time.time())           # time: host-side
        return toks, k, t0
