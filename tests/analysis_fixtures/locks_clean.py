# Clean twin: mutations under the declared lock; slow work outside it.
import json
import threading

_lock = threading.Lock()
_ring = []                              # guarded-by: _lock


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []                  # guarded-by: _lock

    def ok(self, rec):
        with self._lock:
            self._buf.append(rec)

    def flush(self):
        with self._lock:
            snapshot = list(self._buf)  # reads are free
        return json.dumps(snapshot)     # serialization OUTSIDE


def record(rec):
    with _lock:
        _ring.append(rec)


def on_callback():
    with _lock:
        # A callback DEFINED under a lock does not run under it.
        def later():
            json.dumps({"a": 1})
        _ring.append(later)
