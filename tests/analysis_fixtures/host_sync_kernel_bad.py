# Golden fixture: seeded host-sync violations on the per-tenant
# KV-block quota / charge path (PR 12). The charge bookkeeping runs
# at every claim/growth/free and the quota check per admission pass —
# both must read HOST state (the numpy block table, request token
# lists, the tenant counter dict); fetching device lengths to count a
# tenant's blocks would stall admission itself. Checked as if it were
# skypilot_tpu/infer/engine.py (the hot-loop scope). Never imported.
import numpy as np


class InferenceEngine:
    def _sync_kv_charge(self, slot, tenant=None):
        row = np.asarray(self.cache["table"][slot])  # expect: host-sync
        have = int(self.cache["length"][slot])       # expect: host-sync
        self._slot_kv_charge[slot] = (tenant, have)
        return row

    def _kv_quota_blocked(self, req):
        used = self.cache["kv_used"].item()          # expect: host-sync
        return used >= self._kv_quota(req.tenant)
