# Clean twin: the adapter gather done right — the per-slot (A, B)
# pair is a BATCHED gather indexed by the aid device vector (adapter
# identity stays data; one compiled program serves every catalog
# composition), and the all-zeros base slot makes the no-adapter delta
# an exact zero with no branch. Never imported.
import jax
import jax.numpy as jnp


def _lora_in_delta(h, ab, aid):
    a = ab["a"][aid].astype(h.dtype)
    b = ab["b"][aid].astype(h.dtype)
    u = jnp.einsum("bsd,bdr->bsr", h, a)
    return jnp.einsum("bsr,brhk->bshk", u, b)


def adapter_proj(h, w, llayer, aid):
    y = jnp.einsum("bsd,dhk->bshk", h, w)
    if llayer is not None:
        y = y + _lora_in_delta(h, llayer["wq"], aid)
    return y


@jax.jit
def decode_step(cache, w, lora, aid):
    return adapter_proj(cache["x"], w, lora, aid)
