# Clean twin: goodput attribution done right — wall-clock brackets
# (time.monotonic) around work the loop thread already does, phase
# dicts and bucket floats are pure host state under the recorder
# lock, and the watchdog consumes the float the loop fetched once at
# its own logging cadence. The device is never consulted.
# Never imported.
import time


class GoodputRecorder:
    def _credit_locked(self, bucket, dur):
        if dur <= 0.0:
            return
        self._buckets[bucket] += dur

    def _advance_locked(self, now, bucket):
        self._credit_locked(bucket, now - self._t_last)
        self._t_last = now

    def step_start(self, step):
        now = time.monotonic()
        with self._lock:
            self._advance_locked(now, "host_other")
        self._step = step
        self._step_t0 = now
        self._phases = {}

    def step_end(self, tokens=0, loss=None, grad_norm=None):
        now = time.monotonic()
        wall = now - self._step_t0
        named = sum(self._phases.values())
        other = wall - named if wall > named else 0.0
        with self._lock:
            for phase, dur in self._phases.items():
                bucket = ("productive" if phase == "compute"
                          else "host_other")
                self._credit_locked(bucket, dur)
            self._credit_locked("host_other", other)
            self._t_last = self._step_t0 + wall
        rec = {"dur_s": wall, "toks": tokens, "host": self._host}
        if loss is not None:
            rec["loss"] = loss
        self.recorder.record("train_step", **rec)


class AnomalyWatchdog:
    def observe(self, step, loss, grad_norm=None):
        # `loss` is already a host float — the loop fetched it once at
        # its logging cadence; the watchdog adds zero extra syncs.
        if loss != loss:
            if not self._non_finite:
                self._non_finite = True
                return {"kind": "non_finite", "step": step}
            return None
        self._non_finite = False
        if self._last is not None:
            self._deltas.append(abs(loss - self._last))
        self._last = loss
        return None
