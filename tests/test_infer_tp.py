"""Tensor-parallel serving: the engine sharded over a tp mesh must be
TOKEN-EXACT against the single-device engine — XLA SPMD partitions the
unchanged prefill/decode programs from the input shardings alone
(weights split Megatron-style, the KV cache by kv_heads).

This is the multi-chip serving story (JetStream runs TP on real pods;
reference serves via external engines): one chip can't hold a 70B —
``infer.server --tp N`` can. Runs on the virtual CPU mesh.

Parity holds where accumulation is associative: fp32 activations and
the int8 (w8a8) path. Under bf16 activations the TP all-reduce adds
per-device partial sums that were each rounded to 8 mantissa bits,
while the single-device dot rounds once after the full contraction —
the logits then differ at bf16 epsilon and greedy argmax flips on
near-ties (observed: the tiny model's top-2 logits tie exactly at
bf16 resolution). So the parity tests run the tiny config in fp32;
the int8 test exercises the quantized path whose integer accumulation
is exact under any partitioning.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.infer import kvcache
from skypilot_tpu.models import llama
from skypilot_tpu.parallel import sharding as sh

# heads=4, kv_heads=2 -> tp<=2; fp32 so TP reduction order cannot
# perturb greedy argmax (see module docstring).
CFG = dataclasses.replace(llama.CONFIGS["llama3-tiny"],
                          dtype=jnp.float32)
PROMPTS = [[1, 2, 3, 4, 5], [7, 8, 9], [11, 12]]


def _mesh(tp):
    if len(jax.devices()) < tp:
        pytest.skip(f"needs {tp} devices")
    return Mesh(np.array(jax.devices()[:tp]), ("tp",))


def _params():
    return llama.init_params(jax.random.key(0), CFG)


def _generate(**engine_kwargs):
    e = eng.InferenceEngine(_params(), CFG, n_slots=4, max_len=32,
                            prompt_buckets=(8,), **engine_kwargs)
    return e.generate(PROMPTS, max_new_tokens=6)


def test_tp_engine_matches_single_device():
    base = _generate()
    tp = _generate(mesh=_mesh(2))
    assert tp == base


def test_tp_engine_matches_w8a8_and_kv_int8():
    """The quantized path shards too: int8 weights + their per-channel
    scales split by the same logical names, int8 KV by kv_heads."""
    base = _generate(weights_int8=True, kv_int8=True)
    tp = _generate(weights_int8=True, kv_int8=True, mesh=_mesh(2))
    assert tp == base


def test_tp_shardings_actually_split():
    """The big tensors really are distributed — not silently
    replicated (a replicated wq would make --tp a no-op memory-wise)."""
    mesh = _mesh(2)
    e = eng.InferenceEngine(_params(), CFG, n_slots=2, max_len=32,
                            prompt_buckets=(8,), mesh=mesh)
    wq = e.params["blocks"]["wq"]
    assert "tp" in str(wq.sharding.spec)
    assert e.cache["k"].sharding.spec[3] == "tp"    # kv_heads dim
    # Norms replicate (no rule for 'embed'/'layer').
    assert e.params["blocks"]["ln1"].sharding.spec == \
        jax.sharding.PartitionSpec(None, None) or \
        not any(e.params["blocks"]["ln1"].sharding.spec)


def test_tp_reset_preserves_shardings():
    """After an engine failure + reset, the cache must stay sharded —
    a replicated rebuild would OOM the very next decode on a model
    that only fits sharded."""
    mesh = _mesh(2)
    e = eng.InferenceEngine(_params(), CFG, n_slots=2, max_len=32,
                            prompt_buckets=(8,), mesh=mesh)
    e.generate(PROMPTS[:1], max_new_tokens=3)
    before = e.cache["k"].sharding
    e.reset()
    assert e.cache["k"].sharding == before
    assert e.generate(PROMPTS[:1], max_new_tokens=3)


def test_qweight_logical_axes_match_quantized_tree():
    """The axes tree must mirror quantize_block_weights' structure —
    a drifted name would silently replicate that tensor."""
    params = _params()
    q = {"blocks": kvcache.quantize_block_weights(params),
         "head": kvcache.quantize_head(params, CFG)}
    axes = kvcache.qweight_logical_axes(CFG)
    flat_q = jax.tree_util.tree_flatten_with_path(q)[0]
    for path, arr in flat_q:
        node = axes
        for p in path:
            node = node[p.key]
        assert isinstance(node, tuple), path
        assert len(node) == arr.ndim, (path, node, arr.shape)


def test_sharded_init_materializes_on_mesh():
    """sharded_init builds params jit-with-out_shardings: every big
    tensor lands tp-split (a 70B must never materialize replicated on
    device 0 first), and the engine accepts them unchanged."""
    mesh = _mesh(2)
    params = eng.InferenceEngine.sharded_init(CFG, mesh)
    assert "tp" in str(params["blocks"]["wq"].sharding.spec)
    assert "tp" in str(params["embed"].sharding.spec)  # vocab-split
    e = eng.InferenceEngine(params, CFG, n_slots=2, max_len=32,
                            prompt_buckets=(8,), mesh=mesh)
    base = _generate()
    assert e.generate(PROMPTS, max_new_tokens=6) == base


@pytest.mark.slow
def test_server_main_tp_end_to_end(tmp_path):
    """`infer.server --tp 2` as a real subprocess: /health flips ready
    and /generate streams tokens — the full CLI surface of TP serving,
    not just the engine (the virtual CPU mesh stands in for chips)."""
    import json
    import os
    import socket
    import subprocess
    import sys
    import time
    import urllib.request

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.Popen(
        [sys.executable, "-m", "skypilot_tpu.infer.server",
         "--config", "llama3-tiny", "--port", str(port),
         "--tp", "2", "--slots", "2", "--max-len", "64"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 300
        while True:
            assert time.time() < deadline, "server never became ready"
            assert proc.poll() is None, "server process died"
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/health",
                        timeout=5) as r:
                    if r.status == 200:
                        break
            except OSError:
                pass
            time.sleep(1)
        body = json.dumps({"tokens": [1, 2, 3],
                           "max_new_tokens": 4}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json.loads(r.read())
        assert len(out["tokens"]) == 4
    finally:
        proc.terminate()
        proc.wait(timeout=10)
