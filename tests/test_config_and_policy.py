"""Config layering, schema validation, admin policy, cloud check.

Reference parity for test strategy: the reference's offline config and
admin-policy tests (tests/test_config.py, SURVEY.md §4) — everything
runs with SKYPILOT_TPU_HOME pointed at a tmp dir.
"""

import os

import pytest

from skypilot_tpu import admin_policy, check as check_lib
from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu.task import Task
from skypilot_tpu.utils import schemas


@pytest.fixture(autouse=True)
def tmp_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    monkeypatch.delenv("SKYPILOT_TPU_CONFIG", raising=False)
    config_lib.reload()
    yield
    config_lib.reload()


def test_config_roundtrip_and_nesting():
    assert config_lib.get_nested(("gcp", "project")) is None
    config_lib.set_nested(("gcp", "project"), "proj-1")
    config_lib.set_nested(("provisioner", "ssh_timeout"), 120)
    assert config_lib.get_nested(("gcp", "project")) == "proj-1"
    assert config_lib.get_nested(("provisioner", "ssh_timeout")) == 120
    assert config_lib.get_nested(("gcp", "missing"), "dflt") == "dflt"
    cfg = config_lib.to_dict()
    schemas.validate_global_config(cfg)


def test_config_override_context():
    config_lib.set_nested(("gcp", "project"), "base")
    with config_lib.override_config({"gcp": {"project": "task-level"}}):
        assert config_lib.get_nested(("gcp", "project")) == "task-level"
    assert config_lib.get_nested(("gcp", "project")) == "base"


def test_task_schema_rejects_bad_yaml():
    with pytest.raises(exceptions.InvalidTaskError):
        Task.from_yaml_config({"num_nodes": "not-an-int"})
    with pytest.raises(exceptions.InvalidTaskError):
        Task.from_yaml_config({"unknown_field": 1})
    with pytest.raises(exceptions.InvalidTaskError):
        Task.from_yaml_config({"resources": {"bogus": True}})


def test_task_config_overrides_parsed():
    task = Task.from_yaml_config({
        "run": "echo hi",
        "config_overrides": {"gcp": {"project": "override-me"}},
    })
    assert task.config_overrides == {"gcp": {"project": "override-me"}}


class _RenamePolicy(admin_policy.AdminPolicy):
    @classmethod
    def validate_and_mutate(cls, user_request):
        user_request.task.name = "policy-renamed"
        return admin_policy.MutatedUserRequest(
            task=user_request.task,
            skypilot_config=user_request.skypilot_config)


class _RejectPolicy(admin_policy.AdminPolicy):
    @classmethod
    def validate_and_mutate(cls, user_request):
        raise admin_policy.PolicyError("spot only!")


class _ConfigMutatingPolicy(admin_policy.AdminPolicy):
    @classmethod
    def validate_and_mutate(cls, user_request):
        cfg = dict(user_request.skypilot_config)
        cfg.setdefault("gcp", {})["project"] = "policy-project"
        return admin_policy.MutatedUserRequest(
            task=user_request.task, skypilot_config=cfg)


def test_admin_policy_mutates_task():
    config_lib.set_nested(
        ("admin_policy",),
        f"{__name__}._RenamePolicy")
    task = Task(name="orig", run="echo hi")
    out, mutated_cfg = admin_policy.apply(task)
    assert out.name == "policy-renamed"
    assert mutated_cfg is None  # config untouched by this policy


def test_admin_policy_mutated_config_returned():
    config_lib.set_nested(
        ("admin_policy",), f"{__name__}._ConfigMutatingPolicy")
    _, mutated_cfg = admin_policy.apply(Task(run="echo hi"))
    assert mutated_cfg["gcp"]["project"] == "policy-project"
    with config_lib.replace_config(mutated_cfg):
        assert config_lib.get_nested(("gcp", "project")) == "policy-project"
    assert config_lib.get_nested(("gcp", "project")) is None


def test_admin_policy_rejects():
    config_lib.set_nested(("admin_policy",), f"{__name__}._RejectPolicy")
    with pytest.raises(admin_policy.PolicyError, match="spot only"):
        admin_policy.apply(Task(run="echo hi"))


def test_admin_policy_absent_is_noop():
    task = Task(run="echo hi")
    out, cfg = admin_policy.apply(task)
    assert out is task and cfg is None


def test_get_nested_returns_copies():
    config_lib.set_nested(("gcp", "project"), "base")
    view = config_lib.get_nested(("gcp",))
    view["project"] = "mutated-by-caller"
    assert config_lib.get_nested(("gcp", "project")) == "base"


def test_check_caches_enabled_clouds():
    enabled = check_lib.check(quiet=True, clouds=["local"])
    assert enabled == ["local"]
    cached = check_lib.get_cached_enabled_clouds_or_refresh()
    assert cached == ["local"]
    assert os.path.exists(os.path.join(
        os.environ["SKYPILOT_TPU_HOME"], "enabled_clouds.json"))


def test_check_subset_merges_cache(monkeypatch):
    check_lib.check(quiet=True, clouds=["local"])
    # A failing subset check must not clobber previously enabled clouds.
    monkeypatch.setattr(check_lib, "_check_one",
                        lambda c: (False, "forced failure"))
    enabled = check_lib.check(quiet=True, clouds=["gcp"])
    assert enabled == ["local"]
    assert "local" in check_lib.get_cached_enabled_clouds_or_refresh()
