"""Multi-LoRA adapter catalog: pool units, engine greedy parity,
hot-load compile discipline, typed 404/failure at both serving tiers.

The headline guarantees (docs/serving.md §Adapter catalog):
* a zero-adapter request on an adapter-capable engine is BIT-IDENTICAL
  to an adapterless engine (pool slot 0 is all zeros — exact-zero
  delta);
* a mixed-adapter batch is BIT-IDENTICAL to per-adapter sequential
  runs (the per-slot gather is row-independent), across
  {fp32, int8 KV} x {spec on, off} on the paged layout;
* adapter count/identity never enters program identity — adapters
  hot-load/evict mid-traffic under ``declare_warmup_complete`` with
  ZERO unexpected compiles;
* an unknown fine-tune is a typed 404 at the LB and the model server
  (stream path included); a failed checkpoint load fails the request
  typed — never a silent fall-through to the base model's weights.
"""

import http.server
import json
import socket
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from skypilot_tpu import chaos
from skypilot_tpu.chaos import plan as chaos_plan
from skypilot_tpu.infer import adapters as ad
from skypilot_tpu.infer import engine as eng
from skypilot_tpu.infer import server as srv
from skypilot_tpu.models import llama

CFG = llama.CONFIGS["llama3-tiny"]
RANK = 4
PROMPTS = [[3, 17, 42, 5], [7, 9, 11, 13, 2], [23, 29, 31]]


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.key(0), CFG)


def _mk_params(seed, rank=RANK, targets=None, scale=0.05):
    """A random nonzero adapter tree in the train/lora layout."""
    r = np.random.default_rng(seed)
    L = CFG.n_layers
    shapes = ad.target_shapes(CFG, rank)
    out = {}
    for t, (sa, sb) in shapes.items():
        if targets is not None and t not in targets:
            continue
        sa = sa[:-1] + (rank,)
        sb = (rank,) + sb[1:]
        out[t] = {"a": r.normal(size=(L,) + sa).astype(np.float32)
                  * scale,
                  "b": r.normal(size=(L,) + sb).astype(np.float32)
                  * scale}
    return out


def _catalog(n_adapters=4, rank=RANK, register=3):
    cat = ad.AdapterCatalog(CFG, n_adapters=n_adapters, rank=rank)
    for i in range(register):
        cat.register(f"ft-{i}", params=_mk_params(100 + i, rank))
    return cat


def _engine(params, catalog=None, **kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("prompt_buckets", (8, 16))
    kw.setdefault("kv_block", 16)
    kw.setdefault("prefill_chunk", 0)
    return eng.InferenceEngine(params, CFG, adapters=catalog, **kw)


# ---------------------------------------------------------------------------
# Catalog units: registry, content addressing, LRU, pins.


def test_unknown_adapter_typed():
    cat = _catalog()
    with pytest.raises(ad.UnknownAdapterError) as e:
        cat.check("nope")
    assert e.value.typed_error["type"] == "unknown_adapter"
    assert e.value.http_status == 404
    cat.check("ft-0")       # known: no raise
    cat.check(None)         # base model: no raise


def test_engine_without_catalog_knows_no_adapters(params):
    e = _engine(params)
    with pytest.raises(ad.UnknownAdapterError):
        e.add_request(PROMPTS[0], 4, adapter="ft-0")


def _bind_fake_loader(cat):
    loads = []

    def loader(pool, slot, weights):
        loads.append(int(slot))
        return pool

    cat.bind_loader(loader)
    return loads


def test_content_addressed_sharing():
    """Two names registering identical bytes share ONE pool slot (and
    one hot-load)."""
    cat = ad.AdapterCatalog(CFG, n_adapters=4, rank=RANK)
    same = _mk_params(1)
    cat.register("alias-a", params=same)
    cat.register("alias-b", params={t: {k: v.copy()
                                        for k, v in ab.items()}
                                    for t, ab in same.items()})
    loads = _bind_fake_loader(cat)
    s1 = cat.acquire("alias-a")
    s2 = cat.acquire("alias-b")
    assert s1 == s2
    assert loads == [s1]
    assert cat.resident_count() == 1


def test_alpha_is_part_of_content_identity():
    """alpha folds into B at install, so identical raw weights under
    different alphas are DIFFERENT effective models — they must never
    dedup to one pool slot."""
    cat = ad.AdapterCatalog(CFG, n_adapters=4, rank=RANK)
    same = _mk_params(3)
    cat.register("a16", params=same, alpha=16.0)
    cat.register("a32", params={t: {k: v.copy() for k, v in ab.items()}
                                for t, ab in same.items()}, alpha=32.0)
    loads = _bind_fake_loader(cat)
    s1 = cat.acquire("a16")
    s2 = cat.acquire("a32")
    assert s1 != s2
    assert loads == [s1, s2]
    assert cat.resident_count() == 2


def test_path_alias_shares_one_slot(tmp_path):
    """Two names registered from the SAME checkpoint path (digest
    unknown until first load) still converge on one resident slot —
    one digest must never map two slots."""
    path = str(tmp_path / "ft.npz")
    ad.save_adapter(path, _mk_params(9), alpha=8.0)
    cat = ad.AdapterCatalog(CFG, n_adapters=4, rank=RANK)
    cat.register("alias-a", path=path)
    cat.register("alias-b", path=path)
    _bind_fake_loader(cat)
    s1 = cat.acquire("alias-a")
    s2 = cat.acquire("alias-b")
    assert s1 == s2
    assert cat.resident_count() == 1
    assert cat.pins(s1) == 2
    # The duplicate install's slot went back to the free list: a third
    # distinct adapter still fits without eviction.
    cat.register("other", params=_mk_params(11))
    assert cat.acquire("other") not in (None, s1)
    assert cat.evictions == 0


def test_lru_eviction_and_pinning():
    """Eviction is LRU over UNPINNED residents; an adapter pinned by
    an in-flight request is never evicted — a full-pinned pool stalls
    (None) instead."""
    cat = ad.AdapterCatalog(CFG, n_adapters=3, rank=RANK)  # 2 + base
    for i in range(4):
        cat.register(f"ft-{i}", params=_mk_params(200 + i))
    _bind_fake_loader(cat)
    s0 = cat.acquire("ft-0")
    s1 = cat.acquire("ft-1")
    assert cat.resident_count() == 2
    # Pool full, both pinned: a third acquire STALLS, evicts nothing.
    assert cat.acquire("ft-2") is None
    assert cat.evictions == 0
    # Release ft-0's pin: it stays resident (warm) but evictable...
    cat.release(s0)
    s2 = cat.acquire("ft-2")
    assert s2 == s0                  # ...and LRU eviction reused it
    assert cat.evictions == 1
    assert cat.resident_count() == 2
    # ft-1 (still pinned) survived; re-acquiring it is a warm hit.
    assert cat.acquire("ft-1") == s1
    assert cat.loads == 3            # ft-0, ft-1, ft-2 — no reload


def test_release_refcounts():
    cat = ad.AdapterCatalog(CFG, n_adapters=2, rank=RANK)
    cat.register("ft-0", params=_mk_params(1))
    cat.register("ft-1", params=_mk_params(2))
    _bind_fake_loader(cat)
    s = cat.acquire("ft-0")
    s_again = cat.acquire("ft-0")
    assert s == s_again and cat.pins(s) == 2
    cat.release(s)
    assert cat.pins(s) == 1          # still pinned by the other
    assert cat.acquire("ft-1") is None
    cat.release(s)
    assert cat.pins(s) == 0
    assert cat.acquire("ft-1") is not None     # now evictable
    # Base slot (0) never refcounts.
    assert cat.acquire(None) == 0
    cat.release(0)


def test_rank_validation():
    cat = ad.AdapterCatalog(CFG, n_adapters=2, rank=2)
    with pytest.raises(ValueError, match="rank"):
        cat.register("big", params=_mk_params(1, rank=4))
    cat.register("small", params=_mk_params(1, rank=1))  # zero-pads


def test_save_load_roundtrip(tmp_path, params):
    """A path-registered .npz checkpoint serves end to end and
    matches the same adapter registered in memory."""
    tree = _mk_params(7)
    path = str(tmp_path / "ft.npz")
    ad.save_adapter(path, tree, alpha=8.0)
    loaded, alpha = ad.load_adapter_file(path)
    assert alpha == 8.0
    assert set(loaded) == set(tree)

    cat_mem = _catalog(register=0)
    cat_mem.register("ft", params=tree, alpha=8.0)
    e1 = _engine(params, cat_mem)
    r1 = e1.add_request(PROMPTS[0], 6, adapter="ft")
    e1.run_to_completion()
    out_mem = [r.tokens for r in e1.finished if r.rid == r1][0]

    cat_path = _catalog(register=0)
    cat_path.register("ft", path=path)
    e2 = _engine(params, cat_path)
    r2 = e2.add_request(PROMPTS[0], 6, adapter="ft")
    e2.run_to_completion()
    out_path = [r.tokens for r in e2.finished if r.rid == r2][0]
    assert out_mem == out_path


# ---------------------------------------------------------------------------
# Greedy parity matrix: {fp32, int8 KV} x {spec on, off}, paged layout.


@pytest.mark.parametrize("kv_int8", [False, True],
                         ids=["fp32", "int8kv"])
@pytest.mark.parametrize("spec_k", [0, 2], ids=["spec0", "spec2"])
def test_parity_matrix(params, kv_int8, spec_k):
    """(a) A zero-adapter request on an adapter-capable engine is
    bit-identical to an adapterless engine. (b) A mixed-adapter batch
    is bit-identical to per-adapter sequential runs."""
    kw = dict(kv_int8=kv_int8, spec_k=spec_k, prefill_chunk=8,
              prefix_pool=2)
    base = _engine(params, None, **kw)
    want = base.generate(PROMPTS, max_new_tokens=6)

    def build():
        return _engine(params, _catalog(), **kw)

    e = build()
    got = e.generate(PROMPTS, max_new_tokens=6)
    assert got == want, "zero-adapter output drifted from adapterless"

    names = ["ft-0", "ft-1", None]
    e = build()
    ids = [e.add_request(p, 6, adapter=n)
           for p, n in zip(PROMPTS, names)]
    e.run_to_completion()
    mixed = {r.rid: r.tokens for r in e.finished}
    for i, (p, n) in enumerate(zip(PROMPTS, names)):
        solo = build()
        rid = solo.add_request(p, 6, adapter=n)
        solo.run_to_completion()
        assert mixed[ids[i]] == solo.finished[0].tokens, \
            f"mixed batch diverged from sequential for {n}"
        if n is None:
            assert mixed[ids[i]] == want[i]
        else:
            assert mixed[ids[i]] != want[i], \
                "adapter output identical to base — vacuous test"


def test_prefix_cache_is_adapter_scoped(params):
    """Stored K/V rows carry the fine-tune's wk/wv deltas, so the
    prefix cache must be keyed PER ADAPTER: a shared prompt prefix
    warmed under adapter A must never serve B or the base model — and
    within one adapter, the warm hit still pays off and stays
    bit-identical to cold."""
    shared = list(np.random.default_rng(5).integers(
        1, CFG.vocab_size, 24))
    tails = [[1, 2, 3], [4, 5, 6], [7, 8, 9]]
    kw = dict(prefill_chunk=8, prefix_pool=4, max_len=64,
              prompt_buckets=(8, 32))

    def run(e, tail, adapter):
        rid = e.add_request(shared + tail, 5, adapter=adapter)
        e.run_to_completion()
        req = [r for r in e.finished if r.rid == rid][0]
        e.finished.clear()
        return list(req.tokens), req.cached_len

    # Cold references, one engine per (adapter, tail).
    want = {}
    for i, name in enumerate(["ft-0", "ft-1", None]):
        solo = _engine(params, _catalog(), **kw)
        want[name] = run(solo, tails[i], name)[0]

    # One engine, interleaved: A warms the prefix, then B and base
    # use the same prompt prefix — no cross-adapter hit may occur.
    e = _engine(params, _catalog(), **kw)
    out_a, cached_a = run(e, tails[0], "ft-0")
    assert out_a == want["ft-0"] and cached_a == 0
    out_b, cached_b = run(e, tails[1], "ft-1")
    assert cached_b == 0, "cross-adapter prefix hit"
    assert out_b == want["ft-1"]
    out_base, cached_base = run(e, tails[2], None)
    assert cached_base == 0, "adapter-warmed prefix served the base"
    assert out_base == want[None]
    # Same adapter again: the warm hit fires and stays bit-identical.
    out_a2, cached_a2 = run(e, tails[2], "ft-0")
    assert cached_a2 > 0
    solo = _engine(params, _catalog(), **kw)
    assert out_a2 == run(solo, tails[2], "ft-0")[0]


# ---------------------------------------------------------------------------
# Hot-load compile discipline.


def test_hot_load_zero_unexpected_compiles(params):
    """Adapters hot-load/evict mid-traffic under an armed compile
    watch: adapter count/identity never enters program identity."""
    cat = ad.AdapterCatalog(CFG, n_adapters=3, rank=RANK)
    for i in range(6):
        cat.register(f"ft-{i}", params=_mk_params(300 + i))
    e = _engine(params, cat, spec_k=2, prefill_chunk=8,
                max_wave=4, pad_waves=True)
    e.warm_programs()
    e.declare_warmup_complete()
    for i in range(6):
        e.add_request(PROMPTS[i % len(PROMPTS)], 4,
                      adapter=f"ft-{i}")
        e.run_to_completion()
        e.finished.clear()
    assert cat.loads >= 6            # every name demand-loaded once
    assert cat.evictions >= 4        # the pool churned
    assert e.compile_watch.unexpected == [], (
        "adapter hot-load caused a mid-traffic compile: "
        f"{e.compile_watch.unexpected}")


def test_pinned_pool_stall_steps_aside(params):
    """A request whose fine-tune cannot load because every adapter
    slot is pinned steps ASIDE — base-model traffic behind it keeps
    admitting (the quota-held idiom, not a head-of-line stall) — and
    admits once a retirement unpins a slot."""
    cat = ad.AdapterCatalog(CFG, n_adapters=2, rank=RANK)  # 1 + base
    cat.register("ft-0", params=_mk_params(1))
    cat.register("ft-1", params=_mk_params(2))
    e = _engine(params, cat, n_slots=4)
    r0 = e.add_request(PROMPTS[0], 8, adapter="ft-0")
    r1 = e.add_request(PROMPTS[1], 4, adapter="ft-1")   # pool pinned
    r2 = e.add_request(PROMPTS[2], 4)                   # base, behind
    e.admit()
    admitted = {r.rid for r in e.slot_req.values()}
    assert r0 in admitted
    assert r1 not in admitted        # held: its pool slot is pinned
    assert r2 in admitted, "base request head-of-line blocked"
    e.run_to_completion()            # ft-0 retires -> ft-1 admits
    by_rid = {r.rid: r for r in e.finished}
    assert by_rid[r1].error is None and len(by_rid[r1].tokens) == 4
    assert cat.evictions == 1        # ft-1 evicted the unpinned ft-0


def test_aid_device_cache_dirty_tracking(params):
    """The device aid copy only rebuilds when a claim/retire changed
    the host array (the table_device idiom)."""
    e = _engine(params, _catalog())
    d1 = e.aid_device()
    assert e.aid_device() is d1
    rid = e.add_request(PROMPTS[0], 3, adapter="ft-0")
    e.admit()
    d2 = e.aid_device()
    assert d2 is not d1
    slot = [r for r in e.slot_req.values() if r.rid == rid][0].slot
    assert int(np.asarray(d2)[slot]) > 0
    e.run_to_completion()
    assert int(np.asarray(e.aid_device())[slot]) == 0


# ---------------------------------------------------------------------------
# Chaos: the adapter.load fault point.


def _chaos_plan(times):
    return chaos_plan.parse_plan({
        "seed": 0,
        "faults": [{"point": "adapter.load",
                    "match": {"adapter": "ft-0"},
                    "times": times, "error": "OSError",
                    "message": "injected load fault"}],
    })


def test_load_fault_retries_then_succeeds(params):
    """One injected fault is absorbed by utils/retry — the request
    generates normally under its fine-tune."""
    cat = _catalog()
    e = _engine(params, cat)
    ref = _engine(params, _catalog())
    rid_ref = ref.add_request(PROMPTS[0], 5, adapter="ft-0")
    ref.run_to_completion()
    want = [r.tokens for r in ref.finished if r.rid == rid_ref][0]
    chaos.configure(_chaos_plan(times=1))
    try:
        rid = e.add_request(PROMPTS[0], 5, adapter="ft-0")
        e.run_to_completion()
    finally:
        chaos.deactivate()
    got = [r for r in e.finished if r.rid == rid][0]
    assert got.error is None
    assert got.tokens == want
    assert cat.loads == 1


def test_load_fault_exhaustion_fails_typed(params):
    """Exhausted retries fail the REQUEST typed — it never falls
    through to the base model's weights — while other requests keep
    admitting."""
    cat = _catalog()
    e = _engine(params, cat)
    chaos.configure(_chaos_plan(times=4))
    try:
        rid_bad = e.add_request(PROMPTS[0], 5, adapter="ft-0")
        rid_ok = e.add_request(PROMPTS[1], 5, adapter="ft-1")
        rid_base = e.add_request(PROMPTS[2], 5)
        e.run_to_completion()
    finally:
        chaos.deactivate()
    by_rid = {r.rid: r for r in e.finished}
    bad = by_rid[rid_bad]
    assert bad.error is not None
    assert bad.error["type"] == "adapter_load_failed"
    assert bad.error["adapter"] == "ft-0"
    assert bad.tokens == []          # NOT base-model output
    assert len(by_rid[rid_ok].tokens) == 5
    assert len(by_rid[rid_base].tokens) == 5
    # The failed slot never became resident; the pool has no leak.
    assert cat.resident_count() == 1          # ft-1 only
    # The catalog recovers once the fault clears.
    rid2 = e.add_request(PROMPTS[0], 5, adapter="ft-0")
    e.run_to_completion()
    assert by_rid[rid_bad].error is not None
    got2 = [r for r in e.finished if r.rid == rid2][0]
    assert got2.error is None and len(got2.tokens) == 5


# ---------------------------------------------------------------------------
# Model-server tier: model= field, typed 404 (blocking AND stream),
# typed load failure, trailer.


@pytest.fixture(scope="module")
def model_server(params):
    cat = ad.AdapterCatalog(CFG, n_adapters=4, rank=RANK)
    for i in range(3):
        cat.register(f"ft-{i}", params=_mk_params(100 + i))
    engine = eng.InferenceEngine(params, CFG, n_slots=2, max_len=64,
                                 prompt_buckets=(16,), adapters=cat)
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    model, httpd = srv.serve(engine, host="127.0.0.1", port=port)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    assert model._ready.wait(timeout=300)
    yield f"http://127.0.0.1:{port}", engine
    model.shutdown()
    httpd.shutdown()


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_model_generates_under_adapter(model_server, params):
    url, engine = model_server
    prompt = [3, 17, 42]
    solo = _engine(params, _catalog(), kv_block=0,
                   prompt_buckets=(16,), n_slots=1)
    rid = solo.add_request(prompt, 5, adapter="ft-0")
    solo.run_to_completion()
    want = [r.tokens for r in solo.finished if r.rid == rid][0]
    code, out = _post(f"{url}/generate",
                      {"tokens": prompt, "max_new_tokens": 5,
                       "model": "ft-0"})
    assert code == 200
    assert out["tokens"] == want
    assert out["model"] == "ft-0"    # the trailer names the fine-tune


def test_http_model_header_path(model_server):
    url, _ = model_server
    code, out = _post(f"{url}/generate",
                      {"tokens": [1, 2], "max_new_tokens": 3},
                      headers={ad.MODEL_HEADER: "ft-1"})
    assert code == 200 and out["model"] == "ft-1"


def test_http_unknown_adapter_404(model_server):
    url, _ = model_server
    code, out = _post(f"{url}/generate",
                      {"tokens": [1, 2], "max_new_tokens": 3,
                       "model": "nope"})
    assert code == 404
    assert out["error"]["type"] == "unknown_adapter"
    assert out["error"]["adapter"] == "nope"


def test_http_unknown_adapter_404_stream(model_server):
    """The stream path rejects BEFORE any 200/stream bytes go out —
    a clean typed 404, not an error chunk mid-stream."""
    url, _ = model_server
    code, out = _post(f"{url}/generate",
                      {"tokens": [1, 2], "max_new_tokens": 3,
                       "stream": True, "model": "nope"})
    assert code == 404
    assert out["error"]["type"] == "unknown_adapter"


def test_http_load_failure_typed(model_server):
    """A mid-traffic load failure surfaces as the typed 503 body on
    the blocking path and as a typed error chunk on a live stream."""
    url, _ = model_server
    chaos.configure(chaos_plan.parse_plan({
        "seed": 0,
        "faults": [{"point": "adapter.load",
                    "match": {"adapter": "ft-2"},
                    "error": "OSError", "message": "injected"}],
    }))
    try:
        code, out = _post(f"{url}/generate",
                          {"tokens": [1, 2], "max_new_tokens": 3,
                           "model": "ft-2"})
    finally:
        chaos.deactivate()
    assert code == 503
    assert out["error"]["type"] == "adapter_load_failed"
    assert out["error"]["adapter"] == "ft-2"


def test_http_stream_load_failure_error_chunk(model_server):
    """Stream path: the load failure happens AFTER admission (claim
    time), so the stream is already open — the typed error must ride
    a stream chunk, not vanish."""
    url, _ = model_server
    chaos.configure(chaos_plan.parse_plan({
        "seed": 0,
        "faults": [{"point": "adapter.load",
                    "match": {"adapter": "ft-2"},
                    "error": "OSError", "message": "injected"}],
    }))
    try:
        req = urllib.request.Request(
            f"{url}/generate",
            data=json.dumps({"tokens": [1, 2], "max_new_tokens": 3,
                             "stream": True,
                             "model": "ft-2"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            lines = [json.loads(x) for x in r.read().decode()
                     .strip().split("\n") if x]
    finally:
        chaos.deactivate()
    assert any(c.get("error", {}).get("type") == "adapter_load_failed"
               for c in lines if isinstance(c.get("error"), dict)), lines


# ---------------------------------------------------------------------------
# Load-balancer tier: typed 404 one hop early + affinity routing.


class _FakeReplica(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    seen = []     # (port, path, model)

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        body = json.loads(self.rfile.read(n) or b"{}")
        type(self).seen.append((self.server.server_address[1],
                                self.path, body.get("model")))
        out = json.dumps({"tokens": [1], "model": body.get("model")})
        out = out.encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def log_message(self, *a):
        pass


@pytest.fixture()
def adapter_lb(tmp_path, monkeypatch):
    from skypilot_tpu.serve import load_balancer, serve_state
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    load_balancer._adapter_cache.clear()
    _FakeReplica.seen = []
    replicas, urls = [], []
    for _ in range(2):
        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                _FakeReplica)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        replicas.append(httpd)
        urls.append(f"http://127.0.0.1:{httpd.server_address[1]}")
    serve_state.add_service(
        "adlb", {"adapters": {"ft-a": "/ckpt/a.npz",
                              "ft-b": "/ckpt/b.npz"}}, {}, 0)
    for i, u in enumerate(urls):
        serve_state.upsert_replica("adlb", i + 1, f"r{i + 1}",
                                   serve_state.ReplicaStatus.READY, u)
    httpd = load_balancer._ThreadingServer(
        ("127.0.0.1", 0),
        load_balancer.make_handler("adlb",
                                   load_balancer.LeastLoadPolicy()))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", urls
    httpd.shutdown()
    for r in replicas:
        r.shutdown()
    load_balancer._adapter_cache.clear()


def test_lb_unknown_adapter_404(adapter_lb):
    lb_url, _ = adapter_lb
    code, out = _post(f"{lb_url}/generate",
                      {"tokens": [1], "model": "nope"})
    assert code == 404
    assert out["error"]["type"] == "unknown_adapter"
    assert not _FakeReplica.seen     # rejected BEFORE a proxied hop


def test_lb_unknown_adapter_404_stream(adapter_lb):
    lb_url, _ = adapter_lb
    code, out = _post(f"{lb_url}/generate",
                      {"tokens": [1], "stream": True, "model": "nope"})
    assert code == 404
    assert out["error"]["type"] == "unknown_adapter"


def test_lb_known_adapter_routes_with_affinity(adapter_lb):
    """Known names pass through AND stick to one replica (rendezvous
    affinity keeps each fine-tune's device pool warm)."""
    lb_url, _ = adapter_lb
    for _ in range(4):
        code, out = _post(f"{lb_url}/generate",
                          {"tokens": [1], "model": "ft-a"})
        assert code == 200 and out["model"] == "ft-a"
    ports = {p for p, _, m in _FakeReplica.seen if m == "ft-a"}
    assert len(ports) == 1           # all four hit ONE replica
    # Header path routes identically to the body path.
    code, out = _post(f"{lb_url}/generate", {"tokens": [1]},
                      headers={ad.MODEL_HEADER: "ft-a"})
    assert code == 200
    assert {p for p, _, m in _FakeReplica.seen} == ports
    # Base-model traffic still spreads via the policy (no affinity).
    for _ in range(4):
        code, _ = _post(f"{lb_url}/generate", {"tokens": [1]})
        assert code == 200
    assert len({p for p, _, m in _FakeReplica.seen if m is None}) == 2


# ---------------------------------------------------------------------------
# Service spec + smoke-bench wiring.


def test_service_spec_adapters_roundtrip():
    from skypilot_tpu import exceptions
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec.from_yaml_config({
        "port": 8080, "replicas": 2,
        "adapters": {"ft-a": "/ckpt/a.npz", "ft-b": "/ckpt/b.npz"},
    })
    assert spec.adapters == {"ft-a": "/ckpt/a.npz",
                             "ft-b": "/ckpt/b.npz"}
    rt = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert rt.adapters == spec.adapters
    with pytest.raises(exceptions.ServeError, match="adapters"):
        SkyServiceSpec(adapters={"": "/x"})


@pytest.mark.slow
def test_adapter_smoke_bench():
    """CI-sized bench wiring: overhead reported, parity and the
    zero-compile contract hold (the 1.15x TPOT gate binds via
    bench.py on hardware)."""
    from skypilot_tpu.infer import bench_serve
    r = bench_serve.run_adapters_smoke()
    assert r["parity_ok"]
    assert r["unexpected_compiles"] == 0
    assert r["hot_loads"] > 0 and r["evictions"] > 0
    assert r["overhead_ratio"] > 0
