"""Multislice env contract: MEGASCALE_* injection by the gang driver and
jax.distributed bootstrap purely from the injected env (VERDICT r1 #2
done-when)."""

import os
import socket
import subprocess
import sys

from skypilot_tpu.runtime import constants
from skypilot_tpu.runtime.driver import build_job_env


def _meta(n_slices, hosts_per_slice=1):
    hosts = []
    for s in range(n_slices):
        for w in range(hosts_per_slice):
            hosts.append({"host_id": len(hosts), "node_id": s,
                          "worker_id": w,
                          "internal_ip": f"10.0.{s}.{w + 1}",
                          "workspace": None, "kind": "ssh"})
    return {"provider": "gcp", "cluster_name": "ms", "zone": "z",
            "head_host_id": 0, "hosts": hosts}


def test_driver_injects_megascale_on_multislice():
    meta = _meta(n_slices=2, hosts_per_slice=2)
    env = build_job_env(meta, 7, meta["hosts"][3])
    assert env[constants.ENV_MEGASCALE_NUM_SLICES] == "2"
    assert env[constants.ENV_MEGASCALE_SLICE_ID] == "1"
    assert env[constants.ENV_MEGASCALE_COORDINATOR] == \
        f"10.0.0.1:{constants.MEGASCALE_PORT}"
    # Global jax.distributed contract spans all slices.
    assert env[constants.ENV_NUM_PROCESSES] == "4"
    assert env[constants.ENV_PROCESS_ID] == "3"
    assert env[constants.ENV_NODE_RANK] == "1"
    assert env[constants.ENV_WORKER_ID] == "1"


def test_no_megascale_on_single_slice():
    meta = _meta(n_slices=1, hosts_per_slice=4)
    env = build_job_env(meta, 1, meta["hosts"][2])
    assert constants.ENV_MEGASCALE_NUM_SLICES not in env
    assert env[constants.ENV_NUM_PROCESSES] == "4"


_CHILD = """
import os
from skypilot_tpu.parallel.distributed import initialize_from_env
topo = initialize_from_env()
import jax
assert jax.process_count() == 2, jax.process_count()
assert jax.process_index() == topo.process_id
print("RESULT", topo.process_id, jax.device_count(), flush=True)
"""


def test_jax_distributed_initializes_from_injected_env():
    """Two CPU processes rendezvous using ONLY the env the driver
    injects — the contract a real multi-host slice job relies on."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    meta = _meta(n_slices=2, hosts_per_slice=1)
    procs = []
    for hid in (0, 1):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(build_job_env(meta, 1, meta["hosts"][hid]))
        env[constants.ENV_COORDINATOR] = f"127.0.0.1:{port}"
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep +
            env.get("PYTHONPATH", ""))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = [p.communicate(timeout=120) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}\n{err}"
    results = sorted(o.strip().splitlines()[-1] for o, _ in outs)
    assert results[0].startswith("RESULT 0")
    assert results[1].startswith("RESULT 1")
