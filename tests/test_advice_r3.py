"""Regression tests for round-3 advisor findings: cancel-during-launch,
MoE zigzag layout, launch-slot reap race, hostd stdin transport,
single-file mount uploads."""

import dataclasses
import time

import pytest


@pytest.fixture(autouse=True)
def sky_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))


# -- jobs: cancel during launch must not be resurrected ---------------------

def test_transition_to_running_honors_cancelling():
    from skypilot_tpu.jobs import state
    jid = state.add("j", {"run": "true"}, "FAILOVER")
    state.set_status(jid, state.ManagedJobStatus.STARTING)
    assert state.transition_to_running(jid)
    assert state.get(jid)["status"] == state.ManagedJobStatus.RUNNING

    jid2 = state.add("j2", {"run": "true"}, "FAILOVER")
    state.set_status(jid2, state.ManagedJobStatus.STARTING)
    # A cancel lands mid-provision...
    state.set_status(jid2, state.ManagedJobStatus.CANCELLING)
    # ...so the post-launch RUNNING write must not apply.
    assert not state.transition_to_running(jid2)
    assert state.get(jid2)["status"] == state.ManagedJobStatus.CANCELLING


def test_transition_to_running_honors_terminal():
    from skypilot_tpu.jobs import state
    jid = state.add("j", {"run": "true"}, "FAILOVER")
    state.set_status(jid, state.ManagedJobStatus.CANCELLED)
    assert not state.transition_to_running(jid)
    assert state.get(jid)["status"] == state.ManagedJobStatus.CANCELLED


# -- jobs: launch-slot reaping ----------------------------------------------

def test_fresh_null_pid_slot_not_reaped(monkeypatch):
    """A slot whose controller hasn't recorded its pid yet (Popen just
    returned) must survive reaping; only a stale NULL-pid slot frees."""
    from skypilot_tpu.jobs import state
    monkeypatch.setenv("SKYTPU_JOBS_MAX_LAUNCHES", "1")
    j1 = state.add("a", {"run": "true"}, "FAILOVER")
    j2 = state.add("b", {"run": "true"}, "FAILOVER")
    state.acquire_launch_slot(j1)  # pid still NULL — newly spawned
    with pytest.raises(TimeoutError):
        state.acquire_launch_slot(j2, poll=0.05, timeout=0.3)
    # Backdate j1's claim beyond the grace window -> corpse, reapable.
    with state._db() as c:
        c.execute(
            "UPDATE managed_jobs SET launch_started_at=? WHERE job_id=?",
            (time.time() - 2 * state._NULL_PID_GRACE_SECONDS, j1))
    state.acquire_launch_slot(j2, poll=0.05, timeout=5)
    assert state.launch_window(j2)[0] is not None


def test_live_pid_slot_not_reaped(monkeypatch):
    import os

    from skypilot_tpu.jobs import state
    monkeypatch.setenv("SKYTPU_JOBS_MAX_LAUNCHES", "1")
    j1 = state.add("a", {"run": "true"}, "FAILOVER")
    j2 = state.add("b", {"run": "true"}, "FAILOVER")
    state.set_controller_pid(j1, os.getpid())  # alive forever (us)
    state.acquire_launch_slot(j1)
    with state._db() as c:
        c.execute(
            "UPDATE managed_jobs SET launch_started_at=? WHERE job_id=?",
            (time.time() - 3600, j1))
    with pytest.raises(TimeoutError):
        state.acquire_launch_slot(j2, poll=0.05, timeout=0.3)


# -- hostd: stdin is data, never shell --------------------------------------

def test_hostd_stdin_marker_passthrough():
    """stdin containing the old heredoc EOF marker must pass through
    byte-for-byte (previously it truncated the input and executed the
    remainder as shell on the pod)."""
    from skypilot_tpu.runtime import hostd
    payload = "line1\nSKYTPU_STDIN_EOF\necho pwned\n"
    resp = hostd.handle_request(
        {"op": "run", "cmd": "cat", "stdin": payload})
    assert resp["ok"] and resp["rc"] == 0
    assert resp["out"] == payload


def test_hostd_run_without_stdin_still_works():
    from skypilot_tpu.runtime import hostd
    resp = hostd.handle_request({"op": "run", "cmd": "echo hi"})
    assert resp["ok"] and resp["out"].strip() == "hi"


# -- storage: single-file mounts --------------------------------------------

class FakeRun:
    def __init__(self):
        self.cmds = []

    def __call__(self, cmd):
        self.cmds.append(cmd)
        return 0, ""


def test_gcs_upload_file_uses_cp(tmp_path):
    from skypilot_tpu.data import storage
    f = tmp_path / "cfg.json"
    f.write_text("{}")
    run = FakeRun()
    storage.GcsStore("b", run=run).upload(str(f), "run1/mount0")
    assert len(run.cmds) == 1
    assert "storage cp" in run.cmds[0]
    assert run.cmds[0].endswith("gs://b/run1/mount0/")
    assert "rsync" not in run.cmds[0]


def test_gcs_upload_dir_still_rsyncs(tmp_path):
    from skypilot_tpu.data import storage
    d = tmp_path / "src"
    d.mkdir()
    run = FakeRun()
    storage.GcsStore("b", run=run).upload(str(d), "run1/workdir")
    assert any("rsync -r" in c for c in run.cmds)


def test_s3_upload_file_uses_cp(tmp_path):
    from skypilot_tpu.data import storage
    f = tmp_path / "cfg.json"
    f.write_text("{}")
    run = FakeRun()
    storage.S3Store("b", run=run).upload(str(f), "run1/mount0")
    assert any("s3 cp" in c for c in run.cmds)
    assert not any("s3 sync" in c for c in run.cmds)


def test_sync_auto_command_probes_object(tmp_path):
    """Cluster-side materialize must not guess file-vs-dir from the URL
    (extensionless files materialized as empty dirs); the generated
    command probes the object and picks cp or rsync host-side."""
    from skypilot_tpu.data import cloud_stores
    gs = cloud_stores.get_storage_from_path("gs://b/run1/mount0/run_task")
    cmd = gs.make_sync_auto_command("gs://b/run1/mount0/run_task",
                                    "/home/u/bin/run_task")
    assert "gcloud storage objects describe" in cmd
    assert "gcloud storage cp" in cmd and "rsync -r" in cmd
    s3 = cloud_stores.get_storage_from_path("s3://bkt/sub/name")
    cmd = s3.make_sync_auto_command("s3://bkt/sub/name", "/d/name")
    assert "head-object --bucket bkt --key sub/name" in cmd
    assert "s3 cp" in cmd and "s3 sync" in cmd


def test_sync_auto_command_behavior(tmp_path):
    """Run the generated gs auto-command against a stub gcloud: object
    -> cp; definitive not-found -> rsync; any other probe failure (auth,
    metadata timeout) -> loud non-zero exit, NO silent empty dir."""
    import subprocess

    from skypilot_tpu.data import cloud_stores
    bindir = tmp_path / "bin"
    bindir.mkdir()
    log = tmp_path / "calls.log"
    stub = bindir / "gcloud"
    stub.write_text(f"""#!/bin/sh
case "$*" in
  *"objects describe"*isfile*) exit 0;;
  *"objects describe"*isdir*) echo "ERROR: Not Found (404)"; exit 1;;
  *"objects describe"*) echo "ERROR: could not refresh credentials"; exit 1;;
  *" cp "*) echo CP >> {log}; exit 0;;
  *rsync*) echo RSYNC >> {log}; exit 0;;
esac
exit 2
""")
    stub.chmod(0o755)
    gs = cloud_stores.get_storage_from_path("gs://b/x")
    env = {"PATH": f"{bindir}:/usr/bin:/bin", "HOME": str(tmp_path)}

    def run(src):
        cmd = gs.make_sync_auto_command(src, str(tmp_path / "dst"))
        return subprocess.run(["bash", "-c", cmd], env=env,
                              capture_output=True, text=True)

    assert run("gs://b/sub/isfile").returncode == 0
    assert log.read_text().strip() == "CP"
    log.write_text("")
    assert run("gs://b/sub/isdir").returncode == 0
    assert log.read_text().strip() == "RSYNC"
    log.write_text("")
    r = run("gs://b/sub/authfail")
    assert r.returncode != 0
    assert "credentials" in r.stderr
    assert log.read_text() == ""  # neither cp nor rsync ran


def test_set_status_guards_forward_writes():
    """RECOVERING/STARTING must not clobber CANCELLING, and CANCELLING
    must not clobber a terminal state (the recovery-path half of the
    cancel-during-launch race)."""
    from skypilot_tpu.jobs import state
    jid = state.add("j", {"run": "true"}, "FAILOVER")
    state.set_status(jid, state.ManagedJobStatus.CANCELLING)
    assert not state.set_status(jid, state.ManagedJobStatus.RECOVERING)
    assert not state.set_status(jid, state.ManagedJobStatus.STARTING)
    assert state.get(jid)["status"] == state.ManagedJobStatus.CANCELLING
    # Terminal writes are unconditional (cancel completes).
    assert state.set_status(jid, state.ManagedJobStatus.CANCELLED)
    # CANCELLING never resurrects a finished job.
    assert not state.set_status(jid, state.ManagedJobStatus.CANCELLING)
    assert state.get(jid)["status"] == state.ManagedJobStatus.CANCELLED


# -- MoE zigzag layout -------------------------------------------------------

def test_moe_zigzag_matches_contiguous():
    """MoE forward under rules seq_layout=zigzag == the plain-ring
    forward (moe.forward_hidden now owns the permute, like llama's).
    Full capacity so routing keeps every token — drop priority is
    order-dependent, everything else is order-agnostic. float32: bf16
    summation-reorder noise flips borderline top-k expert picks, which
    discretely amplifies into large output diffs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from skypilot_tpu.models import moe
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel import sharding as sh
    cfg = dataclasses.replace(
        moe.CONFIGS["moe-tiny"],
        capacity_factor=float(moe.CONFIGS["moe-tiny"].n_experts),
        dtype=jnp.float32)
    params = moe.init_params(jax.random.key(0), cfg)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(dp=2, sp=2, tp=2))
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 1,
                                cfg.vocab_size, dtype=jnp.int32)
    zz_rules = dict(sh.ACT_RULES, seq_layout="zigzag")
    logits_zz, aux_zz = moe.forward(params, tokens, cfg, mesh=mesh,
                                    rules=zz_rules)
    logits, aux = moe.forward(params, tokens, cfg, mesh=mesh)
    np.testing.assert_allclose(np.asarray(logits_zz), np.asarray(logits),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(aux_zz), np.asarray(aux),
                               rtol=1e-5)


def test_moe_zigzag_nondivisible_falls_back():
    """Seq not divisible by 2*sp: the layout key is dropped and the
    contiguous path runs instead of mis-permuting."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from skypilot_tpu.models import moe
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel import sharding as sh
    cfg = moe.CONFIGS["moe-tiny"]
    params = moe.init_params(jax.random.key(0), cfg)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(dp=2, sp=2, tp=2))
    tokens = jax.random.randint(jax.random.key(1), (2, 66), 1,
                                cfg.vocab_size, dtype=jnp.int32)
    zz_rules = dict(sh.ACT_RULES, seq_layout="zigzag")
    out_zz, _ = moe.forward(params, tokens, cfg, mesh=mesh, rules=zz_rules)
    out, _ = moe.forward(params, tokens, cfg, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out_zz), np.asarray(out),
                               rtol=2e-4, atol=2e-4)
