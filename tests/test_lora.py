"""LoRA finetuning: init identity, adapter-only training, sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import lora, trainer


@pytest.fixture(scope="module")
def cfg():
    return llama.CONFIGS["llama3-tiny"]


@pytest.fixture(scope="module")
def base(cfg):
    return llama.init_params(jax.random.key(0), cfg)


@pytest.fixture(scope="module")
def lc():
    return lora.LoRAConfig(rank=4, alpha=8.0)


def test_identity_at_init(cfg, base, lc):
    """B starts at zero: merged model == base model exactly."""
    adapters = lora.init_lora_params(jax.random.key(1), cfg, lc)
    merged = lora.merge(base, adapters, lc)
    tokens = jnp.asarray([[3, 17, 42, 7]], jnp.int32)
    ref = llama.forward(base, tokens, cfg)
    got = llama.forward(merged, tokens, cfg)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_trainable_fraction_tiny(cfg, lc):
    n_lora = lora.num_trainable_params(cfg, lc)
    n_base = cfg.num_params()
    assert 0 < n_lora < n_base * 0.2


def test_adapters_learn_base_frozen(cfg, base, lc):
    tc = trainer.TrainConfig(learning_rate=5e-3, warmup_steps=1,
                             total_steps=20)
    state = lora.create_lora_state(cfg, lc, tc, None)
    step = lora.make_lora_train_step(cfg, lc, tc, None)
    batch = trainer.synthetic_batch(cfg, 2, 32)
    snapshot = jax.tree.map(lambda x: np.asarray(x).copy(), base)
    first = None
    for _ in range(8):
        state, metrics = step(state, base, batch)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first
    # Adapters actually moved; the B factor is no longer all-zero.
    b = state["params"]["wq"]["b"]
    assert float(jnp.max(jnp.abs(b))) > 0
    # The base is bitwise untouched (no donation, no updates).
    jax.tree.map(
        lambda a, s: np.testing.assert_array_equal(np.asarray(a), s),
        base, snapshot)


def test_sharded_lora_step(cfg, base, lc):
    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(dp=2, fsdp=2, tp=2))
    tc = trainer.TrainConfig(warmup_steps=1, total_steps=4)
    state = lora.create_lora_state(cfg, lc, tc, mesh)
    step = lora.make_lora_train_step(cfg, lc, tc, mesh)
    import skypilot_tpu.parallel.sharding as sh
    base_sh = sh.logical_to_sharding(
        llama.param_logical_axes(cfg), mesh, sh.DEFAULT_RULES,
        shapes=base)
    base_s = jax.device_put(base, base_sh)
    batch = trainer.synthetic_batch(cfg, 4, 32)
    state, metrics = step(state, base_s, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert len(state["params"]["wq"]["a"].sharding.device_set) == 8


def test_unknown_target_rejected(cfg):
    with pytest.raises(ValueError):
        lora.init_lora_params(
            jax.random.key(0), cfg,
            lora.LoRAConfig(targets=("w_nonexistent",)))
