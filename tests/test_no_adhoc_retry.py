"""Lint: all retrying goes through ``utils/retry.py``.

Two patterns are rejected anywhere under ``skypilot_tpu/``:

1. ``time.sleep`` (any ``*.sleep(...)`` call) lexically inside an
   ``except`` handler that sits inside a loop — the signature of a
   hand-rolled retry/backoff loop. Those loops each reinvent backoff
   math and deadline handling, which is exactly what made recovery
   behavior untestable before the chaos layer; route them through
   ``retry.call`` / ``retry.pause`` instead.
2. Broad swallow-and-continue: ``except Exception:`` (or a bare
   ``except:``) whose body is only ``pass`` — it silently eats the
   failures the chaos harness injects. Catch the narrow type, or
   record a typed event before continuing.

A fixed allowlist grandfathers pre-policy call sites; do NOT add
entries — new code starts at zero.
"""

import ast
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "skypilot_tpu")

# path (relative to skypilot_tpu/) -> max allowed hits.
SLEEP_ALLOWLIST = {
    # `skytpu top`'s DOWN-frame render loop: the "retry" is the live
    # monitoring view itself surviving an API-server outage.
    "client/cli.py": 1,
    # The flock acquisition poll inside the lock primitive — the
    # bottom of the stack the retry module itself sits on.
    "utils/timeline.py": 1,
}
EXCEPT_PASS_ALLOWLIST = {
    "benchmark/benchmark_utils.py": 1,
    "runtime/driver.py": 1,
    "observability/aggregate.py": 1,
    "observability/health.py": 1,
    "usage/usage_lib.py": 1,
    "provision/gcp_auth.py": 2,
}


def _scan(path):
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    sleeps, passes = [], []

    def in_handler_sleeps(handler):
        for sub in ast.walk(handler):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "sleep"):
                yield sub.lineno

    def walk(node, loop_depth):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.For, ast.While, ast.AsyncFor)):
                walk(child, loop_depth + 1)
                continue
            if isinstance(child, ast.ExceptHandler):
                broad = child.type is None or (
                    isinstance(child.type, ast.Name)
                    and child.type.id in ("Exception", "BaseException"))
                if broad and all(isinstance(s, ast.Pass)
                                 for s in child.body):
                    passes.append(child.lineno)
                if loop_depth > 0:
                    sleeps.extend(in_handler_sleeps(child))
                    continue   # already scanned the whole handler
            # A nested def/lambda resets loop context: a sleep inside a
            # callback defined within a loop is not this loop's retry.
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                walk(child, 0)
            else:
                walk(child, loop_depth)

    walk(tree, 0)
    return sleeps, passes


def _files():
    for dirpath, _, names in os.walk(PKG):
        if "__pycache__" in dirpath:
            continue
        for name in sorted(names):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def test_no_sleep_in_except_retry_loops():
    violations = []
    for path in _files():
        rel = os.path.relpath(path, PKG)
        if rel == os.path.join("utils", "retry.py"):
            continue   # the policy module IS the allowed sleeper
        sleeps, _ = _scan(path)
        if len(sleeps) > SLEEP_ALLOWLIST.get(rel, 0):
            violations.append(f"{rel}: sleep inside except at lines "
                              f"{sleeps} (allowed: "
                              f"{SLEEP_ALLOWLIST.get(rel, 0)})")
    assert not violations, (
        "ad-hoc retry loop (time.sleep inside an except handler inside "
        "a loop) — use skypilot_tpu.utils.retry (retry.call / "
        "retry.pause) so backoff, deadlines, and telemetry stay "
        "uniform:\n  " + "\n  ".join(violations))


def test_no_broad_except_pass():
    violations = []
    for path in _files():
        rel = os.path.relpath(path, PKG)
        _, passes = _scan(path)
        if len(passes) > EXCEPT_PASS_ALLOWLIST.get(rel, 0):
            violations.append(f"{rel}: broad except-pass at lines "
                              f"{passes} (allowed: "
                              f"{EXCEPT_PASS_ALLOWLIST.get(rel, 0)})")
    assert not violations, (
        "`except Exception: pass` swallows the failures the chaos "
        "harness injects — catch the narrow type or record a typed "
        "event:\n  " + "\n  ".join(violations))


@pytest.mark.parametrize("rel", sorted({**SLEEP_ALLOWLIST,
                                        **EXCEPT_PASS_ALLOWLIST}))
def test_allowlist_entries_still_exist(rel):
    """A renamed/cleaned-up file must drop its allowlist entry, or the
    budget silently covers a future regression elsewhere."""
    assert os.path.exists(os.path.join(PKG, rel)), (
        f"{rel} gone — remove its allowlist entry")
