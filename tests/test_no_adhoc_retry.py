"""Lint: all retrying goes through ``utils/retry.py``.

Thin wrapper over the ``adhoc-retry`` checker in
``skypilot_tpu/analysis`` (see docs/analysis.md). Rejected patterns
are unchanged from the original standalone lint:

1. ``time.sleep`` inside an ``except`` handler inside a loop — a
   hand-rolled retry/backoff loop; route through ``retry.call`` /
   ``retry.pause``.
2. Broad ``except Exception:``/bare ``except:`` whose body is only
   ``pass`` — silently eats the failures the chaos harness injects.

The fixed allowlists became ``lint_baseline.json`` entries with the
same budgets; stale-baseline detection replaces the old
entries-still-exist test.
"""

import os

from skypilot_tpu import analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run():
    return analysis.run(root=REPO, checkers=["adhoc-retry"],
                        use_cache=False)


def test_no_adhoc_retry_or_broad_swallow():
    res = _run()
    assert not res.new, (
        "ad-hoc retry loop or broad except-pass — use "
        "skypilot_tpu.utils.retry (retry.call / retry.pause) and "
        "narrow catches:\n  "
        + "\n  ".join(f.format() for f in res.new))


def test_grandfathered_budgets_not_rotted():
    res = _run()
    assert not res.stale, (
        "stale adhoc-retry baseline entries (remove them from "
        f"lint_baseline.json): {res.stale}")
    assert not res.unjustified, (
        f"adhoc-retry baseline entries lack justification: "
        f"{res.unjustified}")


def test_retry_module_is_the_allowed_sleeper():
    """utils/retry.py IS the policy module: its sleeps never flag."""
    from skypilot_tpu.analysis.core import FileContext, get_checker
    src = ("import time\n"
           "def call(op):\n"
           "    for _ in range(3):\n"
           "        try:\n"
           "            return op()\n"
           "        except OSError:\n"
           "            time.sleep(1)\n")
    checker = get_checker("adhoc-retry")
    inside = checker.check_file(FileContext(
        "<fixture>", "skypilot_tpu/utils/retry.py", source=src))
    assert not inside
    outside = checker.check_file(FileContext(
        "<fixture>", "skypilot_tpu/utils/other.py", source=src))
    assert [f.rule for f in outside] == ["sleep-in-except"]
