"""Speculative decoding: n-gram draft + fixed-K batched verify.

Tier-1 guards for the spec path's one non-negotiable claim — greedy
output is EXACTLY the spec-off output (fp32 and int8, paged and
contiguous, warm-prefix and chunked-admission prompts, EOS and
max_len edges) — plus the rollback invariant (rejected draft rows
leave the cache bit-equal to a never-drafted one), the drafter's
host-side semantics, the K knob, and the acceptance-collapse
fallback.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.infer import kvcache, sampling
from skypilot_tpu.models import llama


@pytest.fixture(scope="module")
def cfg():
    # fp32: accumulation differences cannot hide behind bf16 eps (the
    # PR 6 test_infer_tp lesson); the int8 tests cover the quantized
    # cache, whose integer accumulation is exact.
    return dataclasses.replace(llama.CONFIGS["llama3-tiny"],
                               dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.key(0), cfg)


def _prompts(cfg, n=3, length=12, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, length).tolist()
            for _ in range(n)]


def _engine(params, cfg, spec_k=None, slots=4, max_len=128,
            buckets=(32,), **kw):
    return eng.InferenceEngine(params, cfg, n_slots=slots,
                               max_len=max_len, prompt_buckets=buckets,
                               spec_k=spec_k, **kw)


def _replay_drafter(outputs, transform=None):
    """Drafter factory replaying a known continuation per prompt: the
    ORACLE (transform=None — every draft accepted) or a derived
    always-wrong variant (e.g. transform shifting each token — every
    draft rejected). One implementation of the catch_up/draft
    protocol for every test that scripts drafts."""

    class Replay:
        def __init__(self, req):
            self.out = outputs[tuple(req.prompt)]
            self.seen = 0

        def catch_up(self, prompt, generated):
            self.seen = len(generated)

        def draft(self, k):
            nxt = self.out[self.seen:self.seen + k]
            return ([transform(t) for t in nxt] if transform
                    else list(nxt))

    return Replay


# -- drafter ----------------------------------------------------------------

def test_drafter_match_and_miss():
    d = eng.NGramDrafter([1, 2, 3, 9, 1, 2], n=2)
    # Tail [1, 2] occurred at position 0 with continuation [3, 9, 1].
    assert d.draft(3) == [3, 9, 1]
    assert d.draft(1) == [3]
    # Tail with no earlier occurrence: miss drafts nothing.
    assert eng.NGramDrafter([1, 2, 3, 4, 5], n=2).draft(4) == []


def test_drafter_self_extends_through_cycles():
    # A period-2 cycle: the nearest match sits at the tail, but the
    # draft keeps following the cycle through its own proposal.
    d = eng.NGramDrafter([7, 8, 7, 8, 7, 8], n=2)
    assert d.draft(6) == [7, 8, 7, 8, 7, 8]


def test_drafter_degenerate_short_context():
    assert eng.NGramDrafter([], n=2).draft(4) == []
    assert eng.NGramDrafter([5], n=2).draft(4) == []
    assert eng.NGramDrafter([5, 5], n=3).draft(4) == []
    # k <= 0 never drafts.
    assert eng.NGramDrafter([1, 2, 1, 2], n=2).draft(0) == []


def test_drafter_extend_and_catch_up():
    d = eng.NGramDrafter([1, 2, 3], n=2)
    d.catch_up([1, 2, 3], [1, 2])      # two tokens committed elsewhere
    assert d.tokens == [1, 2, 3, 1, 2]
    # [1, 2] (position 0) now has a continuation -> drafting works.
    assert d.draft(2) == [3, 1]
    # catch_up is idempotent.
    d.catch_up([1, 2, 3], [1, 2])
    assert d.tokens == [1, 2, 3, 1, 2]


# -- knobs ------------------------------------------------------------------

def test_spec_k_env_knob_and_clamp(params, cfg, monkeypatch):
    monkeypatch.setenv("SKYTPU_SPEC_K", "3")
    assert _engine(params, cfg).spec_k == 3
    monkeypatch.setenv("SKYTPU_SPEC_K", "0")
    assert _engine(params, cfg).spec_k == 0
    monkeypatch.delenv("SKYTPU_SPEC_K")
    # Library default: off. Ctor arg wins over env, clamped to [0, 16].
    assert _engine(params, cfg).spec_k == 0
    assert _engine(params, cfg, spec_k=-5).spec_k == 0
    assert _engine(params, cfg, spec_k=99).spec_k == 16
    # Greedy-exact only: temperature sampling forces spec off.
    e = _engine(params, cfg, spec_k=4,
                sampling_params=sampling.SamplingParams(temperature=0.7))
    assert e.spec_k == 0


# -- parity -----------------------------------------------------------------

@pytest.mark.parametrize("kv_block", [0, 8], ids=["contiguous", "paged"])
@pytest.mark.parametrize("kv_int8", [False, True], ids=["fp32", "int8"])
def test_spec_parity_layouts_and_dtypes(params, cfg, kv_block, kv_int8):
    """The headline guarantee: spec-on greedy generation is identical
    to spec-off, across both storage layouts and the int8 KV cache."""
    prompts = _prompts(cfg)
    off = _engine(params, cfg, kv_block=kv_block, kv_int8=kv_int8)
    want = off.generate(prompts, max_new_tokens=24)
    on = _engine(params, cfg, spec_k=4, kv_block=kv_block,
                 kv_int8=kv_int8)
    assert on.generate(prompts, max_new_tokens=24) == want
    assert on._spec_drafted_total >= 0  # path exercised without error


def test_spec_parity_weights_int8(cfg):
    """w8a8 decode: the verify program runs the same quantized matmuls
    as the plain burst."""
    params, qw = kvcache.random_quantized_params(cfg)
    prompts = _prompts(cfg, n=2)
    kw = dict(n_slots=2, max_len=96, prompt_buckets=(32,),
              qweights=qw, kv_block=8)
    want = eng.InferenceEngine(params, cfg, **kw).generate(
        prompts, max_new_tokens=16)
    got = eng.InferenceEngine(params, cfg, spec_k=3, **kw).generate(
        prompts, max_new_tokens=16)
    assert got == want


def test_spec_parity_warm_prefix_and_chunked_admission(params, cfg):
    """Spec decode composes with chunked prefill + prefix reuse: cold
    (chunked) and warm (suffix-only) admissions generate the spec-off
    tokens, and the warm pass still hits the prefix cache."""
    system = list(range(5, 21))                     # 16 tokens, 2 chunks
    pa, pb = system + [31, 32, 33], system + [41, 42]
    kw = dict(buckets=(48,), max_len=96, prefill_chunk=8,
              prefix_pool=4, kv_block=8)
    off = _engine(params, cfg, **kw)
    on = _engine(params, cfg, spec_k=4, **kw)
    want_a = off.generate([pa], max_new_tokens=10)[0]
    off.finished.clear()
    want_b = off.generate([pb], max_new_tokens=10)[0]   # warm hit
    got_a = on.generate([pa], max_new_tokens=10)[0]
    on.finished.clear()
    got_b = on.generate([pb], max_new_tokens=10)[0]
    (req_b,) = on.finished
    assert got_a == want_a and got_b == want_b
    assert req_b.cached_len == 16                   # hit survived spec


def test_spec_bursts_interleave_with_chunked_admission(params, cfg):
    """A verify burst scatters K+1 garbage rows for EVERY slot — a
    slot mid-chunked-prefill (claimed, length stamped to max_len) must
    drop them exactly as plain bursts do, or finished chunks corrupt.
    Same interleave as test_chunked_prefill_interleaves_with_decode,
    spec on."""
    kw = dict(max_len=96, buckets=(48,), prefill_chunk=8,
              prefix_pool=0, kv_block=8)
    short, long_p = [3, 1, 4], list(range(1, 29))   # 28 -> 4 chunks
    solo = _engine(params, cfg, **kw)
    want_short = solo.generate([short], max_new_tokens=12)[0]
    solo.finished.clear()
    want_long = solo.generate([long_p], max_new_tokens=4)[0]

    e = _engine(params, cfg, spec_k=4, **kw)
    e.add_request(short, max_new_tokens=12)
    e.step_burst(max_burst=2)                 # short active, decoding
    e.add_request(long_p, max_new_tokens=4)   # chunks interleave
    e.run_to_completion(max_burst=2)
    by_prompt = {tuple(r.prompt): r.tokens for r in e.finished}
    assert by_prompt[tuple(short)] == want_short
    assert by_prompt[tuple(long_p)] == want_long


def test_spec_parity_at_max_len_boundary(params, cfg):
    """Near max_len a slot lacks K+1 rows of headroom: it rides verify
    bursts with an empty draft (spare window rows past max_len drop),
    and generation still matches spec-off to the cap."""
    prompts = _prompts(cfg, n=2, length=12)
    off = _engine(params, cfg, slots=2, max_len=32)
    want = off.generate(prompts, max_new_tokens=64)   # capped by rows
    on = _engine(params, cfg, spec_k=4, slots=2, max_len=32)
    got = on.generate(prompts, max_new_tokens=64)
    assert got == want
    assert all(len(p) + len(t) == 32 for p, t in zip(prompts, want))


def test_tight_slot_does_not_disable_neighbors_spec(params, cfg):
    """One request within K+1 rows of max_len must not turn
    speculation off engine-wide: the tight slot drafts nothing while
    its neighbor keeps drafting (and accepting, via an oracle), and
    both outputs match spec-off exactly."""
    tight_p = list(range(1, 21))                  # 20 rows, cap at 32
    roomy_p = [3, 1, 4]
    off = _engine(params, cfg, slots=2, max_len=32, buckets=(24,))
    want_t = off.generate([tight_p], max_new_tokens=64)[0]
    off.finished.clear()
    want_r = off.generate([roomy_p], max_new_tokens=12)[0]
    oracle = {tuple(tight_p): want_t, tuple(roomy_p): want_r}
    on = _engine(params, cfg, spec_k=4, slots=2, max_len=32,
                 buckets=(24,), spec_drafter=_replay_drafter(oracle))
    on.add_request(tight_p, max_new_tokens=64)    # tight within bursts
    on.add_request(roomy_p, max_new_tokens=12)
    on.run_to_completion(max_burst=4)
    by_prompt = {tuple(r.prompt): r for r in on.finished}
    assert by_prompt[tuple(tight_p)].tokens == want_t
    assert by_prompt[tuple(roomy_p)].tokens == want_r
    # The roomy slot drafted (oracle: all accepted) even while the
    # tight slot was pinned to empty drafts.
    assert by_prompt[tuple(roomy_p)].spec_drafted > 0
    assert (by_prompt[tuple(roomy_p)].spec_accepted
            == by_prompt[tuple(roomy_p)].spec_drafted)
    # The tight slot stopped drafting once headroom ran out: it can
    # never have drafted past the point where rows + K + 1 > max_len.
    assert by_prompt[tuple(tight_p)].spec_drafted <= 32 - 20 - 5 + 4


def test_spec_parity_with_eos_mid_commit(params, cfg):
    """EOS inside an accepted run retires the request at the same
    token spec-off does (surplus committed tokens are discarded
    host-side)."""
    prompts = _prompts(cfg, n=2)
    ref = _engine(params, cfg).generate(prompts, max_new_tokens=24)
    eos = ref[0][len(ref[0]) // 2]                  # appears mid-output
    off = _engine(params, cfg)
    off.eos_id = eos
    want = off.generate(prompts, max_new_tokens=24)
    on = _engine(params, cfg, spec_k=4)
    on.eos_id = eos
    assert on.generate(prompts, max_new_tokens=24) == want
    assert any(len(t) < 24 for t in want)           # EOS actually fired


def test_spec_oracle_full_acceptance(params, cfg):
    """A drafter that replays the true continuation accepts everything:
    n_commit == K+1 per burst, acceptance rate exactly 1.0, and the
    output is still bit-identical (the bonus token past the draft is
    the plain path's next token)."""
    prompts = _prompts(cfg, n=2)
    want = _engine(params, cfg).generate(prompts, max_new_tokens=20)
    oracle = {tuple(p): o for p, o in zip(prompts, want)}
    on = _engine(params, cfg, spec_k=4,
                 spec_drafter=_replay_drafter(oracle))
    assert on.generate(prompts, max_new_tokens=20) == want
    assert on._spec_drafted_total > 0
    assert on._spec_accepted_total == on._spec_drafted_total


# -- rollback ---------------------------------------------------------------

def _seeded_cache(params, cfg, kv_int8, prompt, table=None):
    cache = (kvcache.init_cache(cfg, 2, 64, kv_int8=kv_int8)
             if table is None else
             kvcache.init_paged_cache(cfg, 2, 10, 8, kv_int8=kv_int8))
    prefix, logits = kvcache.prefill(
        params, jnp.asarray(prompt, jnp.int32),
        jnp.asarray(len(prompt), jnp.int32), cfg)
    first = int(np.argmax(np.asarray(logits)))
    cache = kvcache.insert(cache, prefix, jnp.asarray(0, jnp.int32),
                           jnp.asarray(len(prompt), jnp.int32),
                           jnp.asarray(first, jnp.int32), table=table)
    return cache


@pytest.mark.parametrize("layout", ["contiguous", "paged"])
@pytest.mark.parametrize("kv_int8", [False, True], ids=["fp32", "int8"])
def test_rollback_leaves_kv_bit_equal(params, cfg, kv_int8, layout):
    """Kernel-level rollback invariant: a verify burst whose draft is
    fully REJECTED leaves every committed row (and length/last_token)
    bit-equal to the same burst run with no draft at all — rejected
    rows sit past the committed length and are never readable. Paged:
    the 'rollback' is purely the length not advancing; no block
    moves."""
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    K = 4
    table = None
    if layout == "paged":
        # Slot 0 owns blocks 0..7 logically in order; slot 1 + the
        # sentinel column stay unmapped (the engine's claim shape).
        tbl = np.full((2, 9), 10, np.int32)
        tbl[0, :8] = np.arange(8)
        table = jnp.asarray(tbl)
    cache = _seeded_cache(params, cfg, kv_int8, prompt, table=table)
    active = jnp.asarray(np.array([True, False]))

    # The model's actual next tokens (so the wrong draft provably
    # mismatches at position 0).
    _, ref_toks, _ = kvcache.verify_draft_staged(
        params, cache, jnp.zeros((2, K), jnp.int32),
        jnp.zeros((2,), jnp.int32), active, K, cfg, table=table)
    wrong = (np.asarray(ref_toks)[0, 0] + 1) % cfg.vocab_size
    draft = np.zeros((2, K), np.int32)
    draft[0] = wrong

    rej, toks_r, commit_r = kvcache.verify_draft_staged(
        params, cache, jnp.asarray(draft),
        jnp.asarray(np.array([K, 0], np.int32)), active, K, cfg,
        table=table)
    bare, toks_b, commit_b = kvcache.verify_draft_staged(
        params, cache, jnp.zeros((2, K), jnp.int32),
        jnp.zeros((2,), jnp.int32), active, K, cfg, table=table)

    assert int(commit_r[0]) == 1 and int(commit_b[0]) == 1
    assert int(commit_r[1]) == 0                    # inactive slot
    assert int(toks_r[0, 0]) == int(toks_b[0, 0])
    n = int(bare["length"][0])
    assert n == len(prompt) + 1
    assert int(rej["length"][0]) == n
    assert int(rej["last_token"][0]) == int(bare["last_token"][0])
    for name in ("k", "v", "k_scale", "v_scale"):
        if name not in cache:
            continue
        a, b = np.asarray(rej[name]), np.asarray(bare[name])
        if layout == "contiguous":
            rows_a = a[:, 0, :n] if name in ("k", "v") else a[:, 0, :, :n]
            rows_b = b[:, 0, :n] if name in ("k", "v") else b[:, 0, :, :n]
        else:
            # Logical rows 0..n-1 live in blocks 0..ceil(n/8)-1; the
            # committed region is rows [0, n) of the gathered view.
            ga = a[:, np.arange(8)]
            gb = b[:, np.arange(8)]
            if name in ("k", "v"):
                rows_a = ga.reshape(a.shape[0], 64, *a.shape[3:])[:, :n]
                rows_b = gb.reshape(b.shape[0], 64, *b.shape[3:])[:, :n]
            else:
                rows_a = ga.transpose(0, 2, 1, 3).reshape(
                    a.shape[0], a.shape[2], 64)[:, :, :n]
                rows_b = gb.transpose(0, 2, 1, 3).reshape(
                    b.shape[0], b.shape[2], 64)[:, :, :n]
        assert np.array_equal(rows_a, rows_b), name


def test_rejected_drafts_roll_back_engine_level(params, cfg):
    """An always-wrong drafter: zero acceptance, every draft rolled
    back, output still exactly spec-off (each burst commits only the
    correction token)."""
    prompts = _prompts(cfg, n=2)
    want = _engine(params, cfg).generate(prompts, max_new_tokens=16)
    oracle = {tuple(p): o for p, o in zip(prompts, want)}
    # Drafts (true_next + 1) mod vocab — mismatch guaranteed.
    on = _engine(params, cfg, spec_k=3, spec_drafter=_replay_drafter(
        oracle, transform=lambda t: (t + 1) % cfg.vocab_size))
    on.spec_min_rate = 0.0                  # keep drafting to the end
    assert on.generate(prompts, max_new_tokens=16) == want
    assert on._spec_drafted_total > 0
    assert on._spec_accepted_total == 0


# -- fallback ---------------------------------------------------------------

def test_acceptance_collapse_falls_back_per_request(params, cfg):
    """A request whose drafts never verify stops drafting once it
    crosses the collapse floor (spec_off), and the engine's bursts
    degrade to plain decode — bounded waste, same tokens."""
    prompts = _prompts(cfg, n=1, length=8)
    want = _engine(params, cfg).generate(prompts, max_new_tokens=32)
    oracle = {tuple(p): o for p, o in zip(prompts, want)}
    # Drafts (true_next + 1) mod vocab — never accepted.
    on = _engine(params, cfg, spec_k=4, spec_drafter=_replay_drafter(
        oracle, transform=lambda t: (t + 1) % cfg.vocab_size))
    on.spec_min_drafted = 8
    got = on.generate(prompts, max_new_tokens=32)
    assert got == want
    (req,) = on.finished
    assert req.spec_off                       # collapse fired
    assert req.spec_accepted == 0
    # Drafting stopped shortly after the floor, not at the end.
    assert 8 <= req.spec_drafted < 31
    assert on._spec_drafted_total == req.spec_drafted


def test_no_draft_everywhere_runs_plain_burst(params, cfg):
    """spec_decode_burst declines (returns None) when no active slot
    drafted — a K+1-wide verify with nothing to verify would be
    strictly worse than a plain burst."""
    e = _engine(params, cfg, spec_k=4,
                spec_drafter=lambda req: eng.NGramDrafter(req.prompt))
    # Distinct-token prompt: no repeated 2-gram, drafter always misses.
    e.add_request(list(range(1, 9)), max_new_tokens=4)
    e.admit()
    assert e.spec_decode_burst() is None
    out = e.decode_burst(4)                   # falls through to plain
    assert out and e._spec_drafted_total == 0


# -- metrics + bench wiring -------------------------------------------------

def test_spec_metrics_and_gauge(params, cfg):
    from skypilot_tpu.observability import metrics as metrics_lib

    def val(name):
        fam = metrics_lib.REGISTRY.snapshot()[name]
        return fam["samples"][0]["value"]

    d0, a0, r0 = (val("skytpu_spec_drafted_total"),
                  val("skytpu_spec_accepted_total"),
                  val("skytpu_spec_rollbacks_total"))

    class AlwaysDraft:
        """Two fixed tokens per burst — drafting is guaranteed without
        depending on the random model's n-gram structure; whether they
        verify is irrelevant to counter consistency."""

        def __init__(self, req):
            pass

        def catch_up(self, prompt, generated):
            pass

        def draft(self, k):
            return [0, 1][:k]

    on = _engine(params, cfg, spec_k=3, spec_drafter=AlwaysDraft)
    on.spec_min_rate = 0.0
    on.generate(_prompts(cfg, n=1), max_new_tokens=12)
    drafted = val("skytpu_spec_drafted_total") - d0
    accepted = val("skytpu_spec_accepted_total") - a0
    rolled = val("skytpu_spec_rollbacks_total") - r0
    assert drafted == on._spec_drafted_total > 0
    assert accepted == on._spec_accepted_total
    assert rolled == drafted - accepted
    rate = val("skytpu_spec_acceptance_rate")
    assert rate == pytest.approx(accepted / drafted)


def test_spec_smoke_bench_wiring():
    """CI-sized bench pass: parity on every column of both phases,
    oracle acceptance is exactly 1.0 (deterministic — no dependence on
    the random model's loop behavior), the model drafter accepts on
    the non-repetitive workload where n-gram drafting is a wash, and
    the pipeline's draft dispatches structurally overlap verify
    windows. Wall-clock speedups are reported, never asserted, on
    CPU."""
    from skypilot_tpu.infer import bench_serve
    r = bench_serve.run_spec_smoke()
    # Phase B (repetition-heavy, PR 8's columns unchanged).
    assert r["parity_ok"] and r["oracle_parity_ok"]
    assert r["oracle_accept_rate"] == 1.0
    assert r["drafted"] > 0
    assert 0.0 <= r["accept_rate"] <= 1.0
    assert r["bursts_spec"] > 0 and r["bursts_oracle"] > 0
    # Oracle bursts commit up to K+1 tokens per SLOT each:
    # structurally fewer dispatches than one-token decoding would need.
    assert (r["bursts_oracle"] * (r["spec_k"] + 1) * r["requests"]
            >= r["decode_tokens"])
    # Phase A (non-repetitive, model drafter): parity in every mode,
    # the distilled draft accepts where prompt-lookup cannot, and the
    # pipeline's overlap is structurally proven from flight records.
    assert r["model_parity_ok"] and r["model_sync_parity_ok"]
    assert r["ngram_nonrep_parity_ok"]
    assert r["model_accept_rate"] > 0.9
    assert r["ngram_nonrep_accept_rate"] < 0.5   # the honest wash
    assert r["overlap_ok"] and r["draft_records"] > 0
    assert r["draft_reuse_hits"] > 0
