"""Device-truth attribution (ISSUE 16): the sampled device-time
calibrator, the analytical HBM ledger (+ the memory_stats fallback and
the leak audit), the roofline cost model, the bubble analyzer, the
hbm-headroom SLO rule, and the `skytpu top` / `skytpu flight` wiring.
"""

import json

import jax
import numpy as np
import pytest

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.models import llama
from skypilot_tpu.observability import attribution
from skypilot_tpu.observability import flight as fl
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import slo, tracing


def _counter_total(snap, name):
    if name not in snap:
        return 0.0
    return sum(s.get("value", s.get("count", 0))
               for s in snap[name]["samples"])


def _gauge_value(name, **labels):
    snap = metrics_lib.REGISTRY.snapshot()
    if name not in snap:
        return None
    for s in snap[name]["samples"]:
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            return s["value"]
    return None


# ---------------------------------------------------------------------------
# (a) The device-time calibrator.

def test_devtime_every_env(monkeypatch):
    monkeypatch.delenv("SKYTPU_DEVTIME_EVERY", raising=False)
    assert attribution.devtime_every() == 64
    monkeypatch.setenv("SKYTPU_DEVTIME_EVERY", "8")
    assert attribution.devtime_every() == 8
    monkeypatch.setenv("SKYTPU_DEVTIME_EVERY", "0")
    assert attribution.devtime_every() == 0
    monkeypatch.setenv("SKYTPU_DEVTIME_EVERY", "nonsense")
    assert attribution.devtime_every() == 64


def test_tick_cadence_first_dispatch_then_every_nth():
    cal = attribution.DeviceTimeCalibrator(every=4)
    got = [cal.tick("prog[a]") for _ in range(9)]
    # The first post-compile dispatch seeds the EWMA, then every 4th.
    assert got == [True, False, False, False,
                   True, False, False, False, True]
    # Keys count independently.
    assert cal.tick("prog[b]") is True


def test_tick_off_and_suppressed():
    cal = attribution.DeviceTimeCalibrator(every=0)
    assert not any(cal.tick("p") for _ in range(8))
    cal2 = attribution.DeviceTimeCalibrator(every=1)
    with metrics_lib.suppress():
        # Warmup sweeps never sample: a bracket would serialize the
        # sweep and poison the EWMA with compile-adjacent timings.
        assert cal2.tick("p") is False
    assert cal2.tick("p") is True


def test_ewma_update_estimate_and_metrics():
    before = metrics_lib.REGISTRY.snapshot()
    cal = attribution.DeviceTimeCalibrator(every=1, alpha=0.25)
    cal.update("prog[x]", 0.100)
    assert cal.estimate("prog[x]") == pytest.approx(0.100)
    cal.update("prog[x]", 0.200)
    # EWMA: prev + alpha * (x - prev).
    assert cal.estimate("prog[x]") == pytest.approx(0.125)
    assert cal.estimate("prog[never]") is None
    assert cal.estimate(None) is None
    after = metrics_lib.REGISTRY.snapshot()
    assert _counter_total(after, "skytpu_devtime_calibrations_total") \
        - _counter_total(before, "skytpu_devtime_calibrations_total") \
        == 2
    assert _gauge_value("skytpu_devtime_ewma_ms", program="prog[x]") \
        == pytest.approx(125.0)
    summ = cal.summary()
    assert summ["prog[x]"]["dev_ms"] == pytest.approx(125.0)
    assert summ["prog[x]"]["age_s"] >= 0


def test_timed_call_brackets_and_returns():
    cal = attribution.DeviceTimeCalibrator(every=1)
    out = cal.timed_call("prog[y]", lambda a, b: a + b,
                         np.ones(4), np.ones(4))
    np.testing.assert_array_equal(out, np.full(4, 2.0))
    assert cal.estimate("prog[y]") is not None
    assert cal.samples == 1


def test_compile_watch_calibrator_rides_hit_path_only():
    watch = fl.CompileWatch()
    cal = attribution.DeviceTimeCalibrator(every=1)
    watch.calibrator = cal
    wrapped = watch.wrap("prog", lambda x, k=0: np.asarray([x * k]),
                         ("k",))
    wrapped(2, k=3)            # first dispatch = compile, never timed
    assert cal.samples == 0
    assert watch.last_key == "prog[k=3]"
    wrapped(2, k=3)            # hit path: every=1 -> bracketed
    assert cal.samples == 1
    assert cal.estimate("prog[k=3]") is not None


# ---------------------------------------------------------------------------
# (b) The HBM ledger.

def test_ledger_set_snapshot_total_clear():
    led = attribution.HbmLedger()
    led.set_bytes("weights", 1000)
    led.set_bytes("kv_pool", 500)
    led.set_bytes("kv_used", -3)      # clamped, never negative
    assert led.snapshot() == {"weights": 1000, "kv_pool": 500,
                              "kv_used": 0}
    assert led.total() == 1500
    assert _gauge_value("skytpu_hbm_bytes", component="weights") == 1000
    led.clear()
    assert led.snapshot() == {} and led.total() == 0
    assert _gauge_value("skytpu_hbm_bytes", component="weights") == 0


def test_memstats_unavailable_typed_event_once():
    led = attribution.HbmLedger()

    class _NoStats:
        platform = "cpu"

    def _events():
        return [r for r in tracing.buffered_records()
                if r.get("name") == "attribution.memstats_unavailable"]

    n0 = len(_events())
    assert led.cross_check(device=_NoStats()) is None
    assert len(_events()) == n0 + 1
    # Once per ledger — never a per-refresh event storm.
    assert led.cross_check(device=_NoStats()) is None
    assert len(_events()) == n0 + 1


def test_memstats_cross_check_publishes():
    led = attribution.HbmLedger()

    class _Dev:
        platform = "tpu"

        def memory_stats(self):
            return {"bytes_in_use": 123456, "bytes_limit": 1000000}

    out = led.cross_check(device=_Dev())
    assert out == {"bytes_in_use": 123456, "bytes_limit": 1000000}
    assert _gauge_value("skytpu_hbm_device_bytes_in_use") == 123456
    assert _gauge_value("skytpu_hbm_limit_bytes") == 1000000


# ---------------------------------------------------------------------------
# (c) The roofline cost model.

def _roofline():
    return attribution.Roofline(
        param_count=1000, weight_bytes=2000, kv_token_bytes=16,
        d_model=8, n_layers=2, n_heads=2, head_dim=4, max_len=128,
        chunk_tokens=8)


def test_roofline_decode_burst():
    # k x rows tokens, k weight passes. attn = 4*L*nh*hd = 64 / token
    # / span row.
    flops, moved = _roofline().record_cost(
        "decode", {"k": 2, "span": 32}, 3, 6)
    assert flops == 2 * 1000 * 6 + 64 * 32 * 6
    assert moved == 2 * 2000 + 2 * 3 * 32 * 16 + 6 * 16


def test_roofline_wave_chunk_verify():
    rl = _roofline()
    flops, moved = rl.record_cost("wave", {"rows": 2, "bucket": 16},
                                  2, 2)
    # Causal prefill: rows*bucket tokens at mean span bucket/2.
    assert flops == 2 * 1000 * 32 + 64 * 8 * 32
    assert moved == 2000 + 2 * 8 * 16 + 32 * 16
    flops, moved = rl.record_cost("chunk", {"span": 64}, 1, 0)
    assert flops == 2 * 1000 * 8 + 64 * 64 * 8
    assert moved == 2000 + 64 * 16 + 8 * 16
    flops, moved = rl.record_cost("verify", {"k": 2, "span": 32}, 2, 4)
    assert flops == 2 * 1000 * 6 + 64 * 32 * 6
    assert moved == 2000 + 2 * 32 * 16 + 6 * 16


def test_roofline_unknown_burst_costs_nothing():
    assert _roofline().record_cost("preempt", {}, 1, 0) == (0, 0)


def test_device_peaks_env_override(monkeypatch):
    monkeypatch.setenv("SKYTPU_PEAK_TFLOPS", "918")
    monkeypatch.setenv("SKYTPU_PEAK_GBPS", "1638")
    f, b = attribution.device_peaks()
    assert f == pytest.approx(918e12)
    assert b == pytest.approx(1638e9)


# ---------------------------------------------------------------------------
# Bubble analysis.

def _rec(ts, dur, burst, **kw):
    r = {"kind": "flight", "ts_s": ts, "dur_s": dur, "burst": burst,
         "program": {}, "toks": 0}
    r.update(kw)
    return r


def _synthetic_window():
    return [
        _rec(0.000, 0.010, "wave"),
        _rec(0.015, 0.008, "chunk"),                     # 5ms admission
        _rec(0.026, 0.010, "decode", dev_ms_est=6.0),    # 3ms overhead
        _rec(0.040, 0.010, "verify"),                    # 4ms drafter
        _rec(0.052, 0.010, "decode", priorities={"1": 2}),  # 2ms qos
    ]


def test_analyze_bubbles_attributes_named_causes():
    rep = attribution.analyze_bubbles(_synthetic_window())
    assert rep["n_records"] == 5
    assert set(rep["by_cause"]) <= set(attribution.BUBBLE_CAUSES)
    assert rep["by_cause"]["admission"] == pytest.approx(5.0, abs=1e-6)
    assert rep["by_cause"]["drafter_sync"] == pytest.approx(4.0,
                                                            abs=1e-6)
    assert rep["by_cause"]["qos_reorder"] == pytest.approx(2.0,
                                                           abs=1e-6)
    # Inter-record gap (3ms) + within-record slack (dur 10 - dev 6).
    assert rep["by_cause"]["dispatch_overhead"] == \
        pytest.approx(7.0, abs=1e-6)
    assert rep["device_idle_ms"] == pytest.approx(18.0, abs=1e-6)
    assert rep["device_busy_ms"] == pytest.approx(44.0, abs=1e-6)
    # The acceptance bar: >= 90% of idle attributed to a named cause.
    assert rep["coverage"] >= 0.9
    assert rep["window_ms"] == pytest.approx(62.0, abs=1e-6)


def test_analyze_bubbles_residue_lowers_coverage():
    recs = [_rec(0.0, 0.010, "flush"),
            _rec(0.020, 0.010, "decode")]   # unnameable 10ms gap
    rep = attribution.analyze_bubbles(recs)
    assert rep["by_cause"] == {"host_other": pytest.approx(10.0)}
    assert rep["coverage"] == 0.0


def test_analyze_bubbles_empty_and_single():
    assert attribution.analyze_bubbles([])["coverage"] == 1.0
    rep = attribution.analyze_bubbles([_rec(0.0, 0.01, "decode")])
    assert rep["n_records"] == 1 and rep["bubbles"] == []


def test_idle_spans_are_perfetto_ready():
    spans = attribution.idle_spans(_synthetic_window())
    assert spans and all(s["kind"] == "span" for s in spans)
    names = {s["name"] for s in spans}
    assert "bubble:admission" in names
    assert all(s["end_s"] > s["start_s"] for s in spans)


def test_render_bubbles_report():
    out = attribution.render_bubbles(
        attribution.analyze_bubbles(_synthetic_window()))
    assert "idle by cause" in out
    assert "admission" in out and "largest bubbles" in out


# ---------------------------------------------------------------------------
# The hbm-headroom SLO rule.

def _hbm_rule():
    return next(r for r in slo.DEFAULT_RULES if r.name == "hbm-headroom")


def _hbm_fams(capacity_frac, occupancy_frac=0.3, limit=1000.0):
    return {
        "skytpu_hbm_bytes": {"type": "gauge", "samples": [
            ({"component": "weights"}, limit * capacity_frac * 0.6),
            ({"component": "kv_pool"}, limit * capacity_frac * 0.4),
            ({"component": "kv_used"}, limit * occupancy_frac),
            ({"component": "prefix_pinned"}, limit * occupancy_frac)]},
        "skytpu_hbm_limit_bytes": {"type": "gauge",
                                   "samples": [({}, limit)]},
    }


def test_hbm_headroom_rule_is_default_and_instant():
    rule = _hbm_rule()
    assert rule.kind in slo._INSTANT_KINDS
    assert rule.exclude_labels == {"component": ["kv_used",
                                                 "prefix_pinned"]}


def test_hbm_headroom_excludes_occupancy_views():
    rule = _hbm_rule()
    # Capacity 85% + occupancy views that would naively push the sum
    # past 1.0: the rule must read 0.85 (kv_used lives INSIDE kv_pool
    # — summing both double-counts), so no breach at threshold 0.92.
    wd = slo.Watchdog(rules=[rule])
    assert wd.observe(_hbm_fams(0.85), []) == []
    v = slo._eval_window(rule, None,
                         (0.0, _hbm_fams(0.85), []))
    assert v == pytest.approx(0.85)


def test_hbm_headroom_breaches_and_recovers():
    wd = slo.Watchdog(rules=[_hbm_rule()])
    ev = wd.observe(_hbm_fams(0.95), [])
    assert [e["event"] for e in ev] == ["slo.breach"]
    ev = wd.observe(_hbm_fams(0.5), [])
    assert [e["event"] for e in ev] == ["slo.recovered"]


def test_hbm_headroom_no_limit_no_verdict():
    rule = _hbm_rule()
    fams = _hbm_fams(0.99)
    del fams["skytpu_hbm_limit_bytes"]
    assert slo._eval_window(rule, None, (0.0, fams, [])) is None
    assert slo._eval_window(rule, None, (0.0, {}, [])) is None


# ---------------------------------------------------------------------------
# Engine integration: the ledger leak audit + attribution wiring.

def _tiny_engine(**overrides):
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    kw = dict(n_slots=4, max_len=128, prompt_buckets=(16, 64),
              prefill_chunk=8, prefix_pool=4, spec_k=0, kv_block=16,
              max_wave=4, pad_waves=True)
    kw.update(overrides)
    return eng.InferenceEngine(params, cfg, **kw)


def _prompts():
    rng = np.random.default_rng(7)
    return ([rng.integers(1, 40, 6).tolist() for _ in range(2)]
            + [rng.integers(1, 40, 20).tolist() for _ in range(2)])


def test_engine_ledger_leak_audit():
    """Admit -> retire -> clear must return every component gauge to
    its post-build baseline: the ledger mirrors the engine's own
    bookkeeping, so a residue here IS a KV/prefix leak."""
    e = _tiny_engine()
    base = e.hbm_ledger.snapshot()
    assert base["weights"] > 0 and base["kv_pool"] > 0
    assert base["workspace"] > 0
    assert base["kv_used"] == 0 and base["prefix_pinned"] == 0
    e.generate(_prompts(), max_new_tokens=6)
    e._refresh_hbm_ledger()
    mid = e.hbm_ledger.snapshot()
    # Capacity components are static for the engine's lifetime.
    for c in ("weights", "kv_pool", "prefix_pool", "draft_pool",
              "adapter_pool", "workspace"):
        assert mid[c] == base[c], c
    # The run left prefixes resident (that's the cache working) —
    # visible as pinned occupancy, not as capacity drift.
    assert mid["prefix_pinned"] > 0
    e.clear_prefix_cache()
    e._refresh_hbm_ledger()
    end = e.hbm_ledger.snapshot()
    assert end == base
    # And the published gauges agree with the snapshot.
    for comp, val in end.items():
        assert _gauge_value("skytpu_hbm_bytes", component=comp) == val


def test_engine_publishes_roofline_peaks_and_limit():
    e = _tiny_engine()
    assert _gauge_value("skytpu_roofline_peak_flops") > 0
    assert _gauge_value("skytpu_roofline_peak_hbm_bytes_per_s") > 0
    # No env override: the limit defaults to 1.25x the build-time
    # ledger total, so headroom starts at 80%.
    lim = _gauge_value("skytpu_hbm_limit_bytes")
    assert lim >= e.hbm_ledger.total()


def test_engine_devtime_calibrates_during_serving(monkeypatch):
    monkeypatch.setenv("SKYTPU_DEVTIME_EVERY", "1")
    e = _tiny_engine(flight_recorder=fl.FlightRecorder())
    seq0 = e.flight.seq()
    e.generate(_prompts(), max_new_tokens=6)
    assert e.devtime.samples > 0
    window = e.flight.since(seq0)
    assert any("dev_ms_est" in r for r in window)
    assert e.devtime.summary()


def test_engine_devtime_off_is_bit_identical(monkeypatch):
    monkeypatch.setenv("SKYTPU_DEVTIME_EVERY", "0")
    e = _tiny_engine()
    out_off = e.generate(_prompts(), max_new_tokens=6)
    assert e.devtime.samples == 0
    monkeypatch.setenv("SKYTPU_DEVTIME_EVERY", "1")
    e2 = _tiny_engine()
    out_on = e2.generate(_prompts(), max_new_tokens=6)
    assert e2.devtime.samples > 0
    assert [list(r) for r in out_off] == [list(r) for r in out_on]


# ---------------------------------------------------------------------------
# CLI wiring: `skytpu top` columns and `skytpu flight --bubbles`.

def test_top_serve_line_mfu_bw_columns():
    from skypilot_tpu.client import cli as cli_mod

    def fams(flops, hbm):
        return {
            "skytpu_http_requests_total": {
                "type": "counter",
                "samples": [({"route": "/generate", "code": "200"},
                             10.0)]},
            "skytpu_device_flops_total": {
                "type": "counter", "samples": [({}, float(flops))]},
            "skytpu_device_hbm_moved_bytes_total": {
                "type": "counter", "samples": [({}, float(hbm))]},
            "skytpu_roofline_peak_flops": {
                "type": "gauge", "samples": [({}, 0.5e12)]},
            "skytpu_roofline_peak_hbm_bytes_per_s": {
                "type": "gauge", "samples": [({}, 50e9)]},
        }

    payload = {"components": [], "alerts": []}
    now = 1000.0
    frame = cli_mod._render_top_frame(
        fams(0, 0), now - 10.0,
        fams(0.35 * 0.5e12 * 10, 0.6 * 50e9 * 10), now, payload)
    serve = next(l for l in frame.splitlines()
                 if l.startswith("serve"))
    assert "mfu 35.0%" in serve
    assert "bw 60.0%" in serve
    # First frame (no prev): the columns are absent, never a lie.
    frame1 = cli_mod._render_top_frame(None, None, fams(1, 1), now,
                                       payload)
    serve1 = next(l for l in frame1.splitlines()
                  if l.startswith("serve"))
    assert "mfu" not in serve1


@pytest.fixture
def fresh_events(tmp_path, monkeypatch):
    monkeypatch.setenv(tracing.EVENTS_DIR_ENV_VAR, str(tmp_path))
    monkeypatch.delenv(tracing.ENV_VAR, raising=False)
    tracing._reset_for_tests()
    yield str(tmp_path)
    tracing._reset_for_tests()


def test_flight_cli_bubbles_and_idle_spans(fresh_events, tmp_path,
                                           monkeypatch):
    from click.testing import CliRunner

    from skypilot_tpu.client import cli as cli_mod

    monkeypatch.setenv("SKYTPU_DEVTIME_EVERY", "1")
    e = _tiny_engine(flight_recorder=fl.FlightRecorder())
    e.generate(_prompts(), max_new_tokens=5)
    e.flight.flush()
    runner = CliRunner()
    res = runner.invoke(cli_mod.cli, ["flight", "--local", "--bubbles"])
    assert res.exit_code == 0, res.output
    assert "idle by cause" in res.output
    assert "% of idle attributed" in res.output
    pf_path = str(tmp_path / "flight.json")
    res2 = runner.invoke(
        cli_mod.cli,
        ["flight", "--local", "--perfetto", pf_path])
    assert res2.exit_code == 0, res2.output
    with open(pf_path, encoding="utf-8") as f:
        pf = json.load(f)
    assert any(ev.get("name", "").startswith("bubble:")
               for ev in pf["traceEvents"])
