"""Managed-jobs scale: the reference caps its controller at 2,000 jobs
(reference: sky/jobs/scheduler.py:66-72 — job limit + 4x-CPU launch
parallelism). These tests prove the same machinery here at scale:
the state DB at the full 2,000-job cap (WAL behavior, launch-slot
contention, list latency) and the real controller-process path at a
burst of jobs (slow profile; VERDICT r3 #8).
"""

import threading
import time

import pytest

from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.jobs.state import ManagedJobStatus


@pytest.fixture(autouse=True)
def sky_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    monkeypatch.setenv("SKYTPU_LOCAL_CLUSTERS_ROOT",
                       str(tmp_path / "cloud"))
    monkeypatch.setenv("SKYTPU_JOBS_POLL", "0.2")


def test_db_at_reference_job_cap():
    """2,000 jobs (the reference's MAX_JOB_LIMIT) in the state DB:
    inserts, status churn, and list stay fast under WAL."""
    n = jobs_state.MAX_JOB_LIMIT
    t0 = time.time()
    ids = [jobs_state.add(f"j{i}", {"run": "true"}, "FAILOVER")
           for i in range(n)]
    insert_s = time.time() - t0
    assert len(set(ids)) == n
    # Status churn across the whole population.
    for i, jid in enumerate(ids):
        if i % 3 == 0:
            jobs_state.set_status(jid, ManagedJobStatus.RUNNING)
        elif i % 3 == 1:
            jobs_state.set_status(jid, ManagedJobStatus.SUCCEEDED)
    t0 = time.time()
    jobs = jobs_state.list_jobs()
    list_s = time.time() - t0
    assert len(jobs) == n
    # The dashboard and `jobs queue` render from list_jobs: it must
    # stay interactive at the cap (single-core CI box -> generous but
    # meaningful bounds).
    assert list_s < 2.0, f"list_jobs took {list_s:.2f}s at {n} jobs"
    assert insert_s < 30.0
    assert jobs_state.count_alive() > 0


def test_launch_slot_contention_64_claimants():
    """64 threads fight for SKYTPU_JOBS_MAX_LAUNCHES=8 slots: observed
    concurrency never exceeds the limit, nobody deadlocks, every
    claimant eventually gets a slot (in-transaction count-and-claim)."""
    import os
    os.environ["SKYTPU_JOBS_MAX_LAUNCHES"] = "8"
    try:
        ids = [jobs_state.add(f"c{i}", {}, "FAILOVER")
               for i in range(64)]
        for jid in ids:
            jobs_state.set_controller_pid(jid, os.getpid())
        lock = threading.Lock()
        active = [0]
        peak = [0]
        errors = []

        def claim(jid):
            try:
                jobs_state.acquire_launch_slot(jid, poll=0.01,
                                               timeout=120)
                with lock:
                    active[0] += 1
                    peak[0] = max(peak[0], active[0])
                time.sleep(0.02)   # hold the slot briefly
                with lock:
                    active[0] -= 1
                jobs_state.release_launch_slot(jid)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=claim, args=(j,))
                   for j in ids]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert not any(t.is_alive() for t in threads), "deadlocked"
        assert peak[0] <= 8, f"{peak[0]} concurrent launches (limit 8)"
        assert peak[0] >= 2, "no concurrency at all — gate too strict"
        # Everyone released: no slot leaked.
        with jobs_state._db() as c:
            leaked = c.execute(
                "SELECT COUNT(*) FROM managed_jobs WHERE"
                " launch_started_at IS NOT NULL AND"
                " launch_ended_at IS NULL").fetchone()[0]
        assert leaked == 0
        assert time.time() - t0 < 120
    finally:
        os.environ.pop("SKYTPU_JOBS_MAX_LAUNCHES", None)


@pytest.mark.slow
def test_controller_burst_end_to_end(monkeypatch):
    """A burst of real managed jobs (controller processes + local
    clusters) through a launch gate: all succeed, the gate holds, and
    `jobs queue` stays responsive mid-storm.

    Observation goes through the CLIENT RPC (jobs_core.queue) — the
    jobs DB lives on the CONTROLLER CLUSTER HEAD's home, not in the
    test process's SKYPILOT_TPU_HOME; a direct jobs_state read here
    sees an empty client-side DB and waits forever (the bug this test
    shipped with)."""
    import os

    from skypilot_tpu.jobs import core as jobs_core
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task

    monkeypatch.setenv("SKYTPU_JOBS_MAX_LAUNCHES", "6")
    n = 40   # one controller process per job on a 1-core CI box

    def _task(i):
        t = Task(name=f"s{i}", run="echo scale-$SKYTPU_JOB_ID")
        t.set_resources(Resources(cloud="local"))
        return t

    jids = [jobs_core.launch(_task(i), name=f"scale{i}")
            for i in range(n)]
    assert len(set(jids)) == n

    # Queue latency sampled while the storm runs — through the RPC,
    # like `skytpu jobs queue` (the responsiveness a user sees).
    latencies = []
    deadline = time.time() + 600
    pending = set(jids)
    rows = {}
    while pending and time.time() < deadline:
        t0 = time.time()
        rows = {r["job_id"]: r for r in jobs_core.queue()}
        latencies.append(time.time() - t0)
        for j in list(pending):
            st = rows.get(j, {}).get("status")
            if st is not None and st.is_terminal():
                pending.discard(j)
        time.sleep(1.0)
    assert not pending, f"{len(pending)} jobs never finished"
    for j in jids:
        assert rows[j]["status"] == ManagedJobStatus.SUCCEEDED, rows[j]
    assert max(latencies) < 10.0, f"queue unresponsive: {max(latencies)}"

    # The launch gate held: overlapping launch windows never exceeded
    # the limit (sweep the window edges). Window timestamps live in
    # the head-side DB: point this process's home at the head's.
    head_home = os.path.join(os.environ["SKYTPU_LOCAL_CLUSTERS_ROOT"],
                             "sky-jobs-controller", "host0",
                             ".skypilot_tpu")
    assert os.path.isdir(head_home), head_home
    monkeypatch.setenv("SKYPILOT_TPU_HOME", head_home)
    windows = []
    for j in jids:
        s, e = jobs_state.launch_window(j)
        assert s is not None and e is not None
        windows.append((s, e))
    events = sorted([(s, 1) for s, _ in windows]
                    + [(e, -1) for _, e in windows])
    depth = peak = 0
    for _, d in events:
        depth += d
        peak = max(peak, depth)
    assert peak <= 6, f"launch gate breached: {peak} concurrent"
