"""Prefix KV-cache reuse + chunked prefill: parity, staleness, LRU.

Tier-1 guards for the serving engine's two interference killers:
(1) prefix reuse — cached-prefix generation must be token-identical to
the cold path (greedy), and (2) chunked prefill — the chunk program
must match the per-bucket monolith and the oracle. Plus the slot-reuse
staleness invariant the `_retire` comment promises, and the host-side
LRU index semantics.
"""

import jax
import numpy as np
import pytest

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.infer import kvcache
from skypilot_tpu.models import llama


@pytest.fixture(scope="module")
def cfg():
    return llama.CONFIGS["llama3-tiny"]


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.key(0), cfg)


def _engine(params, cfg, chunk=8, pool=4, slots=4, max_len=64,
            buckets=(48,), **kw):
    return eng.InferenceEngine(params, cfg, n_slots=slots,
                               max_len=max_len, prompt_buckets=buckets,
                               prefill_chunk=chunk, prefix_pool=pool,
                               **kw)


def test_cached_prefix_token_identical_to_cold(cfg, params):
    """The headline parity guarantee: a request whose prompt shares a
    resident prefix (suffix-only prefill over copied KV rows) generates
    EXACTLY the cold chunked path's tokens — and both match the
    monolithic engine and the full-forward oracle (greedy)."""
    e = _engine(params, cfg)
    system = list(range(5, 21))                 # 16 tokens = 2 chunks
    pa = system + [31, 32, 33, 34]
    pb = system + [41, 42, 43]

    # Oracle + monolith reference for the chunk program itself.
    mono = _engine(params, cfg, chunk=0, pool=0)
    want_a = mono.generate([pa], max_new_tokens=6)[0]
    logits_ref = llama.forward(params,
                               np.asarray([pa], np.int32), cfg)[0, -1]
    assert want_a[0] == int(np.argmax(np.asarray(logits_ref)))

    got_a = e.generate([pa], max_new_tokens=6)[0]   # cold, stores prefix
    assert got_a == want_a
    e.finished.clear()

    warm_b = e.generate([pb], max_new_tokens=6)[0]  # prefix hit
    (req_b,) = e.finished
    assert req_b.cached_len == 16                   # suffix-only prefill
    assert req_b.n_chunks == 1
    e.finished.clear()

    e.clear_prefix_cache()
    cold_b = e.generate([pb], max_new_tokens=6)[0]
    assert warm_b == cold_b
    assert cold_b == mono.generate([pb], max_new_tokens=6)[0]


def test_cached_prefix_parity_kv_int8(cfg, params):
    """Same guarantee over the int8 KV cache: pool rows copy the
    already-quantized bytes, so warm == cold bit-for-bit."""
    e = _engine(params, cfg, slots=2, pool=2, kv_int8=True)
    system = list(range(5, 21))
    pa, pb = system + [31, 32], system + [41, 42, 43]
    e.generate([pa], max_new_tokens=4)
    e.finished.clear()
    warm = e.generate([pb], max_new_tokens=6)[0]
    assert e.finished[0].cached_len == 16
    e.finished.clear()
    e.clear_prefix_cache()
    assert warm == e.generate([pb], max_new_tokens=6)[0]


def test_chunked_prefill_interleaves_with_decode(cfg, params):
    """The chunk scheduler: a long prompt admitted while another
    request decodes must not change either request's tokens, and the
    decode slot keeps emitting between chunks."""
    e = _engine(params, cfg, pool=0)
    short, long_p = [3, 1, 4], list(range(1, 29))   # 28 -> 4 chunks
    solo = _engine(params, cfg, pool=0)
    want_short = solo.generate([short], max_new_tokens=10)[0]
    want_long = solo.generate([long_p], max_new_tokens=4)[0]

    e.add_request(short, max_new_tokens=10)
    e.step_burst(max_burst=2)                 # short active, decoding
    e.add_request(long_p, max_new_tokens=4)
    e.run_to_completion(max_burst=2)
    by_prompt = {tuple(r.prompt): r.tokens for r in e.finished}
    assert by_prompt[tuple(short)] == want_short
    assert by_prompt[tuple(long_p)] == want_long


def test_slot_reuse_never_reads_dead_rows(cfg, params):
    """Satellite: retire a slot mid-sequence, re-admit a shorter
    prompt into it, and decode attention must never read the dead
    occupant's rows (the `_retire` no-cache-scrub invariant)."""
    e = eng.InferenceEngine(params, cfg, n_slots=1, max_len=64,
                            prompt_buckets=(32,))
    e.add_request(list(range(1, 29)), max_new_tokens=64)
    e.step()
    e.step()                                  # rows grow past 30
    (req,) = e.slot_req.values()
    e._retire(req)                            # mid-sequence retirement
    e.finished.clear()

    short = [3, 1, 4]
    got = e.generate([short], max_new_tokens=6)[0]
    fresh = eng.InferenceEngine(params, cfg, n_slots=1, max_len=64,
                                prompt_buckets=(32,))
    want = fresh.generate([short], max_new_tokens=6)[0]
    assert got == want
    # Stronger than token equality: the next decode's logits over the
    # reused cache match a never-dirtied cache bit-for-bit (a leaked
    # dead row would perturb attention before it flips an argmax).
    _, l_reused = kvcache.decode_step(e.params, e.cache, cfg,
                                      table=e.table_device())
    _, l_fresh = kvcache.decode_step(fresh.params, fresh.cache, cfg,
                                     table=fresh.table_device())
    assert np.array_equal(np.asarray(l_reused[0]), np.asarray(l_fresh[0]))


def test_prefix_index_lru_eviction():
    idx = eng.PrefixIndex(rows=2, block=4)
    a = list(range(100, 120))
    b = list(range(200, 220))
    c = list(range(300, 320))
    r0, ev = idx.acquire_row()
    assert (r0, ev) == (0, False)
    idx.register(a, 8, r0)
    r1, ev = idx.acquire_row()
    assert (r1, ev) == (1, False)
    idx.register(b, 8, r1)
    assert idx.lookup(a) == (0, 8)        # bumps row 0; row 1 is LRU
    r2, ev = idx.acquire_row()
    assert ev and r2 == 1                 # b evicted
    idx.register(c, 8, r2)
    assert idx.lookup(b) is None
    assert idx.lookup(c) == (1, 8)
    assert idx.lookup(a) == (0, 8)
    # Longest-aligned-prefix semantics: a prompt sharing only a's
    # first block hits at 4 tokens, not 8.
    assert idx.lookup(a[:4] + [9] * 5) == (0, 4)
    # At least one suffix token must remain: an exact-length prompt
    # can only hit a strictly shorter prefix.
    assert idx.lookup(a[:8]) == (0, 4)
    idx.clear()
    assert idx.lookup(a) is None


def test_budget_knobs_from_env(monkeypatch, cfg, params):
    monkeypatch.setenv("SKYTPU_PREFILL_CHUNK", "16")
    monkeypatch.setenv("SKYTPU_PREFIX_POOL", "3")
    # Contiguous layout (paging off): the separate pool tensor exists.
    monkeypatch.setenv("SKYTPU_KV_BLOCK", "0")
    e = eng.InferenceEngine(params, cfg, n_slots=1, max_len=32,
                            prompt_buckets=(16,))
    assert e.prefill_chunk == 16 and e.prefix_pool == 3
    assert not e.paged
    assert e.pool is not None and e.pool["k"].shape[1] == 3
    # Chunking off forces the pool off too (no suffix program to use
    # a hit with), regardless of SKYTPU_PREFIX_POOL.
    monkeypatch.setenv("SKYTPU_PREFILL_CHUNK", "0")
    e2 = eng.InferenceEngine(params, cfg, n_slots=1, max_len=32,
                             prompt_buckets=(16,))
    assert e2.prefill_chunk is None and e2.prefix_pool == 0
    assert e2.pool is None
    # Paged (the default): no pool tensor — prefixes are shared
    # blocks; SKYTPU_KV_BLOCK sizes the block, clamped to a divisor
    # of max_len, and SKYTPU_KV_BLOCKS sizes the pool.
    monkeypatch.setenv("SKYTPU_PREFILL_CHUNK", "16")
    monkeypatch.setenv("SKYTPU_KV_BLOCK", "8")
    monkeypatch.setenv("SKYTPU_KV_BLOCKS", "6")
    e3 = eng.InferenceEngine(params, cfg, n_slots=1, max_len=32,
                             prompt_buckets=(16,))
    assert e3.paged and e3.kv_block == 8 and e3.n_kv_blocks == 6
    assert e3.pool is None and e3.prefix_pool == 3
    assert e3.cache["k"].shape[1] == 6      # block pool, not slots
    assert e3.block_table.shape == (2, 32 // 8 + 1)
    monkeypatch.delenv("SKYTPU_KV_BLOCKS")
    # Default pool size: the contiguous-equivalent HBM.
    e4 = eng.InferenceEngine(params, cfg, n_slots=1, max_len=32,
                             prompt_buckets=(16,))
    assert e4.n_kv_blocks == 2 * (32 // 8)


def test_bench_serve_smoke_guard():
    """Satellite: `bench_serve --smoke` — the fast regression guard for
    the interference scheduler. Parity and prefix hits are asserted on
    every CI run; the chunk scheduler must actually have alternated
    (one admission burst per chunk, not one monolithic stall)."""
    from skypilot_tpu.infer import bench_serve

    r = bench_serve.run_smoke()
    assert r["parity_ok"]
    assert r["prefix_hits"] >= 1 and r["hit_rate"] > 0
    assert r["cold_hits"] == 0
    # Structural, not wall-clock (host timing noise at tiny-model scale
    # made a warm<cold ms assertion flaky): the warm pass must have
    # prefilled suffixes only — strictly fewer chunk programs.
    assert r["warm_chunks"] < r["cold_chunks"]
    assert r["warm_chunks"] == r["requests"]      # 1 suffix chunk each
    inter = r["interference"]
    # 2 long prompts x ceil(30/8)=4 chunks -> >= 8 alternation bursts.
    assert inter["admission_bursts"] >= 8
    assert inter["decode_stall_p99_ms"] > 0
