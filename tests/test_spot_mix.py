"""Spot/on-demand mixed-fleet serving: decision matrix + e2e backfill.

Reference parity: sky/serve/autoscalers.py FallbackRequestRateAutoscaler
(:546) — on-demand availability floor under a spot fleet, with
preemption-aware dynamic backfill.
"""

import time

import pytest

from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.serve.service_spec import SkyServiceSpec


def _spec(**policy):
    return SkyServiceSpec.from_yaml_config({
        "readiness_probe": "/", "port": 18300,
        "replica_policy": dict({"min_replicas": 3, "max_replicas": 3},
                               **policy),
    })


def _rep(rid, is_spot, status=ReplicaStatus.READY):
    return {"replica_id": rid, "is_spot": is_spot, "status": status}


def test_from_spec_selects_fallback():
    spec = _spec(base_ondemand_fallback_replicas=1)
    a = autoscalers.Autoscaler.from_spec(spec)
    assert isinstance(a, autoscalers.FallbackRequestRateAutoscaler)
    assert spec.use_ondemand_fallback
    # Round-trips through YAML (the controller re-parses the spec).
    spec2 = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert spec2.base_ondemand_fallback_replicas == 1


def test_startup_provisions_base_plus_dynamic_backfill():
    """No replicas yet: spot fleet provisions AND on-demand covers the
    whole not-yet-ready spot target (serves while spot comes up)."""
    a = autoscalers.Autoscaler.from_spec(
        _spec(base_ondemand_fallback_replicas=1,
              dynamic_ondemand_fallback=True))
    d = a.decide_mixed(0.0, [])
    assert d.mixed
    assert d.spot_target == 2
    assert d.ondemand_target == 1 + 2


def test_steady_state_drains_backfill():
    """All spot READY: on-demand returns to the base floor."""
    a = autoscalers.Autoscaler.from_spec(
        _spec(base_ondemand_fallback_replicas=1,
              dynamic_ondemand_fallback=True))
    reps = [_rep(1, True), _rep(2, True), _rep(3, False)]
    d = a.decide_mixed(0.0, reps)
    assert d.spot_target == 2 and d.ondemand_target == 1


def test_preemption_triggers_backfill():
    """One of two spot replicas gone: one extra on-demand covers it."""
    a = autoscalers.Autoscaler.from_spec(
        _spec(base_ondemand_fallback_replicas=1,
              dynamic_ondemand_fallback=True))
    reps = [_rep(1, True), _rep(3, False)]
    d = a.decide_mixed(0.0, reps)
    assert d.spot_target == 2 and d.ondemand_target == 2


def test_static_base_without_dynamic():
    a = autoscalers.Autoscaler.from_spec(
        _spec(base_ondemand_fallback_replicas=2))
    d = a.decide_mixed(0.0, [])
    assert d.spot_target == 1 and d.ondemand_target == 2
    d = a.decide_mixed(0.0, [_rep(1, False)])
    assert d.ondemand_target == 2  # never more than the base


def test_all_spot_fleet_with_dynamic_only():
    a = autoscalers.Autoscaler.from_spec(
        _spec(dynamic_ondemand_fallback=True))
    d = a.decide_mixed(0.0, [_rep(i, True) for i in (1, 2, 3)])
    assert d.spot_target == 3 and d.ondemand_target == 0
    d = a.decide_mixed(0.0, [_rep(1, True), _rep(2, True)])
    assert d.ondemand_target == 1


def test_base_capped_at_overall_target():
    from skypilot_tpu import exceptions
    with pytest.raises(exceptions.ServeError):
        SkyServiceSpec.from_yaml_config({
            "readiness_probe": "/", "port": 18300,
            "replica_policy": {"min_replicas": 1, "max_replicas": 1,
                               "base_ondemand_fallback_replicas": 5}})
    # base == max is fine and fully on-demand.
    spec = SkyServiceSpec.from_yaml_config({
        "readiness_probe": "/", "port": 18300,
        "replica_policy": {"min_replicas": 2, "max_replicas": 2,
                           "base_ondemand_fallback_replicas": 2}})
    a = autoscalers.Autoscaler.from_spec(spec)
    d = a.decide_mixed(0.0, [])
    assert d.spot_target == 0 and d.ondemand_target == 2


def test_rate_scaling_composes_with_mix(monkeypatch):
    """QPS pushes the overall target up; the split follows."""
    spec = SkyServiceSpec.from_yaml_config({
        "readiness_probe": "/", "port": 18300,
        "replica_policy": {"min_replicas": 1, "max_replicas": 4,
                           "target_qps_per_replica": 1.0,
                           "upscale_delay_seconds": 0,
                           "downscale_delay_seconds": 0,
                           "base_ondemand_fallback_replicas": 1,
                           "dynamic_ondemand_fallback": True}})
    a = autoscalers.Autoscaler.from_spec(spec)
    reps = [_rep(1, True), _rep(2, False)]
    # decide() proposes 4 (qps 4 / 1 per replica); zero delays let it
    # apply after two calls (proposal then confirm).
    a.decide_mixed(4.0, reps)
    d = a.decide_mixed(4.0, reps)
    assert d.target == 4
    assert d.spot_target == 3
    assert d.ondemand_target == 1 + (3 - 1)


def test_backfill_overage_never_feeds_back():
    """Regression: the live count includes backfill overage; the
    hysteresis echo of that count must be clamped to max_replicas or
    the spot target inflates geometrically (launch runaway)."""
    a = autoscalers.Autoscaler.from_spec(
        _spec(base_ondemand_fallback_replicas=1,
              dynamic_ondemand_fallback=True))  # min=max=3
    # 7 live replicas (overage from repeated backfill), none ready.
    reps = [_rep(i, i % 2 == 0, ReplicaStatus.STARTING)
            for i in range(7)]
    for _ in range(5):
        d = a.decide_mixed(0.0, reps)
        assert d.target == 3
        assert d.spot_target == 2
        assert d.ondemand_target <= 3  # base + full backfill


# -- e2e: kill a spot replica, watch on-demand backfill ---------------------

def test_spot_preemption_backfills_ondemand(tmp_path, monkeypatch):
    """Local-provider e2e: a mixed service loses its spot replica; the
    controller backfills with on-demand, then the spot fleet recovers."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    monkeypatch.setenv("SKYTPU_LOCAL_CLUSTERS_ROOT", str(tmp_path / "cloud"))
    monkeypatch.setenv("SKYTPU_SERVE_POLL", "0.3")
    from skypilot_tpu.provision import local as lp
    from skypilot_tpu.serve import core as serve_core
    from skypilot_tpu.task import Task
    from tests.test_serve import REPLICA_RUN

    cfg = {
        "name": "svc",
        "resources": {"cloud": "local"},
        "run": REPLICA_RUN,
        "service": {
            "readiness_probe": {"path": "/", "initial_delay_seconds": 15},
            "port": 18310,
            "replica_policy": {
                "min_replicas": 2, "max_replicas": 2,
                "base_ondemand_fallback_replicas": 1,
                "dynamic_ondemand_fallback": True,
            },
        },
    }
    serve_core.up(Task.from_yaml_config(cfg), "mixsvc")
    try:
        serve_core.wait_ready("mixsvc", timeout=300)

        def replicas():
            rows = serve_core.status("mixsvc")
            return rows[0]["replicas"] if rows else []

        # Converge to steady state: 1 spot + 1 on-demand, all READY
        # (the startup backfill on-demand drains once spot is READY).
        deadline = time.time() + 300
        while time.time() < deadline:
            reps = [r for r in replicas()
                    if r["status"] == ReplicaStatus.READY]
            spot = [r for r in reps if r.get("is_spot")]
            od = [r for r in reps if not r.get("is_spot")]
            if len(spot) == 1 and len(od) == 1:
                break
            time.sleep(0.5)
        assert len(spot) == 1 and len(od) == 1, replicas()

        # Preempt the spot replica cloud-side.
        lp.terminate_instances(spot[0]["cluster_name"], "local")

        # Backfill: a NEW on-demand replica appears while spot is gone.
        deadline = time.time() + 300
        seen_backfill = False
        while time.time() < deadline:
            reps = replicas()
            od_now = [r for r in reps if not r.get("is_spot")
                      and r["status"] not in (ReplicaStatus.SHUTTING_DOWN,
                                              ReplicaStatus.SHUTDOWN)]
            if len(od_now) >= 2:
                seen_backfill = True
                break
            time.sleep(0.3)
        assert seen_backfill, replicas()

        # And the fleet converges back: spot replacement READY, extra
        # on-demand drained to the base floor.
        deadline = time.time() + 300
        while time.time() < deadline:
            reps = [r for r in replicas()
                    if r["status"] == ReplicaStatus.READY]
            spot = [r for r in reps if r.get("is_spot")]
            od = [r for r in reps if not r.get("is_spot")]
            if len(spot) == 1 and len(od) == 1:
                break
            time.sleep(0.5)
        assert len(spot) == 1 and len(od) == 1, replicas()
    finally:
        serve_core.down("mixsvc")
