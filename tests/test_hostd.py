"""Per-host exec agent (runtime/hostd.py) + TcpAgentRunner: the gang
driver's transport on kubernetes pods. Two agents on localhost emulate
a 2-pod cluster; the REAL driver gang-runs a job across them."""

import json
import os
import socket
import threading
import time

import pytest

from skypilot_tpu.runtime import hostd, job_queue, topology
from skypilot_tpu.runtime.driver import run_job
from skypilot_tpu.utils.command_runner import TcpAgentRunner

TOKEN = "test-token-123"


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def agent(tmp_path):
    """One hostd serving with HOME pointed at a fresh 'pod' dir."""
    port = _free_port()
    pod_home = tmp_path / "pod0"
    pod_home.mkdir()
    old_home = os.environ.get("HOME")
    os.environ["HOME"] = str(pod_home)
    srv = hostd._Server(("127.0.0.1", port), hostd._Handler)
    srv.token = TOKEN
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield TcpAgentRunner("127.0.0.1", port, TOKEN), pod_home
    finally:
        os.environ["HOME"] = old_home or ""
        srv.shutdown()


def test_agent_run_roundtrip(agent):
    runner, home = agent
    rc, out, err = runner.run("echo hello-$FOO", env={"FOO": "bar"})
    assert rc == 0 and out.strip() == "hello-bar"
    rc, _, _ = runner.run("exit 7")
    assert rc == 7


def test_agent_detached_rc_and_kill(agent):
    runner, home = agent
    pid = runner.run_detached("sleep 0.2; echo done > marker; "
                              "echo 0 > rc", cwd=str(home),
                              log_path="out.log")
    deadline = time.time() + 10
    while runner.read_file("rc") is None:
        assert time.time() < deadline
        time.sleep(0.05)
    assert runner.read_file("marker").strip() == "done"
    # kill a long-running group (the dead child stays a zombie until the
    # in-process server reaps it, so check /proc state, not os.kill)
    pid2 = runner.run_detached("sleep 60", cwd=str(home),
                               log_path="out2.log")
    runner.kill(pid2)

    def _running(pid):
        try:
            with open(f"/proc/{pid}/stat") as f:
                return f.read().rsplit(")", 1)[1].split()[0] not in ("Z",
                                                                    "X")
        except OSError:
            return False

    deadline = time.time() + 5
    while _running(pid2):
        assert time.time() < deadline, "killed process still running"
        time.sleep(0.05)


def test_agent_rejects_bad_token(agent):
    runner, _ = agent
    bad = TcpAgentRunner(runner.ip, runner.port, "wrong")
    with pytest.raises(RuntimeError, match="bad token"):
        bad.run("true")


def test_agent_stdin_support(agent):
    runner, _ = agent
    # stdin rides the protocol as data, byte-exact (no heredoc newline).
    rc, out, _ = runner.run("wc -c", stdin="12345")
    assert rc == 0 and out.strip().endswith("5")


def test_agent_ping_reports_protocol(agent):
    from skypilot_tpu.runtime import hostd
    runner, _ = agent
    assert runner._agent_protocol() == hostd.PROTOCOL_VERSION


def test_agent_stdin_v1_fallback(agent, monkeypatch):
    """Against a v1 agent (no stdin field) the runner base64-wraps the
    payload into the command line — data-safe even when stdin contains
    shell or the old heredoc EOF marker."""
    runner, _ = agent
    monkeypatch.setattr(type(runner), "_agent_protocol", lambda self: 1)
    payload = "a\nSKYTPU_STDIN_EOF\necho pwned\n"
    rc, out, _ = runner.run("cat", stdin=payload)
    assert rc == 0 and out == payload


def test_driver_gang_over_host_agents(tmp_path, monkeypatch):
    """The REAL gang driver runs a 2-'pod' job through hostd agents —
    the code path a multi-pod GKE cluster takes (head=local, peer=k8s
    agent)."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "headhome"))
    # The peer "pod": hostd anchors everything at $HOME (real pods have
    # no workspace dir), so point the agent at its own home.
    pod_home = tmp_path / "podhome"
    pod_home.mkdir()
    monkeypatch.setenv("HOME", str(pod_home))
    port = _free_port()
    srv = hostd._Server(("127.0.0.1", port), hostd._Handler)
    srv.token = TOKEN
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    servers = [srv]
    head_ws = tmp_path / "pod0"
    head_ws.mkdir()
    hosts = [
        {"host_id": 0, "node_id": 0, "worker_id": 0,
         "internal_ip": "127.0.0.1", "workspace": str(head_ws),
         "kind": "local"},
        {"host_id": 1, "node_id": 1, "worker_id": 0,
         "internal_ip": "127.0.0.1", "workspace": None, "kind": "k8s"},
    ]
    # provider "kubernetes" without kubectl: the driver's best-effort
    # preemption probe fails and is ignored (exactly the GKE shape when
    # the head pod lacks cloud credentials).
    meta = {"provider": "kubernetes", "cluster_name": "ktest", "zone": "z",
            "head_host_id": 0, "agent_token": TOKEN,
            "agent_port": port,
            "provider_env": {}, "hosts": hosts}
    cdir = topology.cluster_dir("ktest")
    topology.save(cdir, meta)
    db = os.path.join(cdir, "jobs.db")
    job_id = job_queue.add_job(db, "gang", "")
    script = (f"echo rank-$SKYTPU_HOST_ID-of-$SKYTPU_NUM_HOSTS")
    spath = os.path.join(cdir, f"job_{job_id}.sh")
    with open(spath, "w") as f:
        f.write(script)
    job_queue.set_run_cmd(db, job_id, f"bash {spath}")
    try:
        rc = run_job("ktest", job_id)
    finally:
        for srv in servers:
            srv.shutdown()
    assert rc == 0
    job = job_queue.get_job(db, job_id)
    assert job["status"] == job_queue.JobStatus.SUCCEEDED
    logs = sorted(os.listdir(os.path.join(cdir, "logs",
                                          f"job_{job_id}")))
    ranks = [f for f in logs if f.startswith("rank-")]
    assert len(ranks) == 2
    combined = "".join(
        open(os.path.join(cdir, "logs", f"job_{job_id}", f)).read()
        for f in ranks)
    assert "rank-0-of-2" in combined and "rank-1-of-2" in combined
