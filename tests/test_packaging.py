"""Packaging: console script declaration + CLI entry (VERDICT r1 #6).

The remote-host half of #6 (rsynced package importable via the injected
PYTHONPATH on a host that shares nothing with the client) is covered by
tests/test_remote_cluster.py::test_remote_hosts_import_rsynced_framework.
"""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_pyproject_declares_skytpu_script():
    try:
        import tomllib
    except ImportError:  # py<3.11: tomli is not a declared dep
        import pytest
        tomllib = pytest.importorskip("tomli")
    with open(os.path.join(ROOT, "pyproject.toml"), "rb") as f:
        meta = tomllib.load(f)
    assert meta["project"]["scripts"]["skytpu"] == \
        "skypilot_tpu.client.cli:main"
    assert meta["project"]["name"] == "skypilot-tpu"


def test_cli_entry_runs():
    out = subprocess.run(
        [sys.executable, "-m", "skypilot_tpu.client.cli", "--help"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": ROOT})
    assert out.returncode == 0
    assert "Commands:" in out.stdout


def test_console_entry_function_exists():
    from skypilot_tpu.client import cli
    assert callable(cli.main)
