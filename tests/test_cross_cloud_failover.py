"""Cross-cloud failover END TO END through the backend: a GPU task hits
capacity stockouts across every GCP zone, the RetryingProvisioner
blocklists each and re-optimizes, and the SAME cluster lands on EC2 via
the fake AWS Query API (reference: the failover loop at
cloud_vm_ray_backend.py:1988 + re-optimization at :2140 — the
optimizer-level arbitrage tests cover the plan; this covers the loop).
"""

import pytest

from skypilot_tpu import exceptions, state
from skypilot_tpu.backend import RetryingProvisioner
from skypilot_tpu.provision import aws, gcp
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task
from tests.test_aws_provision import FakeEc2


class _StockoutGcp:
    """Every GCP API interaction reports exhausted capacity; counts
    calls so tests can assert GCP was genuinely visited first."""

    def __init__(self):
        self.calls = 0

    def __call__(self, method, url, body):
        self.calls += 1
        raise exceptions.CapacityError("ZONE_RESOURCE_POOL_EXHAUSTED")


@pytest.fixture
def clouds(tmp_path, monkeypatch):
    """Scratch home + both fake transports installed (and ALWAYS
    uninstalled — a leaked global transport would poison every later
    test in the process) + the runtime bootstrap stubbed out: the
    failover loop and provider routing are under test, not SSH."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "h"))
    # URL construction needs a project even though the fake transport
    # never reaches GCP.
    monkeypatch.setenv("GOOGLE_CLOUD_PROJECT", "fake-proj")
    priv = tmp_path / "sky-key"
    priv.write_text("fake key\n")
    (tmp_path / "sky-key.pub").write_text("ssh-ed25519 AAAAfake t\n")
    monkeypatch.setenv("SKYPILOT_TPU_SSH_KEY", str(priv))
    from skypilot_tpu import authentication
    from skypilot_tpu import backend as backend_mod
    authentication.get_or_generate_keys.cache_clear()
    monkeypatch.setattr(backend_mod, "_setup_and_init_runtime",
                        lambda *a, **k: None)
    fake_gcp, fake_ec2 = _StockoutGcp(), FakeEc2()
    gcp.set_transport(fake_gcp)
    aws.set_transport(fake_ec2)
    try:
        yield fake_gcp, fake_ec2
    finally:
        gcp.set_transport(None)
        aws.set_transport(None)
        authentication.get_or_generate_keys.cache_clear()


def test_gcp_stockout_fails_over_to_aws(clouds):
    fake_gcp, fake_ec2 = clouds
    task = Task(name="gpu", run="nvidia-smi")
    task.set_resources(Resources(accelerators="A100:8"))
    handle = RetryingProvisioner().provision(task, "xcloud")
    # Landed on EC2 after exhausting the (cheaper) GCP zones.
    assert handle.provider == "aws"
    assert handle.resources.instance_type == "p4d.24xlarge"
    assert fake_ec2.instances, "no EC2 instances created"
    # The loop genuinely visited GCP first (cheaper in the catalog) —
    # without this, a price shift could silently turn the test into a
    # straight-to-AWS launch that exercises no failover at all.
    assert fake_gcp.calls > 0, "GCP was never tried; no failover ran"
    rec = state.get_cluster("xcloud")
    assert rec is not None
    assert state.ClusterStatus(rec["status"]) == state.ClusterStatus.UP
    assert aws.query_instances("xcloud", handle.zone) == "UP"


def test_both_clouds_exhausted_raises_with_history(clouds):
    fake_gcp, fake_ec2 = clouds
    fake_ec2.capacity_errors = 99
    task = Task(name="gpu", run="true")
    task.set_resources(Resources(accelerators="A100:8"))
    with pytest.raises(exceptions.ResourcesUnavailableError) as ei:
        RetryingProvisioner().provision(task, "xc2")
    # The failover history records failures from BOTH clouds.
    hist = getattr(ei.value, "failover_history", [])
    assert hist, "no failover history recorded"
    assert fake_gcp.calls > 0


@pytest.fixture
def three_clouds(clouds, monkeypatch):
    """The two-cloud fixture plus the fake ARM: azure becomes the third
    failover leg."""
    from skypilot_tpu.provision import azure
    from tests.test_azure_provision import FakeArm
    fake_arm = FakeArm()
    azure.set_transport(fake_arm)
    try:
        yield (*clouds, fake_arm)
    finally:
        azure.set_transport(None)


def test_gcp_and_aws_stockout_fail_over_to_azure(three_clouds):
    """A100-80GB:8 is offered by all three catalogs (azure's
    ND96amsr is the cheapest 8-GPU box). GCP capacity is gone and EC2
    keeps erroring, so the SAME cluster must land on Azure — the third
    leg of the arbitrage."""
    fake_gcp, fake_ec2, fake_arm = three_clouds
    fake_ec2.capacity_errors = 99
    task = Task(name="gpu", run="nvidia-smi")
    task.set_resources(Resources(accelerators="A100-80GB:8"))
    handle = RetryingProvisioner().provision(task, "xc3")
    assert handle.provider == "azure"
    assert handle.resources.instance_type == "Standard_ND96amsr_A100_v4"
    assert any("/virtualMachines/" in k for k in fake_arm.resources)
    from skypilot_tpu.provision import azure
    assert azure.query_instances("xc3", handle.zone) == "UP"
    rec = state.get_cluster("xc3")
    assert state.ClusterStatus(rec["status"]) == state.ClusterStatus.UP
