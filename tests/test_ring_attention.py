"""Ring / Ulysses context-parallel attention vs the XLA oracle.

Runs on the 8-device virtual CPU mesh (conftest). The oracle is
ops.attention.xla_attention on the unsharded arrays; ring must match in
both forward values and gradients (it is numerically the same online
softmax, just block-scheduled around the ring).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.ops import attention as attn_ops
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel import ring_attention as ra


@pytest.fixture(scope="module")
def sp_mesh():
    return mesh_lib.make_mesh(mesh_lib.MeshShape(dp=2, sp=2, tp=2))


@pytest.fixture(scope="module")
def sp4_mesh():
    return mesh_lib.make_mesh(mesh_lib.MeshShape(sp=4, tp=2))


def _qkv(b=2, s=32, h=4, d=8, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_xla_forward(sp_mesh, causal):
    q, k, v = _qkv()
    want = attn_ops.xla_attention(q, k, v, causal=causal)
    got = ra.ring_attention(q, k, v, sp_mesh, causal=causal)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ring_sp4(sp4_mesh):
    q, k, v = _qkv(b=1, s=64)
    want = attn_ops.xla_attention(q, k, v, causal=True)
    got = ra.ring_attention(q, k, v, sp4_mesh, causal=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_gradients_match(sp_mesh, causal):
    q, k, v = _qkv(s=16)
    w = jax.random.normal(jax.random.key(9), q.shape)

    def loss_ring(q, k, v):
        return jnp.sum(ra.ring_attention(q, k, v, sp_mesh, causal=causal) * w)

    def loss_xla(q, k, v):
        return jnp.sum(attn_ops.xla_attention(q, k, v, causal=causal) * w)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_xla = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for gr, gx, name in zip(g_ring, g_xla, "qkv"):
        np.testing.assert_allclose(gr, gx, rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name} mismatch")


def test_ring_gqa_unrepeated_kv(sp_mesh):
    """GQA: Hq=4, Hkv=2 — unrepeated KV circulates; oracle repeats."""
    q, _, _ = _qkv(h=4)
    _, k, v = _qkv(h=2, seed=3)
    want = attn_ops.xla_attention(q, attn_ops.repeat_kv(k, 2),
                                  attn_ops.repeat_kv(v, 2), causal=True)
    got = ra.ring_attention(q, k, v, sp_mesh, causal=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ring_gqa_gradients(sp_mesh):
    q, _, _ = _qkv(h=4, s=16)
    _, k, v = _qkv(h=2, s=16, seed=3)
    w = jax.random.normal(jax.random.key(9), q.shape)

    def loss_ring(q, k, v):
        return jnp.sum(ra.ring_attention(q, k, v, sp_mesh) * w)

    def loss_xla(q, k, v):
        return jnp.sum(attn_ops.xla_attention(
            q, attn_ops.repeat_kv(k, 2), attn_ops.repeat_kv(v, 2)) * w)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_xla = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for gr, gx, name in zip(g_ring, g_xla, "qkv"):
        np.testing.assert_allclose(gr, gx, rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name} mismatch")


def _segments(b=2, s=32, seed=5):
    """Random packed-segment ids: contiguous, increasing, some padding 0."""
    rng = np.random.RandomState(seed)
    out = np.zeros((b, s), np.int32)
    for i in range(b):
        pos = 0
        sid = 1
        while pos < s - 2:
            length = rng.randint(3, max(4, s // 3))
            out[i, pos:pos + length] = sid
            pos += length
            sid += 1
        # tail left as 0 = padding
    return jnp.asarray(out)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_segments_match_xla(sp_mesh, causal):
    """VERDICT r1 #7: packed+sp>1 — ring with circulating segment ids
    must match the segment-masked XLA oracle."""
    q, k, v = _qkv()
    seg = _segments()
    want = attn_ops.xla_attention(q, k, v, causal=causal, segment_ids=seg)
    got = ra.ring_attention(q, k, v, sp_mesh, causal=causal,
                            segment_ids=seg)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ring_segments_gradients(sp_mesh):
    q, k, v = _qkv(s=16)
    seg = _segments(s=16)
    w = jax.random.normal(jax.random.key(9), q.shape)

    def loss_ring(q, k, v):
        return jnp.sum(ra.ring_attention(q, k, v, sp_mesh,
                                         segment_ids=seg) * w)

    def loss_xla(q, k, v):
        return jnp.sum(attn_ops.xla_attention(q, k, v,
                                              segment_ids=seg) * w)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_xla = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for gr, gx, name in zip(g_ring, g_xla, "qkv"):
        np.testing.assert_allclose(gr, gx, rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name} mismatch")


def test_ring_segments_gqa_sp4(sp4_mesh):
    q, _, _ = _qkv(b=1, s=64, h=4)
    _, k, v = _qkv(b=1, s=64, h=2, seed=3)
    seg = _segments(b=1, s=64)
    want = attn_ops.xla_attention(q, attn_ops.repeat_kv(k, 2),
                                  attn_ops.repeat_kv(v, 2), causal=True,
                                  segment_ids=seg)
    got = ra.ring_attention(q, k, v, sp4_mesh, causal=True,
                            segment_ids=seg)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ulysses_segments_match_xla(sp_mesh):
    q, k, v = _qkv()
    seg = _segments()
    want = attn_ops.xla_attention(q, k, v, causal=True, segment_ids=seg)
    got = ra.ulysses_attention(q, k, v, sp_mesh, causal=True,
                               segment_ids=seg)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_packed_model_with_sp(tiny_cfg, sp_mesh):
    """Packed llama training composes with sp>1: same loss as sp=1."""
    from skypilot_tpu.models import llama
    params = llama.init_params(jax.random.key(0), tiny_cfg)
    B, S = 2, 64
    tokens = jax.random.randint(jax.random.key(1), (B, S), 1,
                                tiny_cfg.vocab_size, dtype=jnp.int32)
    seg = _segments(b=B, s=S, seed=7)
    pos = jnp.broadcast_to(jnp.arange(S), (B, S)).astype(jnp.int32)
    batch = {"tokens": tokens, "segment_ids": seg, "positions": pos}
    loss_sp, _ = llama.loss_fn(params, batch, tiny_cfg, mesh=sp_mesh)
    loss_local, _ = llama.loss_fn(params, batch, tiny_cfg, mesh=None)
    np.testing.assert_allclose(np.asarray(loss_sp),
                               np.asarray(loss_local), rtol=2e-4)


def test_zigzag_layout_roundtrip():
    x = jnp.arange(2 * 32 * 3).reshape(2, 32, 3)
    z = ra.zigzag_permute(x, n=4)
    back = ra.zigzag_unpermute(z, n=4)
    np.testing.assert_array_equal(back, x)
    # Shard i holds chunks (i, 2n-1-i): first shard starts with chunk 0
    # then chunk 7.
    c = 32 // 8
    np.testing.assert_array_equal(z[:, :c], x[:, :c])
    np.testing.assert_array_equal(z[:, c:2 * c], x[:, 7 * c:8 * c])


@pytest.mark.parametrize("n_name,mesh_fix", [("sp2", "sp_mesh"),
                                             ("sp4", "sp4_mesh")])
def test_zigzag_matches_xla(n_name, mesh_fix, request):
    """Zigzag ring == causal oracle, via permute -> attend -> unpermute
    (the layout a zigzag training run lives in end to end)."""
    mesh = request.getfixturevalue(mesh_fix)
    n = mesh.shape["sp"]
    q, k, v = _qkv(s=32)
    want = attn_ops.xla_attention(q, k, v, causal=True)
    qz = ra.zigzag_permute(q, n)
    kz = ra.zigzag_permute(k, n)
    vz = ra.zigzag_permute(v, n)
    oz = ra.zigzag_ring_attention(qz, kz, vz, mesh)
    got = ra.zigzag_unpermute(oz, n)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_zigzag_gradients_match(sp_mesh):
    n = sp_mesh.shape["sp"]
    q, k, v = _qkv(s=16)
    w = jax.random.normal(jax.random.key(9), q.shape)

    def loss_zz(q, k, v):
        o = ra.zigzag_ring_attention(
            ra.zigzag_permute(q, n), ra.zigzag_permute(k, n),
            ra.zigzag_permute(v, n), sp_mesh)
        return jnp.sum(ra.zigzag_unpermute(o, n) * w)

    def loss_xla(q, k, v):
        return jnp.sum(attn_ops.xla_attention(q, k, v, causal=True) * w)

    g_zz = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    g_xla = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for gz, gx, name in zip(g_zz, g_xla, "qkv"):
        np.testing.assert_allclose(gz, gx, rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name} mismatch")


def test_zigzag_gqa_and_segments(sp4_mesh):
    n = sp4_mesh.shape["sp"]
    q, _, _ = _qkv(b=1, s=64, h=4)
    _, k, v = _qkv(b=1, s=64, h=2, seed=3)
    seg = _segments(b=1, s=64)
    want = attn_ops.xla_attention(q, attn_ops.repeat_kv(k, 2),
                                  attn_ops.repeat_kv(v, 2), causal=True,
                                  segment_ids=seg)
    oz = ra.zigzag_ring_attention(
        ra.zigzag_permute(q, n), ra.zigzag_permute(k, n),
        ra.zigzag_permute(v, n), sp4_mesh,
        segment_ids=ra.zigzag_permute(seg, n))
    got = ra.zigzag_unpermute(oz, n)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_zigzag_under_jit(sp_mesh):
    n = sp_mesh.shape["sp"]
    q, k, v = _qkv(s=32)

    @jax.jit
    def f(q, k, v):
        return ra.zigzag_ring_attention(q, k, v, sp_mesh)

    want = ra.zigzag_unpermute(
        f(ra.zigzag_permute(q, n), ra.zigzag_permute(k, n),
          ra.zigzag_permute(v, n)), n)
    ref = attn_ops.xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(want, ref, rtol=1e-5, atol=1e-5)


def test_model_zigzag_matches_contiguous(tiny_cfg, sp_mesh):
    """Full llama loss under rules seq_layout=zigzag == the plain-ring
    loss (the model permutes once after embedding, unpermutes before
    the head; packed segments ride along)."""
    from skypilot_tpu.models import llama
    from skypilot_tpu.parallel import sharding as sh
    params = llama.init_params(jax.random.key(0), tiny_cfg)
    B, S = 2, 64
    tokens = jax.random.randint(jax.random.key(1), (B, S), 1,
                                tiny_cfg.vocab_size, dtype=jnp.int32)
    seg = _segments(b=B, s=S, seed=7)
    pos = jnp.broadcast_to(jnp.arange(S), (B, S)).astype(jnp.int32)
    batch = {"tokens": tokens, "segment_ids": seg, "positions": pos}
    zz_rules = dict(sh.ACT_RULES, seq_layout="zigzag")
    loss_zz, _ = llama.loss_fn(params, batch, tiny_cfg, mesh=sp_mesh,
                               rules=zz_rules)
    loss_plain, _ = llama.loss_fn(params, batch, tiny_cfg, mesh=sp_mesh)
    np.testing.assert_allclose(np.asarray(loss_zz),
                               np.asarray(loss_plain), rtol=2e-4)


def test_model_zigzag_nondivisible_falls_back(tiny_cfg, sp_mesh):
    """Seq not divisible by 2*sp: the layout key is dropped and the
    model runs the contiguous path instead of mis-permuting."""
    from skypilot_tpu.models import llama
    from skypilot_tpu.parallel import sharding as sh
    params = llama.init_params(jax.random.key(0), tiny_cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 66), 1,
                                tiny_cfg.vocab_size, dtype=jnp.int32)
    zz_rules = dict(sh.ACT_RULES, seq_layout="zigzag")
    out = llama.forward(params, tokens, tiny_cfg, mesh=sp_mesh,
                        rules=zz_rules)
    ref = llama.forward(params, tokens, tiny_cfg, mesh=sp_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_nondivisible_dims_replicate(sp_mesh):
    """Batch=3 (not divisible by dp*fsdp) and heads=3 (not by tp): the
    spec falls back to replication instead of erroring."""
    q, k, v = _qkv(b=3, h=3)
    want = attn_ops.xla_attention(q, k, v, causal=True)
    got = ra.ring_attention(q, k, v, sp_mesh, causal=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ring_gqa_tp_divides_q_not_kv(sp4_mesh):
    """tp=2 divides Hq=8 but... here Hkv=2 IS divisible; use a mesh where
    tp=4 divides neither jointly: Hq=8 % 4 == 0 but Hkv=2 % 4 != 0 —
    heads sharding must be all-or-nothing or grouped heads mis-pair."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(sp=2, tp=4))
    q, _, _ = _qkv(h=8)
    _, k, v = _qkv(h=2, seed=3)
    want = attn_ops.xla_attention(q, attn_ops.repeat_kv(k, 4),
                                  attn_ops.repeat_kv(v, 4), causal=True)
    got = ra.ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_model_odd_seq_falls_back_to_local(tiny_cfg, sp_mesh):
    """Seq not divisible by sp: forward degrades to local attention
    instead of raising (the repo-wide divisibility-fallback convention)."""
    from skypilot_tpu.models import llama
    params = llama.init_params(jax.random.key(0), tiny_cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 65), 0,
                                tiny_cfg.vocab_size, dtype=jnp.int32)
    out = llama.forward(params, tokens, tiny_cfg, mesh=sp_mesh)
    assert out.shape == (2, 65, tiny_cfg.vocab_size)
    assert np.isfinite(np.asarray(out)).all()


def test_ulysses_matches_xla(sp_mesh):
    # heads per tp shard = 4/2 = 2, divisible by sp=2.
    q, k, v = _qkv()
    want = attn_ops.xla_attention(q, k, v, causal=True)
    got = ra.ulysses_attention(q, k, v, sp_mesh, causal=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ring_under_jit(sp_mesh):
    q, k, v = _qkv()

    @jax.jit
    def f(q, k, v):
        return ra.ring_attention(q, k, v, sp_mesh, causal=True)

    want = attn_ops.xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(f(q, k, v), want, rtol=1e-5, atol=1e-5)


def test_model_forward_with_sp(tiny_cfg, sp_mesh):
    """End-to-end: llama forward with the sp ring == unsharded forward."""
    from skypilot_tpu.models import llama

    params = llama.init_params(jax.random.key(0), tiny_cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0,
                                tiny_cfg.vocab_size, dtype=jnp.int32)
    base = llama.forward(params, tokens, tiny_cfg)
    sp = llama.forward(params, tokens, tiny_cfg, mesh=sp_mesh)
    # bf16 compute: allow small elementwise slack on logits.
    np.testing.assert_allclose(np.asarray(sp), np.asarray(base),
                               rtol=5e-2, atol=5e-2)


def test_train_step_with_sp(tiny_cfg, sp_mesh):
    """Full sharded train step with ring attention: runs, finite, learns."""
    from skypilot_tpu.train import trainer

    tc = trainer.TrainConfig(warmup_steps=1, total_steps=8)
    state = trainer.create_train_state(tiny_cfg, tc, sp_mesh)
    step = trainer.make_train_step(tiny_cfg, tc, sp_mesh)
    batch = trainer.synthetic_batch(tiny_cfg, 4, 64)
    state, m0 = step(state, batch)
    for _ in range(5):
        state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))
    assert float(m["loss"]) < float(m0["loss"])
