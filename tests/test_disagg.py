"""Fleet-scale cache-aware serving: prefix-affinity routing +
disaggregated prefill/decode tiers.

Covers the routing key's byte-parity with the engine's PrefixIndex
digest (same blake2b-128, same adapter salting), the load-spill rule
shared by adapter and prefix affinity, the disaggregation service
spec, tier-labeled replica state, and the end-to-end two-tier flow
over real model servers: a /prefill on the prefill tier, a paged-KV
handoff to the decode tier, greedy output bit-identical to
single-tier, one stitched trace across both tiers, and the
``handoff.transfer`` chaos point retrying a mid-transfer decode death
on a survivor with zero lost requests and zero leaked blocks.
"""

import http.server
import json
import socket
import threading
import urllib.error
import urllib.request

import jax
import pytest

from skypilot_tpu import chaos, exceptions
from skypilot_tpu.infer import engine as eng
from skypilot_tpu.infer import server as srv
from skypilot_tpu.models import llama
from skypilot_tpu.serve import load_balancer, serve_state
from skypilot_tpu.serve.service_spec import SkyServiceSpec

CFG = llama.CONFIGS["llama3-tiny"]
CHUNK = 8
PROMPT_BASE = list(range(5, 21))        # 16 tokens = 2 prefill chunks


@pytest.fixture(scope="module", autouse=True)
def _home(tmp_path_factory):
    import os
    home = str(tmp_path_factory.mktemp("home"))
    old = {k: os.environ.get(k)
           for k in ("SKYPILOT_TPU_HOME", "SKYTPU_PREFILL_CHUNK")}
    os.environ["SKYPILOT_TPU_HOME"] = home
    os.environ["SKYTPU_PREFILL_CHUNK"] = str(CHUNK)
    load_balancer._disagg_cache.clear()
    load_balancer._adapter_cache.clear()
    yield home
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


# -- routing key parity -----------------------------------------------------

def test_lb_digest_matches_engine_prefix_index():
    """The LB's routing key is byte-for-byte the engine PrefixIndex
    digest of the longest chunk-aligned proper prefix — including the
    salt namespace — so affinity routing pins exactly the families the
    engine caches."""
    idx = eng.PrefixIndex(rows=4, block=CHUNK)
    for prompt in (list(range(100, 130)),        # 30 -> n=24
                   list(range(7, 23)),           # 16 -> n=8 (proper!)
                   list(range(50, 59))):         # 9  -> n=8
        n = ((len(prompt) - 1) // CHUNK) * CHUNK
        for salt in (b"", b"\x01adapter-content-digest\xff"):
            assert load_balancer.prefix_affinity_key(
                prompt, chunk=CHUNK, salt=salt) \
                == idx._digest(prompt, n, salt)
    # Ineligibility mirrors PrefixIndex.eligible: a prompt no longer
    # than one chunk has no cacheable proper prefix.
    short = list(range(CHUNK))
    assert load_balancer.prefix_affinity_key(short, chunk=CHUNK) is None
    assert not idx.eligible(short)


def test_lb_digest_adapter_content_salt_parity():
    """With a REAL adapter-content digest as the salt (what the engine
    feeds its index), the LB function still reproduces the engine
    digest — and different salts split the same prompt into different
    routing families (two fine-tunes must not share a replica pin for
    cache reasons: their KV rows differ)."""
    import numpy as np
    from skypilot_tpu.infer import adapters as adapters_lib
    digest = adapters_lib._content_digest(
        {"attn_q": {"a": np.ones((4, 2), np.float32),
                    "b": np.zeros((2, 4), np.float32)}}, alpha=32.0)
    assert digest and len(digest) == 16
    idx = eng.PrefixIndex(rows=4, block=CHUNK)
    prompt = list(range(60, 90))
    n = ((len(prompt) - 1) // CHUNK) * CHUNK
    assert load_balancer.prefix_affinity_key(
        prompt, chunk=CHUNK, salt=digest) == idx._digest(prompt, n,
                                                         digest)
    assert load_balancer.prefix_affinity_key(prompt, chunk=CHUNK,
                                             salt=b"ft-a") \
        != load_balancer.prefix_affinity_key(prompt, chunk=CHUNK,
                                             salt=b"ft-b")


# -- affinity load spill ----------------------------------------------------

def test_affinity_pick_spills_on_load(monkeypatch):
    """Rendezvous affinity pins a key to one replica; once that
    replica's live load exceeds the least-loaded candidate by more
    than SKYTPU_LB_SPILL, the pick spills to the NEXT ranked replica
    (deterministic second choice, not random), and returns home when
    the load drains."""
    monkeypatch.delenv("SKYTPU_LB_SPILL", raising=False)
    pol = load_balancer.LeastLoadPolicy()
    urls = [f"http://r{i}" for i in range(3)]
    ranked = load_balancer._ranked_urls("hot-key", urls)
    assert load_balancer._affinity_pick("hot-key", urls, pol) \
        == ranked[0]
    for _ in range(4):                   # load == margin: still home
        pol.acquire(ranked[0])
    assert load_balancer._affinity_pick("hot-key", urls, pol) \
        == ranked[0]
    pol.acquire(ranked[0])               # load > floor + margin
    assert load_balancer._affinity_pick("hot-key", urls, pol) \
        == ranked[1]
    for _ in range(5):
        pol.done(ranked[0])
    assert load_balancer._affinity_pick("hot-key", urls, pol) \
        == ranked[0]


def test_policy_load_accounting_shared_by_all_pick_paths():
    """The in-flight load map lives on the Policy BASE class —
    acquire/done from any pick path (policy or affinity) feeds the
    same numbers LeastLoadPolicy.select and the spill rule read."""
    pol = load_balancer.LeastLoadPolicy()
    pol.acquire("a")
    pol.acquire("a")
    pol.acquire("b")
    assert pol.load("a") == 2 and pol.load("b") == 1
    assert pol.select(["a", "b"]) == "b"   # select READS, no increment
    assert pol.load("b") == 1
    pol.done("a")
    pol.done("a")
    pol.done("a")                          # over-done clamps at zero
    assert pol.load("a") == 0


# -- service spec + tier state ----------------------------------------------

def test_disaggregation_spec_validation_and_roundtrip():
    cfg = {"replicas": 3,
           "disaggregation": {"prefill_replicas": 1,
                              "decode_replicas": 2}}
    spec = SkyServiceSpec.from_yaml_config(dict(cfg))
    assert spec.disaggregation == {"prefill_replicas": 1,
                                   "decode_replicas": 2}
    again = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert again.disaggregation == spec.disaggregation
    # Tiers must cover the fleet exactly.
    with pytest.raises(exceptions.ServeError):
        SkyServiceSpec.from_yaml_config({
            "replicas": 2,
            "disaggregation": {"prefill_replicas": 1,
                               "decode_replicas": 2}})
    # Autoscaling is incompatible: tier membership is launch-time.
    with pytest.raises(exceptions.ServeError):
        SkyServiceSpec.from_yaml_config({
            "replica_policy": {"min_replicas": 1, "max_replicas": 3,
                               "target_qps_per_replica": 1},
            "disaggregation": {"prefill_replicas": 1,
                               "decode_replicas": 2}})
    # Exact key set, integer counts >= 1.
    for bad in ({"prefill_replicas": 1},
                {"prefill_replicas": 0, "decode_replicas": 3},
                {"prefill_replicas": 1, "decode_replicas": 1,
                 "extra": 1}):
        with pytest.raises(exceptions.ServeError):
            SkyServiceSpec(min_replicas=3, max_replicas=3,
                           disaggregation=bad)


def test_replica_tier_state_and_filtered_ready_urls():
    serve_state.add_service("tiertest", {}, {}, 0)
    up = serve_state.upsert_replica
    up("tiertest", 1, "c1", serve_state.ReplicaStatus.READY,
       "http://p1", tier="prefill")
    up("tiertest", 2, "c2", serve_state.ReplicaStatus.READY,
       "http://d1", tier="decode")
    up("tiertest", 3, "c3", serve_state.ReplicaStatus.STARTING,
       "http://d2", tier="decode")
    assert serve_state.ready_urls("tiertest") == ["http://p1",
                                                  "http://d1"]
    assert serve_state.ready_urls("tiertest", tier="prefill") \
        == ["http://p1"]
    assert serve_state.ready_urls("tiertest", tier="decode") \
        == ["http://d1"]
    # A status flip through set_replica_status keeps the tier.
    serve_state.set_replica_status("tiertest", 3,
                                   serve_state.ReplicaStatus.READY)
    assert serve_state.ready_urls("tiertest", tier="decode") \
        == ["http://d1", "http://d2"]
    replicas = {r["replica_id"]: r
                for r in serve_state.list_replicas("tiertest")}
    assert replicas[1]["tier"] == "prefill"
    assert replicas[3]["tier"] == "decode"
    serve_state.remove_service("tiertest")


# -- prefix-affinity routing over fake replicas -----------------------------

def _spawn_counting_replica(counts):
    class _Fake(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            self.rfile.read(n)
            port = self.server.server_address[1]
            counts[port] = counts.get(port, 0) + 1
            out = json.dumps({"tokens": [1], "done": True}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Fake)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_prefix_affinity_concentrates_family_on_one_replica():
    """Requests sharing a chunk-aligned prompt prefix all land on ONE
    replica (the family's rendezvous pick) instead of spreading — the
    property that turns per-replica prefix caches into a fleet-wide
    cache. Least-load alone would spread 6 sequential requests across
    the tie."""
    counts = {}
    fakes = [_spawn_counting_replica(counts) for _ in range(3)]
    try:
        serve_state.add_service("afftest", {}, {}, 0)
        for i, (_, url) in enumerate(fakes):
            serve_state.upsert_replica(
                "afftest", i + 1, f"r{i+1}",
                serve_state.ReplicaStatus.READY, url)
        lb = load_balancer._ThreadingServer(
            ("127.0.0.1", 0),
            load_balancer.make_handler(
                "afftest", load_balancer.LeastLoadPolicy()))
        threading.Thread(target=lb.serve_forever, daemon=True).start()
        lb_url = f"http://127.0.0.1:{lb.server_address[1]}"
        family = list(range(200, 240))         # 40 tokens, 5 chunks
        try:
            for i in range(6):
                code, _ = _post(f"{lb_url}/generate",
                                {"tokens": family + [i],
                                 "max_new_tokens": 4})
                assert code == 200
        finally:
            lb.shutdown()
        assert sorted(counts.values()) == [6]  # one replica took all
    finally:
        serve_state.remove_service("afftest")
        for httpd, _ in fakes:
            httpd.shutdown()


# -- end-to-end two-tier fleet ----------------------------------------------

def _mk_engine(params, **kw):
    base = dict(n_slots=4, max_len=64, prompt_buckets=(48,),
                prefill_chunk=CHUNK, prefix_pool=8, kv_block=CHUNK)
    base.update(kw)
    return eng.InferenceEngine(params, CFG, **base)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def fleet(params, _home):
    """1 prefill + 2 decode replicas behind a real LB, registered as a
    disaggregated service."""
    servers, urls = [], []
    for _ in range(3):
        engine = _mk_engine(params)
        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        model, httpd = srv.serve(engine, host="127.0.0.1", port=port)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        assert model._ready.wait(timeout=300)
        servers.append((model, httpd, engine))
        urls.append(f"http://127.0.0.1:{port}")
    spec = {"disaggregation": {"prefill_replicas": 1,
                               "decode_replicas": 2}}
    serve_state.add_service("disagg", spec, {}, 0)
    for i, tier in enumerate(("prefill", "decode", "decode")):
        serve_state.upsert_replica("disagg", i + 1, f"r{i+1}",
                                   serve_state.ReplicaStatus.READY,
                                   urls[i], tier=tier)
    load_balancer._disagg_cache.clear()
    lb_httpd = load_balancer._ThreadingServer(
        ("127.0.0.1", 0),
        load_balancer.make_handler("disagg",
                                   load_balancer.LeastLoadPolicy()))
    threading.Thread(target=lb_httpd.serve_forever,
                     daemon=True).start()
    yield (f"http://127.0.0.1:{lb_httpd.server_address[1]}",
           servers, urls)
    lb_httpd.shutdown()
    for model, httpd, _ in servers:
        model.shutdown()
        httpd.shutdown()
    serve_state.remove_service("disagg")


def _resident_blocks(engine):
    idx = engine._prefix_index
    return sum(len(p) for p in idx.payloads()) if idx else 0


def test_two_tier_blocking_parity_and_no_leaks(fleet):
    """A blocking /generate through the LB on a disaggregated service
    runs prefill-tier admission + KV handoff + decode-tier resume and
    returns tokens BIT-IDENTICAL to the single-tier path; the prefill
    tier afterwards holds exactly its refcounted resident prefixes
    (zero leaked blocks)."""
    lb_url, servers, urls = fleet
    prompt = PROMPT_BASE + [31, 32, 33]
    ok_before = load_balancer.LB_HANDOFFS.labels(result="ok").value
    code, out = _post(f"{lb_url}/generate",
                      {"tokens": prompt, "max_new_tokens": 6})
    assert code == 200 and "error" not in out
    # Single-tier reference, direct to a decode replica.
    ref_code, ref = _post(f"{urls[2]}/generate",
                          {"tokens": prompt, "max_new_tokens": 6})
    assert ref_code == 200
    assert out["tokens"] == ref["tokens"]
    assert len(out["tokens"]) == 6
    assert load_balancer.LB_HANDOFFS.labels(result="ok").value \
        == ok_before + 1
    # Donor audit: every block the prefill engine holds is owned by a
    # resident prefix entry — the handoff left it exactly as warm as
    # any cached serve, nothing dangling.
    pf_engine = servers[0][2]
    assert pf_engine.blocks_used == _resident_blocks(pf_engine)


def test_two_tier_short_prompt_falls_back_single_tier(fleet):
    """A prompt no longer than one chunk can't hand off (no cacheable
    prefix) — the LB serves it single-tier on the decode tier, and the
    'single' tier counter records the fallback."""
    lb_url, _, urls = fleet
    single_before = load_balancer.LB_TIER_REQUESTS.labels(
        tier="single").value
    prompt = [3, 1, 4]
    code, out = _post(f"{lb_url}/generate",
                      {"tokens": prompt, "max_new_tokens": 4})
    assert code == 200 and len(out["tokens"]) == 4
    ref = _post(f"{urls[1]}/generate",
                {"tokens": prompt, "max_new_tokens": 4})[1]
    assert out["tokens"] == ref["tokens"]
    assert load_balancer.LB_TIER_REQUESTS.labels(
        tier="single").value == single_before + 1


def test_two_tier_streaming_parity(fleet):
    """The streaming flavor: the decode tier streams the committed
    token first (the client's TTFT is the prefill tier's), the full
    sequence is duplicate-free and bit-identical to single-tier, and
    the done line carries the stitched token count."""
    lb_url, _, urls = fleet
    prompt = PROMPT_BASE + [71, 72]
    ref = _post(f"{urls[1]}/generate",
                {"tokens": prompt, "max_new_tokens": 6})[1]
    req = urllib.request.Request(
        f"{lb_url}/generate",
        data=json.dumps({"tokens": prompt, "max_new_tokens": 6,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    toks, done = [], None
    with urllib.request.urlopen(req, timeout=120) as r:
        for line in r:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            assert "error" not in obj
            if "done" in obj:
                done = obj
                break
            toks.extend(obj.get("tokens") or [])
    assert toks == ref["tokens"]
    assert done is not None and done["n_tokens"] == len(toks)


def test_two_tier_trace_stitched_across_tiers(fleet):
    """Both tiers' engine spans land in ONE trace: the LB propagates
    the same traceparent to /prefill and /handoff (minting one when
    the client sends none), so `skytpu trace` and the perfetto export
    render a single tree spanning two request ids."""
    from skypilot_tpu.observability import trace_view, tracing
    lb_url, _, _ = fleet
    trace_id = tracing.new_trace_id()
    tp = tracing.format_traceparent(
        tracing.SpanContext(trace_id, tracing.new_span_id()))
    prompt = PROMPT_BASE + [81, 82, 83, 84]
    code, out = _post(f"{lb_url}/generate",
                      {"tokens": prompt, "max_new_tokens": 5},
                      headers={"traceparent": tp})
    assert code == 200 and "error" not in out
    tracing.flush()          # spans sit in the in-process ring buffer
    records = trace_view.load_trace(trace_id)
    spans = [r for r in records if r.get("kind") == "span"]
    rids = {(r.get("attrs") or {}).get("rid") for r in spans
            if (r.get("attrs") or {}).get("rid") is not None}
    # Two requests (prefill-tier rid + decode-tier rid) in one trace.
    assert len(rids) >= 2
    rendered = trace_view.render(records, trace_id)
    assert "engine.prefill" in rendered
    perfetto = trace_view.to_perfetto(records)
    assert any(e.get("ph") == "X" for e in perfetto["traceEvents"])


def test_handoff_chaos_decode_death_retries_on_survivor(fleet):
    """A seeded ``handoff.transfer`` fault (decode replica dies
    mid-transfer) retries the export — held in LB memory — on the
    surviving decode replica: the request completes bit-identical
    (zero lost requests), and the prefill tier's block pool still
    holds exactly its resident prefixes (zero leaked blocks)."""
    lb_url, servers, urls = fleet
    prompt = PROMPT_BASE + [91, 92, 93, 94]
    ref = _post(f"{urls[1]}/generate",
                {"tokens": prompt, "max_new_tokens": 6})[1]
    retry_before = load_balancer.LB_HANDOFFS.labels(
        result="retry").value
    ok_before = load_balancer.LB_HANDOFFS.labels(result="ok").value
    chaos.configure({"seed": 3, "faults": [
        {"point": "handoff.transfer", "times": 1}]})
    try:
        code, out = _post(f"{lb_url}/generate",
                          {"tokens": prompt, "max_new_tokens": 6})
        fired = chaos.injector().fired
    finally:
        chaos.deactivate()
    assert len(fired) == 1
    assert fired[0]["point"] == "handoff.transfer"
    assert code == 200 and out["tokens"] == ref["tokens"]
    assert load_balancer.LB_HANDOFFS.labels(result="retry").value \
        == retry_before + 1
    assert load_balancer.LB_HANDOFFS.labels(result="ok").value \
        == ok_before + 1
    pf_engine = servers[0][2]
    assert pf_engine.blocks_used == _resident_blocks(pf_engine)
