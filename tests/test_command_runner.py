"""utils/command_runner.py failure modes: timeouts, nonzero exits vs.
transport errors, and partial-output preservation — the classification
contract the RPC layer's transport-failure handling builds on."""

import os
import subprocess

import pytest

from skypilot_tpu.utils.command_runner import CommandRunner, LocalRunner


@pytest.fixture(autouse=True)
def sky_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))


# -- raw runner behavior ----------------------------------------------------

def test_nonzero_exit_preserves_output():
    rc, out, err = LocalRunner().run(
        "echo partial-stdout; echo partial-stderr >&2; exit 7")
    assert rc == 7
    assert "partial-stdout" in out
    assert "partial-stderr" in err


def test_timeout_raises_with_partial_output():
    with pytest.raises(subprocess.TimeoutExpired) as ei:
        LocalRunner().run("echo before-hang; exec sleep 30", timeout=0.5)
    got = ei.value.stdout or ei.value.output or b""
    if isinstance(got, bytes):
        got = got.decode(errors="replace")
    assert "before-hang" in got


def test_log_path_keeps_partial_output_on_failure(tmp_path):
    log = tmp_path / "logs" / "cmd.log"
    rc, out, err = LocalRunner().run(
        "echo logged-line; exit 3", log_path=str(log))
    assert rc == 3
    assert (out, err) == ("", "")           # tee'd, not captured
    assert "logged-line" in log.read_text()


def test_log_path_keeps_partial_output_on_timeout(tmp_path):
    log = tmp_path / "logs" / "cmd.log"
    with pytest.raises(subprocess.TimeoutExpired):
        LocalRunner().run("echo flushed; exec sleep 30",
                          timeout=0.5, log_path=str(log))
    assert "flushed" in log.read_text()


def test_read_file_missing_returns_none(tmp_path):
    r = LocalRunner()
    assert r.read_file(str(tmp_path / "nope")) is None
    p = tmp_path / "yes"
    p.write_text("content")
    assert r.read_file(str(p)) == "content"


# -- classification through the RPC transport -------------------------------
# rc != 0, TimeoutExpired, and OSError must ALL surface as the typed
# ClusterRpcError counted as kind=transport — never a raw exception.

class _FailingRunner(CommandRunner):
    def __init__(self, exc=None, rc=None, out="", err=""):
        super().__init__()
        self.exc = exc
        self.rc = rc
        self.out, self.err = out, err
        self.calls = 0

    def run(self, cmd, env=None, cwd=None, timeout=None, log_path=None,
            stdin=None):
        self.calls += 1
        if self.exc is not None:
            raise self.exc
        return self.rc, self.out, self.err

    def framework_invocation(self, module):
        return f"python3 -m {module}"


def _transport_count(method):
    from skypilot_tpu.runtime.rpc_client import RPC_FAILURES
    return RPC_FAILURES.labels(method=method, kind="transport").value


@pytest.mark.parametrize("runner", [
    _FailingRunner(exc=ConnectionRefusedError("head down")),
    _FailingRunner(exc=subprocess.TimeoutExpired("cmd", 1.0)),
    _FailingRunner(rc=255, err="ssh: connection reset"),
], ids=["oserror", "timeout", "nonzero-rc"])
def test_rpc_classifies_as_transport_and_retries(runner):
    from skypilot_tpu.runtime.rpc_client import ClusterRpc, ClusterRpcError
    before = _transport_count("ping")
    rpc = ClusterRpc(runner, "t-cluster")
    # Budget comfortably above the worst-case backoff total (1s + 2s):
    # this asserts the retry count, not the deadline cutoff.
    with pytest.raises(ClusterRpcError):
        rpc.call("ping", timeout=10.0)
    # Idempotent method: all transport attempts burned and counted.
    assert runner.calls == 3
    assert _transport_count("ping") - before == 3


def test_rpc_partial_output_lands_in_typed_error():
    """The head's stderr tail rides the ClusterRpcError message — the
    diagnostic a human needs must not vanish with the raw rc."""
    from skypilot_tpu.runtime.rpc_client import ClusterRpc, ClusterRpcError
    runner = _FailingRunner(rc=1, out="partial head output",
                            err="traceback: ImportError")
    with pytest.raises(ClusterRpcError, match="ImportError"):
        ClusterRpc(runner, "t-cluster").call("submit", timeout=3.0)
    assert runner.calls == 1        # non-idempotent: exactly one attempt
