"""Span-bucketed decode attention: ladder selection, bit parity vs
the full view, retrace discipline, regrouping, lazy block growth.

Tier-1 guards for the PR-9 bandwidth refactor (ROADMAP item 1's
follow-up to the paged cache):

* Span-on greedy output is BIT-identical to the full-view programs —
  {fp32, int8 KV} x {paged, contiguous} x {spec-on, spec-off} — on
  mixed-length workloads: the span read is a prefix of the full view
  whose dropped rows all carried exact-zero softmax weight.
* Retrace discipline: a mixed-length run compiles at most one
  decode/verify program per span-ladder rung — never one per observed
  length.
* Regrouping: a single long slot in a burst promotes only ITS group's
  bucket; short neighbors keep their small-span reads.
* Lazy growth (SKYTPU_KV_LAZY): admission reserves prompt + one burst
  of blocks, growth happens at dispatch, and the existing block-leak
  audits still hold (admit/retire -> clear -> 0 blocks used).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.models import llama


@pytest.fixture(scope="module")
def cfg():
    # fp32: accumulation differences cannot hide behind bf16 eps (the
    # PR 6 test_infer_tp lesson); the int8 tests cover the quantized
    # cache, whose integer accumulation is exact.
    return dataclasses.replace(llama.CONFIGS["llama3-tiny"],
                               dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.key(0), cfg)


def _mixed_prompts(cfg, lengths=(5, 12, 30, 9), seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, n).tolist()
            for n in lengths]


def _engine(params, cfg, span_buckets=None, kv_block=8, max_len=64,
            slots=4, **kw):
    kw.setdefault("prompt_buckets", (16, 32))
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefix_pool", 2)
    return eng.InferenceEngine(params, cfg, n_slots=slots,
                               max_len=max_len, kv_block=kv_block,
                               span_buckets=span_buckets, **kw)


# -- ladder knob ------------------------------------------------------------

def test_span_ladder_default_and_knobs(params, cfg, monkeypatch):
    # Default: power-of-two ladder ending at max_len.
    e = _engine(params, cfg, kv_block=8, max_len=64)
    assert e.span_ladder == (8, 16, 32, 64)
    # Explicit rungs keep their values (no block alignment needed —
    # the paged gather covers whole blocks and slices to the span)
    # and max_len always closes the ladder.
    e = _engine(params, cfg, span_buckets=(12, 40), kv_block=8)
    assert e.span_ladder == (12, 40, 64)
    # 0 disables: the full view is the only rung.
    e = _engine(params, cfg, span_buckets=0)
    assert e.span_ladder == (64,)
    # Env knob (ctor arg None falls through).
    monkeypatch.setenv("SKYTPU_SPAN_BUCKETS", "16,32")
    e = _engine(params, cfg)
    assert e.span_ladder == (16, 32, 64)
    monkeypatch.setenv("SKYTPU_SPAN_BUCKETS", "0")
    e = _engine(params, cfg)
    assert e.span_ladder == (64,)
    # Contiguous layout: identical semantics.
    e = _engine(params, cfg, span_buckets=(12, 40), kv_block=0)
    assert e.span_ladder == (12, 40, 64)
    # A rung smaller than one block still buckets: the gather covers
    # the first block and slices — parity is the matrix test's job.
    e = _engine(params, cfg, span_buckets=(4,), kv_block=16)
    assert e.span_ladder == (4, 64)


def test_span_for_and_arg(params, cfg):
    e = _engine(params, cfg, kv_block=8, max_len=64)
    assert e._span_for(1) == 8
    assert e._span_for(8) == 8
    assert e._span_for(9) == 16
    assert e._span_for(64) == 64
    # max_len rung dispatches as the UNSLICED full-view program.
    assert e._span_arg(64) is None
    assert e._span_arg(16) == 16


# -- parity: span-on == full view across the whole matrix -------------------

@pytest.mark.parametrize("kv_block", [8, 0], ids=["paged", "contig"])
@pytest.mark.parametrize("kv_int8", [False, True],
                         ids=["fp32", "int8"])
@pytest.mark.parametrize("spec_k", [0, 3], ids=["spec-off", "spec-on"])
def test_span_parity_matrix(params, cfg, kv_block, kv_int8, spec_k):
    """Greedy output with the span ladder is bit-identical to the
    full-view programs: the rows a span read drops were all masked to
    exact-zero softmax weight, and the kept rows keep their order."""
    prompts = _mixed_prompts(cfg)

    def run(span_buckets):
        e = _engine(params, cfg, span_buckets=span_buckets,
                    kv_block=kv_block, kv_int8=kv_int8, spec_k=spec_k)
        outs = e.generate(prompts, max_new_tokens=20)
        return e, outs

    e_span, out_span = run(None)
    _, out_full = run(0)
    assert out_span == out_full
    # The span pass really ran bucketed programs (not just the
    # fallback): some dispatched burst read fewer than max_len rows.
    spans = [s for kind, *_, s in e_span.decode_programs
             if kind in ("burst", "verify") and s is not None]
    assert spans and min(spans) < e_span.max_len


def test_span_parity_weights_int8(cfg):
    """w8a8 engines (slim fp tree) span-bucket identically."""
    from skypilot_tpu.infer import kvcache
    params, qw = kvcache.random_quantized_params(cfg)
    prompts = _mixed_prompts(cfg)

    def run(span_buckets):
        e = _engine(params, cfg, span_buckets=span_buckets,
                    qweights=qw, kv_int8=True)
        return e.generate(prompts, max_new_tokens=16)

    assert run(None) == run(0)


# -- retrace discipline -----------------------------------------------------

def test_program_count_bounded_by_ladder(params, cfg):
    """A mixed-length workload (many distinct lengths) compiles at
    most one decode program and one verify program per ladder rung —
    the ladder, not the length distribution, bounds the compile
    count."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, cfg.vocab_size, n).tolist()
               for n in (3, 5, 7, 9, 11, 14, 17, 21, 25, 30)]
    e = _engine(params, cfg, slots=5, spec_k=3)
    e.generate(prompts, max_new_tokens=17)
    ladder = len(e.span_ladder)
    by_kind = {}
    for key in e.decode_programs:
        by_kind.setdefault(key[0], set()).add(key)
    # Burst width is pinned (max_burst rounds to one power of two
    # here), so each kind's program count is ladder-bounded.
    for kind in ("burst", "verify"):
        widths = {k[1] for k in by_kind.get(kind, ())}
        for w in widths:
            n = len([k for k in by_kind[kind] if k[1] == w])
            assert n <= ladder, (
                f"{kind}@{w}: {n} programs > ladder {ladder}")
    # Spans dispatched are ladder rungs (None = the max_len rung).
    for key in e.decode_programs:
        span = key[-1]
        assert span is None or span in e.span_ladder


# -- regrouping -------------------------------------------------------------

def test_single_long_slot_promotes_only_its_group(params, cfg):
    """One long conversation in a mixed burst rides the big bucket
    ALONE; its short neighbors keep their small-span programs."""
    rng = np.random.default_rng(2)
    short = [rng.integers(1, cfg.vocab_size, 4).tolist()
             for _ in range(3)]
    long_p = rng.integers(1, cfg.vocab_size, 30).tolist()
    e = _engine(params, cfg, span_buckets=(8, 16), slots=4)
    assert e.span_ladder == (8, 16, 64)
    for p in short:
        e.add_request(p, max_new_tokens=8)
    e.add_request(long_p, max_new_tokens=8)
    e.admit()
    while e.chunking:
        e.prefill_chunk_step()
    groups = e._span_groups(8)
    assert len(groups) == 2
    (span_s, slots_s), (span_l, slots_l) = groups
    assert span_s in (8, 16) and len(slots_s) == 3
    assert span_l == 64 and len(slots_l) == 1
    # Dispatch + complete: the short group really ran a small-span
    # program, the long group the full view; outputs land for all.
    handle = e.dispatch_decode_burst(max_burst=4)
    out = e.complete_decode_burst(handle)
    assert len(out) == 4
    kinds = {(k, s) for k, _, s in e.decode_programs if k == "burst"}
    assert ("burst", span_s) in kinds
    assert ("burst", None) in kinds          # long slot: max_len rung


# -- lazy block growth ------------------------------------------------------

def test_lazy_reserves_less_and_grows(params, cfg):
    prompts = _mixed_prompts(cfg, lengths=(5, 9))

    def admit_only(kv_lazy):
        e = _engine(params, cfg, kv_block=8, kv_lazy=kv_lazy, slots=2,
                    prefix_pool=0)
        for p in prompts:
            e.add_request(p, max_new_tokens=40)
        e.admit()
        while e.chunking:
            e.prefill_chunk_step()
        return e

    lazy, eager = admit_only(True), admit_only(False)
    assert lazy.kv_lazy and not eager.kv_lazy
    # Admission-time reservation: prompt + one burst, not the full
    # max_new_tokens worst case.
    assert lazy.blocks_used < eager.blocks_used
    used0 = lazy.blocks_used
    while lazy.slot_req:
        lazy.decode_burst(max_burst=4)
    # Growth happened at dispatch (the budget needs more rows than
    # the admission reservation backed), and every grown block was
    # released at retirement (prefix pool is off here).
    assert max(len(r.tokens) for r in lazy.finished) > 1
    assert lazy.blocks_used == 0
    outs_l = {r.rid: r.tokens for r in lazy.finished}
    while eager.slot_req:
        eager.decode_burst(max_burst=4)
    outs_e = {r.rid: r.tokens for r in eager.finished}
    # Lazy-vs-eager greedy parity: growth only changes WHEN blocks
    # are mapped, never what the programs read.
    assert outs_l == outs_e
    assert used0 > 0


def test_lazy_block_leak_audit(params, cfg):
    """The existing audit extends to lazy mode: a full admit/decode/
    retire cycle plus a prefix-cache clear ends at 0 blocks used."""
    e = _engine(params, cfg, kv_lazy=True, spec_k=3)
    e.generate(_mixed_prompts(cfg), max_new_tokens=20)
    assert not e.slot_req and not e.chunking
    e.clear_prefix_cache()
    assert e.blocks_used == 0
    # And reset() from any state.
    e.generate(_mixed_prompts(cfg, seed=3), max_new_tokens=8)
    e.reset()
    assert e.blocks_used == 0


def test_lazy_env_knob(params, cfg, monkeypatch):
    monkeypatch.setenv("SKYTPU_KV_LAZY", "1")
    assert _engine(params, cfg).kv_lazy
    monkeypatch.delenv("SKYTPU_KV_LAZY")
    assert not _engine(params, cfg).kv_lazy
    # Contiguous engines have no pool to be lazy about.
    assert not _engine(params, cfg, kv_block=0, kv_lazy=True).kv_lazy


def test_lazy_grows_metric(params, cfg):
    from skypilot_tpu.observability import metrics as obs

    def grows():
        fam = obs.REGISTRY.snapshot().get("skytpu_kv_lazy_grows_total")
        if not fam:
            return 0
        return sum(s.get("value", 0) for s in fam["samples"])

    v0 = grows()
    e = _engine(params, cfg, kv_lazy=True, prefix_pool=0)
    e.generate(_mixed_prompts(cfg), max_new_tokens=30)
    assert grows() > v0


# -- bench wiring -----------------------------------------------------------

def test_span_smoke_bench_wiring():
    """CI-sized bench pass: parity, and the structural (timing-free)
    evidence — the span pass gathered a fraction of the full view
    with a ladder-bounded program count. Wall-clock speedup is
    reported, never asserted, on CPU."""
    from skypilot_tpu.infer import bench_serve
    r = bench_serve.run_span_smoke()
    assert r["parity_ok"]
    assert r["rows_span"] * 8 <= r["rows_full"]
    assert r["n_span_programs"] <= len(r["span_ladder"])
    assert r["speedup"] > 0
