"""Live GCP pricing fetcher against recorded SKU fixtures (offline).

The fixture mimics the Cloud Billing skus.list response shape
(pagination, pricingInfo tiers, description conventions) so the parse +
merge pipeline runs for real without egress.
"""

import csv

import pytest

from skypilot_tpu.catalog.fetchers import fetch_gcp


def _sku(desc, regions, units, nanos, usage="OnDemand"):
    return {
        "description": desc,
        "serviceRegions": regions,
        "category": {"resourceFamily": "Compute", "usageType": usage},
        "pricingInfo": [{
            "pricingExpression": {
                "tieredRates": [{
                    "unitPrice": {"units": str(units), "nanos": nanos},
                }],
            },
        }],
    }


FIXTURE_PAGES = {
    # Compute Engine service carries v5e/v5p/v6e per-chip SKUs.
    fetch_gcp.COMPUTE_SERVICE_ID: [
        {"skus": [
            _sku("TpuV5e chip hour in us-west4", ["us-west4"], 1, 56e7),
            _sku("Preemptible TpuV5e chip hour in us-west4",
                 ["us-west4"], 0, 62e7),
            _sku("TpuV6e chip hour in us-east5", ["us-east5"], 2, 97e7),
        ], "nextPageToken": "page2"},
        {"skus": [
            _sku("TpuV5p chip hour in us-east5", ["us-east5"], 4, 2e8),
        ]},
    ],
    # Cloud TPU service carries v2-v4 per-core SKUs, Pod/device split.
    fetch_gcp.TPU_SERVICE_ID: [
        {"skus": [
            _sku("Tpu-v3 accelerator core running in Americas",
                 ["us-central1"], 1, 0),
            _sku("Tpu-v3 Pod accelerator core running in Americas",
                 ["us-central1"], 1, 25e7),
        ]},
    ],
}


@pytest.fixture
def fake_fetch():
    state = {"pages": {}, "calls": []}

    def fetch(url):
        state["calls"].append(url)
        for sid, pages in FIXTURE_PAGES.items():
            if f"/services/{sid}/" in url:
                i = state["pages"].get(sid, 0)
                if "pageToken" in url:
                    assert i > 0, "pageToken on first call"
                state["pages"][sid] = i + 1
                return pages[i]
        raise AssertionError(f"unexpected url {url}")

    fetch.state = state
    return fetch


def test_get_skus_paginates(fake_fetch):
    skus = fetch_gcp.get_skus(fetch_gcp.COMPUTE_SERVICE_ID, fake_fetch)
    assert len(skus) == 4
    assert len(fake_fetch.state["calls"]) == 2
    assert "pageToken=page2" in fake_fetch.state["calls"][1]


def test_unit_price_units_plus_nanos():
    sku = _sku("x", [], 2, 97e7)
    assert abs(fetch_gcp.unit_price(sku) - 2.97) < 1e-9
    assert fetch_gcp.unit_price({"pricingInfo": []}) is None


def test_tpu_chip_price_per_chip_generations():
    skus = FIXTURE_PAGES[fetch_gcp.COMPUTE_SERVICE_ID][0]["skus"]
    od = fetch_gcp.tpu_chip_price(skus, "v5e", "us-west4", spot=False,
                                  is_pod=True)
    sp = fetch_gcp.tpu_chip_price(skus, "v5e", "us-west4", spot=True,
                                  is_pod=True)
    assert abs(od - 1.56) < 1e-9
    assert abs(sp - 0.62) < 1e-9
    # Wrong region -> no match, keep static price.
    assert fetch_gcp.tpu_chip_price(skus, "v5e", "europe-west4",
                                    spot=False, is_pod=False) is None


def test_tpu_chip_price_per_core_pod_split():
    skus = FIXTURE_PAGES[fetch_gcp.TPU_SERVICE_ID][0]["skus"]
    dev = fetch_gcp.tpu_chip_price(skus, "v3", "us-central1", spot=False,
                                   is_pod=False)
    pod = fetch_gcp.tpu_chip_price(skus, "v3", "us-central1", spot=False,
                                   is_pod=True)
    # Per-core SKU -> per-chip price is 2x (2 cores per chip).
    assert abs(dev - 2.00) < 1e-9
    assert abs(pod - 2.50) < 1e-9


def test_fetch_and_write_overlays_live_prices(tmp_path, fake_fetch):
    out = tmp_path / "gcp.csv"
    path, updated, total = fetch_gcp.fetch_and_write(str(out), fake_fetch)
    assert updated > 0 and total >= updated
    with open(path, newline="") as f:
        rows = list(csv.DictReader(f))
    # v5e-16 in us-west4: 16 chips x live $1.56 = $24.96 (static was
    # 16 x $1.20 = $19.20).
    v5e = [r for r in rows if r["instance_type"] == "tpu-v5e"
           and r["zone"] == "us-west4-a" and r["chips"] == "16"]
    assert v5e and float(v5e[0]["price"]) == 24.96
    assert float(v5e[0]["spot_price"]) == 16 * 0.62
    # Rows the fixture has no SKU for keep their static snapshot.
    v2 = [r for r in rows if r["instance_type"] == "tpu-v2"]
    assert v2 and all(float(r["price"]) > 0 for r in v2)


def test_catalog_loads_fetched_csv(tmp_path, fake_fetch, monkeypatch):
    """The query layer reads a fetched CSV identically to the static."""
    from skypilot_tpu.catalog import catalog
    fetch_gcp.fetch_and_write(str(tmp_path / "gcp.csv"), fake_fetch)
    monkeypatch.setattr(catalog, "_DATA_DIR", str(tmp_path))
    catalog.reload()
    try:
        cost = catalog.get_hourly_cost("tpu-v5e-16", use_spot=False,
                                       zone="us-west4-a")
        assert cost == 24.96  # live price, not the static 19.20
    finally:
        catalog.reload()
