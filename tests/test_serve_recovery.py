"""Serving fault tolerance (docs/robustness.md §Replica loss & rolling
update): engine crash recovery, graceful drain, and mid-stream LB
failover, chaos-verified.

The headline guarantees:
* an unrecoverable device error at ANY dispatch seam (admit wave,
  prefill chunk, decode burst, spec verify, KV block alloc) resets the
  engine and re-admits every in-flight request through the preemption
  resume path — greedy output BIT-IDENTICAL to a fault-free run,
  across {fp32, int8 KV} x {spec on/off} x {adapters on/off};
* a crash leaks nothing: KV blocks return to the pool, adapter pins
  release, drafter slots free;
* ``POST /drain`` stops admissions (typed 503 + Retry-After, body
  consumed on keep-alive), finishes in-flight work, and flips
  ``/healthz`` to draining (degraded past the deadline) so the LB and
  controller stop routing BEFORE the kill;
* the LB resumes a died-mid-stream generation on a surviving replica
  by replaying prompt + committed tokens with a reduced budget — the
  client sees ONE gapless, duplicate-free token sequence;
* the serve tier drains a replica before terminating it, and the CLI
  reads a planned drain as exit 0, a stuck one as exit 2.
"""

import http.client
import http.server
import json
import socket
import threading
import time
import urllib.error
import urllib.request
from urllib.parse import urlsplit

import jax
import numpy as np
import pytest

from skypilot_tpu import chaos
from skypilot_tpu.infer import adapters as ad
from skypilot_tpu.infer import draft as draft_lib
from skypilot_tpu.infer import engine as eng
from skypilot_tpu.infer import server as srv
from skypilot_tpu.models import llama
from skypilot_tpu.observability import flight as fl
from skypilot_tpu.observability import forensics
from skypilot_tpu.observability import health as health_lib
from skypilot_tpu.serve import load_balancer, serve_state

CFG = llama.CONFIGS["llama3-tiny"]
PROMPT_LEN = 12   # > prefill_chunk=8: chunk-admitted, resume-covered
NEW_TOKENS = 8


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos._reset_for_tests()
    yield
    chaos._reset_for_tests()


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def distilled(params):
    """(target, draft_params, draft_cfg) at the self-distillation
    endpoint — high acceptance without a training run."""
    return draft_lib.self_distilled_pair(params, CFG, 1)


def _prompts(n=3, length=PROMPT_LEN, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, CFG.vocab_size, length).tolist()
            for _ in range(n)]


def _mk_adapter_params(seed, rank=4, scale=0.05):
    r = np.random.default_rng(seed)
    L = CFG.n_layers
    out = {}
    for t, (sa, sb) in ad.target_shapes(CFG, rank).items():
        sa = sa[:-1] + (rank,)
        sb = (rank,) + sb[1:]
        out[t] = {"a": r.normal(size=(L,) + sa).astype(np.float32)
                  * scale,
                  "b": r.normal(size=(L,) + sb).astype(np.float32)
                  * scale}
    return out


def _catalog(register=2):
    cat = ad.AdapterCatalog(CFG, n_adapters=4, rank=4)
    for i in range(register):
        cat.register(f"ft-{i}", params=_mk_adapter_params(100 + i))
    return cat


def _drive(e, max_burst=4, max_steps=500):
    """Run the engine dry, recovering through every typed dispatch
    crash (a crash is an involuntary preemption). Returns the number
    of recoveries taken."""
    recovered = 0
    for _ in range(max_steps):
        if not (e.waiting or e.chunking or e.slot_req):
            return recovered
        try:
            e.step_burst(max_burst=max_burst)
        except eng.EngineDispatchError as ex:
            e.recover(ex)
            recovered += 1
    raise AssertionError("engine failed to drain")


def _run_batch(e, prompts, adapter=None):
    ids = [e.add_request(list(p), max_new_tokens=NEW_TOKENS,
                         adapter=adapter)
           for p in prompts]
    recovered = _drive(e)
    by_rid = {r.rid: r for r in e.finished}
    assert all(i in by_rid for i in ids)
    return [list(by_rid[i].tokens) for i in ids], recovered


def _recoveries_total():
    return sum(c.value for _, c in eng.ENGINE_RECOVERIES.children())


# ---------------------------------------------------------------------------
# Engine crash recovery: bit-identical resume across the full matrix.


@pytest.mark.parametrize("kv_int8,spec,adapters", [
    (False, False, False), (False, False, True),
    (False, True, False), (False, True, True),
    (True, False, False), (True, False, True),
    (True, True, False), (True, True, True),
])
def test_crash_resume_parity_matrix(params, distilled, kv_int8, spec,
                                    adapters):
    """A seeded chaos fault at the decode (spec: verify) seam mid-run
    resets and resumes every in-flight request with BIT-IDENTICAL
    greedy output, leaking neither KV blocks nor adapter pins —
    across {fp32, int8 KV} x {spec on/off} x {adapters on/off}."""
    kw = dict(n_slots=2, max_len=48, prompt_buckets=(16,),
              prefill_chunk=8, prefix_pool=4, kv_block=16,
              max_wave=2, pad_waves=True, kv_int8=kv_int8)
    eng_params = params
    if spec:
        target, dparams, dcfg = distilled
        eng_params = target
        kw.update(spec_k=4,
                  draft_engine=draft_lib.DraftEngine(
                      dparams, dcfg, n_slots=2, max_len=48,
                      kv_int8=kv_int8))
    cat = _catalog() if adapters else None
    e = eng.InferenceEngine(eng_params, CFG, adapters=cat, **kw)
    prompts = _prompts()
    adapter = "ft-0" if adapters else None

    want, _ = _run_batch(e, prompts, adapter=adapter)
    assert all(len(t) == NEW_TOKENS for t in want)
    e.reset()
    e.clear_prefix_cache()

    seam = "verify" if spec else "decode"
    chaos.configure({"seed": 7, "faults": [
        {"point": "engine.dispatch", "match": {"seam": seam},
         "times": 1}]})
    before = _recoveries_total()
    got, recovered = _run_batch(e, prompts, adapter=adapter)
    inj = chaos.injector()
    chaos.deactivate()

    assert len(inj.fired) == 1
    assert recovered == 1
    assert _recoveries_total() == before + 1
    assert got == want
    # Nothing leaked across the reset: blocks back in the pool once
    # the prefix cache lets go, adapter pins released.
    e.clear_prefix_cache()
    assert e.blocks_used == 0
    assert all(not r.adapter_pinned for r in e.finished)
    if cat is not None:
        assert all(cat.pins(s) == 0 for s in range(cat.n_adapters))


def test_crash_at_admit_seam_recovers(params):
    """A device error during the admission wave (short prompts, no
    chunked prefill) is the same recoverable crash: the victims had
    committed nothing, re-admit from scratch, parity exact."""
    def mk():
        return eng.InferenceEngine(params, CFG, n_slots=2, max_len=32,
                                   prompt_buckets=(8,), kv_block=16)
    prompts = _prompts(length=4, seed=3)
    want, _ = _run_batch(mk(), prompts)

    chaos.configure({"seed": 5, "faults": [
        {"point": "engine.dispatch", "match": {"seam": "admit"},
         "times": 1}]})
    e = mk()
    got, recovered = _run_batch(e, prompts)
    fired = chaos.injector().fired
    chaos.deactivate()
    assert len(fired) == 1 and fired[0]["ctx"]["seam"] == "admit"
    assert recovered == 1 and got == want
    assert e.blocks_used == 0


def test_kv_alloc_fault_recovers_typed(params):
    """A fault at the KV block-allocation point surfaces as a typed
    recoverable EngineDispatchError (the alloc runs inside the
    admit/chunk boundary), never a raw ChaosError, and the run still
    finishes bit-identical."""
    def mk():
        return eng.InferenceEngine(params, CFG, n_slots=2, max_len=48,
                                   prompt_buckets=(16,),
                                   prefill_chunk=8, kv_block=16)
    prompts = _prompts(seed=11)
    want, _ = _run_batch(mk(), prompts)

    chaos.configure({"seed": 2, "faults": [
        {"point": "kv.alloc", "times": 1}]})
    e = mk()
    got, recovered = _run_batch(e, prompts)
    fired = chaos.injector().fired
    chaos.deactivate()
    assert len(fired) == 1
    assert recovered >= 1 and got == want
    assert e.blocks_used == 0


def test_crash_mid_chunk_releases_blocks_and_adapter_pins(params):
    """Leak audit, crash mid prefill-chunk on an adapter engine: after
    recovery and completion the block pool returns to empty and no
    adapter pool slot stays pinned."""
    cat = _catalog()
    e = eng.InferenceEngine(params, CFG, adapters=cat, n_slots=2,
                            max_len=48, prompt_buckets=(16,),
                            prefill_chunk=8, prefix_pool=4,
                            kv_block=16)
    chaos.configure({"seed": 9, "faults": [
        {"point": "engine.dispatch", "match": {"seam": "chunk"},
         "times": 1}]})
    out, recovered = _run_batch(e, _prompts(seed=4), adapter="ft-1")
    chaos.deactivate()
    assert recovered == 1
    assert all(len(t) == NEW_TOKENS for t in out)
    assert all(not r.adapter_pinned for r in e.finished)
    assert all(cat.pins(s) == 0 for s in range(cat.n_adapters))
    e.clear_prefix_cache()
    assert e.blocks_used == 0


def test_crash_mid_verify_releases_drafter_slots(params, distilled):
    """Leak audit, crash mid spec-verify: every drafter slot is free
    after the recovered run — the draft engine's claims died with the
    reset instead of wedging future admissions."""
    target, dparams, dcfg = distilled
    de = draft_lib.DraftEngine(dparams, dcfg, n_slots=2, max_len=48)
    e = eng.InferenceEngine(target, CFG, n_slots=2, max_len=48,
                            prompt_buckets=(16,), prefill_chunk=8,
                            kv_block=16, spec_k=4, draft_engine=de)
    chaos.configure({"seed": 13, "faults": [
        {"point": "engine.dispatch", "match": {"seam": "verify"},
         "times": 1}]})
    out, recovered = _run_batch(e, _prompts(seed=5))
    chaos.deactivate()
    assert recovered == 1
    assert all(len(t) == NEW_TOKENS for t in out)
    assert all(not de.claimed(s) for s in range(de.n_slots))


def test_recover_ledger_names_stall_recover(params):
    """Forensics: a crash victim's critical-path ledger carries the
    requeued outage as a NAMED stall_recover phase, and the phases
    still sum to the wall — the recovery window is attributed, not
    smeared into host_other."""
    e = eng.InferenceEngine(params, CFG, n_slots=2, max_len=48,
                            prompt_buckets=(16,), prefill_chunk=8,
                            kv_block=16,
                            flight_recorder=fl.FlightRecorder())
    chaos.configure({"seed": 21, "faults": [
        {"point": "engine.dispatch", "match": {"seam": "decode"},
         "times": 1}]})
    _run_batch(e, _prompts(seed=6))
    chaos.deactivate()
    victims = [r for r in e.finished if r.recoveries >= 1]
    assert victims
    ledger = forensics.ledger_from_records(victims[0].rid,
                                           e.flight.tail())
    assert ledger is not None
    names = {p["phase"] for p in ledger["phases"]}
    assert "stall_recover" in names
    total = sum(p["ms"] for p in ledger["phases"])
    assert total == pytest.approx(ledger["wall_ms"], abs=0.05)


# ---------------------------------------------------------------------------
# Model server: graceful drain lifecycle + crash-recovery storm guard.


class _SlowEngine:
    """Engine double: one token per slot per decode burst, with a
    per-burst delay so requests stay in flight while the test walks
    the drain lifecycle around them."""

    def __init__(self, n_slots=2, delay_s=0.0):
        self.n_slots = n_slots
        self.delay_s = delay_s
        self.waiting = []
        self.slot_req = {}
        self.finished = []
        self.free_slots = list(range(n_slots))
        self.buckets = (16,)
        self._rid = 0
        self.reset_calls = 0

    def add_request(self, tokens, max_new):
        r = eng.Request(rid=self._rid, prompt=list(tokens),
                        max_new_tokens=max_new)
        self._rid += 1
        self.waiting.append(r)
        return r.rid

    def admit(self, on_wave=None):
        while self.waiting and self.free_slots:
            r = self.waiting.pop(0)
            r.slot = self.free_slots.pop(0)
            r.tokens.append(7)
            r.first_token_s = time.time()
            self.slot_req[r.slot] = r
            if on_wave:
                on_wave()

    def decode_burst(self, max_burst=8):
        if self.delay_s:
            time.sleep(self.delay_s)
        for slot, r in list(self.slot_req.items()):
            r.tokens.append(8)
            if len(r.tokens) >= r.max_new_tokens:
                self.slot_req.pop(slot)
                self.free_slots.append(slot)
                self.finished.append(r)
        return {}

    def generate(self, prompts, max_new_tokens=2):
        return [[1] * max_new_tokens for _ in prompts]

    def reset(self):
        self.reset_calls += 1
        self.waiting.clear()
        self.slot_req.clear()
        self.finished.clear()
        self.free_slots = list(range(self.n_slots))


def _spawn_model_server(engine, **kw):
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    model, httpd = srv.serve(engine, host="127.0.0.1", port=port, **kw)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    assert model._ready.wait(timeout=60)
    return model, httpd, f"http://127.0.0.1:{port}"


def _post(url, payload, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_drain_lifecycle():
    """The full rolling-update dance on one replica: healthy -> drain
    requested mid-flight -> admissions 503 typed (body consumed on a
    keep-alive socket) -> /health 503 + /healthz draining -> in-flight
    request still completes -> /drain polls to drained, deadline
    stable across idempotent repeats."""
    fake = _SlowEngine(n_slots=2, delay_s=0.02)
    model, httpd, url = _spawn_model_server(fake, max_burst=1)
    try:
        with urllib.request.urlopen(f"{url}/healthz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "healthy"
        # Malformed drain body: typed 400, state untouched.
        code, out = _post(f"{url}/drain", [1, 2])
        assert code == 400 and not model.draining()

        result = {}

        def client():
            result["resp"] = _post(f"{url}/generate",
                                   {"tokens": [1, 2],
                                    "max_new_tokens": 40})

        t = threading.Thread(target=client)
        t.start()
        deadline = time.time() + 30
        while model.queue_depth() == 0 and time.time() < deadline:
            time.sleep(0.005)
        assert model.queue_depth() > 0

        code, st = _post(f"{url}/drain", {"grace_s": 20})
        assert code == 200
        assert st["draining"] and not st["drained"]
        assert st["in_flight"] >= 1
        deadline_s = st["deadline_s"]

        # New admissions shed typed on a KEEP-ALIVE connection — and
        # the connection stays parseable afterwards (the body was
        # consumed, not left to corrupt the next request).
        parts = urlsplit(url)
        conn = http.client.HTTPConnection(parts.hostname, parts.port,
                                          timeout=30)
        body = json.dumps({"tokens": [3], "max_new_tokens": 4}).encode()
        conn.request("POST", "/generate", body=body,
                     headers={"Content-Type": "application/json"})
        r = conn.getresponse()
        assert r.status == 503
        assert r.getheader("Retry-After") == "1"
        shed = json.loads(r.read())
        assert shed["error"]["type"] == "draining"
        conn.request("GET", "/healthz")
        r2 = conn.getresponse()
        hz = json.loads(r2.read())
        assert hz["status"] == "draining"
        assert "in flight" in hz["reason"]
        conn.close()

        # /health flips 503 so the LB/controller stop routing here.
        try:
            urllib.request.urlopen(f"{url}/health", timeout=30)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert e.headers.get("Retry-After") == "1"
            assert json.loads(e.read())["status"] == "draining"

        # The in-flight request FINISHES — drain sheds admissions,
        # never work already accepted.
        t.join(timeout=60)
        code, out = result["resp"]
        assert code == 200 and len(out["tokens"]) == 40

        deadline = time.time() + 30
        st = model.drain_status()
        while not st["drained"] and time.time() < deadline:
            time.sleep(0.02)
            code, st = _post(f"{url}/drain", {"grace_s": 20})
        assert st["drained"] and st["in_flight"] == 0
        # Idempotent: the repeat polls kept the FIRST deadline.
        assert st["deadline_s"] == deadline_s
    finally:
        model.shutdown()
        httpd.shutdown()


def test_drain_past_deadline_degrades_healthz():
    """A drain that cannot finish inside its grace window self-reports
    degraded on /healthz — which rolls up to `skytpu status --health`
    exit 2 (a stuck rolling update is an incident, a progressing one
    is not)."""
    fake = _SlowEngine(n_slots=1, delay_s=0.02)
    model, httpd, url = _spawn_model_server(fake, max_burst=1)
    try:
        p = model._add([1], 10 ** 6)        # never finishes
        deadline = time.time() + 30
        while model.queue_depth() == 0 and time.time() < deadline:
            time.sleep(0.005)
        code, st = _post(f"{url}/drain", {"grace_s": 0})
        assert code == 200 and st["draining"]
        time.sleep(0.05)
        with urllib.request.urlopen(f"{url}/healthz", timeout=30) as r:
            hz = json.loads(r.read())
        assert hz["status"] == "degraded"
        assert "past deadline" in hz["reason"]
        del p
    finally:
        model.shutdown()
        httpd.shutdown()


class _DeviceGone(RuntimeError):
    recoverable = True
    seam = "decode"


class _CrashLoopEngine(_SlowEngine):
    """Raises a recoverable device error on every decode burst while
    work is in flight; recover() requeues the victims — the crash
    repeats until the server's storm guard gives up."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.recover_calls = 0

    def decode_burst(self, max_burst=8):
        if self.slot_req:
            raise _DeviceGone("HBM parity storm")
        return {}

    def recover(self, exc=None):
        self.recover_calls += 1
        victims = list(self.slot_req.values())
        self.slot_req.clear()
        self.free_slots = list(range(self.n_slots))
        for r in victims:
            r.slot = None
            self.waiting.append(r)
        return len(victims)


def test_recovery_storm_guard_fails_over_to_reset(monkeypatch):
    """A crash LOOP must not recover forever: past the rolling-window
    storm limit the server stops resetting-and-requeuing, fails the
    in-flight requests typed, and does a plain reset — bounded victim
    retries instead of an invisible livelock."""
    monkeypatch.setenv("SKYTPU_RECOVERY_STORM_LIMIT", "2")
    fake = _CrashLoopEngine(n_slots=1)
    model = srv.ModelServer(fake, max_burst=4)
    try:
        p = model._add([1], 8)
        assert p.event.wait(timeout=30)
        assert "error" in (p.result or {})
        # Exactly limit recoveries were attempted, then the guard
        # routed to the fail-all path (which resets the engine).
        assert fake.recover_calls == 2
        assert fake.reset_calls >= 1
        assert model._ready.is_set()
    finally:
        model.shutdown()


# ---------------------------------------------------------------------------
# Load balancer: mid-stream failover onto a surviving replica.


def _tok(pos):
    """The scripted replicas' shared greedy function: the token at
    CONTEXT POSITION pos. Replaying prompt+committed on any replica
    continues the same sequence — the determinism mid-stream failover
    leans on."""
    return (pos * 37 + 11) % 997


class _Scripted(http.server.BaseHTTPRequestHandler):
    """A scripted streaming replica. Fault switches are CLASS state
    shared by every replica in the service, so 'the first replica the
    policy picks dies once' is deterministic regardless of selection
    order."""

    protocol_version = "HTTP/1.1"
    bodies = []
    die_after = None       # emit N token lines, then cut the socket
    die_drop_done = False  # emit ALL tokens, then die before done
    boom_first = False     # 500 the first request (connect phase)
    died = 0

    @classmethod
    def reset(cls):
        cls.bodies = []
        cls.die_after = None
        cls.die_drop_done = False
        cls.boom_first = False
        cls.died = 0

    def _chunk(self, obj):
        data = json.dumps(obj).encode() + b"\n"
        self.wfile.write(b"%x\r\n%s\r\n" % (len(data), data))
        self.wfile.flush()

    def _die(self):
        # close() alone won't send FIN while rfile/wfile still hold
        # makefile refs on the socket — shutdown() makes the death
        # visible to the LB immediately.
        try:
            self.connection.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.connection.close()

    def do_POST(self):
        cls = type(self)
        n = int(self.headers.get("Content-Length") or 0)
        fields = json.loads(self.rfile.read(n) or b"{}")
        cls.bodies.append(fields)
        if cls.boom_first:
            cls.boom_first = False
            out = b"exploded"
            self.send_response(500)
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)
            return
        start = len(fields["tokens"])
        budget = int(fields["max_new_tokens"])
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        for i in range(budget):
            if (cls.die_after is not None and cls.died == 0
                    and i >= cls.die_after):
                cls.died = 1
                self._die()   # abrupt: no terminal chunk
                return
            self._chunk({"tokens": [_tok(start + i)]})
        if cls.die_drop_done and cls.died == 0:
            cls.died = 1
            self._die()
            return
        self._chunk({"done": True, "n_tokens": budget})
        self.wfile.write(b"0\r\n\r\n")

    def finish(self):
        try:
            super().finish()
        except Exception:  # noqa: BLE001 — scripted abrupt close
            pass

    def log_message(self, *a):
        pass


class _QuietServer(http.server.ThreadingHTTPServer):
    daemon_threads = True

    def handle_error(self, request, client_address):
        pass


@pytest.fixture()
def lb2(tmp_path, monkeypatch):
    """An LB over TWO scripted replicas."""
    yield from _mk_lb(tmp_path, monkeypatch, n_replicas=2)


@pytest.fixture()
def lb1(tmp_path, monkeypatch):
    """An LB over ONE scripted replica (candidate exhaustion)."""
    yield from _mk_lb(tmp_path, monkeypatch, n_replicas=1)


def _mk_lb(tmp_path, monkeypatch, n_replicas):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    _Scripted.reset()
    serve_state.add_service("rec", {}, {}, 0)
    replicas = []
    for i in range(n_replicas):
        httpd = _QuietServer(("127.0.0.1", 0), _Scripted)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        serve_state.upsert_replica(
            "rec", i + 1, f"r{i + 1}", serve_state.ReplicaStatus.READY,
            f"http://127.0.0.1:{httpd.server_address[1]}")
        replicas.append(httpd)
    lb_httpd = load_balancer._ThreadingServer(
        ("127.0.0.1", 0),
        load_balancer.make_handler("rec",
                                   load_balancer.LeastLoadPolicy()))
    threading.Thread(target=lb_httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{lb_httpd.server_address[1]}"
    lb_httpd.shutdown()
    for r in replicas:
        r.shutdown()


def _lb_stream(lb_url, payload, timeout=30):
    """POST a streaming generate through the LB; returns the parsed
    NDJSON objects. read() raises on a truncated chunked body, so a
    normal return PROVES the terminal chunk arrived."""
    parts = urlsplit(lb_url)
    conn = http.client.HTTPConnection(parts.hostname, parts.port,
                                      timeout=timeout)
    conn.request("POST", "/generate",
                 body=json.dumps(payload).encode(),
                 headers={"Content-Type": "application/json"})
    r = conn.getresponse()
    assert r.status == 200
    body = r.read()
    conn.close()
    return [json.loads(ln) for ln in body.split(b"\n") if ln.strip()]


def _fo(phase):
    return load_balancer.LB_FAILOVERS.labels(phase=phase).value


def test_lb_mid_stream_failover_gapless(lb2):
    """A replica dying mid-stream is invisible to the client: the LB
    replays prompt + committed tokens on the survivor with a reduced
    budget and the stitched stream is gapless and duplicate-free."""
    _Scripted.die_after = 4
    before = _fo("mid_stream")
    prompt = [5, 9, 2, 7, 1]
    objs = _lb_stream(lb2, {"tokens": prompt, "max_new_tokens": 12,
                            "stream": True})
    want = [_tok(len(prompt) + i) for i in range(12)]
    got = [t for o in objs for t in o.get("tokens", [])]
    assert got == want
    done = objs[-1]
    assert done["done"] and done["n_tokens"] == 12
    assert done["failovers"] == 1
    assert _fo("mid_stream") == before + 1
    # The survivor was handed EXACTLY prompt + committed, with the
    # budget reduced by what already streamed.
    replay = _Scripted.bodies[-1]
    assert replay["tokens"] == prompt + want[:4]
    assert replay["max_new_tokens"] == 8


def test_lb_connect_phase_failover(lb2):
    """A replica that 500s before any byte streams costs a connect-
    phase failover, not a client-visible error: the next candidate
    serves the whole generation."""
    _Scripted.boom_first = True
    before = _fo("connect")
    prompt = [4, 4, 4]
    objs = _lb_stream(lb2, {"tokens": prompt, "max_new_tokens": 6,
                            "stream": True})
    got = [t for o in objs for t in o.get("tokens", [])]
    assert got == [_tok(3 + i) for i in range(6)]
    assert objs[-1]["done"] and objs[-1]["failovers"] == 1
    assert _fo("connect") == before + 1


def test_lb_exhausted_candidates_typed_in_stream_error(lb1):
    """No survivor left: the stream ends with a typed in-stream
    upstream_lost error AND a clean terminal chunk — a parseable
    failure, never a truncation the client must infer from framing."""
    _Scripted.die_after = 2
    objs = _lb_stream(lb1, {"tokens": [1, 2], "max_new_tokens": 6,
                            "stream": True})
    got = [t for o in objs for t in o.get("tokens", [])]
    assert got == [_tok(2), _tok(3)]
    err = objs[-1]["error"]
    assert err["type"] == "upstream_lost"
    assert err["n_streamed"] == 2
    assert err["failovers"] == 1


def test_lb_full_budget_lost_done_line_minted(lb1):
    """The replica delivered the whole budget but died before its done
    line: the LB mints the trailer itself instead of replaying a
    zero-budget generation."""
    _Scripted.die_drop_done = True
    objs = _lb_stream(lb1, {"tokens": [6, 6], "max_new_tokens": 5,
                            "stream": True})
    got = [t for o in objs for t in o.get("tokens", [])]
    assert got == [_tok(2 + i) for i in range(5)]
    done = objs[-1]
    assert done["done"] and done["lb_minted"]
    assert done["n_tokens"] == 5 and done["failovers"] == 1


def test_lb_failover_disabled_env(tmp_path, monkeypatch):
    """SKYTPU_LB_FAILOVER=0 restores the raw-splice contract: a
    replica death mid-stream is a client-visible truncation and no
    failover is counted."""
    monkeypatch.setenv("SKYTPU_LB_FAILOVER", "0")
    gen = _mk_lb(tmp_path, monkeypatch, n_replicas=2)
    lb_url = next(gen)
    try:
        _Scripted.die_after = 2
        before = _fo("mid_stream") + _fo("connect")
        with pytest.raises((http.client.IncompleteRead,
                            http.client.HTTPException,
                            ConnectionError, OSError)):
            parts = urlsplit(lb_url)
            conn = http.client.HTTPConnection(parts.hostname,
                                              parts.port, timeout=30)
            conn.request(
                "POST", "/generate",
                body=json.dumps({"tokens": [1], "max_new_tokens": 6,
                                 "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            r = conn.getresponse()
            r.read()
            raise ConnectionError("truncated body read as complete")
        assert _fo("mid_stream") + _fo("connect") == before
    finally:
        for _ in gen:
            pass


def test_lb_typed_503_carries_retry_after(tmp_path, monkeypatch):
    """Zero ready replicas: the streaming path sheds typed 503
    overloaded WITH Retry-After — a client can distinguish 'back off'
    from a replica 5xx without parsing prose."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    serve_state.add_service("empty", {}, {}, 0)
    lb_httpd = load_balancer._ThreadingServer(
        ("127.0.0.1", 0),
        load_balancer.make_handler("empty",
                                   load_balancer.LeastLoadPolicy()))
    threading.Thread(target=lb_httpd.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{lb_httpd.server_address[1]}"
        code, out = _post(f"{url}/generate",
                          {"tokens": [1], "max_new_tokens": 4,
                           "stream": True})
        assert code == 503
        assert out["error"]["type"] == "overloaded"
        req = urllib.request.Request(
            f"{url}/generate",
            data=json.dumps({"tokens": [1], "max_new_tokens": 4,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert e.headers.get("Retry-After") is not None
            e.read()
    finally:
        lb_httpd.shutdown()


def test_lb_chunked_request_411(lb2):
    """A chunked request body is a typed 411 + close: reading it is
    unimplemented, and NOT reading it would poison the keep-alive
    socket for the next request."""
    parts = urlsplit(lb2)
    with socket.create_connection((parts.hostname, parts.port),
                                  timeout=30) as s:
        s.sendall(b"POST /generate HTTP/1.1\r\n"
                  b"Host: lb\r\n"
                  b"Transfer-Encoding: chunked\r\n\r\n")
        data = b""
        while True:   # 411 closes the connection: read to EOF
            piece = s.recv(65536)
            if not piece:
                break
            data += piece
    assert b" 411 " in data.split(b"\r\n", 1)[0]
    assert b"length_required" in data


# ---------------------------------------------------------------------------
# Serve tier: the controller drains a replica BEFORE terminating it.


class _DrainEndpoint(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    calls = 0

    def do_POST(self):
        cls = type(self)
        n = int(self.headers.get("Content-Length") or 0)
        self.rfile.read(n)
        if self.path != "/drain":
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        cls.calls += 1
        body = json.dumps({
            "draining": True,
            "in_flight": 0 if cls.calls >= 2 else 1,
            "drained": cls.calls >= 2,
            "deadline_s": 0,
        }).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


def _mk_manager(monkeypatch, tmp_path, service):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    monkeypatch.setenv("SKYTPU_SERVE_DRAIN_GRACE_S", "10")
    from skypilot_tpu.serve import replica_managers
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    serve_state.add_service(service, {}, {}, 0)
    return replica_managers.ReplicaManager(service, SkyServiceSpec(), {})


def test_terminate_replica_drains_before_kill(monkeypatch, tmp_path):
    """_terminate_replica flips the replica to DRAINING synchronously
    (instantly out of ready_urls: the LB stops routing BEFORE any
    kill), polls POST /drain until drained, and only then moves to
    SHUTTING_DOWN and removes it."""
    _DrainEndpoint.calls = 0
    httpd = _QuietServer(("127.0.0.1", 0), _DrainEndpoint)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        mgr = _mk_manager(monkeypatch, tmp_path, "drainsvc")
        serve_state.upsert_replica("drainsvc", 1, "c1",
                                   serve_state.ReplicaStatus.READY, url)
        assert serve_state.ready_urls("drainsvc") == [url]
        mgr._terminate_replica(1)
        # Synchronous part: DRAINING and unrouted immediately.
        (row,) = serve_state.list_replicas("drainsvc")
        assert row["status"] == serve_state.ReplicaStatus.DRAINING
        assert serve_state.ready_urls("drainsvc") == []
        deadline = time.time() + 30
        while (serve_state.list_replicas("drainsvc")
               and time.time() < deadline):
            time.sleep(0.02)
        assert serve_state.list_replicas("drainsvc") == []
        # Drained via polling: the first poll reported in-flight work,
        # so the manager waited for at least one more.
        assert _DrainEndpoint.calls >= 2
        mgr._pool.shutdown(wait=True)
    finally:
        httpd.shutdown()


def test_terminate_replica_immediate_kill_skips_drain(monkeypatch,
                                                      tmp_path):
    """drain=False (preemption, teardown): straight to SHUTTING_DOWN,
    zero /drain calls — the endpoint is already gone or going."""
    _DrainEndpoint.calls = 0
    mgr = _mk_manager(monkeypatch, tmp_path, "killsvc")
    serve_state.upsert_replica("killsvc", 1, "c1",
                               serve_state.ReplicaStatus.READY,
                               "http://127.0.0.1:1")
    mgr._terminate_replica(1, drain=False)
    (row,) = serve_state.list_replicas("killsvc") or [None]
    if row is not None:   # async removal may not have landed yet
        assert row["status"] == serve_state.ReplicaStatus.SHUTTING_DOWN
    deadline = time.time() + 30
    while (serve_state.list_replicas("killsvc")
           and time.time() < deadline):
        time.sleep(0.02)
    assert serve_state.list_replicas("killsvc") == []
    assert _DrainEndpoint.calls == 0
    mgr._pool.shutdown(wait=True)


def test_draining_excluded_from_capacity_and_probes(monkeypatch,
                                                    tmp_path):
    """A DRAINING replica is on its way out: it must not count toward
    scale capacity nor be probed (a probe failure would double-
    terminate it)."""
    mgr = _mk_manager(monkeypatch, tmp_path, "capsvc")
    serve_state.upsert_replica("capsvc", 1, "c1",
                               serve_state.ReplicaStatus.DRAINING,
                               "http://127.0.0.1:1")
    serve_state.upsert_replica("capsvc", 2, "c2",
                               serve_state.ReplicaStatus.READY,
                               "http://127.0.0.1:2")
    live = mgr._live_replicas()
    assert [r["replica_id"] for r in live] == [2]

    probed = []
    monkeypatch.setattr(mgr, "_cluster_gone", lambda name: False)
    monkeypatch.setattr(mgr, "_probe_one",
                        lambda r: probed.append(r["replica_id"]) or True)
    mgr.probe_all()
    assert probed == [2]
    mgr._pool.shutdown(wait=True)


# ---------------------------------------------------------------------------
# Fleet health + CLI: a planned drain is visible, not an incident.


def test_worst_ranks_draining_between_healthy_and_degraded():
    mk = health_lib.component
    comps = [mk("model-server", "s/1", health_lib.HEALTHY)]
    assert health_lib.worst(comps) == health_lib.HEALTHY
    comps.append(mk("model-server", "s/2", health_lib.DRAINING))
    assert health_lib.worst(comps) == health_lib.DRAINING
    comps.append(mk("model-server", "s/3", health_lib.DEGRADED))
    assert health_lib.worst(comps) == health_lib.DEGRADED
    comps.append(mk("model-server", "s/4", health_lib.DEAD))
    assert health_lib.worst(comps) == health_lib.DEAD


def test_probe_replica_draining_branch():
    """A DRAINING replica row probes the replica itself: within its
    deadline it self-reports draining; past it, degraded; no URL reads
    as draining without a probe."""
    class _Healthz(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        status = health_lib.DRAINING
        reason = "draining (2 in flight)"

        def do_GET(self):
            health_lib.write_healthz(self, type(self).status,
                                     reason=type(self).reason)

        def log_message(self, *a):
            pass

    httpd = _QuietServer(("127.0.0.1", 0), _Healthz)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        row = {"replica_id": 1,
               "status": serve_state.ReplicaStatus.DRAINING,
               "url": url}
        got = health_lib._probe_replica(row, "svc", timeout=5)
        assert got["status"] == health_lib.DRAINING
        assert "in flight" in got["reason"]

        _Healthz.status = health_lib.DEGRADED
        _Healthz.reason = "draining past deadline (2 in flight)"
        got = health_lib._probe_replica(row, "svc", timeout=5)
        assert got["status"] == health_lib.DEGRADED

        row["url"] = None
        got = health_lib._probe_replica(row, "svc", timeout=5)
        assert got["status"] == health_lib.DRAINING
    finally:
        httpd.shutdown()


def test_status_health_exit_codes(monkeypatch):
    """`skytpu status --health`: a fleet whose worst component is
    draining is a PLANNED rolling update (exit 0, '-' mark); degraded
    or dead is an incident (exit 2)."""
    from click.testing import CliRunner
    from skypilot_tpu.client import cli as cli_mod

    def payload(status):
        return {"status": status, "alerts": [], "components": [
            health_lib.component("model-server", "svc/1", status,
                                 reason="draining (1 in flight)")]}

    monkeypatch.setattr(cli_mod, "_fleet_fetch",
                        lambda need_metrics=True: (None,
                                                   payload("draining")))
    res = CliRunner().invoke(cli_mod.cli, ["status", "--health"])
    assert res.exit_code == 0
    assert "fleet: DRAINING" in res.output
    assert "-  model-server" in res.output

    monkeypatch.setattr(cli_mod, "_fleet_fetch",
                        lambda need_metrics=True: (None,
                                                   payload("degraded")))
    res = CliRunner().invoke(cli_mod.cli, ["status", "--health"])
    assert res.exit_code == 2


def test_top_serve_line_fault_tolerance_columns():
    """`skytpu top`: replicas mid-drain, the crash-recovery rate, and
    the LB failover rate show on the serve line while they happen —
    and ride the --json data dict under the same names."""
    from skypilot_tpu.client import cli as cli_mod

    def fams(req, rec, fo, drain):
        return {
            "skytpu_http_requests_total": {
                "type": "counter",
                "samples": [({"code": "200"}, float(req))]},
            "skytpu_server_draining": {
                "type": "gauge", "samples": [({}, float(drain))]},
            "skytpu_engine_recoveries_total": {
                "type": "counter",
                "samples": [({"seam": "decode"}, float(rec))]},
            "skytpu_lb_failovers_total": {
                "type": "counter",
                "samples": [({"phase": "mid_stream"}, float(fo))]},
        }

    payload = {"status": "draining", "components": [], "alerts": []}
    now = 1000.0
    rendered, data = cli_mod._top_frame(
        fams(0, 0, 0, 0), now - 10.0, fams(10, 5, 3, 2), now, payload)
    serve_line = next(ln for ln in rendered.splitlines()
                      if ln.startswith("serve"))
    assert "drain 2" in serve_line
    assert "recov 0.50/s" in serve_line
    assert "failover 0.30/s" in serve_line
    assert data["serve"]["replicas_draining"] == 2
    assert data["serve"]["recoveries_per_s"] == pytest.approx(0.5)
    assert data["serve"]["failovers_per_s"] == pytest.approx(0.3)


# ---------------------------------------------------------------------------
# The end-to-end chaos gate (bench_serve --failover, CI sizing).


def test_bench_failover_smoke():
    """The chaos-verified e2e gate: a seeded engine.dispatch fault and
    a seeded replica.kill against a 2-replica LB deployment — crash
    recovery AND mid-stream failover both bit-identical, zero lost
    requests."""
    from skypilot_tpu.infer import bench_serve
    r = bench_serve.run_failover_smoke()
    assert r["gate_ok"]
    assert r["crash_parity_ok"] and r["kill_parity_ok"]
    assert r["recoveries"] >= 1 and r["trailer_recoveries"] >= 1
    assert r["failovers"] >= 1 and r["trailer_failovers"] >= 1
    assert r["lost_requests"] == 0
