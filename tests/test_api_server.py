"""API server + SDK tests: a real server on a random port, real worker
subprocesses, the local fake cloud underneath (reference pattern:
in-process API server fixture, tests/common_test_fixtures.py:45 — here
the server runs for real in a thread)."""

import socket
import threading
import time

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.client import sdk
from skypilot_tpu.resources import Resources
from skypilot_tpu.server import server as server_mod
from skypilot_tpu.task import Task


@pytest.fixture()
def api_server(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    monkeypatch.setenv("SKYTPU_API_SERVER_URL", f"http://127.0.0.1:{port}")
    executor = server_mod.Executor()
    executor.start()
    httpd = server_mod._Server(("127.0.0.1", port),
                               server_mod.make_handler())
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    executor.stop()
    httpd.shutdown()


def _local_task(run, name="t"):
    t = Task(name=name, run=run)
    t.set_resources(Resources(cloud="local"))
    return t


def test_health(api_server):
    info = sdk.api_info()
    assert info["status"] == "healthy"


def test_launch_via_server(api_server):
    rid = sdk.launch(_local_task("echo via-server"), cluster_name="api1")
    result = sdk.get(rid, timeout=120)
    assert result["cluster_name"] == "api1"
    assert result["job_id"] == 1

    rid = sdk.status()
    records = sdk.get(rid, timeout=60)
    assert any(r["name"] == "api1" for r in records)

    rid = sdk.queue("api1")
    jobs = sdk.get(rid, timeout=60)
    assert jobs and jobs[0]["job_id"] == 1

    rid = sdk.down("api1")
    assert sdk.get(rid, timeout=60)["ok"]


def test_failed_request_propagates_error(api_server):
    rid = sdk.queue("no-such-cluster")
    with pytest.raises(exceptions.SkyTpuError) as ei:
        sdk.get(rid, timeout=60)
    assert "not found" in str(ei.value)


def test_request_log_streaming(api_server):
    rid = sdk.launch(_local_task("echo streamed"), cluster_name="api2")
    sdk.get(rid, timeout=120)
    import io
    rid2 = sdk.down("api2")
    buf = io.StringIO()
    sdk.stream_and_get(rid2, timeout=60, out=buf)


def test_api_status_lists_requests(api_server):
    rid = sdk.status()
    sdk.get(rid, timeout=60)
    rows = sdk.api_status()
    assert any(r["request_id"] == rid for r in rows)


def test_api_cancel(api_server):
    rid = sdk.launch(_local_task("sleep 120"), cluster_name="api3")
    time.sleep(0.5)
    sdk.api_cancel(rid)
    with pytest.raises(exceptions.SkyTpuError):
        sdk.get(rid, timeout=30)


def test_dashboard_and_json_endpoints(api_server):
    import json
    import urllib.request

    html = urllib.request.urlopen(f"{api_server}/dashboard").read().decode()
    assert "skypilot-tpu" in html and "Clusters" in html

    clusters = json.loads(
        urllib.request.urlopen(f"{api_server}/api/clusters").read())
    assert isinstance(clusters, list)
    jobs = json.loads(
        urllib.request.urlopen(f"{api_server}/api/jobs").read())
    assert isinstance(jobs, list)


def test_server_concurrent_load(api_server):
    """Load test: concurrent status requests + a burst of submissions
    (reference analogue: tests/load_tests/test_load_on_server.py)."""
    import concurrent.futures as cf
    import json
    import urllib.request

    def get_status(_):
        with urllib.request.urlopen(f"{api_server}/api/status",
                                    timeout=30) as r:
            return r.status

    def submit(_):
        body = json.dumps({"cluster_name": "nonexistent-xyz"}).encode()
        req = urllib.request.Request(
            f"{api_server}/status", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())["request_id"]

    with cf.ThreadPoolExecutor(max_workers=16) as pool:
        codes = list(pool.map(get_status, range(40)))
        rids = list(pool.map(submit, range(10)))
    assert all(c == 200 for c in codes)
    assert len(set(rids)) == 10


def test_metrics_endpoint(api_server):
    import json as json_lib
    import urllib.request

    from skypilot_tpu.observability import metrics as metrics_lib

    rid = sdk.launch(_local_task("echo metrics"), cluster_name="apim")
    sdk.get(rid, timeout=120)

    def scrape():
        with urllib.request.urlopen(f"{api_server}/metrics",
                                    timeout=10) as r:
            assert r.status == 200
            assert (r.headers.get("Content-Type")
                    == metrics_lib.CONTENT_TYPE)
            return metrics_lib.parse_exposition(r.read().decode())

    fams = scrape()
    launched = fams["skytpu_api_requests_total"]
    assert any(labels.get("endpoint") == "launch" and v >= 1
               for labels, v in launched["samples"])
    assert "skytpu_api_workers_busy" in fams

    def finished_ok(fams):
        fam = fams.get("skytpu_api_requests_finished_total")
        return fam and any(
            labels.get("status") == "SUCCEEDED" and v >= 1
            for labels, v in fam["samples"])

    # The DB records SUCCEEDED before the executor reaps the worker
    # process (its loop ticks every 50ms) — poll the scrape briefly.
    deadline = time.time() + 30
    while not finished_ok(fams) and time.time() < deadline:
        time.sleep(0.1)
        fams = scrape()
    assert finished_ok(fams)
