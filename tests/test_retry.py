"""utils/retry.py: backoff math, deadlines, classification, breaker."""

import random
import time

import pytest

from skypilot_tpu.utils import retry


def test_backoff_exponential_capped_no_jitter():
    p = retry.RetryPolicy(backoff_base_s=1.0, backoff_multiplier=2.0,
                          backoff_max_s=5.0, jitter=0.0)
    assert [p.backoff_s(a) for a in range(5)] == [1.0, 2.0, 4.0, 5.0, 5.0]


def test_backoff_jitter_bounded_and_seed_deterministic():
    p = retry.RetryPolicy(backoff_base_s=2.0, jitter=0.5)
    seq1 = [p.backoff_s(0, rng=random.Random(42)) for _ in range(1)]
    seq2 = [p.backoff_s(0, rng=random.Random(42)) for _ in range(1)]
    assert seq1 == seq2
    for _ in range(50):
        b = p.backoff_s(0, rng=random.Random())
        # Jitter only shortens: cap stays a hard upper bound.
        assert 1.0 <= b <= 2.0


def test_call_retries_then_succeeds():
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise ValueError("transient")
        return "ok"

    out = retry.call(fn, policy=retry.RetryPolicy(
        max_attempts=5, backoff_base_s=0.001, jitter=0.0))
    assert out == "ok" and len(calls) == 3


def test_call_exhausts_and_reraises_last():
    calls = []

    def fn():
        calls.append(1)
        raise ValueError(f"attempt {len(calls)}")

    with pytest.raises(ValueError, match="attempt 3"):
        retry.call(fn, policy=retry.RetryPolicy(
            max_attempts=3, backoff_base_s=0.001, jitter=0.0))
    assert len(calls) == 3


def test_call_non_retryable_raises_immediately():
    calls = []

    def fn():
        calls.append(1)
        raise KeyError("not transient")

    with pytest.raises(KeyError):
        retry.call(fn, policy=retry.RetryPolicy(
            max_attempts=5, backoff_base_s=0.001,
            retry_on=(ValueError,)))
    assert len(calls) == 1


def test_give_up_on_carves_out_subclass():
    class Transient(Exception):
        pass

    class Permanent(Transient):
        pass

    calls = []

    def fn():
        calls.append(1)
        raise Permanent("permanent refusal")

    with pytest.raises(Permanent):
        retry.call(fn, policy=retry.RetryPolicy(
            max_attempts=5, backoff_base_s=0.001,
            retry_on=(Transient,), give_up_on=(Permanent,)))
    assert len(calls) == 1


def test_deadline_stops_retry_without_sleeping_past_budget():
    calls = []

    def fn():
        calls.append(1)
        raise ValueError("x")

    t0 = time.monotonic()
    with pytest.raises(ValueError):
        retry.call(fn,
                   policy=retry.RetryPolicy(max_attempts=100,
                                            backoff_base_s=0.5,
                                            jitter=0.0),
                   deadline=retry.Deadline(0.3))
    elapsed = time.monotonic() - t0
    # Budget 0.3s with 0.5s backoffs: at most one pause fits nothing —
    # the loop must give up with the REAL error well under a second.
    assert elapsed < 1.0
    assert len(calls) <= 2


def test_deadline_clamp_shrinks_per_attempt_timeout():
    d = retry.Deadline(10.0)
    assert d.clamp(120.0) <= 10.0
    assert d.clamp(1.0) == 1.0
    assert retry.Deadline(None).clamp(7.0) == 7.0
    assert retry.Deadline(None).remaining() is None


def test_deadline_expired_raises_before_first_attempt():
    d = retry.Deadline(0.0)
    time.sleep(0.001)
    with pytest.raises(retry.DeadlineExceededError):
        retry.call(lambda: "never", deadline=d)


def test_on_retry_hook_fires_per_backoff():
    seen = []

    def fn():
        if len(seen) < 2:
            raise ValueError("x")
        return 1

    retry.call(fn,
               policy=retry.RetryPolicy(max_attempts=5,
                                        backoff_base_s=0.001, jitter=0.0),
               on_retry=lambda attempt, exc, pause: seen.append(
                   (attempt, type(exc).__name__, pause)))
    assert seen == [(0, "ValueError", 0.001), (1, "ValueError", 0.002)]


def test_named_policy_records_metrics_and_events():
    from skypilot_tpu.observability import tracing

    def fn():
        raise ValueError("x")

    before = retry.RETRIES.labels(name="unit.test",
                                  outcome="retried").value
    with pytest.raises(ValueError):
        retry.call(fn, name="unit.test", policy=retry.RetryPolicy(
            max_attempts=3, backoff_base_s=0.001, jitter=0.0))
    assert retry.RETRIES.labels(name="unit.test",
                                outcome="retried").value == before + 2
    evs = [r for r in tracing.buffered_records()
           if r.get("name") == "retry.backoff"
           and r.get("attrs", {}).get("policy") == "unit.test"]
    assert len(evs) >= 2


def test_circuit_breaker_half_open_probe_is_exclusive():
    """Only ONE caller gets the half-open probe per reset window —
    concurrent callers keep failing fast until the probe reports."""
    br = retry.CircuitBreaker("unit", failure_threshold=1,
                              reset_after_s=0.05)
    br.record_failure()
    assert not br.allow()
    time.sleep(0.08)
    assert br.allow()          # claims the probe, re-arms the window
    assert not br.allow()      # a second concurrent caller stays blocked
    br.record_success()
    assert br.allow()          # closed again


def test_circuit_breaker_opens_and_half_opens():
    br = retry.CircuitBreaker("unit", failure_threshold=2,
                              reset_after_s=0.15)

    def boom():
        raise ValueError("x")

    for _ in range(2):
        with pytest.raises(ValueError):
            retry.call(boom, policy=retry.NO_RETRY, breaker=br)
    # Open: fails fast without running fn.
    with pytest.raises(retry.CircuitOpenError):
        retry.call(lambda: "never", breaker=br)
    time.sleep(0.2)
    # Half-open probe: a success closes the circuit again.
    assert retry.call(lambda: "ok", breaker=br) == "ok"
    assert retry.call(lambda: "ok", breaker=br) == "ok"


def test_pause_returns_backoff_taken():
    p = retry.RetryPolicy(backoff_base_s=0.01, jitter=0.0)
    slept = []
    took = retry.pause(p, 1, sleep=slept.append)
    assert took == 0.02 and slept == [0.02]
