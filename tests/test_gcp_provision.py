"""GCP TPU provisioning against a fake TPU REST API (offline).

The fake transport models the queuedResources/nodes state machine:
create -> WAITING -> ACTIVE (+node READY), plus injectable stockouts and
quota errors — the seam the reference tests at the codegen boundary,
here tested at the HTTP boundary."""

import re

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import gcp
from skypilot_tpu.provision.common import ProvisionConfig


class FakeTpuApi:
    def __init__(self, stockout_zones=(), quota_zones=(), ready_after=1):
        self.nodes = {}        # (zone, name) -> node dict
        self.qrs = {}          # (zone, name) -> qr dict
        self.vms = {}          # (zone, name) -> compute instance dict
        self.stockout_zones = set(stockout_zones)
        self.quota_zones = set(quota_zones)
        self.ready_after = ready_after  # GETs until node turns READY
        self.calls = []

    def __call__(self, method, url, body):
        self.calls.append((method, url))
        if "compute.googleapis.com" in url:
            return self._compute(method, url, body)
        m = re.search(r"locations/([^/]+)/(queuedResources|nodes)"
                      r"(?:/([^/:?]+))?(?::(\w+))?(?:\?(.*))?$", url)
        zone, kind, name, verb, query = m.groups()
        if query and not name:
            name = re.search(r"(?:queuedResourceId|nodeId)=([\w-]+)",
                             query).group(1)
        key = (zone, name)
        if method == "POST" and verb is None:
            if zone in self.quota_zones:
                raise exceptions.QuotaExceededError("quota exceeded for zone")
            if zone in self.stockout_zones:
                raise exceptions.CapacityError("no more capacity in zone")
            if kind == "queuedResources":
                self.qrs[key] = {"state": {"state": "WAITING"}, "body": body}
                spec = body["tpu"]["nodeSpec"][0]
                node_body = spec["node"]
                ms = spec.get("multiNodeParams")
                if ms:
                    # Multislice: the API generates {prefix}-{i} nodes.
                    for i in range(ms["nodeCount"]):
                        self.nodes[(zone, f"{ms['nodeIdPrefix']}-{i}")] = \
                            dict(node_body, state="CREATING", _gets=0)
                else:
                    self.nodes[key] = dict(node_body, state="CREATING",
                                           _gets=0)
            else:
                self.nodes[key] = dict(body, state="CREATING", _gets=0)
            return {"name": f"op-{name}"}
        if method == "GET" and kind == "nodes":
            node = self.nodes.get(key)
            if node is None:
                raise exceptions.ClusterNotUpError("not found")
            node["_gets"] += 1
            if node["state"] == "CREATING" and node["_gets"] >= self.ready_after:
                node["state"] = "READY"
                n_hosts = self._n_hosts(node["acceleratorType"])
                node["networkEndpoints"] = [
                    {"ipAddress": f"10.0.0.{i+1}",
                     "accessConfig": {"externalIp": f"34.0.0.{i+1}"}}
                    for i in range(n_hosts)]
            return {k: v for k, v in node.items() if not k.startswith("_")}
        if method == "GET" and kind == "queuedResources":
            qr = self.qrs.get(key)
            if qr is None:
                raise exceptions.ClusterNotUpError("not found")
            return qr
        if method == "POST" and verb == "stop":
            self.nodes[key]["state"] = "STOPPED"
            return {}
        if method == "POST" and verb == "start":
            self.nodes[key]["state"] = "READY"
            return {}
        if method == "DELETE":
            store = self.nodes if kind == "nodes" else self.qrs
            if key not in store:
                raise exceptions.ClusterNotUpError("not found")
            del store[key]
            return {}
        raise AssertionError(f"unhandled {method} {url}")

    def _compute(self, method, url, body):
        if "/global/firewalls" in url:
            return self._firewalls(method, url, body)
        m = re.search(r"zones/([^/]+)/instances"
                      r"(?:/([\w-]+))?(?:/(\w+))?(?:\?(.*))?$", url)
        zone, name, verb, query = m.groups()
        if method == "POST" and name is None:
            if zone in self.quota_zones:
                raise exceptions.QuotaExceededError("quota exceeded")
            if zone in self.stockout_zones:
                raise exceptions.CapacityError("no capacity")
            vm = dict(body, status="RUNNING")
            vm.setdefault("networkInterfaces", [{}])
            n = len(self.vms)
            vm["networkInterfaces"][0].setdefault("networkIP",
                                                  f"10.1.0.{n+1}")
            vm["networkInterfaces"][0].setdefault(
                "accessConfigs", [{"natIP": f"35.0.0.{n+1}"}])
            self.vms[(zone, body["name"])] = vm
            return {"name": f"op-{body['name']}"}
        if method == "GET" and query and "filter=" in query:
            cluster = re.search(r"skypilot-tpu-cluster%3D([\w-]+)",
                                query).group(1)
            items = [v for (z, n), v in self.vms.items()
                     if z == zone and
                     v.get("labels", {}).get("skypilot-tpu-cluster")
                     == cluster]
            return {"items": items}
        key = (zone, name)
        if method == "POST" and verb == "stop":
            self.vms[key]["status"] = "TERMINATED"
            return {}
        if method == "POST" and verb == "start":
            self.vms[key]["status"] = "RUNNING"
            return {}
        if method == "DELETE":
            if key not in self.vms:
                raise exceptions.ClusterNotUpError("not found")
            del self.vms[key]
            return {}
        raise AssertionError(f"unhandled compute {method} {url}")

    def _firewalls(self, method, url, body):
        if not hasattr(self, "firewalls"):
            self.firewalls = {}
        name = url.rsplit("firewalls", 1)[1].lstrip("/")
        if method == "POST":
            name = body["name"]
            if name in self.firewalls:
                err = exceptions.ResourcesUnavailableError(
                    f"firewall {name} already exists")
                err.http_code = 409
                raise err
            self.firewalls[name] = body
            return {"name": f"op-fw-{name}"}
        if method == "PATCH":
            assert name in self.firewalls, f"PATCH of missing rule {name}"
            self.firewalls[name] = body
            return {"name": f"op-fw-{name}"}
        if method == "DELETE":
            if name not in self.firewalls:
                raise exceptions.ClusterNotUpError("rule not found")
            del self.firewalls[name]
            return {}
        raise AssertionError(f"unhandled firewall {method} {url}")

    @staticmethod
    def _n_hosts(accel_type):
        gen, _, size = accel_type.partition("-")
        size = int(size)
        if gen == "v5litepod" or gen == "v6e":
            return max(1, size // 8)
        return max(1, size // 8)  # core-suffixed gens: 8 cores/host


@pytest.fixture()
def fake_api(monkeypatch):
    api = FakeTpuApi()
    gcp.set_transport(api)
    monkeypatch.setenv("GOOGLE_CLOUD_PROJECT", "test-proj")
    yield api
    gcp.set_transport(None)


def _config(accel="tpu-v5e-16", zone="us-west4-a", num_nodes=1, **kw):
    from skypilot_tpu.catalog import catalog
    info = catalog.tpu_slice_info(accel)
    return ProvisionConfig(
        cluster_name="tputest", num_nodes=num_nodes,
        hosts_per_node=info["hosts"],
        zone=zone, region=zone.rsplit("-", 1)[0], accelerator=accel,
        runtime_version="v2-alpha-tpuv5-lite", **kw)


def test_accelerator_type_mapping():
    assert gcp.to_gcp_accelerator_type("tpu-v5e-16") == "v5litepod-16"
    assert gcp.to_gcp_accelerator_type("tpu-v5p-128") == "v5p-128"
    assert gcp.to_gcp_accelerator_type("tpu-v6e-8") == "v6e-8"
    assert gcp.to_gcp_accelerator_type("tpu-v3-32") == "v3-32"


def test_v5e_goes_through_queued_resources(fake_api):
    gcp.run_instances(_config())
    assert ("us-west4-a", "tputest") in fake_api.qrs
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    assert gcp.query_instances("tputest", "us-west4-a") == "UP"


def test_v3_goes_direct_node_create(fake_api):
    gcp.run_instances(_config(accel="tpu-v3-32", zone="us-central1-a"))
    assert not fake_api.qrs
    assert ("us-central1-a", "tputest") in fake_api.nodes


def test_spot_queued_resource(fake_api):
    gcp.run_instances(_config(use_spot=True))
    qr = fake_api.qrs[("us-west4-a", "tputest")]
    assert "spot" in qr["body"]


def test_cluster_info_enumerates_slice_hosts(fake_api):
    gcp.run_instances(_config())  # v5e-16 = 2 hosts
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    info = gcp.get_cluster_info("tputest", "us-west4-a")
    assert len(info.hosts) == 2
    assert info.hosts[0].internal_ip == "10.0.0.1"
    assert info.hosts[1].external_ip == "34.0.0.2"
    assert info.hosts[1].worker_id == 1
    runners = gcp.get_command_runners(info)
    assert len(runners) == 2


def test_stockout_raises_capacity_error(fake_api):
    fake_api.stockout_zones.add("us-west4-a")
    with pytest.raises(exceptions.CapacityError):
        gcp.run_instances(_config())


def test_quota_error(fake_api):
    fake_api.quota_zones.add("us-west4-a")
    with pytest.raises(exceptions.QuotaExceededError):
        gcp.run_instances(_config())


def test_terminate_removes_node_and_qr(fake_api):
    gcp.run_instances(_config())
    gcp.terminate_instances("tputest", "us-west4-a")
    assert not fake_api.nodes and not fake_api.qrs
    assert gcp.query_instances("tputest", "us-west4-a") == "NOT_FOUND"


def test_multihost_stop_rejected(fake_api):
    gcp.run_instances(_config())
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    with pytest.raises(exceptions.ResourcesUnavailableError):
        gcp.stop_instances("tputest", "us-west4-a")


def test_failed_queued_resource_fails_over(fake_api):
    gcp.run_instances(_config())
    # Node never materializes; QR flips to FAILED.
    key = ("us-west4-a", "tputest")
    del fake_api.nodes[key]
    fake_api.qrs[key]["state"]["state"] = "FAILED"
    with pytest.raises(exceptions.CapacityError):
        gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)


def test_http_error_mapping():
    err = gcp._map_http_error(429, "RESOURCE_EXHAUSTED")
    assert isinstance(err, exceptions.CapacityError)
    err = gcp._map_http_error(403, "Quota 'TPUS' exceeded")
    assert isinstance(err, exceptions.QuotaExceededError)
    err = gcp._map_http_error(404, "nope")
    assert isinstance(err, exceptions.ClusterNotUpError)
    err = gcp._map_http_error(500, "boom")
    assert isinstance(err, exceptions.ResourcesUnavailableError)


def test_multislice_single_qr_creates_n_slices(fake_api):
    """VERDICT r1 #2: num_nodes>1 = N slices under ONE queued resource
    (atomic gang provisioning; nodes named {prefix}-{i})."""
    gcp.run_instances(_config(num_nodes=3))
    assert len(fake_api.qrs) == 1
    qr = fake_api.qrs[("us-west4-a", "tputest")]
    ms = qr["body"]["tpu"]["nodeSpec"][0]["multiNodeParams"]
    assert ms == {"nodeCount": 3, "nodeIdPrefix": "tputest"}
    assert set(fake_api.nodes) == {("us-west4-a", f"tputest-{i}")
                                   for i in range(3)}
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    assert gcp.query_instances("tputest", "us-west4-a") == "UP"


def test_multislice_host_enumeration_across_slices(fake_api):
    gcp.run_instances(_config(num_nodes=2))  # v5e-16 = 2 hosts/slice
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    info = gcp.get_cluster_info("tputest", "us-west4-a")
    assert len(info.hosts) == 4
    assert [(h.host_id, h.node_id, h.worker_id) for h in info.hosts] == [
        (0, 0, 0), (1, 0, 1), (2, 1, 0), (3, 1, 1)]
    assert info.metadata["num_slices"] == 2


def test_multislice_terminate_removes_all(fake_api):
    gcp.run_instances(_config(num_nodes=2))
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    gcp.terminate_instances("tputest", "us-west4-a")
    assert not fake_api.nodes and not fake_api.qrs
    assert gcp.query_instances("tputest", "us-west4-a") == "NOT_FOUND"


def test_multislice_partial_preemption_visible(fake_api):
    gcp.run_instances(_config(num_nodes=2))
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    del fake_api.nodes[("us-west4-a", "tputest-1")]
    assert gcp.query_instances("tputest", "us-west4-a") == "PARTIAL"


def test_multislice_stop_rejected(fake_api):
    gcp.run_instances(_config(num_nodes=2))
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    with pytest.raises(exceptions.ResourcesUnavailableError):
        gcp.stop_instances("tputest", "us-west4-a")


def test_multislice_requires_queued_resource_generation(fake_api):
    with pytest.raises(exceptions.ResourcesUnavailableError,
                       match="queued-resource"):
        gcp.run_instances(_config(accel="tpu-v3-32", zone="us-central1-a",
                                  num_nodes=2))


def _vm_config(accel=None, count=0, itype="n2-standard-4",
               zone="us-central1-a", **kw):
    return ProvisionConfig(
        cluster_name="vmtest", num_nodes=1, hosts_per_node=1,
        zone=zone, region=zone.rsplit("-", 1)[0], accelerator=accel,
        accelerator_count=count, instance_type=itype, **kw)


def test_gpu_row_provisions_compute_vm_not_tpu(fake_api):
    """VERDICT r1 #4: picking A100 on gcp must hit the Compute Engine
    API, never the TPU API."""
    gcp.run_instances(_vm_config(accel="A100", count=8,
                                 itype="a2-highgpu-8g"))
    assert not fake_api.nodes and not fake_api.qrs
    vm = fake_api.vms[("us-central1-a", "vmtest")]
    assert vm["machineType"].endswith("machineTypes/a2-highgpu-8g")
    # A2 family embeds its GPUs: no guestAccelerators attachment.
    assert "guestAccelerators" not in vm
    assert all("tpu.googleapis" not in u for _, u in fake_api.calls
               if "POST" in _)


def test_t4_attaches_guest_accelerator(fake_api):
    gcp.run_instances(_vm_config(accel="T4", count=4,
                                 itype="n1-standard-16"))
    vm = fake_api.vms[("us-central1-a", "vmtest")]
    assert vm["guestAccelerators"][0]["acceleratorCount"] == 4
    assert vm["guestAccelerators"][0]["acceleratorType"].endswith(
        "nvidia-tesla-t4")
    assert vm["scheduling"]["onHostMaintenance"] == "TERMINATE"


def test_cpu_vm_lifecycle(fake_api):
    """CPU VMs (controller hosts): create -> UP -> stop -> start ->
    terminate, all through the compute path."""
    gcp.run_instances(_vm_config())
    gcp.wait_instances("vmtest", "us-central1-a", timeout=5, poll=0.01)
    assert gcp.query_instances("vmtest", "us-central1-a") == "UP"
    info = gcp.get_cluster_info("vmtest", "us-central1-a")
    assert len(info.hosts) == 1
    assert info.hosts[0].internal_ip.startswith("10.1.0.")
    assert info.metadata.get("vm_cluster")
    gcp.stop_instances("vmtest", "us-central1-a")
    assert gcp.query_instances("vmtest", "us-central1-a") == "STOPPED"
    gcp.run_instances(_vm_config())  # resume
    assert gcp.query_instances("vmtest", "us-central1-a") == "UP"
    gcp.terminate_instances("vmtest", "us-central1-a")
    assert gcp.query_instances("vmtest", "us-central1-a") == "NOT_FOUND"


def test_gpu_launch_end_to_end_via_optimizer(fake_api, tmp_path,
                                             monkeypatch):
    """The done-when for VERDICT #4: `launch --gpus A100` provisions a
    VM through optimizer -> failover provisioner -> compute API."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    import skypilot_tpu.backend as backend_mod
    monkeypatch.setattr(backend_mod, "_setup_and_init_runtime",
                        lambda provider, cluster_name, zone, **kw: None)
    from skypilot_tpu.backend import RetryingProvisioner
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task
    t = Task(name="t", run="echo x")
    t.set_resources(Resources(accelerators="A100:8", cloud="gcp"))
    handle = RetryingProvisioner().provision(t, "vmtest")
    assert handle.provider == "gcp"
    assert any("compute.googleapis" in u for _, u in fake_api.calls)
    assert fake_api.vms


def test_end_to_end_failover_across_zones(fake_api, tmp_path, monkeypatch):
    """Full backend failover: us-west4-a stocked out -> lands elsewhere."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    import skypilot_tpu.backend as backend_mod
    monkeypatch.setattr(backend_mod, "_setup_and_init_runtime",
                        lambda provider, cluster_name, zone, **kw: None)
    from skypilot_tpu.backend import RetryingProvisioner
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task

    # Cheapest v5e zones are us-*; stock out the two cheapest.
    fake_api.stockout_zones |= {"us-central1-a", "us-east1-c", "us-east5-b",
                                "us-west4-a", "us-west4-b"}
    fake_api.ready_after = 1
    t = Task(name="t", run="echo x")
    t.set_resources(Resources(accelerators="tpu-v5e-16", cloud="gcp"))
    handle = RetryingProvisioner().provision(t, "tputest")
    assert handle.zone not in fake_api.stockout_zones
    assert handle.provider == "gcp"


def test_tpu_stop_start_dispatches_tpu_path(fake_api, tmp_path,
                                            monkeypatch):
    """Regression: start() must rebuild the FULL ProvisionConfig from
    the handle — a bare config (no accelerator) sent a stopped TPU
    node down the Compute Engine path and tried to create machineType
    'None' VMs instead of POSTing node:start."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    import skypilot_tpu.backend as backend_mod
    monkeypatch.setattr(backend_mod, "_setup_and_init_runtime",
                        lambda provider, cluster_name, zone, **kw: None)
    from skypilot_tpu.backend import RetryingProvisioner, TpuVmBackend
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task
    t = Task(name="t", run="echo x")
    # v3: single-node path supports plain node stop/start.
    t.set_resources(Resources(accelerators="tpu-v3-8", cloud="gcp",
                              zone="us-central1-a"))
    handle = RetryingProvisioner().provision(t, "tpustst")
    be = TpuVmBackend()
    be.stop(handle)
    key = ("us-central1-a", "tpustst")
    assert fake_api.nodes[key]["state"] == "STOPPED"
    n_calls = len(fake_api.calls)
    be.start("tpustst")
    assert fake_api.nodes[key]["state"] == "READY"
    # Only TPU-API traffic on restart: no compute-instance creation.
    assert not [u for _, u in fake_api.calls[n_calls:]
                if "compute.googleapis" in u and "firewalls" not in u]
    assert not fake_api.vms


# -- reservations (gcp.specific_reservations) -------------------------------

@pytest.fixture()
def reservations_config():
    from skypilot_tpu import config as config_lib
    config_lib.set_nested(("gcp", "specific_reservations"), ["res-1"])
    yield
    config_lib.set_nested(("gcp", "specific_reservations"), None)


def test_vm_create_carries_reservation_affinity(fake_api,
                                                reservations_config,
                                                monkeypatch):
    # The zone holds res-1 with free capacity for this machine type.
    monkeypatch.setattr(
        gcp, "list_reservations_available",
        lambda zone, itype=None: {"res-1": 2}
        if zone == "us-central1-a" else {})
    gcp.run_instances(_vm_config())
    vm = fake_api.vms[("us-central1-a", "vmtest")]
    aff = vm["reservationAffinity"]
    assert aff["consumeReservationType"] == "SPECIFIC_RESERVATION"
    assert aff["values"] == ["res-1"]


def test_vm_affinity_skipped_where_reservation_absent(fake_api,
                                                      reservations_config,
                                                      monkeypatch):
    """A reservation that lives in another zone (or is full) must NOT
    be named in this zone's create — the API would reject it and turn
    an advisory discount into a provisioning outage."""
    monkeypatch.setattr(gcp, "list_reservations_available",
                        lambda zone, itype=None: {"res-1": 0})
    gcp.run_instances(_vm_config())
    vm = fake_api.vms[("us-central1-a", "vmtest")]
    assert "reservationAffinity" not in vm


def test_spot_vm_never_consumes_reservation(fake_api,
                                            reservations_config):
    gcp.run_instances(_vm_config(use_spot=True))
    vm = fake_api.vms[("us-central1-a", "vmtest")]
    assert "reservationAffinity" not in vm


def test_qr_reserved_tier_has_its_own_key(fake_api,
                                          reservations_config):
    """VM reservation names must NOT force the TPU guaranteed tier (a
    project with only VM reservations would see every QR FAILED); the
    tier has its own config key."""
    gcp.run_instances(_config())
    assert "guaranteed" not in fake_api.qrs[("us-west4-a",
                                             "tputest")]["body"]
    from skypilot_tpu import config as config_lib
    config_lib.set_nested(("gcp", "use_reserved_tpu_capacity"), True)
    try:
        gcp.terminate_instances("tputest", "us-west4-a")
        gcp.run_instances(_config())
        qr = fake_api.qrs[("us-west4-a", "tputest")]
        assert qr["body"]["guaranteed"] == {"reserved": True}
    finally:
        config_lib.set_nested(("gcp", "use_reserved_tpu_capacity"), None)


def test_no_reservation_fields_without_config(fake_api):
    gcp.run_instances(_vm_config())
    vm = fake_api.vms[("us-central1-a", "vmtest")]
    assert "reservationAffinity" not in vm
    gcp.run_instances(_config())
    assert "guaranteed" not in fake_api.qrs[("us-west4-a",
                                             "tputest")]["body"]


def test_list_reservations_available_parses_and_filters():
    def transport(method, url, body):
        assert method == "GET" and url.endswith("/reservations")
        return {"items": [
            {"name": "res-1", "specificReservation": {
                "count": "4", "inUseCount": "1",
                "instanceProperties": {"machineType": "n2-standard-8"}}},
            {"name": "res-other", "specificReservation": {"count": "9"}},
        ]}

    from skypilot_tpu import config as config_lib
    config_lib.set_nested(("gcp", "specific_reservations"), ["res-1"])
    gcp.set_transport(transport)
    try:
        import os
        os.environ.setdefault("GOOGLE_CLOUD_PROJECT", "test-proj")
        # Unfiltered: 4 - 1 = 3 free; unconfigured names excluded.
        assert gcp.list_reservations_available("us-central1-a") == \
            {"res-1": 3}
        # Machine-type filter: mismatch -> empty.
        assert gcp.list_reservations_available(
            "us-central1-a", "n2-standard-8") == {"res-1": 3}
        assert gcp.list_reservations_available(
            "us-central1-a", "a2-highgpu-8g") == {}
    finally:
        gcp.set_transport(None)
        config_lib.set_nested(("gcp", "specific_reservations"), None)


# -- firewall / port exposure (VERDICT r3 #1) --------------------------------

def test_launch_with_ports_creates_firewall_rule(fake_api):
    gcp.run_instances(_config(ports=[8080, 8081]))
    rules = getattr(fake_api, "firewalls", {})
    rule = rules.get("skytpu-tputest-ports")
    assert rule, f"no firewall rule created: {rules}"
    assert rule["allowed"] == [{"IPProtocol": "tcp",
                                "ports": ["8080", "8081"]}]
    assert rule["targetTags"] == ["tputest"]
    assert rule["direction"] == "INGRESS"
    assert rule["sourceRanges"] == ["0.0.0.0/0"]
    # The TPU node carries the matching network tag from creation.
    node = fake_api.nodes[("us-west4-a", "tputest")]
    assert node["tags"] == ["tputest"]


def test_launch_without_ports_no_firewall(fake_api):
    gcp.run_instances(_config())
    assert not getattr(fake_api, "firewalls", {})


def test_ports_reopen_on_resume_updates_rule(fake_api):
    """A second run_instances (resume) with different ports converges
    the existing rule via PATCH instead of failing on the 409."""
    gcp.run_instances(_config(ports=[8080]))
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    gcp.run_instances(_config(ports=[8080, 9090]))
    rule = fake_api.firewalls["skytpu-tputest-ports"]
    assert rule["allowed"][0]["ports"] == ["8080", "9090"]
    assert any(m == "PATCH" for m, _ in fake_api.calls)


def test_terminate_cleans_up_firewall_rule(fake_api):
    gcp.run_instances(_config(ports=[8080]))
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    assert fake_api.firewalls
    gcp.terminate_instances("tputest", "us-west4-a")
    assert not fake_api.firewalls


def test_terminate_without_rule_is_clean(fake_api):
    gcp.run_instances(_config())
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    gcp.terminate_instances("tputest", "us-west4-a")  # no raise


def test_compute_vm_ports_firewall_and_tags(fake_api):
    cfg = ProvisionConfig(
        cluster_name="vmtest", num_nodes=1, hosts_per_node=1,
        zone="us-central1-a", region="us-central1",
        instance_type="n2-standard-8", ports=[3000])
    gcp.run_instances(cfg)
    vm = fake_api.vms[("us-central1-a", "vmtest")]
    assert vm["tags"] == {"items": ["vmtest"]}
    rule = fake_api.firewalls["skytpu-vmtest-ports"]
    assert rule["allowed"][0]["ports"] == ["3000"]
    assert rule["targetTags"] == ["vmtest"]


def test_provision_dispatcher_open_cleanup_ports(fake_api):
    from skypilot_tpu import provision
    gcp.run_instances(_config())
    provision.open_ports("gcp", "tputest", [8888])
    assert fake_api.firewalls["skytpu-tputest-ports"][
        "allowed"][0]["ports"] == ["8888"]
    provision.cleanup_ports("gcp", "tputest")
    assert not fake_api.firewalls


# -- custom images / TPU runtime versions (VERDICT r3 #5) --------------------

def test_custom_tpu_runtime_version_reaches_api(fake_api):
    from skypilot_tpu.catalog import catalog
    info = catalog.tpu_slice_info("tpu-v5e-16")
    gcp.run_instances(ProvisionConfig(
        cluster_name="tputest", num_nodes=1, hosts_per_node=info["hosts"],
        zone="us-west4-a", region="us-west4", accelerator="tpu-v5e-16",
        runtime_version="tpu-ubuntu2204-base"))
    node = fake_api.nodes[("us-west4-a", "tputest")]
    assert node["runtimeVersion"] == "tpu-ubuntu2204-base"


def test_custom_vm_image_reaches_api(fake_api):
    cfg = ProvisionConfig(
        cluster_name="vmimg", num_nodes=1, hosts_per_node=1,
        zone="us-central1-a", region="us-central1",
        instance_type="n2-standard-8",
        image_id="projects/my-proj/global/images/my-golden")
    gcp.run_instances(cfg)
    vm = fake_api.vms[("us-central1-a", "vmimg")]
    src = vm["disks"][0]["initializeParams"]["sourceImage"]
    assert src == "projects/my-proj/global/images/my-golden"


def test_docker_image_id_boots_stock_vm_image(fake_api):
    cfg = ProvisionConfig(
        cluster_name="vmdock", num_nodes=1, hosts_per_node=1,
        zone="us-central1-a", region="us-central1",
        instance_type="n2-standard-8", image_id="docker:myorg/img:3")
    gcp.run_instances(cfg)
    vm = fake_api.vms[("us-central1-a", "vmdock")]
    src = vm["disks"][0]["initializeParams"]["sourceImage"]
    assert src == gcp.DEFAULT_VM_IMAGE


def test_resources_yaml_runtime_version_and_accelerator_args():
    from skypilot_tpu.resources import Resources
    r = Resources.from_yaml_config(
        {"cloud": "gcp", "accelerators": "tpu-v5e-8",
         "runtime_version": "v2-custom"})
    assert r.runtime_version == "v2-custom"
    # Reference-YAML compat path.
    r2 = Resources.from_yaml_config(
        {"cloud": "gcp", "accelerators": "tpu-v5e-8",
         "accelerator_args": {"runtime_version": "v2-alpha-custom"}})
    assert r2.runtime_version == "v2-alpha-custom"
    # Default still applies when neither is given.
    r3 = Resources.from_yaml_config(
        {"cloud": "gcp", "accelerators": "tpu-v5e-8"})
    assert r3.runtime_version
    import pytest as _pytest
    from skypilot_tpu import exceptions as _exc
    with _pytest.raises(_exc.InvalidTaskError):
        Resources.from_yaml_config(
            {"accelerators": "tpu-v5e-8",
             "accelerator_args": {"tpu_vm": False}})
