"""GCP TPU provisioning against a fake TPU REST API (offline).

The fake transport models the queuedResources/nodes state machine:
create -> WAITING -> ACTIVE (+node READY), plus injectable stockouts and
quota errors — the seam the reference tests at the codegen boundary,
here tested at the HTTP boundary."""

import re

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import gcp
from skypilot_tpu.provision.common import ProvisionConfig


class FakeTpuApi:
    def __init__(self, stockout_zones=(), quota_zones=(), ready_after=1):
        self.nodes = {}        # (zone, name) -> node dict
        self.qrs = {}          # (zone, name) -> qr dict
        self.stockout_zones = set(stockout_zones)
        self.quota_zones = set(quota_zones)
        self.ready_after = ready_after  # GETs until node turns READY
        self.calls = []

    def __call__(self, method, url, body):
        self.calls.append((method, url))
        m = re.search(r"locations/([^/]+)/(queuedResources|nodes)"
                      r"(?:/([^/:?]+))?(?::(\w+))?(?:\?(.*))?$", url)
        zone, kind, name, verb, query = m.groups()
        if query and not name:
            name = re.search(r"(?:queuedResourceId|nodeId)=([\w-]+)",
                             query).group(1)
        key = (zone, name)
        if method == "POST" and verb is None:
            if zone in self.quota_zones:
                raise exceptions.QuotaExceededError("quota exceeded for zone")
            if zone in self.stockout_zones:
                raise exceptions.CapacityError("no more capacity in zone")
            if kind == "queuedResources":
                self.qrs[key] = {"state": {"state": "WAITING"}, "body": body}
                node_body = body["tpu"]["nodeSpec"][0]["node"]
                self.nodes[key] = dict(node_body, state="CREATING",
                                       _gets=0)
            else:
                self.nodes[key] = dict(body, state="CREATING", _gets=0)
            return {"name": f"op-{name}"}
        if method == "GET" and kind == "nodes":
            node = self.nodes.get(key)
            if node is None:
                raise exceptions.ClusterNotUpError("not found")
            node["_gets"] += 1
            if node["state"] == "CREATING" and node["_gets"] >= self.ready_after:
                node["state"] = "READY"
                n_hosts = self._n_hosts(node["acceleratorType"])
                node["networkEndpoints"] = [
                    {"ipAddress": f"10.0.0.{i+1}",
                     "accessConfig": {"externalIp": f"34.0.0.{i+1}"}}
                    for i in range(n_hosts)]
            return {k: v for k, v in node.items() if not k.startswith("_")}
        if method == "GET" and kind == "queuedResources":
            qr = self.qrs.get(key)
            if qr is None:
                raise exceptions.ClusterNotUpError("not found")
            return qr
        if method == "POST" and verb == "stop":
            self.nodes[key]["state"] = "STOPPED"
            return {}
        if method == "POST" and verb == "start":
            self.nodes[key]["state"] = "READY"
            return {}
        if method == "DELETE":
            store = self.nodes if kind == "nodes" else self.qrs
            if key not in store:
                raise exceptions.ClusterNotUpError("not found")
            del store[key]
            return {}
        raise AssertionError(f"unhandled {method} {url}")

    @staticmethod
    def _n_hosts(accel_type):
        gen, _, size = accel_type.partition("-")
        size = int(size)
        if gen == "v5litepod" or gen == "v6e":
            return max(1, size // 8)
        return max(1, size // 8)  # core-suffixed gens: 8 cores/host


@pytest.fixture()
def fake_api(monkeypatch):
    api = FakeTpuApi()
    gcp.set_transport(api)
    monkeypatch.setenv("GOOGLE_CLOUD_PROJECT", "test-proj")
    yield api
    gcp.set_transport(None)


def _config(accel="tpu-v5e-16", zone="us-west4-a", **kw):
    from skypilot_tpu.catalog import catalog
    info = catalog.tpu_slice_info(accel)
    return ProvisionConfig(
        cluster_name="tputest", num_nodes=1, hosts_per_node=info["hosts"],
        zone=zone, region=zone.rsplit("-", 1)[0], accelerator=accel,
        runtime_version="v2-alpha-tpuv5-lite", **kw)


def test_accelerator_type_mapping():
    assert gcp.to_gcp_accelerator_type("tpu-v5e-16") == "v5litepod-16"
    assert gcp.to_gcp_accelerator_type("tpu-v5p-128") == "v5p-128"
    assert gcp.to_gcp_accelerator_type("tpu-v6e-8") == "v6e-8"
    assert gcp.to_gcp_accelerator_type("tpu-v3-32") == "v3-32"


def test_v5e_goes_through_queued_resources(fake_api):
    gcp.run_instances(_config())
    assert ("us-west4-a", "tputest") in fake_api.qrs
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    assert gcp.query_instances("tputest", "us-west4-a") == "UP"


def test_v3_goes_direct_node_create(fake_api):
    gcp.run_instances(_config(accel="tpu-v3-32", zone="us-central1-a"))
    assert not fake_api.qrs
    assert ("us-central1-a", "tputest") in fake_api.nodes


def test_spot_queued_resource(fake_api):
    gcp.run_instances(_config(use_spot=True))
    qr = fake_api.qrs[("us-west4-a", "tputest")]
    assert "spot" in qr["body"]


def test_cluster_info_enumerates_slice_hosts(fake_api):
    gcp.run_instances(_config())  # v5e-16 = 2 hosts
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    info = gcp.get_cluster_info("tputest", "us-west4-a")
    assert len(info.hosts) == 2
    assert info.hosts[0].internal_ip == "10.0.0.1"
    assert info.hosts[1].external_ip == "34.0.0.2"
    assert info.hosts[1].worker_id == 1
    runners = gcp.get_command_runners(info)
    assert len(runners) == 2


def test_stockout_raises_capacity_error(fake_api):
    fake_api.stockout_zones.add("us-west4-a")
    with pytest.raises(exceptions.CapacityError):
        gcp.run_instances(_config())


def test_quota_error(fake_api):
    fake_api.quota_zones.add("us-west4-a")
    with pytest.raises(exceptions.QuotaExceededError):
        gcp.run_instances(_config())


def test_terminate_removes_node_and_qr(fake_api):
    gcp.run_instances(_config())
    gcp.terminate_instances("tputest", "us-west4-a")
    assert not fake_api.nodes and not fake_api.qrs
    assert gcp.query_instances("tputest", "us-west4-a") == "NOT_FOUND"


def test_multihost_stop_rejected(fake_api):
    gcp.run_instances(_config())
    gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)
    with pytest.raises(exceptions.ResourcesUnavailableError):
        gcp.stop_instances("tputest", "us-west4-a")


def test_failed_queued_resource_fails_over(fake_api):
    gcp.run_instances(_config())
    # Node never materializes; QR flips to FAILED.
    key = ("us-west4-a", "tputest")
    del fake_api.nodes[key]
    fake_api.qrs[key]["state"]["state"] = "FAILED"
    with pytest.raises(exceptions.CapacityError):
        gcp.wait_instances("tputest", "us-west4-a", timeout=5, poll=0.01)


def test_http_error_mapping():
    err = gcp._map_http_error(429, "RESOURCE_EXHAUSTED")
    assert isinstance(err, exceptions.CapacityError)
    err = gcp._map_http_error(403, "Quota 'TPUS' exceeded")
    assert isinstance(err, exceptions.QuotaExceededError)
    err = gcp._map_http_error(404, "nope")
    assert isinstance(err, exceptions.ClusterNotUpError)
    err = gcp._map_http_error(500, "boom")
    assert isinstance(err, exceptions.ResourcesUnavailableError)


def test_end_to_end_failover_across_zones(fake_api, tmp_path, monkeypatch):
    """Full backend failover: us-west4-a stocked out -> lands elsewhere."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    import skypilot_tpu.backend as backend_mod
    monkeypatch.setattr(backend_mod, "_setup_and_init_runtime",
                        lambda provider, cluster_name, zone: None)
    from skypilot_tpu.backend import RetryingProvisioner
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task

    # Cheapest v5e zones are us-*; stock out the two cheapest.
    fake_api.stockout_zones |= {"us-central1-a", "us-east1-c", "us-east5-b",
                                "us-west4-a", "us-west4-b"}
    fake_api.ready_after = 1
    t = Task(name="t", run="echo x")
    t.set_resources(Resources(accelerators="tpu-v5e-16", cloud="gcp"))
    handle = RetryingProvisioner().provision(t, "tputest")
    assert handle.zone not in fake_api.stockout_zones
    assert handle.provider == "gcp"
