"""Lint: daemon/server-side modules must use the structured event log
(``tracing.add_event``/``start_span``), not bare ``print(...)`` — a
print is invisible to `skytpu trace` and unparseable by anything.

Scope: the runtime, server, and jobs layers (the processes whose
diagnostics feed the flight recorder). CLI-facing modules are out of
scope, and a small allowlist grandfathers pre-tracing call sites that
are genuine console/log output; new files start at zero.
"""

import ast
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "skypilot_tpu")

SCOPED_DIRS = ("runtime", "server", "jobs")

# path (relative to skypilot_tpu/) -> max allowed bare print() calls.
# These predate the structured event log and are legitimate console or
# per-job-log output; do NOT add entries — record an event (optionally
# echo=True) instead.
ALLOWLIST = {
    "runtime/driver.py": 2,      # per-job driver log lines
    "runtime/hostd.py": 1,       # CLI startup error before any log
    "jobs/controller.py": 1,     # the controller's own log stream
    "jobs/core.py": 1,           # client-facing tail_logs note
}


def _bare_prints(path):
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    hits = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            hits.append(node.lineno)
    return hits


def _scoped_files():
    for d in SCOPED_DIRS:
        root = os.path.join(PKG, d)
        for dirpath, _, names in os.walk(root):
            for name in sorted(names):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def test_no_new_bare_prints_in_daemon_modules():
    violations = []
    for path in _scoped_files():
        rel = os.path.relpath(path, PKG)
        hits = _bare_prints(path)
        allowed = ALLOWLIST.get(rel, 0)
        if len(hits) > allowed:
            violations.append(f"{rel}: {len(hits)} print() at lines "
                              f"{hits} (allowed: {allowed})")
    assert not violations, (
        "bare print() in daemon/server modules — use "
        "tracing.add_event(..., echo=True) so the message reaches the "
        "structured event log:\n  " + "\n  ".join(violations))


@pytest.mark.parametrize("rel", sorted(ALLOWLIST))
def test_allowlist_entries_still_exist(rel):
    """A renamed/cleaned-up file must drop its allowlist entry, or the
    budget silently covers a future regression elsewhere."""
    assert os.path.exists(os.path.join(PKG, rel)), (
        f"{rel} gone — remove its ALLOWLIST entry")
