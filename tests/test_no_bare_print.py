"""Lint: daemon/server-side modules must use the structured event log
(``tracing.add_event``/``start_span``), not bare ``print(...)``.

Thin wrapper over the ``bare-print`` checker in
``skypilot_tpu/analysis`` (the framework this lint grew into — see
docs/analysis.md). The old fixed per-file allowlist became entries in
``lint_baseline.json`` with the same budgets; the guarantees are
unchanged:

  * new bare prints in daemon modules fail (now including ``infer/``
    and ``serve/``, which the original scope predated);
  * a grandfathered budget whose file/finding disappears fails too
    (stale-baseline detection replaces the old entries-still-exist
    test), so a budget can never silently cover a regression.
"""

import os

from skypilot_tpu import analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run():
    return analysis.run(root=REPO, checkers=["bare-print"],
                        use_cache=False)


def test_no_new_bare_prints_in_daemon_modules():
    res = _run()
    assert not res.new, (
        "bare print() in daemon/server modules — use "
        "tracing.add_event(..., echo=True) so the message reaches "
        "the structured event log:\n  "
        + "\n  ".join(f.format() for f in res.new))


def test_grandfathered_budgets_not_rotted():
    """A fixed print (or a renamed file) must drop its baseline entry,
    or the budget silently covers a future regression elsewhere."""
    res = _run()
    assert not res.stale, (
        "stale bare-print baseline entries (remove them from "
        f"lint_baseline.json): {res.stale}")
    assert not res.unjustified, (
        f"bare-print baseline entries lack justification: "
        f"{res.unjustified}")


def test_checker_still_catches_a_seeded_print():
    """The wrapper keeps the original lint's teeth: a print() in a
    scoped module is reported."""
    from skypilot_tpu.analysis.core import FileContext, get_checker
    ctx = FileContext("<fixture>", "skypilot_tpu/runtime/seeded.py",
                      source='def f():\n    print("x")\n')
    findings = get_checker("bare-print").check_file(ctx)
    assert [f.line for f in findings] == [2]
