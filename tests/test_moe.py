"""MoE model + expert parallelism tests (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import moe
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel import sharding as sh
from skypilot_tpu.train import trainer


@pytest.fixture(scope="module")
def cfg():
    return moe.CONFIGS["moe-tiny"]


@pytest.fixture(scope="module")
def params(cfg):
    return moe.init_params(jax.random.key(0), cfg)


def test_forward_shapes_and_finite(cfg, params):
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    logits, aux = jax.jit(lambda p, t: moe.forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()
    # Balanced-routing optimum is 1.0; any routing gives aux >= 1 - o(1).
    assert 0.5 < float(aux) < float(cfg.n_experts)


def test_capacity_static(cfg):
    assert moe.expert_capacity(cfg, 32) == int(
        np.ceil(1.25 * cfg.top_k * 32 / cfg.n_experts))


def test_full_capacity_routes_all_tokens(cfg, params):
    """With capacity >= S*k, dispatch keeps every (token, choice) pair:
    combine weights per token sum to 1."""
    import dataclasses
    big = dataclasses.replace(cfg, capacity_factor=float(cfg.n_experts))
    h = jax.random.normal(jax.random.key(2), (2, 16, big.d_model),
                          big.dtype)
    layer = jax.tree.map(lambda x: x[0], params["blocks"])
    out, aux = moe.moe_ffn(big, h, layer)
    assert out.shape == h.shape
    assert np.isfinite(np.asarray(out)).all()


def test_ep_sharded_matches_unsharded(cfg, params):
    """The same forward under an ep=4 mesh must match single-device."""
    tokens = jax.random.randint(jax.random.key(3), (2, 32), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    ref_logits, ref_aux = jax.jit(
        lambda p, t: moe.forward(p, t, cfg))(params, tokens)

    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(dp=2, ep=4))
    constrain = sh.make_constrain(mesh, sh.ACT_RULES)
    p_sh = sh.logical_to_sharding(moe.param_logical_axes(cfg), mesh,
                                  sh.DEFAULT_RULES)
    params_s = jax.device_put(params, p_sh)
    logits, aux = jax.jit(
        lambda p, t: moe.forward(p, t, cfg, constrain))(params_s, tokens)
    # bf16 compute: reassociation across the ep all-to-all costs ~2 ulps.
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-2, atol=6e-2)
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-3)


def test_train_step_on_ep_mesh(cfg):
    """Full train step (grad through dispatch) on dp x ep x tp mesh."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(dp=2, ep=2, tp=2))
    tc = trainer.TrainConfig(warmup_steps=1, total_steps=4)
    state = trainer.create_train_state(cfg, tc, mesh, model=moe)
    step = trainer.make_train_step(cfg, tc, mesh, model=moe)
    batch = trainer.synthetic_batch(cfg, 4, 32)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["aux_loss"]))
    assert float(metrics["grad_norm"]) > 0.0
    # Expert weights really sharded over ep.
    we = state["params"]["blocks"]["we_gate"]
    spec = we.sharding.spec
    assert "ep" in str(spec)


def test_loss_decreases(cfg):
    """A few steps on a repeated batch must reduce the loss (routing +
    experts + attention all learning together)."""
    tc = trainer.TrainConfig(learning_rate=1e-3, warmup_steps=1,
                             total_steps=20)
    state = trainer.create_train_state(cfg, tc, None, model=moe)
    step = trainer.make_train_step(cfg, tc, None, model=moe)
    batch = trainer.synthetic_batch(cfg, 2, 32)
    first = None
    for _ in range(8):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["xent"])
    assert float(metrics["xent"]) < first
