"""SkyServe e2e on the local fake cloud: replicas are real HTTP servers,
the LB is a real proxy, probes are real GETs.

Reference pattern: tests/skyserve/ fixtures driven by smoke tests —
here fully offline."""

import json
import time
import urllib.request

import pytest

from skypilot_tpu.serve import core as serve_core
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus, ServiceStatus
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.task import Task

# A replica: tiny HTTP server that reports its replica id.
REPLICA_RUN = (
    "python3 -c \""
    "import http.server, os, socketserver\n"
    "rid = os.environ.get('SKYTPU_REPLICA_ID', '?')\n"
    "class H(http.server.BaseHTTPRequestHandler):\n"
    "    def do_GET(self):\n"
    "        body = ('replica-' + rid).encode()\n"
    "        self.send_response(200)\n"
    "        self.send_header('Content-Length', str(len(body)))\n"
    "        self.end_headers()\n"
    "        self.wfile.write(body)\n"
    "    def log_message(self, *a): pass\n"
    "socketserver.TCPServer.allow_reuse_address = True\n"
    "http.server.ThreadingHTTPServer(('127.0.0.1', "
    "int(os.environ['SKYTPU_REPLICA_PORT'])), H).serve_forever()\""
)


@pytest.fixture(autouse=True)
def sky_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    monkeypatch.setenv("SKYTPU_LOCAL_CLUSTERS_ROOT", str(tmp_path / "cloud"))
    monkeypatch.setenv("SKYTPU_SERVE_POLL", "0.3")


def _ready_urls(service):
    """Replica URLs as the controller cluster reports them (the serve
    state DB lives on the controller head, reached via RPC)."""
    rows = serve_core.status(service)
    if not rows:
        return []
    return [r["url"] for r in rows[0]["replicas"]
            if r["status"] == ReplicaStatus.READY and r.get("url")]


def _replicas(service):
    rows = serve_core.status(service)
    return rows[0]["replicas"] if rows else []


def _service_task(replicas=2, qps=None, port=18200):
    cfg = {
        "name": "svc",
        "resources": {"cloud": "local"},
        "run": REPLICA_RUN,
        "service": {
            "readiness_probe": {"path": "/", "initial_delay_seconds": 15},
            "port": port,
        },
    }
    if qps is not None:
        cfg["service"]["replica_policy"] = {
            "min_replicas": 1, "max_replicas": 3,
            "target_qps_per_replica": qps,
            "upscale_delay_seconds": 1, "downscale_delay_seconds": 2,
        }
    else:
        cfg["service"]["replicas"] = replicas
    return Task.from_yaml_config(cfg)


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


def test_service_spec_yaml():
    spec = SkyServiceSpec.from_yaml_config({
        "readiness_probe": "/health", "replicas": 3, "port": 9000})
    assert spec.readiness_path == "/health"
    assert spec.min_replicas == spec.max_replicas == 3
    assert spec.replica_port == 9000
    spec2 = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert spec2.min_replicas == 3


def test_serve_up_ready_balance_down():
    info = serve_core.up(_service_task(replicas=2), "websvc")
    try:
        serve_core.wait_ready("websvc", timeout=300)
        # Wait until both replicas are READY (LB retries mask one).
        # Generous: under full-suite load, two RPC-launched replica
        # clusters + the controller compete with other tests' process
        # storms.
        deadline = time.time() + 420
        while time.time() < deadline:
            ready = _ready_urls("websvc")
            if len(ready) == 2:
                break
            time.sleep(0.3)
        assert len(ready) == 2

        # The LB must reach both replicas (least-load alternates).
        seen = set()
        for _ in range(10):
            status, body = _get(info["endpoint"] + "/")
            assert status == 200
            seen.add(body)
        assert seen == {"replica-1", "replica-2"}, seen
    finally:
        serve_core.down("websvc")
    assert serve_core.status("websvc") == []
    # Replica clusters cleaned up (cloud ground truth).
    from skypilot_tpu.provision import local as lp
    for rid in (1, 2):
        assert lp.query_instances(f"sky-serve-websvc-{rid}",
                                  "local") == "NOT_FOUND"


# A replica that dribbles 5 chunks ~0.3s apart over chunked encoding:
# only a streaming LB delivers the first chunk before the last exists.
STREAM_RUN = (
    "python3 -c \""
    "import http.server, os, time\n"
    "class H(http.server.BaseHTTPRequestHandler):\n"
    "    protocol_version = 'HTTP/1.1'\n"
    "    def do_GET(self):\n"
    "        self.send_response(200)\n"
    "        self.send_header('Transfer-Encoding', 'chunked')\n"
    "        self.end_headers()\n"
    "        for i in range(5):\n"
    "            data = ('tick%d' % i).encode()\n"
    "            self.wfile.write(('%x' % len(data)).encode()"
    " + b'\\r\\n' + data + b'\\r\\n')\n"
    "            self.wfile.flush()\n"
    "            time.sleep(0.3)\n"
    "        self.wfile.write(b'0\\r\\n\\r\\n')\n"
    "    def log_message(self, *a): pass\n"
    "http.server.ThreadingHTTPServer(('127.0.0.1', "
    "int(os.environ['SKYTPU_REPLICA_PORT'])), H).serve_forever()\""
)


def test_streaming_through_lb():
    """First chunk must reach the client through the LB while the
    replica is still producing — the LB proxies chunk-by-chunk instead
    of buffering whole responses (the JetStream-style TTFT path)."""
    cfg = _service_task(replicas=1, port=18270).to_yaml_config()
    cfg["run"] = STREAM_RUN
    info = serve_core.up(Task.from_yaml_config(cfg), "streamsvc")
    try:
        serve_core.wait_ready("streamsvc", timeout=300)
        times = []
        with urllib.request.urlopen(info["endpoint"] + "/",
                                    timeout=60) as r:
            assert r.headers.get("Transfer-Encoding") == "chunked"
            while True:
                piece = r.read1(65536)
                if not piece:
                    break
                times.append(time.time())
        # All 5 ticks arrived, spread over the replica's ~1.2s dribble —
        # a buffering LB would deliver everything in one instant burst.
        assert len(times) >= 3
        assert times[-1] - times[0] > 0.5
    finally:
        serve_core.down("streamsvc")


def test_tls_termination(tmp_path):
    """LB terminates TLS: https endpoint serves, plaintext is refused.
    Reference parity: sky/serve/service_spec.py tls fields."""
    import ssl
    import subprocess
    key, cert = tmp_path / "key.pem", tmp_path / "cert.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost"], check=True, capture_output=True)
    cfg = _service_task(replicas=1, port=18280).to_yaml_config()
    cfg["service"]["tls"] = {"keyfile": str(key), "certfile": str(cert)}
    info = serve_core.up(Task.from_yaml_config(cfg), "tlssvc")
    try:
        assert info["endpoint"].startswith("https://")
        serve_core.wait_ready("tlssvc", timeout=300)
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        deadline = time.time() + 120
        body = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(info["endpoint"] + "/",
                                            timeout=10,
                                            context=ctx) as r:
                    body = r.read().decode()
                    break
            except Exception:
                time.sleep(0.5)
        assert body == "replica-1", body
        # Plaintext on the TLS port is refused.
        plain = info["endpoint"].replace("https://", "http://")
        with pytest.raises(Exception):
            urllib.request.urlopen(plain + "/", timeout=10)
    finally:
        serve_core.down("tlssvc")


def test_tls_stalled_client_does_not_block_lb(tmp_path, monkeypatch):
    """Per-connection deferred handshake: a client that connects and
    sends nothing must not stall the accept loop (one-connection DoS).
    Unit-level — LB serving threads directly, no clusters."""
    import socket
    import ssl
    import subprocess
    import threading

    from skypilot_tpu.serve import load_balancer, serve_state
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "h"))
    key, cert = tmp_path / "key.pem", tmp_path / "cert.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost"], check=True, capture_output=True)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    serve_state.add_service("tlsu", {}, {}, port)
    t = threading.Thread(
        target=load_balancer.serve,
        kwargs=dict(service="tlsu", port=port, certfile=str(cert),
                    keyfile=str(key)),
        daemon=True)
    t.start()
    time.sleep(0.5)
    # The silent client: TCP connect, never a TLS hello.
    stalled = socket.create_connection(("127.0.0.1", port))
    try:
        time.sleep(0.3)
        # A real TLS request still gets through (503: no replicas —
        # but the handshake + HTTP round trip completed).
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"https://127.0.0.1:{port}/",
                                   timeout=10, context=ctx)
        assert ei.value.code == 503
    finally:
        stalled.close()


def test_replica_failure_recovery():
    info = serve_core.up(_service_task(replicas=1), "failsvc")
    try:
        serve_core.wait_ready("failsvc", timeout=300)
        # Kill the replica's cluster out-of-band (slice preemption).
        reps = _replicas("failsvc")
        from skypilot_tpu.provision import local as lp
        lp.terminate_instances(reps[0]["cluster_name"], "local")
        # Controller must replace it and return to READY.
        time.sleep(1)
        serve_core.wait_ready("failsvc", timeout=300)
        new_reps = [r for r in _replicas("failsvc")
                    if r["status"] == ReplicaStatus.READY]
        assert new_reps
        assert new_reps[0]["replica_id"] != reps[0]["replica_id"]
        status, body = _get(info["endpoint"] + "/")
        assert status == 200
    finally:
        serve_core.down("failsvc")


def test_autoscaler_scales_up_under_load():
    info = serve_core.up(_service_task(qps=2.0), "autosvc")
    try:
        serve_core.wait_ready("autosvc", timeout=300)
        assert len(_ready_urls("autosvc")) == 1
        # Push ~20 qps for a few seconds -> desired replicas hits max 3.
        deadline = time.time() + 45
        scaled = False
        while time.time() < deadline:
            for _ in range(10):
                try:
                    _get(info["endpoint"] + "/", timeout=10)
                except Exception:
                    pass
            if len(_ready_urls("autosvc")) >= 2:
                scaled = True
                break
            time.sleep(0.3)
        assert scaled, "autoscaler never scaled up"
    finally:
        serve_core.down("autosvc")


def test_serve_survives_client_death(tmp_path, monkeypatch):
    """VERDICT r1 #3 done-when: the controller runs as a cluster job;
    the endpoint is the controller cluster head's address; the service
    keeps serving after the launching client is erased."""
    info = serve_core.up(_service_task(replicas=1, port=18300), "deathsvc")
    try:
        serve_core.wait_ready("deathsvc", timeout=300)
        # Endpoint host is the controller cluster head's address, built
        # from cluster info — not a hardcoded loopback default.
        from skypilot_tpu import provision
        from skypilot_tpu.controller_utils import SERVE_CONTROLLER_CLUSTER
        from skypilot_tpu.provision import local as lp
        head = lp.get_cluster_info(SERVE_CONTROLLER_CLUSTER,
                                   "local").head
        assert info["endpoint"] == \
            f"http://{head.internal_ip}:{info['lb_port']}"

        # Client dies: its entire home (state DB, logs) is erased.
        import shutil
        shutil.rmtree(tmp_path / "skyhome", ignore_errors=True)
        monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "client2"))

        # The service keeps serving...
        status, body = _get(info["endpoint"] + "/")
        assert status == 200 and body.startswith("replica-")
        # ...and a fresh client can reach its state via the controller
        # cluster alone.
        from skypilot_tpu.runtime.rpc_client import ClusterRpc
        rpc = ClusterRpc(
            provision.get_command_runners(
                lp.get_cluster_info(SERVE_CONTROLLER_CLUSTER, "local"))[0],
            SERVE_CONTROLLER_CLUSTER)
        rows = rpc.call("serve_status", service_name="deathsvc")
        assert rows and rows[0]["status"] == "READY"
        # The fresh client tears the service down through the RPC alone
        # (no client-side record needed).
        rpc.call("serve_down", service_name="deathsvc")
        deadline = time.time() + 120
        while time.time() < deadline:
            rows = rpc.call("serve_status", service_name="deathsvc")
            if not rows or rows[0]["status"] in ("SHUTDOWN", "FAILED"):
                break
            time.sleep(0.3)
        rpc.call("serve_remove", service_name="deathsvc")
        assert rpc.call("serve_status", service_name="deathsvc") == []
    finally:
        try:
            serve_core.down("deathsvc", purge=True)
        except Exception:  # noqa: BLE001 — already removed via RPC
            pass


def test_rolling_update_zero_downtime():
    """VERDICT r1 #9 done-when: `serve update` drains old replicas only
    after new ones are READY; a request loop across the update sees zero
    503s."""
    task_v1 = _service_task(replicas=1, port=18400)
    task_v1.update_envs({"SKYTPU_MARKER": "v1"})
    info = serve_core.up(task_v1, "rollsvc")
    try:
        serve_core.wait_ready("rollsvc", timeout=300)

        task_v2 = _service_task(replicas=1, port=18400)
        task_v2.update_envs({"SKYTPU_MARKER": "v2"})
        r = serve_core.update(task_v2, "rollsvc")
        assert r["version"] == 2

        saw_v2_replica = False
        deadline = time.time() + 240
        while time.time() < deadline:
            # Every request during the rollover must succeed.
            status, body = _get(info["endpoint"] + "/", timeout=30)
            assert status == 200, f"got {status} mid-update"
            reps = _replicas("rollsvc")
            v2_ready = [x for x in reps
                        if x.get("version") == 2
                        and x["status"] == ReplicaStatus.READY]
            v1_left = [x for x in reps if x.get("version") in (None, 1)]
            if v2_ready and not v1_left:
                saw_v2_replica = True
                break
            time.sleep(0.3)
        assert saw_v2_replica, f"rollover never completed: " \
            f"{_replicas('rollsvc')}"
        # Old replica fully drained; new one serves.
        status, _ = _get(info["endpoint"] + "/")
        assert status == 200
    finally:
        serve_core.down("rollsvc")


def test_lb_503_when_no_replicas():
    info = serve_core.up(_service_task(replicas=1), "coldsvc")
    try:
        # Immediately query before any replica is ready.
        try:
            status, body = _get(info["endpoint"] + "/", timeout=15)
            assert status == 503 or status == 200
        except urllib.error.HTTPError as e:
            assert e.code == 503
        except Exception:
            pass  # LB itself may not be up yet; that's fine
    finally:
        serve_core.down("coldsvc")
