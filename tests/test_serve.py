"""SkyServe e2e on the local fake cloud: replicas are real HTTP servers,
the LB is a real proxy, probes are real GETs.

Reference pattern: tests/skyserve/ fixtures driven by smoke tests —
here fully offline."""

import json
import time
import urllib.request

import pytest

from skypilot_tpu.serve import core as serve_core
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus, ServiceStatus
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.task import Task

# A replica: tiny HTTP server that reports its replica id.
REPLICA_RUN = (
    "python3 -c \""
    "import http.server, os, socketserver\n"
    "rid = os.environ.get('SKYTPU_REPLICA_ID', '?')\n"
    "class H(http.server.BaseHTTPRequestHandler):\n"
    "    def do_GET(self):\n"
    "        body = ('replica-' + rid).encode()\n"
    "        self.send_response(200)\n"
    "        self.send_header('Content-Length', str(len(body)))\n"
    "        self.end_headers()\n"
    "        self.wfile.write(body)\n"
    "    def log_message(self, *a): pass\n"
    "socketserver.TCPServer.allow_reuse_address = True\n"
    "http.server.ThreadingHTTPServer(('127.0.0.1', "
    "int(os.environ['SKYTPU_REPLICA_PORT'])), H).serve_forever()\""
)


@pytest.fixture(autouse=True)
def sky_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    monkeypatch.setenv("SKYTPU_SERVE_POLL", "0.3")


def _service_task(replicas=2, qps=None):
    cfg = {
        "name": "svc",
        "resources": {"cloud": "local"},
        "run": REPLICA_RUN,
        "service": {
            "readiness_probe": {"path": "/", "initial_delay_seconds": 15},
            "port": 18200,
        },
    }
    if qps is not None:
        cfg["service"]["replica_policy"] = {
            "min_replicas": 1, "max_replicas": 3,
            "target_qps_per_replica": qps,
            "upscale_delay_seconds": 1, "downscale_delay_seconds": 2,
        }
    else:
        cfg["service"]["replicas"] = replicas
    return Task.from_yaml_config(cfg)


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


def test_service_spec_yaml():
    spec = SkyServiceSpec.from_yaml_config({
        "readiness_probe": "/health", "replicas": 3, "port": 9000})
    assert spec.readiness_path == "/health"
    assert spec.min_replicas == spec.max_replicas == 3
    assert spec.replica_port == 9000
    spec2 = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert spec2.min_replicas == 3


def test_serve_up_ready_balance_down():
    info = serve_core.up(_service_task(replicas=2), "websvc")
    try:
        serve_core.wait_ready("websvc", timeout=300)
        # Wait until both replicas are READY (LB retries mask one).
        deadline = time.time() + 240
        while time.time() < deadline:
            ready = serve_state.ready_urls("websvc")
            if len(ready) == 2:
                break
            time.sleep(0.3)
        assert len(ready) == 2

        # The LB must reach both replicas (least-load alternates).
        seen = set()
        for _ in range(10):
            status, body = _get(info["endpoint"] + "/")
            assert status == 200
            seen.add(body)
        assert seen == {"replica-1", "replica-2"}, seen
    finally:
        serve_core.down("websvc")
    assert serve_state.get_service("websvc") is None
    # Replica clusters cleaned up.
    from skypilot_tpu import state as cluster_state
    assert all(not c["name"].startswith("sky-serve-websvc")
               for c in cluster_state.list_clusters())


def test_replica_failure_recovery():
    info = serve_core.up(_service_task(replicas=1), "failsvc")
    try:
        serve_core.wait_ready("failsvc", timeout=300)
        # Kill the replica's cluster out-of-band (slice preemption).
        reps = serve_state.list_replicas("failsvc")
        from skypilot_tpu.provision import local as lp
        lp.terminate_instances(reps[0]["cluster_name"], "local")
        # Controller must replace it and return to READY.
        time.sleep(1)
        serve_core.wait_ready("failsvc", timeout=300)
        new_reps = [r for r in serve_state.list_replicas("failsvc")
                    if r["status"] == ReplicaStatus.READY]
        assert new_reps
        assert new_reps[0]["replica_id"] != reps[0]["replica_id"]
        status, body = _get(info["endpoint"] + "/")
        assert status == 200
    finally:
        serve_core.down("failsvc")


def test_autoscaler_scales_up_under_load():
    info = serve_core.up(_service_task(qps=2.0), "autosvc")
    try:
        serve_core.wait_ready("autosvc", timeout=300)
        assert len(serve_state.ready_urls("autosvc")) == 1
        # Push ~20 qps for a few seconds -> desired replicas hits max 3.
        deadline = time.time() + 45
        scaled = False
        while time.time() < deadline:
            for _ in range(10):
                try:
                    _get(info["endpoint"] + "/", timeout=10)
                except Exception:
                    pass
            if len(serve_state.ready_urls("autosvc")) >= 2:
                scaled = True
                break
            time.sleep(0.3)
        assert scaled, "autoscaler never scaled up"
    finally:
        serve_core.down("autosvc")


def test_lb_503_when_no_replicas():
    info = serve_core.up(_service_task(replicas=1), "coldsvc")
    try:
        # Immediately query before any replica is ready.
        try:
            status, body = _get(info["endpoint"] + "/", timeout=15)
            assert status == 503 or status == 200
        except urllib.error.HTTPError as e:
            assert e.code == 503
        except Exception:
            pass  # LB itself may not be up yet; that's fine
    finally:
        serve_core.down("coldsvc")
