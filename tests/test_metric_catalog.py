"""Lint: every metric the framework registers must carry the
``skytpu_`` prefix AND appear in the docs/observability.md catalog.

Thin wrapper over the ``metric-catalog`` checker in
``skypilot_tpu/analysis`` (see docs/analysis.md). Guarantees are
unchanged from the original standalone lint: literal declarations
through the module-level sugar are scanned tree-wide, synthesized
fleet families are held to the same contract, and a scan that
suddenly sees almost no declarations fails rather than passing
vacuously (the ``scan-degenerate`` rule).
"""

import os

from skypilot_tpu import analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_metric_names_prefixed_and_documented():
    res = analysis.run(root=REPO, checkers=["metric-catalog"],
                       use_cache=False)
    assert not res.new, (
        "metric catalog drift (prefix or docs/observability.md "
        "row):\n  " + "\n  ".join(f.format() for f in res.new))
    assert not res.stale and not res.unjustified, (
        f"rotted metric-catalog baseline entries: "
        f"stale={res.stale} unjustified={res.unjustified}")


def test_scan_sees_the_instrumented_tree():
    """The degenerate-scan guard is a *finding*, so the gate itself
    notices if a refactor breaks the declaration idiom; double-check
    the mechanism here."""
    from skypilot_tpu.analysis.core import FileContext, get_checker
    checker = get_checker("metric-catalog")
    ctx = FileContext("<fixture>", "skypilot_tpu/empty.py",
                      source="x = 1\n")
    findings = checker.check_project([ctx], REPO)
    assert any(f.rule == "scan-degenerate" for f in findings)
