"""Lint: every metric the framework registers must carry the
``skytpu_`` prefix AND appear in the docs/observability.md catalog —
drift between the code's registry and the operator-facing catalog
fails tier-1 (same style as test_no_bare_print.py).

Scope: literal-name declarations through the module-level sugar
(``metrics.counter/gauge/histogram(...)`` and the ``obs_metrics`` /
``metrics_lib`` aliases) anywhere under skypilot_tpu/. Dynamic names
and per-test registries are out of scope by construction.
"""

import ast
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "skypilot_tpu")
DOC = os.path.join(REPO, "docs", "observability.md")

_FACTORY_ATTRS = {"counter", "gauge", "histogram"}
_RECEIVERS = {"metrics", "obs_metrics", "metrics_lib"}

# The federation tier synthesizes these family names at render time
# (no registry declaration to scan) — hold them to the same contract.
_SYNTHESIZED = {"skytpu_fleet_scrape_up", "skytpu_fleet_merge_errors"}


def _declared_metrics():
    for dirpath, _, names in os.walk(PKG):
        if "__pycache__" in dirpath:
            continue
        for fname in sorted(names):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, PKG)
            if rel == os.path.join("observability", "metrics.py"):
                continue   # the factories themselves
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _FACTORY_ATTRS
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in _RECEIVERS
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                yield rel, node.lineno, node.args[0].value


def test_metric_names_prefixed_and_documented():
    with open(DOC, encoding="utf-8") as f:
        doc = f.read()
    declared = list(_declared_metrics())
    # Sanity: the scan must actually see the instrumented tree — a
    # refactor that silently breaks it would otherwise pass vacuously.
    assert len(declared) >= 30, (
        f"metric declaration scan found only {len(declared)} sites — "
        f"did the declaration idiom change?")
    bad_prefix, undocumented = [], []
    for rel, lineno, name in declared:
        if not name.startswith("skytpu_"):
            bad_prefix.append(f"{rel}:{lineno}: {name}")
        if name not in doc:
            undocumented.append(f"{rel}:{lineno}: {name}")
    for name in sorted(_SYNTHESIZED):
        if name not in doc:
            undocumented.append(f"(synthesized): {name}")
    assert not bad_prefix, (
        "metric names must carry the skytpu_ prefix:\n  "
        + "\n  ".join(bad_prefix))
    assert not undocumented, (
        "metrics missing from the docs/observability.md catalog "
        "(document them or the fleet dashboard lies by omission):\n  "
        + "\n  ".join(undocumented))
