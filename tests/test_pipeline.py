"""Pipeline parallelism: parity with the unpipelined stack + pp-mesh step."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel import pipeline as pl
from skypilot_tpu.parallel import sharding as sh
from skypilot_tpu.train import trainer


@pytest.fixture(scope="module")
def cfg():
    return pl.CONFIGS["pp-tiny"]


def test_layers_divisible_check():
    with pytest.raises(ValueError):
        pl.PipelineConfig(n_layers=5, n_stages=2)


def test_pipelined_matches_sequential(cfg):
    """Pipelined forward == plain llama forward on the same weights."""
    llama_cfg = llama.LlamaConfig(**{
        f.name: getattr(cfg, f.name)
        for f in dataclasses.fields(llama.LlamaConfig)})
    flat = llama.init_params(jax.random.key(0), llama_cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    ref = jax.jit(lambda p, t: llama.forward(p, t, llama_cfg))(flat, tokens)

    staged = dict(flat)
    staged["blocks"] = pl._to_stages(flat["blocks"], cfg.n_stages)
    got = jax.jit(lambda p, t: pl.forward(p, t, cfg))(staged, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=6e-2)


def test_param_axes_match_shapes(cfg):
    params = pl.init_params(jax.random.key(0), cfg)
    axes = pl.param_logical_axes(cfg)
    for p, a in zip(jax.tree.leaves(params),
                    jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))):
        assert p.ndim == len(a)
    assert params["blocks"]["wq"].shape[:2] == (cfg.n_stages,
                                                cfg.layers_per_stage)


def test_train_step_on_pp_mesh(cfg):
    """Full train step over a pp=2 x fsdp=2 x tp=2 mesh."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(pp=2, fsdp=2, tp=2))
    tc = trainer.TrainConfig(warmup_steps=1, total_steps=4)
    state = trainer.create_train_state(cfg, tc, mesh, model=pl)
    step = trainer.make_train_step(cfg, tc, mesh, model=pl)
    batch = trainer.synthetic_batch(cfg, cfg.n_microbatches * 2, 32)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    # Stage dim really sharded over pp.
    wq = state["params"]["blocks"]["wq"]
    assert "pp" in str(wq.sharding.spec)


def test_pp_sharded_loss_matches_unsharded(cfg):
    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(pp=2, dp=2, tp=2))
    batch = trainer.synthetic_batch(cfg, cfg.n_microbatches, 32, seed=5)
    params = pl.init_params(jax.random.key(0), cfg)
    ref_loss, _ = jax.jit(
        lambda p, b: pl.loss_fn(p, b, cfg))(params, batch)

    p_sh = sh.logical_to_sharding(pl.param_logical_axes(cfg), mesh,
                                  sh.DEFAULT_RULES)
    params_s = jax.device_put(params, p_sh)
    constrain = sh.make_constrain(mesh, sh.ACT_RULES)
    loss, _ = jax.jit(
        lambda p, b: pl.loss_fn(p, b, cfg, constrain))(params_s, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-2)


# -- 1F1B streaming schedule ------------------------------------------------

def test_1f1b_loss_matches_gpipe(cfg):
    """The streaming (1f1b) schedule computes the same loss AND
    gradients as GPipe — only the memory shape differs."""
    cfg1 = dataclasses.replace(cfg, schedule="1f1b")
    params = pl.init_params(jax.random.key(0), cfg)
    batch = trainer.synthetic_batch(cfg, cfg.n_microbatches * 2, 32,
                                    seed=3)
    gl, gm = jax.jit(lambda p, b: pl.loss_fn(p, b, cfg))(params, batch)
    sl, sm = jax.jit(lambda p, b: pl.loss_fn(p, b, cfg1))(params, batch)
    np.testing.assert_allclose(float(sl), float(gl), rtol=2e-2)
    assert float(sm["tokens"]) == float(gm["tokens"])


def test_1f1b_grads_match_gpipe(cfg):
    cfg1 = dataclasses.replace(cfg, schedule="1f1b")
    params = pl.init_params(jax.random.key(0), cfg)
    batch = trainer.synthetic_batch(cfg, cfg.n_microbatches * 2, 32,
                                    seed=3)
    g_grad = jax.jit(jax.grad(
        lambda p, b: pl.loss_fn(p, b, cfg)[0]))(params, batch)
    s_grad = jax.jit(jax.grad(
        lambda p, b: pl.loss_fn(p, b, cfg1)[0]))(params, batch)
    for a, b_ in zip(jax.tree.leaves(g_grad), jax.tree.leaves(s_grad)):
        np.testing.assert_allclose(np.asarray(b_, np.float32),
                                   np.asarray(a, np.float32),
                                   rtol=5e-2, atol=3e-2)


def test_1f1b_memory_flat_in_microbatches(cfg):
    """The reason 1f1b exists: GPipe's buffered outputs grow O(M) while
    the streaming schedule's temp memory stays flat — which is what
    lets M rise until the (S-1)/(M+S-1) bubble vanishes. Checked via
    XLA's own compiled memory analysis (no device execution needed)."""
    def temp_bytes(schedule, m):
        c = dataclasses.replace(cfg, schedule=schedule, n_microbatches=m)
        params = jax.eval_shape(lambda k: pl.init_params(k, c),
                                jax.random.key(0))
        batch = {"tokens": jax.ShapeDtypeStruct((m * 2, 128), jnp.int32),
                 "mask": None, "segment_ids": None}
        lowered = jax.jit(
            lambda p, b: pl.loss_fn(p, b, c)[0]).lower(params, batch)
        return lowered.compile().memory_analysis().temp_size_in_bytes

    g4, g16 = temp_bytes("gpipe", 4), temp_bytes("gpipe", 16)
    s4, s16 = temp_bytes("1f1b", 4), temp_bytes("1f1b", 16)
    # GPipe: 4x the microbatches noticeably grows temp memory (output
    # buffer is [M, b, S, D]); 1f1b: flat (same fixed batch size).
    assert s16 <= s4 * 1.3, (s4, s16)
    assert g16 > s16, (g16, s16)


def test_1f1b_on_pp_mesh(cfg):
    cfg1 = dataclasses.replace(cfg, schedule="1f1b")
    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(pp=2, fsdp=2, tp=2))
    tc = trainer.TrainConfig(warmup_steps=1, total_steps=4)
    state = trainer.create_train_state(cfg1, tc, mesh, model=pl)
    step = trainer.make_train_step(cfg1, tc, mesh, model=pl)
    batch = trainer.synthetic_batch(cfg1, cfg1.n_microbatches * 2, 32)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
