"""Pipeline parallelism: parity with the unpipelined stack + pp-mesh step."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel import pipeline as pl
from skypilot_tpu.parallel import sharding as sh
from skypilot_tpu.train import trainer


@pytest.fixture(scope="module")
def cfg():
    return pl.CONFIGS["pp-tiny"]


def test_layers_divisible_check():
    with pytest.raises(ValueError):
        pl.PipelineConfig(n_layers=5, n_stages=2)


def test_pipelined_matches_sequential(cfg):
    """Pipelined forward == plain llama forward on the same weights."""
    llama_cfg = llama.LlamaConfig(**{
        f.name: getattr(cfg, f.name)
        for f in dataclasses.fields(llama.LlamaConfig)})
    flat = llama.init_params(jax.random.key(0), llama_cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    ref = jax.jit(lambda p, t: llama.forward(p, t, llama_cfg))(flat, tokens)

    staged = dict(flat)
    staged["blocks"] = pl._to_stages(flat["blocks"], cfg.n_stages)
    got = jax.jit(lambda p, t: pl.forward(p, t, cfg))(staged, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=6e-2)


def test_param_axes_match_shapes(cfg):
    params = pl.init_params(jax.random.key(0), cfg)
    axes = pl.param_logical_axes(cfg)
    for p, a in zip(jax.tree.leaves(params),
                    jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))):
        assert p.ndim == len(a)
    assert params["blocks"]["wq"].shape[:2] == (cfg.n_stages,
                                                cfg.layers_per_stage)


def test_train_step_on_pp_mesh(cfg):
    """Full train step over a pp=2 x fsdp=2 x tp=2 mesh."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(pp=2, fsdp=2, tp=2))
    tc = trainer.TrainConfig(warmup_steps=1, total_steps=4)
    state = trainer.create_train_state(cfg, tc, mesh, model=pl)
    step = trainer.make_train_step(cfg, tc, mesh, model=pl)
    batch = trainer.synthetic_batch(cfg, cfg.n_microbatches * 2, 32)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    # Stage dim really sharded over pp.
    wq = state["params"]["blocks"]["wq"]
    assert "pp" in str(wq.sharding.spec)


def test_pp_sharded_loss_matches_unsharded(cfg):
    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(pp=2, dp=2, tp=2))
    batch = trainer.synthetic_batch(cfg, cfg.n_microbatches, 32, seed=5)
    params = pl.init_params(jax.random.key(0), cfg)
    ref_loss, _ = jax.jit(
        lambda p, b: pl.loss_fn(p, b, cfg))(params, batch)

    p_sh = sh.logical_to_sharding(pl.param_logical_axes(cfg), mesh,
                                  sh.DEFAULT_RULES)
    params_s = jax.device_put(params, p_sh)
    constrain = sh.make_constrain(mesh, sh.ACT_RULES)
    loss, _ = jax.jit(
        lambda p, b: pl.loss_fn(p, b, cfg, constrain))(params_s, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-2)
