"""Flagship pipeline recipe e2e (llm/pipeline-qlora-serve.yaml chain,
scaled to the local fake cloud + tiny model).

One managed job, four sequential steps on their own clusters, with the
artifact directory as the inter-step contract (the YAML's bucket
mount, here a shared directory): corpus prep (real packer CLI) ->
train with checkpoints -> eval gate (perplexity JSON, chain stops if
the gate fails) -> deploy check (restore the checkpoint into the
inference engine and generate). Slow profile: four real clusters +
a training run.
"""

import json
import os
import time

import pytest

from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task


@pytest.fixture(autouse=True)
def sky_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    monkeypatch.setenv("SKYTPU_LOCAL_CLUSTERS_ROOT",
                       str(tmp_path / "cloud"))
    monkeypatch.setenv("SKYTPU_JOBS_POLL", "0.2")


def _step(name, run, artifacts):
    t = Task(name=name, run=run, envs={"ARTIFACTS": artifacts})
    t.set_resources(Resources(cloud="local"))
    return t


@pytest.mark.slow
def test_pipeline_prep_train_eval_deploy(tmp_path):
    artifacts = str(tmp_path / "artifacts")
    os.makedirs(artifacts)
    steps = [
        _step("prep",
              "python -m skypilot_tpu.data.prep_corpus "
              "--input synthetic:40 --vocab-size 512 "
              "--seq 64 --rows 4 --out $ARTIFACTS/packed",
              artifacts),
        _step("train",
              # The packed artifact from step 1 gates the train step —
              # a broken handoff fails here, not silently.
              "test -f $ARTIFACTS/packed/META.json && "
              "python -m skypilot_tpu.train.run --config llama3-tiny "
              "--steps 4 --seq 64 --batch 2 --packed "
              "--ckpt-dir $ARTIFACTS/ckpt --ckpt-every 2",
              artifacts),
        _step("eval-gate",
              "python -m skypilot_tpu.train.evaluate "
              "--config llama3-tiny --ckpt-dir $ARTIFACTS/ckpt "
              "--batches 2 --batch 2 --seq 64 --packed "
              "> $ARTIFACTS/eval.json\n"
              "python - <<'PYEOF'\n"
              "import json, os\n"
              "m = json.load(open(os.environ['ARTIFACTS'] "
              "+ '/eval.json'))\n"
              "assert m['perplexity'] > 0, m   # the rollout gate\n"
              "PYEOF",
              artifacts),
        _step("deploy-check",
              "python - <<'PYEOF'\n"
              "import os\n"
              "from skypilot_tpu.infer import engine as eng\n"
              "from skypilot_tpu.models import llama\n"
              "from skypilot_tpu.parallel import mesh as mesh_lib\n"
              "from skypilot_tpu.train import checkpoints, trainer\n"
              "import jax\n"
              "cfg = llama.CONFIGS['llama3-tiny']\n"
              "mesh = mesh_lib.make_mesh(\n"
              "    mesh_lib.default_shape_for(jax.device_count()))\n"
              "tc = trainer.TrainConfig()\n"
              "mgr = checkpoints.CheckpointManager(\n"
              "    os.environ['ARTIFACTS'] + '/ckpt')\n"
              "target = trainer.create_abstract_state(cfg, tc, mesh)\n"
              "params = mgr.restore(target)['params']\n"
              "e = eng.InferenceEngine(params, cfg, n_slots=2,\n"
              "                        max_len=32, prompt_buckets=(8,))\n"
              "out = e.generate([[1, 2, 3]], max_new_tokens=4)\n"
              "assert len(out[0]) == 4, out\n"
              "print('deploy-check ok', out[0])\n"
              "PYEOF",
              artifacts),
    ]
    jid = jobs_core.launch(steps, name="flagship")
    status = jobs_core.wait(jid, timeout=600)
    rec = jobs_core.get(jid)
    assert status == ManagedJobStatus.SUCCEEDED, rec
    assert rec["num_tasks"] == 4 and rec["current_task"] == 3

    # The artifacts really flowed: packed shards, checkpoints, eval
    # metrics all exist.
    meta = json.load(open(f"{artifacts}/packed/META.json"))
    assert meta["shards"] >= 1 and meta["tokens"] > 0
    assert os.path.isdir(f"{artifacts}/ckpt")
    ppl = json.load(open(f"{artifacts}/eval.json"))["perplexity"]
    assert ppl > 0

    # And the deploy check's output is in the job log.
    import io
    out = io.StringIO()
    jobs_core.tail_job_output(jid, out=out)
    assert "deploy-check ok" in out.getvalue()


@pytest.mark.slow
def test_pipeline_eval_gate_failure_stops_deploy(tmp_path):
    """A failing eval gate must stop the chain before the deploy step
    (the rollout-safety property the recipe exists for)."""
    artifacts = str(tmp_path / "artifacts")
    os.makedirs(artifacts)
    steps = [
        _step("eval-gate", "exit 1", artifacts),
        _step("deploy", "echo DEPLOYED > $ARTIFACTS/deployed", artifacts),
    ]
    jid = jobs_core.launch(steps, name="gate")
    status = jobs_core.wait(jid, timeout=240)
    assert status == ManagedJobStatus.FAILED
    assert not os.path.exists(f"{artifacts}/deployed")
