"""`skytpu local up` — the real kubernetes provider against a live
kind cluster (reference: sky/core.py:1010 local_up + the
tests/kubernetes harness).

The live test needs docker + kind + kubectl and runs ONLY in the slow
profile on machines that have them; everywhere else it skips with the
reason visible. The argument-validation tests run anywhere.
"""

import shutil
import subprocess
import time

import pytest

from skypilot_tpu import core, exceptions

_HAVE_STACK = all(shutil.which(b) for b in ("docker", "kind", "kubectl"))
if _HAVE_STACK:
    try:
        _HAVE_STACK = subprocess.run(
            ["docker", "info"], capture_output=True,
            timeout=30).returncode == 0
    except Exception:  # noqa: BLE001
        _HAVE_STACK = False

needs_stack = pytest.mark.skipif(
    not _HAVE_STACK,
    reason="docker/kind/kubectl not available — live kind test skipped")


def test_local_up_requires_docker(monkeypatch):
    monkeypatch.setenv("PATH", "/nonexistent")
    with pytest.raises(exceptions.NotSupportedError, match="docker"):
        core.local_up()


def test_local_down_requires_kind(monkeypatch):
    monkeypatch.setenv("PATH", "/nonexistent")
    with pytest.raises(exceptions.NotSupportedError):
        core.local_down()


@needs_stack
@pytest.mark.slow
def test_kind_cluster_end_to_end(tmp_path, monkeypatch):
    """Bring up kind, drive the REAL kubernetes provider (pods,
    NodePort exposure, teardown) against the live API server, then
    delete the kind cluster. This exercises the exact code paths the
    fake-kubectl suite (tests/test_kubernetes_provision.py) covers
    offline."""
    from skypilot_tpu import check as check_mod
    from skypilot_tpu.provision import kubernetes as k8s
    from skypilot_tpu.provision.common import ProvisionConfig

    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    name = "skytpu-test"
    ctx = core.local_up(name)
    try:
        assert ctx == f"kind-{name}"
        ok, reason = k8s.check_credentials()
        assert ok, reason
        assert "kubernetes" in (check_mod.cached_enabled_clouds() or [])

        config = ProvisionConfig(
            cluster_name="kindc", num_nodes=1, hosts_per_node=1,
            zone="in-cluster", region="in-cluster",
            instance_type="cpu", accelerator=None,
            ports=[8080],
            # docker: image_id becomes the pod image directly.
            image_id="docker:python:3.11-slim")
        k8s.run_instances(config)
        try:
            k8s.wait_instances("kindc", "in-cluster", timeout=300)
            assert k8s.query_instances("kindc", "in-cluster") == "UP"
            info = k8s.get_cluster_info("kindc", "in-cluster")
            assert info.hosts and info.hosts[0].internal_ip
            # NodePort exposure round-trips through the live API server.
            k8s.open_ports("kindc", [8080])
            deadline = time.time() + 60
            ports = {}
            while time.time() < deadline and 8080 not in ports:
                ports = k8s.query_ports("kindc")
                time.sleep(2)
            assert 8080 in ports, f"NodePort never appeared: {ports}"
            # The pod is really running python.
            rc = subprocess.run(
                ["kubectl", "exec", "kindc-0-0", "--",
                 "python", "-c", "print(40+2)"],
                capture_output=True, text=True, timeout=120)
            assert rc.returncode == 0 and "42" in rc.stdout
        finally:
            k8s.terminate_instances("kindc", "in-cluster")
        assert k8s.query_instances("kindc", "in-cluster") == "NOT_FOUND"
    finally:
        core.local_down(name)
