"""QLoRA: LoRA adapters over a frozen int8 base (the 8B-on-one-chip
finetune path). Oracles against the fp model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.infer import kvcache
from skypilot_tpu.models import llama
from skypilot_tpu.train import qlora, trainer
from skypilot_tpu.train.lora import LoRAConfig, init_lora_params


@pytest.fixture(scope="module")
def cfg():
    return llama.CONFIGS["llama3-tiny"]


@pytest.fixture(scope="module")
def quantized(cfg):
    params = llama.init_params(jax.random.key(0), cfg)
    qw = {"blocks": kvcache.quantize_block_weights(params),
          "head": kvcache.quantize_head(params, cfg)}
    return params, qw, kvcache.slim_params(params)


@pytest.fixture(scope="module")
def batch(cfg):
    tokens = jax.random.randint(jax.random.key(2), (2, 32), 1,
                                cfg.vocab_size, dtype=jnp.int32)
    return {"tokens": tokens}


def test_zero_adapters_match_fp_model(cfg, quantized, batch):
    """With B=0 adapters the int8 forward is the base model up to
    quantization error (measured ~0.04% on the loss)."""
    params, qw, fp = quantized
    lc = LoRAConfig(rank=4)
    adapters = init_lora_params(jax.random.key(1), cfg, lc)
    loss_q, metrics = jax.jit(
        lambda a: qlora.loss_fn(qw, fp, a, batch, cfg, lc))(adapters)
    loss_fp, _ = jax.jit(lambda p: llama.loss_fn(p, batch, cfg))(params)
    np.testing.assert_allclose(float(loss_q), float(loss_fp), rtol=5e-3)
    assert np.isfinite(float(metrics["accuracy"]))


def test_qlora_adapters_learn(cfg, quantized, batch):
    """Gradients flow through the dequantized matmuls into the
    adapters: loss drops on a fixed batch with the base frozen."""
    _, qw, fp = quantized
    lc = LoRAConfig(rank=8)
    tc = trainer.TrainConfig(learning_rate=1e-2, warmup_steps=1)
    step = qlora.make_qlora_train_step(cfg, lc, tc)
    state = qlora.create_qlora_state(cfg, lc, tc)
    first = last = None
    for _ in range(8):
        state, metrics = step(state, qw, fp, batch)
        loss = float(metrics["loss"])
        first = loss if first is None else first
        last = loss
    assert last < first - 0.5, (first, last)
    assert float(metrics["grad_norm"]) > 0


def test_qlora_grads_only_adapters(cfg, quantized, batch):
    """value_and_grad wrt adapters only — every adapter leaf gets a
    finite gradient, and wq's B-grad is nonzero (B=0 start still gets
    gradient through A)."""
    _, qw, fp = quantized
    lc = LoRAConfig(rank=4)
    adapters = init_lora_params(jax.random.key(3), cfg, lc)
    grads = jax.jit(jax.grad(
        lambda a: qlora.loss_fn(qw, fp, a, batch, cfg, lc)[0]))(adapters)
    for leaf in jax.tree.leaves(grads):
        assert np.isfinite(np.asarray(leaf)).all()
    assert float(jnp.abs(grads["wq"]["b"]).sum()) > 0


def test_random_quantized_params_device_side(cfg):
    """The 8B bench's weight builder: no host numpy arrays, leaves live
    on device, engine-compatible structure."""
    fp, qw = kvcache.random_quantized_params(cfg, seed=1)
    assert qw["blocks"]["wq"]["w"].dtype == jnp.int8
    assert fp["embed"].dtype == jnp.bfloat16
    lc = LoRAConfig(rank=4)
    adapters = init_lora_params(jax.random.key(1), cfg, lc)
    loss, _ = jax.jit(lambda a: qlora.loss_fn(
        qw, fp, a, {"tokens": jnp.ones((1, 16), jnp.int32)}, cfg,
        lc))(adapters)
    assert np.isfinite(float(loss))
