"""Fleet health tier: metrics federation merge semantics, the
component health model, the SLO watchdog's multi-window burn rate, the
new LB/replica/usage telemetry, and an e2e `skytpu top` / `GET
/metrics/fleet` pass over three live local processes."""

import http.server
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest
from click.testing import CliRunner

from skypilot_tpu.observability import aggregate, health, metrics, slo


# -- merge semantics --------------------------------------------------------

def _regs_pair():
    r1, r2 = metrics.Registry(), metrics.Registry()
    r1.counter("skytpu_m_total", "").inc(3)
    r2.counter("skytpu_m_total", "").inc(4)
    r1.gauge("skytpu_m_gauge", "").set(5)
    r2.gauge("skytpu_m_gauge", "").set(7)
    r1.histogram("skytpu_m_seconds", "", buckets=(1.0, 5.0)).observe(0.5)
    r2.histogram("skytpu_m_seconds", "", buckets=(1.0, 5.0)).observe(3.0)
    return r1, r2


def _federate(*regs, components=None):
    eps = [aggregate.endpoint(components[i] if components else "c",
                              f"i{i}", get_text=regs[i].render)
           for i in range(len(regs))]
    return aggregate.federate(eps)


def test_merge_counters_sum_across_instances():
    snap = _federate(*_regs_pair())
    assert snap.errors == []
    assert aggregate.sample_value(snap.families, "skytpu_m_total") == 7.0


def test_merge_gauges_keep_instance_labels():
    snap = _federate(*_regs_pair())
    samples = snap.families["skytpu_m_gauge"]["samples"]
    assert sorted((l["instance"], v) for l, v in samples) == [
        ("i0", 5.0), ("i1", 7.0)]


def test_merge_histograms_sum_buckets_and_roundtrip():
    snap = _federate(*_regs_pair())
    fams = metrics.parse_exposition(snap.render())   # render round-trips
    count = aggregate.sample_value(fams, "skytpu_m_seconds",
                                   sample_name="skytpu_m_seconds_count")
    total = aggregate.sample_value(fams, "skytpu_m_seconds",
                                   sample_name="skytpu_m_seconds_sum")
    assert count == 2.0 and total == pytest.approx(3.5)
    le1 = next(v for l, v in fams["skytpu_m_seconds"]["samples"]
               if l.get("le") == "1")
    assert le1 == 1.0


def test_merge_bucket_mismatch_reported_not_summed():
    r1, r2 = _regs_pair()
    r3 = metrics.Registry()
    r3.histogram("skytpu_m_seconds", "", buckets=(2.0,)).observe(0.5)
    snap = _federate(r1, r2, r3)
    assert any("bucket mismatch" in e and "skytpu_m_seconds" in e
               for e in snap.errors)
    # Fallback keeps the data visible per-instance instead of summing.
    fam = snap.families["skytpu_m_seconds"]
    assert all("instance" in labels for labels, _ in fam["samples"])
    # The merged exposition carries the error count.
    assert "skytpu_fleet_merge_errors 1" in snap.render()


def test_merge_type_conflict_skips_family():
    r1 = metrics.Registry()
    r1.counter("skytpu_conflict", "").inc()
    r2 = metrics.Registry()
    r2.gauge("skytpu_conflict", "").set(1)
    snap = _federate(r1, r2)
    assert "skytpu_conflict" not in snap.families
    assert any("type conflict" in e for e in snap.errors)


def test_scrape_down_target_reported_not_fatal():
    r1, _ = _regs_pair()
    with socket.socket() as s:            # a port nothing listens on
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    eps = [aggregate.endpoint("a", "up", get_text=r1.render),
           aggregate.endpoint("b", "down",
                              url=f"http://127.0.0.1:{dead_port}/metrics")]
    snap = aggregate.federate(eps, timeout=0.5)
    by_inst = {t["instance"]: t for t in snap.targets}
    assert by_inst["up"]["ok"] and not by_inst["down"]["ok"]
    # scrape_up is synthesized at render time; check via the text.
    fams = metrics.parse_exposition(snap.render())
    up = {(l["component"], l["instance"]): v
          for l, v in fams["skytpu_fleet_scrape_up"]["samples"]}
    assert up[("a", "up")] == 1.0 and up[("b", "down")] == 0.0


def test_stale_exposition_file_counts_as_down(tmp_path):
    p = tmp_path / "metrics.prom"
    p.write_text("# TYPE skytpu_x_total counter\nskytpu_x_total 1\n")
    old = time.time() - 1000
    os.utime(p, (old, old))
    fams, err = aggregate.scrape(
        aggregate.endpoint("skylet", "c1", path=str(p),
                           stale_after_s=60.0))
    assert fams is None and "stale" in err
    fams, err = aggregate.scrape(
        aggregate.endpoint("skylet", "c1", path=str(p)))
    assert err is None and "skytpu_x_total" in fams


# -- snapshot math ----------------------------------------------------------

def _counter_fams(**series):
    return {"skytpu_c_total": {"type": "counter", "samples": [
        ({"k": k}, float(v)) for k, v in series.items()]}}


def test_delta_clamps_counter_reset():
    prev = _counter_fams(a=100)
    cur = _counter_fams(a=5)              # process restarted mid-window
    assert aggregate.delta(prev, cur, "skytpu_c_total") == 0.0
    assert aggregate.delta(prev, _counter_fams(a=130),
                           "skytpu_c_total") == 30.0


def test_filtered_delta_clamps_per_series():
    # One replica reset (100 -> 2), another grew (50 -> 70): the reset
    # must not erase the survivor's increase.
    prev = _counter_fams(a=100, b=50)
    cur = _counter_fams(a=2, b=70)
    got = aggregate.filtered_delta(prev, cur, "skytpu_c_total",
                                   lambda l: True)
    assert got == pytest.approx(20.0)     # max(2-100, 0) + (70-50)


def test_histogram_quantile_windowed():
    def hist(counts):                      # le: 0.1 / 1 / +Inf
        cum, samples = 0, []
        for le, n in zip(("0.1", "1", "+Inf"), counts):
            cum += n
            samples.append(({"__name__": "skytpu_h_seconds_bucket",
                             "le": le}, float(cum)))
        return {"skytpu_h_seconds": {"type": "histogram",
                                     "samples": samples}}
    prev = hist((100, 0, 0))               # all fast so far
    cur = hist((100, 0, 20))               # window: 20 slow samples
    q = aggregate.histogram_quantile(prev, cur, "skytpu_h_seconds", 0.95)
    assert q == 1.0                        # +Inf answers the last bound
    assert aggregate.histogram_quantile(
        prev, prev, "skytpu_h_seconds", 0.95) is None   # empty window


# -- component health model -------------------------------------------------

def _write_heartbeat(cdir, ts):
    with open(os.path.join(cdir, aggregate.METRICS_FILENAME), "w") as f:
        f.write("# TYPE skytpu_skylet_last_tick_timestamp_seconds gauge\n"
                f"skytpu_skylet_last_tick_timestamp_seconds {ts}\n")


def test_skylet_health_states(tmp_path):
    cdir = str(tmp_path / "clusters" / "c1")
    os.makedirs(cdir)
    # No autostop armed, no skylet: idle by design, not dead.
    assert health.skylet_health(cdir)["status"] == "healthy"
    # Armed + alive + fresh heartbeat.
    with open(os.path.join(cdir, "skylet.pid"), "w") as f:
        f.write(str(os.getpid()))
    with open(os.path.join(cdir, "autostop.json"), "w") as f:
        f.write("{}")
    _write_heartbeat(cdir, time.time())
    h = health.skylet_health(cdir)
    assert h["status"] == "healthy" and h["last_seen_s"] < 5
    # Alive but the heartbeat went stale: degraded.
    _write_heartbeat(cdir, time.time() - 600)
    h = health.skylet_health(cdir)
    assert h["status"] == "degraded" and "stale" in h["reason"]
    # Armed but the process is gone: dead.
    import subprocess
    import sys
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    with open(os.path.join(cdir, "skylet.pid"), "w") as f:
        f.write(str(proc.pid))
    assert health.skylet_health(cdir)["status"] == "dead"
    assert health.skylet_expected(cdir)
    # Autostop FIRED successfully: autostop.json stays behind but the
    # marker proves the exit was by design — healthy, not dead, and
    # the frozen heartbeat must stop feeding the staleness SLO rule.
    with open(os.path.join(cdir, "autostop_fired"), "w") as f:
        f.write("{}")
    h = health.skylet_health(cdir)
    assert h["status"] == "healthy" and "fired" in h["reason"]
    assert not health.skylet_expected(cdir)


def test_discover_skips_by_design_exited_skylets(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    alive = tmp_path / "clusters" / "armed"
    gone = tmp_path / "clusters" / "fired"
    for d in (alive, gone):
        os.makedirs(d)
        _write_heartbeat(str(d), time.time() - 10_000)
    (alive / "skylet.pid").write_text(str(os.getpid()))
    (gone / "autostop.json").write_text("{}")
    (gone / "autostop_fired").write_text("{}")
    eps = aggregate.discover_endpoints()
    skylets = {e["instance"] for e in eps if e["component"] == "skylet"}
    # The live (here: wedged) skylet federates — its old heartbeat IS
    # the staleness signal; the by-design-exited one must not breach
    # the heartbeat rule forever.
    assert skylets == {"armed"}


def test_rpc_get_metrics_and_healthz(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    from skypilot_tpu.runtime import rpc, skylet
    db = str(tmp_path / "clusters" / "rc1" / "jobs.db")
    os.makedirs(os.path.dirname(db))
    skylet.observe_tick(db)               # writes metrics.prom
    got = rpc.dispatch("rc1", "get_metrics", {})
    fams = metrics.parse_exposition(got["exposition"])
    assert "skytpu_skylet_ticks_total" in fams
    assert got["mtime"] is not None
    hz = rpc.dispatch("rc1", "healthz", {})
    assert hz["status"] == "healthy"
    assert set(hz) == {"status", "reason", "last_seen_s"}


def test_probe_http_maps_statuses(tmp_path):
    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/healthz":
                health.write_healthz(self, health.DEGRADED,
                                     reason="warming")
            else:
                body = b'{"status": "ok"}'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        got = health.probe_http(f"{base}/healthz", comp="m", instance="1")
        assert got["status"] == "degraded" and got["reason"] == "warming"
        # /health-style {"status": "ok"} maps onto the model.
        assert health.probe_http(f"{base}/health")["status"] == "healthy"
    finally:
        httpd.shutdown()
    # Unreachable = dead.
    got = health.probe_http(f"http://127.0.0.1:1/healthz", timeout=0.5)
    assert got["status"] == "dead"


# -- SLO watchdog -----------------------------------------------------------

def _http_fams(ok, err):
    return {"skytpu_http_requests_total": {"type": "counter", "samples": [
        ({"route": "/generate", "code": "200"}, float(ok)),
        ({"route": "/generate", "code": "500"}, float(err))]}}


def test_slo_multiwindow_needs_both_windows():
    rule = slo.SloRule("5xx", "ratio", threshold=0.1,
                       metric="skytpu_http_requests_total",
                       label_prefix={"code": "5"}, min_events=5.0,
                       short_window_s=10, long_window_s=60)
    wd = slo.Watchdog(rules=[rule])
    t0 = time.time() - 200
    assert wd.observe(_http_fams(100, 0), [], ts=t0) == []
    # A short error burst: short window breaches, long does not -> no
    # page (the single-slow-request guarantee).
    assert wd.observe(_http_fams(110, 3), [], ts=t0 + 15) == []
    assert wd.active_alerts() == []
    # Sustained errors: both windows breach -> one slo.breach.
    ev = wd.observe(_http_fams(120, 40), [], ts=t0 + 70)
    assert [e["event"] for e in ev] == ["slo.breach"]
    assert wd.active_alerts()[0]["rule"] == "5xx"
    # Still breached: no duplicate event.
    assert wd.observe(_http_fams(125, 60), [], ts=t0 + 85) == []
    # Healthy again on both windows -> slo.recovered.
    ev = wd.observe(_http_fams(400, 60), [], ts=t0 + 160)
    assert [e["event"] for e in ev] == ["slo.recovered"]
    assert wd.active_alerts() == []


def test_slo_breach_events_are_typed_and_echoed():
    from skypilot_tpu.observability import tracing
    rule = slo.SloRule("dead", "component_dead", threshold=0.0)
    wd = slo.Watchdog(rules=[rule])
    wd.observe({}, [health.component("model-server", "s/1",
                                     health.DEAD, "gone")])
    recs = [r for r in tracing.buffered_records()
            if r.get("name") == "slo.breach"
            and r.get("attrs", {}).get("rule") == "dead"]
    assert recs and recs[-1]["attrs"]["dead_components"] == \
        ["model-server/s/1"]


def test_slo_heartbeat_staleness_is_instant():
    rule = slo.SloRule("hb", "heartbeat_staleness", threshold=120.0,
                       metric="skytpu_skylet_last_tick_timestamp_seconds")
    wd = slo.Watchdog(rules=[rule])
    now = time.time()
    # One FRESH skylet must not mask a wedged sibling: staleness reads
    # the OLDEST heartbeat across instances.
    fams = {"skytpu_skylet_last_tick_timestamp_seconds": {
        "type": "gauge", "samples": [({"instance": "c1"}, now - 300),
                                     ({"instance": "c2"}, now)]}}
    ev = wd.observe(fams, [], ts=now)
    assert [e["event"] for e in ev] == ["slo.breach"]
    fams["skytpu_skylet_last_tick_timestamp_seconds"]["samples"] = [
        ({"instance": "c1"}, now), ({"instance": "c2"}, now)]
    ev = wd.observe(fams, [], ts=now + 1)
    assert [e["event"] for e in ev] == ["slo.recovered"]


def test_slo_ratio_excludes_monitoring_routes():
    """The watchdog's own /metrics scrapes and /healthz probes must not
    pad the 5xx-ratio denominator (they would dilute the error ratio
    of a low-traffic service below its threshold)."""
    (rule,) = [r for r in slo.DEFAULT_RULES if r.name == "http-5xx-ratio"]
    rule = slo.SloRule.from_dict({**rule.to_dict(),
                                  "short_window_s": 10,
                                  "long_window_s": 30})

    def fams(gen_ok, gen_err, monitor):
        return {"skytpu_http_requests_total": {
            "type": "counter", "samples": [
                ({"route": "/generate", "code": "200"}, float(gen_ok)),
                ({"route": "/generate", "code": "500"}, float(gen_err)),
                ({"route": "/metrics", "code": "200"}, float(monitor)),
                ({"route": "/healthz", "code": "200"}, float(monitor)),
            ]}}

    wd = slo.Watchdog(rules=[rule])
    t0 = time.time() - 100
    wd.observe(fams(50, 0, 1000), [], ts=t0)
    wd.observe(fams(52, 2, 2000), [], ts=t0 + 35)
    # All real traffic in the window is 5xx; the 2000+ monitor hits
    # would mask it if they counted in the denominator.
    ev = wd.observe(fams(52, 8, 3000), [], ts=t0 + 70)
    assert [e["event"] for e in ev] == ["slo.breach"]


def test_slo_train_step_regression():
    rule = slo.SloRule("regress", "train_step_regression", threshold=1.5,
                       metric="skytpu_train_step_seconds",
                       baseline_metric="skytpu_train_step_median_seconds",
                       min_events=3.0, short_window_s=10,
                       long_window_s=30)

    def fams(count, total, median):
        return {
            "skytpu_train_step_seconds": {"type": "histogram", "samples": [
                ({"__name__": "skytpu_train_step_seconds_count"},
                 float(count)),
                ({"__name__": "skytpu_train_step_seconds_sum"},
                 float(total))]},
            "skytpu_train_step_median_seconds": {
                "type": "gauge", "samples": [({}, float(median))]}}

    wd = slo.Watchdog(rules=[rule])
    t0 = time.time() - 100
    wd.observe(fams(100, 100.0, 1.0), [], ts=t0)        # 1s steps
    wd.observe(fams(110, 110.0, 1.0), [], ts=t0 + 35)
    # Steps now take 3x the trailing median on both windows.
    ev = wd.observe(fams(130, 170.0, 1.0), [], ts=t0 + 70)
    assert [e["event"] for e in ev] == ["slo.breach"]


def test_slo_rules_load_and_fallback(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    assert [r.name for r in slo.load_rules()] == \
        [r.name for r in slo.DEFAULT_RULES]
    path = tmp_path / slo.RULES_FILENAME
    path.write_text(json.dumps([
        {"name": "custom", "kind": "rate", "threshold": 1.0,
         "metric": "skytpu_rpc_failures_total",
         "labels": {"kind": "transport"}}]))
    rules = slo.load_rules()
    assert len(rules) == 1 and rules[0].name == "custom"
    assert rules[0].labels == {"kind": "transport"}
    path.write_text("not json")
    assert [r.name for r in slo.load_rules()] == \
        [r.name for r in slo.DEFAULT_RULES]
    path.write_text(json.dumps([{"name": "x", "kind": "rate",
                                 "threshold": 1, "bogus_field": 2}]))
    assert [r.name for r in slo.load_rules()] == \
        [r.name for r in slo.DEFAULT_RULES]


# -- LB telemetry (satellite) -----------------------------------------------

class _EchoReplica(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(n)
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    do_GET = do_POST

    def log_message(self, *a):
        pass


@pytest.fixture()
def lb_service(tmp_path, monkeypatch):
    from skypilot_tpu.serve import load_balancer, serve_state
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    replica = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                              _EchoReplica)
    threading.Thread(target=replica.serve_forever, daemon=True).start()
    rurl = f"http://127.0.0.1:{replica.server_address[1]}"
    serve_state.add_service("fh", {}, {}, 0)
    serve_state.upsert_replica("fh", 1, "r1",
                               serve_state.ReplicaStatus.READY, rurl)
    httpd = load_balancer._ThreadingServer(
        ("127.0.0.1", 0),
        load_balancer.make_handler("fh",
                                   load_balancer.RoundRobinPolicy()))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", rurl
    httpd.shutdown()
    replica.shutdown()


def test_lb_exposes_metrics_and_healthz(lb_service):
    from skypilot_tpu.serve import load_balancer, serve_state
    lb_url, rurl = lb_service
    before = load_balancer.LB_PROXIED.labels(
        backend=rurl, code="200").value
    req = urllib.request.Request(lb_url + "/echo", data=b"hi",
                                 method="POST")
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.read() == b"hi"
    with urllib.request.urlopen(lb_url + "/metrics", timeout=30) as r:
        assert r.headers["Content-Type"] == metrics.CONTENT_TYPE
        fams = metrics.parse_exposition(r.read().decode())
    got = next(v for l, v in fams["skytpu_lb_proxied_total"]["samples"]
               if l == {"backend": rurl, "code": "200"})
    assert got == before + 1
    with urllib.request.urlopen(lb_url + "/healthz", timeout=30) as r:
        hz = json.loads(r.read())
    assert hz["status"] == "healthy" and "1 ready" in hz["reason"]
    # No ready replicas -> degraded (the LB is up; routing is not).
    serve_state.set_replica_status("fh", 1,
                                   serve_state.ReplicaStatus.NOT_READY)
    with urllib.request.urlopen(lb_url + "/healthz", timeout=30) as r:
        assert json.loads(r.read())["status"] == "degraded"


def test_lb_counts_retries_and_503(lb_service):
    from skypilot_tpu.serve import load_balancer, serve_state
    lb_url, rurl = lb_service
    retries0 = load_balancer.LB_RETRIES.labels(backend=rurl).value
    none0 = load_balancer.LB_PROXIED.labels(backend="none",
                                            code="503").value
    # Point the only replica at a dead port: forward fails, retry
    # counted, terminal 503 counted under backend="none".
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead = f"http://127.0.0.1:{s.getsockname()[1]}"
    serve_state.upsert_replica("fh", 1, "r1",
                               serve_state.ReplicaStatus.READY, dead)
    req = urllib.request.Request(lb_url + "/echo", data=b"x",
                                 method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=30)
    assert ei.value.code == 503
    assert load_balancer.LB_RETRIES.labels(backend=dead).value >= 1
    assert load_balancer.LB_PROXIED.labels(
        backend="none", code="503").value == none0 + 1
    assert load_balancer.LB_RETRIES.labels(backend=rurl).value == retries0


# -- replica probe telemetry (satellite) ------------------------------------

def test_replica_probe_metrics(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    from skypilot_tpu.serve import replica_managers, serve_state
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    serve_state.add_service("pm", {}, {}, 0)
    serve_state.upsert_replica("pm", 1, "c1",
                               serve_state.ReplicaStatus.READY,
                               "http://127.0.0.1:1")
    mgr = replica_managers.ReplicaManager("pm", SkyServiceSpec(), {})
    monkeypatch.setattr(mgr, "_cluster_gone", lambda name: False)
    fails0 = replica_managers.PROBE_FAILURES.labels(service="pm").value
    monkeypatch.setattr(mgr, "_probe_one", lambda r: False)
    for _ in range(replica_managers.PROBE_FAILURES_BEFORE_NOT_READY):
        mgr.probe_all()
    assert replica_managers.PROBE_FAILURES.labels(
        service="pm").value == fails0 + 3
    assert replica_managers.REPLICA_PROBE_OK.labels(
        service="pm", replica="1").value == 0
    (row,) = serve_state.list_replicas("pm")
    assert row["status"] == serve_state.ReplicaStatus.NOT_READY
    monkeypatch.setattr(mgr, "_probe_one", lambda r: True)
    t0 = time.time()
    mgr.probe_all()
    assert replica_managers.REPLICA_PROBE_OK.labels(
        service="pm", replica="1").value == 1
    assert replica_managers.REPLICA_PROBE_OK_TS.labels(
        service="pm", replica="1").value >= t0
    assert replica_managers.PROBE_FAILURES.labels(
        service="pm").value == fails0 + 3


# -- usage sends bounded (satellite) ----------------------------------------

def test_usage_dead_endpoint_bounded_and_counted(tmp_path, monkeypatch):
    from skypilot_tpu.usage import usage_lib
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    monkeypatch.delenv(usage_lib.DISABLE_ENV, raising=False)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    monkeypatch.setenv(usage_lib.ENDPOINT_ENV,
                       f"http://127.0.0.1:{dead_port}/ingest")
    monkeypatch.setenv(usage_lib.TIMEOUT_ENV, "0.5")
    fails0 = usage_lib.USAGE_SEND_FAILURES._require_default().value
    file0 = usage_lib.USAGE_REPORTS.labels(sink="file").value
    t0 = time.time()
    with usage_lib.entrypoint_context("launch"):
        pass
    assert time.time() - t0 < 5.0          # bounded, never stalls
    assert usage_lib.USAGE_SEND_FAILURES._require_default().value == \
        fails0 + 1
    # The record fell back to the local file sink (and was counted).
    assert usage_lib.USAGE_REPORTS.labels(sink="file").value == file0 + 1
    assert (tmp_path / "usage" / "usage.jsonl").exists()


# -- trainer regression source ----------------------------------------------

def test_trainer_exports_step_median(monkeypatch):
    import numpy as np

    from skypilot_tpu.train import trainer
    calls = {"n": 0}

    def fake_step(state, batch):
        calls["n"] += 1
        return state, {}

    wrapped = trainer._instrument_step(fake_step)
    batch = {"tokens": np.zeros((2, 4), dtype=np.int32)}
    wrapped(None, batch)                   # compile call: skipped
    for _ in range(3):
        wrapped(None, batch)
    assert calls["n"] == 4
    last = trainer.TRAIN_STEP_LAST._require_default().value
    med = trainer.TRAIN_STEP_MEDIAN._require_default().value
    assert last > 0 and med > 0


# -- e2e: three live processes, /metrics/fleet, status --health, top --------

class _FakeModelProcess:
    """A model-server stand-in with its OWN registry (as a separate
    process would have): /health, /healthz, /metrics."""

    def __init__(self, requests_total: float, queue_depth: float):
        reg = metrics.Registry()
        reg.counter("skytpu_fake_requests_total", "t").inc(requests_total)
        reg.gauge("skytpu_fake_queue_depth", "t").set(queue_depth)
        reg.histogram("skytpu_fake_latency_seconds", "t",
                      buckets=(0.1, 1.0)).observe(0.05)

        class H(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self):
                if self.path == "/metrics":
                    body = reg.render().encode()
                    ctype = metrics.CONTENT_TYPE
                elif self.path in ("/health", "/healthz"):
                    body = json.dumps(
                        health.healthz_payload(health.HEALTHY)).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            do_POST = do_GET

            def log_message(self, *a):
                pass

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def kill(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def fleet(tmp_path, monkeypatch):
    """API server + load balancer + two model-server stand-ins, all
    live on localhost, registered in the serve DB the way `serve up`
    would leave them."""
    from skypilot_tpu.serve import load_balancer, serve_state
    from skypilot_tpu.server import server as server_mod
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    m1 = _FakeModelProcess(requests_total=3, queue_depth=2)
    m2 = _FakeModelProcess(requests_total=4, queue_depth=5)
    lb = load_balancer._ThreadingServer(
        ("127.0.0.1", 0),
        load_balancer.make_handler("svc",
                                   load_balancer.RoundRobinPolicy()))
    threading.Thread(target=lb.serve_forever, daemon=True).start()
    serve_state.add_service("svc", {}, {}, lb.server_address[1])
    serve_state.set_controller_pid("svc", os.getpid())
    serve_state.set_service_status("svc", serve_state.ServiceStatus.READY)
    serve_state.upsert_replica("svc", 1, "c1",
                               serve_state.ReplicaStatus.READY, m1.url)
    serve_state.upsert_replica("svc", 2, "c2",
                               serve_state.ReplicaStatus.READY, m2.url)
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    monkeypatch.setenv("SKYTPU_API_SERVER_URL",
                       f"http://127.0.0.1:{port}")
    executor = server_mod.Executor()
    executor.start()
    httpd = server_mod._Server(("127.0.0.1", port),
                               server_mod.make_handler())
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    monkeypatch.setattr(server_mod, "_WATCHDOG", None)
    yield {"api": f"http://127.0.0.1:{port}", "m1": m1, "m2": m2,
           "server_mod": server_mod}
    executor.stop()
    httpd.shutdown()
    m1.kill()
    m2.kill()


def test_e2e_fleet_metrics_health_top_and_breach(fleet):
    server_mod = fleet["server_mod"]
    # 1) GET /metrics/fleet merges all three processes: counters
    # summed, gauges instance-labeled, LB + API server families there.
    with urllib.request.urlopen(f"{fleet['api']}/metrics/fleet",
                                timeout=30) as r:
        assert r.headers["Content-Type"] == metrics.CONTENT_TYPE
        fams = metrics.parse_exposition(r.read().decode())
    assert aggregate.sample_value(fams, "skytpu_fake_requests_total") \
        == 7.0
    depths = {l["instance"]: v
              for l, v in fams["skytpu_fake_queue_depth"]["samples"]}
    assert depths == {"svc/1": 2.0, "svc/2": 5.0}
    assert "skytpu_api_requests_total" in fams   # the API server's own
    up = {(l["component"], l["instance"]): v
          for l, v in fams["skytpu_fleet_scrape_up"]["samples"]}
    assert up[("api-server", "self")] == 1.0
    assert up[("load-balancer", "svc")] == 1.0
    assert up[("model-server", "svc/1")] == 1.0
    assert up[("model-server", "svc/2")] == 1.0

    # 2) skytpu status --health: every component healthy.
    from skypilot_tpu.client import cli as cli_mod
    out = CliRunner().invoke(cli_mod.cli, ["status", "--health"])
    assert out.exit_code == 0, out.output
    assert "fleet: HEALTHY" in out.output
    for needle in ("api-server", "load-balancer", "model-server",
                   "serve-controller"):
        assert needle in out.output
    assert "dead" not in out.output

    # 3) skytpu top --once renders the fleet table.
    out = CliRunner().invoke(cli_mod.cli, ["top", "--once"])
    assert out.exit_code == 0, out.output
    assert "COMPONENT" in out.output and "model-server" in out.output
    assert "0 active alert(s)" in out.output

    # 4) Kill one model server: within one watchdog interval the
    # component flips to dead and a typed slo.breach event fires.
    from skypilot_tpu.observability import tracing
    wd = server_mod.start_watchdog(interval_s=30)  # tick driven below
    assert wd.tick() == []                          # healthy baseline
    fleet["m1"].kill()
    events = wd.tick()                              # one interval later
    assert any(e["event"] == "slo.breach"
               and e["rule"] == "component-alive" for e in events)
    assert any("model-server/svc/1" in str(e.get("dead_components"))
               for e in events)
    recs = [r for r in tracing.buffered_records()
            if r.get("name") == "slo.breach"]
    assert recs, "breach must land in the structured event log"
    out = CliRunner().invoke(cli_mod.cli, ["status", "--health"])
    assert out.exit_code == 2                       # non-healthy fleet
    assert "dead" in out.output
    out = CliRunner().invoke(cli_mod.cli, ["top", "--once"])
    assert "ALERT component-alive" in out.output
    wd.stop()
