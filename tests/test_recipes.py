"""Recipes: every shipped YAML parses; train-run entry point works."""

import glob
import json
import os
import subprocess
import sys

import pytest
import yaml

from skypilot_tpu.task import Task

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("path", sorted(
    glob.glob(os.path.join(REPO, "examples", "*.yaml"))
    + glob.glob(os.path.join(REPO, "llm", "*.yaml"))))
def test_recipe_yaml_parses(path):
    # from_yaml_all handles single- and multi-document (pipeline) YAMLs.
    tasks = Task.from_yaml_all(path)
    assert tasks
    for task in tasks:
        assert task.run
        assert task.resources


def test_train_run_cli_smoke(tmp_path):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               SKYTPU_CALLBACK_LOG_DIR=str(tmp_path),
               PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "skypilot_tpu.train.run",
         "--config", "llama3-tiny", "--steps", "3", "--seq", "64",
         "--tp", "2", "--log-every", "1",
         "--ckpt-dir", str(tmp_path / "ck"), "--ckpt-every", "2"],
        capture_output=True, text=True, env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["steps"] == 3
    assert out["tokens_per_sec"] > 0
    assert (tmp_path / "ck").exists()

    # Resume from the saved checkpoint.
    proc2 = subprocess.run(
        [sys.executable, "-m", "skypilot_tpu.train.run",
         "--config", "llama3-tiny", "--steps", "5", "--seq", "64",
         "--tp", "2", "--ckpt-dir", str(tmp_path / "ck"), "--resume"],
        capture_output=True, text=True, env=env, timeout=600)
    assert proc2.returncode == 0, proc2.stderr[-2000:]
    assert "resumed from step 3" in proc2.stderr
    out2 = json.loads(proc2.stdout.strip().splitlines()[-1])
    assert out2["steps"] == 2


def test_collectives_bench_smoke():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "collectives_bench.py"),
         "--mb", "1", "--iters", "2"],
        capture_output=True, text=True, env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["devices"] == 8
    assert out["all_reduce"]["algbw_gbps"] > 0
    assert out["all_gather"]["time_ms"] > 0
    assert out["ppermute"]["time_ms"] > 0


def test_evaluate_cli_smoke(tmp_path):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=REPO)
    # Train 2 steps with a checkpoint, then evaluate from it.
    proc = subprocess.run(
        [sys.executable, "-m", "skypilot_tpu.train.run",
         "--config", "llama3-tiny", "--steps", "2", "--seq", "64",
         "--ckpt-dir", str(tmp_path / "ck"), "--ckpt-every", "2"],
        capture_output=True, text=True, env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr[-1500:]
    proc = subprocess.run(
        [sys.executable, "-m", "skypilot_tpu.train.evaluate",
         "--config", "llama3-tiny", "--seq", "64", "--batches", "2",
         "--batch", "2", "--ckpt-dir", str(tmp_path / "ck"), "--packed"],
        capture_output=True, text=True, env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr[-1500:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["batches"] == 2
    assert out["perplexity"] > 1.0
    assert "restored step 2" in proc.stderr


def test_train_run_qlora_cli_smoke(tmp_path):
    """--qlora: int8-quantized base + adapters via the CLI, single
    virtual device (the flag is the single-chip path)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               SKYTPU_CALLBACK_LOG_DIR=str(tmp_path),
               PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "skypilot_tpu.train.run",
         "--config", "llama3-tiny", "--qlora", "4", "--steps", "3",
         "--seq", "64", "--log-every", "1"],
        capture_output=True, text=True, env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "QLoRA rank 4" in proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["steps"] == 3
