"""Live-AWS smoke tests: the EC2 provider against real credentials
(reference: tests/smoke_tests/test_cluster_job.py aws cases). Skipped
without SKYTPU_SMOKE=1 + AWS keys — see smoke_utils.has_aws_credentials.

Cost notes: the lifecycle test uses m6i.large (~$0.10/h); the spot test
uses a g4dn.xlarge spot T4 (~$0.16/h). Every test tears its cluster
down in a finally, pass or fail.
"""

from tests.smoke.smoke_utils import (SKYTPU, SmokeTest, requires_aws,
                                     run_one_test, smoke_name,
                                     wait_cluster_status,
                                     wait_job_status)

pytestmark = requires_aws


def test_aws_vm_lifecycle():
    """launch -> exec -> stop -> start -> down on the cheapest EC2 VM:
    exercises RunInstances/Describe/Stop/Start/Terminate, the
    hashed-name keypair import, and the cluster security group."""
    name = smoke_name("awsvm")
    run_one_test(SmokeTest(
        name="aws_vm_lifecycle",
        commands=[
            f"{SKYTPU} launch -c {name} --cloud aws 'echo hello-aws' "
            f"--detach-run",
            wait_cluster_status(name, ["UP"]),
            wait_job_status(name, 1, ["SUCCEEDED"]),
            f"{SKYTPU} exec {name} 'hostname && echo exec-ok'",
            f"{SKYTPU} logs {name} 1 --no-follow | grep hello-aws",
            f"{SKYTPU} stop {name}",
            wait_cluster_status(name, ["STOPPED"], timeout_s=600),
            f"{SKYTPU} start {name}",
            wait_cluster_status(name, ["UP"], timeout_s=900),
        ],
        teardown=f"{SKYTPU} down {name} || true",
    ))


def test_aws_ports_security_group():
    """ports: must become SG ingress rules reachable from outside."""
    name = smoke_name("awsports")
    run_one_test(SmokeTest(
        name="aws_ports_security_group",
        commands=[
            f"cat > /tmp/{name}.yaml <<'EOF'\n"
            f"resources:\n  cloud: aws\n  ports: [8043]\n"
            f"run: timeout 600 python3 -m http.server 8043\n"
            f"EOF",
            f"{SKYTPU} launch -c {name} /tmp/{name}.yaml --detach-run",
            wait_cluster_status(name, ["UP"]),
            wait_job_status(name, 1, ["RUNNING"]),
            # External reachability through the SG rule.
            f"ip=$({SKYTPU} status --ip {name}) && "
            f"curl -sf --max-time 20 http://$ip:8043/ >/dev/null",
        ],
        teardown=f"{SKYTPU} down {name} || true",
    ))


def test_aws_spot_gpu():
    """Spot T4 via InstanceMarketOptions; nvidia-smi sees the GPU."""
    name = smoke_name("awsspot")
    run_one_test(SmokeTest(
        name="aws_spot_gpu",
        commands=[
            f"{SKYTPU} launch -c {name} --cloud aws "
            f"--gpus T4 --use-spot 'nvidia-smi -L' --detach-run",
            wait_cluster_status(name, ["UP"], timeout_s=1200),
            wait_job_status(name, 1, ["SUCCEEDED"]),
            f"{SKYTPU} logs {name} 1 --no-follow | grep -i tesla",
        ],
        teardown=f"{SKYTPU} down {name} || true",
    ))
