"""Live-GCP smoke tests: launch/exec/status/logs/autostop/down against
real credentials (reference: tests/smoke_tests/test_cluster_job.py,
incl. the TPU cases at :530-601). Skipped without SKYTPU_SMOKE=1 +
gcloud credentials — see smoke_utils.has_gcp_credentials.

Cost notes: the CPU tests use e2-small (~$0.02/h); the TPU test uses a
spot v5e-1 where available. Every test tears its cluster down in a
finally, pass or fail.
"""

import pytest

from tests.smoke.smoke_utils import (SKYTPU, SmokeTest, requires_gcp,
                                     run_one_test, smoke_name,
                                     wait_cluster_status,
                                     wait_job_status)

pytestmark = requires_gcp


def test_minimal_vm_lifecycle():
    """launch -> exec -> queue/logs -> stop -> start -> down on the
    cheapest VM (reference: test_cluster_job.py test_minimal)."""
    name = smoke_name("vm")
    run_one_test(SmokeTest(
        name="minimal_vm_lifecycle",
        commands=[
            f"{SKYTPU} launch -c {name} --cloud gcp 'echo hello-smoke' "
            f"--detach-run",
            wait_cluster_status(name, ["UP"]),
            wait_job_status(name, 1, ["SUCCEEDED"]),
            f"{SKYTPU} exec {name} 'hostname && echo exec-ok'",
            f"{SKYTPU} logs {name} 1 --no-follow | grep hello-smoke",
            f"{SKYTPU} stop {name}",
            wait_cluster_status(name, ["STOPPED"], timeout_s=600),
            f"{SKYTPU} start {name}",
            wait_cluster_status(name, ["UP"], timeout_s=900),
        ],
        teardown=f"{SKYTPU} down {name} || true",
    ))


def test_task_with_ports_firewall():
    """ports: in the task YAML must be reachable from outside the VPC
    (the r4 firewall path: skytpu-<cluster>-ports rule + network tag)."""
    name = smoke_name("ports")
    run_one_test(SmokeTest(
        name="task_with_ports_firewall",
        commands=[
            f"cat > /tmp/{name}.yaml <<'EOF'\n"
            f"resources:\n  cloud: gcp\n  ports: [8043]\n"
            # Serve in the foreground (bounded): a backgrounded server
            # dies with the job's process group at run-script exit.
            f"run: timeout 600 python3 -m http.server 8043\n"
            f"EOF",
            f"{SKYTPU} launch -c {name} /tmp/{name}.yaml --detach-run",
            wait_cluster_status(name, ["UP"]),
            wait_job_status(name, 1, ["RUNNING"]),
            # The rule must target the cluster's network tag (a rule
            # with the wrong targetTags would pass a name-only check
            # while blackholing traffic).
            f"gcloud compute firewall-rules describe "
            f"skytpu-{name}-ports --format='value(targetTags.list())' "
            f"| grep -x {name}",
            # The point of the firewall: reachable from OUTSIDE the
            # VPC — curl the VM's external IP from this machine, not
            # from the VM (localhost bypasses the firewall entirely).
            f"ip=$({SKYTPU} status {name} --ip) && ok= && "
            f"for i in $(seq 1 12); do "
            f"curl -s --max-time 10 \"http://$ip:8043/\" >/dev/null "
            f"&& ok=1 && break; sleep 5; done; "
            f"[ -n \"$ok\" ] && echo port-reachable-externally",
        ],
        teardown=f"{SKYTPU} down {name} || true",
    ))


def test_tpu_v5e_spot_slice():
    """A 1-chip spot v5e slice through the queued-resource path
    (reference: test_cluster_job.py:530-601 TPU cases; this exercises
    skypilot_tpu/provision/gcp.py queuedResources end-to-end)."""
    name = smoke_name("tpu")
    run_one_test(SmokeTest(
        name="tpu_v5e_spot_slice",
        commands=[
            f"{SKYTPU} launch -c {name} --cloud gcp "
            f"--gpus tpu-v5e-1 --use-spot --detach-run "
            f"'python3 -c \"import jax; print(jax.devices())\"'",
            wait_cluster_status(name, ["UP"], timeout_s=1800),
            wait_job_status(name, 1, ["SUCCEEDED", "FAILED"],
                            timeout_s=900),
            f"{SKYTPU} logs {name} 1 --no-follow | grep -i tpu",
        ],
        teardown=f"{SKYTPU} down {name} || true",
        timeout=40 * 60,
    ))


def test_autostop_fires_cluster_side():
    """-i 1: the skylet on the head must stop the cluster with the
    client gone (reference: test_cluster_job.py autostop case)."""
    name = smoke_name("astop")
    run_one_test(SmokeTest(
        name="autostop_fires_cluster_side",
        commands=[
            f"{SKYTPU} launch -c {name} --cloud gcp 'echo up' "
            f"-i 1 --detach-run",
            wait_cluster_status(name, ["UP"]),
            # No client activity; the cluster must stop itself.
            wait_cluster_status(name, ["STOPPED"], timeout_s=10 * 60,
                                poll_s=30),
        ],
        teardown=f"{SKYTPU} down {name} || true",
    ))


@pytest.mark.parametrize("store", ["gs"])
def test_storage_bucket_lifecycle(store):
    """Bucket create -> file mount -> delete via the storage CLI
    (reference: smoke storage tests)."""
    name = smoke_name(f"st-{store}")
    bucket = f"{name}-bkt"
    run_one_test(SmokeTest(
        name=f"storage_{store}_lifecycle",
        commands=[
            f"echo smoke-data > /tmp/{bucket}.txt",
            f"cat > /tmp/{name}.yaml <<EOF\n"
            f"resources:\n  cloud: gcp\n"
            f"file_mounts:\n  /data/in.txt: /tmp/{bucket}.txt\n"
            f"run: grep smoke-data /data/in.txt\n"
            f"EOF",
            f"{SKYTPU} launch -c {name} /tmp/{name}.yaml --detach-run",
            wait_cluster_status(name, ["UP"]),
            wait_job_status(name, 1, ["SUCCEEDED"]),
        ],
        teardown=f"{SKYTPU} down {name} || true",
    ))
