"""Live-cloud smoke-test DSL (reference:
tests/smoke_tests/smoke_tests_utils.py — Test NamedTuple + run_one_test
shell runner). A smoke test is an ordered list of shell commands run
serially against REAL cloud credentials; any nonzero exit fails the
test and the teardown always runs.

These tests are skipped unless GCP credentials and a project are
configured (`gcloud auth` + project) — the first user with a project
can validate provisioning end-to-end with:

    SKYTPU_SMOKE=1 pytest tests/smoke/ -v
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import subprocess
import sys
import time
import uuid
from typing import Dict, List, Optional

import pytest

DEFAULT_TIMEOUT_S = 25 * 60

# Suffix every cluster name so two smoke runs (or two users in one
# project) never collide.
_RUN_ID = uuid.uuid4().hex[:4]

SKYTPU = f"{sys.executable} -m skypilot_tpu.client.cli"


def has_gcp_credentials() -> bool:
    """Credentials + project present AND smoke explicitly requested —
    a `pytest tests/` in CI must never bill a cloud account by
    accident."""
    if not os.environ.get("SKYTPU_SMOKE"):
        return False
    if shutil.which("gcloud") is None:
        return False
    try:
        from skypilot_tpu.provision import gcp_auth
        return bool(gcp_auth.get_project()) and \
            bool(gcp_auth.get_access_token())
    except Exception:  # noqa: BLE001
        return False


requires_gcp = pytest.mark.skipif(
    not has_gcp_credentials(),
    reason="live-GCP smoke test: set SKYTPU_SMOKE=1 with gcloud "
           "credentials and a project configured")


def smoke_name(prefix: str) -> str:
    return f"smk-{prefix}-{_RUN_ID}"


@dataclasses.dataclass
class SmokeTest:
    name: str
    commands: List[str]          # serial; first failure stops the test
    teardown: Optional[str] = None   # always runs
    timeout: int = DEFAULT_TIMEOUT_S
    env: Optional[Dict[str, str]] = None


def wait_cluster_status(cluster: str, statuses: List[str],
                        timeout_s: int = 900, poll_s: int = 15) -> str:
    """Shell snippet: poll `skytpu status` until the cluster shows one
    of ``statuses`` (reference: smoke_tests_utils.py
    get_cmd_wait_until_cluster_status_contains)."""
    pat = r"\|".join(statuses)
    return (
        f"end=$(( $(date +%s) + {timeout_s} )); "
        f"while [ $(date +%s) -lt $end ]; do "
        f"s=$({SKYTPU} status {cluster} 2>/dev/null); echo \"$s\"; "
        f"echo \"$s\" | grep -E '{pat}' && exit 0; "
        f"sleep {poll_s}; done; "
        f"echo 'TIMEOUT waiting for {'/'.join(statuses)}'; exit 1")


def wait_job_status(cluster: str, job_id: int, statuses: List[str],
                    timeout_s: int = 900, poll_s: int = 10) -> str:
    pat = r"\|".join(statuses)
    return (
        f"end=$(( $(date +%s) + {timeout_s} )); "
        f"while [ $(date +%s) -lt $end ]; do "
        f"q=$({SKYTPU} queue {cluster} 2>/dev/null); echo \"$q\"; "
        f"echo \"$q\" | grep -E '^ *{job_id} .*({pat})' && exit 0; "
        f"sleep {poll_s}; done; "
        f"echo 'TIMEOUT waiting for job {job_id}'; exit 1")


def run_one_test(test: SmokeTest) -> None:
    """Run the commands serially through bash, streaming output; the
    teardown runs regardless of pass/fail (billable resources must not
    outlive a red test)."""
    env = dict(os.environ, **(test.env or {}))
    failed_cmd = None
    try:
        for cmd in test.commands:
            print(f"[{test.name}] $ {cmd}", file=sys.stderr, flush=True)
            t0 = time.time()
            proc = subprocess.run(["bash", "-c", cmd], env=env,
                                  timeout=test.timeout)
            print(f"[{test.name}] rc={proc.returncode} "
                  f"({time.time() - t0:.0f}s)", file=sys.stderr,
                  flush=True)
            if proc.returncode != 0:
                failed_cmd = cmd
                break
    finally:
        if test.teardown:
            print(f"[{test.name}] teardown: {test.teardown}",
                  file=sys.stderr, flush=True)
            subprocess.run(["bash", "-c", test.teardown], env=env,
                           timeout=test.timeout)
    assert failed_cmd is None, \
        f"smoke test {test.name} failed at: {failed_cmd}"


def has_aws_credentials() -> bool:
    """AWS keys present AND smoke explicitly requested (same accident
    guard as GCP: a bare `pytest tests/` must never bill an account)."""
    if not os.environ.get("SKYTPU_SMOKE"):
        return False
    try:
        from skypilot_tpu.provision import aws_auth
        return aws_auth.load_credentials() is not None
    except Exception:  # noqa: BLE001
        return False


requires_aws = pytest.mark.skipif(
    not has_aws_credentials(),
    reason="live AWS smoke needs SKYTPU_SMOKE=1 + AWS credentials")
