"""Offline optimizer/resources/catalog tests (the reference's dryrun-suite
pattern: real catalog data, no cloud calls)."""

import pytest

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions, optimizer
from skypilot_tpu.catalog import catalog
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task


def _task(accel=None, **kw):
    t = Task(name="t")
    t.set_resources(Resources(accelerators=accel, **kw))
    return t


def test_catalog_tpu_info():
    info = catalog.tpu_slice_info("tpu-v5e-16")
    assert info == {"chips": 16, "hosts": 2}
    info = catalog.tpu_slice_info("tpu-v5p-16")  # 16 cores = 8 chips
    assert info == {"chips": 8, "hosts": 2}


def test_catalog_prices_scale_with_chips():
    c8 = catalog.get_hourly_cost("tpu-v5e-8")
    c16 = catalog.get_hourly_cost("tpu-v5e-16")
    assert abs(c16 - 2 * c8) < 1e-6


def test_optimizer_picks_cheapest_zone():
    r = optimizer.optimize_task(_task("tpu-v5e-8"))
    assert r.cloud == "gcp"
    assert r.region.startswith("us")  # us cheaper than europe/asia
    assert r.price == catalog.get_hourly_cost("tpu-v5e-8")


def test_optimizer_spot_cheaper():
    on_demand = optimizer.optimize_task(_task("tpu-v5e-8"))
    spot = optimizer.optimize_task(_task("tpu-v5e-8", use_spot=True))
    assert spot.price < on_demand.price


def test_optimizer_reserved_capacity_wins(monkeypatch):
    """Reserved nodes are already paid for: a zone holding a matching
    reservation costs 0 there and beats the nominally cheapest zone
    (reference: sky/optimizer.py:345-355)."""
    from skypilot_tpu import config as config_lib
    from skypilot_tpu.provision import gcp
    baseline = optimizer.optimize_task(_task(instance_type="n2-standard-8"))
    assert baseline.region.startswith("us")

    config_lib.set_nested(("gcp", "specific_reservations"), ["res-eu"])
    try:
        def fake_avail(zone, instance_type=None):
            if zone == "europe-west4-a" and \
                    instance_type == "n2-standard-8":
                return {"res-eu": 4}
            return {}

        monkeypatch.setattr(gcp, "list_reservations_available",
                            fake_avail)
        chosen = optimizer.optimize_task(_task(instance_type="n2-standard-8"))
        assert chosen.zone == "europe-west4-a"
        # Spot candidates never consume reservations.
        spot = optimizer.optimize_task(
            _task(instance_type="n2-standard-8", use_spot=True))
        assert spot.region.startswith("us")
    finally:
        config_lib.set_nested(("gcp", "specific_reservations"), None)


def test_optimizer_blocklist_failover():
    first = optimizer.optimize_task(_task("tpu-v5e-8"))
    blocked = {("gcp", first.region, first.zone)}
    second = optimizer.optimize_task(_task("tpu-v5e-8"), blocked)
    assert (second.region, second.zone) != (first.region, first.zone)
    assert second.price >= first.price

    # Block the whole cloud -> unavailable.
    with pytest.raises(exceptions.ResourcesUnavailableError):
        optimizer.optimize_task(_task("tpu-v5e-8"), {("gcp", None, None)})


def test_optimizer_region_pin():
    r = optimizer.optimize_task(_task("tpu-v6e-8", region="europe-west4"))
    assert r.region == "europe-west4"


def test_optimizer_gpu_and_cpu():
    r = optimizer.optimize_task(_task("A100:8"))
    assert r.instance_type == "a2-highgpu-8g"  # GCP A100 beats EC2 p4d
    # Cross-cloud arbitrage: the cheapest 8-vCPU VM is an EC2 m6i
    # ($0.384 vs n2-standard-8 $0.389); pinning the cloud restores n2.
    r = optimizer.optimize_task(_task(None, cpus="8+"))
    assert r.cloud == "aws" and r.instance_type == "m6i.2xlarge"
    r = optimizer.optimize_task(_task(None, cpus="8+", cloud="gcp"))
    assert r.instance_type.startswith("n2-")


def test_chain_dag_prefers_same_region():
    """Downstream task should co-locate with upstream when prices tie."""
    a, b = _task("tpu-v5e-8"), Task(name="b")
    b.set_resources(Resources(accelerators="tpu-v5e-8"))
    d = dag_lib.Dag()
    with d:
        a >> b
    plan = optimizer.optimize(d)
    assert plan[a].cloud == plan[b].cloud == "gcp"


def _plan_cost(d, plan):
    """Objective value of a plan (node costs + egress edges), using the
    optimizer's own terms."""
    total = 0.0
    for t in d.tasks:
        total += plan[t].get_cost(optimizer.DEFAULT_RUNTIME_ESTIMATE_S)
    for u, v in d.graph.edges:
        total += optimizer._egress_cost(plan[u], plan[v],
                                        u.estimated_outputs_gb or 0.0)
    return total


def _brute_force(d, blocked=None):
    """Exact reference: enumerate every candidate assignment."""
    import itertools
    order = d.topological_order()
    per = {t: [c.resources for c in
               optimizer._candidates_for(t, blocked or set())]
           for t in order}
    best, best_plan = None, None
    for combo in itertools.product(*(per[t] for t in order)):
        plan = dict(zip(order, combo))
        cost = _plan_cost(d, plan)
        if best is None or cost < best:
            best, best_plan = cost, plan
    return best, best_plan


def _cpu_task(name, outputs_gb=None):
    t = Task(name=name)
    t.set_resources(Resources(instance_type="n2-standard-8"))
    if outputs_gb:
        t.estimated_outputs_gb = outputs_gb
    return t


def test_fanout_tree_dag_is_exact():
    """Fan-out (1 root -> 2 children) is no longer rejected; the tree
    DP matches the brute-force optimum, co-locating children with the
    root when egress dominates."""
    root = _cpu_task("root", outputs_gb=500.0)
    kids = [_cpu_task("k1"), _cpu_task("k2")]
    d = dag_lib.Dag()
    for k in kids:
        d.add_edge(root, k)
    plan = optimizer.optimize(d)
    want_cost, _ = _brute_force(d)
    assert abs(_plan_cost(d, plan) - want_cost) < 1e-9
    assert plan[kids[0]].region == plan[root].region
    assert plan[kids[1]].region == plan[root].region


def test_diamond_dag_refines_to_optimum():
    """Multi-parent diamond (A -> B,C -> D): coordinate descent finds
    the brute-force optimum on this instance."""
    a = _cpu_task("a", outputs_gb=200.0)
    b = _cpu_task("b", outputs_gb=200.0)
    c = _cpu_task("c", outputs_gb=200.0)
    dd = _cpu_task("d")
    d = dag_lib.Dag()
    d.add_edge(a, b)
    d.add_edge(a, c)
    d.add_edge(b, dd)
    d.add_edge(c, dd)
    plan = optimizer.optimize(d)
    want_cost, _ = _brute_force(d)
    assert abs(_plan_cost(d, plan) - want_cost) < 1e-9
    regions = {plan[t].region for t in (a, b, c, dd)}
    assert len(regions) == 1  # egress dominates -> co-located


def test_general_dag_without_egress_is_per_task_argmin():
    a, b, c = _cpu_task("a"), _cpu_task("b"), _cpu_task("c")
    d = dag_lib.Dag()
    d.add_edge(a, c)
    d.add_edge(b, c)
    plan = optimizer.optimize(d)
    for t in (a, b, c):
        solo = optimizer.optimize_task(t)
        assert plan[t].price == solo.price


def test_time_target_minimizes_makespan_not_sum():
    """Fan-out under TIME: branches run in parallel, so the plan must
    minimize the longest branch (makespan), not the branch-time sum.
    Cross-region edges are prohibitive, so children follow the root:
    root@r1 gives branch times (10, 300) — sum 310, makespan 300;
    root@r2 gives (155, 160) — sum 315, makespan 160. A sum objective
    picks r1 and finishes 140s later."""
    import unittest.mock as mock
    root, a, b = _cpu_task("root"), _cpu_task("a"), _cpu_task("b")
    d = dag_lib.Dag()
    d.add_edge(root, a)
    d.add_edge(root, b)

    times = {("root", "r1"): 1.0, ("root", "r2"): 1.0,
             ("a", "r1"): 10.0, ("a", "r2"): 155.0,
             ("b", "r1"): 300.0, ("b", "r2"): 160.0}

    def fake_cands(t, blocked, reserved_cache=None):
        out = []
        for region in ("r1", "r2"):
            res = Resources(instance_type="n2-standard-8")
            object.__setattr__(res, "region", region)
            object.__setattr__(res, "zone", region + "-a")
            out.append(optimizer.Candidate(
                res, cost=1.0, time_s=times[(t.name, region)]))
        return out

    def cross_region_edge(ra, rb, gb):
        return 0.0 if ra.region == rb.region else 1e6

    with mock.patch.object(optimizer, "_candidates_for",
                           side_effect=fake_cands), \
         mock.patch.object(optimizer, "_egress_time",
                           cross_region_edge):
        plan = optimizer.optimize(
            d, minimize=optimizer.OptimizeTarget.TIME)
    assert plan[root].region == "r2"
    assert plan[a].region == "r2" and plan[b].region == "r2"
    a, b = _cpu_task("a"), _cpu_task("b")
    d = dag_lib.Dag()
    d.add_edge(a, b)
    d.add_edge(b, a)
    with pytest.raises(exceptions.InvalidTaskError):
        optimizer.optimize(d)


def test_resources_yaml_roundtrip():
    r = Resources.from_yaml_config({
        "accelerators": "tpu-v5p-16", "use_spot": True,
        "region": "us-east5"})
    assert r.accelerators == "tpu-v5p-16"
    assert r.runtime_version == "v2-alpha-tpuv5"
    cfg = r.to_yaml_config()
    r2 = Resources.from_yaml_config(cfg)
    assert r2 == r


def test_resources_dict_accelerator_form():
    r = Resources.from_yaml_config({"accelerators": {"A100": 8}})
    assert r.accelerators == "A100:8"


def test_resources_rejects_unknown_fields():
    with pytest.raises(exceptions.InvalidTaskError):
        Resources.from_yaml_config({"acelerators": "tpu-v5e-8"})


def test_less_demanding_than():
    small = Resources(accelerators="A100:4")
    big = Resources(accelerators="A100:8", cloud="gcp")
    assert small.less_demanding_than(big)
    assert not big.less_demanding_than(small)


def test_task_yaml_roundtrip(tmp_path):
    cfg = {
        "name": "train",
        "resources": {"accelerators": "tpu-v5e-8"},
        "num_nodes": 1,
        "setup": "echo setup",
        "run": "echo run",
        "envs": {"FOO": "bar"},
    }
    t = Task.from_yaml_config(cfg)
    assert t.resources[0].accelerators == "tpu-v5e-8"
    p = tmp_path / "task.yaml"
    t.to_yaml(str(p))
    t2 = Task.from_yaml(str(p))
    assert t2.name == "train"
    assert t2.envs == {"FOO": "bar"}
    assert t2.resources[0].accelerators == "tpu-v5e-8"


def test_task_rejects_unknown_fields():
    with pytest.raises(exceptions.InvalidTaskError):
        Task.from_yaml_config({"name": "x", "nodes": 2})


def test_hosts_per_node():
    assert Resources(accelerators="tpu-v5e-32").hosts_per_node == 4
    assert Resources(accelerators="A100:8").hosts_per_node == 1


def test_egress_steers_chain_to_same_region():
    """VERDICT r1 #8 done-when: a cross-region chain picks the cheaper
    same-region plan because of a nonzero egress term."""
    from skypilot_tpu.catalog import catalog
    a = Task(name="prod", run="true")
    a.set_resources(Resources(accelerators="tpu-v5e-8",
                              region="us-central1"))
    a.estimated_outputs_gb = 5000.0  # 5 TB handed to the consumer
    b = Task(name="cons", run="true")
    b.set_resources(Resources(accelerators="tpu-v5e-8"))
    d = dag_lib.Dag()
    with d:
        a >> b
    plan = optimizer.optimize(d)
    # Without egress, the cheapest v5e-8 region wins regardless of a's
    # region; 5TB * $0.12/GB = $600 of egress dwarfs any price delta,
    # so b must co-locate.
    assert plan[b].region == "us-central1"

    # Control: with negligible data, b is free to pick its own cheapest.
    a.estimated_outputs_gb = 0.0
    plan2 = optimizer.optimize(d)
    cheapest = min(
        (c for c in optimizer._candidates_for(b, set())),
        key=lambda c: c.cost)
    assert plan2[b].price == cheapest.resources.price


def test_runtime_scales_with_accelerator_units():
    """estimated_runtime_seconds is v5e-chip-equivalent work: a bigger
    slice finishes proportionally sooner, so same-$/chip-hour offerings
    cost the same while wall time differs."""
    t8 = Task(name="w8", run="true")
    t8.set_resources(Resources(accelerators="tpu-v5e-8"))
    t8.estimated_runtime_seconds = 3600.0
    c8 = min(optimizer._candidates_for(t8, set()), key=lambda c: c.cost)

    t16 = Task(name="w16", run="true")
    t16.set_resources(Resources(accelerators="tpu-v5e-16"))
    t16.estimated_runtime_seconds = 3600.0
    c16 = min(optimizer._candidates_for(t16, set()), key=lambda c: c.cost)

    assert c16.time_s == pytest.approx(c8.time_s / 2)
    assert c16.cost == pytest.approx(c8.cost, rel=0.05)

    # Without an estimate, the default is a flat DURATION: no scaling.
    t16.estimated_runtime_seconds = None
    flat = min(optimizer._candidates_for(t16, set()), key=lambda c: c.cost)
    assert flat.time_s == optimizer.DEFAULT_RUNTIME_ESTIMATE_S


def test_cross_cloud_failover_blocklist():
    """The reference's core value prop (SURVEY §0): when one cloud is
    blocked wholesale (capacity/quota exhausted across its regions),
    re-optimization lands the SAME task on the other cloud."""
    t = _task("A100:8")
    first = optimizer.optimize_task(t)
    assert first.cloud == "gcp"                 # cheapest A100:8 overall
    r = optimizer.optimize_task(t, blocked_resources={("gcp", None, None)})
    assert r.cloud == "aws"
    assert r.instance_type == "p4d.24xlarge"
    # Both clouds blocked -> clean ResourcesUnavailableError.
    with pytest.raises(exceptions.ResourcesUnavailableError):
        optimizer.optimize_task(t, blocked_resources={
            ("gcp", None, None), ("aws", None, None)})


def test_cross_cloud_spot_arbitrage():
    """EC2 spot discounts run deeper than GCP's: the same GPU class can
    flip clouds between on-demand and spot."""
    od = optimizer.optimize_task(_task("H100:8"))
    spot = optimizer.optimize_task(_task("H100:8", use_spot=True))
    assert od.cloud == "gcp"        # a3-highgpu-8g undercuts p5 on-demand
    assert spot.cloud == "aws"      # p5 spot undercuts a3 spot


def test_enabled_cloud_cache_gates_candidates(tmp_path, monkeypatch):
    """Once a credential check has run, disabled clouds drop out of the
    candidate set (reference: optimizer candidates come only from
    enabled clouds); without a cache every catalog cloud stays in so
    offline dryruns need no credentials."""
    import json
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path))
    free = optimizer.optimize_task(_task(None, cpus="8+"))
    assert free.cloud == "aws"          # no cache: cheapest overall
    with open(tmp_path / "enabled_clouds.json", "w") as f:
        json.dump({"enabled": ["gcp", "local"]}, f)
    gated = optimizer.optimize_task(_task(None, cpus="8+"))
    assert gated.cloud == "gcp"
    with pytest.raises(exceptions.ResourcesUnavailableError,
                       match="not enabled"):
        optimizer.optimize_task(_task(None, cpus="8+", cloud="aws"))
    # Any-of lists FALL THROUGH a disabled pinned cloud to the next
    # feasible option instead of aborting the whole optimize.
    t = Task(name="anyof")
    t.set_resources([Resources(cloud="aws", cpus="8+"),
                     Resources(cloud="gcp", cpus="8+")])
    assert optimizer.optimize_task(t).cloud == "gcp"
    # Catalog clouds all disabled -> clear error, not empty plan.
    with open(tmp_path / "enabled_clouds.json", "w") as f:
        json.dump({"enabled": ["local"]}, f)
    with pytest.raises(exceptions.ResourcesUnavailableError,
                       match="skytpu check"):
        optimizer.optimize_task(_task(None, cpus="8+"))
    # A malformed cache degrades to "no check has run", not a crash.
    with open(tmp_path / "enabled_clouds.json", "w") as f:
        f.write('{"enabled": null}')
    assert optimizer.optimize_task(_task(None, cpus="8+")).cloud == "aws"
    # Local tasks stay unaffected by the gate.
    with open(tmp_path / "enabled_clouds.json", "w") as f:
        json.dump({"enabled": ["local"]}, f)
    assert optimizer.optimize_task(
        _task(None, cloud="local")).cloud == "local"
