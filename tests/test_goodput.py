"""Training goodput forensics: the step-phase ledger's exact
partition, counter<->record consistency, restart-surviving stamps,
the loss/grad anomaly watchdog, the train-goodput-floor SLO rule and
the `skytpu train-why` / `skytpu top` surfaces
(docs/observability.md §Training goodput forensics)."""

import json
import math
import os
import time

import pytest

from skypilot_tpu.observability import flight as fl
from skypilot_tpu.observability import forensics
from skypilot_tpu.observability import goodput as gp_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import slo, tracing


def _counter_delta(before, after, name):
    def total(snap):
        if name not in snap:
            return 0.0
        return sum(s.get("value", s.get("count", 0))
                   for s in snap[name]["samples"])
    return total(after) - total(before)


def _drive_steps(gp, n_steps=3, tokens=64, sleep=0.004):
    """Drive the recorder the way run.py does; returns the records."""
    recs = []
    for step in range(n_steps):
        gp.step_start(step)
        with gp.phase("data_wait"):
            time.sleep(sleep)
        with gp.phase("compute"):
            time.sleep(2 * sleep)
        with gp.phase("eval"):
            time.sleep(sleep / 2)
        rec = gp.step_end(tokens=tokens, loss=2.0 - 0.1 * step)
        recs.append(rec)
    return recs


# ---------------------------------------------------------------------------
# The exact-partition invariants.

def test_step_record_phases_sum_to_wall():
    rec = fl.FlightRecorder()
    gp = gp_lib.GoodputRecorder(recorder=rec, host="0",
                                param_count=1000, enable=True)
    records = _drive_steps(gp, n_steps=4)
    assert len(records) == 4
    for r in records:
        # phases (ms) sum to dur_s exactly — host_other carries the
        # remainder, never silence.
        assert sum(r["phases"].values()) == \
            pytest.approx(r["dur_s"] * 1e3, abs=0.05)
        assert r["phases"].get("host_other", 0.0) >= 0.0
        assert {"data_wait", "compute", "eval"} <= set(r["phases"])
    # Warmup semantics: first step cold, rest warm; only warm steps
    # carry the device attribution fields.
    assert records[0]["warm"] is False
    assert "flops" not in records[0] and "dev_ms_est" not in records[0]
    for r in records[1:]:
        assert r["warm"] is True
        assert r["flops"] == 6 * 1000 * 64
        assert r["dev_ms_est"] > 0


def test_ledger_sums_gate(tmp_path, monkeypatch):
    """The tier-1 ledger gate: loaded back off disk, every train_step
    ledger's phases sum to its wall and the named (non-host_other)
    share dominates a sleep-phased run."""
    monkeypatch.setenv(tracing.EVENTS_DIR_ENV_VAR, str(tmp_path))
    rec = fl.FlightRecorder()
    gp = gp_lib.GoodputRecorder(recorder=rec, host="0", enable=True)
    _drive_steps(gp, n_steps=3, sleep=0.01)
    rec.flush()
    records = fl.load_records(dirs=[str(tmp_path)])
    train = gp_lib.train_records(records)
    assert len(train) == 3
    for r in train:
        led = gp_lib.ledger_for_step(records, step=r["step"])
        assert led is not None
        assert sum(p["ms"] for p in led["phases"]) == \
            pytest.approx(led["wall_ms"], abs=0.05)
        assert led["named_ms"] >= 0.90 * led["wall_ms"]
    summary = gp_lib.summarize_steps(records)
    assert summary["steps"] == 3
    assert sum(p["ms"] for p in summary["phases"]) == \
        pytest.approx(summary["wall_ms"], abs=0.2)
    # Renderers carry the sum-equals-wall footer.
    assert "sum (= wall)" in gp_lib.render_step_ledger(
        gp_lib.ledger_for_step(records))
    assert "named" in gp_lib.render_summary(summary)


def test_counter_deltas_match_record_sums():
    """Counters and records are incremented on the SAME path with the
    SAME values — a drift means double counting somewhere."""
    before = metrics_lib.REGISTRY.snapshot()
    rec = fl.FlightRecorder()
    gp = gp_lib.GoodputRecorder(recorder=rec, host="gate-host",
                                param_count=500, enable=True)
    records = _drive_steps(gp, n_steps=4, tokens=32)
    snap = gp.snapshot()
    after = metrics_lib.REGISTRY.snapshot()
    flops = sum(r.get("flops", 0) for r in records)
    assert flops == 3 * 6 * 500 * 32          # warm steps only
    assert _counter_delta(before, after,
                          "skytpu_device_flops_total") == flops
    dev_s = sum(r.get("dev_ms_est", 0.0) for r in records) / 1e3
    # dev_ms_est is rounded to 1e-4 ms on the record; counters take
    # the raw value.
    assert _counter_delta(before, after,
                          "skytpu_device_seconds_total") == \
        pytest.approx(dev_s, abs=1e-6)
    assert snap["tokens"] == sum(r["toks"] for r in records)
    assert snap["steps"] == len(records)
    # The counter-level partition: wall == productive + unproductive.
    wall = _counter_delta(before, after,
                          "skytpu_train_wall_seconds_total")
    prod = _counter_delta(before, after,
                          "skytpu_train_productive_seconds_total")
    unprod = _counter_delta(
        before, after, "skytpu_train_unproductive_seconds_total")
    assert wall == pytest.approx(prod + unprod, abs=1e-9)
    # Warm compute credited productive; the cold step's compute went
    # to warmup_compile, so both sides are non-zero.
    assert prod > 0 and unprod > 0


def test_snapshot_buckets_sum_to_elapsed():
    gp = gp_lib.GoodputRecorder(recorder=fl.FlightRecorder(),
                                host="0", enable=True)
    with gp.account("restart_replay"):
        time.sleep(0.01)
    _drive_steps(gp, n_steps=2)
    snap = gp.snapshot()
    assert sum(snap["buckets"].values()) == \
        pytest.approx(snap["elapsed_s"], abs=1e-6)
    assert snap["buckets"]["restart_replay"] >= 0.01
    assert 0.0 <= snap["goodput_ratio"] <= 1.0
    assert snap["goodput_ratio"] == pytest.approx(
        snap["buckets"]["productive"] / snap["elapsed_s"])


def test_disabled_recorder_is_noop():
    rec = fl.FlightRecorder()
    gp = gp_lib.GoodputRecorder(recorder=rec, enable=False)
    gp.step_start(0)
    with gp.phase("compute"):
        pass
    with gp.account("ckpt_stall"):
        pass
    assert gp.step_end(tokens=8) is None
    assert rec.tail() == []
    monkey_state = gp.snapshot()
    assert monkey_state["buckets"]["productive"] == 0.0


def test_env_disable(monkeypatch):
    monkeypatch.setenv("SKYTPU_GOODPUT", "0")
    assert gp_lib.GoodputRecorder(recorder=fl.FlightRecorder()) \
        .enabled is False
    monkeypatch.delenv("SKYTPU_GOODPUT")
    assert gp_lib.GoodputRecorder(recorder=fl.FlightRecorder()) \
        .enabled is True


def test_unknown_phase_and_bucket_rejected():
    gp = gp_lib.GoodputRecorder(recorder=fl.FlightRecorder(),
                                enable=True)
    with pytest.raises(ValueError):
        with gp.phase("mystery"):
            pass
    with pytest.raises(ValueError):
        with gp.account("mystery"):
            pass


# ---------------------------------------------------------------------------
# Restart-surviving stamps.

def test_stamps_persist_and_fold_across_restart(tmp_path):
    gp = gp_lib.GoodputRecorder(recorder=fl.FlightRecorder(),
                                host="0", enable=True)
    _drive_steps(gp, n_steps=2, tokens=16)
    assert gp.persist(str(tmp_path)) is True
    stamps = json.load(open(tmp_path / gp_lib.STAMPS_FILE))
    assert stamps["steps"] == 2 and stamps["tokens"] == 32
    # The next incarnation folds the priors in additively.
    gp2 = gp_lib.GoodputRecorder(recorder=fl.FlightRecorder(),
                                 host="0", enable=True)
    assert gp2.load_stamps(str(tmp_path)) is True
    _drive_steps(gp2, n_steps=1, tokens=16)
    snap = gp2.snapshot()
    assert snap["steps"] == 3 and snap["tokens"] == 48
    assert snap["elapsed_s"] > stamps["elapsed_s"]
    assert sum(snap["buckets"].values()) == \
        pytest.approx(snap["elapsed_s"], abs=1e-6)


def test_stamps_corrupt_or_missing_is_fresh_start(tmp_path):
    gp = gp_lib.GoodputRecorder(recorder=fl.FlightRecorder(),
                                enable=True)
    assert gp.load_stamps(str(tmp_path)) is False
    (tmp_path / gp_lib.STAMPS_FILE).write_text("{not json")
    assert gp.load_stamps(str(tmp_path)) is False
    (tmp_path / gp_lib.STAMPS_FILE).write_text("[1, 2]")
    assert gp.load_stamps(str(tmp_path)) is False
    # Disabled recorders never write.
    off = gp_lib.GoodputRecorder(recorder=fl.FlightRecorder(),
                                 enable=False)
    assert off.persist(str(tmp_path / "off")) is False
    assert not (tmp_path / "off").exists()


# ---------------------------------------------------------------------------
# The anomaly watchdog.

@pytest.fixture
def fresh_events(tmp_path, monkeypatch):
    monkeypatch.setenv(tracing.EVENTS_DIR_ENV_VAR, str(tmp_path))
    monkeypatch.delenv(tracing.ENV_VAR, raising=False)
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    monkeypatch.setenv("SKYTPU_INCIDENT_MIN_INTERVAL_S", "0")
    forensics._last_capture_s = 0.0
    tracing._reset_for_tests()
    yield str(tmp_path)
    tracing._reset_for_tests()


def _anomaly_events():
    return [r for r in tracing.buffered_records()
            if r.get("name") == "train.anomaly"]


def test_nan_latch_exactly_one_event_and_bundle(fresh_events):
    rec = fl.FlightRecorder()
    rec.record("train_step", step=1, dur_s=0.01)
    wd = gp_lib.AnomalyWatchdog(recorder=rec)
    before = metrics_lib.REGISTRY.snapshot()
    for step in range(5):
        wd.observe(step, 2.0 - 0.01 * step)
    # One NaN excursion spanning three logging ticks: ONE event, ONE
    # bundle, ONE counter inc — however long the excursion lasts.
    info = wd.observe(5, float("nan"))
    assert info["kind"] == "non_finite" and info["signal"] == "loss"
    assert wd.observe(6, float("nan")) is None
    assert wd.observe(7, float("inf")) is None
    after = metrics_lib.REGISTRY.snapshot()
    assert _counter_delta(before, after,
                          "skytpu_train_anomalies_total") == 1
    assert len(_anomaly_events()) == 1
    base = forensics.incidents_dir()
    bundles = [n for n in os.listdir(base)
               if n.endswith("train-anomaly-non_finite")]
    assert len(bundles) == 1
    assert info["incident"] in bundles
    # The bundle froze the ring tail from before the divergence.
    flight_tail = open(os.path.join(
        base, bundles[0], "flight.jsonl")).read()
    assert json.loads(flight_tail.splitlines()[0])["step"] == 1
    # Finite values re-arm the latch; the NEXT excursion fires again.
    assert wd.observe(8, 1.9) is None
    info2 = wd.observe(9, float("nan"))
    assert info2 is not None and info2["kind"] == "non_finite"
    assert len(_anomaly_events()) == 2


def test_nan_grad_fires_and_never_poisons_estimators(fresh_events):
    wd = gp_lib.AnomalyWatchdog(recorder=fl.FlightRecorder())
    wd.observe(0, 2.0, grad_norm=1.0)
    info = wd.observe(1, 2.0, grad_norm=float("inf"))
    assert info["kind"] == "non_finite" and info["signal"] == "grad_norm"
    # The poisoned sample never entered the last-value state.
    assert wd._last_grad == 1.0
    assert math.isfinite(wd._last_loss)


def test_spike_detection_and_cooldown(fresh_events):
    wd = gp_lib.AnomalyWatchdog(min_samples=5, cooldown_steps=10,
                                spike_factor=4.0,
                                recorder=fl.FlightRecorder())
    step = 0
    for _ in range(12):                # stable deltas ~0.01
        wd.observe(step, 2.0 + 0.01 * (step % 2))
        step += 1
    info = wd.observe(step, 12.0)      # |delta| ~10 >> 4 x p99
    assert info is not None and info["kind"] == "loss_spike"
    assert info["delta"] > info["threshold"]
    # Inside the cooldown a second excursion is suppressed.
    assert wd.observe(step + 1, 30.0) is None
    assert len(_anomaly_events()) == 1


def test_spike_needs_min_samples(fresh_events):
    wd = gp_lib.AnomalyWatchdog(min_samples=50,
                                recorder=fl.FlightRecorder())
    for step in range(10):
        wd.observe(step, 2.0)
    # A huge delta before the estimator warms up never fires.
    assert wd.observe(10, 100.0) is None


def test_anomaly_pause_lands_in_open_step_ledger(fresh_events):
    rec = fl.FlightRecorder()
    gp = gp_lib.GoodputRecorder(recorder=rec, host="0", enable=True)
    wd = gp_lib.AnomalyWatchdog(recorder=rec, goodput=gp)
    gp.step_start(0)
    with gp.phase("compute"):
        pass
    assert wd.observe(0, float("nan"))["kind"] == "non_finite"
    r = gp.step_end(tokens=1)
    assert r["phases"].get("anomaly_pause", 0.0) >= 0.0
    assert "anomaly_pause" in r["phases"]


# ---------------------------------------------------------------------------
# The train-goodput-floor SLO rule.

def test_goodput_floor_rule_registered():
    (rule,) = [r for r in slo.DEFAULT_RULES
               if r.name == "train-goodput-floor"]
    assert rule.kind == "ratio"
    assert rule.metric == "skytpu_train_unproductive_seconds_total"
    assert rule.denominator == "skytpu_train_wall_seconds_total"
    assert rule.exclude_labels == {"bucket": ["warmup_compile"]}


def _goodput_fams(wall, input_bound, warmup):
    return {
        "skytpu_train_wall_seconds_total": {
            "type": "counter", "samples": [({}, float(wall))]},
        "skytpu_train_unproductive_seconds_total": {
            "type": "counter", "samples": [
                ({"bucket": "input_bound"}, float(input_bound)),
                ({"bucket": "warmup_compile"}, float(warmup))]},
    }


def test_goodput_floor_breach_and_warmup_exclusion():
    (base,) = [r for r in slo.DEFAULT_RULES
               if r.name == "train-goodput-floor"]
    rule = slo.SloRule.from_dict({**base.to_dict(),
                                  "short_window_s": 10,
                                  "long_window_s": 30})
    # Sustained input-bound badput above half of wall: breach.
    wd = slo.Watchdog(rules=[rule])
    t0 = time.time() - 100
    wd.observe(_goodput_fams(100, 10, 50), [], ts=t0)
    wd.observe(_goodput_fams(140, 20, 50), [], ts=t0 + 35)
    ev = wd.observe(_goodput_fams(240, 95, 50), [], ts=t0 + 70)
    assert [e["event"] for e in ev] == ["slo.breach"]
    # The same wall dominated by warmup compile never pages — a cold
    # start is expected badput, not an incident.
    wd2 = slo.Watchdog(rules=[rule])
    wd2.observe(_goodput_fams(100, 1, 10), [], ts=t0)
    wd2.observe(_goodput_fams(140, 3, 40), [], ts=t0 + 35)
    assert wd2.observe(_goodput_fams(200, 5, 90), [],
                       ts=t0 + 70) == []


# ---------------------------------------------------------------------------
# CLI surfaces: `skytpu train-why` and the `skytpu top` train columns.

def test_train_why_cli(fresh_events):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli as cli_mod
    rec = fl.FlightRecorder()
    gp = gp_lib.GoodputRecorder(recorder=rec, host="0", enable=True)
    _drive_steps(gp, n_steps=3)
    rec.flush()
    runner = CliRunner()
    res = runner.invoke(cli_mod.cli, ["train-why"])
    assert res.exit_code == 0, res.output
    assert "train step 2" in res.output
    assert "sum (= wall)" in res.output
    assert "compute" in res.output
    # A specific step, and the machine-readable form.
    res = runner.invoke(cli_mod.cli, ["train-why", "--step", "1"])
    assert res.exit_code == 0 and "train step 1" in res.output
    res = runner.invoke(cli_mod.cli, ["train-why", "--json"])
    assert res.exit_code == 0
    payload = json.loads(res.output)
    assert payload["ledger"]["step"] == 2
    assert payload["summary"]["steps"] == 3
    # An unrecorded step is a clear error, not an empty table.
    res = runner.invoke(cli_mod.cli, ["train-why", "--step", "99"])
    assert res.exit_code != 0


def test_train_why_cli_no_records(fresh_events):
    from click.testing import CliRunner
    from skypilot_tpu.client import cli as cli_mod
    res = CliRunner().invoke(cli_mod.cli, ["train-why"])
    assert res.exit_code != 0


def test_top_train_goodput_and_straggler_columns():
    from skypilot_tpu.client import cli as cli_mod

    def fams(flops):
        return {
            "skytpu_train_step_last_seconds": {
                "type": "gauge", "samples": [({}, 0.050)]},
            "skytpu_train_step_median_seconds": {
                "type": "gauge", "samples": [({}, 0.048)]},
            "skytpu_train_tokens_per_second": {
                "type": "gauge", "samples": [({}, 1000.0)]},
            "skytpu_train_goodput_ratio": {
                "type": "gauge", "samples": [
                    ({"host": "0"}, 0.91), ({"host": "3"}, 0.62)]},
            "skytpu_roofline_peak_flops": {
                "type": "gauge", "samples": [({}, 0.5e12)]},
            "skytpu_device_flops_total": {
                "type": "counter", "samples": [({}, float(flops))]},
            "skytpu_train_host_step_seconds": {
                "type": "gauge", "samples": [
                    ({"host": "0"}, 0.050), ({"host": "3"}, 0.091)]},
        }

    payload = {"components": [], "alerts": []}
    now = 1000.0
    frame = cli_mod._render_top_frame(
        fams(0), now - 10.0, fams(0.4 * 0.5e12 * 10), now, payload)
    train = next(l for l in frame.splitlines()
                 if l.startswith("train"))
    # Worst host's goodput (min), windowed MFU, and the straggler's
    # lag over the fastest host.
    assert "goodput 62.0%" in train
    assert "mfu 40.0%" in train
    assert "straggler host-3 (+41 ms)" in train
