"""Request forensics: the P² tail estimators, exemplar pinning past
ring rollover, SLO incident bundles, the /debug/forensics + ?since=
cursor surfaces, and the serving latency-bucket ladder
(docs/observability.md §Request forensics)."""

import json
import os
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.models import llama
from skypilot_tpu.observability import flight as fl
from skypilot_tpu.observability import forensics
from skypilot_tpu.observability import metrics as metrics_lib


# ---------------------------------------------------------------------------
# P-squared streaming quantiles.

def test_p2_matches_numpy_percentile():
    """Five floats vs the full reservoir: the P² estimate lands within
    a few percent of numpy's exact quantile on a lognormal stream (the
    latency-shaped distribution the detector actually watches)."""
    rng = np.random.default_rng(3)
    xs = rng.lognormal(mean=3.0, sigma=0.6, size=20_000)
    for q in (0.5, 0.9, 0.99):
        est = forensics.P2Quantile(q)
        for x in xs:
            est.observe(float(x))
        exact = float(np.percentile(xs, 100 * q))
        assert est.value() == pytest.approx(exact, rel=0.08), \
            f"q={q}: est {est.value()} vs exact {exact}"
        assert est.count == len(xs)


def test_p2_small_stream_and_validation():
    with pytest.raises(ValueError):
        forensics.P2Quantile(1.0)
    est = forensics.P2Quantile(0.9)
    assert est.value() is None
    for x in (5.0, 1.0, 3.0):
        est.observe(x)
    # Pre-marker regime: the empirical quantile of what we have.
    assert est.value() == 5.0


def test_tail_detector_warmup_and_crossing(monkeypatch):
    monkeypatch.setenv("SKYTPU_TAIL_QUANTILE", "0.9")
    monkeypatch.setenv("SKYTPU_TAIL_MIN_SAMPLES", "10")
    det = forensics.TailDetector()
    assert det.quantile == 0.9 and det.min_samples == 10
    # Warmup: nothing crosses while count < min_samples, even an
    # outlier 100x the rest.
    crossed, _ = det.observe("ttft", 500.0)
    assert not crossed
    for _ in range(12):
        crossed, _ = det.observe("ttft", 5.0)
    # Past warmup an outlier above the p90-of-priors crosses...
    crossed, thr = det.observe("ttft", 400.0)
    assert crossed and thr is not None
    # ...and a typical sample does not.
    crossed, _ = det.observe("ttft", 5.0)
    assert not crossed
    snap = det.snapshot()
    assert snap["estimates"]["ttft"]["count"] == 15
    assert snap["estimates"]["tpot"]["count"] == 0


def test_exemplar_store_bounded_newest_wins():
    store = forensics.ExemplarStore(capacity=3)
    for i in range(6):
        store.pin({"rid": i % 2, "metric": "ttft", "value_ms": i})
    assert len(store) == 3
    # get() returns the NEWEST pin for a rid.
    assert store.get(1)["value_ms"] == 5
    assert store.get(99) is None
    rows = store.list()
    assert [r["value_ms"] for r in rows] == [5, 4, 3]


# ---------------------------------------------------------------------------
# Engine integration: pinning survives ring rollover.

def _tiny_engine(**overrides):
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    kw = dict(n_slots=2, max_len=64, prompt_buckets=(16,),
              flight_recorder=fl.FlightRecorder())
    kw.update(overrides)
    return eng.InferenceEngine(params, cfg, **kw)


def test_exemplar_survives_ring_rollover(monkeypatch):
    """The tail store's reason to exist: a slow request's full ledger
    evidence stays retrievable after the flight ring rolled past its
    records. A tiny ring + an every-request tail bar make the
    rollover and the pin both certain."""
    monkeypatch.setenv("SKYTPU_TAIL_QUANTILE", "0.5")
    monkeypatch.setenv("SKYTPU_TAIL_MIN_SAMPLES", "5")
    store = forensics.ExemplarStore(capacity=8)
    e = _tiny_engine(flight_recorder=fl.FlightRecorder(capacity=32),
                     exemplar_store=store)
    rid = None
    for _ in range(10):
        ids = [e.add_request([4, 9, 2], max_new_tokens=3)]
        e.run_to_completion(4)
        ex = next((ex for i in ids
                   if (ex := store.get(i)) is not None), None)
        if ex is not None:
            rid = ids[0]
            break
    assert rid is not None, "no retirement crossed a p50 tail bar"
    ex = store.get(rid)
    assert ex["ledger"] is not None and ex["records"]
    assert ex["ledger"]["rid"] == rid
    assert any(r["burst"] == "retire" for r in ex["records"])
    # Roll the ring: 32-slot capacity, 40 fresh records.
    for i in range(40):
        e.flight.record("decode", toks=0)
    assert forensics.ledger_from_records(rid, e.flight.tail()) is None
    # The pin still answers `skytpu why` with the full ledger.
    ex = store.get(rid)
    total = sum(p["ms"] for p in ex["ledger"]["phases"])
    assert total == pytest.approx(ex["ledger"]["wall_ms"], abs=0.05)
    assert metrics_lib.REGISTRY.snapshot()[
        "skytpu_tail_exemplars_pinned_total"]["samples"]


def test_forensics_off_is_inert(monkeypatch):
    """SKYTPU_FORENSICS=0: no retire records, no stall dict growth on
    the records, no pins — and identical greedy output (the parity
    the bench gates; here the structural half)."""
    store = forensics.ExemplarStore(capacity=4)
    e_on = _tiny_engine()
    out_on = e_on.generate([[4, 9, 2]], max_new_tokens=4)
    monkeypatch.setenv("SKYTPU_FORENSICS", "0")
    e_off = _tiny_engine(exemplar_store=store)
    assert e_off.forensics is False
    out_off = e_off.generate([[4, 9, 2]], max_new_tokens=4)
    assert out_on == out_off
    assert not any(r["burst"] == "retire" for r in e_off.flight.tail())
    assert len(store) == 0
    # Explicit ctor flag beats the env.
    e_forced = _tiny_engine(forensics=True)
    assert e_forced.forensics is True


# ---------------------------------------------------------------------------
# Incident bundles.

def _reset_rate_limit():
    forensics._last_capture_s = 0.0


def test_incident_capture_bundle_and_gc(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYTPU_INCIDENTS_KEEP", "2")
    _reset_rate_limit()
    base = str(tmp_path / "incidents")
    rec = fl.FlightRecorder()
    rec.record("decode", toks=3)
    store = forensics.ExemplarStore(capacity=4)
    store.pin({"rid": 7, "metric": "ttft", "value_ms": 123.0})
    path = forensics.capture_incident(
        "ttft-p95", {"value": 12.0, "threshold": 10.0},
        recorder=rec, exemplars=store,
        health={"components": [{"component": "model-server",
                                "status": "degraded"}]},
        base_dir=base, force=True)
    assert path is not None and os.path.isdir(path)
    names = set(os.listdir(path))
    assert {"meta.json", "alert.json", "health.json",
            "exemplars.json", "flight.jsonl",
            "metrics.prom"} <= names
    meta = json.load(open(os.path.join(path, "meta.json")))
    assert meta["rule"] == "ttft-p95"
    assert meta["attrs"]["threshold"] == 10.0
    exemplars = json.load(open(os.path.join(path, "exemplars.json")))
    assert exemplars[0]["rid"] == 7
    flight_lines = open(os.path.join(path, "flight.jsonl")).read()
    assert json.loads(flight_lines.splitlines()[0])["toks"] == 3
    # list / load round-trip.
    rows = forensics.list_incidents(base)
    assert rows[0]["rule"] == "ttft-p95"
    bundle = forensics.load_incident(rows[0]["name"], base)
    assert bundle["meta"]["rule"] == "ttft-p95"
    assert any(f["file"] == "flight.jsonl" and f["lines"] == 1
               for f in bundle["files"])
    # Path traversal never escapes the incidents dir.
    assert forensics.load_incident("../oops", base) is None
    # GC: keep=2 — two more captures leave exactly two on disk.
    for i in range(2):
        assert forensics.capture_incident(
            f"rule-{i}", {}, recorder=rec, exemplars=store,
            base_dir=base, force=True)
    kept = [n for n in os.listdir(base) if not n.endswith(".tmp")]
    assert len(kept) == 2
    assert not any(n.endswith("ttft-p95") for n in kept)


def test_incident_rate_limit_and_disable(tmp_path, monkeypatch):
    base = str(tmp_path / "inc")
    rec = fl.FlightRecorder()
    _reset_rate_limit()
    monkeypatch.setenv("SKYTPU_INCIDENT_MIN_INTERVAL_S", "3600")
    first = forensics.capture_incident("r", {}, recorder=rec,
                                       base_dir=base)
    assert first is not None
    # A flapping rule inside the interval captures nothing...
    assert forensics.capture_incident("r", {}, recorder=rec,
                                      base_dir=base) is None
    # ...unless forced (tests, manual `capture now`).
    assert forensics.capture_incident("r", {}, recorder=rec,
                                      base_dir=base, force=True)
    monkeypatch.setenv("SKYTPU_INCIDENTS", "0")
    _reset_rate_limit()
    assert forensics.capture_incident("r", {}, recorder=rec,
                                      base_dir=base,
                                      force=True) is None


def test_watchdog_breach_captures_incident(tmp_path, monkeypatch):
    """The slo.py hook: a breach TRANSITION captures a bundle and
    stamps its name into the breach event's attrs."""
    import time

    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    _reset_rate_limit()
    from skypilot_tpu.observability import slo

    rule = slo.SloRule(
        "hb", "heartbeat_staleness", threshold=120.0,
        metric="skytpu_skylet_last_tick_timestamp_seconds")
    wd = slo.Watchdog(rules=[rule])
    now = time.time()
    fams = {"skytpu_skylet_last_tick_timestamp_seconds": {
        "type": "gauge", "samples": [({"instance": "c1"}, now - 900)]}}
    transitions = wd.observe(fams, [], ts=now)
    assert [t["event"] for t in transitions] == ["slo.breach"]
    inc = transitions[0].get("incident")
    assert inc, "breach event carries no incident attr"
    bundle = forensics.load_incident(inc)
    assert bundle is not None
    assert bundle["meta"]["rule"] == "hb"
    assert forensics.list_incidents()[0]["name"] == inc


# ---------------------------------------------------------------------------
# Latency bucket ladder.

def test_latency_buckets_env_override(monkeypatch):
    default = metrics_lib.latency_buckets()
    assert default == metrics_lib.SERVING_LATENCY_BUCKETS
    assert default[0] < 0.005 and list(default) == sorted(default)
    monkeypatch.setenv("SKYTPU_LATENCY_BUCKETS", "0.5, 0.1, 1.0")
    assert metrics_lib.latency_buckets() == (0.1, 0.5, 1.0)
    monkeypatch.setenv("SKYTPU_LATENCY_BUCKETS", "0.1,bogus")
    assert metrics_lib.latency_buckets() == \
        metrics_lib.SERVING_LATENCY_BUCKETS
    monkeypatch.setenv("SKYTPU_LATENCY_BUCKETS", "0,-1")
    assert metrics_lib.latency_buckets() == \
        metrics_lib.SERVING_LATENCY_BUCKETS


# ---------------------------------------------------------------------------
# Ledger edge shapes (unit level; the sums gate rides test_flight).

def test_ledger_stall_attribution_and_gaps():
    """Queue-ish gaps consume the retire record's typed stall totals
    before falling back to plain queue_wait; chunk->chunk gaps read as
    prefill_interleave; the post-burst tail is deliver."""
    retire = {"burst": "retire", "rids": [1], "submit_s": 100.0,
              "end_s": 100.5, "first_token_s": 100.3,
              "stalls": {"kv_quota": 60.0, "pool_dry": 20.0},
              "n_toks": 4}
    records = [
        retire,
        {"burst": "chunk", "rids": [1], "ts_s": 100.2, "dur_s": 0.05,
         "seq": 1},
        {"burst": "chunk", "rids": [1], "ts_s": 100.3, "dur_s": 0.05,
         "seq": 2},
        {"burst": "decode", "rids": [1], "ts_s": 100.4, "dur_s": 0.05,
         "seq": 3, "dev_ms_est": 30.0},
    ]
    led = forensics.build_ledger(retire, records)
    ph = {p["phase"]: p["ms"] for p in led["phases"]}
    # 200ms pre-first-burst gap: 20 pool_dry + 60 kv_quota + 120 queue.
    assert ph["stall_pool_dry"] == pytest.approx(20.0, abs=0.01)
    assert ph["stall_kv_quota"] == pytest.approx(60.0, abs=0.01)
    assert ph["queue_wait"] == pytest.approx(120.0, abs=0.01)
    assert ph["prefill_interleave"] == pytest.approx(50.0, abs=0.01)
    assert ph["prefill_chunk"] == pytest.approx(100.0, abs=0.01)
    assert ph["decode_device"] == pytest.approx(30.0, abs=0.01)
    assert ph["decode_host"] == pytest.approx(70.0, abs=0.01)
    assert ph["deliver"] == pytest.approx(50.0, abs=0.01)
    assert sum(ph.values()) == pytest.approx(led["wall_ms"], abs=0.05)
    assert led["other_ms"] == 0.0
    # Phase render order follows PHASE_ORDER.
    order = [p["phase"] for p in led["phases"]]
    assert order == [k for k in forensics.PHASE_ORDER if k in ph]


def test_ledger_no_records_is_all_other():
    retire = {"burst": "retire", "rids": [2], "submit_s": 10.0,
              "end_s": 10.1, "stalls": {}}
    led = forensics.build_ledger(retire, [retire])
    assert led["n_records"] == 0
    assert led["named_ms"] == 0.0
    assert led["other_ms"] == pytest.approx(100.0, abs=0.01)


# ---------------------------------------------------------------------------
# CLI surfaces: `skytpu why --local`, `skytpu incidents`, `top --json`.

@pytest.fixture
def fresh_events(tmp_path, monkeypatch):
    from skypilot_tpu.observability import tracing
    monkeypatch.setenv(tracing.EVENTS_DIR_ENV_VAR, str(tmp_path))
    monkeypatch.delenv(tracing.ENV_VAR, raising=False)
    tracing._reset_for_tests()
    yield str(tmp_path)
    tracing._reset_for_tests()


def test_why_cli_local(fresh_events):
    from click.testing import CliRunner

    from skypilot_tpu.client import cli as cli_mod

    e = _tiny_engine()
    rid = e.add_request([5, 3, 8, 2], max_new_tokens=4)
    e.run_to_completion(4)
    e.flight.flush()
    runner = CliRunner()
    res = runner.invoke(cli_mod.cli, ["why", str(rid), "--local"])
    assert res.exit_code == 0, res.output
    assert f"request {rid}" in res.output
    assert "sum (= wall)" in res.output
    res_json = runner.invoke(cli_mod.cli,
                             ["why", str(rid), "--local", "--json"])
    assert res_json.exit_code == 0, res_json.output
    led = json.loads(res_json.output)
    assert led["rid"] == rid
    assert sum(p["ms"] for p in led["phases"]) == \
        pytest.approx(led["wall_ms"], abs=0.05)
    # A rid that never retired is a typed error, not a traceback.
    res_miss = runner.invoke(cli_mod.cli, ["why", "424242", "--local"])
    assert res_miss.exit_code != 0
    assert "no retired request 424242" in res_miss.output


def test_incidents_cli_list_show(tmp_path, monkeypatch):
    from click.testing import CliRunner

    from skypilot_tpu.client import cli as cli_mod

    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "home"))
    _reset_rate_limit()
    rec = fl.FlightRecorder()
    rec.record("decode", toks=1)
    path = forensics.capture_incident(
        "ttft-p95", {"value": 11.0}, recorder=rec,
        exemplars=forensics.ExemplarStore(capacity=2), force=True)
    assert path is not None
    name = os.path.basename(path)
    runner = CliRunner()
    res = runner.invoke(cli_mod.cli, ["incidents", "list"])
    assert res.exit_code == 0, res.output
    assert name in res.output and "ttft-p95" in res.output
    res_show = runner.invoke(cli_mod.cli, ["incidents", "show", name])
    assert res_show.exit_code == 0, res_show.output
    assert "rule:     ttft-p95" in res_show.output
    assert "flight.jsonl" in res_show.output
    res_miss = runner.invoke(cli_mod.cli,
                             ["incidents", "show", "nope"])
    assert res_miss.exit_code != 0
    assert "no incident" in res_miss.output


def test_top_json_frame_is_machine_readable():
    """--json emits one dict mirroring the rendered frame: the same
    rates/columns, no ANSI, parseable by dashboards."""
    from skypilot_tpu.client import cli as cli_mod

    def fams(n):
        return {
            "skytpu_http_requests_total": {
                "type": "counter",
                "samples": [({"route": "/generate", "code": "200"},
                             float(n))]},
            "skytpu_slots_active": {
                "type": "gauge", "samples": [({}, 3.0)]},
            "skytpu_slots_total": {
                "type": "gauge", "samples": [({}, 4.0)]},
        }

    payload = {"status": "healthy",
               "components": [{"component": "model-server",
                               "instance": "i1", "status": "healthy",
                               "reason": "", "last_seen_s": 0.0}],
               "alerts": []}
    now = 2000.0
    rendered, data = cli_mod._top_frame(fams(0), now - 10.0, fams(20),
                                        now, payload)
    # The wrapper the existing column tests call is the same string.
    assert rendered == cli_mod._render_top_frame(
        fams(0), now - 10.0, fams(20), now, payload)
    assert data["serve"]["req_per_s"] == pytest.approx(2.0)
    assert data["serve"]["slots_active"] == 3
    assert data["serve"]["slots_total"] == 4
    assert data["fleet"]["status"] == "healthy"
    assert data["window_s"] == pytest.approx(10.0)
    json.dumps(data, default=str)   # round-trips as JSON
