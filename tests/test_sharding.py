"""Sharding: mesh construction, sharded train step, single-vs-multi parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib, sharding as sh
from skypilot_tpu.train import trainer


def test_make_mesh_shapes():
    m = mesh_lib.make_mesh(mesh_lib.MeshShape(dp=2, fsdp=2, tp=2))
    assert dict(m.shape) == {"pp": 1, "dp": 2, "fsdp": 2, "ep": 1, "tp": 2, "sp": 1}
    with pytest.raises(ValueError):
        mesh_lib.make_mesh(mesh_lib.MeshShape(dp=3, fsdp=2, tp=2))


def test_default_shape_factorization():
    s = mesh_lib.default_shape_for(8, tp=2)
    assert s.as_dict() == {"pp": 1, "dp": 1, "fsdp": 4, "ep": 1, "tp": 2, "sp": 1}


def test_param_shardings_resolve(mesh8, tiny_cfg):
    shardings = sh.logical_to_sharding(
        llama.param_logical_axes(tiny_cfg), mesh8)
    wq = shardings["blocks"]["wq"]
    assert wq.spec == P(None, "fsdp", "tp", None)
    assert shardings["embed"].spec == P("tp", "fsdp")


def test_sharded_train_step_runs(mesh8, tiny_cfg):
    tc = trainer.TrainConfig(warmup_steps=1, total_steps=10)
    state = trainer.create_train_state(tiny_cfg, tc, mesh8)
    # Params are actually distributed:
    wq = state["params"]["blocks"]["wq"]
    assert len(wq.sharding.device_set) == 8
    step = trainer.make_train_step(tiny_cfg, tc, mesh8)
    batch = trainer.synthetic_batch(tiny_cfg, 8, 32)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state["step"]) == 1


def test_sharded_matches_unsharded(mesh8, tiny_cfg):
    """Same seed, same batch: sharded and single-device losses agree."""
    tc = trainer.TrainConfig(warmup_steps=1, total_steps=10)
    batch = trainer.synthetic_batch(tiny_cfg, 8, 32, seed=7)

    s1 = trainer.create_train_state(tiny_cfg, tc, mesh=None, seed=0)
    step1 = trainer.make_train_step(tiny_cfg, tc, mesh=None)
    _, m1 = step1(s1, batch)

    s8 = trainer.create_train_state(tiny_cfg, tc, mesh8, seed=0)
    step8 = trainer.make_train_step(tiny_cfg, tc, mesh8)
    _, m8 = step8(s8, batch)

    np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]),
                               rtol=2e-2)


def test_multislice_mesh_virtual_slices(tiny_cfg):
    """2 virtual slices x 4 devices: dp spans slices, train step runs."""
    mesh = mesh_lib.make_multislice_mesh(
        mesh_lib.MeshShape(dp=2, fsdp=2, tp=2), n_slices=2)
    assert dict(mesh.shape)["dp"] == 2
    # Slice 0's devices occupy dp index 0 exactly.
    devs = jax.devices()
    assert set(mesh.devices[:, 0].flat) == set(devs[:4])
    assert set(mesh.devices[:, 1].flat) == set(devs[4:])

    tc = trainer.TrainConfig(warmup_steps=1, total_steps=4)
    state = trainer.create_train_state(tiny_cfg, tc, mesh)
    step = trainer.make_train_step(tiny_cfg, tc, mesh)
    _, metrics = step(state, trainer.synthetic_batch(tiny_cfg, 8, 32))
    assert np.isfinite(float(metrics["loss"]))


def test_multislice_mesh_validation():
    with pytest.raises(ValueError):
        mesh_lib.make_multislice_mesh(
            mesh_lib.MeshShape(dp=3, fsdp=2), n_slices=2)
    with pytest.raises(ValueError):
        mesh_lib.make_multislice_mesh(
            mesh_lib.MeshShape(dp=2, fsdp=3), n_slices=2)
