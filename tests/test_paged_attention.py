"""Pallas paged decode-attention kernel: block-table-native KV reads.

Tier-1 guards for the PR-12 kernel (ROADMAP item 1's final half —
the gather transient's removal), run in Pallas interpret mode on CPU
(the flash-attention precedent):

* Kernel numerics vs a numpy online-softmax reference: fuzzed slot
  lengths (0, partial final blocks, full), scattered physical block
  ids, sentinel table entries, span-bounded sweeps, fp32 and int8
  pools with per-(block, head, row) scales.
* Greedy parity vs the XLA gather oracle — the gather path is kept
  VERBATIM and stays runtime-selectable (the flag off) — across
  {fp32, int8 KV} x {spec on, off} x the span-rung ladder x
  partial final blocks, through the real engine (chunked admission,
  prefix reuse, span regrouping). Workloads are pinned: the oracle's
  own bf16 weight-cast sets a ~1e-3 logit noise floor, so EXACT ties
  (a tiny random-weight model produces them; PR 6's test_infer_tp
  lesson) can flip under any summation reorganization — the
  layer-level test below asserts parity wherever the top-2 gap
  exceeds that floor, seed-robustly.
* Program identity: the kernel flag rides the compile-watch key
  (never a retrace surface), warm_programs covers the kernel grid and
  live traffic then compiles NOTHING new.
* Observability: decode/verify flight records carry
  ``attn=kernel|gather``; the path counter feeds ``skytpu top``.
* Fallback: a contiguous engine requesting the kernel falls back to
  the gather (typed event), bit-identical behavior.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.models import llama
from skypilot_tpu.observability import flight as flight_lib
from skypilot_tpu.ops import paged_attention as pa


@pytest.fixture(scope="module")
def cfg():
    # fp32 activations: reorganization noise is not amplified by bf16
    # output casts (the PR 6 lesson); the int8 cells cover the
    # quantized cache.
    return dataclasses.replace(llama.CONFIGS["llama3-tiny"],
                               dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.key(0), cfg)


def _engine(params, cfg, **kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("prompt_buckets", (32,))
    kw.setdefault("kv_block", 16)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefix_pool", 4)
    return eng.InferenceEngine(params, cfg, **kw)


# Pinned parity workload (seed 1): prompt lengths cross the chunk
# boundary (20 > chunk 8 -> chunked admission with a partial final
# chunk; 5, 3 ride waves), none block-aligned (partial final BLOCKS),
# and active rows sweep span rungs 8 -> 32 of the default ladder.
_PROMPT_LENS = (5, 11, 3, 20)
_SEED = 1


def _prompts(cfg, seed=_SEED):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, n).tolist()
            for n in _PROMPT_LENS]


# -- kernel vs numpy reference ----------------------------------------------

def _np_reference(q, kp, vp, ks, vs, table, lengths, layer, span):
    """Online-softmax stats the kernel must reproduce, in numpy."""
    B, G, R, hd = q.shape
    n_blocks, bl = kp.shape[1], kp.shape[2]
    nbs = -(-span // bl)
    acc = np.zeros((B, G, R, hd), np.float64)
    m = np.full((B, G, R), -1e30, np.float64)
    l = np.zeros((B, G, R), np.float64)
    for b in range(B):
        n = int(lengths[b])
        cols_k, cols_v, sk_cols, sv_cols = [], [], [], []
        for j in range(nbs):
            t = int(table[b, j])
            if j * bl >= n:
                continue
            t = 0 if t >= n_blocks else t
            cols_k.append(kp[layer, t].astype(np.float64))
            cols_v.append(vp[layer, t].astype(np.float64))
            if ks is not None:
                sk_cols.append(ks[layer, t].astype(np.float64))
                sv_cols.append(vs[layer, t].astype(np.float64))
        if not cols_k:
            continue
        K = np.concatenate(cols_k)              # [M, G, hd]
        V = np.concatenate(cols_v)
        M_ = K.shape[0]
        col = np.arange(M_)
        for g in range(G):
            s = (q[b, g].astype(np.float64) * hd ** -0.5) @ K[:, g].T
            if ks is not None:
                s = s * np.concatenate(
                    [c[g] for c in sk_cols])[None, :]
            s = np.where(col[None, :] < n, s, -1e30)
            mm = s.max(1)
            p = np.exp(s - mm[:, None])
            ll = p.sum(1)
            if vs is not None:
                pv = p * np.concatenate(
                    [c[g] for c in sv_cols])[None, :]
            else:
                pv = p
            acc[b, g] = pv @ V[:, g]
            m[b, g] = mm
            l[b, g] = ll
    return acc, m, l


@pytest.mark.parametrize("quant", [False, True], ids=["fp", "int8"])
def test_kernel_vs_numpy_fuzz(quant):
    rng = np.random.default_rng(0)
    L, n_blocks, bl, G, hd = 2, 12, 8, 2, 16
    B, R = 4, 3
    nb = 5
    if quant:
        kp = rng.integers(-127, 128,
                          (L, n_blocks, bl, G, hd)).astype(np.int8)
        vp = rng.integers(-127, 128,
                          (L, n_blocks, bl, G, hd)).astype(np.int8)
        ks = (rng.random((L, n_blocks, G, bl)) * 0.02
              + 1e-3).astype(np.float32)
        vs = (rng.random((L, n_blocks, G, bl)) * 0.02
              + 1e-3).astype(np.float32)
    else:
        kp = rng.standard_normal(
            (L, n_blocks, bl, G, hd)).astype(np.float32)
        vp = rng.standard_normal(
            (L, n_blocks, bl, G, hd)).astype(np.float32)
        ks = vs = None
    for trial in range(4):
        q = rng.standard_normal((B, G, R, hd)).astype(np.float32)
        table = np.full((B, nb + 1), n_blocks, np.int32)
        lengths = np.zeros((B,), np.int32)
        for b in range(B):
            # Fuzz: 0 rows, partial final blocks, full allocations,
            # scattered physical ids, sentinel tails.
            n = int(rng.integers(0, nb * bl + 1))
            have = -(-n // bl)
            table[b, :have] = rng.choice(n_blocks, size=have,
                                         replace=False)
            lengths[b] = n
        span = int(rng.integers(1, nb * bl + 1))
        layer = int(rng.integers(0, L))
        acc, m, l = pa.paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            None if ks is None else jnp.asarray(ks),
            None if vs is None else jnp.asarray(vs),
            jnp.asarray(table), jnp.asarray(lengths),
            jnp.int32(layer), span_blocks=-(-span // bl))
        racc, rm, rl = _np_reference(q, kp, vp, ks, vs, table,
                                     lengths, layer, span)
        acc, m, l = np.asarray(acc), np.asarray(m), np.asarray(l)
        for b in range(B):
            n = min(int(lengths[b]), -(-span // bl) * bl)
            if n == 0:
                assert np.all(m[b] == -1e30)
                assert np.all(l[b] == 0)
                continue
            # The kernel only sweeps span_blocks; the reference's mask
            # bound must match what the kernel saw.
            r2acc, r2m, r2l = racc[b], rm[b], rl[b]
            assert np.allclose(m[b], r2m, rtol=1e-5, atol=1e-5)
            assert np.allclose(l[b], r2l, rtol=1e-4, atol=1e-5)
            assert np.allclose(acc[b], r2acc, rtol=1e-3, atol=1e-4)


def test_kernel_under_scan_traced_layer():
    """The layer index is a TRACED scalar (the engine calls the kernel
    inside the layer scan) — scalar prefetch must route it."""
    rng = np.random.default_rng(1)
    L, n_blocks, bl, G, hd = 3, 6, 8, 1, 16
    kp = rng.standard_normal((L, n_blocks, bl, G, hd)).astype(np.float32)
    vp = rng.standard_normal((L, n_blocks, bl, G, hd)).astype(np.float32)
    q = rng.standard_normal((1, G, 2, hd)).astype(np.float32)
    table = np.array([[2, 4, n_blocks]], np.int32)
    lengths = np.array([13], np.int32)

    def body(i, _):
        return i + 1, pa.paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            None, None, jnp.asarray(table), jnp.asarray(lengths), i,
            span_blocks=2)[0]

    _, accs = jax.lax.scan(body, jnp.int32(0), None, length=L)
    for li in range(L):
        racc, _, _ = _np_reference(q, kp, vp, None, None, table,
                                   lengths, li, 16)
        assert np.allclose(np.asarray(accs)[li], racc, rtol=1e-4,
                           atol=1e-5), f"layer {li}"


# -- layer-level logits: gap-aware greedy parity (seed-robust) --------------

@pytest.mark.parametrize("kv_int8", [False, True], ids=["fp", "int8"])
def test_layer_logits_close_and_untied_argmax_equal(params, cfg,
                                                    kv_int8):
    """One staged decode step's logits, kernel vs gather, on a REAL
    mid-generation cache: logits agree within the oracle's bf16
    weight-cast noise floor, and argmax agrees on every slot whose
    top-2 gap exceeds it — the seed-robust statement of greedy parity
    (exact ties flip under ANY summation reorganization)."""
    from skypilot_tpu.infer import kvcache

    e = _engine(params, cfg, kv_int8=kv_int8, kv_kernel=False)
    for p in _prompts(cfg):
        e.add_request(p, max_new_tokens=4)
    e.admit()
    while e.chunking:
        e.prefill_chunk_step()
    e.step_decode_once()
    cache = {k: jnp.copy(v) for k, v in e.cache.items()}
    table = e.table_device()
    L, G, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    B = cache["length"].shape[0]
    quant = "k_scale" in cache
    kdt = cache["k"].dtype

    def one_step_logits(kernel):
        c = {k: jnp.copy(v) for k, v in cache.items()}
        pos0 = c["length"]
        valid = jnp.arange(64)[None, :] < pos0[:, None]
        batch_ix = jnp.arange(B)
        sk = jnp.zeros((L, B, 1, G, hd), kdt)
        sv = jnp.zeros((L, B, 1, G, hd), kdt)
        zero = jnp.zeros((), jnp.float32)
        sks = (jnp.zeros((L, B, 1, G), c["k_scale"].dtype)
               if quant else zero)
        svs = (jnp.zeros((L, B, 1, G), c["k_scale"].dtype)
               if quant else zero)
        x = params["embed"].astype(cfg.dtype)[c["last_token"][:, None]]
        cos, sin = llama.rope_frequencies(cfg, pos0[:, None])
        stage_valid = jnp.arange(1)[None, :] <= 0
        i = jnp.int32(0)
        for li in range(L):
            layer = jax.tree.map(lambda w: w[li], params["blocks"])
            x, sk, sv, sks, svs = kvcache._staged_attn_layer(
                cfg, c, table, layer, None, x, cos, sin, i, 0,
                sk, sv, sks, svs, valid, stage_valid, batch_ix,
                None, pos0, li == li and kernel)
            i = i + 1
        return np.asarray(kvcache._decode_head(cfg, params, None, x))

    lg = one_step_logits(False)
    lk = one_step_logits(True)
    noise = np.abs(lg - lk).max()
    assert noise < 0.05, f"kernel-vs-gather logit delta {noise}"
    for s in range(B - 1):          # spare slot excluded
        top2 = np.sort(lg[s])[-2:]
        if top2[1] - top2[0] > 0.1:
            assert lg[s].argmax() == lk[s].argmax(), f"slot {s}"


# -- engine greedy-parity matrix (pinned workloads) -------------------------

@pytest.mark.parametrize("kv_int8", [False, True], ids=["fp", "int8"])
@pytest.mark.parametrize("spec_k", [0, 3], ids=["spec0", "spec3"])
def test_engine_parity_matrix(params, cfg, kv_int8, spec_k):
    """Kernel-on greedy output == the gather oracle, end to end
    through the engine: chunked admission (partial final chunks),
    wave admission, prefix reuse, span regrouping over rungs 8..32,
    partial final blocks (no prompt is block-aligned), spec verify
    when spec_k > 0. Workload pinned (module docstring: exact ties)."""
    def gen(kv_kernel):
        e = _engine(params, cfg, kv_int8=kv_int8, spec_k=spec_k,
                    kv_kernel=kv_kernel)
        assert e.kv_kernel == kv_kernel
        return e.generate(_prompts(cfg), max_new_tokens=8)

    assert gen(True) == gen(False)


def test_parity_with_ladder_disabled(params, cfg):
    """span_buckets=0 (full-view reads, span=None -> the kernel
    sweeps the whole table) produces oracle-identical output; the
    laddered rungs (incl. the sub-block rung 8 < block 16) are swept
    by the matrix above via the default ladder."""
    def gen(kv_kernel):
        e = _engine(params, cfg, span_buckets=0, kv_kernel=kv_kernel)
        return e.generate(_prompts(cfg), max_new_tokens=8)

    assert gen(True) == gen(False)


# -- program identity + retrace discipline ----------------------------------

def test_kernel_flag_in_program_identity_and_warm_grid(params, cfg):
    """The kernel flag rides the compile-watch key; warm_programs
    covers the kernel grid, and live traffic after
    declare_warmup_complete compiles NOTHING (acceptance criterion:
    zero unexpected compiles with the kernel enabled)."""
    e = _engine(params, cfg, kv_kernel=True, max_wave=2,
                pad_waves=True)
    n = e.warm_programs(max_burst=8)
    assert n > 0
    assert any("kernel=True" in k for k in e.compile_watch.summary())
    e.declare_warmup_complete()
    out = e.generate(_prompts(cfg), max_new_tokens=8)
    assert out and all(len(t) == 8 for t in out)
    assert e.compile_watch.unexpected == [], \
        f"mid-traffic compiles: {e.compile_watch.unexpected}"
    # Dispatched program keys stay ladder-bounded (kind, width, span):
    # the kernel adds no cardinality — it is engine-constant.
    spans = {s for _, _, s in e.decode_programs}
    allowed = {None} | {s for s in e.span_ladder}
    assert spans <= allowed


# -- fallback + observability -----------------------------------------------

def test_contiguous_fallback(params, cfg):
    """A contiguous engine requesting the kernel falls back to the
    gather path (the kernel is block-table-native) and still serves;
    the flag reads False so records/benches tell the truth."""
    e = _engine(params, cfg, kv_block=0, kv_kernel=True)
    assert e.paged is False and e.kv_kernel is False
    out = e.generate(_prompts(cfg), max_new_tokens=4)
    assert all(len(t) == 4 for t in out)


def test_flight_records_attn_path(params, cfg):
    """decode/verify/chunk records carry attn=kernel when the flag is
    on; decode1 (not kernel-wired) says gather; the path counter
    moves."""
    rec = flight_lib.FlightRecorder(capacity=256)
    rec.enabled = True
    before = eng.DECODE_ATTN_PATH.labels(path="kernel").value
    e = _engine(params, cfg, kv_kernel=True, spec_k=3,
                flight_recorder=rec)
    e.generate(_prompts(cfg), max_new_tokens=6)
    e2 = _engine(params, cfg, kv_kernel=True, flight_recorder=rec)
    for p in _prompts(cfg)[:2]:
        e2.add_request(p, max_new_tokens=2)
    e2.admit()
    while e2.chunking:
        e2.prefill_chunk_step()
    e2.step_decode_once()
    kinds = {}
    for r in rec.tail():
        prog = r.get("program") or {}
        if "attn" in prog:
            kinds.setdefault(r["burst"], set()).add(prog["attn"])
    assert kinds.get("decode", set()) | kinds.get("verify", set()) \
        <= {"kernel"}
    assert "kernel" in (kinds.get("decode", set())
                        | kinds.get("verify", set()))
    assert kinds.get("chunk") == {"kernel"}
    assert kinds.get("decode1") == {"gather"}
    assert eng.DECODE_ATTN_PATH.labels(path="kernel").value > before


def test_gather_engine_records_gather(params, cfg):
    rec = flight_lib.FlightRecorder(capacity=64)
    rec.enabled = True
    e = _engine(params, cfg, kv_kernel=False, flight_recorder=rec)
    e.generate(_prompts(cfg)[:2], max_new_tokens=3)
    attns = {(r.get("program") or {}).get("attn")
             for r in rec.tail() if r["burst"] == "decode"}
    assert attns == {"gather"}


def test_env_knob(params, cfg, monkeypatch):
    monkeypatch.setenv("SKYTPU_KV_KERNEL", "1")
    assert _engine(params, cfg).kv_kernel is True
    monkeypatch.setenv("SKYTPU_KV_KERNEL", "0")
    assert _engine(params, cfg).kv_kernel is False
    monkeypatch.delenv("SKYTPU_KV_KERNEL")
    assert _engine(params, cfg).kv_kernel is False
    # ctor wins over env
    monkeypatch.setenv("SKYTPU_KV_KERNEL", "1")
    assert _engine(params, cfg, kv_kernel=False).kv_kernel is False
