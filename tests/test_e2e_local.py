"""End-to-end offline tests on the local fake cloud: launch -> exec ->
queue/logs/cancel -> stop/start -> down, gang semantics, failover."""

import os
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import exceptions, state
from skypilot_tpu.backend import TpuVmBackend
from skypilot_tpu.resources import Resources
from skypilot_tpu.runtime.job_queue import JobStatus
from skypilot_tpu.task import Task


@pytest.fixture(autouse=True)
def sky_home(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "skyhome"))
    yield str(tmp_path / "skyhome")


def _local_task(run, name="t", num_nodes=1, hosts_per_node=1, **task_kw):
    t = Task(name=name, run=run, num_nodes=num_nodes, **task_kw)
    t.set_resources(Resources(cloud="local"))
    return t


def _wait(handle, job_id, timeout=30):
    return TpuVmBackend().wait_job(handle, job_id, timeout)


def test_launch_end_to_end():
    t = _local_task("echo hello-from-$SKYTPU_NODE_RANK")
    job_id, handle = sky.launch(t, cluster_name="c1")
    assert _wait(handle, job_id) == JobStatus.SUCCEEDED

    rec = state.get_cluster("c1")
    assert rec["status"] == state.ClusterStatus.UP

    logs = TpuVmBackend().job_log_paths(handle, job_id)
    assert len(logs) == 1
    assert "hello-from-0" in open(logs[0]).read()


def test_env_contract_injected():
    t = _local_task(
        'echo "rank=$SKYTPU_NODE_RANK hosts=$SKYTPU_NUM_HOSTS '
        'coord=$JAX_COORDINATOR_ADDRESS pid=$JAX_PROCESS_ID"')
    job_id, handle = sky.launch(t, cluster_name="c2")
    assert _wait(handle, job_id) == JobStatus.SUCCEEDED
    content = open(TpuVmBackend().job_log_paths(handle, job_id)[0]).read()
    assert "rank=0 hosts=1 coord=127.0.0.1:8476 pid=0" in content


def test_exec_on_existing_cluster_and_queue():
    t = _local_task("echo one")
    job1, handle = sky.launch(t, cluster_name="c3")
    _wait(handle, job1)
    t2 = _local_task("echo two", name="second")
    job2, _ = sky.exec(t2, cluster_name="c3")
    assert _wait(handle, job2) == JobStatus.SUCCEEDED
    q = sky.queue("c3")
    assert [j["job_id"] for j in q] == [job2, job1]
    assert all(j["status"] == JobStatus.SUCCEEDED for j in q)


def test_gang_fail_one_kills_all():
    # Host 0 fails fast; host 1 would run for 30s. Gang semantics must
    # kill host 1 and fail the job quickly.
    t = _local_task(
        'if [ "$SKYTPU_HOST_ID" = "0" ]; then exit 3; else sleep 30; fi',
        num_nodes=2)
    start_t = time.time()
    job_id, handle = sky.launch(t, cluster_name="c4")
    status = _wait(handle, job_id, timeout=20)
    assert status == JobStatus.FAILED
    assert time.time() - start_t < 15


def test_cancel_running_job():
    t = _local_task("sleep 60")
    job_id, handle = sky.launch(t, cluster_name="c5")
    deadline = time.time() + 10
    while sky.job_status("c5", job_id) != JobStatus.RUNNING:
        assert time.time() < deadline
        time.sleep(0.1)
    sky.cancel("c5", job_id)
    assert sky.job_status("c5", job_id) == JobStatus.CANCELLED


def test_setup_and_envs():
    t = _local_task("cat marker.txt", name="with-setup")
    t.setup = "echo from-setup-$MYVAR > marker.txt"
    t.update_envs({"MYVAR": "42"})
    job_id, handle = sky.launch(t, cluster_name="c6")
    assert _wait(handle, job_id) == JobStatus.SUCCEEDED
    content = open(TpuVmBackend().job_log_paths(handle, job_id)[0]).read()
    assert "from-setup-42" in content


def test_stop_start_down():
    t = _local_task("echo x")
    job_id, handle = sky.launch(t, cluster_name="c7")
    _wait(handle, job_id)
    sky.stop("c7")
    assert state.get_cluster("c7")["status"] == state.ClusterStatus.STOPPED
    with pytest.raises(exceptions.ClusterNotUpError):
        sky.exec(_local_task("echo y"), cluster_name="c7")
    sky.start("c7")
    assert state.get_cluster("c7")["status"] == state.ClusterStatus.UP
    sky.down("c7")
    assert state.get_cluster("c7") is None
    report = sky.cost_report()
    assert any(r["name"] == "c7" for r in report)


def test_failover_retry_until_up(monkeypatch):
    # First 2 provision attempts hit injected CapacityError; since the
    # local cloud has one candidate, retry_until_up sweeps again.
    monkeypatch.setenv("SKYTPU_LOCAL_FAIL_ATTEMPTS", "2")
    t = _local_task("echo recovered")
    job_id, handle = sky.launch(t, cluster_name="c8", retry_until_up=True)
    assert _wait(handle, job_id) == JobStatus.SUCCEEDED


def test_failover_exhausted_raises(monkeypatch):
    monkeypatch.setenv("SKYTPU_LOCAL_FAIL_ATTEMPTS", "99")
    t = _local_task("echo never")
    with pytest.raises(exceptions.ResourcesUnavailableError):
        sky.launch(t, cluster_name="c9")


def test_multihost_rank_assignment():
    # 2 logical nodes x 2 hosts each = 4 hosts; check the rank math.
    t = Task(name="ranks",
             run='echo "h=$SKYTPU_HOST_ID n=$SKYTPU_NODE_RANK '
                 'w=$SKYTPU_WORKER_ID np=$JAX_NUM_PROCESSES"',
             num_nodes=2)
    t.set_resources(Resources(cloud="local"))
    job_id, handle = sky.launch(t, cluster_name="c10")
    # Local provider: hosts_per_node comes from resources (1 for local);
    # num_nodes=2 -> 2 hosts, ranks 0/1.
    assert _wait(handle, job_id) == JobStatus.SUCCEEDED
    logs = TpuVmBackend().job_log_paths(handle, job_id)
    assert len(logs) == 2
    combined = "".join(open(p).read() for p in logs)
    assert "h=0 n=0 w=0 np=2" in combined
    assert "h=1 n=1 w=0 np=2" in combined


def test_refresh_detects_external_teardown():
    t = _local_task("echo z")
    job_id, handle = sky.launch(t, cluster_name="c11")
    _wait(handle, job_id)
    # Simulate out-of-band deletion (cloud console teardown).
    from skypilot_tpu.provision import local as local_provider
    local_provider.terminate_instances("c11", "local")
    records = sky.status(["c11"], refresh=True)
    assert records == []
    assert state.get_cluster("c11") is None
