"""Kubernetes provisioning offline: a recording fake kubectl shim.

Mirrors the reference's strategy of testing provisioning logic without a
cluster (reference: tests/unit_tests/kubernetes/).
"""

import json
import os
import stat
import textwrap

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import kubernetes as k8s
from skypilot_tpu.provision.common import ProvisionConfig


@pytest.fixture()
def fake_kubectl(tmp_path, monkeypatch):
    """A shim that records argv+stdin and replays scripted pod JSON."""
    record = tmp_path / "calls.jsonl"
    pods_file = tmp_path / "pods.json"
    pods_file.write_text(json.dumps({"items": []}))
    shim = tmp_path / "kubectl"
    shim.write_text(textwrap.dedent(f"""\
        #!/usr/bin/env python3
        import json, sys
        stdin = sys.stdin.read() if not sys.stdin.isatty() else ""
        with open({str(record)!r}, "a") as f:
            f.write(json.dumps({{"argv": sys.argv[1:], "stdin": stdin}})
                    + "\\n")
        if sys.argv[1:3] == ["get", "pods"]:
            print(open({str(pods_file)!r}).read())
        """))
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("SKYTPU_KUBECTL", str(shim))

    class Ctl:
        def calls(self):
            if not record.exists():
                return []
            return [json.loads(l) for l in record.read_text().splitlines()]

        def set_pods(self, items):
            pods_file.write_text(json.dumps({"items": items}))

    return Ctl()


def _cfg(**kw):
    defaults = dict(cluster_name="kt", num_nodes=1, hosts_per_node=4,
                    zone="us-central2-b", region="us-central2",
                    accelerator="tpu-v5e-16", accelerator_count=16)
    defaults.update(kw)
    return ProvisionConfig(**defaults)


def _pod_item(name, node, worker, phase="Running", ip="10.0.0.1"):
    return {"metadata": {"name": name,
                         "labels": {k8s.LABEL: "kt",
                                    k8s.NODE_LABEL: str(node),
                                    k8s.WORKER_LABEL: str(worker)}},
            "status": {"phase": phase, "podIP": ip}}


def test_pod_manifest_tpu_selectors():
    spec = k8s.pod_manifest(_cfg(), node_id=0, worker_id=2)
    sel = spec["spec"]["nodeSelector"]
    assert sel["cloud.google.com/gke-tpu-accelerator"] == \
        "tpu-v5-lite-podslice"
    assert sel["cloud.google.com/gke-tpu-topology"] == "4x4"
    # 16 chips over 4 hosts -> 4 chips per pod.
    res = spec["spec"]["containers"][0]["resources"]
    assert res["limits"]["google.com/tpu"] == "4"
    assert spec["metadata"]["labels"][k8s.WORKER_LABEL] == "2"


def test_pod_manifest_spot_tolerations():
    spec = k8s.pod_manifest(_cfg(use_spot=True), 0, 0)
    assert spec["spec"]["nodeSelector"]["cloud.google.com/gke-spot"] == \
        "true"
    assert any(t["key"] == "cloud.google.com/gke-spot"
               for t in spec["spec"]["tolerations"])


def test_pod_manifest_unknown_topology():
    with pytest.raises(exceptions.ProvisionError):
        k8s.pod_manifest(_cfg(accelerator="tpu-v5e-12"), 0, 0)


def test_run_instances_applies_all_pods(fake_kubectl):
    rec = k8s.run_instances(_cfg())
    assert len(rec.created_instance_ids) == 4
    applies = [c for c in fake_kubectl.calls() if c["argv"][0] == "apply"]
    assert len(applies) == 4
    manifest = json.loads(applies[0]["stdin"])
    assert manifest["metadata"]["name"] == "kt-0-0"


def test_query_and_wait(fake_kubectl):
    assert k8s.query_instances("kt", "z") == "NOT_FOUND"
    fake_kubectl.set_pods([_pod_item("kt-0-0", 0, 0, "Pending")])
    assert k8s.query_instances("kt", "z") == "PARTIAL"
    fake_kubectl.set_pods([_pod_item("kt-0-0", 0, 0, "Running")])
    assert k8s.query_instances("kt", "z") == "UP"
    k8s.wait_instances("kt", "z", timeout=5)


def test_get_cluster_info_orders_hosts(fake_kubectl):
    fake_kubectl.set_pods([
        _pod_item("kt-0-1", 0, 1, ip="10.0.0.2"),
        _pod_item("kt-0-0", 0, 0, ip="10.0.0.1"),
    ])
    info = k8s.get_cluster_info("kt", "z")
    assert [h.worker_id for h in info.hosts] == [0, 1]
    assert info.hosts[0].internal_ip == "10.0.0.1"
    runners = k8s.get_command_runners(info)
    assert [r.pod_name for r in runners] == ["kt-0-0", "kt-0-1"]


def test_terminate_and_stop(fake_kubectl):
    k8s.terminate_instances("kt", "z")
    deletes = [c for c in fake_kubectl.calls()
               if c["argv"][0] == "delete"]
    assert deletes and f"{k8s.LABEL}=kt" in deletes[0]["argv"]
    with pytest.raises(exceptions.NotSupportedError):
        k8s.stop_instances("kt", "z")


def test_feature_negotiation_registry():
    """Reference parity: CloudImplementationFeatures (cloud.py:29) —
    capabilities are declared per provider, not rediscovered ad hoc."""
    from skypilot_tpu import provision
    from skypilot_tpu.provision import Feature
    assert not provision.supports("kubernetes", Feature.STOP)
    assert provision.supports("kubernetes", Feature.MULTI_NODE_EXEC)
    assert provision.supports("kubernetes",
                              Feature.HOST_CONTROLLERS)
    assert provision.supports("gcp", Feature.MULTI_NODE_EXEC)
    assert provision.supports("local", Feature.STOP)
