"""Kubernetes provisioning offline: a recording fake kubectl shim.

Mirrors the reference's strategy of testing provisioning logic without a
cluster (reference: tests/unit_tests/kubernetes/).
"""

import json
import os
import stat
import textwrap

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import kubernetes as k8s
from skypilot_tpu.provision.common import ProvisionConfig


@pytest.fixture()
def fake_kubectl(tmp_path, monkeypatch):
    """A shim that records argv+stdin and replays scripted pod JSON."""
    record = tmp_path / "calls.jsonl"
    pods_file = tmp_path / "pods.json"
    pods_file.write_text(json.dumps({"items": []}))
    svc_file = tmp_path / "svc.json"
    ing_file = tmp_path / "ingress.json"
    nodes_file = tmp_path / "nodes.json"
    nodes_file.write_text(json.dumps({"items": [
        {"status": {"addresses": [
            {"type": "InternalIP", "address": "10.9.0.1"},
            {"type": "ExternalIP", "address": "34.9.0.1"}]}}]}))
    shim = tmp_path / "kubectl"
    # -S skips sitecustomize (which imports the axon JAX plugin, ~2s
    # per kubectl invocation; the shim is stdlib-only).
    shim.write_text(textwrap.dedent(f"""\
        #!/usr/bin/env -S python3 -S
        import json, os, sys
        stdin = sys.stdin.read() if not sys.stdin.isatty() else ""
        with open({str(record)!r}, "a") as f:
            f.write(json.dumps({{"argv": sys.argv[1:], "stdin": stdin}})
                    + "\\n")
        argv = sys.argv[1:]
        if argv[:2] == ["get", "pods"]:
            print(open({str(pods_file)!r}).read())
        elif argv[:2] == ["get", "nodes"]:
            print(open({str(nodes_file)!r}).read())
        elif argv[:2] == ["get", "service"]:
            if not os.path.exists({str(svc_file)!r}):
                print("not found", file=sys.stderr)
                sys.exit(1)
            print(open({str(svc_file)!r}).read())
        elif argv[:2] == ["get", "ingress"]:
            if not os.path.exists({str(ing_file)!r}):
                print("not found", file=sys.stderr)
                sys.exit(1)
            print(open({str(ing_file)!r}).read())
        elif argv[0] == "apply" and '"kind": "Service"' in stdin:
            # A minimal API server: NodePort Services get node ports
            # allocated; LoadBalancer Services get an external IP.
            svc = json.loads(stdin)
            if svc["spec"].get("type") == "NodePort":
                for i, p in enumerate(svc["spec"]["ports"]):
                    p.setdefault("nodePort", 30000 + i)
            if svc["spec"].get("type") == "LoadBalancer":
                svc["status"] = {{"loadBalancer": {{
                    "ingress": [{{"ip": "35.200.0.9"}}]}}}}
            with open({str(svc_file)!r}, "w") as f:
                json.dump(svc, f)
        elif argv[0] == "apply" and '"kind": "Ingress"' in stdin:
            ing = json.loads(stdin)
            ing["status"] = {{"loadBalancer": {{
                "ingress": [{{"ip": "34.120.0.7"}}]}}}}
            with open({str(ing_file)!r}, "w") as f:
                json.dump(ing, f)
        elif argv[:2] == ["delete", "service"]:
            if os.path.exists({str(svc_file)!r}):
                os.unlink({str(svc_file)!r})
        elif argv[:2] == ["delete", "ingress"]:
            if os.path.exists({str(ing_file)!r}):
                os.unlink({str(ing_file)!r})
        """))
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("SKYTPU_KUBECTL", str(shim))

    class Ctl:
        def calls(self):
            if not record.exists():
                return []
            return [json.loads(l) for l in record.read_text().splitlines()]

        def set_pods(self, items):
            pods_file.write_text(json.dumps({"items": items}))

        def service(self):
            return (json.loads(svc_file.read_text())
                    if svc_file.exists() else None)

        def ingress(self):
            return (json.loads(ing_file.read_text())
                    if ing_file.exists() else None)

    return Ctl()


def _cfg(**kw):
    defaults = dict(cluster_name="kt", num_nodes=1, hosts_per_node=4,
                    zone="us-central2-b", region="us-central2",
                    accelerator="tpu-v5e-16", accelerator_count=16)
    defaults.update(kw)
    return ProvisionConfig(**defaults)


def _pod_item(name, node, worker, phase="Running", ip="10.0.0.1"):
    return {"metadata": {"name": name,
                         "labels": {k8s.LABEL: "kt",
                                    k8s.NODE_LABEL: str(node),
                                    k8s.WORKER_LABEL: str(worker)}},
            "status": {"phase": phase, "podIP": ip}}


def test_pod_manifest_tpu_selectors():
    spec = k8s.pod_manifest(_cfg(), node_id=0, worker_id=2)
    sel = spec["spec"]["nodeSelector"]
    assert sel["cloud.google.com/gke-tpu-accelerator"] == \
        "tpu-v5-lite-podslice"
    assert sel["cloud.google.com/gke-tpu-topology"] == "4x4"
    # 16 chips over 4 hosts -> 4 chips per pod.
    res = spec["spec"]["containers"][0]["resources"]
    assert res["limits"]["google.com/tpu"] == "4"
    assert spec["metadata"]["labels"][k8s.WORKER_LABEL] == "2"


def test_pod_manifest_spot_tolerations():
    spec = k8s.pod_manifest(_cfg(use_spot=True), 0, 0)
    assert spec["spec"]["nodeSelector"]["cloud.google.com/gke-spot"] == \
        "true"
    assert any(t["key"] == "cloud.google.com/gke-spot"
               for t in spec["spec"]["tolerations"])


def test_pod_manifest_unknown_topology():
    with pytest.raises(exceptions.ProvisionError):
        k8s.pod_manifest(_cfg(accelerator="tpu-v5e-12"), 0, 0)


def test_run_instances_applies_all_pods(fake_kubectl):
    rec = k8s.run_instances(_cfg())
    assert len(rec.created_instance_ids) == 4
    applies = [c for c in fake_kubectl.calls() if c["argv"][0] == "apply"]
    assert len(applies) == 4
    manifest = json.loads(applies[0]["stdin"])
    assert manifest["metadata"]["name"] == "kt-0-0"


def test_query_and_wait(fake_kubectl):
    assert k8s.query_instances("kt", "z") == "NOT_FOUND"
    fake_kubectl.set_pods([_pod_item("kt-0-0", 0, 0, "Pending")])
    assert k8s.query_instances("kt", "z") == "PARTIAL"
    fake_kubectl.set_pods([_pod_item("kt-0-0", 0, 0, "Running")])
    assert k8s.query_instances("kt", "z") == "UP"
    k8s.wait_instances("kt", "z", timeout=5)


def test_get_cluster_info_orders_hosts(fake_kubectl):
    fake_kubectl.set_pods([
        _pod_item("kt-0-1", 0, 1, ip="10.0.0.2"),
        _pod_item("kt-0-0", 0, 0, ip="10.0.0.1"),
    ])
    info = k8s.get_cluster_info("kt", "z")
    assert [h.worker_id for h in info.hosts] == [0, 1]
    assert info.hosts[0].internal_ip == "10.0.0.1"
    runners = k8s.get_command_runners(info)
    assert [r.pod_name for r in runners] == ["kt-0-0", "kt-0-1"]


def test_terminate_and_stop(fake_kubectl):
    k8s.terminate_instances("kt", "z")
    deletes = [c for c in fake_kubectl.calls()
               if c["argv"][0] == "delete"]
    # terminate removes the Service (port cleanup) AND the pods.
    assert any(f"{k8s.LABEL}=kt" in c["argv"] for c in deletes)
    assert any("service" in c["argv"] for c in deletes)
    with pytest.raises(exceptions.NotSupportedError):
        k8s.stop_instances("kt", "z")


def test_feature_negotiation_registry():
    """Reference parity: CloudImplementationFeatures (cloud.py:29) —
    capabilities are declared per provider, not rediscovered ad hoc."""
    from skypilot_tpu import provision
    from skypilot_tpu.provision import Feature
    assert not provision.supports("kubernetes", Feature.STOP)
    assert provision.supports("kubernetes", Feature.MULTI_NODE_EXEC)
    assert provision.supports("kubernetes",
                              Feature.HOST_CONTROLLERS)
    assert provision.supports("gcp", Feature.MULTI_NODE_EXEC)
    assert provision.supports("local", Feature.STOP)


# -- networking: NodePort Service exposure ----------------------------------

def test_ports_create_nodeport_service(fake_kubectl):
    k8s.run_instances(_cfg(ports=[8080, 9000]))
    svc = fake_kubectl.service()
    assert svc is not None
    assert svc["spec"]["type"] == "NodePort"
    assert svc["spec"]["selector"] == {
        k8s.LABEL: "kt", k8s.NODE_LABEL: "0", k8s.WORKER_LABEL: "0"}
    assert [p["port"] for p in svc["spec"]["ports"]] == [8080, 9000]


def test_query_ports_maps_node_address(fake_kubectl):
    k8s.run_instances(_cfg(ports=[8080]))
    eps = k8s.query_ports("kt")
    # The fake API allocates nodePort 30000; node ExternalIP preferred.
    assert eps == {8080: "34.9.0.1:30000"}


def test_dispatcher_query_ports(fake_kubectl):
    """provision.query_ports routes to the k8s provider; providers
    without port exposure answer {} without a provider call."""
    from skypilot_tpu import provision
    k8s.run_instances(_cfg(ports=[8080]))
    assert provision.query_ports("kubernetes", "kt") == \
        {8080: "34.9.0.1:30000"}
    assert provision.query_ports("local", "whatever") == {}


def test_terminate_cleans_up_service(fake_kubectl):
    k8s.run_instances(_cfg(ports=[8080]))
    assert fake_kubectl.service() is not None
    k8s.terminate_instances("kt", "us-central2-b")
    assert fake_kubectl.service() is None
    assert k8s.query_ports("kt") == {}


def test_no_service_without_ports(fake_kubectl):
    k8s.run_instances(_cfg())
    assert fake_kubectl.service() is None
    fake_kubectl.set_pods([_pod_item("kt-0-0", 0, 0)])
    info = k8s.get_cluster_info("kt", "us-central2-b")
    assert "port_endpoints" not in info.metadata


def test_port_forward_command(fake_kubectl):
    cmd = k8s.port_forward_command("kt", 8080, local_port=18080)
    assert "port-forward" in cmd
    assert "service/kt-skytpu-svc" in cmd
    assert "18080:8080" in cmd


def test_replica_url_prefers_port_endpoints(monkeypatch, tmp_path):
    """serve's replica URL uses the NodePort endpoint when the provider
    publishes one (pod IPs are cluster-internal)."""
    monkeypatch.setenv("SKYPILOT_TPU_HOME", str(tmp_path / "h"))
    from skypilot_tpu import provision
    from skypilot_tpu.serve import replica_managers
    from skypilot_tpu.serve.service_spec import SkyServiceSpec

    monkeypatch.setattr(
        provision, "query_ports",
        lambda provider, name: {8080: "34.9.0.1:30123"}
        if provider == "kubernetes" else {})
    spec = SkyServiceSpec.from_yaml_config({"readiness_probe": "/",
                                            "port": 8080, "replicas": 1})
    mgr = replica_managers.ReplicaManager(
        "s", spec, {"resources": {"cloud": "kubernetes"}})
    from skypilot_tpu.backend import ClusterHandle
    handle = ClusterHandle({"cluster_name": "c", "provider": "kubernetes",
                            "zone": "z"})
    assert mgr._replica_url(handle, 1) == "http://34.9.0.1:30123"


def test_replica_port_override_normalizes_forms():
    """The schema allows ports as string/scalar forms; the replica
    override must not crash on them (a TypeError here silently FAILs
    every replica)."""
    from skypilot_tpu.serve.replica_managers import \
        _apply_resource_overrides
    for raw in (["8080"], "8080", 8080, None, [8080, "8081"]):
        cfg = _apply_resource_overrides(
            {"resources": {"cloud": "local", "ports": raw}},
            use_spot=None, port=9001)
        ports = cfg["resources"]["ports"]
        assert 9001 in ports
        assert all(isinstance(p, int) for p in ports)
    # List-of-resources form + spot override compose.
    cfg = _apply_resource_overrides(
        {"resources": [{"cloud": "local"}, {"cloud": "gcp"}]},
        use_spot=True, port=8080)
    assert all(r["use_spot"] and r["ports"] == [8080]
               for r in cfg["resources"])


# -- GPU-on-k8s + ingress/LoadBalancer exposure (VERDICT r3 #9) --------------

def test_pod_manifest_gpu_selectors():
    cfg = _cfg(accelerator="A100", accelerator_count=8)
    spec = k8s.pod_manifest(cfg, 0, 0)
    sel = spec["spec"]["nodeSelector"]
    assert sel["cloud.google.com/gke-accelerator"] == "nvidia-tesla-a100"
    res = spec["spec"]["containers"][0]["resources"]
    assert res["requests"]["nvidia.com/gpu"] == "8"
    assert res["limits"]["nvidia.com/gpu"] == "8"
    assert any(t["key"] == "nvidia.com/gpu"
               for t in spec["spec"]["tolerations"])


def test_pod_manifest_unknown_gpu():
    with pytest.raises(exceptions.ProvisionError):
        k8s.pod_manifest(_cfg(accelerator="RTX9999",
                              accelerator_count=1), 0, 0)


def test_pod_manifest_gpu_spot():
    spec = k8s.pod_manifest(_cfg(accelerator="A100",
                                 accelerator_count=1,
                                 use_spot=True), 0, 0)
    assert spec["spec"]["nodeSelector"][
        "cloud.google.com/gke-spot"] == "true"
    assert any(t["key"] == "cloud.google.com/gke-spot"
               for t in spec["spec"]["tolerations"])


def test_pod_manifest_docker_image_id():
    """docker:<img> on k8s: the pod IS the container — the bare image
    becomes the pod image (not the literal 'docker:...' reference)."""
    spec = k8s.pod_manifest(_cfg(image_id="docker:myorg/env:7"), 0, 0)
    assert spec["spec"]["containers"][0]["image"] == "myorg/env:7"
    # Plain image ids pass through untouched.
    spec = k8s.pod_manifest(_cfg(image_id="ubuntu:22.04"), 0, 0)
    assert spec["spec"]["containers"][0]["image"] == "ubuntu:22.04"


def test_loadbalancer_mode(fake_kubectl):
    from skypilot_tpu import config as config_lib
    with config_lib.replace_config({"kubernetes":
                                    {"ports": "loadbalancer"}}):
        k8s.open_ports("kt", [8080, 9090])
        svc = fake_kubectl.service()
        assert svc["spec"]["type"] == "LoadBalancer"
        eps = k8s.query_ports("kt")
    assert eps == {8080: "35.200.0.9:8080", 9090: "35.200.0.9:9090"}


def test_ingress_mode_endpoints(fake_kubectl):
    from skypilot_tpu import config as config_lib
    with config_lib.replace_config({"kubernetes": {"ports": "ingress"}}):
        k8s.open_ports("kt", [8080])
        svc = fake_kubectl.service()
        assert svc["spec"]["type"] == "ClusterIP"
        ing = fake_kubectl.ingress()
        path = ing["spec"]["rules"][0]["http"]["paths"][0]
        assert path["backend"]["service"]["port"]["number"] == 8080
        assert "/skytpu/kt/8080" in path["path"]
        eps = k8s.query_ports("kt")
    # Ingress endpoints are path-based and flow into query_ports the
    # way NodePort endpoints do (usable as http://{endpoint}).
    assert eps == {8080: "34.120.0.7/skytpu/kt/8080"}
    k8s.cleanup_ports("kt")
    assert fake_kubectl.ingress() is None


def test_bad_ports_mode_rejected():
    from skypilot_tpu import config as config_lib
    with config_lib.replace_config({"kubernetes": {"ports": "magic"}}):
        with pytest.raises(exceptions.ProvisionError):
            k8s.ports_mode()
