"""Flight recorder + compile watch: burst records, ring discipline,
the unexpected-compile alarm, metrics<->record consistency, and the
CLI/trace surfaces (docs/observability.md §Flight recorder)."""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.models import llama
from skypilot_tpu.observability import flight as fl
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import trace_view, tracing


# ---------------------------------------------------------------------------
# Recorder core.

def test_ring_bounded():
    rec = fl.FlightRecorder(capacity=16)
    for i in range(100):
        rec.record("decode", toks=i)
    recs = rec.tail()
    assert len(recs) == 16
    # Oldest dropped, newest kept, seq monotone.
    assert [r["toks"] for r in recs] == list(range(84, 100))
    assert rec.seq() == 100


def test_concurrent_records_thread_safe():
    rec = fl.FlightRecorder(capacity=10_000)
    n_threads, per = 8, 200

    def worker(t):
        for i in range(per):
            rec.record("decode", t=t, i=i)

    ts = [threading.Thread(target=worker, args=(t,))
          for t in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    recs = rec.tail()
    assert len(recs) == n_threads * per
    # Every record intact and uniquely sequenced.
    assert len({r["seq"] for r in recs}) == n_threads * per


def test_suppress_honored():
    rec = fl.FlightRecorder()
    with metrics_lib.suppress():
        rec.record("decode", toks=1)
    assert rec.tail() == []
    rec.record("decode", toks=1)
    assert len(rec.tail()) == 1


def test_disabled_recorder_is_noop():
    rec = fl.FlightRecorder()
    rec.enabled = False
    rec.record("decode", toks=1)
    assert rec.tail() == [] and rec.seq() == 0
    rec.enabled = True
    rec.record("decode", toks=1)
    assert rec.seq() == 1


def test_env_disable(monkeypatch):
    monkeypatch.setenv("SKYTPU_FLIGHT", "0")
    assert fl.FlightRecorder().enabled is False
    monkeypatch.delenv("SKYTPU_FLIGHT")
    assert fl.FlightRecorder().enabled is True


def test_flush_load_roundtrip_and_corrupt_skip(tmp_path, monkeypatch):
    monkeypatch.setenv(tracing.EVENTS_DIR_ENV_VAR, str(tmp_path))
    rec = fl.FlightRecorder()
    rec.record("decode", ts_s=2.0, toks=3,
               program={"k": 8, "span": 64, "layout": "paged"})
    rec.record("chunk", ts_s=1.0, toks=1,
               program={"final": True, "layout": "paged"})
    rec.flush()
    files = [n for n in os.listdir(tmp_path) if n.startswith("flight-")]
    assert len(files) == 1
    # A torn/corrupt line and a foreign file must be skipped quietly.
    with open(tmp_path / files[0], "a", encoding="utf-8") as f:
        f.write("{not json\n")
    (tmp_path / "flight-foreign-1-2.jsonl").write_text("junk\n{}\n")
    loaded = fl.load_records(dirs=[str(tmp_path)])
    assert [r["burst"] for r in loaded] == ["chunk", "decode"]  # ts order
    # Idempotent flush: nothing new -> no rewrite needed.
    rec.flush()
    assert len([n for n in os.listdir(tmp_path)
                if n.startswith("flight-")]) == 2


# ---------------------------------------------------------------------------
# Compile watch.

def test_compile_watch_keys_costs_and_unexpected():
    watch = fl.CompileWatch()
    calls = []
    wrapped = watch.wrap("prog", lambda *a, **kw: calls.append(kw),
                         ("k", "span"))
    before = metrics_lib.REGISTRY.snapshot()
    wrapped(1, k=8, span=64)
    wrapped(1, k=8, span=64)          # cached key: no new program
    wrapped(1, k=4, span=64)
    assert watch.count == 2
    assert set(watch.summary()) == {"prog[k=8 span=64]",
                                    "prog[k=4 span=64]"}
    assert watch.drain_new() == ["prog[k=8 span=64]",
                                 "prog[k=4 span=64]"]
    assert watch.drain_new() == []
    assert not watch.unexpected and not watch.warm
    after = metrics_lib.REGISTRY.snapshot()

    def delta(name, key="value"):
        def total(snap):
            return sum(s[key] for s in snap[name]["samples"]) \
                if name in snap else 0
        return total(after) - total(before)

    assert delta("skytpu_programs_compiled_total") == 2
    assert delta("skytpu_unexpected_compiles_total") == 0
    # Post-warm compiles alarm: counter + typed echo event.
    watch.declare_warm()
    wrapped(1, k=2, span=None)
    assert watch.unexpected == ["prog[k=2 span=None]"]
    snap3 = metrics_lib.REGISTRY.snapshot()
    assert (sum(s["value"] for s in
                snap3["skytpu_unexpected_compiles_total"]["samples"])
            - sum(s["value"] for s in
                  after["skytpu_unexpected_compiles_total"]["samples"])
            ) == 1
    events = [r for r in tracing.buffered_records()
              if r.get("name") == "engine.unexpected_compile"]
    assert events and events[-1]["attrs"]["program"] == \
        "prog[k=2 span=None]"


def test_compile_watch_key_fn_shape_identity():
    watch = fl.CompileWatch()
    wrapped = watch.wrap("wave", lambda *a, **kw: None, ("bucket",),
                         key_fn=lambda a, kw: (("rows", len(a[0])),))
    wrapped([1, 2], bucket=128)
    wrapped([1, 2, 3], bucket=128)    # same statics, new shape
    assert set(watch.summary()) == {"wave[bucket=128 rows=2]",
                                    "wave[bucket=128 rows=3]"}


# ---------------------------------------------------------------------------
# Engine integration: one tiny engine, the full mixed workload.

def _mk_engine(**overrides):
    cfg = llama.CONFIGS["llama3-tiny"]
    params = llama.init_params(jax.random.key(0), cfg)
    kw = dict(n_slots=4, max_len=128, prompt_buckets=(16, 64),
              prefill_chunk=8, prefix_pool=4, spec_k=2, kv_block=16,
              max_wave=4, pad_waves=True,
              flight_recorder=fl.FlightRecorder())
    kw.update(overrides)
    return eng.InferenceEngine(params, cfg, **kw)


def _mixed_prompts(n_short=2, n_long=2):
    rng = np.random.default_rng(7)
    shorts = [rng.integers(1, 40, 6).tolist() for _ in range(n_short)]
    longs = [rng.integers(1, 40, 20).tolist() for _ in range(n_long)]
    return shorts + longs


@pytest.fixture(scope="module")
def flown_engine():
    """One engine driven through the mixed workload (waves + chunked
    admission + spec verify + decode bursts), plus the counter
    snapshots around the run — shared by the coverage and consistency
    tests (compile cost paid once)."""
    e = _mk_engine()
    before = metrics_lib.REGISTRY.snapshot()
    seq0 = e.flight.seq()
    prompts = _mixed_prompts()
    ids = [e.add_request(p, max_new_tokens=10) for p in prompts]
    e.run_to_completion(max_burst=4)
    finished = {r.rid: r for r in e.finished}
    after = metrics_lib.REGISTRY.snapshot()
    window = e.flight.since(seq0)
    return e, window, before, after, ids, finished


def _counter_delta(before, after, name):
    def total(snap):
        if name not in snap:
            return 0.0
        return sum(s.get("value", s.get("count", 0))
                   for s in snap[name]["samples"])
    return total(after) - total(before)


def _hist_count_delta(before, after, name):
    def total(snap):
        if name not in snap:
            return 0
        return sum(s["count"] for s in snap[name]["samples"])
    return total(after) - total(before)


def test_every_burst_has_a_record_with_matching_identity(flown_engine):
    e, window, _, _, ids, finished = flown_engine
    kinds = {r["burst"] for r in window}
    assert {"wave", "chunk"} <= kinds
    assert kinds & {"decode", "verify"}
    # Program identity on decode-side records == what the engine
    # actually selected (both directions).
    rec_dv = {(r["program"]["k"], r["program"]["span"])
              for r in window if r["burst"] in ("decode", "verify")}
    eng_dv = {(k, s) for kind, k, s in e.decode_programs
              if kind in ("burst", "verify")}
    assert rec_dv == eng_dv
    # Layout stamped on every record; host timing sane.
    assert all(r["program"]["layout"] == "paged" for r in window)
    assert all(r["dur_s"] >= 0 and r["ts_s"] > 0 for r in window)
    # Group composition: every record's rids/traces are the member
    # requests', and every finished request appears in some record.
    for r in window:
        assert len(r["rids"]) == len(r["traces"]) <= len(r["slots"]) \
            or r["burst"] in ("wave", "chunk")
        for rid in r["rids"]:
            assert rid in finished
            assert finished[rid].span_ctx.trace_id in r["traces"]
    seen_rids = {rid for r in window for rid in r["rids"]}
    assert set(ids) <= seen_rids
    # The first dispatches compiled: some record carries the compile
    # attribution.
    assert any(r.get("compiled") for r in window)


def test_counter_deltas_match_record_sums(flown_engine):
    """The metrics-consistency gate (ISSUE 10 satellite): over a mixed
    chunk+verify+wave workload, every serving counter's delta equals
    the sum over flight-recorder records — double-counting on any
    path would split them apart."""
    _, window, before, after, _, _ = flown_engine
    chunks = sum(1 for r in window if r["burst"] == "chunk")
    assert _counter_delta(before, after,
                          "skytpu_prefill_chunks_total") == chunks
    decode_toks = sum(r["toks"] for r in window
                      if r["burst"] in ("decode", "verify", "decode1"))
    assert _counter_delta(before, after,
                          "skytpu_decode_tokens_total") == decode_toks
    drafted = sum(r.get("drafted", 0) for r in window)
    accepted = sum(r.get("accepted", 0) for r in window)
    assert _counter_delta(before, after,
                          "skytpu_spec_drafted_total") == drafted
    assert _counter_delta(before, after,
                          "skytpu_spec_accepted_total") == accepted
    assert _counter_delta(
        before, after, "skytpu_spec_rollbacks_total") == \
        drafted - accepted
    # Prefill completions: one wave row or final chunk per request.
    waves_toks = sum(r["toks"] for r in window if r["burst"] == "wave")
    finals = sum(1 for r in window
                 if r["burst"] == "chunk" and r["program"]["final"])
    assert _counter_delta(before, after,
                          "skytpu_prefill_requests_total") == \
        waves_toks + finals
    # Decode-stall observations == records flagged as interference.
    stalls = sum(1 for r in window if r.get("stall"))
    assert _hist_count_delta(before, after,
                             "skytpu_decode_stall_seconds") == stalls
    # Device-truth attribution (ISSUE 16): the roofline counters are
    # incremented on the SAME path that stamps the record fields — a
    # record with a cost and no counter inc (or vice versa) splits
    # these. flops are stamped on every costed burst, so the workload
    # must have produced some.
    flops = sum(r.get("flops", 0) for r in window)
    hbm = sum(r.get("hbm_bytes", 0) for r in window)
    assert flops > 0 and hbm > 0
    assert _counter_delta(before, after,
                          "skytpu_device_flops_total") == flops
    assert _counter_delta(before, after,
                          "skytpu_device_hbm_moved_bytes_total") == hbm
    # dev_ms_est is rounded on the record; the counter takes the raw
    # value — equal to rounding noise.
    dev_s = sum(r.get("dev_ms_est", 0.0) for r in window) / 1e3
    assert _counter_delta(before, after,
                          "skytpu_device_seconds_total") == \
        pytest.approx(dev_s, abs=1e-6)
    # The host-wall split sums back to dur_s exactly wherever present.
    for r in window:
        if "dispatch_wall_ms" in r:
            assert r["dispatch_wall_ms"] >= 0
            assert r["fetch_wall_ms"] >= 0
            assert r["dispatch_wall_ms"] + r["fetch_wall_ms"] == \
                pytest.approx(r["dur_s"] * 1e3, abs=1e-3)
    assert any("dispatch_wall_ms" in r for r in window)


def test_ledger_sums_to_wall(flown_engine):
    """The ledger-sums gate (ISSUE 17): every retired request of the
    mixed workload gets a forensics ledger whose phases sum to the
    measured submit->retire wall (exact partition to rounding), with
    >=90% of the wall in NAMED phases — an unsorted ring or a
    double-counted overlap breaks the sum, a classification hole
    breaks the coverage."""
    from skypilot_tpu.observability import forensics

    _, window, _, _, ids, finished = flown_engine
    retires = [r for r in window if r["burst"] == "retire"]
    assert {r["rids"][0] for r in retires} == set(ids)
    for rid in ids:
        led = forensics.ledger_from_records(rid, window)
        assert led is not None
        total = sum(p["ms"] for p in led["phases"])
        assert total == pytest.approx(led["wall_ms"], abs=0.05), \
            f"rid {rid}: phases sum {total} != wall {led['wall_ms']}"
        assert led["named_ms"] >= 0.90 * led["wall_ms"], \
            f"rid {rid}: named {led['named_ms']} < 90% of " \
            f"{led['wall_ms']}"
        assert led["named_ms"] + led["other_ms"] == \
            pytest.approx(led["wall_ms"], abs=0.05)
        # The retire record mirrors the request's own stamps.
        req = finished[rid]
        assert led["wall_ms"] > 0
        assert led["detail"]["n_toks"] == len(req.tokens)
        # Renders without crashing, names the request.
        assert f"request {rid}" in forensics.render_ledger(led)


def test_chunk_verify_interleave_consistency():
    """The ISSUE-named audit path: chunked prefills interleaving with
    LIVE speculative verify bursts (small vocab => the drafter
    actually drafts). Counter deltas must equal flight-record sums —
    a double count on either side of the interleave splits them."""
    import dataclasses
    cfg = dataclasses.replace(llama.CONFIGS["llama3-tiny"],
                              vocab_size=12)
    params = llama.init_params(jax.random.key(0), cfg)
    e = eng.InferenceEngine(
        params, cfg, n_slots=4, max_len=128, prompt_buckets=(16, 64),
        prefill_chunk=8, prefix_pool=4, spec_k=3, kv_block=16,
        max_wave=4, pad_waves=True,
        flight_recorder=fl.FlightRecorder())
    rng = np.random.default_rng(1)
    before = metrics_lib.REGISTRY.snapshot()
    seq0 = e.flight.seq()
    # Stagger: shorts decode (spec kicks in on the cycling small-vocab
    # output), THEN longs arrive so their chunks interleave with live
    # verify bursts.
    for _ in range(2):
        e.add_request(rng.integers(1, 12, 6).tolist(),
                      max_new_tokens=40)
    e.admit()
    for _ in range(3):
        e.decode_burst(4)
    for _ in range(2):
        e.add_request(rng.integers(1, 12, 30).tolist(),
                      max_new_tokens=40)
    e.run_to_completion(max_burst=4)
    after = metrics_lib.REGISTRY.snapshot()
    window = e.flight.since(seq0)
    # The scenario actually interleaved: chunks AND drafting verifies.
    assert sum(1 for r in window if r["burst"] == "chunk") > 0
    assert sum(1 for r in window if r.get("drafted")) > 0
    drafted = sum(r.get("drafted", 0) for r in window)
    accepted = sum(r.get("accepted", 0) for r in window)
    assert drafted > 0 and 0 < accepted <= drafted
    assert _counter_delta(before, after,
                          "skytpu_spec_drafted_total") == drafted
    assert _counter_delta(before, after,
                          "skytpu_spec_accepted_total") == accepted
    assert _counter_delta(before, after,
                          "skytpu_spec_rollbacks_total") == \
        drafted - accepted
    assert _counter_delta(before, after,
                          "skytpu_prefill_chunks_total") == \
        sum(1 for r in window if r["burst"] == "chunk")
    assert _counter_delta(before, after,
                          "skytpu_decode_tokens_total") == \
        sum(r["toks"] for r in window
            if r["burst"] in ("decode", "verify", "decode1"))
    assert _hist_count_delta(before, after,
                             "skytpu_decode_stall_seconds") == \
        sum(1 for r in window if r.get("stall"))


def test_reset_mid_flight_ring_survives():
    e = _mk_engine()
    rec = e.flight
    # Long prompt -> chunked claim; run ONE chunk then reset with the
    # prefill mid-flight.
    e.add_request(list(range(1, 21)), max_new_tokens=4)
    e.admit()
    assert e.chunking
    e.prefill_chunk_step()
    n = rec.seq()
    assert n >= 1
    e.reset()
    # Ring survives the reset (history is the point), bounded, and
    # the engine serves cleanly afterwards with records flowing.
    assert rec.seq() == n
    out = e.generate([[1, 2, 3]], max_new_tokens=3)
    assert len(out[0]) == 3
    assert rec.seq() > n
    assert len(rec.tail()) <= rec.capacity
    # No block leak across the reset + rerun.
    assert e.blocks_used == 0


def test_recorder_off_engine_still_serves():
    e = _mk_engine()
    e.flight.enabled = False
    out = e.generate(_mixed_prompts(1, 1), max_new_tokens=5)
    assert all(len(o) == 5 for o in out)
    assert e.flight.tail() == []


def test_warm_programs_then_zero_unexpected():
    e = _mk_engine()
    n = e.warm_programs(max_burst=8)   # generate() bursts at k<=8
    assert n > 0
    e.declare_warmup_complete()
    e.generate(_mixed_prompts(), max_new_tokens=10)
    assert e.compile_watch.unexpected == []
    # And warming is idempotent: a second sweep compiles nothing.
    assert e.warm_programs(max_burst=8) == 0


def test_unwarmed_engine_alarms_after_declare():
    e = _mk_engine()
    e.declare_warmup_complete()           # lie: nothing compiled yet
    e.generate([[1, 2, 3]], max_new_tokens=3)
    assert e.compile_watch.unexpected     # the alarm fired
    snap = metrics_lib.REGISTRY.snapshot()
    assert sum(s["value"] for s in
               snap["skytpu_unexpected_compiles_total"]["samples"]) > 0
    # Every unexpected key rode some burst record's compile
    # attribution or the pre-burst drain — the typed event always
    # lands.
    names = [r.get("name") for r in tracing.buffered_records()]
    assert "engine.unexpected_compile" in names


# ---------------------------------------------------------------------------
# Trace link + CLI surfaces.

@pytest.fixture()
def fresh_events(tmp_path, monkeypatch):
    monkeypatch.setenv(tracing.EVENTS_DIR_ENV_VAR, str(tmp_path))
    monkeypatch.delenv(tracing.ENV_VAR, raising=False)
    tracing._reset_for_tests()
    yield str(tmp_path)
    tracing._reset_for_tests()


def test_trace_shows_bursts_ridden(fresh_events):
    e = _mk_engine()
    rid = e.add_request(list(range(1, 21)), max_new_tokens=6)
    e.run_to_completion(max_burst=4)
    req = next(r for r in e.finished if r.rid == rid)
    trace_id = req.span_ctx.trace_id
    tracing.flush()
    e.flight.flush()
    records = trace_view.load_trace(trace_id, dirs=[fresh_events])
    flights = [r for r in records if r.get("kind") == "flight"]
    assert flights, "flight records must join the request's trace"
    assert all(trace_id in r["traces"] for r in flights)
    rendered = trace_view.render(records, trace_id)
    assert "bursts ridden" in rendered
    assert "engine.request" in rendered
    # Perfetto export carries the bursts as duration events.
    pf = trace_view.to_perfetto(records)
    assert any(ev.get("ph") == "X" and "chunk[" in ev.get("name", "")
               for ev in pf["traceEvents"])


def test_flight_cli_local_and_perfetto(fresh_events, tmp_path):
    from click.testing import CliRunner

    from skypilot_tpu.client import cli as cli_mod

    e = _mk_engine()
    e.generate(_mixed_prompts(1, 1), max_new_tokens=5)
    e.flight.flush()
    runner = CliRunner()
    res = runner.invoke(cli_mod.cli, ["flight", "--local"])
    assert res.exit_code == 0, res.output
    assert "per-program summary" in res.output
    assert "decode[" in res.output or "wave[" in res.output
    pf_path = str(tmp_path / "flight.json")
    res2 = runner.invoke(cli_mod.cli,
                         ["flight", "--local", "--perfetto", pf_path])
    assert res2.exit_code == 0, res2.output
    with open(pf_path, encoding="utf-8") as f:
        pf = json.load(f)
    assert pf["traceEvents"]


def test_flight_cli_empty_dir(fresh_events):
    from click.testing import CliRunner

    from skypilot_tpu.client import cli as cli_mod

    res = CliRunner().invoke(cli_mod.cli, ["flight", "--local"])
    assert res.exit_code == 0
    assert "no flight records" in res.output


def test_render_table_flags_compiles():
    recs = [{"kind": "flight", "burst": "decode", "ts_s": 1.0,
             "dur_s": 0.01, "toks": 8, "slots": [0, 1],
             "program": {"k": 8, "span": 64, "layout": "paged"},
             "compiled": ["decode_burst[k=8 span=64]"]},
            {"kind": "flight", "burst": "verify", "ts_s": 1.1,
             "dur_s": 0.02, "toks": 5, "slots": [0],
             "program": {"k": 4, "span": 64, "layout": "paged"},
             "drafted": 4, "accepted": 3}]
    out = fl.render_table(recs, {"decode_burst[k=8 span=64]": 1.25})
    assert "COMPILED=1" in out
    assert "spec 3/4" in out
    assert "decode_burst[k=8 span=64]" in out and "1250.0ms" in out


def test_summarize_rollup():
    recs = [{"burst": "decode", "ts_s": 1.0, "dur_s": 0.01, "toks": 4,
             "program": {"k": 8, "span": 64, "layout": "paged"}},
            {"burst": "decode", "ts_s": 1.1, "dur_s": 0.03, "toks": 6,
             "program": {"k": 8, "span": 64, "layout": "paged"}}]
    agg = fl.summarize(recs)
    (label,) = agg
    assert label == "decode[k=8 span=64 paged]"
    assert agg[label]["count"] == 2 and agg[label]["toks"] == 10
    assert agg[label]["mean_ms"] == 20.0


# ---------------------------------------------------------------------------
# SLO wiring.

def test_unexpected_compiles_slo_rule_registered():
    from skypilot_tpu.observability import slo
    (rule,) = [r for r in slo.DEFAULT_RULES
               if r.name == "unexpected-compiles"]
    assert rule.kind == "rate" and rule.threshold == 0.0
    assert rule.metric == "skytpu_unexpected_compiles_total"


def test_unexpected_compiles_rule_breaches_on_one_compile():
    from skypilot_tpu.observability import slo
    (rule,) = [r for r in slo.DEFAULT_RULES
               if r.name == "unexpected-compiles"]

    def fams(v):
        return {"skytpu_unexpected_compiles_total": {
            "type": "counter", "samples": [({}, v)]}}

    t0 = time.time()
    history = [(t0 - 400, fams(0), []), (t0 - 90, fams(0), []),
               (t0, fams(1), [])]
    breached, short, long_ = slo.evaluate_rule(rule, history)
    assert breached and short > 0 and long_ > 0
    quiet = [(t0 - 400, fams(1), []), (t0 - 90, fams(1), []),
             (t0, fams(1), [])]
    assert not slo.evaluate_rule(rule, quiet)[0]


# ---------------------------------------------------------------------------
# Bench wiring (CI-sized smoke — structure asserted, wall-clock never).

def test_flight_smoke_bench_wiring():
    from skypilot_tpu.infer import bench_serve
    r = bench_serve.run_flight_smoke()
    assert r["unexpected_compiles"] == 0
    assert r["coverage_ok"] and r["parity_ok"]
    assert r["n_records"] > 0
    for layout in ("paged", "contig"):
        det = r["layouts"][layout]
        assert det["unexpected_compiles"] == 0
        assert det["n_chunk_records"] > 0 and det["n_wave_records"] > 0
