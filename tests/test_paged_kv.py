"""Paged block-table KV cache: allocator invariants, paged-vs-
contiguous bit parity, COW prefix sharing, leak audits, occupancy.

Tier-1 guards for the PR-7 scale refactor (ROADMAP item 1):

* The host-side ``BlockAllocator`` preserves ref-count invariants
  under randomized alloc/incref/decref sequences (no double-free, no
  two-writer blocks) — pure host, no device needed.
* Paged-vs-contiguous greedy generation is BIT-identical (fp32 and
  int8), warm-vs-cold prefix hits included: the paged programs gather
  the same values in the same order, so this is the PR-5 parity
  guarantee extended across storage layouts.
* ``reset()`` / ``clear_prefix_cache()`` free every block — a full
  admit/retire cycle ends at ``blocks_used == 0``.
* The occupancy smoke bench shows >= 4x concurrent slots at equal KV
  HBM bytes.
"""

import random

import jax
import numpy as np
import pytest

from skypilot_tpu.infer import engine as eng
from skypilot_tpu.infer import kvcache
from skypilot_tpu.models import llama


@pytest.fixture(scope="module")
def cfg():
    return llama.CONFIGS["llama3-tiny"]


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.key(0), cfg)


def _pair(params, cfg, *, kv_block, chunk=8, pool=4, slots=4,
          max_len=64, buckets=(16, 48), **kw):
    """(paged engine, contiguous twin) with otherwise identical knobs."""
    mk = lambda blk: eng.InferenceEngine(
        params, cfg, n_slots=slots, max_len=max_len,
        prompt_buckets=buckets, prefill_chunk=chunk, prefix_pool=pool,
        kv_block=blk, **kw)
    return mk(kv_block), mk(0)


# ---------------------------------------------------------------------------
# Satellite 1: allocator property/fuzz (host-only).

def test_block_allocator_invariants_fuzz():
    """Random alloc/incref/decref sequences preserve the invariants:
    ref counts match a model, freed blocks recycle, a block never has
    two writers (ref > 1 => not writable), and double-free raises."""
    rng = random.Random(0)
    n = 16
    for _ in range(50):
        a = kvcache.BlockAllocator(n)
        model = {}                      # block -> refcount
        for _ in range(400):
            op = rng.random()
            if op < 0.45 and a.available:
                b = a.alloc()
                assert b not in model, "alloc handed out a live block"
                assert 0 <= b < n
                model[b] = 1
            elif op < 0.65 and model:
                b = rng.choice(list(model))
                a.incref(b)
                model[b] += 1
            elif model:
                b = rng.choice(list(model))
                a.decref(b)
                model[b] -= 1
                if model[b] == 0:
                    del model[b]
            # Invariants after every op.
            assert a.used == len(model)
            assert a.available == n - len(model)
            for b, refs in model.items():
                assert a.ref(b) == refs
                assert a.writable(b) == (refs == 1)
        # Double-free of anything not live must raise, never corrupt.
        dead = next((b for b in range(n) if b not in model), None)
        if dead is not None:
            with pytest.raises(RuntimeError):
                a.decref(dead)
            assert a.used == len(model)
        # Drain: everything returns to the pool.
        for b, refs in list(model.items()):
            for _ in range(refs):
                a.decref(b)
        assert a.used == 0 and a.available == n


def test_block_allocator_exhaustion_and_reset():
    a = kvcache.BlockAllocator(2)
    a.alloc(), a.alloc()
    with pytest.raises(RuntimeError):
        a.alloc()
    a.reset()
    assert a.available == 2 and a.used == 0
    with pytest.raises(RuntimeError):
        a.incref(0)                     # free block: no phantom refs


# ---------------------------------------------------------------------------
# Paged-vs-contiguous parity (the acceptance bar).

def test_paged_matches_contiguous_greedy_fp32(cfg, params):
    """Mixed wave-path and chunk-path prompts generate token-identical
    output on the paged engine and its contiguous twin, and the
    decode logits over the final caches agree bit-for-bit."""
    e_p, e_c = _pair(params, cfg, kv_block=8)
    prompts = [[3, 17, 42, 7, 99],                 # wave path
               list(range(1, 29)),                 # 28 toks: chunked
               [5, 9, 31],
               list(range(40, 60))]                # chunked
    got = e_p.generate(prompts, max_new_tokens=6)
    want = e_c.generate(prompts, max_new_tokens=6)
    assert got == want


def test_paged_matches_contiguous_greedy_int8(cfg, params):
    e_p, e_c = _pair(params, cfg, kv_block=8, kv_int8=True)
    prompts = [list(range(1, 25)), [3, 1, 4], list(range(30, 48))]
    assert (e_p.generate(prompts, max_new_tokens=8)
            == e_c.generate(prompts, max_new_tokens=8))


def test_paged_warm_vs_cold_prefix_parity(cfg, params):
    """The PR-5 guarantee against the paged cache: a prefix hit (shared
    blocks, zero copies when block | chunk) generates exactly the cold
    path's tokens — which match the contiguous twin's."""
    # kv_block=8 == chunk: stored prefixes are block-aligned, so the
    # hit path is pure block sharing (no COW).
    e_p, e_c = _pair(params, cfg, kv_block=8)
    system = list(range(5, 21))                    # 16 = 2 chunks
    pa, pb = system + [31, 32, 33, 34], system + [41, 42, 43]

    cold_a = e_c.generate([pa], max_new_tokens=6)[0]
    assert e_p.generate([pa], max_new_tokens=6)[0] == cold_a
    e_p.finished.clear()

    cow_before = eng.KV_COW_COPIES._require_default().value
    warm_b = e_p.generate([pb], max_new_tokens=6)[0]
    (req_b,) = e_p.finished
    assert req_b.cached_len == 16                  # suffix-only prefill
    assert req_b.n_chunks == 1
    # Block-aligned share: no copy-on-write happened.
    assert eng.KV_COW_COPIES._require_default().value == cow_before
    e_p.finished.clear()

    e_p.clear_prefix_cache()
    cold_b = e_p.generate([pb], max_new_tokens=6)[0]
    assert warm_b == cold_b == e_c.generate([pb], max_new_tokens=6)[0]


def test_paged_cow_partial_block_share(cfg, params):
    """block_len NOT dividing the chunk: the stored prefix ends inside
    a block, so the store copies-on-share and the hit copies-on-write —
    and parity still holds exactly."""
    # chunk=8, block=16 -> a 24-token prefix = 1 full block + 8 rows.
    e_p, e_c = _pair(params, cfg, kv_block=16, chunk=8)
    system = list(range(5, 29))                    # 24 tokens
    pa, pb = system + [31, 32, 33], system + [41, 42]

    cow0 = eng.KV_COW_COPIES._require_default().value
    assert (e_p.generate([pa], max_new_tokens=6)[0]
            == e_c.generate([pa], max_new_tokens=6)[0])
    assert eng.KV_COW_COPIES._require_default().value == cow0 + 1     # copy-on-share
    e_p.finished.clear()

    warm = e_p.generate([pb], max_new_tokens=6)[0]
    (req,) = e_p.finished
    assert req.cached_len == 24
    assert eng.KV_COW_COPIES._require_default().value >= cow0 + 2     # copy-on-write
    e_p.finished.clear()
    e_p.clear_prefix_cache()
    assert warm == e_p.generate([pb], max_new_tokens=6)[0]
    assert warm == e_c.generate([pb], max_new_tokens=6)[0]


def test_paged_slot_churn_never_leaks_dead_rows(cfg, params):
    """Freed blocks recycle across slot reuse without leaking a dead
    occupant's rows into attention: generation over a churned engine
    equals a fresh engine's, and blocks return to the pool."""
    e = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                            prompt_buckets=(32,), kv_block=8,
                            kv_blocks=10)     # tight pool: forced reuse
    outs = e.generate([[1, 2, 3], [4, 5, 6], list(range(1, 29)),
                       [7, 8]], max_new_tokens=4)
    fresh = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                                prompt_buckets=(32,), kv_block=8)
    assert outs == fresh.generate([[1, 2, 3], [4, 5, 6],
                                   list(range(1, 29)), [7, 8]],
                                  max_new_tokens=4)
    assert e.blocks_used == 0


# ---------------------------------------------------------------------------
# Satellite 2: reset/clear audit + leak test.

def test_no_block_leak_after_admit_retire_cycle(cfg, params):
    """Full lifecycle: admit (wave + chunked + prefix store/hit),
    decode, retire. Slots release their blocks at retirement; the only
    survivors are prefix-cache refs, and clear_prefix_cache() drops
    those -> blocks_used == 0."""
    e_p, _ = _pair(params, cfg, kv_block=8)
    system = list(range(5, 21))
    e_p.generate([system + [31, 32], [3, 1, 4],
                  system + [41, 42, 43]], max_new_tokens=5)
    assert not e_p.slot_req and not e_p.chunking
    held = e_p.blocks_used
    assert held > 0                      # prefix entries hold blocks
    e_p.clear_prefix_cache()
    assert e_p.blocks_used == 0
    # Gauges track the allocator.
    assert eng.KV_BLOCKS_USED._require_default().value == 0


def test_reset_frees_all_blocks_mid_flight(cfg, params):
    """reset() with requests active, queued AND mid-chunk zeroes the
    allocator, the table and the occupancy gauges — and the engine
    still serves afterwards with full parity."""
    e_p, e_c = _pair(params, cfg, kv_block=8, slots=2)
    e_p.add_request([1, 2, 3], max_new_tokens=64)     # active
    e_p.step()
    e_p.add_request(list(range(1, 29)), max_new_tokens=4)  # chunked
    e_p.admit()
    assert e_p.chunking and e_p.blocks_used > 0
    e_p.reset()
    assert e_p.blocks_used == 0
    assert eng.KV_BLOCKS_USED._require_default().value == 0
    assert (e_p.block_table == e_p.n_kv_blocks).all()
    assert not e_p.chunking and not e_p.slot_req and not e_p.waiting
    assert (e_p.generate([[9, 8, 7]], max_new_tokens=4)
            == e_c.generate([[9, 8, 7]], max_new_tokens=4))


def test_pool_dry_stalls_admission_then_recovers(cfg, params):
    """A pool too small for every request at once: admission stalls
    (no crash, no corruption), retirements free blocks, everyone
    completes, outputs match an unconstrained twin."""
    prompts = [[i + 1, i + 2, i + 3] for i in range(6)]
    e = eng.InferenceEngine(params, cfg, n_slots=6, max_len=64,
                            prompt_buckets=(16,), kv_block=8,
                            kv_blocks=8)    # 1 block/req, <6+spare
    ref = eng.InferenceEngine(params, cfg, n_slots=6, max_len=64,
                              prompt_buckets=(16,), kv_block=8)
    got = e.generate(prompts, max_new_tokens=4)
    assert got == ref.generate(prompts, max_new_tokens=4)
    assert e.blocks_used == 0


def test_prefix_eviction_on_dry_pool_frees_blocks(cfg, params):
    """When admission needs blocks the prefix cache is hoarding, LRU
    entries evict (counted) instead of stalling forever."""
    system = list(range(5, 21))
    e = eng.InferenceEngine(params, cfg, n_slots=2, max_len=64,
                            prompt_buckets=(48,), prefill_chunk=8,
                            prefix_pool=4, kv_block=8, kv_blocks=9)
    ev0 = eng.PREFIX_EVICTIONS._require_default().value
    e.generate([system + [31, 32]], max_new_tokens=4)   # stores prefix
    held = e.blocks_used
    assert held > 0
    # Pool: 9 blocks, the stored prefix holds 2. Two concurrent
    # 40-token requests need 6 blocks each -> the second admission
    # finds the pool dry, evicts the prefix entry, and (still short)
    # stalls until the first retires — no deadlock, no corruption.
    e.finished.clear()
    e.generate([list(range(100, 140)), list(range(150, 190))],
               max_new_tokens=4)
    assert eng.PREFIX_EVICTIONS._require_default().value > ev0
    assert not e.waiting and not e.chunking


def test_prefix_hit_survives_dry_pool_admission(cfg, params):
    """A hit admitted against a dry pool must not corrupt itself:
    _alloc_blocks' eviction may reach the hit's own entry, and an
    unpinned payload block could be freed and handed straight back as
    a fresh block (one physical block aliased at two table positions).
    The claim pins the shared blocks first, eviction skips entries
    that would free nothing, and the request stalls until the hog
    retires — warm, uncorrupted, token-identical to contiguous."""
    system = list(range(5, 21))                     # 16 = 2 blocks
    pb = system + [41, 42, 43, 44, 45, 46, 47, 48]  # 24 toks: hit
    mk = lambda blk, **kw: eng.InferenceEngine(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=(48,),
        prefill_chunk=8, prefix_pool=2, kv_block=blk, **kw)
    e = mk(8, kv_blocks=9)
    ref = mk(0)
    e.generate([system + [31, 32]], max_new_tokens=4)   # store prefix
    e.finished.clear()
    assert e.blocks_used == 2                       # entry's 2 blocks
    ev0 = eng.PREFIX_EVICTIONS._require_default().value
    # Hog: 6 blocks -> pool at 8/9 used, 1 free < the hit's 2 fresh.
    e.add_request([1, 2, 3, 4], max_new_tokens=44)
    e.admit()
    assert len(e.slot_req) == 1
    e.add_request(pb, max_new_tokens=4)
    e.run_to_completion(max_burst=4)
    by_prompt = {tuple(r.prompt): r for r in e.finished}
    req_b = by_prompt[tuple(pb)]
    # Still a WARM hit (the entry was not futilely evicted) and still
    # bit-parity with the contiguous twin.
    assert req_b.cached_len == 16
    assert eng.PREFIX_EVICTIONS._require_default().value == ev0
    assert req_b.tokens == ref.generate([pb], max_new_tokens=4)[0]
    e.clear_prefix_cache()
    assert e.blocks_used == 0


# ---------------------------------------------------------------------------
# Knobs + occupancy.

def test_kv_block_clamps_to_max_len_divisor(cfg, params):
    # 256 > max_len=48 -> one block per slot; still paged.
    e = eng.InferenceEngine(params, cfg, n_slots=1, max_len=48,
                            prompt_buckets=(16,))
    assert e.paged and e.kv_block == 48 and e.blocks_per_slot == 1
    # Non-divisor request clamps down to the largest divisor.
    e2 = eng.InferenceEngine(params, cfg, n_slots=1, max_len=48,
                             prompt_buckets=(16,), kv_block=32)
    assert e2.kv_block == 24
    # A pool that cannot hold one max_len request is a config error.
    with pytest.raises(ValueError):
        eng.InferenceEngine(params, cfg, n_slots=1, max_len=48,
                            prompt_buckets=(16,), kv_block=8,
                            kv_blocks=3)


def test_table_device_cache_invalidates_on_mutation(cfg, params):
    e = eng.InferenceEngine(params, cfg, n_slots=2, max_len=32,
                            prompt_buckets=(16,), kv_block=8)
    t0 = e.table_device()
    assert t0 is e.table_device()        # cached between calls
    e.generate([[1, 2, 3]], max_new_tokens=2)
    t1 = e.table_device()
    assert t1 is not t0                  # claims/retires dirtied it
    assert np.array_equal(np.asarray(t1), e.block_table)


def test_bench_occupancy_smoke():
    """Satellite: the >=4x-slots-at-equal-HBM claim, CI-sized. Equal
    pool bytes, 8x the slots, greedy parity, zero leaked blocks."""
    from skypilot_tpu.infer import bench_serve

    r = bench_serve.run_occupancy(smoke=True)
    assert r["same_hbm"]
    assert r["parity_ok"]
    assert r["leak_free"]
    assert r["occupancy_x"] >= 4
    assert not r["occupancy_regressed"]
    assert r["blocks_per_token"] is not None
