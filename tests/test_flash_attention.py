"""Flash-attention kernel numerics vs the einsum oracle (interpret mode
on CPU; the same kernel compiles with Mosaic on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.ops import attention as attn
from skypilot_tpu.ops import flash_attention as fa


def _rand_qkv(b=2, s=256, h=2, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_oracle(causal):
    q, k, v = _rand_qkv()
    out = fa.flash_attention(q, k, v, causal=causal, block_q=128,
                             block_k=128, interpret=True)
    ref = attn.xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_forward_uneven_blocks():
    q, k, v = _rand_qkv(s=256)
    out = fa.flash_attention(q, k, v, causal=True, block_q=64,
                             block_k=128, interpret=True)
    ref = attn.xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_backward_matches_oracle():
    q, k, v = _rand_qkv(b=1, s=128, h=2, d=64)

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, causal=True, block_q=64,
                               block_k=64, interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = attn.xla_attention(q, k, v, causal=True)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3,
                                   err_msg=f"d{name} mismatch")


def test_rejects_indivisible_seq():
    q, k, v = _rand_qkv(s=100)
    with pytest.raises(ValueError):
        fa.flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)


def _rand_segments(b=2, s=256, n_docs=3, seed=7):
    rng = np.random.default_rng(seed)
    seg = np.zeros((b, s), np.int32)
    for bi in range(b):
        cuts = np.sort(rng.choice(np.arange(1, s), n_docs - 1,
                                  replace=False))
        bounds = [0, *cuts.tolist(), s]
        for i in range(n_docs):
            seg[bi, bounds[i]:bounds[i + 1]] = i + 1
    return jnp.asarray(seg)


@pytest.mark.parametrize("causal", [True, False])
def test_segment_forward_matches_oracle(causal):
    q, k, v = _rand_qkv()
    seg = _rand_segments()
    out = fa.flash_attention(q, k, v, causal=causal, segment_ids=seg,
                             block_q=128, block_k=128, interpret=True)
    ref = attn.xla_attention(q, k, v, causal=causal, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_segment_backward_matches_oracle():
    q, k, v = _rand_qkv(s=128)
    seg = _rand_segments(s=128)

    def f_flash(q, k, v):
        return fa.flash_attention(q, k, v, causal=True, segment_ids=seg,
                                  block_q=128, block_k=128,
                                  interpret=True).sum()

    def f_ref(q, k, v):
        return attn.xla_attention(q, k, v, causal=True,
                                  segment_ids=seg).sum()

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


def test_segment_rejects_small_block_k():
    q, k, v = _rand_qkv(s=256)
    with pytest.raises(ValueError):
        fa.flash_attention(q, k, v, segment_ids=_rand_segments(),
                           block_q=64, block_k=64, interpret=True)
