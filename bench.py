"""Benchmark: Llama-family train step throughput on the local accelerator.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Metric: training tokens/sec/chip on the largest pre-baked Llama config
that fits the local chip. ``vs_baseline`` is an *MFU ratio* against the
reference's own TPU training anchor, so it is fair across chip
generations and model sizes:

  reference anchor (BASELINE.md): Llama-3-8B PyTorch/XLA on v6e-8 at
  0.476 samples/s. At the example's seq_len=8192 that is 487.4
  tokens/s/chip => MFU = 487.4 * 6 * 8.03e9 / 918e12 = 2.56%.

  vs_baseline = our_MFU / 0.0256.

All progress chatter goes to stderr; stdout carries only the JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# Peak dense bf16 FLOP/s per chip.
PEAK_FLOPS = {
    "v2": 45e12, "v3": 123e12, "v4": 275e12,
    "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12,
    "v6 lite": 918e12, "v6e": 918e12, "cpu": 5e11,
}

REF_MFU = 487.4 * 6 * 8.03e9 / 918e12  # 0.02558 (see module docstring)


def peak_for(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in sorted(PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return val
    return PEAK_FLOPS["cpu"]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None,
                    help="llama config name (default: sized to chip)")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--remat-policy", default=None,
                    choices=("none", "dots"))
    ap.add_argument("--xent-chunk", type=int, default=None)
    ap.add_argument("--param-dtype", default=None,
                    choices=("float32", "bfloat16"))
    ap.add_argument("--mu-dtype", default=None,
                    choices=("float32", "bfloat16"))
    ap.add_argument("--serve", dest="serve", action="store_true",
                    default=None, help="append serving TTFT/throughput "
                    "metrics (default: on TPU only)")
    ap.add_argument("--no-serve", dest="serve", action="store_false")
    ap.add_argument("--serve-config", default=None,
                    help="serve bench config (default on TPU: llama3-8b "
                         "w8a8 — the baseline's 7/8B serving class)")
    ap.add_argument("--qlora", dest="qlora", action="store_true",
                    default=None, help="append the 8B-class QLoRA train "
                    "bench (default: on TPU only)")
    ap.add_argument("--no-qlora", dest="qlora", action="store_false")
    ap.add_argument("--qlora-config", default=None)
    ap.add_argument("--qlora-batch", type=int, default=2)
    ap.add_argument("--qlora-seq", type=int, default=2048)
    ap.add_argument("--qlora-rank", type=int, default=16)
    ap.add_argument("--goodput", dest="goodput", action="store_true",
                    default=True, help="gate the train goodput "
                    "recorder's parity + overhead contract (default on)")
    ap.add_argument("--no-goodput", dest="goodput", action="store_false")
    ap.add_argument("--emit-metrics", action="store_true", default=False,
                    help="snapshot the observability registry into the "
                         "output JSON under 'observability' — the same "
                         "counters/histograms production scrapes from "
                         "/metrics, so BENCH records carry them")
    ap.add_argument("--emit-trace", action="store_true", default=False,
                    help="aggregate this run's recorded trace spans "
                         "(engine per-request queue-wait/prefill/decode, "
                         "train steps — observability/tracing.py) into "
                         "the output JSON under 'trace' as per-span-name "
                         "count/total/mean/max durations")
    args = ap.parse_args()

    import jax

    from skypilot_tpu.models import llama
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.train import trainer

    devices = jax.devices()
    n_chips = len(devices)
    dev = devices[0]
    kind = getattr(dev, "device_kind", "cpu")
    on_cpu = jax.default_backend() == "cpu"
    log(f"bench: {n_chips}x {kind} backend={jax.default_backend()}")

    if args.config is None:
        # North-star scale on a real chip: the 1B-class config (pure
        # bf16 train state + chunked xent + full remat fit ~1.5B params
        # inside 16 GB).
        args.config = "llama3-tiny" if on_cpu else "llama3-1b"
    if args.config == "llama3-1b" and not on_cpu:
        # Measured sweet spot on a 16G v5e: batch 6, full recompute,
        # bf16 params+moments, 512-token xent chunks -> MFU 0.645.
        if args.xent_chunk is None:
            args.xent_chunk = 512
        if args.mu_dtype is None:
            args.mu_dtype = "bfloat16"
        if args.param_dtype is None:
            args.param_dtype = "bfloat16"
        if args.remat_policy is None:
            args.remat_policy = "none"
    if args.batch is None:
        # batch 6/chip is the sweet spot for both 400M (dots remat) and
        # 1B (full remat) on a 16G v5e.
        args.batch = 2 if on_cpu else 6 * max(n_chips, 1)
    if on_cpu and args.seq > 256:
        args.seq = 128

    if args.remat_policy is None:
        args.remat_policy = "dots"
    cfg = llama.CONFIGS[args.config]
    import dataclasses

    import jax.numpy as jnp
    cfg = dataclasses.replace(cfg, remat_policy=args.remat_policy)
    if args.xent_chunk is not None:
        cfg = dataclasses.replace(cfg, xent_chunk=args.xent_chunk)
    if args.param_dtype is not None:
        cfg = dataclasses.replace(cfg,
                                  param_dtype=jnp.dtype(args.param_dtype))
    seq = min(args.seq, cfg.max_seq_len)
    mesh = mesh_lib.make_mesh() if n_chips > 1 else None

    tc = trainer.TrainConfig(warmup_steps=10, total_steps=1000,
                             mu_dtype=args.mu_dtype)
    t0 = time.time()
    state = trainer.create_train_state(cfg, tc, mesh)
    step = trainer.make_train_step(cfg, tc, mesh)
    batch = trainer.synthetic_batch(cfg, args.batch, seq)
    state, metrics = step(state, batch)
    # NOTE: on the axon TPU relay, jax.block_until_ready does NOT
    # synchronize; a host fetch (float()) is the only reliable sync.
    # The timed loop is chained through donated state, so fetching the
    # final loss waits on every step.
    first_loss = float(metrics["loss"])
    log(f"compile+first step: {time.time()-t0:.1f}s loss={first_loss:.3f}")

    for _ in range(args.warmup - 1):
        state, metrics = step(state, batch)
    float(metrics["loss"])

    t0 = time.time()
    for _ in range(args.steps):
        state, metrics = step(state, batch)
    float(metrics["loss"])  # host fetch = real sync
    dt = (time.time() - t0) / args.steps

    tokens_per_step = args.batch * seq
    tok_s = tokens_per_step / dt
    tok_s_chip = tok_s / n_chips

    n_params = cfg.num_params()
    # 6N per token + attention: ~6 * layers * seq * d_model per token
    # (QK^T + AV, causal-halved, fwd+bwd).
    flops_per_token = 6 * n_params + 6 * cfg.n_layers * seq * cfg.d_model
    mfu = tok_s_chip * flops_per_token / peak_for(dev)

    out = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / REF_MFU, 3),
        "mfu": round(mfu, 4),
        "config": args.config,
        "n_params": n_params,
        "batch": args.batch,
        "seq": seq,
        "n_chips": n_chips,
        "device": kind,
        "step_time_s": round(dt, 4),
        "baseline_note": "vs_baseline = MFU ratio vs reference "
                         "Llama-3-8B@v6e-8 anchor (MFU 2.56%, BASELINE.md)",
    }

    # Goodput recorder contract (docs/observability.md §Training
    # goodput): recorder-off training is bit-identical (the recorder
    # never touches batches or state) and recorder-on stays within a
    # 1.01x step-time budget — the same no-op-guard bound the serving
    # flight recorder holds.
    if args.goodput:
        try:
            gp_res = _goodput_bench(trainer, cfg, tc, mesh,
                                    args.batch, seq)
            out.update(gp_res)
            # Parity gates everywhere; the overhead bound only on
            # hardware (the serving recorder's precedent) — a shared
            # CPU box jitters tiny steps by ~10%, far above the
            # recorder's measured ~50us/step cost.
            out["train_goodput_regressed"] = bool(
                (not on_cpu
                 and gp_res["train_goodput_overhead"] > 1.01)
                or not gp_res["train_goodput_parity_ok"])
            if out["train_goodput_regressed"]:
                log("TRAIN GOODPUT REGRESSION: "
                    f"overhead=x{gp_res['train_goodput_overhead']} "
                    f"(> 1.01) or parity broken "
                    f"(parity_ok={gp_res['train_goodput_parity_ok']})")
        except Exception as e:  # noqa: BLE001 — 1B metric must print
            log(f"goodput bench failed: {e}")
            out["train_goodput_error"] = str(e)[:200]

    # Free the 1B train state before the 8B phases.
    del state, step, batch
    import gc
    gc.collect()

    # 8B-class finetune — the metric BASELINE.json actually names
    # ("Llama-3-8B finetune tokens/sec/chip"). int8 frozen base + LoRA
    # + full remat fit 8B on one 16 GB chip; see train/qlora.py.
    if args.qlora is None:
        args.qlora = not on_cpu
    if args.qlora:
        try:
            q = _qlora_bench(args, dev, n_chips, on_cpu)
            out.update(q)
        except Exception as e:  # noqa: BLE001 — 1B metric must print
            log(f"qlora bench failed: {e}")
            out["qlora_8b_error"] = str(e)[:200]
        gc.collect()

    # Serving metrics in the same artifact (reference anchors: JetStream
    # Llama-2-7B on v6e — median TTFT 1829.33 ms, 2147.98 out tok/s).
    # Streaming TTFT through a real LB (first streamed byte), on the
    # same 7/8B model class as the anchor via w8a8 + int8 KV.
    if args.serve is None:
        args.serve = not on_cpu
    if args.serve:
        try:
            from skypilot_tpu.infer import bench_serve
            serve_cfg = args.serve_config or (
                "llama3-tiny" if on_cpu else "llama3-8b")
            big = "8b" in serve_cfg
            # Realistic prompts (512-1024 token mix), 5 timed runs on
            # the warm server, worst run reported: the r3 driver
            # artifact showed 5x run-to-run TTFT variance, so a single
            # lucky run proves nothing. 32 slots (the r4 KV-cache
            # layout fix freed the HBM for them) at 24 concurrent
            # requests — serving headroom, like production; admission
            # waves of 4 run ONE batched prefill each (padded -> one
            # compiled program per bucket) and the wave programs are
            # dispatched pipelined (first-token fetches overlap later
            # waves' prefill); decode bursts stay short (open_burst)
            # while traffic is arriving and slots remain, and go long
            # (max_burst 32, amortizing relay dispatch) once slots are
            # full or arrivals go quiet. The full_load companion phase
            # measures 32/32 on the same warm server (~1.24k tok/s
            # median-of-3 with the staged burst; engine-only decode is
            # ~1.4k — an ~11% HTTP/LB tax, down from ~30% in r4).
            serve = bench_serve.run_http(
                config=serve_cfg, requests=24, slots=32,
                new_tokens=192, max_burst=32, open_burst=4,
                admit_wave=4, repeats=5, full_load=True,
                weights_int8=big, kv_int8=big)
            # Chip-normalized throughput: our tok/s per peak-TFLOP vs
            # the anchor's tok/s per peak-TFLOP on ITS chip (v6e,
            # 918 TF) — the serve analog of the train metric's MFU
            # ratio, so a v5e result reads fairly against a v6e anchor.
            from skypilot_tpu.infer.bench_serve import REF_TOK_S
            ref_peak = PEAK_FLOPS["v6e"]
            norm = ((serve["out_tok_s"] / peak_for(dev))
                    / (REF_TOK_S / ref_peak))
            out.update({
                "serve_median_ttft_ms": serve["median_ttft_ms"],
                "serve_worst_run_median_ttft_ms":
                    serve["worst_run_median_ttft_ms"],
                "serve_p99_ttft_ms": serve["p99_ttft_ms"],
                "serve_out_tok_s": serve["out_tok_s"],
                "serve_tpot_ms": serve["tpot_ms"],
                "serve_vs_baseline_tpot": serve["vs_baseline_tpot"],
                "serve_vs_baseline_tok_s_normalized": round(norm, 3),
                "serve_tok_s_normalization": (
                    f"(ours/{peak_for(dev)/1e12:.0f}TF) / "
                    f"(anchor {REF_TOK_S}/{ref_peak/1e12:.0f}TF v6e)"),
                "serve_vs_baseline_ttft": serve["vs_baseline_ttft"],
                "serve_worst_run_vs_baseline_ttft":
                    serve["worst_run_vs_baseline_ttft"],
                "serve_regressed": serve["regressed"],
                "serve_worst_run_regressed":
                    serve["worst_run_regressed"],
                "serve_worst_run_below_1p2x":
                    serve["worst_run_below_1p2x"],
                "serve_runs": serve["runs"],
                "serve_prompt_mean_len": serve["prompt_mean_len"],
                "serve_prompt_max_len": serve["prompt_max_len"],
                "serve_new_tokens": serve["new_tokens"],
                "serve_config": serve["config"],
                "serve_transport": serve["transport"],
                "serve_weights_int8": serve["weights_int8"],
            })
            if serve.get("full_load"):
                # Throughput-optimal companion: every slot filled on
                # the same warm server (the 24-request numbers above
                # keep serving headroom for the TTFT metric).
                fl = serve["full_load"]
                out["serve_full_load_requests"] = fl["requests"]
                out["serve_full_load_out_tok_s"] = fl["out_tok_s"]
                out["serve_full_load_median_ttft_ms"] = \
                    fl["median_ttft_ms"]
                out["serve_full_load_tpot_ms"] = fl.get("tpot_ms")
                out["serve_full_load_regressed"] = fl["regressed"]
                if fl["regressed"]:
                    log("SERVE REGRESSION (full load): median TTFT "
                        f"{fl['median_ttft_ms']}ms >= anchor "
                        f"{bench_serve.REF_TTFT_MS}ms")
            if serve["worst_run_below_1p2x"]:
                log("serve worst-run margin below the 1.2x gate: "
                    f"{serve['worst_run_median_ttft_ms']}ms vs anchor "
                    f"{bench_serve.REF_TTFT_MS}ms")
            if serve["regressed"]:
                # Loud regression guard (VERDICT r3): a serve TTFT
                # worse than the anchor must not ship silently.
                log("SERVE REGRESSION: median-of-runs TTFT "
                    f"{serve['median_ttft_ms']}ms >= anchor "
                    f"{bench_serve.REF_TTFT_MS}ms")
            elif serve["worst_run_regressed"]:
                log("serve worst-run above anchor (median still beats): "
                    f"{serve['worst_run_median_ttft_ms']}ms >= "
                    f"{bench_serve.REF_TTFT_MS}ms")
        except Exception as e:  # noqa: BLE001 — train metric must print
            log(f"serve bench failed: {e}")
            out["serve_error"] = str(e)[:200]
        # Prefix-cache + chunked-prefill phase (engine-only, its own
        # guard): warm-prefix TTFT and the decode-interference numbers
        # ride the same BENCH artifact so the r-trajectory captures
        # this PR's effect.
        try:
            from skypilot_tpu.infer import bench_serve as _bs
            ps = _bs.run_prefix_share(config=serve_cfg,
                                      weights_int8=big, kv_int8=big)
            out["serve_prefix_cold_ttft_ms"] = ps["cold_ttft_ms"]
            out["serve_prefix_warm_ttft_ms"] = ps["warm_ttft_ms"]
            out["serve_prefix_warm_speedup"] = ps["warm_speedup"]
            out["serve_prefix_hit_rate"] = ps["hit_rate"]
            out["serve_prefix_parity_ok"] = ps["parity_ok"]
            out["serve_decode_stall_ms"] = ps["decode_stall_p99_ms"]
            out["serve_tpot_admission_ratio"] = \
                ps["interference"]["tpot_admission_ratio"]
            out["serve_tpot_admission_ratio_monolith"] = \
                ps["interference"]["monolith_ratio"]
            # Gates: warm >= 30% below cold; decode TPOT p99 during
            # admission <= 1.3x idle (vs the monolith's multi-x spike).
            out["serve_prefix_regressed"] = bool(
                not ps["warm_below_70pct_of_cold"]
                or not ps["parity_ok"])
            out["serve_interference_regressed"] = bool(
                ps["interference"]["tpot_admission_ratio"] > 1.3)
            if out["serve_prefix_regressed"]:
                log("SERVE PREFIX REGRESSION: warm "
                    f"{ps['warm_ttft_ms']}ms vs cold "
                    f"{ps['cold_ttft_ms']}ms "
                    f"(parity_ok={ps['parity_ok']})")
            if out["serve_interference_regressed"]:
                log("SERVE INTERFERENCE REGRESSION: admission TPOT "
                    f"p99 x{ps['interference']['tpot_admission_ratio']}"
                    " > 1.3x idle")
        except Exception as e:  # noqa: BLE001 — train metric must print
            log(f"prefix-share bench failed: {e}")
            out["serve_prefix_error"] = str(e)[:200]
        # Paged KV-cache occupancy phase: max concurrent slots at the
        # SAME KV HBM bytes, paged vs contiguous, with greedy parity —
        # the >=4x-slots-at-equal-HBM claim tracked release over
        # release (plus blocks/token so allocator efficiency is too).
        try:
            from skypilot_tpu.infer import bench_serve as _bs
            oc = _bs.run_occupancy(config=serve_cfg, weights_int8=big,
                                   kv_int8=big)
            out["serve_kv_hbm_bytes"] = oc["kv_hbm_bytes"]
            out["serve_slots"] = oc["paged_slots"]
            out["serve_slots_contiguous"] = oc["contiguous_slots"]
            out["serve_blocks_per_token"] = oc["blocks_per_token"]
            out["serve_kv_block"] = oc["kv_block"]
            out["serve_occupancy_x"] = oc["occupancy_x"]
            out["serve_paged_parity_ok"] = oc["parity_ok"]
            # Gate: >=4x slots at equal HBM, bit-equal greedy output.
            out["serve_occupancy_regressed"] = oc["occupancy_regressed"]
            if oc["occupancy_regressed"]:
                log("SERVE OCCUPANCY REGRESSION: "
                    f"{oc['paged_slots']} paged vs "
                    f"{oc['contiguous_slots']} contiguous slots "
                    f"(x{oc['occupancy_x']}, "
                    f"parity_ok={oc['parity_ok']})")
        except Exception as e:  # noqa: BLE001 — train metric must print
            log(f"occupancy bench failed: {e}")
            out["serve_occupancy_error"] = str(e)[:200]
        # Speculative-decoding phase. Headline: the MODEL-backed
        # drafter + async draft/verify pipeline on the NON-repetitive
        # workload (the honest one — n-gram speculation is a wash
        # there by design and rides along as a reported column).
        # Secondary: the PR 8 repetition-heavy n-gram column + the
        # oracle-draft ceiling, keys and meanings unchanged. The
        # >= 1.5x wall-clock gates bind on TPU runs only (the
        # kernel-bench precedent: a compute-bound CPU cannot show a
        # memory-bandwidth win); parity and the pipeline-overlap
        # structure gate everywhere.
        try:
            from skypilot_tpu.infer import bench_serve as _bs
            sp = _bs.run_spec(config=serve_cfg, weights_int8=big,
                              kv_int8=big)
            on_tpu = sp["backend"] == "tpu"
            out["serve_spec_model_speedup"] = sp["model_speedup"]
            out["serve_spec_model_accept_rate"] = \
                sp["model_accept_rate"]
            out["serve_spec_model_tpot_off_ms"] = \
                sp["model_tpot_off_ms"]
            out["serve_spec_model_tpot_ms"] = sp["tpot_model_ms"]
            out["serve_spec_model_tpot_sync_ms"] = \
                sp["tpot_model_sync_ms"]
            out["serve_spec_pipeline_ratio"] = sp["pipeline_ratio"]
            out["serve_spec_overlap_ok"] = sp["overlap_ok"]
            out["serve_spec_ngram_nonrep_speedup"] = \
                sp["ngram_nonrep_speedup"]
            out["serve_spec_ngram_nonrep_accept_rate"] = \
                sp["ngram_nonrep_accept_rate"]
            out["serve_spec_model_parity_ok"] = bool(
                sp["model_parity_ok"] and sp["model_sync_parity_ok"]
                and sp["ngram_nonrep_parity_ok"])
            # Gate: >= 1.5x decode tok/s from the model drafter on the
            # non-repetitive workload (TPU; the tentpole target is
            # 2x), bit-identical greedy output in every mode, and the
            # pipeline's draft dispatches structurally inside verify
            # windows.
            out["serve_spec_model_regressed"] = bool(
                not out["serve_spec_model_parity_ok"]
                or not sp["overlap_ok"]
                or (on_tpu and sp["model_speedup"] < 1.5))
            if out["serve_spec_model_regressed"]:
                log("SERVE SPEC MODEL REGRESSION: "
                    f"x{sp['model_speedup']} (< 1.5 on TPU) or "
                    f"parity broken "
                    f"(model={sp['model_parity_ok']}, "
                    f"sync={sp['model_sync_parity_ok']}, "
                    f"ngram={sp['ngram_nonrep_parity_ok']}) or "
                    f"overlap_ok={sp['overlap_ok']}")
            out["serve_spec_speedup"] = sp["speedup"]
            out["serve_spec_accept_rate"] = sp["accept_rate"]
            out["serve_spec_tpot_off_ms"] = sp["tpot_off_ms"]
            out["serve_spec_tpot_ms"] = sp["tpot_spec_ms"]
            out["serve_spec_oracle_speedup"] = sp["oracle_speedup"]
            out["serve_spec_oracle_accept_rate"] = \
                sp["oracle_accept_rate"]
            out["serve_spec_parity_ok"] = bool(
                sp["parity_ok"] and sp["oracle_parity_ok"])
            # Secondary gate: the repetition-heavy n-gram column keeps
            # its floor on TPU with bit-identical greedy output.
            out["serve_spec_regressed"] = bool(
                (on_tpu and sp["speedup"] < 1.5)
                or not out["serve_spec_parity_ok"])
            if out["serve_spec_regressed"]:
                log("SERVE SPEC REGRESSION: "
                    f"x{sp['speedup']} (< 1.5) or parity broken "
                    f"(ngram={sp['parity_ok']}, "
                    f"oracle={sp['oracle_parity_ok']}, "
                    f"accept={sp['accept_rate']})")
        except Exception as e:  # noqa: BLE001 — train metric must print
            log(f"spec bench failed: {e}")
            out["serve_spec_error"] = str(e)[:200]
        # Span-bucketed decode attention phase: decode TPOT with the
        # span ladder vs the full-view read on the same engine, short
        # active conversations on a long-max_len engine — the decode
        # BANDWIDTH lever (the occupancy phase above covers capacity).
        try:
            from skypilot_tpu.infer import bench_serve as _bs
            sa = _bs.run_span(config=serve_cfg, weights_int8=big,
                              kv_int8=big)
            out["serve_span_speedup"] = sa["speedup"]
            out["serve_span_tpot_full_ms"] = sa["tpot_full_ms"]
            out["serve_span_tpot_ms"] = sa["tpot_span_ms"]
            out["serve_span_rows"] = sa["rows_span"]
            out["serve_span_rows_full"] = sa["rows_full"]
            out["serve_span_programs"] = sa["n_span_programs"]
            out["serve_span_parity_ok"] = sa["parity_ok"]
            # Gate: >= 1.5x decode tok/s for active lengths <=
            # max_len/8 with bit-identical greedy output (the
            # tentpole target is 2x; 1.5x is the regression floor).
            out["serve_span_regressed"] = bool(
                sa["speedup"] < 1.5 or not sa["parity_ok"])
            if out["serve_span_regressed"]:
                log("SERVE SPAN REGRESSION: "
                    f"x{sa['speedup']} (< 1.5) or parity broken "
                    f"(parity_ok={sa['parity_ok']}, "
                    f"rows {sa['rows_span']}/{sa['rows_full']})")
        except Exception as e:  # noqa: BLE001 — train metric must print
            log(f"span bench failed: {e}")
            out["serve_span_error"] = str(e)[:200]
        # Pallas paged decode-attention kernel phase: kernel-vs-gather
        # decode TPOT on the same engine at low occupancy (where the
        # gather transient dominates), greedy parity vs the gather
        # oracle. PARITY is required everywhere; the SPEEDUP gate only
        # binds on real TPU runs — on CPU the kernel executes in
        # Pallas interpret mode, where wall-clock is meaningless.
        try:
            from skypilot_tpu.infer import bench_serve as _bs
            ke = _bs.run_kernel(config=serve_cfg, weights_int8=big,
                                kv_int8=big)
            out["serve_kernel_speedup"] = ke["speedup"]
            out["serve_kernel_tpot_gather_ms"] = ke["tpot_gather_ms"]
            out["serve_kernel_tpot_ms"] = ke["tpot_kernel_ms"]
            out["serve_kernel_parity_ok"] = bool(
                ke["parity_ok"] and ke["kernel_programs_ok"])
            on_tpu = ke["backend"] == "tpu"
            if "span_under_kernel_speedup" in ke:
                out["serve_kernel_span_speedup"] = \
                    ke["span_under_kernel_speedup"]
                out["serve_kernel_occupancy_x"] = \
                    ke["occupancy_under_kernel_x"]
            out["serve_kernel_regressed"] = bool(
                not out["serve_kernel_parity_ok"]
                or (on_tpu and ke["speedup"] < 1.2)
                or (on_tpu and not ke.get(
                    "span_under_kernel_parity_ok", True))
                or (on_tpu and not ke.get(
                    "occupancy_under_kernel_ok", True)))
            if out["serve_kernel_regressed"]:
                log("SERVE KERNEL REGRESSION: "
                    f"x{ke['speedup']} or parity broken "
                    f"(parity_ok={ke['parity_ok']}, "
                    f"programs_ok={ke['kernel_programs_ok']}, "
                    f"backend={ke['backend']})")
        except Exception as e:  # noqa: BLE001 — train metric must print
            log(f"kernel bench failed: {e}")
            out["serve_kernel_error"] = str(e)[:200]
        # Multi-tenant QoS phase: background-tenant TPOT isolation
        # under a hot tenant (WFQ + admission control) and
        # preemption-by-eviction parity — the production-hardening
        # gates (ROADMAP item 4).
        try:
            from skypilot_tpu.infer import bench_serve as _bs
            qs = _bs.run_qos(config=serve_cfg, weights_int8=big,
                             kv_int8=big)
            out["serve_qos_fairness_ratio"] = qs["fairness_ratio"]
            out["serve_qos_bg_ttft_wfq_ratio"] = \
                qs["bg_ttft_wfq_ratio"]
            out["serve_qos_bg_ttft_fifo_ratio"] = \
                qs["bg_ttft_fifo_ratio"]
            out["serve_qos_preemptions"] = qs["preemptions"]
            out["serve_preempt_parity_ok"] = bool(
                qs["preempt_parity_ok"] and qs["sched_parity_ok"])
            # Gates: background TPOT p99 <= 1.3x idle under a hot
            # tenant, preempted-request parity exact.
            out["serve_qos_regressed"] = bool(
                qs["fairness_ratio"] > 1.3
                or not out["serve_preempt_parity_ok"])
            if out["serve_qos_regressed"]:
                log("SERVE QOS REGRESSION: fairness "
                    f"x{qs['fairness_ratio']} (> 1.3) or parity "
                    f"broken (preempt={qs['preempt_parity_ok']}, "
                    f"sched={qs['sched_parity_ok']})")
        except Exception as e:  # noqa: BLE001 — train metric must print
            log(f"qos bench failed: {e}")
            out["serve_qos_error"] = str(e)[:200]
        # Multi-LoRA adapter-catalog phase (ROADMAP item 5): N-adapter
        # mixed decode TPOT vs single-adapter on the same engine.
        # Gates: overhead <= 1.15x, greedy parity vs per-adapter
        # sequential runs exact, and ZERO unexpected compiles while
        # adapters hot-load/evict mid-traffic (adapter count/identity
        # must never enter program identity).
        try:
            from skypilot_tpu.infer import bench_serve as _bs
            adp = _bs.run_adapters(config=serve_cfg, weights_int8=big,
                                   kv_int8=big)
            out["serve_adapter_overhead"] = adp["overhead_ratio"]
            out["serve_adapter_tpot_single_ms"] = adp["tpot_single_ms"]
            out["serve_adapter_tpot_mixed_ms"] = adp["tpot_mixed_ms"]
            out["serve_adapter_parity_ok"] = adp["parity_ok"]
            out["serve_adapter_hot_loads"] = adp["hot_loads"]
            out["serve_adapter_unexpected_compiles"] = \
                adp["unexpected_compiles"]
            out["serve_adapter_regressed"] = bool(
                adp["overhead_ratio"] > 1.15
                or not adp["parity_ok"]
                or adp["unexpected_compiles"] != 0)
            if out["serve_adapter_regressed"]:
                log("SERVE ADAPTER REGRESSION: "
                    f"x{adp['overhead_ratio']} (> 1.15) or parity "
                    f"broken (parity_ok={adp['parity_ok']}, "
                    f"unexpected={adp['unexpected_compiles']})")
        except Exception as e:  # noqa: BLE001 — train metric must print
            log(f"adapter bench failed: {e}")
            out["serve_adapter_error"] = str(e)[:200]
        # Flight recorder + compile watch phase: the introspection
        # contract over the full mixed workload (chunked admission +
        # spec decode + span regrouping, paged + contiguous). Gates:
        # nothing may compile inside the timed serving window, every
        # burst must carry a matching flight record, and the recorder
        # must be a no-op guard when off (<1% TPOT).
        try:
            from skypilot_tpu.infer import bench_serve as _bs
            fli = _bs.run_flight(config=serve_cfg, weights_int8=big,
                                 kv_int8=big)
            out["serve_warmup_compile_s"] = fli["warmup_compile_s"]
            out["serve_unexpected_compiles"] = \
                fli["unexpected_compiles"]
            out["serve_flight_records"] = fli["n_records"]
            out["serve_flight_overhead"] = fli["overhead_ratio"]
            out["serve_flight_coverage_ok"] = fli["coverage_ok"]
            out["serve_flight_parity_ok"] = fli["parity_ok"]
            out["serve_flight_calibration_parity_ok"] = \
                fli["calibration_parity_ok"]
            out["serve_flight_calibration_samples"] = \
                fli["calibration_samples"]
            # Request forensics rides the same bench: the per-request
            # ledger/tail machinery must be output-invariant when off
            # and <=1% TPOT when on (same bound as the recorder).
            out["serve_forensics_overhead"] = \
                fli["forensics_overhead_ratio"]
            out["serve_forensics_parity_ok"] = \
                fli["forensics_parity_ok"]
            out["serve_flight_regressed"] = bool(
                fli["unexpected_compiles"] != 0
                or not fli["coverage_ok"] or not fli["parity_ok"]
                or not fli["calibration_parity_ok"]
                or not fli["forensics_parity_ok"]
                or fli["overhead_ratio"] > 1.01
                or fli["forensics_overhead_ratio"] > 1.01)
            if out["serve_flight_regressed"]:
                log("SERVE FLIGHT REGRESSION: "
                    f"unexpected={fli['unexpected_compiles']} "
                    f"coverage={fli['coverage_ok']} "
                    f"parity={fli['parity_ok']} "
                    f"cal_parity={fli['calibration_parity_ok']} "
                    f"forensics_parity={fli['forensics_parity_ok']} "
                    f"overhead=x{fli['overhead_ratio']} "
                    f"forensics=x{fli['forensics_overhead_ratio']} "
                    f"(> 1.01)")
        except Exception as e:  # noqa: BLE001 — train metric must print
            log(f"flight bench failed: {e}")
            out["serve_flight_error"] = str(e)[:200]
        # Fleet prefix-affinity phase: consistent-hash routing on the
        # chunk-aligned prefix digest through the real LB. Gates:
        # fleet prefix hit rate >= 0.8 under affinity (the least-load
        # control lands near 1/N), warm TTFT >= 30% below cold, and
        # greedy parity between the cold and warm passes.
        try:
            from skypilot_tpu.infer import bench_serve as _bs
            af = _bs.run_affinity(config=serve_cfg, weights_int8=big,
                                  kv_int8=big)
            out["serve_affinity_hit_rate"] = af["affinity_hit_rate"]
            out["serve_affinity_control_hit_rate"] = \
                af["control_hit_rate"]
            out["serve_affinity_cold_ttft_ms"] = af["cold_ttft_ms"]
            out["serve_affinity_warm_ttft_ms"] = af["warm_ttft_ms"]
            out["serve_affinity_parity_ok"] = af["parity_ok"]
            out["serve_affinity_regressed"] = not af["gate_ok"]
            if not af["gate_ok"]:
                log("SERVE AFFINITY REGRESSION: hit rate "
                    f"{af['affinity_hit_rate']} (< 0.8) or warm "
                    f"{af['warm_ttft_ms']}ms vs cold "
                    f"{af['cold_ttft_ms']}ms (< 30% saving) or "
                    f"parity broken ({af['parity_ok']})")
        except Exception as e:  # noqa: BLE001 — train metric must print
            log(f"affinity bench failed: {e}")
            out["serve_affinity_error"] = str(e)[:200]
        # Disaggregated prefill/decode phase: 1-prefill + 2-decode
        # fleet behind the real LB. Gates: two-tier output
        # bit-identical to single-tier across {fp32, int8 KV} x
        # {spec on/off}, decode-tier TPOT under heavy prefill <= 1.1x
        # idle (TPU only; the single-tier interleave ratio rides
        # along as the contrast), zero unexpected compiles on either
        # tier, and the handoff.transfer chaos retry with zero lost
        # requests and zero leaked prefill-tier blocks.
        try:
            from skypilot_tpu.infer import bench_serve as _bs
            dg = _bs.run_disagg(config=serve_cfg)
            out["serve_disagg_parity_ok"] = dg["parity_ok"]
            out["serve_disagg_isolation_ratio"] = \
                dg["isolation_ratio"]
            out["serve_disagg_single_tier_ratio"] = \
                dg["single_tier_ratio"]
            out["serve_disagg_chaos_parity_ok"] = \
                dg["chaos_parity_ok"]
            out["serve_disagg_leaked_blocks"] = dg["leaked_blocks"]
            out["serve_disagg_unexpected_compiles"] = \
                dg["unexpected_compiles"]
            out["serve_disagg_regressed"] = not dg["gate_ok"]
            if not dg["gate_ok"]:
                log("SERVE DISAGG REGRESSION: parity "
                    f"{dg['parity_ok']}/{dg['chaos_parity_ok']}, "
                    f"isolation x{dg['isolation_ratio']} (> 1.1), "
                    f"leaked={dg['leaked_blocks']}, "
                    f"unexpected={dg['unexpected_compiles']}")
        except Exception as e:  # noqa: BLE001 — train metric must print
            log(f"disagg bench failed: {e}")
            out["serve_disagg_error"] = str(e)[:200]
    if args.emit_metrics:
        from skypilot_tpu.observability import metrics as obs_metrics
        # Only families something actually recorded into: a bench run
        # exercises a slice of the stack, and all-zero families for the
        # rest would bury the signal. A labeled child exists only once
        # someone called labels(); unlabeled families always carry their
        # implicit default child, so those need a nonzero value/count.
        def _recorded(fam):
            for s in fam["samples"]:
                if s["labels"] or s.get("count", 0) or s.get("value", 0):
                    return True
            return False

        snap = obs_metrics.REGISTRY.snapshot()
        out["observability"] = {
            name: fam for name, fam in snap.items() if _recorded(fam)}
    if args.emit_trace:
        from skypilot_tpu.observability import tracing
        out["trace"] = tracing.span_summary()
    print(json.dumps(out), flush=True)


def _goodput_bench(trainer, cfg, tc, mesh, batch_size, seq,
                   steps=6, reps=2) -> dict:
    """Recorder-off vs recorder-on parity + overhead for the goodput
    step ledger. One jitted step function serves both modes (the
    recorder wraps the CALL SITE, never the program), each run starts
    from a device copy of the same initial state, and the best
    per-mode step time over ``reps`` interleaved runs is compared so
    wall-clock drift doesn't masquerade as recorder overhead."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.observability import flight
    from skypilot_tpu.observability import goodput as goodput_lib

    step_fn = trainer.make_train_step(cfg, tc, mesh)
    batch = trainer.synthetic_batch(cfg, batch_size, seq, seed=0)
    state0 = trainer.create_train_state(cfg, tc, mesh, seed=0)
    # One throwaway compile so neither mode's timed loop pays it.
    warm_state, m = step_fn(jax.tree.map(jnp.copy, state0), batch)
    float(m["loss"])
    del warm_state

    best = {"off": None, "on": None}
    final = {}
    for _ in range(reps):
        for mode in ("off", "on"):
            state = jax.tree.map(jnp.copy, state0)
            rec = flight.FlightRecorder()   # isolated ring
            gp = goodput_lib.GoodputRecorder(
                recorder=rec, enable=(mode == "on"))
            t0 = time.time()
            for i in range(steps):
                gp.step_start(i)
                with gp.phase("compute"):
                    state, m = step_fn(state, batch)
                gp.step_end(tokens=batch_size * seq)
            loss = float(m["loss"])  # host fetch = real sync
            dt = (time.time() - t0) / steps
            final[mode] = loss
            if best[mode] is None or dt < best[mode]:
                best[mode] = dt
    overhead = (best["on"] / best["off"]
                if best["off"] and best["off"] > 0 else 1.0)
    parity = final["on"] == final["off"]
    log(f"goodput bench: off={best['off']*1e3:.2f}ms/step "
        f"on={best['on']*1e3:.2f}ms/step x{overhead:.4f} "
        f"parity={parity}")
    return {
        "train_goodput_overhead": round(overhead, 4),
        "train_goodput_parity_ok": parity,
        "train_goodput_step_ms_off": round(best["off"] * 1e3, 3),
        "train_goodput_step_ms_on": round(best["on"] * 1e3, 3),
    }


def _qlora_bench(args, dev, n_chips, on_cpu) -> dict:
    """8B-class QLoRA finetune throughput on one chip."""
    import dataclasses

    from skypilot_tpu.infer import kvcache
    from skypilot_tpu.models import llama
    from skypilot_tpu.train import qlora, trainer
    from skypilot_tpu.train.lora import LoRAConfig

    config = args.qlora_config or ("llama3-tiny" if on_cpu
                                   else "llama3-8b")
    batch_size = args.qlora_batch if not on_cpu else 2
    seq = args.qlora_seq if not on_cpu else 128
    cfg = dataclasses.replace(
        llama.CONFIGS[config], remat_policy="none",
        xent_chunk=(512 if args.xent_chunk is None else args.xent_chunk))
    seq = min(seq, cfg.max_seq_len)
    lc = LoRAConfig(rank=args.qlora_rank)
    tc = trainer.TrainConfig(warmup_steps=10, total_steps=1000)

    log(f"qlora bench: {config} r={lc.rank} batch={batch_size} seq={seq}")
    t0 = time.time()
    # Weights generate ON DEVICE — an 8 GB host-side tree would stall a
    # tunneled TPU for tens of minutes in transfer.
    fp_params, qweights = kvcache.random_quantized_params(cfg, seed=0)
    state = qlora.create_qlora_state(cfg, lc, tc)
    step = qlora.make_qlora_train_step(cfg, lc, tc)
    batch = trainer.synthetic_batch(cfg, batch_size, seq)
    state, metrics = step(state, qweights, fp_params, batch)
    first_loss = float(metrics["loss"])  # host fetch = sync
    log(f"qlora compile+first step: {time.time()-t0:.1f}s "
        f"loss={first_loss:.3f}")

    for _ in range(max(args.warmup - 1, 0)):
        state, metrics = step(state, qweights, fp_params, batch)
    float(metrics["loss"])

    t0 = time.time()
    for _ in range(args.steps):
        state, metrics = step(state, qweights, fp_params, batch)
    float(metrics["loss"])
    dt = (time.time() - t0) / args.steps

    tok_s_chip = batch_size * seq / dt / max(n_chips, 1)
    n_params = cfg.num_params()
    # Two FLOP bases, both reported (VERDICT r3: mixing bases makes the
    # ratio unimpeachable-proof):
    #  - 4N: the work this step actually does — frozen base runs fwd
    #    (2N) + activation-grad bwd (2N), no weight-grad pass. The
    #    honest hardware-utilization number.
    #  - 6N: the anchor's basis (full-train FLOPs). On this basis the
    #    ratio reduces to peak-normalized tokens/s vs the anchor's
    #    finetune tokens/s — the apples-to-apples throughput ratio.
    attn = cfg.n_layers * seq * cfg.d_model
    mfu_4n = tok_s_chip * (4 * n_params + 4 * attn) / peak_for(dev)
    mfu_6n = tok_s_chip * (6 * n_params + 6 * attn) / peak_for(dev)
    return {
        "qlora_8b_tokens_per_sec_per_chip": round(tok_s_chip, 2),
        "qlora_8b_mfu_4n": round(mfu_4n, 4),
        "qlora_8b_mfu_6n_basis": round(mfu_6n, 4),
        "qlora_8b_vs_baseline": round(mfu_6n / REF_MFU, 3),
        "qlora_8b_vs_baseline_4n": round(mfu_4n / REF_MFU, 3),
        "qlora_8b_config": config,
        "qlora_8b_n_params": n_params,
        "qlora_8b_batch": batch_size,
        "qlora_8b_seq": seq,
        "qlora_8b_rank": args.qlora_rank,
        "qlora_8b_step_time_s": round(dt, 4),
        "qlora_8b_note": "int8 frozen base + LoRA. vs_baseline uses "
                         "the anchor's own 6N FLOP basis (= chip-peak-"
                         "normalized tokens/s ratio); mfu_4n is the "
                         "actual work done (no weight-grad pass)",
    }


if __name__ == "__main__":
    main()
